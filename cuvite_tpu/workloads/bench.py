"""Hardened benchmark harness (the bench.py logic, now self-gating).

Round 4's 7x TEPS "regression" was a jit-cache-key instability silently
recompiling inside the timed runs; round 5 pinned the property in a test
(`tests/test_footprint.py::test_no_recompile_on_second_run`) but the
bench itself still trusted its warm-up.  This harness closes that hole
structurally (VERDICT r5 weak #6): the FIRST timed run executes under a
compile watcher, and any fresh XLA compilation aborts the bench loudly —
with the compile log on stderr — instead of emitting a JSON.  A number
that required compilation mid-measurement can no longer enter the
record.

One JSON schema (``validate_record``) is shared by ``BENCH_*.json``,
the TPU ladder (tools/tpu_ladder3.py) and the workloads CLI, so a
reader never has to guess which generation of bench wrote a record.

Metric follows the reference's TEPS accounting (main.cpp:448, :509):
    TEPS = sum over phases (phase_edges * phase_iterations) / clustering_s

Env knobs (compatible with the historical bench.py): BENCH_SCALE,
BENCH_EF, BENCH_GRAPH=rmat|rgg, BENCH_ENGINE, BENCH_REPEATS,
BENCH_TIME_BUDGET.  CLI flags override env.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# Canonical batched-engine vocabulary (ISSUE 10); core.batch's import
# chain is numpy-only, so this is safe before jax backend init (the
# --batch path sets XLA_FLAGS first) and in perf_regress --self-check.
from cuvite_tpu.core.batch import BATCH_ENGINES

_T_PROC = time.perf_counter()  # budget accounting starts at import

BASELINE_EDGES_PER_SEC_PER_CHIP = 1.0e9 / 64.0

# Bench record schema generation (ISSUE 6): v4 records are
# self-describing via this field; validate_record enforces the v4 keys.
# v5 (ISSUE 20) adds the optional `mix` block — a skewed two-class
# open-loop run's per-class goodput/wait split plus the sub-row packing
# counters; v4 records without it stay valid.
BENCH_SCHEMA_VERSION = 5

REQUIRED_RECORD_KEYS = (
    "metric", "value", "unit", "vs_baseline", "platform", "graph",
    "modularity", "phases", "compile_guard", "stages", "engine",
    "schema", "convergence_summary", "compile_events",
    "hbm_peak_by_buffer",
)

# Kernel-coverage fields every engine='pallas' record must carry (schema
# v3, ISSUE 4): without them a pallas TEPS number cannot say how much of
# the edge mass actually ran through the kernel vs the XLA fallbacks.
REQUIRED_PALLAS_KEYS = ("pallas_coverage", "pallas_width_hits")

# Per-stage wall-clock fields every record must carry (schema v2, ISSUE 3;
# coalesce_s since ISSUE 8 — the device relabel+coalesce slice nested
# inside coarsen_s, i.e. the round-7 sort tax as its own gated number;
# rebin_s since ISSUE 19 — the device plan re-bin of coarse bucketed
# phases, nested inside the driver's plan_s, 0.0 on the host
# BucketPlan.build path): the breakdown that makes the device-resident
# coarsening win measurable per phase instead of hiding inside one wall
# number.  Taken from the tracer of the RECORDED run
# (utils.trace.Tracer.breakdown).
REQUIRED_STAGE_KEYS = ("coarsen_s", "coalesce_s", "rebin_s", "upload_s",
                       "iterate_s")


class BenchCompileGuardError(RuntimeError):
    """The first timed run triggered fresh XLA compilation: the warm-up
    did not eat every compile, so the measurement is invalid."""

    def __init__(self, compile_log: list):
        self.compile_log = compile_log
        super().__init__(
            f"first timed run compiled {len(compile_log)} new "
            "executable(s); refusing to emit a bench record")


def validate_record(rec: dict) -> list:
    """Schema-violation strings for a bench record (empty = valid)."""
    problems = [f"missing key {k!r}" for k in REQUIRED_RECORD_KEYS
                if k not in rec]
    if not problems:
        if not isinstance(rec["value"], (int, float)) or rec["value"] <= 0:
            problems.append(f"non-positive value {rec['value']!r}")
        guard = rec["compile_guard"]
        if not isinstance(guard, dict) or "checked" not in guard:
            problems.append("compile_guard must carry 'checked'")
        elif guard["checked"] and guard.get("new_compiles", -1) != 0:
            problems.append("a checked record must have new_compiles == 0")
        stages = rec["stages"]
        if not isinstance(stages, dict):
            problems.append("stages must be a dict of <stage>_s seconds")
        else:
            for k in REQUIRED_STAGE_KEYS:
                v = stages.get(k)
                if not isinstance(v, (int, float)) or v < 0:
                    problems.append(
                        f"stages[{k!r}] must be a non-negative number, "
                        f"got {v!r}")
        if rec["engine"] == "pallas":
            for k in REQUIRED_PALLAS_KEYS:
                if k not in rec:
                    problems.append(
                        f"a pallas record must carry {k!r} (kernel "
                        "coverage, schema v3)")
            cov = rec.get("pallas_coverage")
            if cov is not None and not (
                    isinstance(cov, (int, float)) and 0.0 <= cov <= 1.0):
                problems.append(
                    f"pallas_coverage must be a fraction in [0, 1], "
                    f"got {cov!r}")
            hits = rec.get("pallas_width_hits")
            if "pallas_width_hits" in rec and not isinstance(hits, dict):
                problems.append("pallas_width_hits must be a dict of "
                                "width -> traversed kernel edges")
        # Schema v4 (ISSUE 6): telemetry fields from the run's flight
        # recorder — per-phase convergence digests, XLA compile events
        # (module + duration), per-buffer HBM peaks.
        if not isinstance(rec["schema"], int) or rec["schema"] < 4:
            problems.append(
                f"schema must be an int >= 4, got {rec['schema']!r}")
        cs = rec["convergence_summary"]
        if not isinstance(cs, list):
            problems.append("convergence_summary must be a list of "
                            "per-phase digests")
        else:
            for i, d in enumerate(cs):
                if not isinstance(d, dict) or "iterations" not in d:
                    problems.append(
                        f"convergence_summary[{i}] must be a dict with "
                        "'iterations'")
                    break
        ce = rec["compile_events"]
        if not isinstance(ce, list) or any(
                not isinstance(e, dict) or "module" not in e for e in ce):
            problems.append("compile_events must be a list of "
                            "{'module', 'dur_s'} dicts")
        if not isinstance(rec["hbm_peak_by_buffer"], dict):
            problems.append("hbm_peak_by_buffer must be a dict of "
                            "category -> peak nbytes")
        ck = rec.get("coalesce_kernel")
        if ck is not None and not (isinstance(ck, (int, float))
                                   and 0.0 <= ck <= 1.0):
            # Optional (device-coarsening runs only): the edge-weighted
            # fraction of inter-phase coalesces that ran a dense
            # seg_coalesce engine instead of the packed-sort fallback
            # (ISSUE 8) — the honesty label tools/perf_regress.py needs
            # next to a coalesce_s number.
            problems.append(
                f"coalesce_kernel must be a fraction in [0, 1], got "
                f"{ck!r}")
        rd = rec.get("rebin_device")
        if rd is not None and not (isinstance(rd, (int, float))
                                   and 0.0 <= rd <= 1.0):
            # Optional (bucketed-engine runs only, ISSUE 19): the
            # fraction of coarse phases whose bucket plan was built ON
            # DEVICE (coarsen/rebin.py) instead of by the host
            # BucketPlan.build — the arm label perf_regress needs to
            # keep device-rebin and host-rebin plan_s non-comparable.
            problems.append(
                f"rebin_device must be a fraction in [0, 1], got "
                f"{rd!r}")
        # Optional `batch` block (ISSUE 9): multi-tenant serving runs
        # carry the batch size, the serving throughput and the padding
        # tax — tools/perf_regress.py gates jobs_per_s like-for-like
        # (same slab class, same B).
        problems.extend(_validate_batch_block(rec.get("batch")))
        # Optional `serve` block (ISSUE 11): open-loop saturation runs
        # against the serving queue — goodput at an arrival rate under
        # a wait-p95 SLO, with the admission/shedding outcome rates.
        problems.extend(_validate_serve_block(rec.get("serve")))
        # Optional `stream` block (ISSUE 17): one churn batch against a
        # resident slab — cold full-run wall vs warm-start delta
        # re-cluster wall, same graph, same compile guard.
        problems.extend(_validate_stream_block(rec.get("stream")))
        # Optional `exchange` block (ISSUE 18): which SPMD exchange arm
        # the run used — a two-level record must carry its (dcn, ici)
        # factorization and per-device table/ghost bytes.
        problems.extend(_validate_exchange_block(rec.get("exchange")))
        # Optional `mix` block (schema v5, ISSUE 20): a skewed
        # two-class run — per-class goodput/wait_p95 plus the sub-row
        # packing counters of the packed-vs-per-class A/B.
        problems.extend(_validate_mix_block(rec.get("mix")))
    return problems


# Required keys of the optional `mix` bench block (schema v5 + ISSUE
# 20): one skewed two-class open-loop run.  merge_packing — which A/B
# arm ran (sub-row merging on, or plain per-class queues); the
# per-class goodput/wait split is what the acceptance compares at equal
# SLO; pack_util (occupied ROWS / padded rows) vs subrow_util (real
# graphs / total sub-row slots) are the two occupancy views that
# diverge exactly when merging happens; merged_batches counts the
# dispatches that actually packed sub-rows (0 in the per-class arm, and
# perf_regress refuses to compare across arms).
REQUIRED_MIX_KEYS = ("merge_packing", "small_goodput_jobs_per_s",
                     "big_goodput_jobs_per_s", "small_wait_p95_ms",
                     "big_wait_p95_ms", "pack_util", "merged_batches",
                     "subrow_util")


def _validate_mix_block(mix) -> list:
    if mix is None:
        return []
    if not isinstance(mix, dict):
        return [f"mix must be a dict, got {type(mix).__name__}"]
    problems = [f"mix block missing key {k!r}"
                for k in REQUIRED_MIX_KEYS if k not in mix]
    if problems:
        return problems
    if not isinstance(mix["merge_packing"], bool):
        problems.append(
            f"mix.merge_packing must be a bool, got "
            f"{mix['merge_packing']!r}")
    for k in ("small_goodput_jobs_per_s", "big_goodput_jobs_per_s",
              "small_wait_p95_ms", "big_wait_p95_ms"):
        v = mix[k]
        if not isinstance(v, (int, float)) or v < 0:
            problems.append(f"mix.{k} must be non-negative, got {v!r}")
    pu = mix["pack_util"]
    if not isinstance(pu, (int, float)) or not 0.0 < pu <= 1.0:
        problems.append(
            f"mix.pack_util must be a fraction in (0, 1], got {pu!r}")
    su = mix["subrow_util"]
    if not isinstance(su, (int, float)) or not 0.0 < su <= 1.0:
        problems.append(
            f"mix.subrow_util must be a fraction in (0, 1], got {su!r}")
    mb = mix["merged_batches"]
    if not isinstance(mb, int) or mb < 0:
        problems.append(
            f"mix.merged_batches must be a non-negative int, got {mb!r}")
    if mix["merge_packing"] is False and mb != 0:
        problems.append(
            "mix.merged_batches must be 0 when merge_packing is off "
            f"(got {mb}) — the per-class arm cannot have merged")
    return problems


# Required keys of the optional `batch` bench block (schema v4 + ISSUE
# 9): B — the padded batch size the compiled program ran at; jobs_per_s
# — real jobs completed per second of serving wall (packing, upload,
# phases, unpack); pack_util — real rows / padded rows (the pack tax).
# `engine` (ISSUE 10, always emitted by run_batch_bench) tags the
# batched per-phase engine so fused and bucketed serving trajectories
# never gate each other in tools/perf_regress.py; it stays OPTIONAL in
# validation — pre-ISSUE-10 v4 batch records could only be fused, and
# perf_regress's comparable() defaults the missing tag the same way, so
# a historical round log must not retroactively fail --self-check.
REQUIRED_BATCH_KEYS = ("B", "jobs_per_s", "pack_util")


def _validate_batch_block(batch) -> list:
    if batch is None:
        return []
    if not isinstance(batch, dict):
        return [f"batch must be a dict, got {type(batch).__name__}"]
    problems = [f"batch block missing key {k!r}"
                for k in REQUIRED_BATCH_KEYS if k not in batch]
    if problems:
        return problems
    if not isinstance(batch["B"], int) or batch["B"] < 1:
        problems.append(f"batch.B must be a positive int, "
                        f"got {batch['B']!r}")
    jps = batch["jobs_per_s"]
    if not isinstance(jps, (int, float)) or jps <= 0:
        problems.append(f"batch.jobs_per_s must be positive, got {jps!r}")
    pu = batch["pack_util"]
    if not isinstance(pu, (int, float)) or not 0.0 < pu <= 1.0:
        problems.append(
            f"batch.pack_util must be a fraction in (0, 1], got {pu!r}")
    if "engine" in batch and batch["engine"] not in BATCH_ENGINES:
        problems.append(
            f"batch.engine must be one of {BATCH_ENGINES}, "
            f"got {batch['engine']!r}")
    return problems


# Required keys of the optional `serve` bench block (schema v4 + ISSUE
# 11): one open-loop load-generator run against the serving queue.
# arrival_jobs_per_s — the OFFERED rate; goodput_jobs_per_s — jobs
# actually completed per second of wall (the serving capacity number);
# wait_p95_ms vs slo_ms — whether the queue-wait SLO held;
# admission — whether admission control was on (the A/B axis of the
# overload acceptance run); reject_rate / shed_rate — the fraction of
# offered jobs terminally rejected (admission) or shed (deadline).
# perf_regress gates goodput like-for-like (same b_max, admission,
# SLO, job shape, engine, pipeline mode).  `pipelined` (ISSUE 14) is
# REQUIRED: a serve record must say which dispatcher architecture ran —
# the pipelined goodput sits well above the serial one by design, so an
# untagged record would poison whichever trajectory it landed in.
# `autotuned_b_max` is optional: the rung the measured-service
# autotuner settled on, when autotuning moved the class off the config
# default.
REQUIRED_SERVE_KEYS = ("b_max", "arrival_jobs_per_s", "goodput_jobs_per_s",
                       "wait_p95_ms", "slo_ms", "admission", "reject_rate",
                       "shed_rate", "pipelined")


def _validate_serve_block(serve) -> list:
    if serve is None:
        return []
    if not isinstance(serve, dict):
        return [f"serve must be a dict, got {type(serve).__name__}"]
    problems = [f"serve block missing key {k!r}"
                for k in REQUIRED_SERVE_KEYS if k not in serve]
    if problems:
        return problems
    if not isinstance(serve["pipelined"], bool):
        problems.append(
            f"serve.pipelined must be a bool, got {serve['pipelined']!r}")
    ab = serve.get("autotuned_b_max")
    if ab is not None and (not isinstance(ab, int) or ab < 1):
        problems.append(
            f"serve.autotuned_b_max must be a positive int rung, "
            f"got {ab!r}")
    if not isinstance(serve["b_max"], int) or serve["b_max"] < 1:
        problems.append(
            f"serve.b_max must be a positive int, got {serve['b_max']!r}")
    for k in ("arrival_jobs_per_s", "goodput_jobs_per_s", "slo_ms"):
        v = serve[k]
        if not isinstance(v, (int, float)) or v <= 0:
            problems.append(f"serve.{k} must be positive, got {v!r}")
    w = serve["wait_p95_ms"]
    if not isinstance(w, (int, float)) or w < 0:
        problems.append(
            f"serve.wait_p95_ms must be non-negative, got {w!r}")
    if not isinstance(serve["admission"], bool):
        problems.append(
            f"serve.admission must be a bool, got {serve['admission']!r}")
    for k in ("reject_rate", "shed_rate"):
        v = serve[k]
        if not isinstance(v, (int, float)) or not 0.0 <= v <= 1.0:
            problems.append(
                f"serve.{k} must be a fraction in [0, 1], got {v!r}")
    if "engine" in serve and serve["engine"] not in BATCH_ENGINES:
        problems.append(
            f"serve.engine must be one of {BATCH_ENGINES}, "
            f"got {serve['engine']!r}")
    return problems


# Required keys of the optional `stream` bench block (schema v4 + ISSUE
# 17): cold_wall_s — a full cold re-cluster of the post-churn graph;
# delta_wall_s — apply_delta_slab + warm-start re-cluster of the SAME
# churn on a resident session; speedup — cold/delta (the streaming
# win); frontier_frac — the delta frontier's share of vertices (how
# local the churn was — the number the speedup must be read against).
# `warm` and `churn_frac` tag the A/B arm and the churn size so
# tools/perf_regress.py gates speedup like-for-like only.
REQUIRED_STREAM_KEYS = ("cold_wall_s", "delta_wall_s", "speedup",
                        "frontier_frac")

STREAM_WARM_MODES = ("labels", "plp", "cold")


def _validate_stream_block(stream) -> list:
    if stream is None:
        return []
    if not isinstance(stream, dict):
        return [f"stream must be a dict, got {type(stream).__name__}"]
    problems = [f"stream block missing key {k!r}"
                for k in REQUIRED_STREAM_KEYS if k not in stream]
    if problems:
        return problems
    for k in ("cold_wall_s", "delta_wall_s", "speedup"):
        v = stream[k]
        if not isinstance(v, (int, float)) or v <= 0:
            problems.append(f"stream.{k} must be positive, got {v!r}")
    ff = stream["frontier_frac"]
    if not isinstance(ff, (int, float)) or not 0.0 <= ff <= 1.0:
        problems.append(
            f"stream.frontier_frac must be a fraction in [0, 1], "
            f"got {ff!r}")
    if "warm" in stream and stream["warm"] not in STREAM_WARM_MODES:
        problems.append(
            f"stream.warm must be one of {STREAM_WARM_MODES}, "
            f"got {stream['warm']!r}")
    cf = stream.get("churn_frac")
    if cf is not None and not (isinstance(cf, (int, float))
                               and 0.0 < cf < 1.0):
        problems.append(
            f"stream.churn_frac must be a fraction in (0, 1), got {cf!r}")
    return problems


# Required keys of the optional `exchange` bench block (schema v4 +
# ISSUE 18) when the record ran the two-level exchange: dcn / ici — the
# hybrid-mesh factorization; table_bytes_per_device — the ICI-gathered
# group-table bytes per chip (the O(nv_total / dcn) figure the per-axis
# replication budget checks); ghost_bytes — the per-iteration DCN ghost
# payload.  Flat SPMD records carry only `mode`.  perf_regress treats
# flat and two-level records as separate arms on this block: shrinking
# the per-chip table window by |dcn| changes the exchange cost model,
# so their TEPS never gate each other.
REQUIRED_TWOLEVEL_KEYS = ("dcn", "ici", "table_bytes_per_device",
                          "ghost_bytes")

EXCHANGE_MODES = ("replicated", "sparse", "twolevel")


def _validate_exchange_block(exch) -> list:
    if exch is None:
        return []
    if not isinstance(exch, dict):
        return [f"exchange must be a dict, got {type(exch).__name__}"]
    mode = exch.get("mode")
    if mode not in EXCHANGE_MODES:
        return [f"exchange.mode must be one of {EXCHANGE_MODES}, "
                f"got {mode!r}"]
    problems = []
    if mode == "twolevel":
        problems += [f"a twolevel exchange block must carry {k!r}"
                     for k in REQUIRED_TWOLEVEL_KEYS if k not in exch]
        for k in REQUIRED_TWOLEVEL_KEYS:
            v = exch.get(k)
            if k in exch and (not isinstance(v, int) or v <= 0):
                problems.append(
                    f"exchange.{k} must be a positive int, got {v!r}")
    return problems


def _loadavg() -> float:
    try:
        with open("/proc/loadavg") as f:
            return float(f.read().split()[0])
    except OSError:  # non-Linux
        return -1.0


def _one_teps(res, wall: float) -> tuple:
    traversed = sum(p.num_edges * p.iterations for p in res.phases)
    clustering_s = sum(p.seconds for p in res.phases) or wall
    return traversed / clustering_s, clustering_s


def _init_backend(max_tries: int = 2, timeout_s: int = 75) -> str:
    """Decide which jax backend this process will use, with a hang guard.

    The axon TPU plugin's backend init is flaky in this image: it can
    raise or hang outright inside a native call.  The probe runs in a
    SUBPROCESS with a hard timeout; only when it proves the default
    backend healthy does this process touch it.  After exhausting
    retries, fall back to cpu so the bench always emits a result (the
    record then carries "platform": "cpu" and cannot be misattributed).
    """
    import subprocess

    import jax

    # Report the backend's REGISTRY name (e.g. 'axon'), not
    # Device.platform ('tpu'): jax_platforms matches registry names.
    probe = ("import jax; from jax._src import xla_bridge as xb; "
             "d = jax.devices(); "
             "n = [k for k, b in xb.backends().items() if b is d[0].client]; "
             "print(n[0] if n else d[0].platform, len(d))")
    for attempt in range(1, max_tries + 1):
        try:
            out = subprocess.run(
                [sys.executable, "-c", probe],
                capture_output=True, text=True, timeout=timeout_s,
            )
            if out.returncode == 0 and out.stdout.strip():
                plat, n = out.stdout.split()
                print(f"# backend: {plat} x{n} (probe attempt {attempt})",
                      file=sys.stderr)
                jax.config.update("jax_platforms", plat)
                return plat
            err = (out.stderr or "").strip().splitlines()
            print(f"# backend probe attempt {attempt}/{max_tries} failed "
                  f"(rc={out.returncode}): {err[-1] if err else '?'}",
                  file=sys.stderr)
        except subprocess.TimeoutExpired:
            print(f"# backend probe attempt {attempt}/{max_tries} hung "
                  f">{timeout_s}s, killed", file=sys.stderr)
        if attempt < max_tries:
            time.sleep(3 * attempt)
    print("# WARNING: default (TPU) backend unavailable after retries; "
          "falling back to cpu", file=sys.stderr)
    jax.config.update("jax_platforms", "cpu")
    return jax.devices()[0].platform


def run_bench(
    graph_source,
    *,
    engine: str = "auto",
    repeats: int = 3,
    budget_s: float = 420.0,
    platform: str = "cpu",
    graph_label: str = "?",
    scale: int | None = None,
    t_start: float | None = None,
    provenance: str | None = None,
) -> dict:
    """Warm-up + compile-guarded best-of-N timed runs -> bench record.

    ``graph_source`` is a Graph, or a zero-arg callable returning one
    per run (a factory; how the guard's own test injects a recompile).
    Raises :class:`BenchCompileGuardError` when the first timed run
    compiles anything new.
    """
    from cuvite_tpu.louvain.driver import louvain_phases
    from cuvite_tpu.obs import FlightRecorder, convergence_summary
    from cuvite_tpu.utils.trace import Tracer, rss_high_water_mb

    get = graph_source if callable(graph_source) else (lambda: graph_source)
    t_start = _T_PROC if t_start is None else t_start

    # The whole bench runs under ONE flight recorder: the warm-up's
    # compiles become the record's cold-compile events, and the HBM
    # ledger peaks over every run.  The watcher (promoted out of this
    # module into obs/compile_watch.py) is installed per window so the
    # guard keeps its historical delineation: warm-up compiles are
    # expected, first-timed-run compiles abort the bench.
    from cuvite_tpu.obs import NO_TRACE, CompileWatcher

    # NO_TRACE: the bench reads only compile_events and the HBM ledger —
    # an emitter would serialize every span/convergence payload inside
    # the timed windows for a record list nobody reads.
    frec = FlightRecorder(NO_TRACE, watch_compiles=False)

    # Warm-up: a full multi-phase run on the same (deterministic) graph
    # eats every compile, so the timed runs measure steady-state
    # execution (the reference likewise excludes one-time costs from its
    # clustering-time metric, main.cpp:499-518).
    t1 = time.perf_counter()
    warm_tr = Tracer(recorder=frec)
    with CompileWatcher(on_event=frec._on_compile):
        res = louvain_phases(get(), engine=engine, tracer=warm_tr)
    warm_wall = time.perf_counter() - t1
    elapsed = time.perf_counter() - t_start

    def record(res, wall, compile_guard, all_teps=(), load=(), tr=None):
        teps, clustering_s = _one_teps(res, wall)
        best = max((teps, *all_teps))
        print(f"# Q={res.modularity:.5f} phases={len(res.phases)} "
              f"iters={res.total_iterations} clustering={clustering_s:.2f}s "
              f"wall={wall:.2f}s guard={compile_guard}", file=sys.stderr)
        out = {
            "metric": "louvain_teps_per_chip",
            "value": round(best, 1),
            "unit": "traversed_edges/sec",
            "vs_baseline": round(best / BASELINE_EDGES_PER_SEC_PER_CHIP, 4),
            "platform": platform,
            "graph": graph_label,
            "modularity": round(float(res.modularity), 6),
            "phases": len(res.phases),
            "iterations": int(res.total_iterations),
            "rss_mb": round(rss_high_water_mb(), 1),
            "compile_guard": compile_guard,
            # Per-stage breakdown of the RECORDED run (schema v2): where
            # the phase-transition time goes — coarsen/upload vs iterate.
            "stages": (tr or Tracer()).breakdown(),
            "engine": engine,
            # Schema v4 (ISSUE 6): the flight recorder's telemetry —
            # per-phase convergence digests of the recorded run, every
            # XLA compile the whole bench saw (warm-up = cold cost; a
            # checked guard proves the timed runs added none), and the
            # per-buffer HBM peaks across all runs.
            "schema": BENCH_SCHEMA_VERSION,
            "convergence_summary": convergence_summary(
                getattr(res, "convergence", None)),
            "compile_events": [dict(e) for e in frec.compile_events],
            "hbm_peak_by_buffer": dict(frec.ledger.peak_by_buffer),
        }
        if scale is not None:
            out["scale"] = scale
        tr_counters = (tr.counters if tr is not None else {})
        co_total = tr_counters.get("coalesce_edges", 0)
        if co_total:
            # Edge-weighted dense-engine coverage of the inter-phase
            # coalesce (ISSUE 8): 0.0 = every coalesce took the
            # packed-sort fallback (the honest default until the chip
            # A/B promotes a dense engine).
            out["coalesce_kernel"] = round(
                tr_counters.get("coalesce_dense_edges", 0) / co_total, 4)
        rb_total = tr_counters.get("rebin_phases", 0)
        if rb_total:
            # Device-rebin coverage of the coarse bucketed phases
            # (ISSUE 19): 1.0 = every coarse plan was built on device
            # (coarsen/rebin.py), 0.0 = every one fell back to the host
            # BucketPlan.build.  The arm label that keeps device-rebin
            # and host-rebin records non-comparable in perf_regress.
            out["rebin_device"] = round(
                tr_counters.get("rebin_device_phases", 0) / rb_total, 4)
        if res.pallas_coverage is not None:
            # Kernel-coverage fields (schema v3): traversed-edge-weighted
            # fraction that ran the Pallas kernel + per-width hit counts,
            # so a pallas TEPS number carries its own honesty label.
            out["pallas_coverage"] = round(float(res.pallas_coverage), 4)
            out["pallas_width_hits"] = {
                str(w): int(n)
                for w, n in sorted(res.pallas_width_hits.items())}
        xs = getattr(res, "exchange_stats", None)
        if xs:
            # The run's SPMD exchange arm (ISSUE 18; validated by
            # _validate_exchange_block — a two-level record must carry
            # its factorization and per-device table/ghost bytes).
            out["exchange"] = {
                k: xs[k] for k in ("mode", "dcn", "ici",
                                   "table_bytes_per_device",
                                   "ghost_bytes") if k in xs}
        if not compile_guard["checked"]:
            out["compile_included"] = True
        if all_teps:
            # Contention telemetry (1-core host: concurrent work halves a
            # timed run): per-run list + loadavg make it visible at sight.
            out["runs"] = len(all_teps)
            out["teps_runs"] = [round(t, 1) for t in all_teps]
            out["spread"] = round(max(all_teps) / min(all_teps), 3)
        if load:
            out["loadavg"] = [round(x, 2) for x in load]
        if provenance:
            out["provenance"] = provenance
        return out

    if elapsed + 1.5 * warm_wall > budget_s:
        # A killed bench reports NOTHING; better a flagged warm-up number
        # than none.  compile_guard.checked=False marks it unguarded.
        print(f"# budget: {elapsed:.0f}s elapsed of {budget_s:.0f}s — "
              f"skipping the steady-state rerun", file=sys.stderr)
        return record(res, warm_wall,
                      {"checked": False, "reason": "budget"},
                      load=[_loadavg()], tr=warm_tr)
    del res  # free the warm-up labels (O(nv)) before the timed runs

    all_teps, loads = [], [_loadavg()]
    last_res, last_wall, last_tr = None, warm_wall, warm_tr
    guard = {"checked": True, "new_compiles": 0}
    while len(all_teps) < max(1, repeats):
        elapsed = time.perf_counter() - t_start
        if all_teps and elapsed + 1.2 * last_wall > budget_s:
            print(f"# budget: stopping after {len(all_teps)} timed runs "
                  f"({elapsed:.0f}s of {budget_s:.0f}s)", file=sys.stderr)
            break
        g = get()
        t1 = time.perf_counter()
        last_tr = Tracer(recorder=frec)
        if not all_teps:
            # THE gate: any fresh compile inside the first timed run
            # invalidates the whole measurement (VERDICT r5 weak #6).
            with CompileWatcher(on_event=frec._on_compile) as watch:
                last_res = louvain_phases(g, engine=engine, verbose=False,
                                          tracer=last_tr)
            if watch.compiles:
                raise BenchCompileGuardError(watch.compiles)
        else:
            last_res = louvain_phases(g, engine=engine, verbose=False,
                                      tracer=last_tr)
        last_wall = time.perf_counter() - t1
        teps, _ = _one_teps(last_res, last_wall)
        all_teps.append(teps)
        loads.append(_loadavg())
        print(f"# run {len(all_teps)}: {teps/1e6:.2f}M TEPS "
              f"(wall {last_wall:.1f}s, load {loads[-1]:.2f})",
              file=sys.stderr)
    return record(last_res, last_wall, guard, all_teps=all_teps,
                  load=loads, tr=last_tr)


def run_batch_bench(
    *,
    B: int,
    n_jobs: int | None = None,
    edges: int = 4096,
    seed: int = 1,
    repeats: int = 3,
    budget_s: float = 420.0,
    platform: str = "cpu",
    engine: str = "fused",
    t_start: float | None = None,
) -> dict:
    """Batched multi-tenant serving bench (ISSUE 9): K deterministic
    synth power-law graphs (distinct splitmix64 streams) through the
    batched driver in chunks of ``B``, compile-guarded like the TEPS
    bench.  The record keeps the standard schema (metric = aggregate
    TEPS over all tenants) and adds the ``batch`` block: B, jobs/sec of
    the best pass, pack_util, the slab class, the engine.  Compare
    records at the SAME class, B and engine only — perf_regress
    enforces that.

    ``engine`` (ISSUE 10): 'fused' or 'bucketed' (see louvain_many).
    Under 'bucketed' the bucket-plan geometry is pinned over the WHOLE
    job set (core.batch.bucket_shape_for), so every chunk runs the one
    phase-0 program the warm-up compiled — the (class, B, engine)
    one-compile guarantee the guard asserts.

    ``n_jobs`` defaults to 3*B rounded up to a multiple of B (so every
    pass runs whole batches and the warm-up covers the only
    (class, B, engine) program set the timed passes use; a partial tail
    batch would compile a second program inside the guard window).
    """
    from cuvite_tpu.core.batch import bucket_shape_for, slab_class_of
    from cuvite_tpu.louvain.driver import louvain_many
    from cuvite_tpu.obs import NO_TRACE, CompileWatcher, FlightRecorder
    from cuvite_tpu.utils.trace import Tracer, rss_high_water_mb
    from cuvite_tpu.workloads.synth import many_seed, synthesize_graph

    t_start = _T_PROC if t_start is None else t_start
    B = int(B)
    if B < 1:
        raise ValueError(f"--batch must be >= 1, got {B}")
    if engine not in BATCH_ENGINES:
        raise ValueError(f"--batch-engine must be one of {BATCH_ENGINES}, "
                         f"got {engine!r}")
    if n_jobs is None:
        n_jobs = 3 * B
    n_jobs = max(B, ((n_jobs + B - 1) // B) * B)
    graphs = [synthesize_graph(edges, seed=many_seed(seed, k))
              for k in range(n_jobs)]
    # Pin ONE slab class for the whole set: per-seed edge counts vary a
    # little, so an --batch-edges near a pow2 boundary would otherwise
    # straddle two classes and break the pack (and the one-compile
    # guarantee the guard asserts).  Elementwise max is the class every
    # graph fits.  The bucketed engine additionally pins ONE bucket-plan
    # geometry (the job-set union) for the same reason: per-chunk degree
    # histograms vary, and an unpinned chunk would compile its own
    # phase-0 program inside the guard window.
    cls = tuple(max(d) for d in zip(*(slab_class_of(g) for g in graphs)))
    shape = bucket_shape_for(graphs) if engine == "bucketed" else None
    chunks = [graphs[i:i + B] for i in range(0, n_jobs, B)]
    frec = FlightRecorder(NO_TRACE, watch_compiles=False)

    def one_pass(tracer):
        t0 = time.perf_counter()
        results = []
        batches = 0
        for chunk in chunks:
            br = louvain_many(chunk, b_pad=B, slab_class=cls,
                              engine=engine, bucket_shape=shape,
                              tracer=tracer)
            results.extend(br.results)
            batches += 1
        wall = time.perf_counter() - t0
        traversed = sum(p.num_edges * p.iterations
                        for r in results for p in r.phases)
        return results, wall, traversed, batches

    # Warm-up: ONE chunk suffices — every chunk runs the same
    # (class, B, engine) program set: the slab class and bucket geometry
    # are pinned above, and the serving-coarse shrink — the one
    # DATA-DEPENDENT branch (it fires iff every active row's coarse
    # graph fits class/4) — takes the same arm on every chunk of this
    # homogeneous synth set with ~100x margin (tenants coarsen to ~7
    # communities vs the 1024 floor).  If a pathological job set ever
    # split the branch, a timed chunk would compile the other arm and
    # the guard would abort loudly (rc=3) rather than mismeasure.
    warm_tr = Tracer(recorder=frec)
    with CompileWatcher(on_event=frec._on_compile):
        louvain_many(chunks[0], b_pad=B, slab_class=cls, engine=engine,
                     bucket_shape=shape, tracer=warm_tr)

    best = None
    guard = {"checked": True, "new_compiles": 0}
    passes = 0
    while passes < max(1, repeats):
        elapsed = time.perf_counter() - t_start
        if best is not None and elapsed + 1.2 * best[1] > budget_s:
            print(f"# budget: stopping after {passes} timed passes",
                  file=sys.stderr)
            break
        tr = Tracer(recorder=frec)
        if passes == 0:
            with CompileWatcher(on_event=frec._on_compile) as watch:
                out = one_pass(tr)
            if watch.compiles:
                raise BenchCompileGuardError(watch.compiles)
        else:
            out = one_pass(tr)
        passes += 1
        if best is None or out[1] < best[1]:
            best = out + (tr,)
        print(f"# pass {passes}: {n_jobs / out[1]:.1f} jobs/s "
              f"(wall {out[1]:.2f}s)", file=sys.stderr)

    results, wall, traversed, batches, tr = best
    from cuvite_tpu.obs import convergence_summary

    jobs_per_s = n_jobs / wall
    teps = traversed / wall
    qs = [float(r.modularity) for r in results]
    rec = {
        "metric": "louvain_teps_per_chip",
        "value": round(teps, 1),
        "unit": "traversed_edges/sec",
        "vs_baseline": round(teps / BASELINE_EDGES_PER_SEC_PER_CHIP, 4),
        "platform": platform,
        "graph": f"synthpl-{edges}x{n_jobs}",
        # Mean per-tenant Q (every tenant is an independent clustering;
        # per-tenant values live in the serving path, not the record).
        "modularity": round(sum(qs) / len(qs), 6),
        "phases": sum(len(r.phases) for r in results),
        "iterations": sum(int(r.total_iterations) for r in results),
        "rss_mb": round(rss_high_water_mb(), 1),
        "compile_guard": guard,
        "stages": tr.breakdown(),
        "engine": "batched",
        "schema": BENCH_SCHEMA_VERSION,
        # Tenant 0's convergence stands in for the batch (64 full
        # curves would dwarf the record; all tenants ride one program).
        "convergence_summary": convergence_summary(
            getattr(results[0], "convergence", None)),
        "compile_events": [dict(e) for e in frec.compile_events],
        "hbm_peak_by_buffer": dict(frec.ledger.peak_by_buffer),
        "batch": {
            "B": int(B),
            "jobs_per_s": round(jobs_per_s, 2),
            "pack_util": round(n_jobs / (batches * B), 4),
            "n_jobs": int(n_jobs),
            "batches": int(batches),
            "class": list(cls),
            "edges_each": int(edges),
            "engine": engine,
        },
    }
    return rec


def warm_serve_rungs(graphs, b_max: int, engine: str) -> tuple:
    """Serve-path compile warm-up: ONE batch at every BATCH_SIZES rung
    <= ``b_max`` with the job-set-pinned bucket geometry, because
    open-loop arrivals dispatch PARTIAL batches (linger/drain) whose
    padded size can be any rung.  Returns ``(slab_class, shape)`` for
    pinning the server.  Shared by :func:`run_serve_bench` and
    tools/serve_load.py so the rung policy cannot drift between them;
    call under a CompileWatcher when the compiles should be recorded.
    Raises when the job set straddles slab classes (the queue would
    split it over several bins and the warm-up could not cover them)."""
    from cuvite_tpu.core.batch import (
        BATCH_SIZES,
        batch_pad,
        bucket_shape_for,
        slab_class_of,
    )
    from cuvite_tpu.louvain.driver import louvain_many

    # ServeConfig rounds b_max UP to a BATCH_SIZES rung; warm the
    # ROUNDED ladder or a non-rung b_max (say 10 -> 16) would compile
    # its full-bin program inside the guarded timed loop.
    b_max = min(batch_pad(b_max), BATCH_SIZES[-1])
    classes = {slab_class_of(g) for g in graphs}
    if len(classes) != 1:
        raise ValueError(
            f"serve job set straddles slab classes {sorted(classes)}; "
            "pick an edge count away from a pow2 boundary so the queue "
            "serves one bin")
    cls = classes.pop()
    shape = bucket_shape_for(graphs) if engine == "bucketed" else None
    for r in (r for r in BATCH_SIZES if r <= b_max):
        louvain_many(graphs[:r], b_pad=r, slab_class=cls, engine=engine,
                     bucket_shape=shape)
    return cls, shape


def run_serve_bench(
    *,
    rate: float,
    b_max: int = 8,
    edges: int = 1024,
    n_jobs: int | None = None,
    seed: int = 1,
    slo_ms: float = 500.0,
    admission: bool = True,
    linger_ms: float = 20.0,
    deadline_ms: float | None = None,
    tenants: int = 1,
    engine: str = "bucketed",
    platform: str = "cpu",
    budget_s: float = 420.0,
    pipelined: bool = False,
    autotune: bool = False,
    t_start: float | None = None,
) -> dict:
    """Open-loop serving bench (ISSUE 11): offer ``n_jobs``
    deterministic synth graphs to a fresh ``LouvainServer`` at
    ``rate`` jobs/s (scheduled arrival stamps, serve/loadgen.py), then
    drain; the record carries the ``serve`` block (goodput at the
    offered rate, queue-wait p95 vs the SLO, reject/shed outcome
    rates).  ``admission=False`` is the overload A/B arm: same rate,
    no intake bound — the run that shows unbounded queue-wait growth.

    ``pipelined`` (ISSUE 14) drives the two-stage dispatcher (packer
    overlaps executor; serve/pipeline.py) instead of the serial
    in-loop ``step()``; the record's ``serve.pipelined`` keeps the two
    architectures' goodput trajectories apart in perf_regress.
    ``autotune`` enables measured-service b_max autotuning (needs
    admission); the rung the tuner settles on lands in
    ``serve.autotuned_b_max``.

    Compile discipline: the warm-up runs ONE batch at every
    BATCH_SIZES rung <= ``b_max`` with the job-set-pinned bucket
    geometry, because open-loop arrivals dispatch PARTIAL batches
    (linger/drain) whose padded size can be any rung — unlike the
    closed chunking of :func:`run_batch_bench`, where one rung
    suffices.  The timed open loop then runs under the compile guard
    like every other bench.
    """
    from cuvite_tpu.obs import (
        NO_TRACE,
        CompileWatcher,
        FlightRecorder,
        convergence_summary,
    )
    from cuvite_tpu.serve import AdmissionConfig, LouvainServer, ServeConfig
    from cuvite_tpu.serve.loadgen import run_open_loop
    from cuvite_tpu.utils.trace import Tracer, rss_high_water_mb
    from cuvite_tpu.workloads.synth import many_seed, synthesize_graph

    from cuvite_tpu.core.batch import BATCH_SIZES, batch_pad

    t_start = _T_PROC if t_start is None else t_start
    if rate <= 0:
        raise ValueError(f"--serve-rate must be > 0 jobs/s, got {rate}")
    if engine not in BATCH_ENGINES:
        raise ValueError(f"serve engine must be one of {BATCH_ENGINES}, "
                         f"got {engine!r}")
    # Round to the rung ServeConfig will serve at, so the record's
    # serve.b_max matches the queue's actual batch cap.
    b_max = min(batch_pad(int(b_max)), BATCH_SIZES[-1])
    if n_jobs is None:
        n_jobs = max(4 * b_max, 32)
    graphs = [synthesize_graph(edges, seed=many_seed(seed, k))
              for k in range(n_jobs)]
    frec = FlightRecorder(NO_TRACE, watch_compiles=False)

    # Warm-up: every rung a partial batch can pad to, one batch each,
    # geometry pinned over the whole job set (the shared helper keeps
    # this policy in lockstep with tools/serve_load.py).
    with CompileWatcher(on_event=frec._on_compile):
        cls, shape = warm_serve_rungs(graphs, b_max, engine)
    elapsed = time.perf_counter() - t_start
    if elapsed > budget_s:
        raise RuntimeError(
            f"serve bench warm-up alone spent {elapsed:.0f}s of the "
            f"{budget_s:.0f}s budget; shrink --serve-b-max/--batch-edges")

    if autotune and not admission:
        raise ValueError("--serve-autotune needs admission on (the "
                         "tuner reads the admission SLO + estimator)")
    config = ServeConfig(
        b_max=b_max, linger_s=linger_ms / 1e3, engine=engine,
        admission=(AdmissionConfig(wait_slo_s=slo_ms / 1e3)
                   if admission else None),
        autotune_b_max=bool(autotune))
    tr = Tracer(recorder=frec)
    server = LouvainServer(config, tracer=tr)
    if shape is not None:
        server.pin_shape(cls, shape)
    with CompileWatcher(on_event=frec._on_compile) as watch:
        rep = run_open_loop(
            server, graphs, rate, tenants=tenants,
            deadline_s=(deadline_ms / 1e3 if deadline_ms is not None
                        else None),
            max_wall_s=max(budget_s - elapsed, 30.0),
            pipelined=pipelined)
    if watch.compiles:
        raise BenchCompileGuardError(watch.compiles)
    if not rep.results:
        raise RuntimeError(
            "serve bench completed no jobs (everything rejected/shed); "
            "the record would carry no throughput — lower --serve-rate")
    if not rep.conservation["ok"]:
        raise RuntimeError(
            f"job-conservation violation: {rep.conservation}")

    results = [r for _, r in rep.results]
    stats_snap = server.stats.to_dict()   # one atomic snapshot
    traversed = sum(p.num_edges * p.iterations
                    for r in results for p in r.phases)
    teps = traversed / max(rep.wall_s, 1e-9)
    qs = [float(r.modularity) for r in results]
    print(f"# serve: rate={rate:.1f}/s goodput="
          f"{rep.goodput_jobs_per_s:.1f}/s wait_p95="
          f"{rep.wait_p95_s * 1e3:.0f}ms (slo {slo_ms:.0f}ms) "
          f"rejected={rep.rejected} shed={rep.shed}", file=sys.stderr)
    return {
        "metric": "louvain_teps_per_chip",
        "value": round(teps, 1),
        "unit": "traversed_edges/sec",
        "vs_baseline": round(teps / BASELINE_EDGES_PER_SEC_PER_CHIP, 4),
        "platform": platform,
        "graph": f"synthpl-{edges}x{n_jobs}-serve",
        "modularity": round(sum(qs) / len(qs), 6),
        "phases": sum(len(r.phases) for r in results),
        "iterations": sum(int(r.total_iterations) for r in results),
        "rss_mb": round(rss_high_water_mb(), 1),
        "compile_guard": {"checked": True, "new_compiles": 0},
        "stages": tr.breakdown(),
        "engine": "batched",
        "schema": BENCH_SCHEMA_VERSION,
        "convergence_summary": convergence_summary(
            getattr(results[0], "convergence", None)),
        "compile_events": [dict(e) for e in frec.compile_events],
        "hbm_peak_by_buffer": dict(frec.ledger.peak_by_buffer),
        "serve": {
            "b_max": int(b_max),
            "engine": engine,
            "pipelined": bool(pipelined),
            **({"autotuned_b_max": int(next(iter(tuned.values())))}
               if (tuned := server.autotuned()) else {}),
            "overlap_frac": stats_snap["overlap_frac"],
            "pack_s": stats_snap["pack_s"],
            "device_s": stats_snap["device_s"],
            "arrival_jobs_per_s": round(rate, 3),
            "goodput_jobs_per_s": round(rep.goodput_jobs_per_s, 3),
            "wait_p50_ms": round(rep.wait_p50_s * 1e3, 3),
            "wait_p95_ms": round(rep.wait_p95_s * 1e3, 3),
            "slo_ms": float(slo_ms),
            "slo_met": bool(rep.wait_p95_s * 1e3 <= slo_ms),
            "admission": bool(admission),
            "reject_rate": round(rep.reject_rate, 4),
            "shed_rate": round(rep.shed_rate, 4),
            "offered": int(rep.offered),
            "done": int(rep.done),
            "rejected": int(rep.rejected),
            "shed": int(rep.shed),
            "failed": int(rep.failed),
            "edges_each": int(edges),
            "linger_ms": float(linger_ms),
            "wall_s": round(rep.wall_s, 3),
        },
    }


def warm_subrow_rungs(smalls, layout, b_max: int) -> None:
    """Merged-program compile warm-up (ISSUE 20): one packed batch at
    every rows-rung <= ``b_max`` under ``layout`` — a merge pops up to
    ``b_max * n_sub`` jobs, so packed dispatches pad to any rows-rung
    up to the class cap.  Sub-row OCCUPANCY never enters the compile
    key, so warming each rung at whatever occupancy the pool allows
    covers every packed batch the timed run can dispatch."""
    from cuvite_tpu.core.batch import BATCH_SIZES, batch_pad
    from cuvite_tpu.louvain.batched import cluster_packed

    b_max = min(batch_pad(int(b_max)), BATCH_SIZES[-1])
    for r in (r for r in BATCH_SIZES if r <= b_max):
        take = min(r * layout.n_sub, len(smalls))
        cluster_packed(smalls[:take], layout, b_pad=r)


def run_mixed_serve_bench(
    *,
    rate: float,
    merge_packing: bool,
    b_max: int = 4,
    small_edges: int = 1024,
    big_scale: int = 13,
    big_edge_factor: int = 2,
    n_small: int | None = None,
    n_big: int | None = None,
    seed: int = 1,
    slo_ms: float = 500.0,
    linger_ms: float = 20.0,
    engine: str = "bucketed",
    platform: str = "cpu",
    budget_s: float = 420.0,
    pipelined: bool = False,
    t_start: float | None = None,
) -> dict:
    """Skewed two-class open-loop serving bench (ISSUE 20): a 90:10
    small:big arrival mix (``mix_schedule``) offered at ``rate`` jobs/s
    to one server, drained, and reported with the per-class split —
    the ``merge_packing`` flag is THE A/B axis: on, small-class bins
    may pack as fenced sub-rows of the big class's compiled program;
    off, each class queues and batches strictly among its own.

    Compile discipline: warm-up covers every plain rung of BOTH
    classes (warm_serve_rungs per pool) and, in the merged arm, every
    packed rows-rung (warm_subrow_rungs) — the timed loop then runs
    under the same compile guard as every other bench.
    """
    from cuvite_tpu.core.batch import (
        BATCH_SIZES,
        batch_pad,
        slab_class_of,
        subrow_layout_for,
    )
    from cuvite_tpu.io.generate import generate_rmat
    from cuvite_tpu.obs import (
        NO_TRACE,
        CompileWatcher,
        FlightRecorder,
        convergence_summary,
    )
    from cuvite_tpu.serve import AdmissionConfig, LouvainServer, ServeConfig
    from cuvite_tpu.serve.loadgen import run_mixed_open_loop
    from cuvite_tpu.utils.trace import Tracer, rss_high_water_mb
    from cuvite_tpu.workloads.synth import many_seed, synthesize_graph

    t_start = _T_PROC if t_start is None else t_start
    if rate <= 0:
        raise ValueError(f"mix rate must be > 0 jobs/s, got {rate}")
    b_max = min(batch_pad(int(b_max)), BATCH_SIZES[-1])
    # 90:10 by COUNT: nine smalls per big, enough work that the packed
    # arm's linger-vs-merge decision actually faces contended bins.
    if n_big is None:
        n_big = max(2 * b_max, 8)
    if n_small is None:
        n_small = 9 * n_big
    smalls = [synthesize_graph(small_edges, seed=many_seed(seed, k))
              for k in range(n_small)]
    bigs = [generate_rmat(big_scale, edge_factor=big_edge_factor,
                          seed=seed * 1000 + k) for k in range(n_big)]
    cls_s, cls_b = slab_class_of(smalls[0]), slab_class_of(bigs[0])
    layout = subrow_layout_for(cls_s, cls_b)
    if layout is None:
        raise ValueError(
            f"big class {cls_b} is not an exact pow2 sub-row multiple of "
            f"small class {cls_s}; pick --mix-big-scale/--mix-big-ef so "
            "the mix has a packable layout")
    frec = FlightRecorder(NO_TRACE, watch_compiles=False)
    with CompileWatcher(on_event=frec._on_compile):
        _, shape_s = warm_serve_rungs(smalls, b_max, engine)
        _, shape_b = warm_serve_rungs(bigs, b_max, engine)
        if merge_packing:
            warm_subrow_rungs(smalls, layout, b_max)
    elapsed = time.perf_counter() - t_start
    if elapsed > budget_s:
        raise RuntimeError(
            f"mix bench warm-up alone spent {elapsed:.0f}s of the "
            f"{budget_s:.0f}s budget; shrink --serve-b-max or the pools")

    config = ServeConfig(
        b_max=b_max, linger_s=linger_ms / 1e3, engine=engine,
        admission=AdmissionConfig(wait_slo_s=slo_ms / 1e3),
        merge_packing=bool(merge_packing))
    tr = Tracer(recorder=frec)
    server = LouvainServer(config, tracer=tr)
    if shape_s is not None:
        server.pin_shape(cls_s, shape_s)
    if shape_b is not None:
        server.pin_shape(cls_b, shape_b)
    with CompileWatcher(on_event=frec._on_compile) as watch:
        mrep = run_mixed_open_loop(
            server, smalls, bigs, rate,
            max_wall_s=max(budget_s - elapsed, 30.0), pipelined=pipelined)
    if watch.compiles:
        raise BenchCompileGuardError(watch.compiles)
    rep = mrep.report
    if not rep.results:
        raise RuntimeError("mix bench completed no jobs; lower the rate")
    if not rep.conservation["ok"]:
        raise RuntimeError(
            f"job-conservation violation: {rep.conservation}")

    results = [r for _, r in rep.results]
    traversed = sum(p.num_edges * p.iterations
                    for r in results for p in r.phases)
    teps = traversed / max(rep.wall_s, 1e-9)
    qs = [float(r.modularity) for r in results]
    small, big = mrep.per_class["small"], mrep.per_class["big"]
    print(f"# mix[{'packed' if merge_packing else 'per-class'}]: "
          f"rate={rate:.1f}/s goodput={rep.goodput_jobs_per_s:.1f}/s "
          f"small p95={small['wait_p95_s'] * 1e3:.0f}ms "
          f"big p95={big['wait_p95_s'] * 1e3:.0f}ms "
          f"merged={mrep.merged_batches} "
          f"subrow_util={mrep.subrow_util:.2f}", file=sys.stderr)
    return {
        "metric": "louvain_teps_per_chip",
        "value": round(teps, 1),
        "unit": "traversed_edges/sec",
        "vs_baseline": round(teps / BASELINE_EDGES_PER_SEC_PER_CHIP, 4),
        "platform": platform,
        "graph": (f"mixpl-{small_edges}x{n_small}"
                  f"+rmat{big_scale}ef{big_edge_factor}x{n_big}"),
        "modularity": round(sum(qs) / len(qs), 6),
        "phases": sum(len(r.phases) for r in results),
        "iterations": sum(int(r.total_iterations) for r in results),
        "rss_mb": round(rss_high_water_mb(), 1),
        "compile_guard": {"checked": True, "new_compiles": 0},
        "stages": tr.breakdown(),
        "engine": "batched",
        "schema": BENCH_SCHEMA_VERSION,
        "convergence_summary": convergence_summary(
            getattr(results[0], "convergence", None)),
        "compile_events": [dict(e) for e in frec.compile_events],
        "hbm_peak_by_buffer": dict(frec.ledger.peak_by_buffer),
        "serve": {
            "b_max": int(b_max),
            "engine": engine,
            "pipelined": bool(pipelined),
            "merge_packing": bool(merge_packing),
            "overlap_frac": rep.stats["overlap_frac"],
            "pack_s": rep.stats["pack_s"],
            "device_s": rep.stats["device_s"],
            "arrival_jobs_per_s": round(rate, 3),
            "goodput_jobs_per_s": round(rep.goodput_jobs_per_s, 3),
            "wait_p50_ms": round(rep.wait_p50_s * 1e3, 3),
            "wait_p95_ms": round(rep.wait_p95_s * 1e3, 3),
            "slo_ms": float(slo_ms),
            "slo_met": bool(rep.wait_p95_s * 1e3 <= slo_ms),
            "admission": True,
            "reject_rate": round(rep.reject_rate, 4),
            "shed_rate": round(rep.shed_rate, 4),
            "offered": int(rep.offered),
            "done": int(rep.done),
            "rejected": int(rep.rejected),
            "shed": int(rep.shed),
            "failed": int(rep.failed),
            "edges_each": int(small_edges),
            "linger_ms": float(linger_ms),
            "wall_s": round(rep.wall_s, 3),
        },
        "mix": {
            "merge_packing": bool(merge_packing),
            "ratio": [int(n_small), int(n_big)],
            "small_class": list(cls_s),
            "big_class": list(cls_b),
            "n_sub": int(layout.n_sub),
            "small_goodput_jobs_per_s": round(
                small["goodput_jobs_per_s"], 3),
            "big_goodput_jobs_per_s": round(big["goodput_jobs_per_s"], 3),
            "small_wait_p95_ms": round(small["wait_p95_s"] * 1e3, 3),
            "big_wait_p95_ms": round(big["wait_p95_s"] * 1e3, 3),
            "small_done": int(small["done"]),
            "big_done": int(big["done"]),
            "pack_util": round(mrep.pack_util, 4),
            "subrow_util": round(mrep.subrow_util, 4),
            "merged_batches": int(mrep.merged_batches),
        },
    }


def run_churn_bench(
    *,
    churn_frac: float,
    scale: int,
    edge_factor: int = 16,
    warm: str = "labels",
    seed: int = 1,
    platform: str = "cpu",
    budget_s: float = 420.0,
    t_start: float | None = None,
) -> dict:
    """Streaming warm-start A/B (ISSUE 17): ONE deterministic churn
    batch (``churn_frac`` of the undirected pairs deleted, as many
    inserted; workloads/synth.churn_batches) against an rmat-``scale``
    graph, measured two ways on the SAME machine state:

    * cold — a fresh resident session re-clusters the post-churn graph
      from scratch (``warm='cold'``: identity seed, full active set);
    * delta — the resident session ingests the batch through
      ``apply_delta_slab`` and re-clusters with ``warm`` seeding
      (previous labels + delta frontier, or the PLP prepass arm).

    Compile discipline matches every other bench: a full warm-up pass
    exercises BOTH paths (cold re-cluster, delta apply, warm
    re-cluster) on a throwaway session, then the timed passes run under
    the compile guard — the streaming claim is *zero fresh compiles per
    delta*, so a compile inside the timed window is not noise, it is
    the regression itself.  The record carries the ``stream`` block
    (cold_wall_s, delta_wall_s, speedup, frontier_frac).
    """
    from cuvite_tpu.io.generate import generate_rmat
    from cuvite_tpu.obs import (
        NO_TRACE,
        CompileWatcher,
        FlightRecorder,
        convergence_summary,
    )
    from cuvite_tpu.stream import DeltaBatch, StreamSession
    from cuvite_tpu.utils.trace import Tracer, rss_high_water_mb
    from cuvite_tpu.workloads.synth import churn_batches

    t_start = _T_PROC if t_start is None else t_start
    if not 0.0 < churn_frac < 1.0:
        raise ValueError(
            f"--churn-frac must be in (0, 1), got {churn_frac}")
    if warm not in STREAM_WARM_MODES:
        raise ValueError(f"--warm-start must be one of "
                         f"{STREAM_WARM_MODES}, got {warm!r}")

    t0 = time.perf_counter()
    graph = generate_rmat(scale, edge_factor=edge_factor, seed=seed)
    print(f"# graph: rmat scale={scale} nv={graph.num_vertices} "
          f"ne={graph.num_edges} gen={time.perf_counter()-t0:.1f}s",
          file=sys.stderr)
    edits = churn_batches(graph, frac=churn_frac, seed=seed)[0]
    batch = DeltaBatch.from_edits(
        graph.num_vertices,
        ins_src=edits["ins_src"], ins_dst=edits["ins_dst"],
        ins_w=edits["ins_w"],
        del_src=edits["del_src"], del_dst=edits["del_dst"])

    frec = FlightRecorder(NO_TRACE, watch_compiles=False)

    # Warm-up: both timed paths, end to end, on a throwaway session.
    with CompileWatcher(on_event=frec._on_compile):
        wsess = StreamSession.from_graph(graph)
        wsess.recluster(warm="cold")
        wsess.apply_delta(batch)
        wsess.recluster(warm=warm)
        del wsess
    elapsed = time.perf_counter() - t_start
    if elapsed > budget_s:
        raise RuntimeError(
            f"churn bench warm-up alone spent {elapsed:.0f}s of the "
            f"{budget_s:.0f}s budget; shrink --scale")

    tr = Tracer(recorder=frec)
    sess = StreamSession.from_graph(graph, tracer=tr)
    with CompileWatcher(on_event=frec._on_compile) as watch:
        # Cold arm FIRST, on the pre-churn slab: its wall is the "full
        # re-run" a non-streaming deployment would pay per update.
        t1 = time.perf_counter()
        res_cold = sess.recluster(warm="cold")
        cold_wall = time.perf_counter() - t1
        # Delta arm: ingest + warm-start re-cluster on the SAME session.
        t1 = time.perf_counter()
        info = sess.apply_delta(batch)
        res_warm = sess.recluster(warm=warm)
        delta_wall = time.perf_counter() - t1
    if watch.compiles:
        raise BenchCompileGuardError(watch.compiles)

    teps, _clustering_s = _one_teps(res_cold, cold_wall)
    speedup = cold_wall / max(delta_wall, 1e-9)
    print(f"# stream: cold={cold_wall:.2f}s delta={delta_wall:.2f}s "
          f"speedup={speedup:.1f}x frontier={info['frontier_frac']:.4f} "
          f"Q_cold={res_cold.modularity:.5f} "
          f"Q_warm={res_warm.modularity:.5f}", file=sys.stderr)
    return {
        "metric": "louvain_teps_per_chip",
        "value": round(teps, 1),
        "unit": "traversed_edges/sec",
        "vs_baseline": round(teps / BASELINE_EDGES_PER_SEC_PER_CHIP, 4),
        "platform": platform,
        "graph": f"rmat{scale}",
        "scale": int(scale),
        # The DELTA arm's quality — the number the golden envelope
        # judges (a warm start that converged somewhere worse must not
        # hide behind the cold run's Q).
        "modularity": round(float(res_warm.modularity), 6),
        "phases": len(res_warm.phases),
        "iterations": int(res_warm.total_iterations),
        "rss_mb": round(rss_high_water_mb(), 1),
        "compile_guard": {"checked": True, "new_compiles": 0},
        "stages": tr.breakdown(),
        "engine": "fused",
        "schema": BENCH_SCHEMA_VERSION,
        "convergence_summary": convergence_summary(
            getattr(res_warm, "convergence", None)),
        "compile_events": [dict(e) for e in frec.compile_events],
        "hbm_peak_by_buffer": dict(frec.ledger.peak_by_buffer),
        "stream": {
            "cold_wall_s": round(cold_wall, 4),
            "delta_wall_s": round(delta_wall, 4),
            "speedup": round(speedup, 3),
            "frontier_frac": round(float(info["frontier_frac"]), 5),
            "warm": warm,
            "churn_frac": float(churn_frac),
            "n_ins": int(info["n_ins"]),
            "n_del": int(info["n_del"]),
            "modularity_cold": round(float(res_cold.modularity), 6),
        },
    }


def _build_parser() -> argparse.ArgumentParser:
    env = os.environ
    p = argparse.ArgumentParser(
        prog="python -m cuvite_tpu.workloads bench",
        description="hardened Louvain TEPS benchmark")
    p.add_argument("--file", help="Vite binary graph input")
    p.add_argument("--bits64", action="store_true")
    p.add_argument("--graph", default=env.get("BENCH_GRAPH", "rmat"),
                   choices=["rmat", "rgg"],
                   help="generated-graph kind when --file is absent")
    p.add_argument("--scale", type=int,
                   default=int(env["BENCH_SCALE"])
                   if "BENCH_SCALE" in env else None)
    p.add_argument("--edge-factor", type=int,
                   default=int(env.get("BENCH_EF", "16")))
    p.add_argument("--engine", default=env.get("BENCH_ENGINE", "auto"))
    p.add_argument("--repeats", type=int,
                   default=int(env.get("BENCH_REPEATS", "3")))
    p.add_argument("--budget", type=float,
                   default=float(env.get("BENCH_TIME_BUDGET", "420")))
    p.add_argument("--out", metavar="FILE",
                   help="also write the JSON record to FILE")
    b = p.add_argument_group("batched multi-tenant serving (ISSUE 9)")
    b.add_argument("--batch", type=int, metavar="B",
                   default=int(env["BENCH_BATCH"])
                   if "BENCH_BATCH" in env else None,
                   help="serve K synth power-law graphs through the "
                        "batched driver in chunks of B; the record "
                        "carries the `batch` block (jobs_per_s, "
                        "pack_util)")
    b.add_argument("--batch-engine", default=env.get("BENCH_BATCH_ENGINE",
                                                     "fused"),
                   choices=list(BATCH_ENGINES),
                   help="batched per-phase engine (ISSUE 10): 'fused' "
                        "(PR 9's sort-formulation loop, every phase) or "
                        "'bucketed' (sort-free vmapped bucketed phase 0 "
                        "+ serving-coarse fused phases); the record's "
                        "batch.engine field keeps the trajectories "
                        "apart in perf_regress")
    b.add_argument("--batch-jobs", type=int, default=None,
                   help="total jobs K (default 3*B, rounded up to a "
                        "multiple of B)")
    b.add_argument("--batch-edges", type=int, default=4096,
                   help="directed edge records per synthetic graph")
    b.add_argument("--host-devices", type=int, default=8,
                   help="virtual CPU devices to shard the batch axis "
                        "over (batch mode, cpu platform only)")
    s = p.add_argument_group("open-loop serving bench (ISSUE 11)")
    s.add_argument("--serve-rate", type=float, metavar="JOBS_PER_S",
                   default=float(env["BENCH_SERVE_RATE"])
                   if "BENCH_SERVE_RATE" in env else None,
                   help="offer synth jobs to the serving queue at this "
                        "open-loop arrival rate; the record carries the "
                        "`serve` block (goodput, wait_p95 vs SLO, "
                        "reject/shed rates).  Uses --batch-edges / "
                        "--batch-engine / --batch-jobs for the job set")
    s.add_argument("--serve-b-max", type=int, default=8,
                   help="serving queue b_max (BATCH_SIZES rung)")
    s.add_argument("--serve-slo-ms", type=float, default=500.0,
                   help="queue-wait p95 SLO the admission controller "
                        "defends")
    s.add_argument("--serve-admission", default="on", choices=["on", "off"],
                   help="'off' is the overload A/B arm: no intake bound, "
                        "queue waits free to grow past the SLO")
    s.add_argument("--serve-linger-ms", type=float, default=20.0)
    s.add_argument("--serve-deadline-ms", type=float, default=None,
                   help="attach a relative deadline to every job "
                        "(exercises shedding)")
    s.add_argument("--serve-tenants", type=int, default=1,
                   help="spread jobs round-robin over N tenant ids")
    s.add_argument("--serve-pipeline", default="off", choices=["on", "off"],
                   help="'on' drives the two-stage pipelined dispatcher "
                        "(ISSUE 14: host pack overlaps device execute); "
                        "the record's serve.pipelined keeps the "
                        "trajectories apart in perf_regress")
    s.add_argument("--serve-autotune", action="store_true",
                   help="measured-service b_max autotuning (needs "
                        "admission on); the settled rung lands in "
                        "serve.autotuned_b_max")
    c = p.add_argument_group("streaming churn A/B (ISSUE 17)")
    c.add_argument("--churn-frac", type=float, metavar="FRAC",
                   default=float(env["BENCH_CHURN_FRAC"])
                   if "BENCH_CHURN_FRAC" in env else None,
                   help="one deterministic churn batch (FRAC of the "
                        "undirected pairs deleted + as many inserted) "
                        "against an rmat --scale graph: cold full "
                        "re-cluster vs apply_delta_slab + warm-start "
                        "re-cluster on a resident session; the record "
                        "carries the `stream` block (cold_wall_s, "
                        "delta_wall_s, speedup, frontier_frac)")
    c.add_argument("--warm-start", default="labels",
                   choices=list(STREAM_WARM_MODES),
                   help="delta-arm seeding: 'labels' (previous run's "
                        "composed labels + delta frontier), 'plp' (the "
                        "label-propagation prepass A/B alternative), or "
                        "'cold' (identity — the null arm)")
    return p


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)

    if args.churn_frac is not None:
        if args.batch is not None or args.serve_rate is not None:
            print("# --churn-frac, --batch and --serve-rate are "
                  "different benches; pick one", file=sys.stderr)
            return 2
        if args.file:
            print("# --churn-frac generates its own rmat graph: --file "
                  "does not apply (use --scale)", file=sys.stderr)
            return 2
        from cuvite_tpu.utils.compile_cache import enable_compile_cache

        enable_compile_cache()
        platform = _init_backend()
        scale = args.scale if args.scale is not None else (
            18 if platform == "cpu" else 20)
        try:
            rec = run_churn_bench(
                churn_frac=args.churn_frac, scale=scale,
                edge_factor=args.edge_factor, warm=args.warm_start,
                platform=platform, budget_s=args.budget,
            )
        except BenchCompileGuardError as e:
            print(f"# BENCH ABORTED: {e}", file=sys.stderr)
            for line in e.compile_log:
                print(f"#   {line[:200]}", file=sys.stderr)
            return 3
        problems = validate_record(rec)
        if problems:
            print(f"# BENCH ABORTED: invalid record: {problems}",
                  file=sys.stderr)
            return 4
        line = json.dumps(rec)
        print(line)
        if args.out:
            with open(args.out, "w", encoding="utf-8") as f:
                f.write(line + "\n")
        return 0

    if args.serve_rate is not None:
        if args.batch is not None:
            print("# --serve-rate and --batch are different benches; "
                  "pick one", file=sys.stderr)
            return 2
        if args.file or args.scale is not None:
            print("# --serve-rate is the synthetic serving bench: "
                  "--file/--scale do not apply", file=sys.stderr)
            return 2
        from cuvite_tpu.utils.envknob import request_host_devices

        request_host_devices(args.host_devices)
        from cuvite_tpu.utils.compile_cache import enable_compile_cache

        enable_compile_cache()
        platform = _init_backend()
        try:
            rec = run_serve_bench(
                rate=args.serve_rate, b_max=args.serve_b_max,
                edges=args.batch_edges, n_jobs=args.batch_jobs,
                slo_ms=args.serve_slo_ms,
                admission=args.serve_admission == "on",
                linger_ms=args.serve_linger_ms,
                deadline_ms=args.serve_deadline_ms,
                tenants=args.serve_tenants,
                engine=args.batch_engine, platform=platform,
                budget_s=args.budget,
                pipelined=args.serve_pipeline == "on",
                autotune=args.serve_autotune,
            )
        except BenchCompileGuardError as e:
            print(f"# BENCH ABORTED: {e}", file=sys.stderr)
            for line in e.compile_log:
                print(f"#   {line[:200]}", file=sys.stderr)
            return 3
        problems = validate_record(rec)
        if problems:
            print(f"# BENCH ABORTED: invalid record: {problems}",
                  file=sys.stderr)
            return 4
        line = json.dumps(rec)
        print(line)
        if args.out:
            with open(args.out, "w", encoding="utf-8") as f:
                f.write(line + "\n")
        return 0

    if args.batch is not None:
        if args.batch < 1:
            print(f"# --batch must be >= 1, got {args.batch}",
                  file=sys.stderr)
            return 2
        # The batch bench generates its own synth job set and runs the
        # batched driver; silently dropping the per-graph flags would
        # mismeasure (the user would read a synthpl record believing it
        # covered their file/engine).
        if args.file or args.scale is not None:
            print("# --batch is the synthetic multi-tenant bench: "
                  "--file/--scale do not apply (use --batch-edges/"
                  "--batch-jobs to shape the job set)", file=sys.stderr)
            return 2
        if args.engine != "auto":
            print(f"# --batch ignores --engine {args.engine!r}: the "
                  "batched driver selects its per-phase engine via "
                  "--batch-engine {fused,bucketed}", file=sys.stderr)
        # Before ANY jax import: the virtual-device split only takes
        # effect at backend init (louvain/batched.py explains why a CPU
        # batch without it serializes its sorts).
        from cuvite_tpu.utils.envknob import request_host_devices

        request_host_devices(args.host_devices)

    from cuvite_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    platform = _init_backend()

    if args.batch is not None:
        try:
            rec = run_batch_bench(
                B=args.batch, n_jobs=args.batch_jobs,
                edges=args.batch_edges, repeats=args.repeats,
                budget_s=args.budget, platform=platform,
                engine=args.batch_engine,
            )
        except BenchCompileGuardError as e:
            print(f"# BENCH ABORTED: {e}", file=sys.stderr)
            for line in e.compile_log:
                print(f"#   {line[:200]}", file=sys.stderr)
            return 3
        problems = validate_record(rec)
        if problems:
            print(f"# BENCH ABORTED: invalid record: {problems}",
                  file=sys.stderr)
            return 4
        line = json.dumps(rec)
        print(line)
        if args.out:
            with open(args.out, "w", encoding="utf-8") as f:
                f.write(line + "\n")
        return 0

    if args.file:
        from cuvite_tpu.io.vite import read_vite
        from cuvite_tpu.workloads.registry import load_provenance

        graph = read_vite(args.file, bits64=args.bits64)
        label = os.path.basename(args.file)
        scale = None
        prov = load_provenance(args.file)
        provenance = prov.get("source") if prov else None
    else:
        # cpu-fallback default scale matches every recorded CPU number
        # and the persistent compile cache (README benchmarks).
        scale = args.scale if args.scale is not None else (
            18 if platform == "cpu" else 20)
        from cuvite_tpu.io.generate import generate_rgg, generate_rmat

        t0 = time.perf_counter()
        if args.graph == "rgg":
            graph = generate_rgg(1 << scale, seed=1)
        else:
            graph = generate_rmat(scale, edge_factor=args.edge_factor,
                                  seed=1)
        print(f"# graph: {args.graph} scale={scale} "
              f"nv={graph.num_vertices} ne={graph.num_edges} "
              f"gen={time.perf_counter()-t0:.1f}s", file=sys.stderr)
        label = f"{args.graph}{scale}"
        provenance = "generated"

    try:
        rec = run_bench(
            graph, engine=args.engine, repeats=args.repeats,
            budget_s=args.budget, platform=platform, graph_label=label,
            scale=scale, provenance=provenance,
        )
    except BenchCompileGuardError as e:
        print(f"# BENCH ABORTED: {e}", file=sys.stderr)
        for line in e.compile_log:
            print(f"#   {line[:200]}", file=sys.stderr)
        print("# no JSON emitted: fix the cache instability (see "
              "tests/test_footprint.py::test_no_recompile_on_second_run) "
              "and rerun", file=sys.stderr)
        return 3
    problems = validate_record(rec)
    if problems:
        print(f"# BENCH ABORTED: invalid record: {problems}",
              file=sys.stderr)
        return 4
    line = json.dumps(rec)
    print(line)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
