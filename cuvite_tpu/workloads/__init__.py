"""Real-graph workload subsystem: converters, dataset registry with an
offline synthesizer fallback, golden result envelopes, and the hardened
bench harness.  CLI: ``python -m cuvite_tpu.workloads {fetch,synth,
convert,bench,verify-golden}``."""
