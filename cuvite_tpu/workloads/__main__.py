"""Workloads CLI.

    python -m cuvite_tpu.workloads fetch com-orkut --dest workloads_data
    python -m cuvite_tpu.workloads synth --edges 1e8 --profile powerlaw
    python -m cuvite_tpu.workloads convert in.txt.gz --out out.vite
    python -m cuvite_tpu.workloads bench --file out.vite
    python -m cuvite_tpu.workloads verify-golden --dataset powerlaw-1e8 \
        --file out.vite [--update-golden]

Every artifact lands next to a ``.provenance.json`` describing where it
came from (fetched + checksum, or offline-synthesized + parameters), so
a BASELINE row can always say which it was.
"""

from __future__ import annotations

import argparse
import json
import sys

DEFAULT_DATA_DIR = "workloads_data"


def _cmd_fetch(args) -> int:
    from cuvite_tpu.workloads.registry import DATASETS, fetch

    if args.list:
        for name, ds in sorted(DATASETS.items()):
            print(f"{name}: |V|={ds.num_vertices} "
                  f"|E|={ds.num_edges_undirected} (undirected) "
                  f"fmt={ds.fmt} sha256={'pinned' if ds.sha256 else 'TOFU'}")
        return 0
    payload = fetch(args.name, args.dest,
                    offline_fallback=not args.no_offline_fallback,
                    synth_edges=args.synth_edges,
                    keep_download=args.keep_download)
    print(json.dumps({"source": payload["source"],
                      "result": payload.get("result")}))
    return 0


def _cmd_synth(args) -> int:
    import os

    from cuvite_tpu.workloads.synth import synthesize, synthesize_many

    out = args.out
    if out is None:
        os.makedirs(DEFAULT_DATA_DIR, exist_ok=True)
        out = os.path.join(DEFAULT_DATA_DIR,
                           f"{args.profile}_{int(args.edges)}.vite")
    if args.many:
        # K small graphs on distinct splitmix64 streams, one provenance
        # file for the set (serving benches/tests, ISSUE 9).
        prefix = out[:-5] if out.endswith(".vite") else out
        payload = synthesize_many(
            prefix, args.many, edges=int(args.edges),
            profile=args.profile, seed=args.seed, alpha=args.alpha,
            mu=args.mu, overlap=args.overlap,
            edge_factor=args.edge_factor, bits64=args.bits64,
            write_truth=not args.no_truth,
        )
        print(json.dumps({
            "out_prefix": prefix, "count": payload["count"],
            "provenance": prefix + ".many.provenance.json",
            "graphs": [m["path"] for m in payload["graphs"]]}))
        return 0
    payload = synthesize(
        out, edges=int(args.edges), profile=args.profile, seed=args.seed,
        alpha=args.alpha, mu=args.mu, overlap=args.overlap,
        edge_factor=args.edge_factor, bits64=args.bits64,
        write_truth=not args.no_truth,
    )
    line = {"out": out, "result": payload["result"],
            "sha256": payload["sha256"]}
    if args.churn:
        # Deterministic insert/delete stream against the graph just
        # written (read back, so the churn indexes the REALIZED edge
        # set), for the streaming warm-start A/B (ISSUE 17).
        from cuvite_tpu.io.vite import read_vite
        from cuvite_tpu.workloads.synth import write_churn

        graph = read_vite(out, bits64=args.bits64)
        churn = write_churn(out, graph, frac=args.churn,
                            seed=args.churn_seed, batches=args.churn_batches)
        line["churn"] = {"npz": out + ".churn.npz",
                         "sha256": churn["sha256"],
                         "frac": churn["churn_frac"],
                         "batches": churn["batches"]}
    print(json.dumps(line))
    return 0


def _cmd_convert(args) -> int:
    from cuvite_tpu.workloads.convert import convert
    from cuvite_tpu.workloads.synth import write_provenance

    stats = convert(args.input, args.out, fmt=args.format,
                    bits64=args.bits64, symmetrize=args.symmetrize,
                    relabel=args.relabel)
    write_provenance(args.out, {"source": "converted",
                                "input": args.input,
                                "result": stats.to_dict()})
    print(json.dumps(stats.to_dict()))
    return 0


def _cmd_bench(args, extra) -> int:
    from cuvite_tpu.workloads.bench import main as bench_main

    return bench_main(extra)


def _cmd_verify_golden(args) -> int:
    import numpy as np  # noqa: F401  (louvain result arrays)

    from cuvite_tpu.io.vite import read_vite
    from cuvite_tpu.louvain.driver import louvain_phases
    from cuvite_tpu.workloads.golden import measure_run, verify
    from cuvite_tpu.workloads.registry import load_provenance

    graph = read_vite(args.file, bits64=args.bits64)
    res = louvain_phases(graph, engine=args.engine, verbose=False)
    prov = load_provenance(args.file)
    truth = args.truth
    if truth is None and prov and prov.get("truth_path"):
        truth = prov["truth_path"]
    measured = measure_run(res.communities, res, truth_path=truth,
                           zero_based_truth=args.truth_zero_based,
                           provenance=prov.get("source") if prov else None)
    ok, problems = verify(args.dataset, args.config, measured,
                          path=args.golden, update=args.update_golden)
    print(json.dumps({"dataset": args.dataset, "config": args.config,
                      "measured": measured, "ok": ok,
                      "problems": problems,
                      "updated": bool(args.update_golden)}))
    return 0 if ok else 1


def build_parser() -> argparse.ArgumentParser:
    from cuvite_tpu.workloads.convert import FORMATS
    from cuvite_tpu.workloads.golden import DEFAULT_GOLDEN_PATH
    from cuvite_tpu.workloads.synth import PROFILES

    p = argparse.ArgumentParser(prog="python -m cuvite_tpu.workloads",
                                description=__doc__.split("\n")[0])
    sub = p.add_subparsers(dest="cmd", required=True)

    f = sub.add_parser("fetch", help="download+verify+convert a dataset "
                                     "(offline: synthesize a stand-in)")
    f.add_argument("name", nargs="?", default="")
    f.add_argument("--dest", default=DEFAULT_DATA_DIR)
    f.add_argument("--list", action="store_true")
    f.add_argument("--no-offline-fallback", action="store_true")
    f.add_argument("--synth-edges", type=float, default=None,
                   help="edge count of the offline stand-in")
    f.add_argument("--keep-download", action="store_true")

    s = sub.add_parser("synth", help="synthesize a power-law community "
                                     "graph as a Vite file")
    s.add_argument("--edges", type=float, required=True,
                   help="target directed edge records (e.g. 1e8)")
    s.add_argument("--profile", default="powerlaw", choices=PROFILES)
    s.add_argument("--out", default=None)
    s.add_argument("--seed", type=int, default=1)
    s.add_argument("--alpha", type=float, default=2.3)
    s.add_argument("--mu", type=float, default=0.25)
    s.add_argument("--overlap", type=float, default=0.05)
    s.add_argument("--edge-factor", type=int, default=16)
    s.add_argument("--bits64", action="store_true")
    s.add_argument("--no-truth", action="store_true",
                   help="skip the ground-truth file (large graphs)")
    s.add_argument("--churn", type=float, metavar="FRAC", default=0.0,
                   help="also emit a deterministic insert/delete churn "
                        "stream (<out>.churn.npz + provenance) deleting "
                        "FRAC of the undirected pairs per batch "
                        "(streaming warm-start A/B, ISSUE 17)")
    s.add_argument("--churn-batches", type=int, default=1)
    s.add_argument("--churn-seed", type=int, default=1)
    s.add_argument("--many", type=int, metavar="K", default=0,
                   help="emit K graphs <out>_<k>.vite on distinct "
                        "splitmix64 streams with ONE set-level "
                        "provenance file (serving benches/tests)")

    c = sub.add_parser("convert", help="convert SNAP/MTX/METIS to Vite")
    c.add_argument("input")
    c.add_argument("--out", required=True)
    c.add_argument("--format", default="auto",
                   choices=("auto",) + tuple(FORMATS))
    c.add_argument("--bits64", action="store_true")
    c.add_argument("--symmetrize", default="auto",
                   choices=["auto", "yes", "no"])
    c.add_argument("--relabel", default=None,
                   choices=[None, "auto", "none", "dense"])

    sub.add_parser("bench", help="hardened TEPS bench (extra args pass "
                                 "through; see bench --help)",
                   add_help=False)

    v = sub.add_parser("verify-golden", help="run clustering and check "
                                             "the golden envelope")
    v.add_argument("--dataset", required=True)
    v.add_argument("--config", default="default")
    v.add_argument("--file", required=True, help="Vite graph file")
    v.add_argument("--bits64", action="store_true")
    v.add_argument("--engine", default="auto")
    v.add_argument("--truth", default=None,
                   help="LFR ground-truth file (default: provenance's)")
    v.add_argument("--truth-zero-based", action="store_true")
    v.add_argument("--golden", default=DEFAULT_GOLDEN_PATH)
    v.add_argument("--update-golden", action="store_true")
    return p


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    # `bench` forwards its tail verbatim to the bench parser (which also
    # reads the historical BENCH_* env knobs).
    if argv and argv[0] == "bench":
        return _cmd_bench(None, argv[1:])
    args = build_parser().parse_args(argv)
    if args.cmd == "fetch":
        if not args.name and not args.list:
            raise SystemExit("fetch: dataset name required (or --list)")
        return _cmd_fetch(args)
    if args.cmd == "synth":
        return _cmd_synth(args)
    if args.cmd == "convert":
        return _cmd_convert(args)
    if args.cmd == "verify-golden":
        return _cmd_verify_golden(args)
    raise SystemExit(f"unknown command {args.cmd!r}")


if __name__ == "__main__":
    sys.exit(main())
