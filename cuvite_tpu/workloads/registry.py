"""Real-graph dataset registry: URLs, checksums, expected sizes, fetch.

The three benchmark-family datasets BASELINE.json names (com-Orkut,
Friendster, uk-2007) are described here with their published vertex/edge
counts; ``fetch`` downloads, checksum-verifies, decompresses and
converts them to Vite binary in one streamed flow.  This module is the
ONLY place in the repo allowed to open a network connection — graftlint
R009 enforces that, and also that every download path here carries
checksum verification.

Offline fallback: when the network is unreachable (this rig usually is),
``fetch(..., offline_fallback=True)`` synthesizes a power-law +
planted-community stand-in at a bounded edge count via workloads.synth
and says so in the provenance record — the workload layer never blocks
on connectivity (VERDICT r5 missing #5).

Checksum policy: entries whose ``sha256`` is None are trust-on-first-use
— the streamed digest is printed and recorded in provenance so a later
fetch (or another machine) can pin it; entries WITH a pinned digest hard-
fail on mismatch and delete the partial download.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import sys
import tarfile
import time

from cuvite_tpu.workloads.convert import convert
from cuvite_tpu.workloads.synth import synthesize, write_provenance

DOWNLOAD_TIMEOUT_S = 120
_BLOCK = 4 << 20

# Published stats: SNAP (com-Orkut / com-Friendster) and LAW/SuiteSparse
# (uk-2007-05).  ``edges`` is the UNDIRECTED published count; the Vite
# file stores ~2x directed records.
@dataclasses.dataclass(frozen=True)
class Dataset:
    name: str
    url: str
    fmt: str                  # converter format of the decompressed file
    num_vertices: int
    num_edges_undirected: int
    sha256: str | None = None  # None => trust-on-first-use (recorded)
    ground_truth_url: str | None = None
    synth_edges: int = 1 << 27  # offline stand-in size (directed records)
    bits64: bool = False
    # Declared width envelope: the maximum vertex/directed-edge counts
    # any slab built from this dataset may carry — what the width audit
    # (analysis/widthcheck.py + tools/width_audit.py) derives its
    # boundary shapes from.  Default to the published counts; a dataset
    # whose pipeline renumbers/expands ids must declare the larger
    # bound explicitly.
    max_nv: int | None = None
    max_ne: int | None = None

    @property
    def num_edges_directed(self) -> int:
        return 2 * self.num_edges_undirected

    @property
    def width_nv(self) -> int:
        return self.max_nv if self.max_nv is not None else self.num_vertices

    @property
    def width_ne(self) -> int:
        return self.max_ne if self.max_ne is not None \
            else self.num_edges_directed


DATASETS: dict = {
    d.name: d for d in (
        Dataset(
            name="com-orkut",
            url="https://snap.stanford.edu/data/bigdata/communities/"
                "com-orkut.ungraph.txt.gz",
            fmt="snap",
            num_vertices=3_072_441,
            num_edges_undirected=117_185_083,
            max_nv=3_072_441,
            max_ne=234_370_166,
            ground_truth_url="https://snap.stanford.edu/data/bigdata/"
                             "communities/com-orkut.all.cmty.txt.gz",
            synth_edges=1 << 27,
        ),
        Dataset(
            name="friendster",
            url="https://snap.stanford.edu/data/bigdata/communities/"
                "com-friendster.ungraph.txt.gz",
            fmt="snap",
            num_vertices=65_608_366,
            num_edges_undirected=1_806_067_135,
            max_nv=65_608_366,
            max_ne=3_612_134_270,
            ground_truth_url="https://snap.stanford.edu/data/bigdata/"
                             "communities/com-friendster.all.cmty.txt.gz",
            synth_edges=1 << 27,
            bits64=True,
        ),
        Dataset(
            name="uk-2007",
            url="https://suitesparse-collection-website.herokuapp.com/"
                "MM/LAW/uk-2007-05.tar.gz",
            fmt="mtx",
            num_vertices=105_896_555,
            num_edges_undirected=3_738_733_648 // 2,
            max_nv=105_896_555,
            max_ne=3_738_733_648,
            synth_edges=1 << 27,
            bits64=True,
        ),
    )
}

# Relative tolerance for the expected |V|/|E| envelope after conversion
# (relabeling drops isolated ids; published counts sometimes exclude
# self-loops): generous enough for bookkeeping drift, tight enough to
# catch a truncated download or a broken converter.
SIZE_ENVELOPE_REL = 0.02


# ---------------------------------------------------------------------------
# Declared width envelope (analysis/widthcheck.py + tools/width_audit.py
# derive every boundary shape from HERE — the single source).

# The synth/R-MAT scale ladder tops out at scale 28 (ROADMAP item 1's
# billion-edge target): nv = 2^28, ne = EDGE_FACTOR * 2^28 = 2^32
# directed records under the synth layout law below.
RMAT_SCALE_MAX = 28
# workloads/synth.SynthSpec's default mean directed degree (the layout
# law is nv = max(64, edges // edge_factor), synth.py::_layout);
# ``edges`` counts DIRECTED records, the repo's slab-row convention.
EDGE_FACTOR = 16

# Serving batch-ladder ceiling (== max(core.batch.BATCH_SIZES), pinned
# by tier-1; restated here so the fetch module never imports the
# device stack).
BATCH_MAX = 64


def _next_pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


def rmat_scale_law(scale: int, edge_factor: int = EDGE_FACTOR) -> tuple:
    """R-MAT/synth scale -> (nv, ne_directed): nv = 2^scale and
    ne = edge_factor * 2^scale directed records — the inverse of the
    synth layout law (nv = edges // edge_factor), so a scale-s stand-in
    synthesized at this ne lands exactly on 2^s vertices."""
    nv = 1 << scale
    return nv, edge_factor * nv


def synth_scale_law(edges: int, edge_factor: int = EDGE_FACTOR) -> tuple:
    """Directed edge count -> (nv, ne_directed) under the synth layout
    law (workloads/synth.py::_layout): nv = max(64, edges //
    edge_factor)."""
    return max(64, int(edges) // int(edge_factor)), int(edges)


def max_workload() -> dict:
    """The registry's declared max workload, in the width-symbol
    vocabulary of analysis/widthcheck.py (which pins its stdlib-only
    MAX_WORKLOAD copy against this dict in tier-1):

    * ``nv_pad``/``nv_total`` — pow2 padding of the largest declared
      vertex space (scale-28 R-MAT's 2^28 tops uk-2007's 105.9 M);
    * ``ne_pad`` — pow2 padding of the largest declared directed edge
      count (Friendster's 3.61 B and the scale-28 law's 2^32 both pad
      to 2^32);
    * ``two_m`` — total-weight ceiling, 2 * ne_pad (headroom for small
      integer weights over the unit-weight mass);
    * ``kbits``/``sbits`` — the packed-sort budget at that vertex space
      (key_bound = nv_pad, src_bound = nv_pad + 1: ops/segment.py);
    * ``B`` — the serving batch-ladder ceiling.
    """
    nv_max = max([d.width_nv for d in DATASETS.values()]
                 + [rmat_scale_law(RMAT_SCALE_MAX)[0]])
    ne_max = max([d.width_ne for d in DATASETS.values()]
                 + [rmat_scale_law(RMAT_SCALE_MAX)[1]])
    nv_pad = _next_pow2(nv_max)
    ne_pad = _next_pow2(ne_max)
    return {
        "nv_pad": nv_pad,
        "nv_total": nv_pad,
        "ne_pad": ne_pad,
        "two_m": 2 * ne_pad,
        "kbits": max(nv_pad - 1, 1).bit_length(),
        "sbits": max(nv_pad, 1).bit_length(),
        "B": BATCH_MAX,
    }


def _verify_checksum(name: str, digest: str, expected: str | None,
                     path: str) -> None:
    """Pinned digest mismatch deletes the artifact and raises; an
    unpinned (TOFU) digest is reported for later pinning."""
    if expected is None:
        print(f"# {name}: sha256 UNPINNED (trust-on-first-use) — computed "
              f"{digest}; pin it in workloads/registry.py", file=sys.stderr)
        return
    if digest != expected:
        os.unlink(path)
        raise ValueError(
            f"{name}: sha256 mismatch (expected {expected}, got {digest}); "
            "partial download deleted")


def _download(url: str, dest: str, timeout: int = DOWNLOAD_TIMEOUT_S) -> str:
    """Stream ``url`` to ``dest`` computing sha256 on the fly; returns
    the hex digest.  (urllib only — see module docstring / R009.)"""
    import urllib.request

    h = hashlib.sha256()
    part = dest + ".part"
    req = urllib.request.Request(url, headers={"User-Agent": "cuvite-tpu"})
    with urllib.request.urlopen(req, timeout=timeout) as resp, \
            open(part, "wb") as out:
        while True:
            buf = resp.read(_BLOCK)
            if not buf:
                break
            h.update(buf)
            out.write(buf)
    os.replace(part, dest)
    return h.hexdigest()


def _extract_payload(archive: str, dest_dir: str, fmt: str) -> str:
    """Resolve the converter's input file from a download: a .tar.gz is
    extracted (largest member matching the format's extension); a plain
    .gz passes through (the text readers stream gzip natively)."""
    if archive.endswith(".tar.gz") or archive.endswith(".tgz"):
        want = {"mtx": ".mtx", "metis": ".graph", "snap": ".txt"}[fmt]
        with tarfile.open(archive, "r:gz") as tf:
            members = [m for m in tf.getmembers()
                       if m.isfile() and m.name.endswith(want)]
            if not members:
                raise ValueError(f"{archive}: no *{want} member")
            member = max(members, key=lambda m: m.size)
            base = os.path.basename(member.name)
            out = os.path.join(dest_dir, base)
            with tf.extractfile(member) as src, open(out, "wb") as dst:
                while True:
                    buf = src.read(_BLOCK)
                    if not buf:
                        break
                    dst.write(buf)
        return out
    return archive


def _check_size_envelope(ds: Dataset, nv: int, ne: int) -> list:
    problems = []
    for label, got, want in (("num_vertices", nv, ds.num_vertices),
                             ("num_edges(directed)", ne,
                              ds.num_edges_directed)):
        if abs(got - want) > SIZE_ENVELOPE_REL * want:
            problems.append(f"{label}: got {got}, expected ~{want} "
                            f"(±{SIZE_ENVELOPE_REL:.0%})")
    return problems


def fetch(name: str, dest_dir: str, offline_fallback: bool = True,
          timeout: int = DOWNLOAD_TIMEOUT_S, synth_edges: int | None = None,
          keep_download: bool = False) -> dict:
    """Materialize dataset ``name`` as ``<dest_dir>/<name>.vite``.

    Downloads + verifies + converts when the network answers; otherwise
    (with ``offline_fallback``) synthesizes a stand-in of
    ``synth_edges`` directed edges and records that provenance honestly.
    Returns the provenance payload.
    """
    if name not in DATASETS:
        raise KeyError(f"unknown dataset {name!r} "
                       f"(choose from {sorted(DATASETS)})")
    ds = DATASETS[name]
    os.makedirs(dest_dir, exist_ok=True)
    out_path = os.path.join(dest_dir, f"{name}.vite")
    archive = os.path.join(dest_dir, os.path.basename(ds.url))
    try:
        digest = _download(ds.url, archive, timeout=timeout)
    except Exception as e:  # URLError, socket.timeout, HTTP errors...
        if not offline_fallback:
            raise
        edges = int(synth_edges if synth_edges is not None
                    else min(ds.num_edges_directed, ds.synth_edges))
        print(f"# {name}: network fetch failed ({type(e).__name__}: {e}); "
              f"synthesizing an offline stand-in at {edges} directed edges",
              file=sys.stderr)
        # Stable per-dataset seed (NOT Python's hash(): that is
        # PYTHONHASHSEED-randomized per process, and the stand-in must
        # be byte-reproducible across runs for golden envelopes).
        seed = int.from_bytes(
            hashlib.sha256(name.encode()).digest()[:4], "big")
        payload = synthesize(
            out_path, edges=edges, profile="powerlaw",
            seed=seed, bits64=ds.bits64,
            provenance_extra={
                "source": "offline-synthesized",
                "stands_in_for": name,
                "fetch_error": f"{type(e).__name__}: {e}",
                "dataset_expected": {
                    "num_vertices": ds.num_vertices,
                    "num_edges_directed": ds.num_edges_directed,
                },
            })
        return payload

    _verify_checksum(name, digest, ds.sha256, archive)
    payload_file = _extract_payload(archive, dest_dir, ds.fmt)
    stats = convert(payload_file, out_path, fmt=ds.fmt, bits64=ds.bits64)
    problems = _check_size_envelope(ds, stats.num_vertices,
                                    stats.num_edges)
    if problems:
        raise ValueError(f"{name}: converted size outside the published "
                         f"envelope: {'; '.join(problems)}")
    if not keep_download and payload_file != archive:
        os.unlink(payload_file)
    if not keep_download:
        os.unlink(archive)
    payload = {
        "source": "fetched",
        "dataset": name,
        "url": ds.url,
        "sha256": digest,
        "sha256_pinned": ds.sha256 is not None,
        "result": stats.to_dict(),
        "created": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    write_provenance(out_path, payload)
    return payload


def load_provenance(vite_path: str) -> dict | None:
    path = vite_path + ".provenance.json"
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return json.load(f)
