"""Open-loop load generation: drive a LouvainServer to saturation.

Closed-loop clients (submit, wait, submit) can never demonstrate
overload — they self-throttle.  This generator is OPEN-LOOP: job k's
arrival time is ``t0 + k/rate`` whether or not the server kept up, so
queue growth under overload is visible instead of hidden in client
backpressure.  Arrivals are stamped with their SCHEDULED time
(``submit(t_submit=...)``): a batch dispatch that blocks the loop for
200 ms cannot understate the waits of the jobs that "arrived" during
it.

Two entry points:

* :func:`run_open_loop` — one run at one arrival rate against a fresh
  server; returns a :class:`LoadReport` (goodput, reject/shed rates,
  wait percentiles, per-job results).
* :func:`saturation_sweep` — geometric rate ramp that finds the
  highest SUSTAINABLE rate: goodput within ``sustain_frac`` of the
  offered rate AND queue-wait p95 within the SLO.  The sweep result
  anchors the acceptance A/B (2x saturation with admission on vs off —
  tools/serve_load.py).

Everything runs on the server's injectable clock/sleep pair, so unit
tests drive whole sweeps on a fake clock with a stub runner in
milliseconds; the bench path uses the real clock and the real batched
driver.  No jax imports here (the queue contract).
"""

from __future__ import annotations

import dataclasses

from cuvite_tpu.serve.admission import AdmissionReject
from cuvite_tpu.serve.queue import LouvainServer, percentile


@dataclasses.dataclass
class LoadReport:
    """One open-loop run's outcome (rates in jobs/s, waits seconds)."""

    rate: float               # offered arrival rate
    offered: int              # jobs the schedule presented
    done: int
    failed: int
    rejected: int
    shed: int
    wall_s: float             # first arrival -> queue fully drained
    goodput_jobs_per_s: float
    wait_p50_s: float
    wait_p95_s: float
    stats: dict               # final ServeStats snapshot
    results: list             # [(job_id, LouvainResult), ...] completed
    conservation: dict        # LouvainServer.conservation() at the end

    @property
    def reject_rate(self) -> float:
        return self.rejected / max(self.offered, 1)

    @property
    def shed_rate(self) -> float:
        return self.shed / max(self.offered, 1)

    def row(self) -> dict:
        """Compact dict for sweep tables / logs."""
        return {
            "rate": round(self.rate, 3),
            "offered": self.offered,
            "done": self.done,
            "rejected": self.rejected,
            "shed": self.shed,
            "failed": self.failed,
            "goodput_jobs_per_s": round(self.goodput_jobs_per_s, 3),
            "wait_p50_ms": round(self.wait_p50_s * 1e3, 3),
            "wait_p95_ms": round(self.wait_p95_s * 1e3, 3),
        }


def run_open_loop(server: LouvainServer, graphs, rate: float, *,
                  tenants: int = 1, deadline_s: float | None = None,
                  max_wall_s: float = 3600.0,
                  pipelined: bool = False) -> LoadReport:
    """Offer ``graphs`` to ``server`` at ``rate`` jobs/s (open loop),
    then drain; the server must be FRESH (stats start at zero).

    ``tenants`` spreads jobs round-robin over that many tenant ids
    (exercising the fairness pop); ``deadline_s`` attaches a relative
    deadline to every job (the shedding path).  ``max_wall_s`` bounds
    a pathological run on the server's clock (e.g. a misconfigured
    rate of 1e-9) — it raises rather than spins forever.

    ``pipelined`` (ISSUE 14) drives the server through the two-stage
    PipelinedDispatcher (serve/pipeline.py) instead of the in-loop
    ``step()`` calls: host pack of batch k+1 overlaps device execution
    of batch k, the pipeline A/B's measured arm.  Pipelined runs need
    the REAL clock/sleep pair (the seam threads block on production
    primitives); fake-clock tests drive the serial path or the concheck
    scheduler.
    """
    if rate <= 0:
        raise ValueError(f"rate must be > 0 jobs/s, got {rate}")
    if pipelined:
        return _run_open_loop_pipelined(
            server, graphs, rate, tenants=tenants, deadline_s=deadline_s,
            max_wall_s=max_wall_s)
    clock, sleep = server.clock, server.sleep
    poll_s = max(min(server.config.linger_s / 2.0, 0.01), 1e-4)
    finished: list = []
    rejected = 0
    t0 = clock()
    i = 0
    n = len(graphs)
    while True:
        now = clock()
        if now - t0 > max_wall_s:
            raise TimeoutError(
                f"open-loop run exceeded max_wall_s={max_wall_s}")
        while i < n and t0 + i / rate <= now:
            try:
                server.submit(graphs[i], tenant=f"t{i % tenants}",
                              deadline_s=deadline_s,
                              t_submit=t0 + i / rate)
            except AdmissionReject:
                rejected += 1
            i += 1
        before = len(finished)
        finished.extend(server.step())
        if i >= n:
            if server.pending() == 0:
                break
            if len(finished) == before:
                # Nothing was due (a partial bin waiting out its
                # linger): advance the clock toward the deadline
                # instead of spinning — on a fake clock this sleep IS
                # what moves time.
                sleep(poll_s)
            continue
        now = clock()
        next_arrival = t0 + i / rate
        if next_arrival > now:
            sleep(min(next_arrival - now, poll_s))
    wall = clock() - t0
    stats = server.stats.to_dict()
    cons = server.conservation()
    with server.stats.lock:
        samples = list(server.stats.wait_samples)
    return LoadReport(
        rate=rate, offered=n, done=stats["jobs_done"],
        failed=stats["jobs_failed"], rejected=rejected,
        shed=stats["jobs_shed"], wall_s=wall,
        goodput_jobs_per_s=stats["jobs_done"] / max(wall, 1e-9),
        wait_p50_s=percentile(samples, 50.0),
        wait_p95_s=percentile(samples, 95.0),
        stats=stats, results=finished, conservation=cons)


def _run_open_loop_pipelined(server: LouvainServer, graphs, rate: float, *,
                             tenants: int, deadline_s: float | None,
                             max_wall_s: float) -> LoadReport:
    """The pipelined arm of :func:`run_open_loop`: submissions feed the
    PipelinedDispatcher's intake lock; the packer/executor seam-threads
    do the dispatching; the report is assembled after a full drain."""
    from cuvite_tpu.serve.pipeline import PipelinedDispatcher

    clock, sleep = server.clock, server.sleep
    pipe = PipelinedDispatcher(
        server, poll_s=max(min(server.config.linger_s / 2.0, 0.01), 1e-3))
    pipe.start()
    rejected = 0
    t0 = clock()
    n = len(graphs)
    for i, g in enumerate(graphs):
        target = t0 + i / rate
        now = clock()
        if target > now:
            sleep(target - now)
        try:
            pipe.submit(g, tenant=f"t{i % tenants}",
                        deadline_s=deadline_s, t_submit=target)
        except AdmissionReject:
            rejected += 1
    pipe.request_drain()
    if not pipe.wait_done(timeout=max_wall_s):
        raise TimeoutError(
            f"pipelined open-loop run exceeded max_wall_s={max_wall_s}")
    wall = clock() - t0
    stats = server.stats.to_dict()
    cons = server.conservation()
    with server.stats.lock:
        samples = list(server.stats.wait_samples)
    return LoadReport(
        rate=rate, offered=n, done=stats["jobs_done"],
        failed=stats["jobs_failed"], rejected=rejected,
        shed=stats["jobs_shed"], wall_s=wall,
        goodput_jobs_per_s=stats["jobs_done"] / max(wall, 1e-9),
        wait_p50_s=percentile(samples, 50.0),
        wait_p95_s=percentile(samples, 95.0),
        stats=stats, results=pipe.results, conservation=cons)


@dataclasses.dataclass
class MixReport:
    """A skewed two-class open-loop run (ISSUE 20): the overall
    LoadReport plus the per-class split and the packing counters the
    packed-vs-per-class A/B compares."""

    report: LoadReport
    mix: tuple                # (n_small, n_big) offered
    classes: dict             # {'small': cls, 'big': cls}
    per_class: dict           # name -> {offered, done, goodput, waits}
    merged_batches: int
    pack_util: float
    subrow_util: float

    def row(self) -> dict:
        out = self.report.row()
        out.update({
            "merged_batches": self.merged_batches,
            "pack_util": round(self.pack_util, 4),
            "subrow_util": round(self.subrow_util, 4),
        })
        for name, blk in self.per_class.items():
            out[f"{name}_goodput_jobs_per_s"] = round(
                blk["goodput_jobs_per_s"], 3)
            out[f"{name}_wait_p95_ms"] = round(blk["wait_p95_s"] * 1e3, 3)
        return out


def mix_schedule(smalls, bigs) -> list:
    """Deterministically interleave two job pools into ONE arrival
    order with the big jobs spread evenly through it (Bresenham, no
    RNG): a 90:10 pool split yields every ~10th arrival big.  Returns
    ``[('small'|'big', graph), ...]`` consuming both pools fully."""
    total = len(smalls) + len(bigs)
    out: list = []
    si = bi = 0
    for k in range(total):
        due_big = bi * total <= k * len(bigs)
        if bi < len(bigs) and (due_big or si >= len(smalls)):
            out.append(("big", bigs[bi]))
            bi += 1
        else:
            out.append(("small", smalls[si]))
            si += 1
    return out


def run_mixed_open_loop(server: LouvainServer, smalls, bigs, rate: float, *,
                        tenants: int = 1, deadline_s: float | None = None,
                        max_wall_s: float = 3600.0,
                        pipelined: bool = False) -> MixReport:
    """Offer a SKEWED two-class mix (``smalls`` + ``bigs`` interleaved
    by :func:`mix_schedule`) at ``rate`` jobs/s and drain — the ISSUE
    20 scenario: with ``merge_packing`` on, the small-class bins should
    ride the big class's compiled program as fenced sub-rows instead of
    lingering for same-class batchmates.  The per-class split comes
    from the server's own ``done_by_class``/``waits_by_class``
    bookkeeping, so the serial and pipelined drives report it the same
    way."""
    from cuvite_tpu.core.batch import slab_class_of  # deferred (queue contract)

    if not smalls or not bigs:
        raise ValueError("a mixed run needs BOTH pools non-empty")
    classes = {"small": slab_class_of(smalls[0]),
               "big": slab_class_of(bigs[0])}
    if classes["small"] == classes["big"]:
        raise ValueError(
            f"mix pools share slab class {classes['small']}; a one-class "
            "mix has nothing to merge — change the big pool's size")
    schedule = mix_schedule(smalls, bigs)
    offered = {"small": len(smalls), "big": len(bigs)}
    rep = run_open_loop(server, [g for _, g in schedule], rate,
                        tenants=tenants, deadline_s=deadline_s,
                        max_wall_s=max_wall_s, pipelined=pipelined)
    split = server.stats.per_class()
    per_class = {}
    for name, cls in classes.items():
        blk = split.get(cls, {"done": 0, "wait_p50_s": 0.0,
                              "wait_p95_s": 0.0})
        per_class[name] = {
            "offered": offered[name],
            "done": blk["done"],
            "goodput_jobs_per_s": blk["done"] / max(rep.wall_s, 1e-9),
            "wait_p50_s": blk["wait_p50_s"],
            "wait_p95_s": blk["wait_p95_s"],
        }
    stats = rep.stats
    return MixReport(
        report=rep, mix=(len(smalls), len(bigs)), classes=classes,
        per_class=per_class,
        merged_batches=stats.get("merged_batches", 0),
        pack_util=stats.get("pack_util", 0.0),
        subrow_util=stats.get("subrow_util", 0.0))


def saturation_sweep(make_server, make_graphs, *, start_rate: float,
                     slo_s: float, growth: float = 1.6,
                     max_rounds: int = 8, sustain_frac: float = 0.9,
                     tenants: int = 1,
                     deadline_s: float | None = None,
                     pipelined: bool = False) -> tuple:
    """Geometric arrival-rate ramp; stops at the first UNSUSTAINABLE
    rate (goodput < sustain_frac * rate, or wait p95 past the SLO).

    ``make_server``/``make_graphs`` are zero-arg factories (each round
    needs a fresh server with zeroed stats; reusing one graph list is
    fine — factories let callers re-synthesize when graphs are
    consumed).  Returns ``(reports, best)`` where ``best`` is the last
    sustainable report (None if even ``start_rate`` overloads).
    """
    reports: list = []
    best = None
    rate = start_rate
    for _ in range(max_rounds):
        rep = run_open_loop(make_server(), make_graphs(), rate,
                            tenants=tenants, deadline_s=deadline_s,
                            pipelined=pipelined)
        reports.append(rep)
        sustainable = (rep.goodput_jobs_per_s >= sustain_frac * rate
                       and rep.wait_p95_s <= slo_s
                       and rep.rejected == 0)
        if not sustainable:
            break
        best = rep
        rate *= growth
    return reports, best
