"""Serving CLI.

    # synthetic multi-tenant load through the batching queue
    python -m cuvite_tpu.serve demo --jobs 64 --edges 4096 --b-max 16

    # cluster many Vite files as one multi-tenant workload
    python -m cuvite_tpu.serve cluster-many a.vite b.vite --output

Both paths run the slab-class batching queue (serve/queue.py) over the
batched driver: jobs bin by class, pack to ``--b-max`` with a
``--linger-ms`` deadline, and per-tenant results stream out as JSON
lines, followed by one summary line (jobs/sec, pack_util, batches).

On CPU the batch axis shards over virtual host devices
(``--host-devices``, default 8): XLA:CPU executes a batched sort
serially, so without the split a batch amortizes dispatch but
serializes compute (louvain/batched.py has the measurement).  The flag
must act before jax initializes — this module sets XLA_FLAGS first
thing in ``main()``, so import jax only after argument parsing.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

# numpy-only import chain: safe before the XLA_FLAGS setup in main().
from cuvite_tpu.core.batch import BATCH_ENGINES


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m cuvite_tpu.serve",
        description="slab-class batched Louvain serving")
    sub = p.add_subparsers(dest="cmd", required=True)

    def common(q):
        q.add_argument("--b-max", type=int, default=64,
                       help="max jobs per packed batch (BATCH_SIZES rung)")
        q.add_argument("--linger-ms", type=float, default=50.0,
                       help="max wait of the oldest job before a partial "
                            "batch dispatches")
        q.add_argument("--threshold", type=float, default=1e-6)
        q.add_argument("--engine", default="bucketed",
                       choices=list(BATCH_ENGINES),
                       help="batched per-phase engine: 'bucketed' "
                            "(default — sort-free phase-0 sweep over "
                            "pack-time bucket plans + serving-coarse "
                            "later phases) or 'fused' (the all-phases "
                            "sort-formulation loop); results are "
                            "bit-identical either way")
        q.add_argument("--host-devices", type=int, default=8,
                       help="virtual CPU devices to shard the batch axis "
                            "over (ignored when jax already initialized "
                            "or on a real accelerator); 1 disables")
        q.add_argument("--trace-out", metavar="FILE.jsonl",
                       help="flight-recorder span/event trace (pack spans, "
                            "tenant_result events; OBSERVABILITY.md)")
        q.add_argument("--json", action="store_true",
                       help="per-tenant JSON result lines")

    d = sub.add_parser("demo", help="synthetic multi-tenant load")
    common(d)
    d.add_argument("--jobs", type=int, default=32)
    d.add_argument("--edges", type=int, default=4096,
                   help="directed edge records per synthetic graph")
    d.add_argument("--seed", type=int, default=1)

    c = sub.add_parser("cluster-many",
                       help="cluster many Vite files through the queue")
    common(c)
    c.add_argument("files", nargs="+", metavar="FILE.vite")
    c.add_argument("--bits64", action="store_true")
    c.add_argument("--output", action="store_true",
                   help="write <file>.communities per input")
    return p


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    from cuvite_tpu.utils.envknob import request_host_devices

    request_host_devices(args.host_devices)

    from cuvite_tpu.serve.queue import LouvainServer, ServeConfig
    from cuvite_tpu.utils.compile_cache import enable_compile_cache
    from cuvite_tpu.utils.trace import Tracer

    enable_compile_cache()

    import contextlib

    rec_ctx = contextlib.nullcontext()
    recorder = None
    if args.trace_out:
        from cuvite_tpu.obs import FlightRecorder, JsonlTraceSink

        recorder = FlightRecorder(JsonlTraceSink(args.trace_out))
        rec_ctx = recorder
    tracer = Tracer(recorder=recorder)

    server = LouvainServer(
        ServeConfig(b_max=args.b_max, linger_s=args.linger_ms / 1e3,
                    threshold=args.threshold, engine=args.engine),
        tracer=tracer)

    t0 = time.perf_counter()
    with rec_ctx:
        if args.cmd == "demo":
            from cuvite_tpu.workloads.synth import many_seed, synthesize_graph

            ids = {}
            for k in range(args.jobs):
                g = synthesize_graph(args.edges, seed=many_seed(args.seed, k))
                ids[server.submit(g)] = f"synth-{k}"
            finished = server.drain()
        else:
            from cuvite_tpu.io.vite import read_vite

            ids = {}
            for path in args.files:
                g = read_vite(path, bits64=args.bits64)
                ids[server.submit(g)] = path
            finished = server.drain()
            if args.output:
                from cuvite_tpu.evaluate.compare import write_communities

                by_id = dict(finished)
                for jid, path in ids.items():
                    if jid in by_id:  # failed jobs have no result
                        write_communities(path + ".communities",
                                          by_id[jid].communities)
    wall = time.perf_counter() - t0

    if args.json:
        for jid, res in finished:
            print(json.dumps({
                "job": ids[jid], "job_id": jid,
                "q": round(float(res.modularity), 6),
                "communities": int(res.num_communities),
                "phases": len(res.phases),
                "iterations": int(res.total_iterations),
            }))
    summary = dict(server.stats.to_dict(), wall_s=round(wall, 3),
                   wall_jobs_per_s=round(len(finished) / max(wall, 1e-9), 2))
    if server.failures:
        summary["failures"] = [
            {"job": ids.get(jid, jid), "error": err}
            for jid, err in server.failures]
    print(json.dumps({"summary": summary}))
    return 0 if not server.failures else 1


if __name__ == "__main__":
    sys.exit(main())
