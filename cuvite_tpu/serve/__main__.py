"""Serving CLI.

    # synthetic multi-tenant load through the batching queue
    python -m cuvite_tpu.serve demo --jobs 64 --edges 4096 --b-max 16

    # cluster many Vite files as one multi-tenant workload
    python -m cuvite_tpu.serve cluster-many a.vite b.vite --output

    # the async daemon: socket intake, admission control, graceful drain
    python -m cuvite_tpu.serve daemon --socket /tmp/cuvite.sock \
        --wait-slo-ms 500 --fault-plan "device:transient:n=1"

All paths run the slab-class batching queue (serve/queue.py) over the
batched driver: jobs bin by class with per-tenant fairness, pack to
``--b-max`` with a ``--linger-ms`` deadline, and per-tenant results
stream out as JSON lines, followed by one summary line.  The daemon
adds newline-delimited-JSON socket intake (serve/daemon.py documents
the wire protocol), SLO-projected admission control
(``--wait-slo-ms``), deadline shedding, deterministic fault injection
(``--fault-plan`` / ``CUVITE_FAULT_PLAN``) and a graceful drain on
SIGTERM/SIGINT: intake closes, queued bins flush, the final stats go
out as a ``serve_summary`` event, and the process exits 0.

On CPU the batch axis shards over virtual host devices
(``--host-devices``, default 8): XLA:CPU executes a batched sort
serially, so without the split a batch amortizes dispatch but
serializes compute (louvain/batched.py has the measurement).  The flag
must act before jax initializes — this module sets XLA_FLAGS first
thing in ``main()``, so import jax only after argument parsing.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

# numpy-only import chain: safe before the XLA_FLAGS setup in main().
from cuvite_tpu.core.batch import BATCH_ENGINES


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m cuvite_tpu.serve",
        description="slab-class batched Louvain serving")
    sub = p.add_subparsers(dest="cmd", required=True)

    def common(q):
        q.add_argument("--b-max", type=int, default=64,
                       help="max jobs per packed batch (BATCH_SIZES rung)")
        q.add_argument("--linger-ms", type=float, default=50.0,
                       help="max wait of the oldest job before a partial "
                            "batch dispatches")
        q.add_argument("--threshold", type=float, default=1e-6)
        q.add_argument("--engine", default="bucketed",
                       choices=list(BATCH_ENGINES),
                       help="batched per-phase engine: 'bucketed' "
                            "(default — sort-free phase-0 sweep over "
                            "pack-time bucket plans + serving-coarse "
                            "later phases) or 'fused' (the all-phases "
                            "sort-formulation loop); results are "
                            "bit-identical either way")
        q.add_argument("--host-devices", type=int, default=8,
                       help="virtual CPU devices to shard the batch axis "
                            "over (ignored when jax already initialized "
                            "or on a real accelerator); 1 disables")
        q.add_argument("--trace-out", metavar="FILE.jsonl",
                       help="flight-recorder span/event trace (pack spans, "
                            "tenant_result events; OBSERVABILITY.md)")
        q.add_argument("--json", action="store_true",
                       help="per-tenant JSON result lines")
        q.add_argument("--wait-slo-ms", type=float, default=None,
                       help="enable admission control: reject (with "
                            "retry_after_s) when a class's projected "
                            "queue wait breaches this SLO")
        q.add_argument("--fault-plan", default=None,
                       metavar="SITE:KIND:PARAMS[;...]",
                       help="deterministic fault injection plan "
                            "(serve/faults.py grammar; default: the "
                            "CUVITE_FAULT_PLAN env var)")
        q.add_argument("--max-retries", type=int, default=3,
                       help="transient-fault retry budget per dispatch")
        q.add_argument("--retry-base-ms", type=float, default=50.0,
                       help="retry backoff base (doubles per attempt)")
        q.add_argument("--pipeline", default="on", choices=["on", "off"],
                       help="two-stage pipelined dispatch (ISSUE 14): "
                            "host pack of batch k+1 overlaps device "
                            "execution of batch k ('on', the default); "
                            "'off' keeps the serial single-dispatcher "
                            "loop (the A/B arm).  Results are "
                            "bit-identical either way")
        q.add_argument("--autotune-b-max", action="store_true",
                       help="per-class b_max autotuning from the "
                            "measured service curve (needs "
                            "--wait-slo-ms): after a warm window each "
                            "class serves at the BATCH_SIZES rung "
                            "maximizing projected goodput under the "
                            "SLO, capped at --b-max")
        q.add_argument("--merge-packing", action="store_true",
                       help="sub-row merge packing (ISSUE 20): small-"
                            "class bins may pack 2^k jobs per row of a "
                            "larger served class's compiled program "
                            "(fenced sub-rows, results bit-identical "
                            "to B=1); merges on bin overflow, and — "
                            "with --wait-slo-ms — whenever measured "
                            "service medians project the packed batch "
                            "beating the linger wait")

    d = sub.add_parser("demo", help="synthetic multi-tenant load")
    common(d)
    d.add_argument("--jobs", type=int, default=32)
    d.add_argument("--edges", type=int, default=4096,
                   help="directed edge records per synthetic graph")
    d.add_argument("--seed", type=int, default=1)

    c = sub.add_parser("cluster-many",
                       help="cluster many Vite files through the queue")
    common(c)
    c.add_argument("files", nargs="+", metavar="FILE.vite")
    c.add_argument("--bits64", action="store_true")
    c.add_argument("--output", action="store_true",
                   help="write <file>.communities per input")

    dm = sub.add_parser("daemon",
                        help="async serving daemon (socket intake, "
                             "graceful SIGTERM drain)")
    common(dm)
    dm.add_argument("--socket", metavar="PATH",
                    help="unix-domain socket path for intake")
    dm.add_argument("--port", type=int, default=None,
                    help="TCP port for intake (0 = ephemeral; mutually "
                         "exclusive with --socket)")
    dm.add_argument("--host", default="127.0.0.1")
    dm.add_argument("--stream-budget-mb", type=float, default=256.0,
                    help="HBM byte budget for resident StreamSessions "
                         "(the `delta` verb's per-tenant live slabs; "
                         "LRU-evicted past the budget — ISSUE 17)")
    return p


def _make_server(args):
    from cuvite_tpu.serve.admission import AdmissionConfig
    from cuvite_tpu.serve.faults import FaultPlan
    from cuvite_tpu.serve.queue import LouvainServer, ServeConfig

    admission = (AdmissionConfig(wait_slo_s=args.wait_slo_ms / 1e3)
                 if args.wait_slo_ms is not None else None)
    faults = (FaultPlan.parse(args.fault_plan)
              if args.fault_plan is not None else FaultPlan.from_env())
    config = ServeConfig(
        b_max=args.b_max, linger_s=args.linger_ms / 1e3,
        threshold=args.threshold, engine=args.engine,
        admission=admission, max_retries=args.max_retries,
        retry_base_s=args.retry_base_ms / 1e3,
        autotune_b_max=bool(getattr(args, "autotune_b_max", False)),
        merge_packing=bool(getattr(args, "merge_packing", False)),
        stream_budget_bytes=int(
            getattr(args, "stream_budget_mb", 256.0) * (1 << 20)))
    return config, faults, LouvainServer


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    from cuvite_tpu.utils.envknob import request_host_devices

    request_host_devices(args.host_devices)

    from cuvite_tpu.utils.compile_cache import enable_compile_cache
    from cuvite_tpu.utils.trace import Tracer

    enable_compile_cache()

    import contextlib

    rec_ctx = contextlib.nullcontext()
    recorder = None
    if args.trace_out:
        from cuvite_tpu.obs import FlightRecorder, JsonlTraceSink

        recorder = FlightRecorder(JsonlTraceSink(args.trace_out))
        rec_ctx = recorder
    tracer = Tracer(recorder=recorder)

    try:
        config, faults, make = _make_server(args)
    except ValueError as e:
        print(f"# config error: {e}", file=sys.stderr)
        return 2
    server = make(config, tracer=tracer, faults=faults)

    if args.cmd == "daemon":
        import signal

        from cuvite_tpu.serve.daemon import ServeDaemon

        if (args.socket is None) == (args.port is None):
            print("# daemon needs exactly one of --socket / --port",
                  file=sys.stderr)
            return 2
        daemon = ServeDaemon(server, sock_path=args.socket,
                             host=args.host, port=args.port,
                             pipelined=args.pipeline == "on")
        with rec_ctx:
            daemon.start()
            for sig in (signal.SIGTERM, signal.SIGINT):
                signal.signal(sig, lambda *_a: daemon.request_drain())
            # The readiness line tells harnesses (tests, the load
            # generator, the TPU ladder) when to connect and where.
            print(json.dumps({"ready": {
                "socket": args.socket, "port": daemon.port,
                "b_max": config.b_max, "engine": config.engine,
                "admission": config.admission is not None,
                "pipelined": daemon.pipelined,
                "autotune": config.autotune_b_max,
                "merge_packing": config.merge_packing,
                "fault_plan": faults.spec()}}), flush=True)
            summary = daemon.serve_forever()
        print(json.dumps({"serve_summary": summary}), flush=True)
        # Per-job failures are handled per job (isolated, reported);
        # a clean drain is a clean exit.
        return 0

    t0 = time.perf_counter()
    with rec_ctx:
        if args.cmd == "demo":
            from cuvite_tpu.workloads.synth import many_seed, synthesize_graph

            ids = {}
            for k in range(args.jobs):
                g = synthesize_graph(args.edges, seed=many_seed(args.seed, k))
                ids[server.submit(g)] = f"synth-{k}"
            finished = server.drain()
        else:
            from cuvite_tpu.io.vite import read_vite

            ids = {}
            for path in args.files:
                g = read_vite(path, bits64=args.bits64)
                ids[server.submit(g)] = path
            finished = server.drain()
            if args.output:
                from cuvite_tpu.evaluate.compare import write_communities

                by_id = dict(finished)
                for jid, path in ids.items():
                    if jid in by_id:  # failed jobs have no result
                        write_communities(path + ".communities",
                                          by_id[jid].communities)
        wall = time.perf_counter() - t0
        summary = dict(server.stats.to_dict(), wall_s=round(wall, 3),
                       wall_jobs_per_s=round(len(finished) / max(wall, 1e-9),
                                             2))
        tracer.event("serve_summary", **summary)

    if args.json:
        for jid, res in finished:
            print(json.dumps({
                "job": ids[jid], "job_id": jid,
                "q": round(float(res.modularity), 6),
                "communities": int(res.num_communities),
                "phases": len(res.phases),
                "iterations": int(res.total_iterations),
            }))
    if server.failures:
        summary["failures"] = [
            {"job": ids.get(jid, jid), "error": err}
            for jid, err in server.failures]
    print(json.dumps({"summary": summary}))
    return 0 if not server.failures else 1


if __name__ == "__main__":
    sys.exit(main())
