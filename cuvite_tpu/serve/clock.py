"""Injectable-clock plumbing: the ONE sanctioned wall-clock site in
``serve/`` (graftlint R016 exempts exactly this module).

Every deadline in the serving layer — linger, job ``deadline_s``
shedding, admission ``retry_after_s``, retry backoff — must run on a
clock the caller can inject, because a deadline that reads
``time.monotonic()`` directly is untestable: the only way to drive it
is to actually sleep, and a suite that sleeps its way through linger
windows is both slow and flaky.  The queue/daemon/load-generator all
take ``clock=`` (and ``sleep=``) parameters defaulting to the
functions below; tests pass a fake pair that advances virtual time
instantly.

``time.perf_counter()`` stays allowlisted everywhere in ``serve/``:
busy-window timing (how long the batched driver ran) measures real
elapsed work and is never compared against an injectable deadline.
"""

from __future__ import annotations

import time


def monotonic() -> float:
    """Default serving clock (seconds, monotonic)."""
    return time.monotonic()


def sleep(seconds: float) -> None:
    """Default serving sleep (the retry-backoff / poll-wait partner of
    :func:`monotonic`); injectable so tests advance a fake clock
    instead of blocking."""
    if seconds > 0:
        time.sleep(seconds)
