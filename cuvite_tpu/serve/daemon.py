"""Async serving daemon: socket intake + dispatcher thread + drain.

Turns the synchronous :class:`~cuvite_tpu.serve.queue.LouvainServer`
into a long-lived service:

  * **Intake** — newline-delimited JSON over a unix-domain socket
    (``--socket PATH``) or a TCP port (``--port N``), stdlib only.
    Each connection gets a reader thread; requests are dicts with an
    ``op``: ``submit`` (a graph spec + optional ``tenant`` /
    ``deadline_s``), ``stats`` (a ServeStats snapshot — the poll that
    makes the stats lock a real requirement), ``drain`` (programmatic
    graceful shutdown, same path as SIGTERM).

  * **Dispatcher** — a two-stage PIPELINE by default (ISSUE 14,
    serve/pipeline.py): a packer thread pops due batches under the
    daemon lock and packs + uploads them OUTSIDE it, while an executor
    thread runs the previous batch's compiled program and routes each
    finished/failed/shed job back to the connection that submitted it
    — batch k+1's host pack overlaps batch k's device execution.
    ``pipelined=False`` keeps the serial loop: ONE thread owning
    ``LouvainServer.step()``, waking on submit or every ``poll_s``.
    Either way, queue mutation happens only under the daemon lock, so
    intake and the dispatcher always see a consistent queue.

  * **Graceful drain** — ``request_drain()`` (wired to SIGTERM/SIGINT
    by the CLI) closes intake, flushes every queued bin via
    ``drain()`` (expired jobs still shed, poison jobs still isolate),
    emits the final ServeStats as a ``serve_summary`` event, notifies
    clients, and lets ``serve_forever`` return — the process then
    exits 0.  Jobs submitted after the drain began are refused with
    ``{"ok": false, "draining": true}``.

Wire protocol (one JSON object per line, both directions)::

    -> {"op": "submit", "graph": {"nv": 4, "src": [0,1], "dst": [1,2],
        "w": [1.0, 1.0]}, "tenant": "t0", "deadline_s": 2.5}
    <- {"ok": true, "job_id": "job-0"}
    -> {"op": "submit", "synth": {"edges": 4096, "seed": 7}}
    <- {"ok": false, "rejected": true, "retry_after_s": 0.81}
    <- {"result": {"job_id": "job-0", "q": 0.71, "communities": 9,
        "phases": 2, "iterations": 11}}
    <- {"failed": {"job_id": "job-3", "error": "..."}}
    <- {"shed": {"job_id": "job-4", "late_s": 0.12}}
    -> {"op": "delta", "tenant": "t0", "synth": {"edges": 4096,
        "seed": 7}, "ins": [[0, 9, 2.0]], "del": [[1, 2]],
        "recluster": true, "warm": "labels"}
    <- {"ok": true, "tenant": "t0", "resident": false, "delta":
        {"n_ins": 2, "n_del": 2, "n_del_hit": 2, "ne": 4101,
         "frontier_frac": 0.004}, "recluster": {"warm": "cold",
         "q": 0.69, "communities": 11, "phases": 3, "iterations": 14}}

The ``delta`` verb (ISSUE 17) mutates the tenant's RESIDENT device
slab through the stream/ chokepoint and answers synchronously on the
reader thread.  A graph spec is required on first contact (the one
full upload); afterwards the session stays resident in the server's
StreamPool (LRU under ``ServeConfig.stream_budget_bytes``) and each
visit pays only its delta.  ``"recluster": true`` re-clusters in the
same request — ``warm`` picks the seed (``labels``: previous labels +
delta-frontier active set; ``plp``: label-propagation prepass;
``cold``: identity), and the reply records which arm actually ran (a
fresh session downgrades ``labels`` to ``cold``, visibly).

Graph specs: inline ``graph`` (nv/src/dst/optional w), ``file`` (a
Vite binary path readable by the daemon), or ``synth`` (the
deterministic workload generator — the load generator's compact spec:
both sides derive the same graph from (edges, seed)).  ``"labels":
true`` on a submit adds the full per-vertex label array to the result
line (small graphs; the chaos harness uses it for bit-identity
checks).
"""

from __future__ import annotations

import json
import os
import re
import socket

from cuvite_tpu.serve import sync
from cuvite_tpu.serve.admission import AdmissionReject
from cuvite_tpu.serve.queue import LouvainServer

# The server's auto-generated job-id namespace (queue.py: f"job-{n}");
# client-supplied ids may not squat on it (route-collision hazard).
_AUTO_ID = re.compile(r"job-\d+")


def _decode_graph(req: dict):
    """Build a Graph from a submit request's spec (exactly one of
    ``graph`` / ``file`` / ``synth``)."""
    import numpy as np

    specs = [k for k in ("graph", "file", "synth") if k in req]
    if len(specs) != 1:
        raise ValueError(
            f"submit needs exactly one of graph/file/synth, got {specs}")
    if "graph" in req:
        from cuvite_tpu.core.graph import Graph

        g = req["graph"]
        w = g.get("w")
        return Graph.from_edges(
            int(g["nv"]),
            np.asarray(g["src"], dtype=np.int64),
            np.asarray(g["dst"], dtype=np.int64),
            weights=(np.asarray(w, dtype=np.float64)
                     if w is not None else None))
    if "file" in req:
        from cuvite_tpu.io.vite import read_vite

        return read_vite(req["file"], bits64=bool(req.get("bits64")))
    from cuvite_tpu.workloads.synth import synthesize_graph

    s = req["synth"]
    return synthesize_graph(int(s["edges"]), seed=int(s["seed"]))


class _Client:
    """One connection: a line reader thread plus a write lock (the
    dispatcher and the reader both write response lines).  The socket
    carries a timeout (``ServeDaemon.io_timeout_s``): a send that
    cannot complete within it marks the client dead — the ONE
    dispatcher thread must never block on a tenant that stopped
    reading (head-of-line starvation of every other tenant); read
    timeouts just mean the client is idle and the reader keeps
    listening."""

    def __init__(self, daemon: "ServeDaemon", conn: socket.socket,
                 idx: int):
        self.daemon = daemon
        self.conn = conn
        self.idx = idx
        self.wlock = sync.Lock()
        self.thread = sync.Thread(
            target=self._read_loop, name=f"serve-client-{idx}", daemon=True)

    def send(self, payload: dict) -> bool:
        """False = the client is dead or too slow to take the payload
        (callers drop it); never blocks past the socket timeout."""
        data = (json.dumps(payload) + "\n").encode()
        try:
            with self.wlock:
                self.conn.sendall(data)
            return True
        except OSError:   # includes socket.timeout: a non-reading peer
            return False

    def _read_loop(self) -> None:
        buf = bytearray()
        limit = self.daemon.max_line_bytes
        try:
            while True:
                try:
                    chunk = self.conn.recv(1 << 16)
                except socket.timeout:
                    continue          # idle client: keep listening
                except OSError:
                    break
                if not chunk:
                    break             # orderly close
                buf.extend(chunk)
                if len(buf) > limit and buf.find(b"\n") < 0:
                    # A newline-free stream past the line cap is a
                    # broken or hostile client; dropping IT beats
                    # growing the buffer until the daemon OOMs and
                    # takes every other tenant down.
                    self.send({"ok": False,
                               "error": f"request line exceeds "
                                        f"{limit} bytes"})
                    break
                while True:
                    nl = buf.find(b"\n")
                    if nl < 0:
                        break
                    line = bytes(buf[:nl]).decode("utf-8",
                                                  "replace").strip()
                    del buf[:nl + 1]
                    if not line:
                        continue
                    try:
                        req = json.loads(line)
                    except json.JSONDecodeError as e:
                        self.send({"ok": False, "error": f"bad json: {e}"})
                        continue
                    self.send(self.daemon.handle(req, self))
        finally:
            self.daemon._forget(self)


class ServeDaemon:
    """The async service around a LouvainServer (see module docstring).

    ``poll_s`` bounds how late a linger deadline can fire when no
    submits arrive to wake the dispatcher; it defaults to half the
    server's linger window (floored at 5 ms).
    """

    def __init__(self, server: LouvainServer, *, sock_path: str | None = None,
                 host: str = "127.0.0.1", port: int | None = None,
                 poll_s: float | None = None, io_timeout_s: float = 10.0,
                 max_line_bytes: int = 64 << 20, pipelined: bool = True):
        if (sock_path is None) == (port is None):
            raise ValueError("exactly one of sock_path / port required")
        self.server = server
        self.sock_path = sock_path
        self.host = host
        self.port = port
        self.poll_s = (poll_s if poll_s is not None
                       else max(server.config.linger_s / 2.0, 0.005))
        self.io_timeout_s = io_timeout_s
        self.max_line_bytes = max_line_bytes
        self.pipelined = bool(pipelined)
        # Every primitive comes from serve/sync.py — the seam that lets
        # concheck (graftlint tier 4) run this exact daemon under a
        # deterministic cooperative scheduler; in production these ARE
        # the plain threading primitives.
        self.lock = sync.RLock()             # guards `server` wholesale
        self._wake = sync.Event()            # submit -> dispatcher
        self._drain_req = sync.Event()
        self._done = sync.Event()
        self._listener: socket.socket | None = None
        self._clients: dict = {}
        self._routes: dict = {}     # job_id -> (client, want_labels)
        self._accept_thread = None
        self._dispatch_thread = None
        self.summary: dict | None = None
        # Pipelined dispatch (ISSUE 14, the default): the packer and
        # executor seam-threads replace the single dispatcher; they
        # share THIS daemon's lock/wake/drain events so the submit-vs-
        # drain recheck invariant spans both architectures.  The serial
        # loop (_dispatch_loop) stays selectable for A/Bs.
        self.pipe = None
        if self.pipelined:
            from cuvite_tpu.serve.pipeline import PipelinedDispatcher

            # route looks _route_results up LATE (per call), so an
            # instance-level replacement — concheck's seeded-bug
            # variants monkeypatch exactly this method — reaches the
            # pipelined path the same way it reaches the serial loop's
            # dynamic attribute lookup.
            self.pipe = PipelinedDispatcher(
                server, lock=self.lock, wake=self._wake,
                drain_req=self._drain_req, poll_s=self.poll_s,
                route=lambda *a: self._route_results(*a),
                on_done=self._finalize)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self.sock_path is not None:
            if os.path.exists(self.sock_path):
                os.unlink(self.sock_path)
            ls = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            ls.bind(self.sock_path)
        else:
            ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            ls.bind((self.host, self.port))
            self.port = ls.getsockname()[1]   # resolve port 0
        ls.listen(16)
        ls.settimeout(0.2)                    # accept loop polls the stop flag
        self._listener = ls
        self._accept_thread = sync.Thread(
            target=self._accept_loop, name="serve-accept", daemon=True)
        self._accept_thread.start()
        if self.pipe is not None:
            self.pipe.start()
            self._dispatch_thread = self.pipe.exec_thread
        else:
            self._dispatch_thread = sync.Thread(
                target=self._dispatch_loop, name="serve-dispatch",
                daemon=True)
            self._dispatch_thread.start()

    def request_drain(self) -> None:
        """Begin graceful shutdown (idempotent; signal-handler safe:
        only sets events)."""
        self._drain_req.set()
        self._wake.set()

    def serve_forever(self, timeout: float | None = None) -> dict:
        """Block until the drain completes; returns the final summary
        (also emitted as the ``serve_summary`` trace event)."""
        self._done.wait(timeout)
        if not self._done.is_set():
            raise TimeoutError("daemon did not drain within the timeout")
        if self.pipe is not None and self.pipe.pack_thread is not None:
            self.pipe.pack_thread.join(timeout=10.0)
        self._dispatch_thread.join(timeout=10.0)
        return self.summary

    # -- intake -------------------------------------------------------------

    def _accept_loop(self) -> None:
        idx = 0
        while not self._drain_req.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            conn.settimeout(self.io_timeout_s)
            client = _Client(self, conn, idx)
            idx += 1
            self._clients[id(client)] = client
            client.thread.start()
        try:
            self._listener.close()
        except OSError:
            pass
        if self.sock_path is not None:
            try:
                os.unlink(self.sock_path)
            except OSError:
                pass

    def _forget(self, client: _Client) -> None:
        self._clients.pop(id(client), None)
        try:
            client.conn.close()
        except OSError:
            pass

    def handle(self, req: dict, client: _Client) -> dict:
        op = req.get("op")
        if op == "submit":
            return self._handle_submit(req, client)
        if op == "stats":
            # The stats poll that makes ServeStats' lock a requirement:
            # this runs on a reader thread while the dispatcher appends.
            # (stats.to_dict() is safe under its own lock; the daemon
            # lock additionally keeps the bin dict stable for pending.)
            with self.lock:
                return {"ok": True, "stats": self.server.stats.to_dict(),
                        "pending": self.server.pending(),
                        "conservation": self.server.conservation()}
        if op == "delta":
            return self._handle_delta(req, client)
        if op == "drain":
            self.request_drain()
            return {"ok": True, "draining": True}
        return {"ok": False, "error": f"unknown op {op!r}"}

    def _handle_delta(self, req: dict, client: _Client) -> dict:
        """The streaming verb (ISSUE 17): mutate the tenant's RESIDENT
        slab and optionally re-cluster it warm, answering on the reader
        thread (synchronous — a delta is one tenant's own slab, there
        is no batch to join; exactly one response line per request).
        First contact must carry a graph spec (the one full upload);
        later deltas find the session resident in the StreamPool and
        pay only the delta — unless the LRU budget evicted it, in which
        case the client is told to re-upload."""
        if self._drain_req.is_set():
            return {"ok": False, "draining": True,
                    "error": "daemon is draining; not accepting deltas"}
        tenant = req.get("tenant")
        if not tenant:
            return {"ok": False, "error": "delta needs a tenant"}
        tenant = str(tenant)
        graph = None
        if any(k in req for k in ("graph", "file", "synth")):
            try:
                graph = _decode_graph(req)
            except Exception as e:  # noqa: BLE001 — protocol boundary
                return {"ok": False, "error": f"bad graph spec: {e!r}"}
        ins = req.get("ins") or []
        dels = req.get("del") or []
        try:
            with self.lock:
                # Same recheck as submit: a delta that sees drain_req
                # here must not touch (or admit to) the pool the drain
                # epilogue is about to clear.
                if self._drain_req.is_set():
                    return {"ok": False, "draining": True,
                            "error": "daemon is draining; "
                                     "not accepting deltas"}
                streams = self.server.streams
                sess = streams.get(tenant)
                resident = sess is not None
                if sess is None:
                    if graph is None:
                        return {"ok": False, "resident": False,
                                "error": f"tenant {tenant!r} has no "
                                         "resident session (first "
                                         "contact, or evicted); include "
                                         "a graph/file/synth spec to "
                                         "(re-)upload"}
                    sess = streams.admit(tenant, graph)
                out = {"ok": True, "tenant": tenant, "resident": resident}
                if ins or dels:
                    from cuvite_tpu.stream.delta import DeltaBatch

                    batch = DeltaBatch.from_edits(
                        sess.nv,
                        ins_src=[e[0] for e in ins],
                        ins_dst=[e[1] for e in ins],
                        ins_w=[(e[2] if len(e) > 2 else 1.0)
                               for e in ins],
                        del_src=[e[0] for e in dels],
                        del_dst=[e[1] for e in dels])
                    info = sess.apply_delta(batch)
                    # A spill may have grown the slab class: re-read
                    # the ledger and let LRU eviction re-balance.
                    streams.reledger(tenant)
                    out["delta"] = {k: info[k] for k in
                                    ("n_ins", "n_del", "n_del_hit", "ne",
                                     "frontier_frac")}
                if req.get("recluster"):
                    warm = str(req.get("warm", "labels"))
                    if warm == "labels" and sess.labels() is None:
                        # A fresh (or re-uploaded) session has no prior
                        # labels: the first recluster is cold by
                        # construction — reported as such, never a
                        # silent stale seed.
                        warm = "cold"
                    res = sess.recluster(warm=warm)
                    rc = {"warm": warm,
                          "q": round(float(res.modularity), 6),
                          "communities": int(res.num_communities),
                          "phases": len(res.phases),
                          "iterations": int(res.total_iterations)}
                    if req.get("labels"):
                        rc["labels"] = [int(x) for x in res.communities]
                    out["recluster"] = rc
                return out
        except Exception as e:  # noqa: BLE001 — protocol boundary
            return {"ok": False, "error": repr(e)}

    def _handle_submit(self, req: dict, client: _Client) -> dict:
        if self._drain_req.is_set():
            return {"ok": False, "draining": True,
                    "error": "daemon is draining; not accepting jobs"}
        try:
            graph = _decode_graph(req)
        except Exception as e:  # noqa: BLE001 — protocol boundary
            return {"ok": False, "error": f"bad graph spec: {e!r}"}
        try:
            with self.lock:
                # Re-check under the lock: the dispatcher only exits
                # once drain_req is set AND the queue is empty, so a
                # submit that sees drain_req here can never enqueue a
                # job the drain would miss.
                if self._drain_req.is_set():
                    return {"ok": False, "draining": True,
                            "error": "daemon is draining; "
                                     "not accepting jobs"}
                rid = req.get("id")
                if rid is not None:
                    # A duplicate id would overwrite the first job's
                    # route: its result would be DELIVERED TO THE
                    # WRONG CLIENT and the second job's dropped.  The
                    # 'job-N' namespace is reserved outright — the
                    # server's auto-generated ids live there, and a
                    # client squatting on one collides with a future
                    # auto id no in-flight check can foresee.
                    if _AUTO_ID.fullmatch(str(rid)):
                        return {"ok": False,
                                "error": f"job id {rid!r} is reserved "
                                         "(server-generated namespace "
                                         "'job-<n>'); pick another"}
                    if rid in self._routes:
                        return {"ok": False,
                                "error": f"duplicate job id {rid!r} "
                                         "still in flight"}
                job_id = self.server.submit(
                    graph, rid,
                    tenant=str(req.get("tenant", "anon")),
                    deadline_s=req.get("deadline_s"))
                self._routes[job_id] = (client, bool(req.get("labels")))
        except AdmissionReject as e:
            return {"ok": False, "rejected": True,
                    "retry_after_s": round(e.retry_after_s, 6),
                    "reason": e.reason}
        except Exception as e:  # noqa: BLE001 — injected submit faults etc.
            return {"ok": False, "error": repr(e)}
        self._wake.set()
        return {"ok": True, "job_id": job_id}

    # -- dispatch -----------------------------------------------------------

    def _send_or_drop(self, client: _Client | None, payload: dict) -> None:
        """Deliver to a client, dropping the CONNECTION (not the
        dispatcher) when it is dead or too slow to read — one stalled
        tenant must never head-of-line-block everyone else's results."""
        if client is not None and not client.send(payload):
            self._forget(client)

    def _route_results(self, finished, fails, sheds) -> None:
        # The route-table pops hold the daemon lock like the inserts in
        # _handle_submit do (graftlint R019: _routes' lock discipline is
        # established there) — an unlocked pop could interleave with a
        # reader thread's duplicate-id check and route a result to the
        # wrong client.  Taken per pop, NOT around the sends: a slow
        # client must never stall intake on a held lock.
        for job_id, res in finished:
            with self.lock:
                client, want_labels = self._routes.pop(job_id,
                                                       (None, False))
            payload = {"job_id": job_id,
                       "q": round(float(res.modularity), 6),
                       "communities": int(res.num_communities),
                       "phases": len(res.phases),
                       "iterations": int(res.total_iterations)}
            if want_labels:
                payload["labels"] = [int(x) for x in res.communities]
            self._send_or_drop(client, {"result": payload})
        for job_id, err in fails:
            with self.lock:
                client, _ = self._routes.pop(job_id, (None, False))
            self._send_or_drop(client,
                               {"failed": {"job_id": job_id, "error": err}})
        for job_id, late_s in sheds:
            with self.lock:
                client, _ = self._routes.pop(job_id, (None, False))
            self._send_or_drop(client,
                               {"shed": {"job_id": job_id,
                                         "late_s": round(late_s, 6)}})

    def _dispatch_loop(self) -> None:
        """The SERIAL dispatcher (pipelined=False): one thread owns the
        whole pack+execute lifecycle under the daemon lock — the
        pre-ISSUE-14 architecture, kept for the pipeline A/B."""
        server = self.server
        while True:
            self._wake.wait(timeout=self.poll_s)
            self._wake.clear()
            draining = self._drain_req.is_set()
            with self.lock:
                finished = (server.drain() if draining
                            else server.step())
            # Terminal reports with no result object: the daemon
            # CONSUMES these (consume_terminal copies + clears) — a
            # long-lived service under sustained shedding or a standing
            # fault plan must not grow them unboundedly.
            fails, sheds = server.consume_terminal()
            self._route_results(finished, fails, sheds)
            if draining and server.pending() == 0:
                break
        self._finalize()

    def _finalize(self) -> None:
        """Drain epilogue (both architectures; runs on the executor /
        dispatcher thread): emit the serve_summary, notify clients,
        unblock serve_forever."""
        server = self.server
        # Resident tenant slabs do not outlive the service: evict all
        # (freeing HBM, emitting one `evict` event each) BEFORE the
        # summary so its stream block shows the final ledger.
        server.streams.clear()
        summary = dict(server.stats.to_dict(),
                       conservation=self.server.conservation(),
                       stream=dict(server.streams.to_dict(),
                                   conservation=server.streams
                                   .conservation()))
        server.tracer.event("serve_summary", **summary)
        self.summary = summary
        for client in list(self._clients.values()):
            client.send({"serve_summary": summary})
            self._forget(client)
        self._done.set()
