"""Deterministic fault injection for the serving dispatch path.

A multi-tenant service earns its robustness claims by *demonstrating*
them: the reference's failure model is all-or-nothing (one MPI rank
faulting kills the whole Ghosh et al. job), whereas the serving layer
must degrade per-job and stay up.  This module injects faults at named
sites in the dispatch path so tests (and operators) can prove that —
deterministically, with no monkeypatching of jax internals.

Fault plans are config/env-driven strings (``CUVITE_FAULT_PLAN``)::

    dispatch:raise:every=7            # every 7th dispatch raises (permanent)
    device:transient:n=2              # the first 2 device passages fail
    pack:transient:p=0.1,seed=42      # seeded coin-flip per passage
    unpack:raise:n=1;device:transient:every=5   # ';' joins directives

Grammar: directives separated by ``;`` (or newlines), each
``site:kind[:key=value[,key=value...]]``.  Sites are the named points
the queue's dispatch path passes through (:data:`FAULT_SITES`); kinds
are ``transient`` (the dispatcher retries with exponential backoff on
the injectable clock) and ``raise`` (permanent: flows to the poison
isolation machinery — the batch splits, batchmates survive, the job
fails exactly once).  Selectors: ``every=N`` (every Nth passage
through the site), ``n=N`` (the first N passages), ``p=F`` with
optional ``seed=S`` (an independent ``random.Random(S)`` coin per
passage — randomized but fully reproducible).

Everything here is stdlib-only and side-effect-free until ``check()``
raises: a plan is pure bookkeeping (per-site passage counters, per-rule
fire counts) the chaos tests can introspect.
"""

from __future__ import annotations

import dataclasses
import os
import random

# Named injection points in the dispatch path, in path order:
#   submit   — intake, after admission but BEFORE the job is accounted:
#              the submit call raises, the job never enqueues, and the
#              conservation ledger counts it as REJECTED (jobs_rejected,
#              a 'reject' event with reason=injected-fault — see
#              LouvainServer.submit);
#   pack     — batch assembly (shape union / slab packing decisions);
#   dispatch — immediately before the batched driver is invoked;
#   device   — wraps the driver invocation itself (the "chip fell over"
#              stand-in);
#   unpack   — after the driver returns, before per-tenant results are
#              emitted.
FAULT_SITES = ("submit", "pack", "dispatch", "device", "unpack")

FAULT_KINDS = ("transient", "raise")

ENV_VAR = "CUVITE_FAULT_PLAN"


class InjectedFault(RuntimeError):
    """A fault fired by the plan.  ``permanent`` decides the recovery
    path: transient -> bounded retry with backoff; permanent -> poison
    isolation (split the batch, fail the job)."""

    def __init__(self, site: str, kind: str, seq: int, permanent: bool):
        self.site = site
        self.kind = kind
        self.seq = seq
        self.permanent = permanent
        flavor = "permanent" if permanent else "transient"
        super().__init__(
            f"injected {flavor} fault at site '{site}' (passage {seq})")


@dataclasses.dataclass
class FaultRule:
    """One parsed directive.  Exactly one selector is set."""

    site: str
    kind: str                 # 'transient' | 'raise'
    every: int | None = None  # fire on every Nth passage
    n: int | None = None      # fire on the first N passages
    p: float | None = None    # seeded coin-flip per passage
    seed: int = 0
    fired: int = 0            # bookkeeping for chaos-test assertions

    @property
    def permanent(self) -> bool:
        return self.kind == "raise"

    def spec(self) -> str:
        if self.every is not None:
            sel = f"every={self.every}"
        elif self.n is not None:
            sel = f"n={self.n}"
        else:
            sel = f"p={self.p},seed={self.seed}"
        return f"{self.site}:{self.kind}:{sel}"


def _parse_directive(text: str) -> FaultRule:
    parts = text.split(":")
    if len(parts) != 3:
        raise ValueError(
            f"fault directive {text!r}: want 'site:kind:key=value[,...]' "
            f"(sites {FAULT_SITES}, kinds {FAULT_KINDS})")
    site, kind, params = (p.strip() for p in parts)
    if site not in FAULT_SITES:
        raise ValueError(
            f"fault directive {text!r}: unknown site {site!r} "
            f"(want one of {FAULT_SITES})")
    if kind not in FAULT_KINDS:
        raise ValueError(
            f"fault directive {text!r}: unknown kind {kind!r} "
            f"(want one of {FAULT_KINDS})")
    rule = FaultRule(site=site, kind=kind)
    selectors = 0
    for kv in filter(None, (s.strip() for s in params.split(","))):
        key, _, value = kv.partition("=")
        try:
            if key == "every":
                rule.every = int(value)
                selectors += 1
                if rule.every < 1:
                    raise ValueError
            elif key == "n":
                rule.n = int(value)
                selectors += 1
                if rule.n < 1:
                    raise ValueError
            elif key == "p":
                rule.p = float(value)
                selectors += 1
                if not 0.0 <= rule.p <= 1.0:
                    raise ValueError
            elif key == "seed":
                rule.seed = int(value)
            else:
                raise ValueError
        except ValueError:
            raise ValueError(
                f"fault directive {text!r}: bad parameter {kv!r} "
                "(want every=N>=1 | n=N>=1 | p=F in [0,1] [,seed=S])"
            ) from None
    if selectors != 1:
        raise ValueError(
            f"fault directive {text!r}: exactly one selector "
            "(every=/n=/p=) required")
    return rule


class FaultPlan:
    """A parsed set of fault rules with per-site passage counters.

    ``check(site)`` advances the site's counter and raises
    :class:`InjectedFault` when any rule elects this passage (first
    matching rule in plan order wins; its ``fired`` count increments
    either way the exception is later handled).  With no rules on the
    site it is a cheap no-op — the queue threads ``check`` calls
    unconditionally.
    """

    def __init__(self, rules: list | None = None):
        self.rules = list(rules or [])
        self.counts: dict[str, int] = {s: 0 for s in FAULT_SITES}
        self._by_site: dict[str, list] = {}
        self._rng: dict[int, random.Random] = {}
        for rule in self.rules:
            self._by_site.setdefault(rule.site, []).append(rule)
            if rule.p is not None:
                self._rng[id(rule)] = random.Random(rule.seed)

    @classmethod
    def parse(cls, spec: str | None) -> "FaultPlan":
        """Parse a plan string (None/'' -> empty plan; ValueError on a
        malformed directive — a typo'd plan must never silently run
        fault-free while the operator believes chaos is on)."""
        rules = []
        for chunk in (spec or "").replace("\n", ";").split(";"):
            chunk = chunk.strip()
            if chunk:
                rules.append(_parse_directive(chunk))
        return cls(rules)

    @classmethod
    def from_env(cls, env_var: str = ENV_VAR) -> "FaultPlan":
        return cls.parse(os.environ.get(env_var))

    def __bool__(self) -> bool:
        return bool(self.rules)

    def check(self, site: str) -> None:
        """One passage through ``site``; raises when a rule elects it."""
        rules = self._by_site.get(site)
        if not rules:
            return
        self.counts[site] += 1
        seq = self.counts[site]
        for rule in rules:
            if rule.every is not None:
                hit = seq % rule.every == 0
            elif rule.n is not None:
                hit = seq <= rule.n
            else:
                # Independent per-rule stream: other rules / sites can
                # never perturb this rule's draw sequence.
                hit = self._rng[id(rule)].random() < rule.p
            if hit:
                rule.fired += 1
                raise InjectedFault(site, rule.kind, seq, rule.permanent)

    def spec(self) -> str:
        return ";".join(r.spec() for r in self.rules)
