"""Admission control + load shedding for the serving queue.

The batched queue (serve/queue.py) bounds nothing by itself: under
sustained overload its bins grow without limit and every job's
enqueue->dispatch wait grows with them — the classic unbounded-queue
failure where the service is "up" but no request meets its latency
target.  This module bounds the system at INTAKE instead:

* **Admission control** — each class's queue depth is bounded by what
  the measured service rate says can still meet the ``wait_p95`` SLO.
  The controller keeps a sliding-window MEDIAN of per-batch service
  seconds per class (observed after every dispatch; median, so a cold
  first batch's XLA compile cannot poison the estimate) and projects
  a new job's wait as
  ``floor(depth / b_max) * est_batch_s`` — the full batches that must
  complete before the job's own batch can dispatch; when the
  projection breaches the SLO the job is REJECTED at submit with a
  structured
  ``retry_after_s`` (the time by which the projection says the backlog
  will have drained enough to admit) — callers back off instead of
  piling on.  Cold start (no estimate yet) admits: the controller can
  only bound what it has measured.

* **Deadline shedding** — jobs may carry ``deadline_s``; an expired
  job is SHED at pop time, before packing (a batch row spent on a job
  whose client already gave up is pure waste — worse, it delays jobs
  that can still make their deadlines).  Shedding happens in the queue
  (serve/queue.py), not here; this module just owns the vocabulary.

Every rejection is a terminal outcome in the job-conservation
invariant: an arriving job ends exactly once as done / failed /
rejected / shed.  Stdlib-only, clock-free (the queue passes depths and
observations in; deadlines run on the queue's injectable clock).
"""

from __future__ import annotations

import collections
import dataclasses
import statistics


class AdmissionReject(RuntimeError):
    """Raised by ``LouvainServer.submit`` when admission control turns
    a job away.  ``retry_after_s`` is the structured backpressure
    signal: the earliest time the projection says a resubmit could be
    admitted.  Daemon clients receive it as
    ``{"ok": false, "rejected": true, "retry_after_s": ...}``."""

    def __init__(self, retry_after_s: float, reason: str):
        self.retry_after_s = float(retry_after_s)
        self.reason = reason
        super().__init__(
            f"admission rejected: {reason} (retry_after_s="
            f"{self.retry_after_s:.3f})")


@dataclasses.dataclass
class AdmissionConfig:
    """Knobs.  ``wait_slo_s`` is the queue-wait p95 target the
    controller defends; ``window`` is how many recent batch service
    times the per-class MEDIAN estimator keeps (a median, not an EWMA,
    on purpose: the first dispatch of a class carries its XLA compile
    — seconds against a tens-of-ms steady state — and an EWMA drags
    that outlier through many batches of decay, slamming intake shut
    on a freshly-started daemon; the median sheds it as soon as two
    normal batches follow); ``headroom`` scales the projection (>1.0
    rejects earlier).  The headroom default aims the projection ~20%
    inside the SLO: the estimator lags a rising service time, the
    queue depth cannot see the batch already in flight, and the
    linger window adds slack on top — a controller that aims exactly
    at the SLO lands just past it under sustained overload (measured:
    wait_p95 512 ms against a 500 ms SLO at 2x saturation with
    headroom 1.0; BASELINE.md round-13)."""

    wait_slo_s: float = 2.0
    window: int = 16
    headroom: float = 1.25

    def __post_init__(self) -> None:
        if self.wait_slo_s <= 0:
            raise ValueError(f"wait_slo_s must be > 0, got {self.wait_slo_s}")
        if self.window < 1:
            raise ValueError(f"window must be >= 1, got {self.window}")
        if self.headroom <= 0:
            raise ValueError(f"headroom must be > 0, got {self.headroom}")


class AdmissionController:
    """Per-class service-time estimator + admit/reject decision.

    The queue calls :meth:`observe` after every completed dispatch
    (measured ``busy_s`` of the batch, on the injectable clock) and
    :meth:`decide` on every submit.  The derived per-class depth bound
    is ``(floor(wait_slo_s / (headroom * est_batch_s)) + 1) * b_max``
    jobs — expressed below as a wait projection so the reject response
    can carry an honest ``retry_after_s``.
    """

    def __init__(self, config: AdmissionConfig | None = None):
        self.config = config or AdmissionConfig()
        # class key -> deque of recent batch service seconds (median
        # estimator; see AdmissionConfig.window for why not an EWMA).
        self._obs: dict = {}

    def estimate(self, key) -> float | None:
        """Median batch-service seconds for a class over the recent
        window (None before the first observation)."""
        obs = self._obs.get(key)
        return statistics.median(obs) if obs else None

    def observe(self, key, busy_s: float) -> None:
        obs = self._obs.get(key)
        if obs is None:
            obs = self._obs[key] = collections.deque(
                maxlen=self.config.window)
        obs.append(busy_s)

    def reset(self, key=None) -> None:
        """Forget observations (one class, or all): the estimator
        restarts cold and admits until re-measured."""
        if key is None:
            self._obs.clear()
        else:
            self._obs.pop(key, None)

    def projected_wait_s(self, key, depth: int, b_max: int) -> float | None:
        """Projected enqueue->dispatch wait of a job joining a class
        bin that already holds ``depth`` jobs (None = no estimate
        yet): ``floor(depth/b_max)`` FULL batches must complete before
        the job's own batch can dispatch, each costing one estimated
        service window.  The job's own batch service is deliberately
        NOT counted — the SLO defends queue wait (enqueue->dispatch),
        and a job joining an empty bin dispatches within the linger
        window regardless of how long its batch then runs; counting
        the own-batch window would permanently lock out any class
        whose batch service exceeds ``slo/headroom`` even at depth 0
        (rejecting traffic an idle server could serve)."""
        est = self.estimate(key)
        if est is None:
            return None
        return (depth // b_max) * est * self.config.headroom

    def decide(self, key, depth: int, b_max: int) -> float | None:
        """None = admit; else the ``retry_after_s`` to reject with.

        ``retry_after_s`` is how long until enough backlog has drained
        that the same projection would admit: the excess wait beyond
        the SLO, floored at one batch service window (an immediate
        resubmit would meet the same queue)."""
        projected = self.projected_wait_s(key, depth, b_max)
        if projected is None or projected <= self.config.wait_slo_s:
            return None
        est = self.estimate(key) * self.config.headroom
        return max(projected - self.config.wait_slo_s, est)


@dataclasses.dataclass
class AutotuneConfig:
    """Knobs of the measured-service ``b_max`` autotuner.  ``min_obs``
    is the per-rung warm window: a rung is a candidate only after that
    many batches DISPATCHED AT IT have been measured — which also means
    its compiled program already exists, so retuning onto it can never
    trigger a fresh XLA compile inside a bench's guard window (the
    rung-candidacy rule IS the compile clamp)."""

    min_obs: int = 3
    window: int = 16

    def __post_init__(self) -> None:
        if self.min_obs < 1:
            raise ValueError(f"min_obs must be >= 1, got {self.min_obs}")
        if self.window < self.min_obs:
            raise ValueError(
                f"window ({self.window}) must be >= min_obs "
                f"({self.min_obs})")


class BmaxAutotuner:
    """Per-class ``b_max`` selection from MEASURED service curves
    (ISSUE 14): instead of trusting the ``ServeConfig.b_max`` constant,
    pick the BATCH_SIZES rung that maximizes projected goodput
    ``rung / est_batch_s(rung)`` among the rungs the class can serve
    INSIDE the wait SLO.  The curve comes from the same injectable-clock
    service observations the admission estimator keeps, separated by
    the rung the batch actually dispatched at (open-loop traffic
    naturally samples several rungs via linger/drain partials).

    Feasibility mirrors the admission projection: a rung whose
    headroom-scaled batch service exceeds the SLO would force every job
    that queues behind ONE full batch past its wait target — a default
    ``b_max=64`` whose batch costs seconds against a 500 ms SLO is the
    motivating misconfiguration.  When no measured rung is feasible the
    tuner falls back to the fastest measured one (least-infeasible:
    strictly better than staying on a slower rung).

    Candidates are clamped to rungs with >= ``min_obs`` observations —
    i.e. rungs whose programs are measured AND compiled — so a retune
    never selects a program that would compile fresh mid-serve."""

    def __init__(self, admission: AdmissionConfig,
                 config: AutotuneConfig | None = None):
        self.slo_s = admission.wait_slo_s
        self.headroom = admission.headroom
        self.config = config or AutotuneConfig()
        # (class key, rung) -> deque of batch service seconds
        self._obs: dict = {}

    def observe(self, key, rung: int, busy_s: float) -> None:
        """One dispatched batch of ``rung`` padded rows took ``busy_s``
        (pack + execute, on the injectable clock)."""
        if rung < 1:
            return
        obs = self._obs.get((key, rung))
        if obs is None:
            obs = self._obs[(key, rung)] = collections.deque(
                maxlen=self.config.window)
        obs.append(busy_s)

    def curve(self, key) -> dict:
        """The measured service curve: {rung: median batch seconds} over
        rungs past their warm window (the candidate set)."""
        out = {}
        for (k, rung), obs in self._obs.items():
            if k == key and len(obs) >= self.config.min_obs:
                out[rung] = statistics.median(obs)
        return out

    def pick(self, key, cap: int) -> int | None:
        """The goodput-optimal measured rung <= ``cap`` (None before
        any rung clears its warm window).  SLO-feasible rungs
        (``est * headroom <= slo``) compete on projected goodput
        ``rung / est``; with none feasible the fastest measured rung
        wins (least-infeasible)."""
        curve = {r: est for r, est in self.curve(key).items() if r <= cap}
        if not curve:
            return None
        feasible = {r: est for r, est in curve.items()
                    if est * self.headroom <= self.slo_s}
        if feasible:
            return max(feasible, key=lambda r: r / max(feasible[r], 1e-9))
        return min(curve, key=curve.get)
