"""cuvite_tpu.serve — the fault-tolerant multi-tenant serving layer.

A slab-class serving queue in front of the batched driver
(louvain/batched.py): incoming jobs bin by their pow2 slab class
(core/batch.py::slab_class_of) with per-tenant fairness sub-queues,
pack into batches up to ``b_max`` with a max-linger deadline, run
through ONE compiled per-phase program per ``(class, B)``, and unpack
into per-tenant ``LouvainResult``s.

Around that core (ISSUE 11): SLO-projected admission control with
structured ``retry_after_s`` rejections (admission.py), deadline
shedding, deterministic fault injection with bounded
exponential-backoff retry (faults.py), an async socket daemon with
graceful SIGTERM drain (daemon.py), and an open-loop saturation load
generator (loadgen.py).  Dispatch is a two-stage pipeline (ISSUE 14,
pipeline.py): a packer thread builds + uploads batch k+1 while an
executor thread runs batch k's compiled program, bridged by a depth-1
handoff slot — steady-state batch period max(pack_s, device_s) instead
of their sum — and the admission estimator's measured service curves
drive per-class ``b_max`` autotuning (admission.py::BmaxAutotuner).  Every deadline runs on the injectable clock
(clock.py; graftlint R016), and every lock/event/thread comes from the
sync seam (sync.py): plain threading in production, a deterministic
cooperative scheduler under the tier-4 concurrency checker
(analysis/concheck.py — races, deadlocks, and lock-across-send
regressions are machine-checked across seeded interleavings before
they can reach a real dispatcher thread).

    python -m cuvite_tpu.serve demo --jobs 64 --b-max 16
    python -m cuvite_tpu.serve cluster-many a.vite b.vite ...
    python -m cuvite_tpu.serve daemon --socket /tmp/cuvite.sock
"""

from cuvite_tpu.serve.admission import (
    AdmissionConfig,
    AdmissionController,
    AdmissionReject,
    AutotuneConfig,
    BmaxAutotuner,
)
from cuvite_tpu.serve.daemon import ServeDaemon
from cuvite_tpu.serve.faults import FaultPlan, InjectedFault
from cuvite_tpu.serve.pipeline import PipelinedDispatcher
from cuvite_tpu.serve.queue import (
    Job,
    LouvainServer,
    PackedBatch,
    ServeConfig,
    ServeStats,
)

__all__ = [
    "AdmissionConfig", "AdmissionController", "AdmissionReject",
    "AutotuneConfig", "BmaxAutotuner", "FaultPlan", "InjectedFault",
    "Job", "LouvainServer", "PackedBatch", "PipelinedDispatcher",
    "ServeConfig", "ServeDaemon", "ServeStats",
]
