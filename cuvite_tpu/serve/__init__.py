"""cuvite_tpu.serve — the multi-tenant serving layer (ISSUE 9).

A slab-class serving queue in front of the batched driver
(louvain/batched.py): incoming jobs bin by their pow2 slab class
(core/batch.py::slab_class_of), pack into batches up to ``b_max`` with
a max-linger deadline, run through ONE compiled per-phase program per
``(class, B)``, and unpack into per-tenant ``LouvainResult``s.

    python -m cuvite_tpu.serve demo --jobs 64 --b-max 16
    python -m cuvite_tpu.serve cluster-many a.vite b.vite ...
"""

from cuvite_tpu.serve.queue import (
    Job,
    LouvainServer,
    ServeConfig,
    ServeStats,
)

__all__ = ["Job", "LouvainServer", "ServeConfig", "ServeStats"]
