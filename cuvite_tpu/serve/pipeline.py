"""Pipelined dispatch: overlap host pack with device execution.

The serial dispatcher serializes every batch's lifecycle — while batch
k runs its compiled program, batch k+1's host-side pack (slab stacking
+ bucket-plan build + upload) waits in the queue, so the batch period
is ``pack_s + device_s``.  The PR 9/10 ``pack`` spans price that tax
directly; the reference line of GPU Louvain work (Naim et al.,
arXiv:1805.10904) hides it by overlapping host preparation with device
kernels.  This module brings the same overlap to the serving path
(ISSUE 14):

  * **packer** — pops one due batch under the intake lock
    (``LouvainServer.pop_due``), then OUTSIDE the lock runs the PACK
    stage (``pack_batch``: shape union, slab stacking, plan build,
    device upload) and hands the PackedBatch over;
  * **handoff** — a depth-1 blocking slot (:class:`Handoff`): the
    packer blocks on ``put`` while the executor is still busy, giving
    classic double buffering — at most one batch packed ahead;
  * **executor** — takes each PackedBatch, runs the EXECUTE stage
    (``execute_batch``: compiled program + retry + per-tenant
    accounting), and delivers results/failures/sheds to the routing
    callback.

Steady-state batch period becomes ``max(pack_s, device_s)`` instead of
their sum; ``ServeStats.overlap_frac`` measures the realized overlap.

Drain ordering (the SIGTERM contract): once drain is requested the
packer flushes every queued bin through pack and the handoff slot, then
posts the close sentinel; the executor finishes the in-flight batch,
drains the slot, sweeps the last terminal reports, and calls the
``on_done`` callback (the daemon's summary emission).  A pack in flight
when the drain arrives is handed off and executed exactly once — the
``drain-vs-inflight-pack`` concheck scenario pins that interleaving.

Every synchronization primitive comes from serve/sync.py (the seam),
so concheck (graftlint tier 4) explores this exact two-thread machine
under its deterministic scheduler; graftlint R022 keeps direct
``threading.*`` construction out of serve/.  Fault-plan sites keep
their stage homes: ``pack`` faults fire (and retry) on the packer
thread, ``dispatch``/``device``/``unpack`` faults on the executor.
Poison isolation runs in whichever stage hit the failure — the batch
splits there and each job re-runs the full serial pack+execute alone.
"""

from __future__ import annotations

from cuvite_tpu.serve import sync

# Handoff close sentinel: posted by the packer after the final batch
# (drain) so the executor can finish the slot and run the epilogue.
_CLOSED = object()


class Handoff:
    """Depth-1 blocking handoff slot between the packer and the
    executor (double buffering).  ``put`` blocks while the previous
    item is still unconsumed; ``get`` blocks until an item (or the
    close sentinel) arrives.  Built on the serve/sync.py Condition so
    every handoff is a happens-before edge under the concheck
    scheduler."""

    def __init__(self, name: str = "handoff"):
        self._cond = sync.Condition(name=name)
        self._item = None
        self._has = False
        self._closed = False

    def put(self, item) -> None:
        with self._cond:
            while self._has:
                self._cond.wait()
            self._item = item
            self._has = True
            self._cond.notify_all()

    def close(self) -> None:
        """Post the end-of-stream marker (after the last ``put``)."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def get(self):
        """The next item, or the ``CLOSED`` sentinel once the packer
        closed an empty slot."""
        with self._cond:
            while not self._has:
                if self._closed:
                    return _CLOSED
                self._cond.wait()
            item = self._item
            self._item = None
            self._has = False
            self._cond.notify_all()
            return item

    @property
    def closed_sentinel(self):
        return _CLOSED


class PipelinedDispatcher:
    """The two seam-threads around a LouvainServer (see module
    docstring).  ``lock`` is the INTAKE lock — pops and submits
    serialize under it (the daemon passes its own lock so the
    drain-recheck invariant spans both); the pack and execute stages
    run outside it.  ``route(finished, fails, sheds)`` delivers
    per-job outcomes (the daemon's ``_route_results``); None collects
    them on the dispatcher (``results``/``fails``/``sheds``) for
    library callers like the load generator.  ``on_done`` runs on the
    executor thread after the drain completes (the daemon's summary
    emission) — ``wait_done`` unblocks after it."""

    def __init__(self, server, *, lock=None, wake=None, drain_req=None,
                 poll_s: float = 0.01, route=None, on_done=None):
        self.server = server
        self.lock = lock if lock is not None else sync.RLock(
            name="PipelinedDispatcher.lock")
        self._wake = wake if wake is not None else sync.Event(
            name="PipelinedDispatcher._wake")
        self._drain_req = drain_req if drain_req is not None else sync.Event(
            name="PipelinedDispatcher._drain_req")
        self._done = sync.Event(name="PipelinedDispatcher._done")
        self.poll_s = poll_s
        self.handoff = Handoff()
        self._route = route
        self._on_done = on_done
        self.results: list = []
        self.fails: list = []
        self.sheds: list = []
        self.pack_thread = None
        self.exec_thread = None
        with server.stats.lock:
            server.stats.pipeline_depth = 2

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self.pack_thread = sync.Thread(
            target=self._pack_loop, name="serve-pack", daemon=True)
        self.exec_thread = sync.Thread(
            target=self._exec_loop, name="serve-execute", daemon=True)
        self.pack_thread.start()
        self.exec_thread.start()

    def submit(self, graph, job_id=None, **kw) -> str:
        """Intake for library callers (the daemon uses its own handle
        path under the shared lock): enqueue under the intake lock and
        wake the packer."""
        with self.lock:
            jid = self.server.submit(graph, job_id, **kw)
        self._wake.set()
        return jid

    def wake(self) -> None:
        self._wake.set()

    def request_drain(self) -> None:
        """Begin the drain (idempotent, signal-handler safe)."""
        self._drain_req.set()
        self._wake.set()

    def wait_done(self, timeout: float | None = None) -> bool:
        return self._done.wait(timeout)

    # -- the two stages -----------------------------------------------------

    def _pack_loop(self) -> None:
        server = self.server
        try:
            while True:
                self._wake.wait(timeout=self.poll_s)
                self._wake.clear()
                draining = self._drain_req.is_set()
                while True:
                    with self.lock:
                        popped = server.pop_due(force=draining)
                    if popped is None:
                        break
                    # The expensive stage, OUTSIDE the intake lock: a
                    # slow pack must never stall submits or the stats
                    # poll.  put() then blocks until the executor takes
                    # the previous batch (depth-1 double buffering).
                    packed = server.pack_batch(*popped)
                    self.handoff.put(packed)
                if draining:
                    with self.lock:
                        # Same-lock recheck as the serial loop: a submit
                        # that saw drain_req unset enqueued under this
                        # lock BEFORE this check, so its job is visible
                        # here; one that sees it set is refused.
                        if server.pending() == 0:
                            break
        finally:
            self.handoff.close()

    def _exec_loop(self) -> None:
        server = self.server
        while True:
            item = self.handoff.get()
            if item is _CLOSED:
                break
            finished = server.execute_batch(item)
            self._deliver(finished)
        # Final sweep: sheds/failures recorded by the packer after the
        # executor's last delivery (e.g. a drain that shed everything).
        self._deliver([])
        if self._on_done is not None:
            self._on_done()
        self._done.set()

    def _deliver(self, finished) -> None:
        fails, sheds = self.server.consume_terminal()
        if self._route is not None:
            self._route(finished, fails, sheds)
        else:
            self.results.extend(finished)
            self.fails.extend(fails)
            self.sheds.extend(sheds)
