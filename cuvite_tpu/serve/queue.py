"""Slab-class batching queue: the serving core under the async daemon.

Queue discipline (ISSUE 9).  Jobs bin by (slab class, accumulator
class) — the pow2 ``(nv_pad, ne_pad)`` shape their graph canonicalizes
to plus its solo in-loop accumulator tag — because only same-class
slabs can stack into one compiled program, and a batch mixing a
ds32-scale tenant with f32 ones would silently change the f32 rows'
results vs their solo runs (louvain/batched.py::accum_class_of).  A
bin dispatches when either

  * it holds ``b_max`` jobs (a full batch), or
  * its OLDEST job has waited ``linger_s`` (the latency bound: a lone
    tenant of a rare class must not wait for batch-mates that never
    come).

Inside a bin jobs live in PER-TENANT sub-queues and pack by
round-robin pop across tenants (ISSUE 11): a tenant streaming 1000
jobs gets at most its fair share of each batch's ``b_max`` rows, and
other tenants' jobs dispatch within ~one batch instead of queueing
behind the firehose.  The linger deadline reads the oldest job across
ALL tenants of the bin, so the firehose cannot hold it hostage either.

Robustness layer (ISSUE 11), in path order:

  * **admission** — with ``ServeConfig.admission`` set, submit rejects
    (``AdmissionReject`` with ``retry_after_s``) when the class's
    measured service rate projects the new job's wait past the
    ``wait_slo_s`` SLO (serve/admission.py);
  * **shedding** — jobs carrying ``deadline_s`` are dropped at pop
    time once expired, BEFORE packing: an expired job never occupies a
    batch row;
  * **fault injection + retry** — a ``FaultPlan`` (serve/faults.py)
    fires at the named dispatch sites; transient faults retry the
    batch with exponential backoff on the injectable clock/sleep pair,
    permanent ones flow to the poison isolation machinery (the batch
    splits, batchmates survive, the job fails exactly once).

Job conservation is the load-bearing invariant: every ADMITTED job
terminates exactly once as done, failed, or shed (rejected jobs never
enter the queue and are their own terminal state) —
``jobs_done + jobs_failed + jobs_shed + pending() == jobs_submitted``
at all times; :meth:`LouvainServer.conservation` spells it out and the
chaos tests assert it under randomized seeded fault plans.

Dispatch is TWO stages since ISSUE 14 — ``pack_batch()`` (shape union,
slab stacking + bucket-plan build + device upload, the 'pack' fault
site) and ``execute_batch()`` (the compiled program + result routing,
'dispatch'/'device'/'unpack' sites; a transient device retry re-runs
the ALREADY-UPLOADED batch bit-identically) — composed serially by
``step()``/``drain()``, or run on two seam-threads with a depth-1
handoff slot by the pipelined dispatcher (serve/pipeline.py), which
makes the steady-state batch period max(pack_s, device_s) instead of
their sum.  Fields the two stages share (``_shapes``, ``_b_max``,
``failures``, ``shed``, every ServeStats counter) live under the stats
lock; bin mutation (``submit``/``pop_due``) serializes under the
caller's intake lock (the daemon lock).

This module deliberately contains NO jax calls: the compiled program
lives at module scope in louvain/batched.py, device placement happens
once per packed batch inside the driver (the pack stage calls
louvain.batched.pack_many, the execute stage execute_many).  graftlint R014 enforces the
corresponding trap (jit/vmap construction or per-job device_put inside
a serve/ queue loop — the compile-per-job and upload-per-job mistakes
that would silently erase the batching win), and R016 keeps every
deadline on the injectable clock (serve/clock.py is the one sanctioned
wall-clock site; ``time.perf_counter`` busy-timing stays allowlisted).

Observability: every dispatch opens a ``pack`` span (class, jobs, B,
trigger) and emits one ``tenant_result`` event per job; the robustness
paths add ``admit``/``reject``/``shed``/``retry`` events and a
``drain`` span — OBSERVABILITY.md documents the fields.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import threading
import warnings

from cuvite_tpu.core.batch import (
    BATCH_ENGINES,
    BATCH_SIZES,
    batch_pad,
    slab_class_of,
)
from cuvite_tpu.core.types import TERMINATION_PHASE_COUNT
from cuvite_tpu.serve import clock as serve_clock
from cuvite_tpu.serve import sync
from cuvite_tpu.serve.admission import (
    AdmissionConfig,
    AdmissionController,
    AdmissionReject,
    BmaxAutotuner,
)
from cuvite_tpu.serve.faults import FaultPlan, InjectedFault


@dataclasses.dataclass
class ServeConfig:
    """Queue knobs.  ``b_max`` should be a BATCH_SIZES rung (it is
    rounded to one, with a warning when that CHANGES the requested
    value): it caps both batch latency amortization and the
    compile-cache footprint per class.  ``linger_s`` bounds the extra
    latency batching may add to any single job.

    ``engine`` (ISSUE 10) selects the batched driver's per-phase
    engine: ``'bucketed'`` (the default — phase 0 through the vmapped
    sort-free bucketed sweep over pack-time plans, coarse phases fused
    at the serving-coarse class; the configuration every per-graph AND
    batched benchmark shows is the fast one) or ``'fused'`` (PR 9's
    all-phases sort-formulation loop).  Engine choice never changes
    results — per-tenant labels/Q are bit-identical across engines.

    Robustness knobs (ISSUE 11): ``admission`` — an
    :class:`~cuvite_tpu.serve.admission.AdmissionConfig` enables
    SLO-projected admission control (None = admit everything, the
    library default); ``max_retries``/``retry_base_s`` bound the
    transient-fault retry loop (backoff = base * 2**(attempt-1), slept
    on the server's injectable sleep)."""

    b_max: int = 64
    linger_s: float = 0.05
    threshold: float = 1.0e-6
    max_phases: int = TERMINATION_PHASE_COUNT
    mesh: object = "auto"   # forwarded to run_batched
    engine: str = "bucketed"
    admission: AdmissionConfig | None = None
    max_retries: int = 3
    retry_base_s: float = 0.05
    # Measured-service b_max autotuning (ISSUE 14): after a per-rung
    # warm window, each class serves at the BATCH_SIZES rung that
    # maximizes projected goodput under the admission SLO (see
    # serve/admission.py::BmaxAutotuner); config b_max stays the cap.
    # Requires `admission` (the SLO and the service estimator live
    # there).
    autotune_b_max: bool = False
    # Tenant slab residency budget (ISSUE 17): total HBM bytes the
    # StreamPool may keep resident across per-tenant StreamSessions
    # before LRU eviction kicks in.  A returning tenant whose session
    # survived pays only its delta; an evicted one re-uploads.
    stream_budget_bytes: int = 256 << 20
    # Mixed-class sub-row merging (ISSUE 20): when on, a due small-class
    # bin may dispatch as ONE merged batch of a larger served class's
    # rows — 2^k fenced sub-rows per row (core/batch.py::SubRowLayout),
    # up to b_max * n_sub jobs per dispatch instead of b_max.  The
    # packer merges when the bin OVERFLOWS its class cap (depth > b_max)
    # or when the measured service medians say the packed batch beats
    # lingering (see LouvainServer._merge_plan).  Results stay
    # bit-identical to solo runs (the fence construction); poison
    # isolation splits a merged batch per job at its OWN class.
    merge_packing: bool = False

    def __post_init__(self) -> None:
        # Config-time validation (ISSUE 11 satellite): a bad knob must
        # refuse HERE, not deep in the driver mid-dispatch.
        if self.b_max < 1:
            raise ValueError("b_max must be >= 1")
        if self.linger_s < 0:
            raise ValueError(f"linger_s must be >= 0, got {self.linger_s}")
        if self.threshold <= 0:
            raise ValueError(f"threshold must be > 0, got {self.threshold}")
        if self.max_retries < 0:
            raise ValueError(
                f"max_retries must be >= 0, got {self.max_retries}")
        if self.retry_base_s < 0:
            raise ValueError(
                f"retry_base_s must be >= 0, got {self.retry_base_s}")
        if self.engine not in BATCH_ENGINES:
            raise ValueError(f"unknown serving engine {self.engine!r}; "
                             f"use one of {BATCH_ENGINES}")
        if self.admission is not None \
                and not isinstance(self.admission, AdmissionConfig):
            raise ValueError(
                "admission must be an AdmissionConfig (or None to "
                f"disable admission control), got {self.admission!r}")
        if self.autotune_b_max and self.admission is None:
            raise ValueError(
                "autotune_b_max needs admission control: the tuner "
                "reads the admission SLO and the measured per-class "
                "service curve (serve/admission.py)")
        if self.stream_budget_bytes < 1:
            raise ValueError("stream_budget_bytes must be >= 1, got "
                             f"{self.stream_budget_bytes}")
        # Round up to a ladder rung (full bins then pack with zero
        # padding), capped at the ladder top — loudly: a silently
        # clamped b_max=1000 serving 64-row batches would mislead
        # capacity planning.
        rung = min(batch_pad(self.b_max), BATCH_SIZES[-1])
        if rung != self.b_max:
            warnings.warn(
                f"b_max={self.b_max} is not a BATCH_SIZES rung; "
                f"using {rung} (ladder {BATCH_SIZES})", stacklevel=2)
        self.b_max = rung


@dataclasses.dataclass
class Job:
    job_id: str
    graph: object
    slab_class: tuple
    t_submit: float
    tenant: str = "anon"
    # Absolute deadline on the server clock (None = never sheds).
    t_deadline: float | None = None


@dataclasses.dataclass
class PackedBatch:
    """The handoff unit between the two dispatch stages (ISSUE 14): one
    popped batch after the PACK stage — jobs, trigger provenance, the
    sticky-union bucket geometry it packed against, and the uploaded
    device-ready batch (``prep``, a louvain.batched.PreparedMany; None
    on the injected-runner path, where execute runs the runner over the
    raw graphs).  ``results`` non-None means the pack stage already
    terminated every job (pack-site failure -> isolation) and
    execute_batch passes them through."""

    jobs: list
    key: tuple
    trigger: str
    now: float               # pop-time clock (wait-measurement base)
    n_real: int
    b_pad: int
    waits: list
    shape: object = None     # geometry to record on success (bucketed)
    prep: object = None      # PreparedMany (uploaded device buffers)
    pack_s: float = 0.0      # pack-stage busy seconds (injectable clock)
    results: list | None = None
    # Sub-row merge provenance (ISSUE 20): the SubRowLayout the batch
    # packed under (None = plain batch), and the occupied-row count for
    # the rows_real accounting (a merged batch's b_pad counts ROWS).
    layout: object = None
    merged: bool = False
    rows_real: int = 0


class _ClassBin:
    """One (slab class, accum class) bin: per-tenant FIFO sub-queues
    with a round-robin pop cursor (the fairness unit — each pop takes
    the front job of the front tenant and rotates that tenant to the
    back)."""

    __slots__ = ("tenants", "order")

    def __init__(self):
        self.tenants: dict = {}              # tenant -> deque[Job]
        self.order: collections.deque = collections.deque()

    def push(self, job: Job) -> None:
        q = self.tenants.get(job.tenant)
        if q is None:
            q = self.tenants[job.tenant] = collections.deque()
            self.order.append(job.tenant)
        q.append(job)

    def depth(self) -> int:
        return sum(len(q) for q in self.tenants.values())

    def oldest_t_submit(self) -> float | None:
        """Oldest enqueue time across ALL tenants (the linger clock:
        a firehose tenant cannot hide another tenant's aging job)."""
        heads = [q[0].t_submit for q in self.tenants.values() if q]
        return min(heads) if heads else None

    def pop_rr(self) -> Job | None:
        while self.order:
            t = self.order.popleft()
            q = self.tenants.get(t)
            if not q:
                self.tenants.pop(t, None)
                continue
            job = q.popleft()
            if q:
                self.order.append(t)
            else:
                self.tenants.pop(t, None)
            return job
        return None


# Queue-wait sample window (ISSUE 10): percentiles cover the most
# recent WAIT_WINDOW dispatched jobs, so a long-lived server's latency
# readout tracks CURRENT queue pressure instead of averaging over its
# whole uptime (and the sample memory stays bounded).
WAIT_WINDOW = 4096


def percentile(samples, q: float) -> float:
    """Nearest-rank percentile (q in [0, 100]) over a sequence — the
    stdlib-only serving-latency estimator; 0.0 on no samples."""
    if not samples:
        return 0.0
    s = sorted(samples)
    rank = max(int(len(s) * q / 100.0 + 0.5), 1)
    return float(s[min(rank, len(s)) - 1])


@dataclasses.dataclass
class ServeStats:
    """Aggregate serving counters.  The queue-wait percentiles
    (enqueue -> dispatch, driven by the server's injectable clock)
    price the latency the batching discipline ADDS: a p95 near
    ``linger_s`` means jobs mostly wait out the deadline (rare classes
    / low traffic); a p95 near zero means bins fill and dispatch full
    (the amortization regime).

    Thread-safety (ISSUE 11 satellite): the daemon's dispatcher
    appends ``wait_samples`` while intake threads poll ``to_dict()``
    or the percentile properties — every read snapshots (and every
    write lands) under ``lock`` (an RLock, so ``to_dict`` can read the
    properties it reuses).  Single-threaded callers pay one
    uncontended acquire."""

    # Every counter is guarded by ``lock`` below.  The explicit
    # guarded-by annotations feed graftlint R019 (analysis/lockset.py):
    # inference alone cannot see the discipline from INSIDE this class
    # (the guarded mutations live in LouvainServer/daemon code), so a
    # future ServeStats method mutating a field lock-free would slip
    # through without them.
    jobs_submitted: int = 0   # graftlint: guarded-by=self.lock — ADMITTED jobs (rejections never enqueue)
    jobs_done: int = 0        # graftlint: guarded-by=self.lock
    jobs_failed: int = 0      # graftlint: guarded-by=self.lock
    jobs_rejected: int = 0    # graftlint: guarded-by=self.lock — admission turned the job away at submit
    jobs_shed: int = 0        # graftlint: guarded-by=self.lock — deadline expired before dispatch
    retries: int = 0          # graftlint: guarded-by=self.lock — transient-fault batch retries
    batches: int = 0          # graftlint: guarded-by=self.lock
    rows_real: int = 0        # graftlint: guarded-by=self.lock
    rows_padded: int = 0      # graftlint: guarded-by=self.lock — total batch rows incl. padding
    linger_dispatches: int = 0  # graftlint: guarded-by=self.lock
    # Sub-row occupancy (ISSUE 20).  pack_util counts ROWS, which
    # saturates at 1.0 the moment every row holds one tenant — a merged
    # batch needs the sub-row ledger to report honest occupancy (and
    # can never report > 1.0): graphs_real real graphs over
    # subrow_capacity total sub-row slots (b_pad * n_sub per batch;
    # n_sub == 1 for plain batches, so the two utilizations coincide
    # until merging happens).
    merged_batches: int = 0   # graftlint: guarded-by=self.lock — dispatches that packed sub-rows
    graphs_real: int = 0      # graftlint: guarded-by=self.lock — real graphs across all batches
    subrow_capacity: int = 0  # graftlint: guarded-by=self.lock — total sub-row slots dispatched
    busy_s: float = 0.0       # graftlint: guarded-by=self.lock — wall spent inside the batched driver
    # Pipeline telemetry (ISSUE 14).  inflight: jobs popped from a bin
    # but not yet terminal (packed / in the handoff slot / executing) —
    # the conservation ledger's in-transit column.  pack_s/device_s:
    # cumulative wall of the two dispatch stages on the injectable
    # clock.  overlap_s: pack wall that ran CONCURRENTLY with a device
    # execute window — overlap_frac = overlap_s / device_s is the
    # pipelining win (0 under the serial dispatcher by construction).
    inflight: int = 0         # graftlint: guarded-by=self.lock — popped, not yet terminal
    pack_s: float = 0.0       # graftlint: guarded-by=self.lock — host pack + upload wall
    device_s: float = 0.0     # graftlint: guarded-by=self.lock — execute-stage wall
    overlap_s: float = 0.0    # graftlint: guarded-by=self.lock — pack wall inside execute windows
    pipeline_depth: int = 1   # graftlint: guarded-by=self.lock — 2 under the pipelined dispatcher
    # Overlap bookkeeping: the in-progress pack/execute window starts
    # and the last completed execute window, on the injectable clock.
    # exec_depth makes the execute window an ENVELOPE over concurrent
    # windows (poison isolation can run a nested execute on the packer
    # thread while the executor's own window is open — the envelope
    # [first start, last end] is what "a device execute was in flight"
    # means for the overlap integral).
    pack_since: float | None = None   # graftlint: guarded-by=self.lock
    exec_since: float | None = None   # graftlint: guarded-by=self.lock
    exec_depth: int = 0               # graftlint: guarded-by=self.lock
    last_exec: tuple | None = None    # graftlint: guarded-by=self.lock
    # enqueue->dispatch waits of the last WAIT_WINDOW jobs (seconds).
    wait_samples: collections.deque = dataclasses.field(  # graftlint: guarded-by=self.lock
        default_factory=lambda: collections.deque(maxlen=WAIT_WINDOW))
    # Per-slab-class breakdown of COMPLETED jobs (ISSUE 20): done
    # counts and recent wait samples keyed by slab class, so a skewed
    # mix's bench record can report per-class goodput/wait_p95 without
    # a second bookkeeping path in the load generator.
    done_by_class: dict = dataclasses.field(  # graftlint: guarded-by=self.lock
        default_factory=dict)
    waits_by_class: dict = dataclasses.field(  # graftlint: guarded-by=self.lock
        default_factory=dict)
    # sync.RLock is the serve/ synchronization seam: a plain
    # threading.RLock in production, a scheduler-backed twin under the
    # concheck cooperative scheduler (graftlint tier 4).
    lock: threading.RLock = dataclasses.field(
        default_factory=sync.RLock, repr=False, compare=False)

    @property
    def pack_util(self) -> float:
        """Occupied batch ROWS over padded rows (a merged batch's row
        is occupied when >= 1 sub-row holds a real graph)."""
        with self.lock:
            return self.rows_real / max(self.rows_padded, 1)

    @property
    def subrow_util(self) -> float:
        """Real graphs over total SUB-row capacity — the honest
        occupancy once sub-row merging is on (ISSUE 20)."""
        with self.lock:
            return self.graphs_real / max(self.subrow_capacity, 1)

    @property
    def overlap_frac(self) -> float:
        """Fraction of device-execute wall during which a host pack was
        concurrently in flight (the measured pipelining win)."""
        with self.lock:
            if self.device_s <= 0:
                return 0.0
            return min(self.overlap_s / self.device_s, 1.0)

    # -- pipeline-stage windows (ISSUE 14) ----------------------------------
    # The packer/executor stages report their attempt windows here; the
    # overlap integral is accumulated on the PACK side only (each pack
    # window is clipped against the running or last-completed execute
    # window), so concurrent reporting never double-counts.  All on the
    # server's injectable clock.

    def pack_begins(self, t0: float) -> None:
        with self.lock:
            self.pack_since = t0

    def pack_ends(self, t0: float, t1: float) -> None:
        with self.lock:
            self.pack_s += t1 - t0
            self.pack_since = None
            if self.exec_since is not None:
                ov = t1 - max(t0, self.exec_since)
            elif self.last_exec is not None:
                s, e = self.last_exec
                ov = min(t1, e) - max(t0, s)
            else:
                ov = 0.0
            if ov > 0.0:
                self.overlap_s += ov

    def exec_begins(self, t0: float) -> None:
        with self.lock:
            self.exec_depth += 1
            if self.exec_depth == 1:
                self.exec_since = t0

    def exec_ends(self, t0: float, t1: float) -> None:
        with self.lock:
            self.device_s += t1 - t0
            self.exec_depth -= 1
            if self.exec_depth <= 0:
                self.exec_depth = 0
                self.last_exec = (self.exec_since
                                  if self.exec_since is not None else t0,
                                  t1)
                self.exec_since = None

    @property
    def jobs_per_s(self) -> float:
        with self.lock:
            return self.jobs_done / max(self.busy_s, 1e-9)

    @property
    def wait_p50_s(self) -> float:
        with self.lock:
            samples = list(self.wait_samples)
        return percentile(samples, 50.0)

    @property
    def wait_p95_s(self) -> float:
        with self.lock:
            samples = list(self.wait_samples)
        return percentile(samples, 95.0)

    def per_class(self) -> dict:
        """``{slab_class: {done, wait_p50_s, wait_p95_s}}`` snapshot —
        the per-class goodput/latency split a skewed-mix bench record
        reports (ISSUE 20)."""
        with self.lock:
            keys = set(self.done_by_class) | set(self.waits_by_class)
            out = {}
            for cls in sorted(keys):
                samples = list(self.waits_by_class.get(cls, ()))
                out[cls] = {
                    "done": self.done_by_class.get(cls, 0),
                    "wait_p50_s": percentile(samples, 50.0),
                    "wait_p95_s": percentile(samples, 95.0),
                }
            return out

    def to_dict(self) -> dict:
        with self.lock:
            samples = list(self.wait_samples)
            out = {
                "jobs_submitted": self.jobs_submitted,
                "jobs_done": self.jobs_done,
                "jobs_failed": self.jobs_failed,
                "jobs_rejected": self.jobs_rejected,
                "jobs_shed": self.jobs_shed,
                "retries": self.retries,
                "batches": self.batches,
                "pack_util": round(self.pack_util, 4),
                "merged_batches": self.merged_batches,
                "subrow_util": round(self.subrow_util, 4),
                "linger_dispatches": self.linger_dispatches,
                "busy_s": round(self.busy_s, 4),
                "jobs_per_s": round(self.jobs_per_s, 2),
                "inflight": self.inflight,
                "pack_s": round(self.pack_s, 4),
                "device_s": round(self.device_s, 4),
                "overlap_frac": round(self.overlap_frac, 4),
                "pipeline_depth": self.pipeline_depth,
            }
        out["wait_p50_ms"] = round(percentile(samples, 50.0) * 1e3, 3)
        out["wait_p95_ms"] = round(percentile(samples, 95.0) * 1e3, 3)
        return out


class StreamPool:
    """Per-tenant resident :class:`~cuvite_tpu.stream.StreamSession`
    registry under an HBM byte budget (ISSUE 17).

    The pool is the serving side of streaming: a tenant's first
    ``delta`` builds a session (full slab upload, via the injectable
    ``factory`` — the pool itself is jax-free, R014); later deltas find
    it resident and pay only the delta.  Residency is LRU under
    ``budget_bytes`` of session :meth:`hbm_bytes`: admitting or growing
    a session evicts least-recently-USED others until the ledger fits
    (the session being touched is never evicted — a tenant cannot be
    evicted by its own request).  One session larger than the whole
    budget is admitted alone (and evicts everyone else): refusing it
    would make the budget a hard per-tenant cap, which is the
    admission controller's job, not the pool's.

    Conservation (the chaos invariant, mirroring job conservation):
    every admitted session is resident or evicted exactly once —
    ``admitted == resident + evicted`` — and ``bytes_resident`` is
    exactly the sum of resident sessions' ledger bytes.  All state
    lives under one ``sync.RLock`` (daemon intake threads race the
    drain path; concheck's ``delta-vs-drain`` scenario drives the
    interleavings).
    """

    def __init__(self, budget_bytes: int, tracer=None, *, factory=None):
        if tracer is None:
            from cuvite_tpu.utils.trace import NullTracer

            tracer = NullTracer()
        self.tracer = tracer
        self.budget_bytes = int(budget_bytes)
        if self.budget_bytes < 1:
            raise ValueError("stream budget must be >= 1 byte")
        self._factory = factory
        self.lock = sync.RLock("stream-pool")
        self._sessions: dict = {}   # graftlint: guarded-by=self.lock — tenant -> StreamSession
        self._order: list = []      # graftlint: guarded-by=self.lock — LRU, oldest first
        self._bytes: dict = {}      # graftlint: guarded-by=self.lock — tenant -> ledger bytes
        self.bytes_resident: int = 0  # graftlint: guarded-by=self.lock
        self.admitted: int = 0      # graftlint: guarded-by=self.lock
        self.evicted: int = 0       # graftlint: guarded-by=self.lock

    def _make_session(self, graph):
        """Build a session OUTSIDE the lock (slab upload is the
        expensive part); jax stays behind the factory seam."""
        if self._factory is not None:
            return self._factory(graph, tracer=self.tracer)
        from cuvite_tpu.stream.session import StreamSession

        return StreamSession.from_graph(graph, tracer=self.tracer)

    def _touch(self, tenant: str) -> None:
        # Callers hold self.lock already; the RLock re-entry keeps the
        # discipline lexical (R019) at zero contention cost.
        with self.lock:
            if tenant in self._order:
                self._order.remove(tenant)
            self._order.append(tenant)

    def _evict_to_fit(self, keep: str) -> None:
        # Caller holds self.lock.  Oldest-first, never ``keep``.
        while self.bytes_resident > self.budget_bytes:
            victim = next((t for t in self._order if t != keep), None)
            if victim is None:
                break
            self._evict_locked(victim, reason="budget")

    def _evict_locked(self, tenant: str, *, reason: str) -> None:
        # Callers hold self.lock already (RLock re-entry, as _touch).
        with self.lock:
            sess = self._sessions.pop(tenant)
            nb = self._bytes.pop(tenant)
            self._order.remove(tenant)
            self.bytes_resident -= nb
            self.evicted += 1
        drop = getattr(sess, "drop", None)
        if drop is not None:
            drop()  # release device buffers eagerly (stubs may omit)
        self.tracer.event("evict", tenant=tenant, bytes=nb,
                          reason=reason,
                          bytes_resident=self.bytes_resident,
                          resident=len(self._sessions))

    def get(self, tenant: str):
        """The tenant's resident session (LRU-touched), or None."""
        with self.lock:
            sess = self._sessions.get(tenant)
            if sess is not None:
                self._touch(tenant)
            return sess

    def admit(self, tenant: str, graph):
        """Build + admit a session for ``tenant`` (replacing any
        resident one), evicting LRU others to fit the budget.  Returns
        the session."""
        sess = self._make_session(graph)
        with self.lock:
            if tenant in self._sessions:
                self._evict_locked(tenant, reason="replace")
            nb = int(sess.hbm_bytes())
            self._sessions[tenant] = sess
            self._bytes[tenant] = nb
            self._order.append(tenant)
            self.bytes_resident += nb
            self.admitted += 1
            self._evict_to_fit(keep=tenant)
        self.tracer.event("stream_admit", tenant=tenant, bytes=nb)
        return sess

    def reledger(self, tenant: str) -> None:
        """Re-read a resident session's :meth:`hbm_bytes` after an op
        that may have grown its slab class (delta spill), then re-run
        eviction.  No-op for unknown tenants (evicted mid-op)."""
        with self.lock:
            sess = self._sessions.get(tenant)
            if sess is None:
                return
            nb = int(sess.hbm_bytes())
            self.bytes_resident += nb - self._bytes[tenant]
            self._bytes[tenant] = nb
            self._evict_to_fit(keep=tenant)

    def evict(self, tenant: str) -> bool:
        """Explicit eviction (daemon shutdown / operator verb)."""
        with self.lock:
            if tenant not in self._sessions:
                return False
            self._evict_locked(tenant, reason="explicit")
            return True

    def clear(self) -> None:
        with self.lock:
            for t in list(self._order):
                self._evict_locked(t, reason="shutdown")

    def conservation(self) -> dict:
        """Session + byte accounting: every admitted session is
        resident or evicted exactly once, and the byte ledger is the
        sum of the residents'."""
        with self.lock:
            s = dict(admitted=self.admitted, evicted=self.evicted,
                     resident=len(self._sessions),
                     bytes_resident=self.bytes_resident)
            s["ok"] = (s["admitted"] == s["resident"] + s["evicted"]
                       and s["bytes_resident"]
                       == sum(self._bytes.values())
                       and set(self._order) == set(self._sessions))
        return s

    def to_dict(self) -> dict:
        with self.lock:
            return {
                "resident": len(self._sessions),
                "admitted": self.admitted,
                "evicted": self.evicted,
                "bytes_resident": self.bytes_resident,
                "budget_bytes": self.budget_bytes,
            }


class LouvainServer:
    """Synchronous serving core: ``submit()`` enqueues, ``step()`` runs
    every due batch and returns finished ``(job_id, LouvainResult)``
    pairs.  The async daemon (serve/daemon.py) wraps this in its
    socket intake + dispatcher thread; keeping the core synchronous
    keeps results deterministic and testable — the queue decides WHAT
    runs together, the batched driver decides how.

    Injectables (all default to the real thing): ``clock``/``sleep``
    (serve/clock.py — tests drive linger deadlines and retry backoff
    without sleeping), ``faults`` (a FaultPlan; empty = no injection),
    ``runner`` (the batch executor, signature of
    ``louvain.batched.cluster_many`` — chaos tests swap in a stub so
    hundreds of conservation-invariant jobs cost milliseconds).
    """

    def __init__(self, config: ServeConfig | None = None, tracer=None,
                 clock=None, *, sleep=None, faults=None, runner=None,
                 stream_factory=None):
        self.config = config or ServeConfig()
        if tracer is None:
            from cuvite_tpu.utils.trace import NullTracer

            tracer = NullTracer()
        self.tracer = tracer
        self.clock = clock if clock is not None else serve_clock.monotonic
        self.sleep = sleep if sleep is not None else serve_clock.sleep
        self.faults = faults if faults is not None else FaultPlan()
        self._runner = runner
        self.stats = ServeStats()
        self.admission = (AdmissionController(self.config.admission)
                          if self.config.admission is not None else None)
        # Measured-service b_max autotuning (ISSUE 14): per-class
        # effective rung in _b_max, retuned after each dispatch from
        # the per-rung service curve; config.b_max stays the cap.
        self.autotuner = (BmaxAutotuner(self.config.admission)
                          if self.config.autotune_b_max else None)
        # Sub-row merge decision inputs (ISSUE 20): a DEDICATED
        # measured-service curve keyed per (bin key | merge key, rung) —
        # separate from the b_max autotuner so merge_packing without
        # autotune_b_max never retunes anything.  None without admission
        # (no SLO/window to size the estimator); the packer then merges
        # on bin overflow only.
        self.merge_tuner = (BmaxAutotuner(self.config.admission)
                            if (self.config.merge_packing
                                and self.config.admission is not None)
                            else None)
        # Slab classes that have COMPLETED at least one batch here —
        # the merge target set: merging aims small jobs at a larger
        # class the server is already running programs for.
        self._served_classes: set = set()  # graftlint: guarded-by=self.stats.lock
        # Tenant slab residency (ISSUE 17): per-tenant resident
        # StreamSessions behind the daemon's `delta` verb, LRU-evicted
        # under the byte budget.  ``stream_factory`` is the chaos seam
        # (stub sessions make the delta-vs-drain scenario cheap).
        self.streams = StreamPool(self.config.stream_budget_bytes,
                                  tracer=self.tracer,
                                  factory=stream_factory)
        # Terminal reports for jobs that never produce a result: jobs
        # whose clustering raised -> (job_id, error string) in
        # ``failures`` (poison isolation, see _dispatch); jobs whose
        # deadline expired before dispatch -> (job_id, late_s) in
        # ``shed``.  The daemon consumes-and-CLEARS both per dispatch
        # tick via consume_terminal() (a long-lived service must not
        # grow them unboundedly); library callers read them after
        # drain().  Under the pipelined dispatcher the packer appends
        # sheds while the executor appends failures, so both lists
        # live under the stats lock.
        self.failures: list = []   # graftlint: guarded-by=self.stats.lock
        self.shed: list = []       # graftlint: guarded-by=self.stats.lock
        self._bins: dict = collections.defaultdict(_ClassBin)
        # Sticky per-slab-class bucket geometry (engine='bucketed'):
        # each dispatch pins the grow-only UNION of every geometry the
        # class has served (core.batch.union_shapes), so per-batch
        # degree-histogram jitter cannot churn compiled phase-0
        # programs — the compile count per class converges (bounded by
        # the class) instead of being one per distinct batch mix.
        # Read by the packer stage, recorded by the executor stage
        # (ISSUE 14) — hence the stats-lock discipline.
        self._shapes: dict = {}    # graftlint: guarded-by=self.stats.lock
        self._b_max: dict = {}     # graftlint: guarded-by=self.stats.lock
        self._ids = itertools.count()

    # -- intake -------------------------------------------------------------

    def submit(self, graph, job_id: str | None = None, *,
               tenant: str = "anon", deadline_s: float | None = None,
               t_submit: float | None = None) -> str:
        """Enqueue one clustering job; returns its id.  Binning is by
        (slab class, accumulator class) — pure host arithmetic, no slab
        is built here.

        ``deadline_s`` (relative to now, on the server clock): the job
        is SHED — never packed — once the deadline passes before
        dispatch.  ``t_submit`` backdates the enqueue timestamp (the
        open-loop load generator stamps scheduled arrival times so
        queue waits are measured from arrival, not from when the
        single-threaded loop got around to submitting).

        Raises :class:`AdmissionReject` (with ``retry_after_s``) when
        admission control is on and the class's projected wait
        breaches the SLO; the job is then terminally REJECTED and
        never enqueued.
        """
        from cuvite_tpu.louvain.batched import accum_class_of

        if job_id is None:
            job_id = f"job-{next(self._ids)}"
        cls = slab_class_of(graph)
        key = (cls, accum_class_of(graph, cls[0]))
        now = self.clock() if t_submit is None else t_submit
        depth = self._bins[key].depth() if key in self._bins else 0
        if self.admission is not None:
            # Under the stats lock: the executor stage observes service
            # times concurrently with intake's projection (ISSUE 14).
            with self.stats.lock:
                retry_after = self.admission.decide(key, depth,
                                                    self.b_max_for(key))
            if retry_after is not None:
                with self.stats.lock:
                    self.stats.jobs_rejected += 1
                self.tracer.event(
                    "reject", job_id=job_id, tenant=tenant,
                    slab_class=list(cls), depth=depth,
                    retry_after_s=round(retry_after, 6))
                raise AdmissionReject(
                    retry_after,
                    f"class {cls} depth {depth} projects past the "
                    f"{self.config.admission.wait_slo_s}s wait SLO")
        try:
            self.faults.check("submit")
        except InjectedFault:
            # An intake fault is a REJECTION seen from the conservation
            # ledger: the job never entered the queue, the caller got
            # an error, and it must not count as submitted.
            with self.stats.lock:
                self.stats.jobs_rejected += 1
            self.tracer.event("reject", job_id=job_id, tenant=tenant,
                              slab_class=list(cls), depth=depth,
                              reason="injected-fault")
            raise
        self._bins[key].push(
            Job(job_id=job_id, graph=graph, slab_class=cls, t_submit=now,
                tenant=tenant,
                t_deadline=(now + deadline_s
                            if deadline_s is not None else None)))
        with self.stats.lock:
            self.stats.jobs_submitted += 1
        self.tracer.event("admit", job_id=job_id, tenant=tenant,
                          slab_class=list(cls), depth=depth + 1)
        return job_id

    def pending(self) -> int:
        return sum(b.depth() for b in self._bins.values())

    def b_max_for(self, key) -> int:
        """The class's EFFECTIVE batch cap: the autotuned rung when the
        tuner has retuned it, else ``config.b_max`` (always <= the
        config cap).  Locked: the executor stage retunes concurrently
        with the packer's due-scan (stats.lock is an RLock, so callers
        already holding it nest cleanly)."""
        with self.stats.lock:
            return self._b_max.get(key, self.config.b_max)

    def autotuned(self) -> dict:
        """{class key: rung} for every class the autotuner has moved
        off the config default (empty without autotune_b_max)."""
        with self.stats.lock:
            return dict(self._b_max)

    def pin_shape(self, slab_class: tuple, shape) -> None:
        """Pre-pin a slab class's bucket geometry (engine='bucketed').
        Benches and the load generator pin the JOB-SET union
        (core.batch.bucket_shape_for) so a warm-up pass covers every
        compiled program the run can touch; the sticky per-dispatch
        union then never grows past it."""
        from cuvite_tpu.core.batch import union_shapes

        with self.stats.lock:
            prev = self._shapes.get(slab_class)
            self._shapes[slab_class] = (shape if prev is None
                                        else union_shapes(prev, shape))

    def consume_terminal(self) -> tuple:
        """Atomically take (and clear) the no-result terminal reports —
        ``(failures, shed)`` — for routing.  The daemon/dispatcher
        calls this per delivery tick so a long-lived service never
        grows the lists unboundedly."""
        with self.stats.lock:
            fails = list(self.failures)
            self.failures.clear()
            sheds = list(self.shed)
            self.shed.clear()
        return fails, sheds

    def conservation(self) -> dict:
        """Terminal accounting — the chaos invariant: every admitted
        job is pending, in flight (popped but not yet terminal — the
        pipelined dispatcher's pack/handoff/execute transit), or
        terminated exactly once (``done + failed + shed + pending +
        inflight == submitted``; rejected jobs are their own terminal
        state and never enqueue)."""
        with self.stats.lock:
            s = dict(submitted=self.stats.jobs_submitted,
                     done=self.stats.jobs_done,
                     failed=self.stats.jobs_failed,
                     shed=self.stats.jobs_shed,
                     rejected=self.stats.jobs_rejected,
                     inflight=self.stats.inflight)
        s["pending"] = self.pending()
        s["ok"] = (s["done"] + s["failed"] + s["shed"] + s["pending"]
                   + s["inflight"] == s["submitted"])
        return s

    # -- dispatch -----------------------------------------------------------

    # -- sub-row merge decision (ISSUE 20) ----------------------------------

    def _merge_obs_key(self, layout) -> tuple:
        """Service-curve key of merged batches at one layout — distinct
        from any bin key, so merged medians never blur plain ones."""
        return ("merge", layout.row_class, layout.n_sub)

    def _merge_target(self, cls: tuple):
        """``(SubRowLayout, row_class)`` packing ``cls`` into the
        SMALLEST larger class this server has already served (its
        programs are warm), or None when no served class is an exact
        pow2 sub-row multiple.  Merging never invents a new class: a
        fresh row class would compile fresh programs mid-serve, the
        trap the sticky-shape machinery exists to avoid."""
        from cuvite_tpu.core.batch import subrow_layout_for

        with self.stats.lock:
            served = sorted(c for c in self._served_classes
                            if c[0] > cls[0])
        for rc in served:
            lay = subrow_layout_for(cls, rc)
            if lay is not None:
                return lay, rc
        return None

    def _merge_plan(self, key, now: float):
        """Merge-vs-linger for one small-class bin: the SubRowLayout to
        pack under, or None to serve the bin plain.

        Merge when either
          * **overflow** — the bin holds more jobs than its class cap
            ``b_max`` (a plain dispatch would leave the excess queued
            behind the cap; sub-rows carry ``b_max * n_sub``), or
          * **measured** — the merge tuner's service medians project
            the packed batch completing before the plain alternative:
            ``est(merged @ rows rung) < remaining linger + est(plain @
            b_max rung)`` — i.e. the packed-batch service beats the
            small class's linger wait.  Cold medians never merge (the
            overflow path is what warms them).

        ds32-scale tenants never reach here: their bins carry a
        non-float32 accum class, refused below (the existing
        ``accum_class_of`` gate), and the row-class re-gate happens at
        pack time (louvain/batched.py::prepare_packed's backstop).

        An INJECTED runner (chaos/concheck seam) still merges: the
        runner receives the popped raw graphs either way, so the whole
        merge-aware queue discipline (overflow pop past b_max,
        conservation, poison isolation of a packed batch) is
        model-checkable without the real packer."""
        if not self.config.merge_packing:
            return None
        cls, acc = key
        if acc != "float32":
            return None
        b = self._bins.get(key)
        depth = b.depth() if b is not None else 0
        if depth < 2:
            return None
        target = self._merge_target(cls)
        if target is None:
            return None
        layout, _row_cls = target
        b_max = self.b_max_for(key)
        if depth > b_max:
            return layout
        if self.merge_tuner is None:
            return None
        n = min(depth, b_max * layout.n_sub)
        rows_rung = batch_pad(-(-n // layout.n_sub))
        with self.stats.lock:
            merged_curve = self.merge_tuner.curve(
                self._merge_obs_key(layout))
            plain_curve = self.merge_tuner.curve(key)
        # Curve lookup rounds UP to the nearest warmed rung: overflow
        # merges only ever warm rows-rungs >= 2 (depth > b_max means
        # ceil(depth / n_sub) rows >= 2 whenever n_sub <= b_max), so an
        # exact-rung lookup would leave small-depth measured merges
        # permanently cold.  A larger rung's median upper-bounds the
        # smaller batch's service — the substitution only ever makes
        # the decision MORE conservative.
        def _at(curve: dict, rung: int):
            if rung in curve:
                return curve[rung]
            ge = [r for r in curve if r >= rung]
            return curve[min(ge)] if ge else None

        est_merged = _at(merged_curve, rows_rung)
        est_plain = _at(plain_curve, batch_pad(min(depth, b_max)))
        if est_merged is None or est_plain is None:
            return None
        oldest = b.oldest_t_submit()
        linger_left = max(
            0.0, self.config.linger_s - (now - (oldest or now)))
        return layout if est_merged < linger_left + est_plain else None

    def _due(self, now: float, force: bool) -> list:
        """Bin keys with a dispatchable batch: full bins always;
        partial bins once their oldest job lingered past the deadline
        (or on ``force``, the drain path); merge-eligible bins as soon
        as the measured medians say packing beats lingering (ISSUE
        20)."""
        due = []
        for key, b in self._bins.items():
            oldest = b.oldest_t_submit()
            if oldest is None:
                continue
            if force or b.depth() >= self.b_max_for(key) \
                    or (now - oldest) >= self.config.linger_s:
                due.append(key)
            elif self.config.merge_packing \
                    and self._merge_plan(key, now) is not None:
                due.append(key)
        return due

    def _shed_job(self, job: Job, now: float) -> None:
        late = now - job.t_deadline
        with self.stats.lock:
            self.stats.jobs_shed += 1
            self.shed.append((job.job_id, late))
        self.tracer.event("shed", job_id=job.job_id, tenant=job.tenant,
                          slab_class=list(job.slab_class),
                          late_s=round(late, 6))

    def _pop_batch(self, b: _ClassBin, key, now: float,
                   cap: int | None = None) -> list:
        """Round-robin pop up to the class's effective ``b_max`` jobs
        (or an explicit ``cap`` — the merge path pops ``b_max * n_sub``,
        ISSUE 20), shedding expired ones BEFORE they can occupy a batch
        row.  Surviving jobs are counted in flight (conservation:
        popped but not yet terminal)."""
        jobs = []
        b_max = self.b_max_for(key) if cap is None else cap
        while len(jobs) < b_max:
            job = b.pop_rr()
            if job is None:
                break
            if job.t_deadline is not None and now > job.t_deadline:
                self._shed_job(job, now)
                continue
            jobs.append(job)
        if jobs:
            with self.stats.lock:
                self.stats.inflight += len(jobs)
        return jobs

    def pop_due(self, now: float | None = None, force: bool = False):
        """Pop ONE due batch — ``(jobs, key, trigger, now)``, or None
        when nothing is due.  The packer stage's intake op: the caller
        must hold the intake lock (the daemon lock) so pops serialize
        against submits; the expensive pack then happens OUTSIDE it.
        Popped jobs are in flight until :meth:`execute_batch` (or the
        failure paths) terminate them."""
        now = self.clock() if now is None else now
        for key in self._due(now, force):
            lay = self._merge_plan(key, now)
            cap = (self.b_max_for(key) * lay.n_sub
                   if lay is not None else None)
            jobs = self._pop_batch(self._bins[key], key, now, cap=cap)
            if not jobs:
                continue  # the whole pop shed
            # Label from the ACTUALLY-PACKED size: a bin that counted
            # as full but shed down to a partial batch is a partial
            # dispatch in the telemetry, not a 'full' one.  A merge pop
            # that shed to one survivor packs plain (a lone job needs
            # no fences).
            if lay is not None and len(jobs) > 1:
                trigger = "merge"
            else:
                trigger = ("full" if len(jobs) >= self.b_max_for(key)
                           else "drain" if force else "linger")
            return jobs, key, trigger, now
        return None

    # -- the two dispatch stages (ISSUE 14) ---------------------------------
    # pack_batch() — host-side batch assembly: shape union, slab
    # stacking, bucket-plan build, device upload ('pack' fault site,
    # with its own bounded transient retry).  execute_batch() — the
    # compiled program + result routing ('dispatch'/'device'/'unpack'
    # sites, retry re-runs the ALREADY-UPLOADED batch bit-identically).
    # The serial path composes them in _dispatch(); the pipelined
    # dispatcher (serve/pipeline.py) runs them on two seam-threads with
    # a depth-1 handoff slot between, so the steady-state batch period
    # is max(pack_s, device_s) instead of their sum.

    def _terminal_failure(self, job: Job, cls, wait, err) -> None:
        """One job fails terminally: ledger + report + event."""
        with self.stats.lock:
            self.stats.jobs_failed += 1
            # A failed job still waited in the queue; its sample
            # belongs in the latency percentiles like any other.
            self.stats.wait_samples.append(wait)
            self.stats.inflight -= 1
            self.failures.append((job.job_id, repr(err)))
        self.tracer.event("tenant_error", job_id=job.job_id,
                          tenant=job.tenant, slab_class=list(cls),
                          error=repr(err))

    def _fail_or_isolate(self, packed, sid, busy, err) -> list:
        """Terminal path of either stage: close the stage span, then
        isolate — a batch whose pack/clustering RAISES must not take
        its batchmates down: the batch splits and each job retries
        alone (a fresh pack+execute per job, in the thread that hit
        the failure); a job that fails alone lands in ``self.failures``
        (never back in the queue — a poison job re-queued would raise
        forever)."""
        jobs, key = packed.jobs, packed.key
        cls, _acc = key
        self.tracer.end_span(sid, wall_s=busy, error=repr(err))
        with self.stats.lock:
            self.stats.busy_s += busy
        if len(jobs) == 1:
            self._terminal_failure(jobs[0], cls, packed.waits[0], err)
            return []
        out = []
        for job in jobs:  # isolate the poison job, save the rest
            out.extend(self._dispatch([job], key, "isolate", packed.now))
        return out

    def pack_batch(self, jobs, key, trigger, now) -> "PackedBatch":
        """The PACK stage: bucket-geometry union, slab stacking + plan
        build + device upload (louvain.batched.pack_many — ledger-
        tracked, jax-free in THIS module), behind the 'pack' fault site
        with bounded transient retry.  Returns a PackedBatch; on a
        terminal pack failure its ``results`` carry the isolation
        outcome and :meth:`execute_batch` passes them through."""
        cls, _acc = key
        # Edgeless jobs are answered inline by the driver and occupy
        # no batch row: the padded shape and the pack accounting follow
        # the rows that actually hit the device.
        n_real = sum(1 for j in jobs if j.graph.num_edges > 0)
        # Sub-row merge (ISSUE 20): a 'merge'-triggered pop packs its
        # jobs as fenced sub-rows of the target row class — IF every
        # job's accumulator stays f32 AT THE ROW CLASS (the padded
        # reduction length grows n_sub-fold; accum_class_of is the
        # existing gate, re-evaluated at the row nv_pad).  A batch any
        # of whose tenants fails the re-gate demotes to a plain pack:
        # refusal means "serve plain", never "fail the job".
        layout = None
        if trigger == "merge" and n_real > 1:
            target = self._merge_target(cls)
            if target is not None:
                from cuvite_tpu.louvain.batched import accum_class_of

                lay = target[0]
                if all(accum_class_of(j.graph, lay.row_class[0])
                       == "float32"
                       for j in jobs if j.graph.num_edges > 0):
                    layout = lay
        rows_real = (-(-n_real // layout.n_sub) if layout is not None
                     else n_real)
        b_pad = batch_pad(rows_real) if n_real else 0
        # Queue-wait latency of THIS batch's jobs (enqueue -> dispatch
        # decision), on the injectable clock: per-batch percentiles ride
        # the pack span; the rolling aggregate feeds the serve summary.
        waits = [max(now - j.t_submit, 0.0) for j in jobs]
        packed = PackedBatch(jobs=jobs, key=key, trigger=trigger, now=now,
                             n_real=n_real, b_pad=b_pad, waits=waits,
                             layout=layout, merged=layout is not None,
                             rows_real=rows_real)
        sid = self.tracer.begin_span(
            "pack", slab_class=list(cls), jobs=len(jobs), b_pad=b_pad,
            trigger=trigger, engine=self.config.engine,
            layout=(layout.n_sub if layout is not None else 1),
            merged=packed.merged,
            tenants=len({j.tenant for j in jobs}),
            wait_p50_s=round(percentile(waits, 50.0), 6),
            wait_p95_s=round(percentile(waits, 95.0), 6))
        # Busy windows run on the INJECTABLE clock (not perf_counter):
        # the admission controller's service-time estimates and the
        # stats' busy_s must be drivable by a fake clock + stub runner,
        # or overload behavior becomes untestable without real sleeps.
        busy = 0.0
        attempt = 0
        while True:
            t0 = self.clock()
            self.stats.pack_begins(t0)
            try:
                self.faults.check("pack")
                if (self.config.engine == "bucketed" and n_real
                        and not packed.merged):
                    from cuvite_tpu.core.batch import (
                        bucket_shape_for,
                        union_shapes,
                    )

                    need = bucket_shape_for(
                        [j.graph for j in jobs if j.graph.num_edges > 0])
                    with self.stats.lock:
                        prev = self._shapes.get(cls)
                    packed.shape = (need if prev is None
                                    else union_shapes(prev, need))
                    # The sticky union is recorded only AFTER the batch
                    # completes (execute_batch): a poison job with an
                    # extreme degree histogram must not inflate the
                    # class's pinned geometry forever when it never
                    # produces a result.
                if self._runner is None and packed.merged:
                    # Merged batch: fenced sub-row pack into the row
                    # class's program.  No bucket-shape union — the
                    # sub-row engine is plan-free; the compile key is
                    # (row class, B, n_sub, engine) only.
                    from cuvite_tpu.louvain.batched import pack_subrow_many

                    packed.prep = pack_subrow_many(
                        [j.graph for j in jobs], packed.layout,
                        b_pad=b_pad or None, mesh=self.config.mesh,
                        tracer=self.tracer)
                elif self._runner is None:
                    from cuvite_tpu.louvain.batched import pack_many

                    packed.prep = pack_many(
                        [j.graph for j in jobs], b_pad=b_pad or None,
                        mesh=self.config.mesh, engine=self.config.engine,
                        bucket_shape=packed.shape, tracer=self.tracer)
            except InjectedFault as e:
                t1 = self.clock()
                busy += t1 - t0
                self.stats.pack_ends(t0, t1)
                if not e.permanent and attempt < self.config.max_retries:
                    attempt += 1
                    backoff = self.config.retry_base_s * (2 ** (attempt - 1))
                    with self.stats.lock:
                        self.stats.retries += 1
                    self.tracer.event(
                        "retry", site=e.site, attempt=attempt,
                        jobs=len(jobs), slab_class=list(cls),
                        backoff_s=round(backoff, 6))
                    self.sleep(backoff)
                    continue
                packed.results = self._fail_or_isolate(packed, sid, busy, e)
                return packed
            except Exception as e:  # noqa: BLE001 — isolation boundary
                t1 = self.clock()
                busy += t1 - t0
                self.stats.pack_ends(t0, t1)
                packed.results = self._fail_or_isolate(packed, sid, busy, e)
                return packed
            t1 = self.clock()
            busy += t1 - t0
            self.stats.pack_ends(t0, t1)
            break
        packed.pack_s = busy
        self.tracer.end_span(sid, wall_s=busy, attempts=attempt + 1)
        return packed

    def _run_batch(self, packed: "PackedBatch"):
        """The driver invocation, behind the 'device' fault site: the
        prepared batch through execute_many, or the injected runner
        (chaos tests) over the raw graphs."""
        self.faults.check("device")
        if self._runner is not None:
            return self._runner(
                [j.graph for j in packed.jobs],
                threshold=self.config.threshold,
                max_phases=self.config.max_phases,
                b_pad=packed.b_pad or None, mesh=self.config.mesh,
                engine=self.config.engine, bucket_shape=packed.shape,
                tracer=self.tracer)
        from cuvite_tpu.louvain.batched import execute_many

        return execute_many(
            packed.prep, threshold=self.config.threshold,
            max_phases=self.config.max_phases, tracer=self.tracer)

    def execute_batch(self, packed: "PackedBatch") -> list:
        """The EXECUTE stage: run the prepared batch's compiled program
        and unpack per-tenant results, with bounded transient-fault
        retry ('dispatch'/'device'/'unpack' sites).  A retry re-runs
        the SAME uploaded batch — execute_prepared restarts from the
        phase-0 device state, bit-identically, with no re-pack."""
        if packed.results is not None:
            return packed.results       # pack stage already terminal
        jobs, key = packed.jobs, packed.key
        cls, _acc = key
        sid = self.tracer.begin_span(
            "execute", slab_class=list(cls), jobs=len(jobs),
            b_pad=packed.b_pad, trigger=packed.trigger,
            engine=self.config.engine)
        busy = 0.0
        attempt = 0
        while True:
            t0 = self.clock()
            self.stats.exec_begins(t0)
            try:
                self.faults.check("dispatch")
                br = self._run_batch(packed)
                self.faults.check("unpack")
            except InjectedFault as e:
                t1 = self.clock()
                busy += t1 - t0
                self.stats.exec_ends(t0, t1)
                if not e.permanent and attempt < self.config.max_retries:
                    attempt += 1
                    backoff = self.config.retry_base_s * (2 ** (attempt - 1))
                    with self.stats.lock:
                        self.stats.retries += 1
                    self.tracer.event(
                        "retry", site=e.site, attempt=attempt,
                        jobs=len(jobs), slab_class=list(cls),
                        backoff_s=round(backoff, 6))
                    self.sleep(backoff)
                    continue
                # Permanent, or transient past the retry budget: the
                # existing poison machinery is the terminal path.  The
                # batch's pack busy is charged too — the pre-split
                # dispatcher accumulated the whole dispatch's busy on
                # failure, and busy_s must not depend on WHICH stage
                # raised.
                return self._fail_or_isolate(packed, sid,
                                             packed.pack_s + busy, e)
            except Exception as e:  # noqa: BLE001 — isolation boundary
                t1 = self.clock()
                busy += t1 - t0
                self.stats.exec_ends(t0, t1)
                return self._fail_or_isolate(packed, sid,
                                             packed.pack_s + busy, e)
            t1 = self.clock()
            busy += t1 - t0
            self.stats.exec_ends(t0, t1)
            break
        self.tracer.end_span(sid, wall_s=busy, phases=br.n_phases,
                             attempts=attempt + 1)
        service_s = packed.pack_s + busy
        with self.stats.lock:
            if packed.shape is not None:
                # UNION with the current sticky state, not an overwrite:
                # under the pipelined dispatcher batch k+1 packs (and
                # reads _shapes) before batch k's execute records, so a
                # plain assignment could drop k's geometry and shrink
                # the grow-only union (churning compiled programs).
                from cuvite_tpu.core.batch import union_shapes

                prev = self._shapes.get(cls)
                self._shapes[cls] = (packed.shape if prev is None
                                     else union_shapes(prev, packed.shape))
            if packed.n_real:
                self.stats.batches += 1
                # rows_real counts OCCUPIED ROWS of the dispatched
                # program (pack_util's numerator); for a merged batch
                # that is ceil(n_real / n_sub), not the job count —
                # graphs_real / subrow_capacity carry the finer
                # sub-row occupancy (subrow_util).
                self.stats.rows_real += (packed.rows_real or packed.n_real)
                self.stats.rows_padded += packed.b_pad
                n_sub = packed.layout.n_sub if packed.merged else 1
                self.stats.graphs_real += packed.n_real
                self.stats.subrow_capacity += packed.b_pad * n_sub
                if packed.merged:
                    self.stats.merged_batches += 1
                else:
                    # Only PLAIN completions certify a class as a merge
                    # target: a merged batch warms the (row, n_sub)
                    # sub-row program, not the row class's own plain
                    # program, and targets must be classes with live
                    # big-tenant traffic.
                    self._served_classes.add(cls)
            self.stats.busy_s += service_s
            if packed.trigger == "linger":
                self.stats.linger_dispatches += 1
            if self.admission is not None and packed.n_real:
                self.admission.observe(key, service_s)
            if self.merge_tuner is not None and packed.n_real:
                okey = (self._merge_obs_key(packed.layout) if packed.merged
                        else key)
                self.merge_tuner.observe(okey, packed.b_pad, service_s)
        if not packed.merged:
            # Merged batches never feed the per-class b_max autotuner:
            # their rung is row-count at the ROW class, not this small
            # class's own batch depth — mixing the two would corrupt
            # the plain-service curve the merge decision compares
            # against.
            self._maybe_retune(key, packed.b_pad, service_s,
                               n_real=packed.n_real)
        out = []
        for job, res, wait in zip(jobs, br.results, packed.waits):
            with self.stats.lock:
                self.stats.jobs_done += 1
                self.stats.wait_samples.append(wait)
                self.stats.done_by_class[cls] = (
                    self.stats.done_by_class.get(cls, 0) + 1)
                self.stats.waits_by_class.setdefault(
                    cls, collections.deque(maxlen=WAIT_WINDOW)).append(wait)
                self.stats.inflight -= 1
            self.tracer.event(
                "tenant_result", job_id=job.job_id, tenant=job.tenant,
                slab_class=list(cls), q=float(res.modularity),
                phases=len(res.phases),
                iterations=int(res.total_iterations),
                communities=int(res.num_communities),
                wait_s=round(wait, 6))
            out.append((job.job_id, res))
        return out

    def _maybe_retune(self, key, b_pad: int, service_s: float, *,
                      n_real: int) -> None:
        """Feed the autotuner one (rung, service) sample and apply its
        pick; an ``autotune`` event fires on EVERY effective-b_max
        change (the operator-visible record of the retune)."""
        if self.autotuner is None or not n_real:
            return
        with self.stats.lock:
            self.autotuner.observe(key, b_pad, service_s)
            new = self.autotuner.pick(key, self.config.b_max)
            cur = self._b_max.get(key, self.config.b_max)
            if new is None or new == cur:
                return
            self._b_max[key] = new
            curve = self.autotuner.curve(key)
        self.tracer.event(
            "autotune", slab_class=list(key[0]), b_max_old=cur,
            b_max_new=new,
            curve={str(r): round(est, 6)
                   for r, est in sorted(curve.items())})

    def _dispatch(self, jobs, key, trigger, now) -> list:
        """The SERIAL dispatch: pack then execute on the calling thread
        (step()/drain() and the per-job isolation splitter).  The
        pipelined dispatcher runs the same two halves on separate
        threads."""
        return self.execute_batch(self.pack_batch(jobs, key, trigger, now))

    def step(self, now: float | None = None, force: bool = False) -> list:
        """Run every due batch; returns [(job_id, LouvainResult), ...]
        in pop order per batch.  One call may run several batches (one
        per due bin); jobs whose clustering raised are reported via
        ``self.failures``, shed jobs via ``self.shed`` — never
        returned."""
        now = self.clock() if now is None else now
        out = []
        for key in self._due(now, force):
            lay = self._merge_plan(key, now)
            cap = (self.b_max_for(key) * lay.n_sub
                   if lay is not None else None)
            jobs = self._pop_batch(self._bins[key], key, now, cap=cap)
            if not jobs:
                continue  # the whole pop shed
            # Label from the ACTUALLY-PACKED size: a bin that counted
            # as full but shed down to a partial batch is a partial
            # dispatch in the telemetry, not a 'full' one; a merge pop
            # shed to one survivor packs plain.
            if lay is not None and len(jobs) > 1:
                trigger = "merge"
            else:
                trigger = ("full" if len(jobs) >= self.b_max_for(key)
                           else "drain" if force else "linger")
            out.extend(self._dispatch(jobs, key, trigger, now))
        return out

    def drain(self) -> list:
        """Flush every queued job regardless of linger/fill state
        (expired jobs still shed rather than pack).  Emits a ``drain``
        span so a service shutdown is visible in the trace."""
        sid = self.tracer.begin_span("drain", pending=self.pending())
        out = []
        while self.pending():
            out.extend(self.step(force=True))
        self.tracer.end_span(sid, done=len(out))
        return out
