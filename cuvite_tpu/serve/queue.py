"""Slab-class batching queue: the serving layer over the batched driver.

Queue discipline (ISSUE 9).  Jobs bin by (slab class, accumulator
class) — the pow2 ``(nv_pad, ne_pad)`` shape their graph canonicalizes
to plus its solo in-loop accumulator tag — because only same-class
slabs can stack into one compiled program, and a batch mixing a
ds32-scale tenant with f32 ones would silently change the f32 rows'
results vs their solo runs (louvain/batched.py::accum_class_of).  A
bin dispatches when either

  * it holds ``b_max`` jobs (a full batch), or
  * its OLDEST job has waited ``linger_s`` (the latency bound: a lone
    tenant of a rare class must not wait for batch-mates that never
    come).

Dispatch packs up to ``b_max`` jobs, pads the batch axis to the
``core.batch.BATCH_SIZES`` rung (so the compile cache sees a bounded
set of ``(class, B)`` keys), runs ``louvain.batched.run_batched``, and
unpacks per-tenant results in submission order.  Padding rows are the
pack tax: ``pack_util`` (real rows / padded rows) is the serving
metric that prices it, and it rides the bench record's ``batch`` block.

This module deliberately contains NO jax calls: the compiled program
lives at module scope in louvain/batched.py, device placement happens
once per packed batch inside the driver.  graftlint R014 enforces the
corresponding trap (jit/vmap construction or per-job device_put inside
a serve/ queue loop — the compile-per-job and upload-per-job mistakes
that would silently erase the batching win).

Observability: every dispatch opens a ``pack`` span (class, jobs, B,
linger-triggered or full) and emits one ``tenant_result`` event per
job; OBSERVABILITY.md documents the fields.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import time

from cuvite_tpu.core.batch import (
    BATCH_ENGINES,
    BATCH_SIZES,
    batch_pad,
    slab_class_of,
)
from cuvite_tpu.core.types import TERMINATION_PHASE_COUNT


@dataclasses.dataclass
class ServeConfig:
    """Queue knobs.  ``b_max`` should be a BATCH_SIZES rung (it is
    clamped to one): it caps both batch latency amortization and the
    compile-cache footprint per class.  ``linger_s`` bounds the extra
    latency batching may add to any single job.

    ``engine`` (ISSUE 10) selects the batched driver's per-phase
    engine: ``'bucketed'`` (the default — phase 0 through the vmapped
    sort-free bucketed sweep over pack-time plans, coarse phases fused
    at the serving-coarse class; the configuration every per-graph AND
    batched benchmark shows is the fast one) or ``'fused'`` (PR 9's
    all-phases sort-formulation loop).  Engine choice never changes
    results — per-tenant labels/Q are bit-identical across engines."""

    b_max: int = 64
    linger_s: float = 0.05
    threshold: float = 1.0e-6
    max_phases: int = TERMINATION_PHASE_COUNT
    mesh: object = "auto"   # forwarded to run_batched
    engine: str = "bucketed"

    def __post_init__(self) -> None:
        if self.b_max < 1:
            raise ValueError("b_max must be >= 1")
        if self.engine not in BATCH_ENGINES:
            raise ValueError(f"unknown serving engine {self.engine!r}; "
                             f"use one of {BATCH_ENGINES}")
        # Round up to a ladder rung (full bins then pack with zero
        # padding), capped at the ladder top.
        self.b_max = min(batch_pad(self.b_max), BATCH_SIZES[-1])


@dataclasses.dataclass
class Job:
    job_id: str
    graph: object
    slab_class: tuple
    t_submit: float


# Queue-wait sample window (ISSUE 10): percentiles cover the most
# recent WAIT_WINDOW dispatched jobs, so a long-lived server's latency
# readout tracks CURRENT queue pressure instead of averaging over its
# whole uptime (and the sample memory stays bounded).
WAIT_WINDOW = 4096


def percentile(samples, q: float) -> float:
    """Nearest-rank percentile (q in [0, 100]) over a sequence — the
    stdlib-only serving-latency estimator; 0.0 on no samples."""
    if not samples:
        return 0.0
    s = sorted(samples)
    rank = max(int(len(s) * q / 100.0 + 0.5), 1)
    return float(s[min(rank, len(s)) - 1])


@dataclasses.dataclass
class ServeStats:
    """Aggregate serving counters (monotone; read any time).  The
    queue-wait percentiles (enqueue -> dispatch, driven by the server's
    injectable clock) price the latency the batching discipline ADDS:
    a p95 near ``linger_s`` means jobs mostly wait out the deadline
    (rare classes / low traffic); a p95 near zero means bins fill and
    dispatch full (the amortization regime)."""

    jobs_submitted: int = 0
    jobs_done: int = 0
    jobs_failed: int = 0
    batches: int = 0
    rows_real: int = 0
    rows_padded: int = 0     # total batch rows incl. padding
    linger_dispatches: int = 0
    busy_s: float = 0.0      # wall spent inside the batched driver
    # enqueue->dispatch waits of the last WAIT_WINDOW jobs (seconds).
    wait_samples: collections.deque = dataclasses.field(
        default_factory=lambda: collections.deque(maxlen=WAIT_WINDOW))

    @property
    def pack_util(self) -> float:
        return self.rows_real / max(self.rows_padded, 1)

    @property
    def jobs_per_s(self) -> float:
        return self.jobs_done / max(self.busy_s, 1e-9)

    @property
    def wait_p50_s(self) -> float:
        return percentile(self.wait_samples, 50.0)

    @property
    def wait_p95_s(self) -> float:
        return percentile(self.wait_samples, 95.0)

    def to_dict(self) -> dict:
        return {
            "jobs_submitted": self.jobs_submitted,
            "jobs_done": self.jobs_done,
            "jobs_failed": self.jobs_failed,
            "batches": self.batches,
            "pack_util": round(self.pack_util, 4),
            "linger_dispatches": self.linger_dispatches,
            "busy_s": round(self.busy_s, 4),
            "jobs_per_s": round(self.jobs_per_s, 2),
            "wait_p50_ms": round(self.wait_p50_s * 1e3, 3),
            "wait_p95_ms": round(self.wait_p95_s * 1e3, 3),
        }


class LouvainServer:
    """Synchronous serving core: ``submit()`` enqueues, ``step()`` runs
    every due batch and returns finished ``(job_id, LouvainResult)``
    pairs.  A daemon wraps this in its arrival loop (serve/__main__.py);
    keeping the core synchronous keeps results deterministic and
    testable — the queue decides WHAT runs together, the batched driver
    decides how.

    ``clock`` is injectable (tests drive linger deadlines without
    sleeping).
    """

    def __init__(self, config: ServeConfig | None = None, tracer=None,
                 clock=time.monotonic):
        self.config = config or ServeConfig()
        if tracer is None:
            from cuvite_tpu.utils.trace import NullTracer

            tracer = NullTracer()
        self.tracer = tracer
        self.clock = clock
        self.stats = ServeStats()
        # Jobs whose clustering raised: (job_id, error string).  They
        # are reported here instead of poisoning their batch — see
        # _dispatch's isolation retry.
        self.failures: list = []
        self._bins: dict = collections.defaultdict(collections.deque)
        # Sticky per-slab-class bucket geometry (engine='bucketed'):
        # each dispatch pins the grow-only UNION of every geometry the
        # class has served (core.batch.union_shapes), so per-batch
        # degree-histogram jitter cannot churn compiled phase-0
        # programs — the compile count per class converges (bounded by
        # the class) instead of being one per distinct batch mix.
        self._shapes: dict = {}
        self._ids = itertools.count()

    # -- intake -------------------------------------------------------------

    def submit(self, graph, job_id: str | None = None) -> str:
        """Enqueue one clustering job; returns its id.  Binning is by
        (slab class, accumulator class) — pure host arithmetic, no slab
        is built here."""
        from cuvite_tpu.louvain.batched import accum_class_of

        if job_id is None:
            job_id = f"job-{next(self._ids)}"
        cls = slab_class_of(graph)
        self._bins[(cls, accum_class_of(graph, cls[0]))].append(
            Job(job_id=job_id, graph=graph, slab_class=cls,
                t_submit=self.clock()))
        self.stats.jobs_submitted += 1
        return job_id

    def pending(self) -> int:
        return sum(len(q) for q in self._bins.values())

    # -- dispatch -----------------------------------------------------------

    def _due(self, now: float, force: bool) -> list:
        """Classes with a dispatchable batch: full bins always; partial
        bins once their oldest job lingered past the deadline (or on
        ``force``, the drain path)."""
        due = []
        for cls, q in self._bins.items():
            if not q:
                continue
            if force or len(q) >= self.config.b_max \
                    or (now - q[0].t_submit) >= self.config.linger_s:
                due.append(cls)
        return due

    def _dispatch(self, jobs, cls, trigger, now) -> list:
        """Run one packed batch and unpack per-tenant results.  A batch
        whose clustering RAISES must not take its batchmates down: the
        batch splits and each job retries alone; a job that fails alone
        lands in ``self.failures`` (never back in the queue — a poison
        job re-queued would raise forever)."""
        from cuvite_tpu.louvain.batched import cluster_many

        # Edgeless jobs are answered inline by cluster_many and occupy
        # no batch row: the padded shape and the pack accounting follow
        # the rows that actually hit the device.
        n_real = sum(1 for j in jobs if j.graph.num_edges > 0)
        b_pad = batch_pad(n_real) if n_real else 0
        shape = None
        if self.config.engine == "bucketed" and n_real:
            from cuvite_tpu.core.batch import bucket_shape_for, union_shapes

            need = bucket_shape_for(
                [j.graph for j in jobs if j.graph.num_edges > 0])
            prev = self._shapes.get(cls)
            shape = need if prev is None else union_shapes(prev, need)
            # The sticky union is recorded only AFTER the batch
            # completes (below): a poison job with an extreme degree
            # histogram must not inflate the class's pinned geometry
            # forever when it never produces a result.
        # Queue-wait latency of THIS batch's jobs (enqueue -> dispatch
        # decision), on the injectable clock: per-batch percentiles ride
        # the pack span; the rolling aggregate feeds the serve summary.
        waits = [max(now - j.t_submit, 0.0) for j in jobs]
        sid = self.tracer.begin_span(
            "pack", slab_class=list(cls), jobs=len(jobs), b_pad=b_pad,
            trigger=trigger, engine=self.config.engine,
            wait_p50_s=round(percentile(waits, 50.0), 6),
            wait_p95_s=round(percentile(waits, 95.0), 6))
        t0 = time.perf_counter()
        try:
            br = cluster_many(
                [j.graph for j in jobs],
                threshold=self.config.threshold,
                max_phases=self.config.max_phases,
                b_pad=b_pad or None, mesh=self.config.mesh,
                engine=self.config.engine, bucket_shape=shape,
                tracer=self.tracer)
        except Exception as e:  # noqa: BLE001 — isolation boundary
            busy = time.perf_counter() - t0
            self.tracer.end_span(sid, wall_s=busy, error=repr(e))
            self.stats.busy_s += busy
            if len(jobs) == 1:
                job = jobs[0]
                self.stats.jobs_failed += 1
                # A failed job still waited in the queue; its sample
                # belongs in the latency percentiles like any other.
                self.stats.wait_samples.append(waits[0])
                self.failures.append((job.job_id, repr(e)))
                self.tracer.event("tenant_error", job_id=job.job_id,
                                  slab_class=list(cls), error=repr(e))
                return []
            out = []
            for job in jobs:  # isolate the poison job, save the rest
                out.extend(self._dispatch([job], cls, "isolate", now))
            return out
        busy = time.perf_counter() - t0
        self.tracer.end_span(sid, wall_s=busy, phases=br.n_phases)
        if shape is not None:
            self._shapes[cls] = shape
        if n_real:
            self.stats.batches += 1
            self.stats.rows_real += n_real
            self.stats.rows_padded += b_pad
        self.stats.busy_s += busy
        if trigger == "linger":
            self.stats.linger_dispatches += 1
        out = []
        for job, res, wait in zip(jobs, br.results, waits):
            self.stats.jobs_done += 1
            self.stats.wait_samples.append(wait)
            self.tracer.event(
                "tenant_result", job_id=job.job_id,
                slab_class=list(cls), q=float(res.modularity),
                phases=len(res.phases),
                iterations=int(res.total_iterations),
                communities=int(res.num_communities),
                wait_s=round(wait, 6))
            out.append((job.job_id, res))
        return out

    def step(self, now: float | None = None, force: bool = False) -> list:
        """Run every due batch; returns [(job_id, LouvainResult), ...]
        in submission order per batch.  One call may run several
        batches (one per due bin); jobs whose clustering raised are
        reported via ``self.failures``, not returned."""
        now = self.clock() if now is None else now
        out = []
        for key in self._due(now, force):
            cls, _acc = key
            q = self._bins[key]
            jobs = [q.popleft() for _ in range(min(len(q),
                                                   self.config.b_max))]
            full = len(jobs) >= self.config.b_max
            trigger = "full" if full else "drain" if force else "linger"
            out.extend(self._dispatch(jobs, cls, trigger, now))
        return out

    def drain(self) -> list:
        """Flush every queued job regardless of linger/fill state."""
        out = []
        while self.pending():
            out.extend(self.step(force=True))
        return out
