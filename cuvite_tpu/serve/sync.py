"""Synchronization seam for ``serve/``: production threading, checkable
under a deterministic cooperative scheduler (graftlint tier 4, concheck).

Every lock, event, and thread the serving daemon creates comes from the
factory functions in this module.  In production they return the plain
``threading`` primitives — zero wrappers, zero per-acquire overhead.
Inside an activated :class:`Scheduler` (``with activated(sched): ...``)
they return scheduler-backed twins instead, and the daemon's threads run
under a **cooperative, serialized, seeded** schedule:

  * exactly ONE managed thread executes at a time; control changes hands
    only at *schedule points* — lock acquire/release, event
    set/clear/wait/is_set, condition wait/notify, thread start/join,
    injectable-clock sleeps, and the annotated shared-field accesses the
    concheck instrumentation reports (analysis/concheck.py);
  * the next thread is picked by a seeded strategy — a uniform
    **random walk** or **PCT**-style bounded-preemption priorities
    (Burckhardt et al., ASPLOS'10) — so every failing schedule is
    REPLAYABLE from its ``(strategy, seed)`` pair alone;
  * time is virtual: ``Scheduler.clock``/``Scheduler.sleep`` plug into
    the serve layer's injectable clock seam (serve/clock.py), timed
    waits park the thread until either the wake condition or a virtual
    deadline, and when no thread is runnable the scheduler advances
    ``now`` to the earliest deadline — a full daemon drain with retry
    backoff explores in milliseconds, sleeping zero real seconds;
  * the scheduler maintains per-thread **vector clocks** with
    happens-before edges from lock release→acquire, event set→observed
    wait, condition notify→wakeup, and thread start/join — the
    happens-before order the race detector (analysis/concheck.py)
    judges accesses against;
  * when no thread is runnable and none holds a timeout, that is a
    **deadlock**: recorded with every blocked thread's wait reason and
    stack, then the schedule is aborted (threads unwind via a
    BaseException so ``except Exception`` handlers in daemon code
    cannot swallow the teardown).

The scheduler itself uses real ``threading`` primitives for the baton
hand-off (one Event per managed thread + one coordinator Condition);
nothing here reads the wall clock (graftlint R016) and nothing sleeps.
"""

from __future__ import annotations

import random
import sys
import threading as _threading
import traceback

# The active scheduler.  Factories consult it at CONSTRUCTION time, so
# objects built inside ``with activated(sched)`` are scheduler-backed
# and everything built outside (production) is plain threading.
_ACTIVE: "Scheduler | None" = None


class activated:
    """Context manager installing ``sched`` as the active scheduler for
    primitive construction (and clearing it on exit, exception-safe)."""

    def __init__(self, sched: "Scheduler"):
        self.sched = sched

    def __enter__(self) -> "Scheduler":
        global _ACTIVE
        if _ACTIVE is not None:
            raise RuntimeError("a Scheduler is already active")
        _ACTIVE = self.sched
        return self.sched

    def __exit__(self, *exc) -> None:
        global _ACTIVE
        _ACTIVE = None


def active_scheduler() -> "Scheduler | None":
    return _ACTIVE


def Lock(name: str | None = None):
    """A mutex: ``threading.Lock`` in production, a scheduler-backed
    twin under an activated checker."""
    if _ACTIVE is None:
        return _threading.Lock()
    return _SchedLock(_ACTIVE, name=name, reentrant=False)


def RLock(name: str | None = None):
    if _ACTIVE is None:
        return _threading.RLock()
    return _SchedLock(_ACTIVE, name=name, reentrant=True)


def Event(name: str | None = None):
    if _ACTIVE is None:
        return _threading.Event()
    return _SchedEvent(_ACTIVE, name=name)


def Condition(lock=None, name: str | None = None):
    if _ACTIVE is None:
        return _threading.Condition(lock)
    return _SchedCondition(_ACTIVE, lock, name=name)


def Thread(*, target, name: str | None = None, args=(), kwargs=None,
           daemon: bool = True):
    """A thread handle: real ``threading.Thread`` in production, a
    scheduler-managed thread under the checker (``start()`` registers
    it; it runs only when the schedule hands it the baton)."""
    if _ACTIVE is None:
        return _threading.Thread(target=target, name=name, args=args,
                                 kwargs=kwargs or {}, daemon=daemon)
    return _ACTIVE.thread(target=target, name=name, args=args,
                          kwargs=kwargs or {})


class SchedulerAbort(BaseException):
    """Unwinds a managed thread when the schedule is torn down
    (deadlock, step budget, explicit abort).  BaseException on purpose:
    daemon code's ``except Exception`` isolation boundaries must not
    swallow the teardown."""


_NEW, _READY, _RUNNING, _BLOCKED, _DONE = (
    "new", "ready", "running", "blocked", "done")


def _vc_join(dst: dict, src: dict) -> None:
    # In-place join IS the contract: dst is the thread's own vector
    # clock (a dict, not a shared buffer — R005's aliased-array hazard
    # does not apply).
    for k, v in src.items():
        if dst.get(k, 0) < v:
            dst[k] = v  # graftlint: disable=R005


class _SchedThread:
    """One managed thread: a real OS thread gated by a personal baton
    event; carries the vector clock and the held-lock list."""

    def __init__(self, sched: "Scheduler", target, name, args, kwargs):
        self.sched = sched
        self.target = target
        self.args = args
        self.kwargs = kwargs
        self.idx = len(sched.threads)
        self.name = name or f"t{self.idx}"
        self.vc: dict = {self.idx: 1}
        self.state = _NEW
        self.turn = _threading.Event()
        self.locks: list = []          # acquisition order, one per hold
        self.wait_reason: tuple | None = None
        self.deadline: float | None = None
        self.timed_out = False
        self.abort = False
        self.pending_op: tuple = ("start", "")
        self.os_thread = _threading.Thread(
            target=self._run, name=f"sched-{self.name}", daemon=True)
        sched.threads.append(self)

    # threading.Thread API surface the daemon uses --------------------------

    def start(self) -> None:
        if self.state != _NEW:
            raise RuntimeError(f"thread {self.name} started twice")
        self.state = _READY
        self.os_thread.start()

    def is_alive(self) -> bool:
        return self.state not in (_NEW, _DONE)

    def join(self, timeout: float | None = None) -> None:
        self.sched.thread_join(self, timeout)

    def tick(self) -> None:
        self.vc[self.idx] = self.vc.get(self.idx, 0) + 1

    def _run(self) -> None:
        s = self.sched
        s.register_ident(self)
        self.turn.wait()
        self.turn.clear()
        try:
            if not self.abort:
                self.target(*self.args, **self.kwargs)
        except SchedulerAbort:
            pass
        except BaseException as e:  # noqa: BLE001 — schedule failure report
            s.record_failure(
                "thread-exception",
                f"thread {self.name!r} died: {e!r}",
                stack=traceback.format_exc(limit=16))
        finally:
            s.thread_finished(self)


class _SchedLock:
    """Scheduler-backed Lock/RLock.  Mutual exclusion is modeled (only
    one thread runs anyway); the point is the blocking semantics, the
    happens-before edges, and the schedule points."""

    def __init__(self, sched: "Scheduler", *, name: str | None,
                 reentrant: bool):
        self.sched = sched
        self.name = name or f"lock-{sched.next_obj_id()}"
        self.reentrant = reentrant
        self.owner: _SchedThread | None = None
        self.count = 0
        self.vc: dict = {}

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        return self.sched.lock_acquire(self, blocking=blocking,
                                       timeout=timeout)

    def release(self) -> None:
        self.sched.lock_release(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class _SchedEvent:
    def __init__(self, sched: "Scheduler", *, name: str | None):
        self.sched = sched
        self.name = name or f"event-{sched.next_obj_id()}"
        self.flag = False
        self.vc: dict = {}

    def is_set(self) -> bool:
        return self.sched.event_is_set(self)

    def set(self) -> None:
        self.sched.event_set(self)

    def clear(self) -> None:
        self.sched.event_clear(self)

    def wait(self, timeout: float | None = None) -> bool:
        return self.sched.event_wait(self, timeout)


class _SchedCondition:
    """Condition variable over a (scheduler-backed) lock.  Not used by
    the daemon today, but the shim must cover the full primitive set so
    a future serve/ refactor stays checkable without touching this
    module."""

    def __init__(self, sched: "Scheduler", lock, *, name: str | None):
        self.sched = sched
        self.lock = lock if lock is not None else _SchedLock(
            sched, name=None, reentrant=True)
        self.name = name or f"cond-{sched.next_obj_id()}"
        self.vc: dict = {}
        self.waiting: list = []

    def __enter__(self):
        self.lock.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.lock.release()

    def wait(self, timeout: float | None = None) -> bool:
        return self.sched.cond_wait(self, timeout)

    def notify(self, n: int = 1) -> None:
        self.sched.cond_notify(self, n)

    def notify_all(self) -> None:
        self.sched.cond_notify(self, len(self.waiting) or 1)


# ---------------------------------------------------------------------------
# Strategies


class RandomWalkStrategy:
    """Uniform seeded choice among runnable threads at every schedule
    point — the breadth workhorse: cheap, unbiased, and every run is a
    distinct sample of the interleaving space."""

    name = "random"

    def __init__(self, seed: int):
        # Seed via a STRING: str seeding is hash-randomization-free, so
        # a failing schedule's seed replays identically across
        # processes (tuples would hash per-process).
        self.rng = random.Random(f"random-walk:{seed}")

    def pick(self, ready: list, step: int):
        return ready[self.rng.randrange(len(ready))]


class PCTStrategy:
    """PCT-style bounded-preemption priorities: each thread gets a
    random priority at registration; the highest-priority runnable
    thread runs until one of ``depth - 1`` pre-sampled change points,
    where the current leader is demoted below everyone.  Finds bugs of
    preemption depth < ``depth`` with known probability — the
    depth-first complement to the random walk."""

    name = "pct"

    def __init__(self, seed: int, depth: int = 3,
                 est_steps: int = 2000):
        self.rng = random.Random(f"pct:{seed}")
        self.depth = depth
        self.change_points = sorted(
            self.rng.randrange(1, est_steps) for _ in range(depth - 1))
        self.prio: dict = {}
        self._next_low = 0.0

    def _priority(self, t) -> float:
        if t.idx not in self.prio:
            self.prio[t.idx] = self.rng.random() + 1.0
        return self.prio[t.idx]

    def pick(self, ready: list, step: int):
        top = max(ready, key=self._priority)
        if self.change_points and step >= self.change_points[0]:
            self.change_points.pop(0)
            self._next_low -= 1.0
            self.prio[top.idx] = self._next_low   # demote below everyone
            top = max(ready, key=self._priority)
        return top


STRATEGIES = {"random": RandomWalkStrategy, "pct": PCTStrategy}


# ---------------------------------------------------------------------------
# The scheduler


class Scheduler:
    """Deterministic cooperative scheduler (see module docstring).

    ``detector`` is duck-typed (analysis/concheck.py's RaceDetector):
    ``record(key, kind, thread, held_lock_names, declared)`` is called
    at every annotated shared-field access; serve/ itself never imports
    the analysis package.
    """

    def __init__(self, *, seed: int = 0, strategy: str = "random",
                 max_steps: int = 50000, now: float = 1000.0,
                 detector=None, pct_depth: int = 3):
        if strategy not in STRATEGIES:
            raise ValueError(f"unknown strategy {strategy!r}; "
                             f"use one of {sorted(STRATEGIES)}")
        self.seed = seed
        self.strategy_name = strategy
        self.strategy = (PCTStrategy(seed, depth=pct_depth)
                         if strategy == "pct"
                         else RandomWalkStrategy(seed))
        self.max_steps = max_steps
        self.now = now
        self.detector = detector
        self.threads: list = []
        self.failures: list = []
        self.trace: list = []          # (thread name, op, detail)
        self.steps = 0
        self.running = False
        self.aborting = False
        self._mon = _threading.Condition()
        self._by_ident: dict = {}
        self._obj_ids = 0

    # -- plumbing -----------------------------------------------------------

    def next_obj_id(self) -> int:
        self._obj_ids += 1
        return self._obj_ids

    def register_ident(self, t: _SchedThread) -> None:
        self._by_ident[_threading.get_ident()] = t

    def current(self) -> _SchedThread | None:
        return self._by_ident.get(_threading.get_ident())

    def thread(self, *, target, name=None, args=(), kwargs=None):
        return _SchedThread(self, target, name, args, kwargs or {})

    def spawn(self, target, *, name=None, args=()) -> _SchedThread:
        t = self.thread(target=target, name=name, args=args)
        t.start()
        return t

    # The serve-layer injectable clock/sleep pair.
    def clock(self) -> float:
        return self.now

    def sleep(self, seconds: float) -> None:
        t = self.current()
        if t is None or not self.running:
            return                      # unmanaged caller: virtual no-op
        self._yield(t, ("sleep", f"{seconds:.6f}"))
        if seconds > 0:
            self._park(t, ("sleep", None), self.now + seconds)

    def held_lock_names(self) -> list:
        t = self.current()
        if t is None:
            return []
        return [lk.name for lk in t.locks]

    def record_failure(self, kind: str, message: str, *,
                       stack: str | None = None) -> None:
        self.failures.append({
            "kind": kind, "message": message, "step": self.steps,
            "stack": stack,
        })

    def signature(self) -> int:
        """Stable hash of the explored interleaving (choice sequence):
        two runs with the same signature explored the same schedule."""
        import zlib

        payload = "\x1e".join(
            f"{name}\x1f{op}\x1f{detail}" for name, op, detail in self.trace)
        return zlib.crc32(payload.encode())

    # -- thread-side transitions -------------------------------------------

    def _yield(self, t: _SchedThread, op: tuple) -> None:
        """Give the baton back; returns when the strategy re-picks this
        thread.  EVERY schedule point funnels through here."""
        if t.abort:
            raise SchedulerAbort()
        t.pending_op = op
        with self._mon:
            t.state = _READY
            self._mon.notify_all()
        t.turn.wait()
        t.turn.clear()
        if t.abort:
            raise SchedulerAbort()

    def _park(self, t: _SchedThread, reason: tuple,
              deadline: float | None = None) -> bool:
        """Block until another thread (or a virtual timeout) makes this
        thread runnable again and the strategy schedules it; True when
        the wake came from the virtual deadline firing."""
        if t.abort:
            raise SchedulerAbort()
        with self._mon:
            t.state = _BLOCKED
            t.wait_reason = reason
            t.deadline = deadline
            self._mon.notify_all()
        t.turn.wait()
        t.turn.clear()
        t.wait_reason = None
        t.deadline = None
        fired = t.timed_out
        t.timed_out = False
        if t.abort:
            raise SchedulerAbort()
        return fired

    def _wake(self, pred) -> None:
        """Mark blocked threads matching ``pred`` runnable (they still
        run only when scheduled)."""
        for w in self.threads:
            if w.state == _BLOCKED and w.wait_reason is not None \
                    and pred(w):
                w.timed_out = False
                w.state = _READY

    def thread_finished(self, t: _SchedThread) -> None:
        # A thread dying while holding locks would wedge every waiter:
        # force-release (and report — an orderly thread never does this).
        with self._mon:
            for lk in list(t.locks):
                if not self.aborting:
                    self.record_failure(
                        "lock-leak",
                        f"thread {t.name!r} exited holding {lk.name}")
                lk.count = 0
                lk.owner = None
                lk.vc = dict(t.vc)
                self._wake(lambda w, lk=lk: w.wait_reason == ("lock", lk))
            t.locks.clear()
            t.state = _DONE
            self._wake(lambda w: w.wait_reason == ("join", t))
            self._mon.notify_all()

    # -- primitive semantics ------------------------------------------------

    def lock_acquire(self, lk: _SchedLock, *, blocking: bool = True,
                     timeout: float = -1) -> bool:
        t = self.current()
        if t is None or not self.running:
            return True                 # unmanaged caller (post-run asserts)
        self._yield(t, ("acquire", lk.name))
        deadline = (self.now + timeout
                    if blocking and timeout is not None and timeout >= 0
                    else None)
        while True:
            if lk.owner is None or (lk.reentrant and lk.owner is t):
                break
            if not blocking:
                return False
            if deadline is not None and self.now >= deadline:
                return False
            fired = self._park(t, ("lock", lk), deadline)
            if fired and lk.owner is not None \
                    and not (lk.reentrant and lk.owner is t):
                return False            # timed acquire expired (virtual)
        if lk.owner is None:
            lk.owner = t
            _vc_join(t.vc, lk.vc)       # HB: last release -> this acquire
        lk.count += 1
        t.locks.append(lk)
        return True

    def lock_release(self, lk: _SchedLock) -> None:
        t = self.current()
        if t is None or not self.running:
            return
        if lk.owner is not t:
            self.record_failure(
                "bad-release",
                f"thread {t.name!r} released {lk.name} it does not hold")
            return
        lk.count -= 1
        if lk in t.locks:
            t.locks.remove(lk)
        if lk.count == 0:
            lk.vc = dict(t.vc)          # publish for the next acquirer
            t.tick()
            lk.owner = None
            with self._mon:
                self._wake(lambda w: w.wait_reason == ("lock", lk))
        self._yield(t, ("release", lk.name))

    def event_set(self, ev: _SchedEvent) -> None:
        t = self.current()
        if t is None or not self.running:
            ev.flag = True
            return
        ev.flag = True
        _vc_join(ev.vc, t.vc)           # HB: set -> observed wait
        t.tick()
        with self._mon:
            self._wake(lambda w: w.wait_reason == ("event", ev))
        self._yield(t, ("set", ev.name))

    def event_clear(self, ev: _SchedEvent) -> None:
        t = self.current()
        ev.flag = False
        # Reset the event's clock: a wait that returns True after this
        # point was released by a LATER set, and must join only that
        # setter — keeping old setters' clocks would fabricate
        # happens-before edges and mask real races.
        ev.vc = {}
        if t is not None and self.running:
            self._yield(t, ("clear", ev.name))

    def event_is_set(self, ev: _SchedEvent) -> bool:
        t = self.current()
        if t is not None and self.running:
            self._yield(t, ("is_set", ev.name))
            if ev.flag:
                _vc_join(t.vc, ev.vc)   # an observed set is synchronization
        return ev.flag

    def event_wait(self, ev: _SchedEvent, timeout: float | None) -> bool:
        t = self.current()
        if t is None or not self.running:
            return ev.flag
        self._yield(t, ("wait", ev.name))
        deadline = None if timeout is None else self.now + timeout
        while not ev.flag:
            if deadline is not None and self.now >= deadline:
                return False
            fired = self._park(t, ("event", ev), deadline)
            if fired and not ev.flag:
                return False
        _vc_join(t.vc, ev.vc)
        return True

    def cond_wait(self, cond: _SchedCondition, timeout: float | None) -> bool:
        t = self.current()
        if t is None or not self.running:
            return True
        lk = cond.lock
        if lk.owner is not t:
            self.record_failure(
                "bad-wait",
                f"thread {t.name!r} waits on {cond.name} without "
                f"holding {lk.name}")
            return False
        held = lk.count                 # full release, RLock-style
        lk.count = 0
        lk.vc = dict(t.vc)
        t.tick()
        lk.owner = None
        for _ in range(held):
            if lk in t.locks:
                t.locks.remove(lk)
        cond.waiting.append(t)
        with self._mon:
            self._wake(lambda w: w.wait_reason == ("lock", lk))
        deadline = None if timeout is None else self.now + timeout
        notified = not self._park(t, ("cond", cond), deadline)
        if t in cond.waiting:
            cond.waiting.remove(t)
        if notified:
            _vc_join(t.vc, cond.vc)
        # reacquire at the original depth
        self.lock_acquire(lk)
        for _ in range(held - 1):
            lk.count += 1
            t.locks.append(lk)
        return notified

    def cond_notify(self, cond: _SchedCondition, n: int) -> None:
        t = self.current()
        if t is None or not self.running:
            return
        _vc_join(cond.vc, t.vc)
        t.tick()
        woken = cond.waiting[:n]
        del cond.waiting[:n]
        with self._mon:
            self._wake(lambda w: w in woken)
        self._yield(t, ("notify", cond.name))

    def thread_join(self, target: _SchedThread,
                    timeout: float | None) -> None:
        t = self.current()
        if t is None or not self.running:
            return
        self._yield(t, ("join", target.name))
        deadline = None if timeout is None else self.now + timeout
        while target.state != _DONE:
            if deadline is not None and self.now >= deadline:
                return
            fired = self._park(t, ("join", target), deadline)
            if fired and target.state != _DONE:
                return
        _vc_join(t.vc, target.vc)       # HB: child's whole life -> joiner

    # -- annotated shared-field accesses (concheck instrumentation) --------

    def access(self, key: str, kind: str, declared=None) -> None:
        """One annotated access to shared field ``key`` (``kind`` is
        'read' or 'write').  A schedule point AND a race-detector
        sample; no-op from unmanaged threads (construction, post-run
        assertions)."""
        t = self.current()
        if t is None or not self.running:
            return
        self._yield(t, (kind, key))
        if self.detector is not None:
            held = tuple(lk.name for lk in t.locks)
            self.detector.record(key, kind, t, held, declared)

    # -- the coordinator ----------------------------------------------------

    def run(self) -> None:
        """Drive the schedule to completion on the calling (unmanaged)
        thread: repeatedly pick a runnable thread, hand it the baton,
        wait for it to yield/block/finish."""
        self.running = True
        try:
            self._loop()
        finally:
            self.running = False

    def _loop(self) -> None:
        while True:
            abort_these = None
            pick = None
            with self._mon:
                while any(t.state == _RUNNING for t in self.threads):
                    self._mon.wait()
                live = [t for t in self.threads if t.state != _DONE
                        and t.state != _NEW]
                if not live:
                    return
                ready = [t for t in live if t.state == _READY]
                if not ready:
                    timed = [t for t in live
                             if t.state == _BLOCKED
                             and t.deadline is not None]
                    if timed:
                        # Virtual time advances only when nothing else
                        # can run — timeouts fire as late as possible,
                        # maximizing the schedules where real work
                        # preempts them.
                        fire = min(t.deadline for t in timed)
                        self.now = max(self.now, fire)
                        for t in timed:
                            if t.deadline <= self.now:
                                t.timed_out = True
                                t.state = _READY
                        continue
                    self._report_deadlock(live)
                    abort_these = live
                else:
                    self.steps += 1
                    if self.steps > self.max_steps:
                        self.record_failure(
                            "step-budget",
                            f"schedule exceeded {self.max_steps} steps "
                            "(livelock?)")
                        abort_these = live
                    else:
                        pick = self.strategy.pick(ready, self.steps)
                        self.trace.append((pick.name, *pick.pending_op))
                        pick.state = _RUNNING
            # The monitor must be RELEASED here: aborted threads need it
            # to report thread_finished, and the picked thread needs it
            # at its next yield.
            if abort_these is not None:
                self._abort(abort_these)
                continue
            pick.turn.set()

    def _report_deadlock(self, live: list) -> None:
        frames = sys._current_frames()
        detail = []
        for t in live:
            reason = t.wait_reason or ("?", None)
            what = reason[0]
            obj = reason[1]
            objname = getattr(obj, "name", None) or ""
            stack = ""
            fr = frames.get(t.os_thread.ident)
            if fr is not None:
                stack = "".join(traceback.format_stack(fr, limit=8))
            detail.append(f"{t.name}: blocked on {what} {objname}\n{stack}")
        self.record_failure(
            "deadlock",
            "no runnable thread and no pending timeout; blocked: "
            + "; ".join(f"{t.name}<-{(t.wait_reason or ('?',))[0]}"
                        for t in live),
            stack="\n".join(detail))

    def _abort(self, live: list) -> None:
        self.aborting = True
        for t in live:
            t.abort = True
            t.state = _RUNNING          # hand every thread the baton
            t.turn.set()
        # Threads unwind via SchedulerAbort and report DONE; wait for
        # them on the REAL clock bounded (they do no real blocking).
        for t in live:
            t.os_thread.join(timeout=10.0)
            if t.os_thread.is_alive():
                self.record_failure(
                    "abort-timeout",
                    f"thread {t.name!r} did not unwind after abort")
            with self._mon:
                if t.state != _DONE:
                    t.state = _DONE
