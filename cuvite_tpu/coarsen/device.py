"""On-device inter-phase coarsening: distbuildNextLevelGraph in HBM.

The host pipeline (coarsen/rebuild.py — the bit-parity oracle for this
module) runs after every phase: device_get the labels, np.unique
renumber, relabel + coalesce the edge list on the host, rebuild the
DistGraph, re-upload the slab.  Between two phases that is two O(E)
PCIe crossings plus an idle device — the single biggest wall-clock
lever left after the engine work (ISSUE 3; PASCO, arXiv:2412.13592,
measures coarsening as the scalability bottleneck of multilevel
clustering, and the GPU Louvain line keeps aggregation on-accelerator
for the same reason, arXiv:1805.10904).

This module is the device-resident equivalent, all under ``jax.jit``
with static pow2-padded shapes:

  1. ``device_renumber`` — dense renumbering of surviving communities
     (presence scatter + exclusive prefix count over the padded label
     space), matching the reference's sorted-order renumbering
     (rebuild.cpp:167-197: smallest surviving label -> 0) and therefore
     ``rebuild.renumber_communities`` exactly;
  2. ``device_coarsen_slab`` — relabel both endpoints to dense ids and
     coalesce duplicate (src, dst) pairs through THE segmented-coalesce
     chokepoint (ops/segment.py::coalesced_runs — packed sort by
     default, the dense dst-tile engines of kernels/seg_coalesce.py on
     request; graftlint R013 keeps stray slab sorts out), landing the
     coarse graph COMPACTED into a prefix of the SAME slab class: out
     arrays keep the input's [ne_pad] shape, real rows in [0, ne2),
     padding (src == nv_pad, w == 0) after.  Phases whose coarse graph
     still fits the class re-enter the same compiled step — zero
     retraces, zero transfers; the driver drops to a smaller pow2 class
     only when the one-scalar-per-phase host sync (already paid for
     convergence) shows the graph fits, via ``shrink_slab``.

Accumulation: duplicate-run weights sum in ``accum_dtype`` (default:
the weight dtype; ``'ds32'`` = double-single pairs, collapsed to f32
once — the scale-safe mode for self-loop runs whose intra-community
mass exceeds f32's 2^24 integer range).  The host oracle accumulates
f64 and casts once, so device == host bit-for-bit whenever the run
sums are exactly representable (unit/dyadic weights — the parity
suite's domain, tests/test_coarsen_device.py); beyond it the ds32 mode
keeps ~2^-48 relative agreement.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from cuvite_tpu.core.types import next_pow2
from cuvite_tpu.ops import segment as seg


def device_coarsen_enabled() -> bool:
    """Device-resident coarsening is the default; CUVITE_DEVICE_COARSEN=0
    keeps the host pipeline (the A/B lever and the escape hatch).  Read
    per call, not at import, so tests and benches can toggle it."""
    return os.environ.get("CUVITE_DEVICE_COARSEN", "1").lower() \
        not in ("", "0", "false")


@functools.partial(jax.jit, static_argnames=("nv_pad",))
def device_renumber(comm, real_mask, *, nv_pad: int):
    """Dense renumbering of the surviving community labels, on device.

    ``comm``: [nv_pad] labels in the padded vertex id space (every real
    vertex's label is a real vertex id < nv_pad); ``real_mask``: [nv_pad]
    bool.  Returns ``(dense_map, nc)``: ``dense_map[c]`` is the dense id
    of surviving community ``c`` in SORTED label order (smallest -> 0,
    matching np.unique/rebuild.cpp:167-197); entries of labels that
    survive nowhere are meaningless and must never be gathered.  ``nc``
    is the surviving-community count (scalar, stays on device).
    """
    lab = jnp.where(real_mask, comm, nv_pad)
    present = jnp.zeros((nv_pad + 1,), jnp.int32).at[lab].set(1, mode="drop")
    present = present[:nv_pad]  # padding labels land in the dropped slot
    dense_map = (jnp.cumsum(present) - present).astype(comm.dtype)
    return dense_map, jnp.sum(present)


@functools.partial(jax.jit,
                   static_argnames=("nv_pad", "accum_dtype", "coalesce"))
def device_coarsen_slab(src, dst, w, comm, real_mask, *, nv_pad: int,
                        accum_dtype=None, dense_map=None, nc=None,
                        coalesce=None):
    """Relabel + coalesce the resident edge slab into the next-phase slab.

    ``src``: [ne_pad] local vertex ids (pad == nv_pad, sorted to the
    tail); ``dst``: [ne_pad] padded-space tail ids (pad == 0, w == 0);
    ``comm``: [nv_pad] phase-end labels; ``real_mask``: [nv_pad] bool.

    Returns ``(src2, dst2, w2, dense_map, nc, ne2)``: the coarse slab in
    the SAME [ne_pad] class, coalesced rows sorted by (src, dst) and
    compacted into [0, ne2), padding (src == nv_pad, dst == 0, w == 0)
    after; ``dense_map``/``nc`` as :func:`device_renumber`.  Intra-
    community weight collapses onto the diagonal as self-loops
    (rebuild.cpp:244-279), which keeps modularity consistent across
    phases.  ``accum_dtype``: run-sum accumulator — None (weight dtype),
    a dtype name, or ``'ds32'`` for double-single pairs.  ``dense_map``/
    ``nc`` (pass both or neither): a precomputed :func:`device_renumber`
    of the SAME ``(comm, real_mask)`` — the fused driver reuses the one
    it already ran for label composition instead of renumbering twice.

    ``coalesce`` (static): the segmented-coalesce engine — 'pallas' /
    'xla' (the dense dst-tile bin-accumulate,
    kernels/seg_coalesce.py; no sorted slab copy) or 'sort' (the packed
    sort fallback).  None resolves via
    ``seg_coalesce.coalesce_engine(nv_pad, accum_dtype)`` AT TRACE TIME
    — callers that want env toggles honored per call (the drivers do)
    must resolve and pass it explicitly.  Every engine produces the
    same contract; weights are bit-identical across engines on the
    exactness domain (see kernels/seg_coalesce.py).
    """
    wdt = w.dtype
    if dense_map is None:
        dense_map, nc = device_renumber(comm, real_mask, nv_pad=nv_pad)

    pad = src >= nv_pad
    safe_src = jnp.minimum(src, nv_pad - 1)
    csrc = jnp.take(dense_map, jnp.take(comm, safe_src))
    cdst = jnp.take(dense_map, jnp.take(comm, dst))
    new_src = jnp.where(pad, jnp.asarray(nv_pad, src.dtype),
                        csrc.astype(src.dtype))
    new_dst = jnp.where(pad, jnp.zeros((), dst.dtype),
                        cdst.astype(dst.dtype))
    w_in = jnp.where(pad, jnp.zeros_like(w), w)

    if coalesce is None:
        from cuvite_tpu.kernels.seg_coalesce import coalesce_engine

        coalesce = coalesce_engine(nv_pad, accum_dtype)
    src2, dst2, w2, ne2 = seg.coalesced_runs(
        new_src, new_dst, w_in, nv_pad=nv_pad, accum_dtype=accum_dtype,
        engine=coalesce)
    w2 = w2.astype(wdt)
    return src2, dst2, w2, dense_map, nc, ne2


@functools.partial(jax.jit, static_argnames=("nv_pad",))
def device_weighted_degrees(src, w, *, nv_pad: int):
    """vDegree of a device-resident slab (padding src >= nv_pad drops)."""
    return seg.segment_sum(w, src, num_segments=nv_pad, sorted_ids=True)


@jax.jit
def device_compose_labels(dense_map, labels, comm_all):
    """Cross-phase label composition on device (main.cpp:374-403):
    original vertex -> current dense vertex id, through this phase's
    padded-space ``labels`` and its ``dense_map``."""
    return jnp.take(dense_map, jnp.take(labels, comm_all))


# --- batched (multi-tenant) lifts, ISSUE 9 ---------------------------------
# The batched driver (louvain/batched.py) runs B same-class graphs
# through one compiled program with a leading batch axis; these are the
# vmap lifts of the device coarsener it embeds.  They are plain
# traceable functions (the inner jits inline under the caller's jit):
# jitting here would fragment the driver's one-program-per-phase
# property into per-helper dispatches.

def batched_renumber(comm, real_mask, *, nv_pad: int):
    """[B, nv_pad] lift of :func:`device_renumber`: per-row dense maps
    and surviving-community counts ``(dense_map [B, nv_pad], nc [B])``."""
    return jax.vmap(
        functools.partial(device_renumber, nv_pad=nv_pad))(comm, real_mask)


def batched_compose_labels(dense_map, labels, comm_all):
    """[B, ...] lift of :func:`device_compose_labels`."""
    return jax.vmap(device_compose_labels)(dense_map, labels, comm_all)


def batched_coarsen_slab(src, dst, w, comm, real_mask, dense_map, nc, *,
                         nv_pad: int, accum_dtype=None, coalesce="sort"):
    """[B, ne_pad] lift of :func:`device_coarsen_slab` (precomputed
    per-row ``dense_map``/``nc`` required — the batched driver always
    has them from the label composition).  ``coalesce`` must be an
    EXPLICIT engine and not ``'pallas'``: the Pallas grid does not lift
    over a batch axis; the XLA twin, the packed sort, and the msd
    two-pass sort all do.  (Not ``'hash'`` either: its per-row
    ``lax.cond`` retry would execute BOTH branches under vmap — the
    batched policy routes hash to 'msd' instead,
    louvain/batched.py::_batched_coalesce_engine.)"""
    assert coalesce in ("sort", "xla", "msd"), \
        f"batched coalesce engine {coalesce!r}: vmap lifts " \
        "'sort'/'xla'/'msd' only"

    def one(s, d, ww, c, rm, dm, n):
        return device_coarsen_slab(
            s, d, ww, c, rm, nv_pad=nv_pad, accum_dtype=accum_dtype,
            dense_map=dm, nc=n, coalesce=coalesce)

    return jax.vmap(one)(src, dst, w, comm, real_mask, dense_map, nc)


# --- sub-row (fenced) lifts, ISSUE 20 --------------------------------------
# A packed row (core/batch.py::SubRowLayout) holds n_sub disjoint graphs
# at fixed vertex offsets; its coarsening must renumber SEGMENT-LOCALLY
# so every sub-row's coarse ids stay inside its own fence interval —
# whole-row dense ranks would blur the seams for the next phase.  Two
# maps come out of one presence scan: the CURRENT-offset map relabels
# the resident slab (whose class may have shrunk), the ORIGINAL-offset
# map composes the cross-phase labels, which therefore always live in
# the pack-time offset space — unpack is a fence slice minus the
# offset, no matter when each sub-row retired or whether the slab
# shrank in between.


@functools.partial(jax.jit, static_argnames=("nv_pad", "n_sub", "nv_sub0"))
def subrow_renumber(comm, real_mask, *, nv_pad: int, n_sub: int,
                    nv_sub0: int):
    """Segment-local dense renumbering of a packed row's surviving
    communities.  Returns ``(dmap_cur, dmap_orig, nc)``: ``dmap_cur[c]``
    is community ``c``'s dense id at CURRENT sub-row offsets
    (``s * (nv_pad // n_sub) + rank``), ``dmap_orig[c]`` the same rank
    at ORIGINAL offsets (``s * nv_sub0 + rank``), ``nc`` the ``[n_sub]``
    per-sub-row surviving counts.  Ranks are the within-segment cumsum
    of the same presence scan :func:`device_renumber` uses, so each
    sub-row's ranks equal its solo run's (smallest label -> 0)."""
    lab = jnp.where(real_mask, comm, nv_pad)
    present = jnp.zeros((nv_pad + 1,), jnp.int32).at[lab].set(1, mode="drop")
    present = present[:nv_pad].reshape(n_sub, -1)
    local = jnp.cumsum(present, axis=-1) - present
    nv_sub = nv_pad // n_sub
    offs_cur = (jnp.arange(n_sub, dtype=jnp.int32) * nv_sub)[:, None]
    offs_orig = (jnp.arange(n_sub, dtype=jnp.int32) * nv_sub0)[:, None]
    dmap_cur = (local + offs_cur).reshape(nv_pad).astype(comm.dtype)
    dmap_orig = (local + offs_orig).reshape(nv_pad).astype(comm.dtype)
    return dmap_cur, dmap_orig, jnp.sum(present, axis=-1)


@functools.partial(jax.jit, static_argnames=("nv_pad", "n_sub", "nv_sub0"))
def subrow_compose_labels(dmap_orig, labels, comm_all, *, nv_pad: int,
                          n_sub: int, nv_sub0: int):
    """Cross-phase label composition for a packed row: ``comm_all``
    holds ORIGINAL-offset dense ids; map them to current offsets (the
    slab class may have shrunk), gather this phase's ``labels``, then
    back to original offsets through ``dmap_orig``.  Gathers clamp —
    retired sub-rows' stale ids may exceed the shrunken segment, and
    their positions are masked out by the caller anyway."""
    nv_sub = nv_pad // n_sub
    s = comm_all // nv_sub0
    r = comm_all % nv_sub0
    v_cur = jnp.minimum(s, n_sub - 1) * nv_sub + jnp.minimum(r, nv_sub - 1)
    v_cur = jnp.minimum(v_cur, nv_pad - 1)
    return jnp.take(dmap_orig, jnp.take(labels, v_cur))


def batched_subrow_renumber(comm, real_mask, *, nv_pad: int, n_sub: int,
                            nv_sub0: int):
    """[B, nv_pad] lift of :func:`subrow_renumber`."""
    return jax.vmap(functools.partial(
        subrow_renumber, nv_pad=nv_pad, n_sub=n_sub, nv_sub0=nv_sub0))(
        comm, real_mask)


def batched_subrow_compose(dmap_orig, labels, comm_all, *, nv_pad: int,
                           n_sub: int, nv_sub0: int):
    """[B, ...] lift of :func:`subrow_compose_labels`."""
    return jax.vmap(functools.partial(
        subrow_compose_labels, nv_pad=nv_pad, n_sub=n_sub,
        nv_sub0=nv_sub0))(dmap_orig, labels, comm_all)


def shrink_slab(src, dst, w, *, new_nv_pad: int, new_ne_pad: int):
    """Drop a compacted coarse slab to a smaller pow2 class — device ops
    only (a prefix slice plus a padding-sentinel rewrite; real ids are
    < nc <= new_nv_pad, so only the old nv_pad sentinels move)."""
    s = src[:new_ne_pad]
    s = jnp.where(s >= new_nv_pad, jnp.asarray(new_nv_pad, s.dtype), s)
    return s, dst[:new_ne_pad], w[:new_ne_pad]


@functools.partial(jax.jit,
                   static_argnames=("nv_pad", "new_nv_pad", "new_ne_pad"))
def grow_slab(src, dst, w, *, nv_pad: int, new_nv_pad: int,
              new_ne_pad: int):
    """Lift a canonical slab to a LARGER pow2 class — the spill twin of
    :func:`shrink_slab`, device ops only (a sentinel rewrite plus a
    sentinel-padded extend).  The streaming delta path (stream/delta.py)
    uses it when an insert batch overflows the resident class's padding
    headroom; real rows keep their prefix order, so the grown slab is
    still canonical."""
    cur_ne_pad = src.shape[0]  # static under jit
    if new_nv_pad < nv_pad or new_ne_pad < cur_ne_pad:
        raise ValueError("grow_slab grows classes; use shrink_slab to drop")
    pad_n = new_ne_pad - cur_ne_pad
    s = jnp.where(src >= nv_pad, jnp.asarray(new_nv_pad, src.dtype), src)
    s = jnp.concatenate([s, jnp.full((pad_n,), new_nv_pad, src.dtype)])
    d = jnp.concatenate([dst, jnp.zeros((pad_n,), dst.dtype)])
    ww = jnp.concatenate([w, jnp.zeros((pad_n,), w.dtype)])
    return s, d, ww


def maybe_shrink_to_class(src, dst, w, *, nc: int, ne2: int, nv_pad: int,
                          ne_pad: int, min_nv_pad: int = 4096,
                          min_ne_pad: int = 16384):
    """THE slab-class transition policy, shared by the sort-engine and
    fused drivers (one copy, so their padding behavior cannot drift):
    recompute the pow2 class for a coarse graph (same floors as
    DistGraph.build's single-shard defaults, so device and host rebuilds
    land on identical compiled-step cache keys) and shrink the slab only
    when a strictly smaller class fits — coarsening never grows nv/ne,
    so the class never grows.  Returns (src, dst, w, nv_pad, ne_pad)."""
    new_nv_pad = max(next_pow2(max(nc, 1)), min_nv_pad)
    new_ne_pad = max(next_pow2(max(ne2, 1)), min_ne_pad)
    if new_nv_pad < nv_pad or new_ne_pad < ne_pad:
        src, dst, w = shrink_slab(src, dst, w, new_nv_pad=new_nv_pad,
                                  new_ne_pad=new_ne_pad)
        return src, dst, w, new_nv_pad, new_ne_pad
    return src, dst, w, nv_pad, ne_pad
