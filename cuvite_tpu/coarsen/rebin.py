"""Device re-binning: bucket plans built ON DEVICE from a coalesced
slab (ISSUE 19 tentpole a).

The degree-bucketed engine's plans (louvain/bucketed.py::BucketPlan)
were host-built every phase: coarse phases of the per-graph driver pay
a host pass + plan upload per phase, and the batched serving path
(louvain/batched.py) downgraded every coarse phase to the FUSED engine
— a packed 2-channel ``lax.sort`` per iteration — because re-binning
needed a host histogram.  GPU Louvain gets its coarse-phase throughput
precisely by keeping per-phase neighbor aggregation in binned form
rather than re-sorting (Naim et al., arXiv:1805.10904), and the
reference's heuristics assume cheap per-phase rebinning (Ghosh et al.,
arXiv:1410.1237).  This module is the TPU translation: a pure-jnp,
jittable, vmappable plan builder — degree histogram over the padded
label space, per-width class assignment against the static
``DEFAULT_BUCKETS`` ladder, gather-index construction into the stacked
``[rows, width]`` dst/w layout ``bucketed_step`` already consumes —
with NO host sync and NO ``lax.sort`` (this module sits inside
graftlint R013's no-sort scope).

Static geometry.  The compile-key set must stay bounded, so bucket
shapes cannot depend on the phase's degree distribution (the host
builder's data-dependent ``nb_pad`` would retrace every phase).
:func:`rebin_geometry` derives a CLASS-static shape instead: every
truncated-ladder width is kept (an empty class is all-padding rows),
and class k's row count is the provable occupancy ceiling

    rows_k = pow2_ceil(min(nv_pad, ne_pad // (prev_k + 1)))

— a vertex in class k has degree > prev_k, so at most
ne_pad // (prev_k + 1) vertices fit the class, and pow2_ceil dominates
the host builder's pow2 ``nb_pad`` (pow2_ceil is monotone), so every
host bucket embeds as the device bucket's prefix.  One program per
``(nv_pad, ne_pad)`` slab class, exactly like the slab kernels.

Eligibility (:func:`rebin_eligible`).  A coalesced slab's max degree is
bounded by nv_pad (distinct neighbors), so nv_pad <= DEFAULT_BUCKETS[-1]
guarantees NO heavy residual — the heavy triple is the host builder's
8-slot all-padding placeholder, statically.  Classes past the ladder
top (nv_pad > 8192, where a heavy residual could exist) and geometries
past the plan-element budget (CUVITE_REBIN_MAX_ELEMS) fall back to the
host ``BucketPlan.build`` oracle, which stays the bit-identity
reference for everything this module emits.

Slab contract: sorted by src with the real rows compacted into the
prefix and padding (src == nv_pad, w == 0) after — what
``DistGraph.build`` CSR expansion, ``coalesced_runs`` output and the
batched coarsen/shrink all guarantee.  Weights are emitted in the slab
weight dtype with NO content-dependent uint8 compression (the
stable-compile-key convention of core/batch.py::batch_bucket_plans);
the self-loop scatter accumulates in the weight dtype, so device ==
host bit-for-bit on the exactness domain (unit/dyadic weights, the
same contract as coarsen/device.py).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from cuvite_tpu.louvain.bucketed import DEFAULT_BUCKETS

# Plan-element ceiling (sum of rows_k * width_k over the geometry): at
# the serving class (4096, 16384) the static geometry costs ~26x ne_pad
# elements — a few MB — but a pathological nv_pad/ne_pad ratio could
# inflate it, so eligibility is budget-gated like every other device
# structure (the CUVITE_HEAVY_ELEMS precedent).
DEFAULT_REBIN_MAX_ELEMS = 1 << 27


def rebin_max_elems() -> int:
    from cuvite_tpu.utils.envknob import env_int

    return env_int("CUVITE_REBIN_MAX_ELEMS", DEFAULT_REBIN_MAX_ELEMS,
                   maximum=1 << 34)


def device_rebin_enabled() -> bool:
    """Device re-binning is the default for eligible coarse phases;
    CUVITE_DEVICE_REBIN=0 pins the host BucketPlan.build path (the A/B
    lever and the escape hatch).  Read per call, not at import, so
    tests and benches can toggle it."""
    return os.environ.get("CUVITE_DEVICE_REBIN", "1").lower() \
        not in ("", "0", "false")


def rebin_geometry(nv_pad: int, ne_pad: int,
                   widths: tuple = DEFAULT_BUCKETS) -> tuple:
    """The CLASS-static bucket geometry: ``((width, rows), ...)`` for
    every ladder width kept after truncation (widths whose predecessor
    already covers nv_pad carry no vertex and are dropped — degree is
    bounded by nv_pad on a coalesced slab).  ``rows`` is the pow2
    occupancy ceiling per class; see the module docstring for the
    bound."""
    geom = []
    prev = 0
    for width in widths:
        if prev >= nv_pad:
            break
        cap = min(nv_pad, max(ne_pad // (prev + 1), 1))
        rows = 1 << max(int(cap - 1).bit_length(), 0)
        geom.append((width, rows))
        prev = width
    return tuple(geom)


def rebin_eligible(nv_pad: int, ne_pad: int,
                   widths: tuple = DEFAULT_BUCKETS) -> bool:
    """True when the class can be re-binned on device with NO heavy
    residual and a bounded plan: nv_pad within the ladder top (max
    coalesced degree <= nv_pad <= widths[-1], so the last kept width
    covers every vertex) and the static geometry within the element
    budget."""
    if nv_pad > widths[-1]:
        return False  # a heavy residual could exist: host oracle path
    geom = rebin_geometry(nv_pad, ne_pad, widths)
    elems = sum(r * w for w, r in geom)
    return elems <= rebin_max_elems()


def rebin_plan(src, dst, w, *, nv_pad: int, base: int, geometry: tuple):
    """Pure-jnp plan builder — trace-safe under jit AND vmap (the
    batched rebinned phase maps it over the tenant axis).

    ``src``: [ne_pad] local vertex ids, sorted, real rows compacted into
    the prefix, padding == nv_pad; ``dst``: [ne_pad] padded-space tail
    ids (padding 0, w 0); ``base``: the shard's first global id (self-
    loop detection, same convention as ``BucketPlan.build``).

    Returns ``(buckets, heavy, self_loop, perm)``: ``buckets`` a tuple
    of ``(verts [R], dmat [R, W], wmat [R, W])`` triples in geometry
    (ladder) order — padding rows carry verts == nv_pad, dmat/wmat 0;
    padding COLUMNS of real rows carry the vertex's own global id with
    weight 0, exactly like the host builder — ``heavy`` the static
    8-slot all-padding triple (eligibility proved no residual),
    ``self_loop`` [nv_pad] per-vertex self-loop weight, and ``perm``
    [nv_pad] int32 vertex -> position in the concatenated bucket-row
    space (no-bucket vertices -> the trailing default slot), the
    ``build_assemble_perm`` contract.
    """
    ne_pad = src.shape[0]
    vdt = src.dtype
    ddt = dst.dtype
    wdt = w.dtype
    real = src < nv_pad
    src_i = jnp.where(real, src, nv_pad).astype(jnp.int32)

    # Degree histogram over the padded label space (padding ids drop via
    # the out-of-range segment) + exclusive prefix = CSR row starts of
    # the already-sorted slab.
    deg = jax.ops.segment_sum(real.astype(jnp.int32), src_i,
                              num_segments=nv_pad,
                              indices_are_sorted=True)
    row_start = jnp.cumsum(deg) - deg  # int32: ne_pad <= SLAB_NE_MAX

    is_self = real & (dst == (src_i + jnp.int32(base)).astype(ddt))
    self_loop = jax.ops.segment_sum(
        jnp.where(is_self, w, jnp.zeros_like(w)), src_i,
        num_segments=nv_pad, indices_are_sorted=True).astype(wdt)

    total = sum(r for _, r in geometry)
    vids = jnp.arange(nv_pad, dtype=jnp.int32)
    perm = jnp.full((nv_pad,), total, jnp.int32)
    buckets = []
    off = 0
    prev = 0
    for width, rows in geometry:
        in_cls = (deg > prev) & (deg <= width)
        # Ascending-id compaction (== np.nonzero order of the host
        # builder): scatter each class vertex to its prefix position.
        pos = jnp.cumsum(in_cls.astype(jnp.int32)) - 1  # graftlint: width-ok=cumsum over the [nv_pad] class mask and rebin_eligible caps nv_pad <= DEFAULT_BUCKETS[-1] = 8192
        verts = jnp.full((rows,), nv_pad, jnp.int32).at[
            jnp.where(in_cls, pos, rows)].set(vids, mode="drop")
        row_real = verts < nv_pad
        safe_v = jnp.minimum(verts, nv_pad - 1)
        cols = jnp.arange(width, dtype=jnp.int32)
        idx = jnp.minimum(row_start[safe_v][:, None] + cols[None, :],
                          ne_pad - 1)
        has = (cols[None, :] < deg[safe_v][:, None]) & row_real[:, None]
        own = (verts + jnp.int32(base)).astype(ddt)[:, None]
        dmat = jnp.where(has, dst[idx],
                         jnp.where(row_real[:, None], own,
                                   jnp.zeros((), ddt)))
        wmat = jnp.where(has, w[idx], jnp.zeros((), wdt))
        buckets.append((verts.astype(vdt), dmat, wmat))
        perm = jnp.where(in_cls, jnp.int32(off) + pos, perm)  # graftlint: width-ok=off + pos < total plan rows, and rebin_eligible caps total plan ELEMENTS at REBIN_MAX_ELEMS < 2^31
        off += rows
        prev = width

    heavy = (jnp.full((8,), nv_pad, vdt), jnp.zeros((8,), ddt),
             jnp.zeros((8,), wdt))
    return tuple(buckets), heavy, self_loop, perm


@functools.partial(jax.jit,
                   static_argnames=("nv_pad", "base", "geometry"))
def device_rebin_plan(src, dst, w, *, nv_pad: int, base: int,
                      geometry: tuple):
    """The jitted eager entry point (per-graph driver): one device
    dispatch per phase, statics = the slab class (``geometry`` comes
    from :func:`rebin_geometry`, so the compile-key set is one program
    per class).  The batched path traces :func:`rebin_plan` directly
    inside its phase program instead."""
    return rebin_plan(src, dst, w, nv_pad=nv_pad, base=base,
                      geometry=geometry)
