"""Inter-phase graph coarsening: communities become vertices.

Equivalent of distbuildNextLevelGraph (/root/reference/rebuild.cpp:430-454):

  1. renumber surviving communities to a dense contiguous id space
     (distReNumber, rebuild.cpp:27-242) — here a host-side np.unique over the
     community vector (the per-phase dynamic shape lives on the host; device
     shapes stay static within a phase);
  2. aggregate edges community->community (fill_newEdgesMap,
     rebuild.cpp:244-279) — here one sparse-matrix coalesce;
  3. re-partition the new graph over the mesh (send_newEdges,
     rebuild.cpp:281-428) — here simply rebuilding DistGraph shards.

Intra-community weight collapses onto the diagonal as self-loops, which is
what keeps modularity consistent across phases.
"""

from __future__ import annotations

import numpy as np

from cuvite_tpu.core.graph import Graph
from cuvite_tpu.core.types import Policy


def renumber_communities(comm: np.ndarray) -> tuple[np.ndarray, int]:
    """Map arbitrary community labels to dense ids [0, nc).

    Returns (dense_labels, nc).  Matches the reference's sorted-order
    renumbering (smallest original label -> 0; rebuild.cpp:167-197 and
    main.cpp:374-394 both sort before assigning new ids).
    """
    uniq, dense = np.unique(comm, return_inverse=True)
    return dense.astype(np.int64), int(len(uniq))


def coarsen_graph(
    graph: Graph, dense_comm: np.ndarray, nc: int, policy: Policy | None = None
) -> Graph:
    """Build the next-phase graph whose vertices are the nc communities."""
    policy = policy or graph.policy
    from cuvite_tpu import native

    # Fused native path: relabel + coalesce straight off the CSR, no
    # expanded int64/f64 edge-list temporaries (the numpy route below
    # peaks at ~3x the radix working set and dominated the host share of
    # benchmark-scale runs).  Output is bit-identical to the fallback
    # (same stable key order, f64 accumulation, one f32 cast).
    if (graph.num_edges >= native.MIN_NATIVE_EDGES and native.available()
            and nc <= 1 << 31 and policy.weight_dtype == np.float32):
        offsets, tails, w = native.coarsen_csr(
            graph.offsets, graph.tails, graph.weights, dense_comm, nc)
        return Graph(
            offsets=offsets,
            tails=tails.astype(policy.vertex_dtype, copy=False),
            weights=w,
            policy=policy,
        )
    src = dense_comm[graph.sources()]
    dst = dense_comm[graph.tails.astype(np.int64)]
    # The slab already holds both edge directions, so aggregation is a
    # plain (src, dst) coalesce — from_edges without symmetrization (which
    # itself dispatches to the native builder above its size threshold).
    return Graph.from_edges(
        nc, src, dst, weights=graph.weights.astype(np.float64),
        symmetrize=False, policy=policy,
    )
