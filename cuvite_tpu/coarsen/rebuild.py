"""Inter-phase graph coarsening: communities become vertices.

Equivalent of distbuildNextLevelGraph (/root/reference/rebuild.cpp:430-454):

  1. renumber surviving communities to a dense contiguous id space
     (distReNumber, rebuild.cpp:27-242) — here a host-side np.unique over the
     community vector (the per-phase dynamic shape lives on the host; device
     shapes stay static within a phase);
  2. aggregate edges community->community (fill_newEdgesMap,
     rebuild.cpp:244-279) — here one sparse-matrix coalesce;
  3. re-partition the new graph over the mesh (send_newEdges,
     rebuild.cpp:281-428) — here simply rebuilding DistGraph shards.

Intra-community weight collapses onto the diagonal as self-loops, which is
what keeps modularity consistent across phases.
"""

from __future__ import annotations

import numpy as np

from cuvite_tpu.core.graph import Graph
from cuvite_tpu.core.types import Policy


def renumber_communities(comm: np.ndarray) -> tuple[np.ndarray, int]:
    """Map arbitrary community labels to dense ids [0, nc).

    Returns (dense_labels, nc).  Matches the reference's sorted-order
    renumbering (smallest original label -> 0; rebuild.cpp:167-197 and
    main.cpp:374-394 both sort before assigning new ids).
    """
    uniq, dense = np.unique(comm, return_inverse=True)
    return dense.astype(np.int64), int(len(uniq))


def coarsen_graph(
    graph: Graph, dense_comm: np.ndarray, nc: int, policy: Policy | None = None
) -> Graph:
    """Build the next-phase graph whose vertices are the nc communities."""
    policy = policy or graph.policy
    src = dense_comm[graph.sources()]
    dst = dense_comm[graph.tails.astype(np.int64)]
    w = graph.weights.astype(np.float64)
    from cuvite_tpu import native

    if len(src) >= (1 << 16) and native.available():
        # The slab already holds both edge directions, so aggregation is a
        # plain (src, dst) coalesce — cv_build_csr with symmetrize off.
        offsets, tails, wsum = native.build_csr(nc, src, dst, w,
                                                symmetrize=False)
    else:
        # Same coalesce in numpy: stable sort by (src, dst), then sum
        # duplicates in input order — the accumulation-order contract shared
        # with cv_build_csr, so native and fallback agree bit-for-bit (a
        # scipy coo->csr coalesce would sum in a different order).
        key = src * np.int64(nc) + dst
        order = np.argsort(key, kind="stable")
        key_s, w_s = key[order], w[order]
        uniq = np.ones(len(key_s), dtype=bool)
        uniq[1:] = key_s[1:] != key_s[:-1]
        seg_ids = np.cumsum(uniq) - 1
        n_uniq = int(seg_ids[-1]) + 1 if len(seg_ids) else 0
        wsum = np.zeros(n_uniq, dtype=np.float64)
        np.add.at(wsum, seg_ids, w_s)
        key_u = key_s[uniq]
        tails = key_u % nc
        counts = np.bincount(key_u // nc, minlength=nc)
        offsets = np.zeros(nc + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
    return Graph(
        offsets=offsets,
        tails=tails.astype(policy.vertex_dtype),
        weights=wsum.astype(policy.weight_dtype),
        policy=policy,
    )
