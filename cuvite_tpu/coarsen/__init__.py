"""cuvite_tpu.coarsen"""
