"""Host-side modularity oracle (numpy, float64).

Same quantity the device step computes
(cf. distComputeModularity, /root/reference/louvain.cpp:2433-2481):

    Q = sum_c e_c / (2m)  -  sum_c (a_c / 2m)^2

where e_c is the total weight of edges with both endpoints in community c
(both directions counted, self-loops once per stored direction) and a_c is the
total weighted degree of community c.
"""

from __future__ import annotations

import numpy as np

from cuvite_tpu.core.graph import Graph


def modularity(graph: Graph, comm: np.ndarray) -> float:
    comm = np.asarray(comm, dtype=np.int64)
    src_c = comm[graph.sources()]
    dst_c = comm[graph.tails.astype(np.int64)]
    w = graph.weights.astype(np.float64)
    two_m = w.sum()
    e_in = w[src_c == dst_c].sum()
    nc = int(comm.max()) + 1 if len(comm) else 0
    a_c = np.bincount(src_c, weights=w, minlength=nc)
    return float(e_in / two_m - np.square(a_c / two_m).sum())
