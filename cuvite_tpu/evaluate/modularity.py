"""Host-side modularity oracle (numpy, float64).

Same quantity the device step computes
(cf. distComputeModularity, /root/reference/louvain.cpp:2433-2481):

    Q = sum_c e_c / (2m)  -  sum_c (a_c / 2m)^2

where e_c is the total weight of edges with both endpoints in community c
(both directions counted, self-loops once per stored direction) and a_c is the
total weighted degree of community c.
"""

from __future__ import annotations

import os

import numpy as np

from cuvite_tpu.core.graph import Graph

# The dense oracle materializes ~4 O(E) temporaries (expanded sources,
# two label gathers, an f64 weight copy): ~25 B per directed edge slot.
# Above this many edges the CLI path must NOT pay that host gather
# (scale-26 would be an ~8.6B-element one, VERDICT r5 weak #7) — the
# driver's distributed f64 device recompute is the reported value there.
HOST_ORACLE_MAX_EDGES = 1 << 27


def host_oracle_max_edges() -> int:
    """Env-overridable oracle ceiling (CUVITE_HOST_ORACLE_MAX_EDGES);
    malformed values warn and keep the default, like the other knobs."""
    raw = os.environ.get("CUVITE_HOST_ORACLE_MAX_EDGES")
    if not raw:
        return HOST_ORACLE_MAX_EDGES
    try:
        return int(float(raw))
    except ValueError:
        import warnings

        warnings.warn(f"CUVITE_HOST_ORACLE_MAX_EDGES={raw!r} is not a "
                      "number; using the default "
                      f"{HOST_ORACLE_MAX_EDGES}", stacklevel=2)
        return HOST_ORACLE_MAX_EDGES


def modularity_gated(graph: Graph, comm: np.ndarray, fallback: float,
                     max_edges: int | None = None) -> tuple:
    """``(q, used_oracle)``: the dense host oracle when the graph is
    small enough, else ``fallback`` (the driver's ds-exact device
    value) — so huge graphs never trigger the O(E) host gather."""
    limit = host_oracle_max_edges() if max_edges is None else max_edges
    if graph.num_edges <= limit:
        return modularity(graph, comm), True
    return float(fallback), False


def modularity(graph: Graph, comm: np.ndarray) -> float:
    comm = np.asarray(comm, dtype=np.int64)
    src_c = comm[graph.sources()]
    dst_c = comm[graph.tails.astype(np.int64)]
    w = graph.weights.astype(np.float64)
    two_m = w.sum()
    e_in = w[src_c == dst_c].sum()
    nc = int(comm.max()) + 1 if len(comm) else 0
    a_c = np.bincount(src_c, weights=w, minlength=nc)
    return float(e_in / two_m - np.square(a_c / two_m).sum())
