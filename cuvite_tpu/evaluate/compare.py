"""Ground-truth community comparison: precision/recall/F-score + Gini.

Equivalent of compare_communities (/root/reference/compare.cpp:8-256), which
counts vertex pairs that agree between ground truth C1 and output C2:

    TP (Same-Same): pairs co-clustered in both
    FN (Same-Diff): co-clustered in truth, split in output
    FP (Diff-Same): split in truth, co-clustered in output

The reference enumerates all intra-community pairs with OpenMP; here the same
counts come from the contingency table n_ij = |{v : C1[v]=i and C2[v]=j}|:
TP = sum C(n_ij,2), pairs-same-in-C1 = sum C(a_i,2), pairs-same-in-C2 =
sum C(b_j,2) — O(N) instead of O(sum of squared community sizes).

Gini coefficient of the cluster-size distribution replicates
compute_gini_coeff (/root/reference/compare.cpp:260-286).
"""

from __future__ import annotations

import dataclasses

import numpy as np
import scipy.sparse as sp


@dataclasses.dataclass
class CompareResult:
    n_vertices: int
    n_truth_comms: int
    n_output_comms: int
    true_positive: int   # Same-Same
    false_negative: int  # Same-Diff
    false_positive: int  # Diff-Same
    precision: float
    recall: float
    f_score: float
    gini_truth: float
    gini_output: float

    def report(self) -> str:
        """Formatted like the reference's rank-0 output (compare.cpp:228-246)."""
        return "\n".join([
            "*******************************************",
            "Communities comparison statistics:",
            "*******************************************",
            f"|C1| (truth)       : {self.n_vertices}",
            f"#communities in C1 : {self.n_truth_comms}",
            f"|C2| (output)      : {self.n_vertices}",
            f"#communities in C2 : {self.n_output_comms}",
            "-------------------------------------------",
            f"Same-Same (True positive)  : {self.true_positive}",
            f"Same-Diff (False negative) : {self.false_negative}",
            f"Diff-Same (False positive) : {self.false_positive}",
            "-------------------------------------------",
            f"Precision :  {self.precision:.6f} ({self.precision * 100:.4f})",
            f"Recall    :  {self.recall:.6f} ({self.recall * 100:.4f})",
            f"F-score   :  {self.f_score:.6f}",
            "-------------------------------------------",
            f"Gini coefficient, C1  :  {self.gini_truth:.6f}",
            f"Gini coefficient, C2  :  {self.gini_output:.6f}",
            "*******************************************",
        ])


def _pairs(x: np.ndarray) -> int:
    return int((x.astype(np.int64) * (x.astype(np.int64) - 1) // 2).sum())


def gini_coefficient(sizes: np.ndarray) -> float:
    """compute_gini_coeff (compare.cpp:260-286): sizes sorted ascending,
    G = 2*sum((i+1)*s_i) / (n*sum(s_i)) - (n+1)/n."""
    s = np.sort(np.asarray(sizes, dtype=np.float64))
    n = len(s)
    if n == 0 or s.sum() == 0:
        return 0.0
    num = ((np.arange(1, n + 1)) * s).sum()
    return float(2.0 * num / (n * s.sum()) - (n + 1) / n)


def compare_communities(truth: np.ndarray, output: np.ndarray) -> CompareResult:
    truth = np.asarray(truth, dtype=np.int64)
    output = np.asarray(output, dtype=np.int64)
    assert len(truth) == len(output) and len(truth) > 0
    n = len(truth)
    nc1 = int(truth.max()) + 1
    nc2 = int(output.max()) + 1

    cont = sp.coo_matrix(
        (np.ones(n, dtype=np.int64), (truth, output)), shape=(nc1, nc2)
    ).tocsr()
    tp = _pairs(cont.data)
    sizes1 = np.bincount(truth, minlength=nc1)
    sizes2 = np.bincount(output, minlength=nc2)
    same1 = _pairs(sizes1)
    same2 = _pairs(sizes2)
    fn = same1 - tp
    fp = same2 - tp

    precision = tp / (tp + fp) if (tp + fp) else 0.0
    recall = tp / (tp + fn) if (tp + fn) else 0.0
    f_score = (2.0 * precision * recall / (precision + recall)
               if (precision + recall) else 0.0)
    return CompareResult(
        n_vertices=n,
        n_truth_comms=nc1,
        n_output_comms=nc2,
        true_positive=tp,
        false_negative=fn,
        false_positive=fp,
        precision=precision,
        recall=recall,
        f_score=f_score,
        gini_truth=gini_coefficient(sizes1),
        gini_output=gini_coefficient(sizes2),
    )


def load_ground_truth(path: str, zero_based: bool = False) -> np.ndarray:
    """LFR-format ground truth: one `vertex community` pair per line
    (cf. loadGroundTruthFile, /root/reference/louvain.cpp:3272-3303; 1-based
    community ids unless ``zero_based``)."""
    data = np.loadtxt(path, dtype=np.int64, ndmin=2)
    comm = data[:, 1].copy()
    if not zero_based:
        comm -= 1
    return comm


def write_communities(path: str, communities: np.ndarray) -> None:
    """Write the final `.communities` file: one label per line, vertex order
    (cf. /root/reference/main.cpp:521-550)."""
    np.savetxt(path, np.asarray(communities, dtype=np.int64), fmt="%d")
