"""cuvite_tpu.evaluate"""
