"""ctypes bindings for the native host runtime (native/cuvite_native.cpp).

The native library accelerates the host-side data layer — CSR construction,
R-MAT generation, Vite binary I/O — the role the reference fills with its
C++/MPI loader and generator (/root/reference/distgraph.cpp).  Every entry
point has a bit-identical pure-numpy fallback in the rest of the package, so
the library is an accelerator, never a requirement: ``available()`` gates
every use.

Build: ``make -C native`` at the repo root, or implicitly on first import
(disable with CUVITE_NO_NATIVE=1).
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_LIB = None  # None = not tried; False = unavailable; else CDLL

# Minimum element count for routing through the native library; below this
# the ctypes/copy overhead outweighs the win.  Shared by every dispatch
# site (Graph.from_edges, read/write_vite).
MIN_NATIVE_EDGES = 1 << 16


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _so_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "libcuvite_native.so")


def _try_build() -> bool:
    src_dir = os.path.join(_repo_root(), "native")
    if not os.path.isfile(os.path.join(src_dir, "cuvite_native.cpp")):
        return False
    try:
        r = subprocess.run(["make", "-C", src_dir], capture_output=True,
                           timeout=180)
        return r.returncode == 0
    except (OSError, subprocess.TimeoutExpired):
        return False


def _bind(lib: ctypes.CDLL) -> None:
    i64 = ctypes.c_int64
    u64 = ctypes.c_uint64
    f64 = ctypes.c_double
    p_i64 = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    p_f64 = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
    lib.cv_build_csr.restype = i64
    lib.cv_build_csr.argtypes = [i64, i64, p_i64, p_i64, p_f64,
                                 ctypes.c_int, p_i64, p_i64, p_f64]
    lib.cv_rmat.restype = None
    lib.cv_rmat.argtypes = [ctypes.c_int, i64, u64, f64, f64, f64,
                            p_i64, p_i64]
    lib.cv_vite_header.restype = ctypes.c_int
    lib.cv_vite_header.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                   ctypes.POINTER(i64), ctypes.POINTER(i64)]
    lib.cv_vite_edges.restype = ctypes.c_int
    lib.cv_vite_edges.argtypes = [ctypes.c_char_p, ctypes.c_int, i64, i64,
                                  i64, p_i64, p_f64]
    lib.cv_vite_write.restype = ctypes.c_int
    lib.cv_vite_write.argtypes = [ctypes.c_char_p, ctypes.c_int, i64, i64,
                                  p_i64, p_i64, p_f64]
    lib.cv_balanced_parts.restype = None
    lib.cv_balanced_parts.argtypes = [i64, p_i64, i64, p_i64]
    lib.cv_openmp_threads.restype = ctypes.c_int
    lib.cv_openmp_threads.argtypes = []
    vp = ctypes.c_void_p
    p_u8 = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
    p_i32 = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    p_f32 = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
    lib.cv_build_csr_unit.restype = i64
    lib.cv_build_csr_unit.argtypes = [i64, i64, p_i32, p_i32, ctypes.c_int,
                                      p_i64, p_i32, p_f32]
    lib.cv_build_csr_w32.restype = i64
    lib.cv_build_csr_w32.argtypes = [i64, i64, vp, vp, p_f64, ctypes.c_int,
                                     ctypes.c_int, p_i64, p_i32, p_f32]
    lib.cv_plan_scan.restype = ctypes.c_int
    lib.cv_plan_scan.argtypes = [i64, i64, i64, vp, vp, vp, ctypes.c_int,
                                 ctypes.c_int, p_f64,
                                 ctypes.POINTER(ctypes.c_int)]
    lib.cv_bucket_fill.restype = ctypes.c_int
    lib.cv_bucket_fill.argtypes = [i64, i64, vp, vp, ctypes.c_int,
                                   ctypes.c_int, p_i64, p_i64, p_u8,
                                   ctypes.c_int, p_i64, p_i64,
                                   ctypes.POINTER(vp), ctypes.POINTER(vp),
                                   ctypes.POINTER(vp), ctypes.c_int, i64,
                                   vp, vp, vp]
    lib.cv_coarsen.restype = i64
    lib.cv_coarsen.argtypes = [i64, i64, p_i64, vp, vp, ctypes.c_int,
                               ctypes.c_int, p_i32, p_i64, p_i32, p_f32,
                               ctypes.c_int]
    lib.cv_weighted_degrees.restype = None
    lib.cv_weighted_degrees.argtypes = [i64, p_i64, vp, ctypes.c_int, p_f64]


def _load():
    global _LIB
    if _LIB is not None:
        return _LIB or None
    if os.environ.get("CUVITE_NO_NATIVE"):
        _LIB = False
        return None
    so = _so_path()
    src = os.path.join(_repo_root(), "native", "cuvite_native.cpp")
    stale = (not os.path.isfile(so)
             or (os.path.isfile(src)
                 and os.path.getmtime(src) > os.path.getmtime(so)))
    if stale and not _try_build():
        # Never load a stale library: its output may no longer match the
        # current numpy fallbacks, silently breaking reproducibility.
        _LIB = False
        return None
    try:
        lib = ctypes.CDLL(so)
        _bind(lib)
        _LIB = lib
    except (OSError, AttributeError):
        # AttributeError: a library built from older sources (but with a
        # newer mtime, e.g. preserved-time copies) lacking newly added
        # symbols — fall back to numpy rather than crash ("accelerator,
        # never a requirement").
        _LIB = False
        return None
    return _LIB


def available() -> bool:
    return _load() is not None


def build_csr(num_vertices: int, src: np.ndarray, dst: np.ndarray,
              weights: np.ndarray, symmetrize: bool = True):
    """Edge list -> coalesced CSR, identical to the numpy path in
    Graph.from_edges.  Returns (offsets, tails[f64 ids], weights[f64])."""
    lib = _load()
    assert lib is not None
    src = np.ascontiguousarray(src, dtype=np.int64)
    dst = np.ascontiguousarray(dst, dtype=np.int64)
    w = np.ascontiguousarray(weights, dtype=np.float64)
    cap = 2 * len(src) if symmetrize else len(src)
    cap = max(cap, 1)
    offsets = np.empty(num_vertices + 1, dtype=np.int64)
    tails = np.empty(cap, dtype=np.int64)
    wout = np.empty(cap, dtype=np.float64)
    n = lib.cv_build_csr(num_vertices, len(src), src, dst, w,
                         int(symmetrize), offsets, tails, wout)
    if n < 0:
        raise ValueError("edge endpoint out of range")
    return offsets, tails[:n].copy(), wout[:n].copy()


def build_csr_unit(num_vertices: int, src: np.ndarray, dst: np.ndarray,
                   symmetrize: bool = True):
    """Unit-weight edge list -> coalesced CSR with int32 ids and f32
    duplicate counts as weights — no f64 array exists at any point
    (identical output to build_csr with all-ones weights after the policy
    cast; see cv_build_csr_unit).  Requires num_vertices <= 2^31."""
    lib = _load()
    assert lib is not None
    src = np.ascontiguousarray(src, dtype=np.int32)
    dst = np.ascontiguousarray(dst, dtype=np.int32)
    cap = max(2 * len(src) if symmetrize else len(src), 1)
    offsets = np.empty(num_vertices + 1, dtype=np.int64)
    tails = np.empty(cap, dtype=np.int32)
    wout = np.empty(cap, dtype=np.float32)
    n = lib.cv_build_csr_unit(num_vertices, len(src), src, dst,
                              int(symmetrize), offsets, tails, wout)
    if n < 0:
        raise ValueError("edge endpoint out of range")
    return offsets, tails[:n].copy(), wout[:n].copy()


def build_csr_w(num_vertices: int, src: np.ndarray, dst: np.ndarray,
                w: np.ndarray, symmetrize: bool = True):
    """Weighted edge list -> coalesced CSR with int32 tails and f32
    weights at a ~24 B/slot sort transient (vs the generic path's 32),
    by sorting an int32 original-edge-index payload and gathering f64
    weights only at the linear coalesce (see cv_build_csr_w32 — output
    identical to build_csr + f32 policy cast).  Requires
    num_vertices <= 2^31 and expanded edge count < 2^31."""
    lib = _load()
    assert lib is not None
    src = np.ascontiguousarray(src)
    dst = np.ascontiguousarray(dst)
    if src.dtype != dst.dtype or src.dtype not in (np.int32, np.int64):
        src = np.ascontiguousarray(src, dtype=np.int64)
        dst = np.ascontiguousarray(dst, dtype=np.int64)
    w = np.ascontiguousarray(w, dtype=np.float64)
    cap = max(2 * len(src) if symmetrize else len(src), 1)
    # Validate BEFORE allocating the outputs: at ne near 2^31 the arrays
    # below are ~16 GB, and the native call would only then reject the
    # sizes with one conflated error.
    if num_vertices > (1 << 31):
        raise ValueError(
            f"build_csr_w: num_vertices={num_vertices} exceeds the int32 "
            f"tail id space (2^31); use the generic build_csr path")
    if cap >= (1 << 31):
        raise ValueError(
            f"build_csr_w: expanded edge count {cap} exceeds the int32 "
            f"index payload (2^31); use the generic build_csr path")
    offsets = np.empty(num_vertices + 1, dtype=np.int64)
    tails = np.empty(cap, dtype=np.int32)
    wout = np.empty(cap, dtype=np.float32)
    n = lib.cv_build_csr_w32(num_vertices, len(src), _vp(src), _vp(dst),
                             w, int(src.dtype == np.int64),
                             int(symmetrize), offsets, tails, wout)
    if n < 0:
        raise ValueError("build_csr_w: edge endpoint out of range")
    return offsets, tails[:n].copy(), wout[:n].copy()


def rmat_edges(scale: int, ne: int, seed: int, a: float, b: float, c: float):
    """Counter-based R-MAT edge list (SplitMix64; bit-identical to the numpy
    fallback in cuvite_tpu.io.generate)."""
    lib = _load()
    assert lib is not None
    src = np.empty(ne, dtype=np.int64)
    dst = np.empty(ne, dtype=np.int64)
    lib.cv_rmat(scale, ne, seed, a, b, c, src, dst)
    return src, dst


def vite_header(path: str, bits64: bool):
    lib = _load()
    assert lib is not None
    nv = ctypes.c_int64()
    ne = ctypes.c_int64()
    rc = lib.cv_vite_header(path.encode(), int(bits64),
                            ctypes.byref(nv), ctypes.byref(ne))
    if rc != 0:
        raise ValueError(f"{path}: cannot read Vite header (rc={rc})")
    return int(nv.value), int(ne.value)


def vite_edges(path: str, bits64: bool, nv: int, e0: int, e1: int):
    """Edge records [e0, e1): one sequential read + parallel deinterleave
    into (tails, weights).  Offsets come from the caller (already read and
    validated by read_vite)."""
    lib = _load()
    assert lib is not None
    tails = np.empty(max(e1 - e0, 1), dtype=np.int64)
    weights = np.empty(max(e1 - e0, 1), dtype=np.float64)
    rc = lib.cv_vite_edges(path.encode(), int(bits64), nv, e0, e1, tails,
                           weights)
    if rc != 0:
        raise ValueError(f"{path}: edge read failed (rc={rc})")
    return tails[: e1 - e0], weights[: e1 - e0]


def vite_write(path: str, bits64: bool, offsets: np.ndarray,
               tails: np.ndarray, weights: np.ndarray) -> None:
    lib = _load()
    assert lib is not None
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    tails = np.ascontiguousarray(tails, dtype=np.int64)
    weights = np.ascontiguousarray(weights, dtype=np.float64)
    rc = lib.cv_vite_write(path.encode(), int(bits64), len(offsets) - 1,
                           len(tails), offsets, tails, weights)
    if rc != 0:
        raise ValueError(f"{path}: write failed (rc={rc})")


def balanced_parts(offsets: np.ndarray, nparts: int) -> np.ndarray:
    lib = _load()
    assert lib is not None
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    parts = np.empty(nparts + 1, dtype=np.int64)
    lib.cv_balanced_parts(len(offsets) - 1, offsets, nparts, parts)
    return parts


def _vp(a: np.ndarray):
    return ctypes.c_void_p(a.ctypes.data)


def _mem_available_bytes():
    """Effective available memory: min of Linux MemAvailable and the
    cgroup limit headroom (a container's cgroup cap binds long before
    host-wide MemAvailable does).  None when neither is readable."""
    avail = None
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    avail = int(line.split()[1]) * 1024
                    break
    except (OSError, ValueError, IndexError):
        pass
    # cgroup v2 (memory.max) then v1 (memory.limit_in_bytes): limit minus
    # current usage, ignored when unlimited ("max" / huge sentinel).  In a
    # nested cgroup without a cgroup namespace the process's own limit
    # lives under the subpath from /proc/self/cgroup, so probe every
    # ancestor of that path down to the mount root (ADVICE r4).
    v2_paths = ["/sys/fs/cgroup/memory.max"]
    v1_paths = ["/sys/fs/cgroup/memory/memory.limit_in_bytes"]
    try:
        with open("/proc/self/cgroup") as f:
            for line in f:
                hid, ctrl, path = line.rstrip("\n").split(":", 2)
                path = path.strip("/")
                parts = path.split("/") if path else []
                sub = [
                    "/".join(parts[:i]) for i in range(len(parts), 0, -1)
                ]
                if hid == "0" and not ctrl:  # v2 unified
                    v2_paths[:0] = [
                        f"/sys/fs/cgroup/{s}/memory.max" for s in sub]
                elif "memory" in ctrl.split(","):
                    v1_paths[:0] = [
                        f"/sys/fs/cgroup/memory/{s}/memory.limit_in_bytes"
                        for s in sub]
    except (OSError, ValueError):
        pass
    probes = [(p, p[: -len("memory.max")] + "memory.current")
              for p in v2_paths]
    probes += [(p, p[: -len("memory.limit_in_bytes")]
                + "memory.usage_in_bytes") for p in v1_paths]
    for lim_path, cur_path in probes:
        try:
            with open(lim_path) as f:
                raw = f.read().strip()
            if raw == "max":
                continue
            limit = int(raw)
            if limit >= (1 << 60):  # v1 "unlimited" sentinel
                continue
            with open(cur_path) as f:
                used = int(f.read().strip())
            head = max(limit - used, 0)
            # The binding limit is the MIN over every level that has one.
            avail = head if avail is None else min(avail, head)
        except (OSError, ValueError):
            continue
    return avail


def coarsen_csr(offsets: np.ndarray, tails: np.ndarray, weights: np.ndarray,
                labels: np.ndarray, nc: int):
    """Fused relabel + coalesce of a CSR graph into its community graph
    (see cv_coarsen).  Returns (offsets[i64], tails[i32], weights[f32]);
    requires nc <= 2^31.  Bit-identical to relabel + Graph.from_edges
    (symmetrize=False, f32 weight policy).

    Path choice for nc > 2^22 (below that the dense path always wins):
    the LSD radix's ping-pong transient is 32 B/slot; when that exceeds
    half of MemAvailable, the 12 B/slot counting+dense path is forced so
    benchmark-scale phase-0 coarsens cannot OOM (both paths are
    bit-identical; CUVITE_COARSEN_FORCE=dense|radix overrides)."""
    lib = _load()
    assert lib is not None
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    tails = np.ascontiguousarray(tails)
    assert tails.dtype in (np.int32, np.int64), tails.dtype
    weights = np.ascontiguousarray(weights)
    if weights.dtype not in (np.float32, np.float64):
        weights = weights.astype(np.float32)
    labels = np.ascontiguousarray(labels, dtype=np.int32)
    force_dense = 0
    if nc > (1 << 22):
        knob = os.environ.get("CUVITE_COARSEN_FORCE", "")
        if knob == "dense":
            force_dense = 1
        elif knob != "radix":
            avail = _mem_available_bytes()
            if avail is not None and 32 * len(tails) > avail // 2:
                force_dense = 1
    cap = max(len(tails), 1)
    offsets_out = np.empty(nc + 1, dtype=np.int64)
    tails_out = np.empty(cap, dtype=np.int32)
    wout = np.empty(cap, dtype=np.float32)
    n = lib.cv_coarsen(len(offsets) - 1, nc, offsets, _vp(tails),
                       _vp(weights), int(tails.dtype == np.int64),
                       int(weights.dtype == np.float64), labels,
                       offsets_out, tails_out, wout, force_dense)
    if n < 0:
        raise ValueError("cv_coarsen: label out of range or nc > 2^31")
    return offsets_out, tails_out[:n].copy(), wout[:n].copy()


def weighted_degrees(offsets: np.ndarray, weights: np.ndarray) -> np.ndarray:
    """Per-vertex f64 weighted degree off the CSR (see cv_weighted_degrees);
    bit-identical to np.bincount(sources, weights=w.astype(f64))."""
    lib = _load()
    assert lib is not None
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    weights = np.ascontiguousarray(weights)
    if weights.dtype not in (np.float32, np.float64):
        weights = weights.astype(np.float64)
    out = np.empty(len(offsets) - 1, dtype=np.float64)
    lib.cv_weighted_degrees(len(offsets) - 1, offsets, _vp(weights),
                            int(weights.dtype == np.float64), out)
    return out


def plan_scan(src, dst, w, nv: int, base: int):
    """One fused pass over an edge slab: (self_loop[f64 nv], sorted, unit,
    tail_padding_ok).  src/dst must share an int32/int64 dtype; w is
    float32/float64 (see cv_plan_scan)."""
    lib = _load()
    assert lib is not None
    self_loop = np.zeros(nv, dtype=np.float64)
    flags = ctypes.c_int(0)
    rc = lib.cv_plan_scan(
        len(src), nv, base, _vp(src), _vp(dst), _vp(w),
        int(src.dtype == np.int64), int(w.dtype == np.float64),
        self_loop, ctypes.byref(flags))
    if rc != 0:
        raise ValueError(f"cv_plan_scan failed (rc={rc})")
    f = flags.value
    return self_loop, bool(f & 1), bool(f & 2), bool(f & 4)


def bucket_fill(dst, w, nv: int, base: int, row_start, deg, cls,
                widths_kept, nb_pad, verts_list, dmat_list, wmat_list,
                unit: bool, heavy_pad: int, hsrc, hdst, hw) -> None:
    """Stream the CSR-ordered slab into pre-allocated bucket matrices and
    heavy triples (see cv_bucket_fill; caller pre-fills all padding)."""
    lib = _load()
    assert lib is not None
    n = len(widths_kept)
    mk = lambda arrs: (ctypes.c_void_p * max(n, 1))(  # noqa: E731
        *[a.ctypes.data for a in arrs], *([0] * (max(n, 1) - len(arrs))))
    rc = lib.cv_bucket_fill(
        nv, base, _vp(dst), _vp(w),
        int(dst.dtype == np.int64), int(w.dtype == np.float64),
        row_start, deg, cls, n,
        np.ascontiguousarray(widths_kept, dtype=np.int64),
        np.ascontiguousarray(nb_pad, dtype=np.int64),
        mk(verts_list), mk(dmat_list), mk(wmat_list),
        int(unit), heavy_pad, _vp(hsrc), _vp(hdst), _vp(hw))
    if rc != 0:
        raise ValueError(f"cv_bucket_fill failed (rc={rc})")
