"""Command-line driver: the equivalent of the `graphClustering` binary.

Collapses the reference's getopt flags + ~25 compile-time macros
(/root/reference/main.cpp:587-712, README:54-102) into one typed config.
Flag parity (reference -> here):

    -f FILE   -> --file FILE          (Vite binary input)
    -b        -> --balanced           (edge-balanced vertex partition)
    -c NC     -> --coloring NC        (distance-1 coloring, phase 0)
    -d NC     -> --vertex-ordering NC (color-based vertex ordering)
    -o        -> --output             (write .communities file)
    -t TYPE   -> --early-term TYPE    (1-4)
    -a ALPHA  -> --et-delta ALPHA     (probability decay, modes 2/4)
    -i        -> --threshold-cycling
    -g FILE   -> --ground-truth FILE  (LFR format comparison; 1-based ids
                 by default, pass --gt-zero-based for 0-based truth files —
                 the reference's -z flag flips the same offset,
                 main.cpp:627-629)
    -p        -> --one-phase
    -n NV     -> --generate NV        (in-memory RGG)
    -e PCT    -> --random-edges PCT
    -s FILE   -> --write-graph FILE   (save generated graph)
    -j        -> --just-process       (load/generate only, no clustering)
    USE_32_BIT_GRAPH -> --bits64 / default 32-bit
    nprocs    -> --shards N           (device mesh size)

Run: python -m cuvite_tpu.cli --file karate.bin --output
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cuvite-tpu",
        description="TPU-native distributed Louvain community detection",
    )
    src = p.add_argument_group("input")
    src.add_argument("--file", "-f", help="Vite binary graph file")
    src.add_argument("--bits64", action="store_true",
                     help="64-bit vertex ids / double weights in the file")
    src.add_argument("--dist-ingest", action="store_true",
                     help="per-host sharded ingest: each process range-reads "
                          "only its shards' edges (the MPI-IO per-rank "
                          "slice analog, distgraph.cpp:69-203); requires "
                          "--file and the bucketed/pallas engines")
    src.add_argument("--generate", "-n", type=int, metavar="NV",
                     help="generate an in-memory RGG with NV vertices")
    src.add_argument("--rmat", type=int, metavar="SCALE",
                     help="generate an R-MAT graph with 2^SCALE vertices")
    src.add_argument("--edge-factor", type=int, default=16)
    src.add_argument("--random-edges", "-e", type=int, default=0, metavar="PCT",
                     help="percent extra random edges for generated graphs")
    src.add_argument("--seed", type=int, default=1)
    src.add_argument("--write-graph", "-s", metavar="FILE",
                     help="write the generated graph in Vite binary format")

    rt = p.add_argument_group("runtime")
    rt.add_argument("--platform", choices=["cpu", "tpu", "axon"],
                    default=None,
                    help="pin the jax backend (e.g. cpu on a TPU-attached "
                         "host whose device tunnel is unavailable; plugin "
                         "registration otherwise overrides JAX_PLATFORMS)")

    dist = p.add_argument_group("distributed (multi-host)")
    dist.add_argument("--distributed", action="store_true",
                      help="connect this process to a multi-host run via "
                           "jax.distributed.initialize (MPI_Init analog, "
                           "main.cpp:67-70); every host runs the same "
                           "command")
    dist.add_argument("--coordinator", metavar="HOST:PORT",
                      help="coordinator address (default: "
                           "$CUVITE_COORDINATOR, else auto-detect on "
                           "Cloud TPU)")
    dist.add_argument("--num-processes", type=int,
                      help="total process count (default: "
                           "$CUVITE_NUM_PROCESSES or auto)")
    dist.add_argument("--process-id", type=int,
                      help="this process's rank (default: "
                           "$CUVITE_PROCESS_ID or auto)")

    run = p.add_argument_group("clustering")
    run.add_argument("--shards", type=int, default=1,
                     help="number of mesh devices (vertex shards)")
    run.add_argument("--mesh", metavar="DCNxICI",
                     help="2-D hybrid mesh 'dcn x ici' (e.g. 2x4) for the "
                          "two-level exchange: community tables replicate "
                          "only inside each fast ICI group, cross-group "
                          "traffic rides the sparse ghost protocol on the "
                          "slow DCN axis; 1xN is bit-compatible with "
                          "--shards N (auto = flat when dcn == 1)")
    run.add_argument("--balanced", "-b", action="store_true",
                     help="edge-balanced partition")
    run.add_argument("--threshold", type=float, default=1e-6)
    run.add_argument("--threshold-cycling", "-i", action="store_true")
    run.add_argument("--one-phase", "-p", action="store_true")
    run.add_argument("--early-term", "-t", type=int, choices=[1, 2, 3, 4],
                     help="early termination mode")
    run.add_argument("--et-delta", "-a", type=float, default=0.25)
    run.add_argument("--coloring", "-c", type=int, metavar="NC",
                     help="distance-1 coloring with NC max colors")
    run.add_argument("--vertex-ordering", "-d", type=int, metavar="NC",
                     help="color-based vertex ordering with NC max colors")
    run.add_argument("--engine", default="auto",
                     choices=["auto", "sort", "bucketed", "pallas", "fused"],
                     help="execution engine (auto = degree-bucketed)")
    run.add_argument("--exchange", default="auto",
                     choices=["auto", "sparse", "replicated", "twolevel"],
                     help="SPMD community exchange: 'sparse' = per-phase "
                          "ghost routing, O(owned+ghosts)/iteration (the "
                          "fillRemoteCommunities analog); 'replicated' = "
                          "all_gather of the full community vector; "
                          "'twolevel' = ICI-group tables + DCN ghost "
                          "routing (requires --mesh with dcn > 1); 'auto' "
                          "picks by graph size per phase")
    run.add_argument("--checkpoint-dir", metavar="DIR",
                     help="save inter-phase state after each phase "
                          "(the reference has no mid-run persistence)")
    run.add_argument("--resume", action="store_true",
                     help="resume from the latest checkpoint in "
                          "--checkpoint-dir")

    out = p.add_argument_group("output")
    out.add_argument("--output", "-o", action="store_true",
                     help="write <input>.communities")
    out.add_argument("--ground-truth", "-g", metavar="FILE",
                     help="compare against LFR ground truth")
    out.add_argument("--gt-zero-based", action="store_true",
                     help="ground-truth community ids start at 0")
    out.add_argument("--just-process", "-j", action="store_true")
    out.add_argument("--json", action="store_true",
                     help="emit a machine-readable summary line")
    out.add_argument("--trace", action="store_true",
                     help="print a stage-time breakdown, TEPS and RSS "
                          "high-water (the reference's per-stage "
                          "MPI_Wtime/getrusage instrumentation)")
    out.add_argument("--dist-stats", action="store_true",
                     help="print graph edge-distribution characteristics "
                          "(the reference's PRINT_DIST_STATS block, "
                          "distgraph.hpp:100-149)")
    out.add_argument("--diag-prefix", metavar="PREFIX",
                     help="write per-shard diagnostic files PREFIX.<shard> "
                          "(the reference's dat.out.<rank> streams, "
                          "main.cpp:101-110)")
    out.add_argument("--trace-out", metavar="FILE.jsonl",
                     help="write the flight recorder's structured "
                          "span/event trace as JSONL (see "
                          "OBSERVABILITY.md for the schema)")
    out.add_argument("--metrics-out", metavar="FILE.json",
                     help="write a machine-readable metrics summary: "
                          "per-phase convergence curves, stage times, "
                          "XLA compile events, HBM peaks")
    out.add_argument("--profile-dir", metavar="DIR",
                     help="capture a jax.profiler trace + device-memory "
                          "profile of the run under DIR (TensorBoard "
                          "format; allocator truth complementing the "
                          "flight recorder's logical HBM ledger)")
    out.add_argument("--quiet", action="store_true")
    return p


def validate(args) -> None:
    if not args.file and args.generate is None and args.rmat is None:
        raise SystemExit("Must specify --file, --generate or --rmat")
    if args.random_edges and args.generate is None:
        raise SystemExit("--random-edges requires --generate")
    if args.coloring and args.vertex_ordering:
        raise SystemExit("Cannot enable both --coloring and --vertex-ordering")
    if args.one_phase and args.threshold_cycling:
        raise SystemExit("Cannot combine --one-phase with --threshold-cycling")
    if args.early_term in (2, 4) and not (0.0 <= args.et_delta <= 1.0):
        raise SystemExit("--et-delta must be in [0, 1]")
    if args.resume and not args.checkpoint_dir:
        raise SystemExit("--resume requires --checkpoint-dir")
    if args.mesh:
        try:
            d, _, i = args.mesh.lower().replace("×", "x").partition("x")
            dcn, ici = int(d), int(i)
        except ValueError:
            raise SystemExit(f"--mesh must be DCNxICI (e.g. 2x4), "
                             f"got {args.mesh!r}")
        if dcn < 1 or ici < 1:
            raise SystemExit("--mesh factors must be >= 1")
        if args.shards not in (1, dcn * ici):
            raise SystemExit(f"--shards {args.shards} conflicts with "
                             f"--mesh {args.mesh} ({dcn * ici} devices)")
        if dcn > 1:
            if args.coloring or args.vertex_ordering:
                raise SystemExit("--mesh with dcn > 1 (two-level exchange) "
                                 "is incompatible with --coloring/"
                                 "--vertex-ordering")
            if args.engine in ("sort", "fused"):
                raise SystemExit("--mesh with dcn > 1 requires the "
                                 "bucketed/pallas engines")
            if args.dist_ingest:
                raise SystemExit("--mesh with dcn > 1 does not support "
                                 "--dist-ingest yet")
            if args.exchange == "replicated":
                raise SystemExit("--mesh with dcn > 1 runs the two-level "
                                 "exchange; --exchange replicated needs a "
                                 "flat mesh")
    elif args.exchange == "twolevel":
        raise SystemExit("--exchange twolevel requires --mesh DCNxICI "
                         "with dcn > 1")
    if args.dist_ingest:
        if not args.file:
            raise SystemExit("--dist-ingest requires --file")
        if args.engine not in ("auto", "bucketed", "pallas"):
            raise SystemExit("--dist-ingest supports only the "
                             "bucketed/pallas engines")
        if (args.coloring or args.vertex_ordering or args.checkpoint_dir
                or args.write_graph):
            raise SystemExit("--dist-ingest is incompatible with "
                             "--coloring/--vertex-ordering/--checkpoint-dir/"
                             "--write-graph (they need the full graph on "
                             "every host)")
    if args.checkpoint_dir and args.one_phase:
        raise SystemExit("--checkpoint-dir is incompatible with --one-phase")


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    validate(args)

    if args.platform:
        # Before any jax backend touch.  A JAX_PLATFORMS env var is NOT
        # enough here: an out-of-tree PJRT plugin registered from
        # sitecustomize (e.g. the axon TPU tunnel) overrides it, and a
        # wedged tunnel hangs backend init indefinitely — this flag is the
        # reliable way to pin the cpu backend on a TPU-attached host.
        import jax

        jax.config.update("jax_platforms", args.platform)

    if args.distributed:
        # Before any jax backend touch: after this, jax.devices() is the
        # GLOBAL device list across all hosts and --shards may span it.
        from cuvite_tpu.comm.multihost import initialize

        initialize(coordinator=args.coordinator,
                   num_processes=args.num_processes,
                   process_id=args.process_id)
        import jax

        if jax.process_index() != 0:
            # Output and chatter are rank-0's job (the reference gates its
            # output/report paths on me == 0, main.cpp:363-406, :521-559);
            # every process still computes the identical result.  File
            # writers must also be gated or hosts sharing a filesystem
            # would write the same paths concurrently.
            args.quiet = True
            args.output = False
            args.json = False
            args.ground_truth = None
            args.trace = False
            args.dist_stats = False
            args.diag_prefix = None
            args.write_graph = None
            args.trace_out = None
            args.metrics_out = None
            args.profile_dir = None

    from cuvite_tpu.core.graph import Graph  # noqa: F401 (re-export context)
    from cuvite_tpu.evaluate.compare import (
        compare_communities, load_ground_truth, write_communities,
    )
    from cuvite_tpu.evaluate.modularity import modularity_gated
    from cuvite_tpu.io.generate import generate_rgg, generate_rmat
    from cuvite_tpu.io.vite import read_vite, write_vite
    from cuvite_tpu.louvain.driver import louvain_phases

    t0 = time.perf_counter()
    if args.file and args.dist_ingest:
        from cuvite_tpu.io.dist_ingest import DistVite

        graph = DistVite.load(args.file, args.shards, bits64=args.bits64,
                              balanced=args.balanced)
        name = args.file
    elif args.file:
        graph = read_vite(args.file, bits64=args.bits64)
        name = args.file
    elif args.rmat is not None:
        graph = generate_rmat(args.rmat, edge_factor=args.edge_factor,
                              seed=args.seed)
        name = f"rmat{args.rmat}"
    else:
        graph = generate_rgg(args.generate, nshards=args.shards,
                             random_edge_percent=args.random_edges,
                             seed=args.seed)
        name = f"rgg{args.generate}"
    load_s = time.perf_counter() - t0
    if not args.quiet:
        print(f"Loaded graph: {graph.num_vertices} vertices, "
              f"{graph.num_edges} directed edges ({load_s:.2f}s)")

    if args.write_graph:
        write_vite(args.write_graph, graph, bits64=args.bits64)
        if not args.quiet:
            print(f"Wrote graph to {args.write_graph}")
    if args.just_process:
        return 0

    from cuvite_tpu.utils.trace import Tracer

    # Flight recorder (ISSUE 6): any of --trace-out / --metrics-out /
    # --profile-dir attaches one; the drivers thread their telemetry
    # through the tracer unconditionally, so a run without these flags
    # pays nothing.
    import contextlib

    recorder = None
    rec_ctx = contextlib.nullcontext()
    if args.trace_out or args.metrics_out or args.profile_dir:
        from cuvite_tpu.obs import NO_TRACE, FlightRecorder, JsonlTraceSink

        # Without --trace-out the recorder serves --metrics-out /
        # --profile-dir only (compile events + HBM ledger): NO_TRACE
        # skips the emitter so no unread span records accumulate.
        sink = JsonlTraceSink(args.trace_out) if args.trace_out else NO_TRACE
        recorder = FlightRecorder(sink, profile_dir=args.profile_dir)
        rec_ctx = recorder

    tracer = Tracer(enabled=args.trace, recorder=recorder)
    with rec_ctx:
        res = louvain_phases(
            graph,
            nshards=args.shards,
            mesh_shape=args.mesh,
            threshold=args.threshold,
            threshold_cycling=args.threshold_cycling,
            one_phase=args.one_phase,
            balanced=args.balanced,
            et_mode=args.early_term or 0,
            et_delta=args.et_delta,
            engine=args.engine,
            exchange=args.exchange,
            coloring=args.coloring or 0,
            vertex_ordering=args.vertex_ordering or 0,
            verbose=not args.quiet,
            tracer=tracer,
            checkpoint_dir=args.checkpoint_dir,
            resume=args.resume,
            dist_stats=args.dist_stats,
            diag_prefix=args.diag_prefix,
        )
    if args.trace:
        print(tracer.report())
    if args.trace_out and not args.quiet:
        print(f"Wrote trace to {args.trace_out}")

    if args.dist_ingest:
        # No process holds the full graph; the driver's distributed f64
        # recompute already produced the reported value.
        q = res.modularity
    else:
        # Size-gated: the dense host oracle only below the O(E)-gather
        # ceiling (VERDICT r5 weak #7); huge graphs report the driver's
        # ds-exact device value instead.
        q, used_oracle = modularity_gated(graph, res.communities,
                                          res.modularity)
        if not used_oracle and not args.quiet:
            print(f"# host modularity oracle skipped: {graph.num_edges} "
                  "edges exceed the O(E) host-gather ceiling "
                  "(CUVITE_HOST_ORACLE_MAX_EDGES); reporting the "
                  "driver's ds-exact device value")
    teps = sum(p.num_edges * p.iterations for p in res.phases) / max(
        sum(p.seconds for p in res.phases), 1e-9)
    if not args.quiet:
        print(f"Final modularity: {q:.6f} "
              f"({res.num_communities} communities, "
              f"{res.total_iterations} iterations, "
              f"{res.total_seconds:.2f}s, TEPS {teps:.3g})")

    if args.output:
        out = name + ".communities"
        write_communities(out, res.communities)
        if not args.quiet:
            print(f"Wrote communities to {out}")

    if args.ground_truth:
        truth = load_ground_truth(args.ground_truth,
                                  zero_based=args.gt_zero_based)
        cmp_res = compare_communities(truth, res.communities)
        print(cmp_res.report())

    summary = {
        "graph": name,
        "nv": graph.num_vertices,
        "ne": graph.num_edges,
        "modularity": q,
        "communities": res.num_communities,
        "iterations": res.total_iterations,
        "phases": len(res.phases),
        "seconds": res.total_seconds,
        "teps": teps,
    }
    if getattr(res, "exchange_stats", None):
        # The SPMD run's exchange arm (ISSUE 18): mode plus — on a
        # two-level run — dcn/ici and the per-device table/ghost bytes;
        # perf_regress keeps flat and two-level records in separate arms
        # on this block.
        xs = res.exchange_stats
        summary["exchange"] = {
            k: xs[k] for k in ("mode", "dcn", "ici",
                               "table_bytes_per_device", "ghost_bytes")
            if k in xs}
    if args.json:
        print(json.dumps(summary))

    if args.metrics_out:
        from cuvite_tpu.utils.trace import rss_high_water_mb

        metrics = dict(summary)
        metrics["stages"] = tracer.breakdown()
        metrics["rss_mb"] = round(rss_high_water_mb(), 1)
        if res.convergence:
            metrics["convergence"] = [pc.to_dict()
                                      for pc in res.convergence]
        if recorder is not None:
            metrics["compile_events"] = recorder.compile_events
            metrics["hbm_peak_by_buffer"] = recorder.ledger.peak_by_buffer
            metrics["hbm_snapshots"] = recorder.ledger.snapshots
        with open(args.metrics_out, "w", encoding="utf-8") as f:
            json.dump(metrics, f, indent=1)
            f.write("\n")
        if not args.quiet:
            print(f"Wrote metrics to {args.metrics_out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
