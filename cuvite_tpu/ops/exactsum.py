"""Scale-safe accumulation on TPU without 64-bit dtypes.

The reference accumulates modularity in C++ double
(/root/reference/louvain.cpp:2433-2481: thread-local double sums + a
2-element MPI_Allreduce of doubles).  TPUs have no native f64, and this
build keeps jax's default 32-bit mode (enabling x64 globally would change
every implicit dtype and double index memory).  At the north-star scale
(2m ~ 8.6e9) plain f32 sums lose ~eps*log(n) ~ 2e-6 relative accuracy —
enough to eat the 1e-6 convergence threshold.

The TPU-native fix is double-single ("ds") arithmetic: a value is carried
as an unevaluated pair (hi, lo) of f32 with |lo| <= ulp(hi)/2, giving
~48 bits of effective mantissa using only IEEE f32 add/mul (Dekker/Knuth
error-free transformations; the classic GPU/TPU f64-emulation technique).
A pairwise ds tree-sum of n addends carries relative error
O(log2(n) * 2^-48) — at n = 2^30 that is ~3e-13, far inside the 1e-9
target — while costing a handful of f32 ops per element, fused by XLA.

Used by the per-phase precise modularity pass
(cuvite_tpu/louvain/precise.py) and — via ``accum_dtype='ds32'``
(segment.DS_ACCUM) — by the per-iteration convergence check itself:
above ``driver.DS_MIN_TOTAL_WEIGHT`` (2m = 2^24) the in-loop
``(mod - prev_mod) < threshold`` test runs on ds pairs with an exact
cross-shard pair reduction (``ds_psum``), because at that scale plain
f32 tree sums can be threshold-wrong (pinned by tests/test_ds_inloop.py).
Below that bound the loop stays plain f32 (|error| ~ 6e-8, well under
every threshold >= 1e-6).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def two_sum(a, b):
    """Knuth TwoSum: s + e == a + b exactly (any magnitudes)."""
    s = a + b
    bb = s - a
    e = (a - (s - bb)) + (b - bb)
    return s, e


def fast_two_sum(a, b):
    """Dekker FastTwoSum: requires |a| >= |b| (or a == 0)."""
    s = a + b
    e = b - (s - a)
    return s, e


def _split(a):
    """Dekker split of f32 into two 12-bit halves (2^12 + 1 = 4097)."""
    c = a * jnp.float32(4097.0)
    hi = c - (c - a)
    return hi, a - hi


def two_prod(a, b):
    """p + e == a * b exactly (barring over/underflow)."""
    p = a * b
    ah, al = _split(a)
    bh, bl = _split(b)
    e = ((ah * bh - p) + ah * bl + al * bh) + al * bl
    return p, e


def ds_add(x, y):
    """(hi, lo) + (hi, lo) -> (hi, lo); error O(2^-48) relative."""
    s, e = two_sum(x[0], y[0])
    e = e + (x[1] + y[1])
    return fast_two_sum(s, e)


def ds_neg(x):
    return (-x[0], -x[1])


def ds_mul(x, y):
    p, e = two_prod(x[0], y[0])
    e = e + (x[0] * y[1] + x[1] * y[0])
    return fast_two_sum(p, e)


def ds_from_f32(a):
    return (a, jnp.zeros_like(a))


def ds_from_f64(value) -> tuple:
    """Host-side split of a python/np float into an exact f32 pair."""
    import numpy as np

    hi = np.float32(value)
    lo = np.float32(np.float64(value) - np.float64(hi))
    return (jnp.float32(hi), jnp.float32(lo))


def ds_to_f64(x) -> float:
    """Host-side combine (call on concrete outputs only)."""
    import numpy as np

    return float(np.float64(np.asarray(x[0], dtype=np.float64))
                 + np.float64(np.asarray(x[1], dtype=np.float64)))


def ds_tree_sum(hi, lo=None):
    """Pairwise ds reduction of a 1-D f32 array (any length; internally
    padded to a power of two with zeros).  Returns a scalar ds pair.

    Error: each level performs one ds_add per surviving pair, so the total
    relative error is O(log2(n) * 2^-48) for same-sign addends.
    """
    n = hi.shape[0]
    if lo is None:
        lo = jnp.zeros_like(hi)
    if n == 0:
        z = jnp.zeros((), dtype=hi.dtype)
        return z, z
    pow2 = 1 << max(int(n - 1).bit_length(), 0)
    if pow2 != n:
        pad = pow2 - n
        hi = jnp.concatenate([hi, jnp.zeros((pad,), dtype=hi.dtype)])
        lo = jnp.concatenate([lo, jnp.zeros((pad,), dtype=lo.dtype)])
    while hi.shape[0] > 1:
        m = hi.shape[0] // 2
        hi, lo = ds_add((hi[:m], lo[:m]), (hi[m:], lo[m:]))
    return hi[0], lo[0]


def ds_psum(pair, axis_name):
    """Exact cross-shard reduction of a scalar ds pair: all_gather the S
    per-shard pairs (S scalars — negligible traffic) and ds-tree-sum them.
    A plain psum of hi/lo parts would re-lose up to S*eps relative — the
    very error the ds formulation removes.

    Both channels ride ONE collective (hi/lo stacked [2]): the R025
    replication audit surfaced this as two separate per-call all_gather
    launches — on the hot ds32 modularity path that is one avoidable
    collective launch per reduction.  Gathers are exact, so the packed
    form is bit-identical to the two-launch one."""
    both = jax.lax.all_gather(jnp.stack([pair[0], pair[1]]), axis_name)  # graftlint: replicated-ok=scope=scalar; O(nshards) ds pairs, not vertex-scaled
    return ds_tree_sum(both[:, 0], both[:, 1])


def ds_segment_sums_sorted(keys, vals, vals_lo=None):
    """Per-run ds sums of ``vals`` (optionally already a ds pair with
    ``vals_lo``) grouped by SORTED ``keys``.

    Returns (run_hi, run_lo, last_mask): arrays of the input length where
    positions flagged by ``last_mask`` hold the ds total of that run
    (other positions are zero).  Uses an inclusive ds prefix scan
    (associative, log-depth) and differences at run boundaries — the
    difference of two monotone ds prefixes keeps absolute error
    O(log n * 2^-48 * total), which is what the modularity a^2 term needs.
    """
    n = keys.shape[0]
    zero = jnp.zeros_like(vals) if vals_lo is None else vals_lo
    p_hi, p_lo = jax.lax.associative_scan(ds_add, (vals, zero))
    idx = jnp.arange(n, dtype=jnp.int32)
    leader = jnp.concatenate(
        [jnp.ones((1,), bool), keys[1:] != keys[:-1]])
    last = jnp.concatenate([keys[1:] != keys[:-1], jnp.ones((1,), bool)])
    run_id = jnp.cumsum(leader.astype(jnp.int32)) - 1
    # start index of each position's run; prefix BEFORE the run = P[start-1]
    start = jax.ops.segment_min(idx, run_id, num_segments=n,
                                indices_are_sorted=True)
    start_i = jnp.take(start, run_id)
    prev = jnp.maximum(start_i - 1, 0)
    prev_hi = jnp.where(start_i > 0, jnp.take(p_hi, prev), 0.0)
    prev_lo = jnp.where(start_i > 0, jnp.take(p_lo, prev), 0.0)
    tot_hi, tot_lo = ds_add((p_hi, p_lo), (-prev_hi, -prev_lo))
    run_hi = jnp.where(last, tot_hi, 0.0)
    run_lo = jnp.where(last, tot_lo, 0.0)
    return run_hi, run_lo, last
