"""cuvite_tpu.ops"""
