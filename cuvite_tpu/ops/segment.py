"""Segment-reduction primitives for edge-parallel graph kernels.

The reference's per-vertex hash maps (distBuildLocalMapCounter,
/root/reference/louvain.cpp:2384-2431) and its GPU dense-scratch dedup kernels
(/root/reference/louvain_cuda.cu:878-1346) both compute the same thing: for
every vertex, the total edge weight into each distinct neighbor community.
On TPU the idiomatic formulation is a lexicographic sort of the edge slab by
``(source vertex, neighbor community)`` followed by run-detection and
segment sums — everything static-shape, everything fused by XLA.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

# CUVITE_DEBUG_BOUNDS is sampled ONCE, at import time: the bound check is
# baked into traced step functions that are cached process-wide
# (driver._STEP_CACHE keys don't include it), so flipping the env var
# after the first compile could never take effect anyway.  Set it before
# importing cuvite_tpu (i.e. before the first compile) or it is ignored.
DEBUG_BOUNDS = os.environ.get("CUVITE_DEBUG_BOUNDS", "0").lower() \
    not in ("", "0", "false")


def segment_sum(data, segment_ids, num_segments, sorted_ids=False):
    return jax.ops.segment_sum(
        data, segment_ids, num_segments=num_segments,
        indices_are_sorted=sorted_ids,
    )


def segment_max(data, segment_ids, num_segments, sorted_ids=False):
    return jax.ops.segment_max(
        data, segment_ids, num_segments=num_segments,
        indices_are_sorted=sorted_ids,
    )


def segment_min(data, segment_ids, num_segments, sorted_ids=False):
    return jax.ops.segment_min(
        data, segment_ids, num_segments=num_segments,
        indices_are_sorted=sorted_ids,
    )


def spmd_env(comm_local, axis_name):
    """Shared SPMD plumbing for the Louvain engines: returns
    ``(comm_full, gsum)`` — the (all_gather'ed) full community vector and the
    cross-shard scalar/array reduction.  Single-shard (``axis_name=None``)
    degenerates to identity."""
    if axis_name is None:
        return comm_local, lambda x: x
    comm_full = jax.lax.all_gather(comm_local, axis_name, tiled=True)  # graftlint: replicated-ok=scope=ici; the replicated exchange's community vector — flat-mesh-only (the hybrid driver rejects exchange='replicated'), so the gather never spans more than one ICI group; the sparse/two-level exchanges are the fix past the cutover
    return comm_full, lambda x: jax.lax.psum(x, axis_name)


# Sentinel accum_dtype selecting double-single (f32-pair) accumulation for
# the in-loop modularity sums — the scale-safe mode for graphs whose 2m
# makes plain f32 reductions eat the 1e-6 convergence threshold (see
# cuvite_tpu/ops/exactsum.py and driver.DS_MIN_TOTAL_WEIGHT).
DS_ACCUM = "ds32"

# Widest edge slab one device call may carry: the run-id/compaction
# cumsums below count slab rows in int32, whose ceiling is 2^31 - 1 —
# and a 2^30-row slab is already ~48 GB of operand HBM, past any single
# chip.  Billion-edge graphs (Friendster's 3.6 B directed rows pad to
# 2^32) MUST arrive pre-sharded into <= SLAB_NE_MAX slabs; the guard
# fails loud instead of wrapping into wrong labels.  widthcheck (R026/
# R028) reads this raise-guard as the eligibility predicate bounding
# ne_pad, and tools/width_audit.py proves the one-past-boundary class
# raises (W002).
SLAB_NE_MAX = 1 << 30


def modularity_terms(counter0, comm_deg, constant, gsum, accum_dtype,
                     axis_name=None):
    """Q = e·c − a²·c² from the per-vertex current-community weights and the
    (already globally reduced) community degrees
    (cf. distComputeModularity, /root/reference/louvain.cpp:2433-2481).

    ``accum_dtype=DS_ACCUM`` accumulates both big reductions in
    double-single f32 pairs (error O(log n * 2^-48) instead of the plain
    tree sum's O(log n * 2^-24)) and collapses to one f32 at the end —
    the in-loop analog of the reference's C++ double accumulation
    (louvain.cpp:2433-2481).  ``axis_name`` is required in SPMD ds mode
    (the cross-shard pair reduction must stay exact; ``gsum`` alone would
    re-lose the low words)."""
    if accum_dtype == DS_ACCUM:
        from cuvite_tpu.ops import exactsum as ds

        le = ds.ds_tree_sum(counter0)
        if axis_name is not None:
            le = ds.ds_psum(le, axis_name)
        # comm_deg is globally replicated after gsum: no cross-shard reduce;
        # square each entry exactly (two_prod) before the pair tree-sum.
        p, e = ds.two_prod(comm_deg, comm_deg)
        la2 = ds.ds_tree_sum(p, e)
        c = ds.ds_from_f32(constant)
        q = ds.ds_add(ds.ds_mul(le, c),
                      ds.ds_neg(ds.ds_mul(la2, ds.ds_mul(c, c))))
        return q[0] + q[1]
    acc = counter0.dtype if accum_dtype is None else accum_dtype
    le_xx = gsum(jnp.sum(counter0.astype(acc)))
    # comm_deg is globally replicated after gsum: no second psum.
    la2_x = jnp.sum(jnp.square(comm_deg.astype(acc)))
    c_acc = constant.astype(acc)
    return le_xx * c_acc - la2_x * c_acc * c_acc


def sort_edges_by_vertex_comm(src, ckey, w, *extras, src_bound=None,
                              key_bound=None):
    """Sort of the edge slab by (src, ckey), stable.

    Returns (src_s, ckey_s, w_s, *extras_s) — any ``extras`` arrays are
    co-sorted as additional payload channels (used by the sparse exchange to
    carry per-slot community degree/size).  Padding edges carry src == nv_pad
    (max segment id) and therefore sort to the tail of the slab.

    With static ``src_bound``/``key_bound`` (exclusive maxima) the two keys
    are packed into ONE integer key ``(src << kbits) | ckey`` — int32 when
    it fits, else int64 — replacing the two-operand lexicographic
    comparator (measured 4-5x faster for the row sorts on TPU).  Equal
    packed keys are exactly equal (src, ckey) pairs and the sort is stable
    either way, so results are bit-identical to the lexicographic path.

    INVARIANT: every src must be < src_bound and every ckey < key_bound,
    or packing corrupts the order (an overflowing ckey bleeds into src's
    bits; at kbits+sbits == 31 the int32 sign bit flips and the row sorts
    to the FRONT).  Callers pass src_bound = nv_local + 1 (padding rows
    carry src == nv_local) and key_bound = nv_total (community ids live in
    padded vertex space).  Set CUVITE_DEBUG_BOUNDS=1 BEFORE the first
    import/compile to verify at runtime (host callback per sort —
    test/debug builds only; the flag is read once at module import into
    DEBUG_BOUNDS, because traced steps are cached process-wide).
    """
    if src_bound is not None and key_bound is not None:
        if DEBUG_BOUNDS:
            def _check(smax, kmax):
                if int(smax) >= int(src_bound) or int(kmax) >= int(key_bound):
                    raise AssertionError(
                        f"packed-sort bound violation: max src {int(smax)} "
                        f"(bound {src_bound}), max ckey {int(kmax)} "
                        f"(bound {key_bound})")

            jax.debug.callback(_check, jnp.max(src), jnp.max(ckey))
        kbits = max(int(key_bound) - 1, 1).bit_length()
        sbits = max(int(src_bound) - 1, 1).bit_length()
        # int64 packing needs jax_enable_x64 (int64 silently degrades to
        # int32 otherwise, corrupting keys); int32 packing is always safe.
        fits32 = kbits + sbits <= 31
        if fits32 or (kbits + sbits <= 63 and jax.config.jax_enable_x64):
            # int64 is legal here BY CONSTRUCTION: the branch above only
            # admits it under jax_enable_x64 (the oracle mode), never in
            # the 32-bit graph mode R003 protects.
            pdt = jnp.int32 if fits32 else jnp.int64  # graftlint: disable=R003
            packed = (src.astype(pdt) << kbits) | ckey.astype(pdt)
            out = jax.lax.sort((packed,) + (w,) + extras, num_keys=1)
            k_s = out[0]
            src_s = (k_s >> kbits).astype(src.dtype)
            ckey_s = (k_s & ((1 << kbits) - 1)).astype(ckey.dtype)
            return (src_s, ckey_s) + tuple(out[1:])
    return jax.lax.sort((src, ckey, w) + extras, num_keys=2)


def sort_edges_msd(src, ckey, w, *, nv_pad):
    """Stable (src, ckey) sort for slab classes whose packed key exceeds
    31 bits: an MSD src-partition as TWO stable int32 single-key sorts,
    replacing the variadic two-operand comparator that
    :func:`sort_edges_by_vertex_comm` degrades to once
    kbits + sbits > 31 (the nv_pad >= 2^16 comparator tax, BASELINE
    round-10).

    Pass 1 sorts by the int32 key ``(src_low << kbits) | ckey`` where
    ``src_low`` keeps the low ``31 - kbits`` bits of src; pass 2 sorts
    the result STABLY by ``src_hi = src >> (31 - kbits)`` alone.  Stable
    composition: pass 2 preserves pass 1's (src_low, ckey) order within
    equal src_hi, so the final order is lexicographic
    (src_hi, src_low, ckey) == (src, ckey) — bit-identical to the
    packed/variadic paths, including run order for ds32 pair sums.
    Padding rows (src == nv_pad, a pow2) have src_low == 0 and the
    maximal src_hi, so they still sort to the tail.

    Classes that fit the single int32 pack delegate to the packed sort
    (one pass beats two); ckey spaces needing >= 31 bits on their own
    (nv_pad >= 2^31 — beyond every slab class) fall back to the
    variadic comparator.
    """
    kbits = max(nv_pad - 1, 1).bit_length()
    sbits = nv_pad.bit_length()  # src_bound = nv_pad + 1 (padding rows)
    if kbits + sbits <= 31:
        return sort_edges_by_vertex_comm(
            src, ckey, w, src_bound=nv_pad + 1, key_bound=nv_pad)
    s_low = 31 - kbits
    if s_low <= 0:
        return jax.lax.sort((src, ckey, w), num_keys=2)
    low_mask = (1 << s_low) - 1
    key1 = (((src.astype(jnp.int32) & low_mask) << kbits)  # graftlint: width-ok=src field masked to s_low = 31 - kbits bits, so key1 < 2^(s_low + kbits) = 2^31 by construction
            | ckey.astype(jnp.int32))
    key1_s, src_1, w_1 = jax.lax.sort(
        (key1, src.astype(jnp.int32), w), num_keys=1)
    ckey_1 = key1_s & ((1 << kbits) - 1)
    hi = src_1 >> s_low
    _, src_s, ckey_s, w_s = jax.lax.sort(
        (hi, src_1, ckey_1, w_1), num_keys=1)
    return (src_s.astype(src.dtype), ckey_s.astype(ckey.dtype), w_s)


def _runs_from_sorted(src_s, ckey_s, w_s, *, nv_pad, accum_dtype):
    """Run detection + run sums + compacted emission over a slab already
    in stable ascending (src, ckey) order — the shared tail of every
    SORTING coalesce engine ('sort', 'msd', and the hash engine's
    collision fallback), so their outputs are bit-identical by
    construction, ds32 pair sums included (equal sorted order => equal
    run segmentation => equal pair arithmetic)."""
    ne_pad = src_s.shape[0]
    if ne_pad > SLAB_NE_MAX:
        raise ValueError(
            f"_runs_from_sorted: slab has {ne_pad} rows, over SLAB_NE_MAX "
            f"= {SLAB_NE_MAX}: the int32 run-id/compaction cumsums "
            f"would overflow (wrong labels, not a crash) — shard the "
            f"slab below the ceiling first")
    wdt = w_s.dtype
    starts = run_starts(src_s, ckey_s)
    run_id = jnp.cumsum(starts.astype(jnp.int32)) - 1
    if accum_dtype == DS_ACCUM:
        # Double-single run sums (ops/exactsum.py): exact integer mass up
        # to ~2^48 — self-loop runs of benchmark-scale communities exceed
        # f32's 2^24 long before they exceed this.  One f32 collapse at
        # the end, like the host oracle's single f64 -> f32 cast.
        from cuvite_tpu.ops import exactsum as ds

        hi, lo, last = ds.ds_segment_sums_sorted(run_id, w_s)
        run_w = (hi + lo).astype(wdt)
    else:
        acc = wdt if accum_dtype is None else accum_dtype
        sums = segment_sum(w_s.astype(acc), run_id,
                           num_segments=ne_pad, sorted_ids=True)
        run_w = jnp.take(sums, run_id).astype(wdt)
        last = jnp.concatenate(
            [(src_s[1:] != src_s[:-1]) | (ckey_s[1:] != ckey_s[:-1]),
             jnp.ones((1,), bool)])

    # Emit one row per run, at the run's LAST position (where the ds sum
    # lives); runs are contiguous, so run order — and hence the compacted
    # output order — is the sorted (src, ckey) order either way.
    emit = last & (src_s < nv_pad)
    n = jnp.sum(emit.astype(jnp.int32))
    pos = jnp.cumsum(emit.astype(jnp.int32)) - 1
    slot = jnp.where(emit, pos, ne_pad)  # non-emitted rows drop
    src_c = jnp.full((ne_pad,), nv_pad, src_s.dtype).at[slot].set(
        src_s, mode="drop")
    ckey_c = jnp.zeros((ne_pad,), ckey_s.dtype).at[slot].set(
        ckey_s, mode="drop")
    w_c = jnp.zeros((ne_pad,), wdt).at[slot].set(run_w, mode="drop")
    return src_c, ckey_c, w_c, n


def coalesced_runs(src, ckey, w, *, nv_pad, accum_dtype=None,
                   engine="sort", interpret=None):
    """Segmented coalesce of an edge slab by (src, ckey): one output row
    per distinct real (src, ckey) pair, rows in ascending (src, ckey)
    order COMPACTED into the slab prefix, duplicate weights summed.

    The ``sort_edges_by_vertex_comm``-shaped entry point of ISSUE 8: same
    (src, ckey, w) operand convention — real ids < ``nv_pad`` (pow2),
    padding rows carry src == nv_pad and w == 0 — but the contract is the
    COALESCED result, not a sorted copy, which frees the engine choice:

    * ``engine='pallas'`` / ``'xla'`` — the dense dst-tile bin-accumulate
      (cuvite_tpu/kernels/seg_coalesce.py): no sorted copy of the slab is
      ever materialized.  Static eligibility (nv_pad within the
      accumulator budget, no ds32) is the CALLER's job via
      ``seg_coalesce.coalesce_engine`` — passing an ineligible class here
      is a bug, not a fallback.
    * ``engine='sort'`` — THE sanctioned packed-sort fallback chokepoint
      (graftlint R013 allows no other full-slab sort in coarsen/ or
      kernels/): stable sort via :func:`sort_edges_by_vertex_comm`
      (src_bound = nv_pad + 1, key_bound = nv_pad), run detection, run
      sums in ``accum_dtype`` (None = weight dtype; ``'ds32'`` =
      double-single pairs collapsed to f32 once), emit at run-last
      positions.  This is bit-for-bit the historical
      device_coarsen_slab coalesce.
    * ``engine='msd'`` — same contract, but the stable (src, ckey) order
      comes from :func:`sort_edges_msd`: two int32 single-key passes for
      the classes where kbits + sbits > 31 degrades the packed sort to
      the variadic comparator (nv_pad >= 2^16).  Shares the run-sum /
      emission tail with 'sort', so it is bit-identical in every mode,
      ds32 included — the drop-in big-class engine.
    * ``engine='hash'`` — hash-slot coalesce
      (kernels/seg_coalesce.py::hash_accumulate): K static slots per
      src, scatter-accumulated in one O(ne) pass, with DEVICE-side
      collision detection; a colliding slab falls back to the
      'msd'-sorted tail inside ``lax.cond`` (no host sync, still
      bit-identical to the sort engines).  Weight sums on the collision-
      free path are in slab (scatter) order — the dense engines'
      exactness domain — so ``accum_dtype`` must be None
      (``coalesce_engine`` routes explicit accumulators to 'msd').

    Returns ``(src_c, ckey_c, w_c, n)``: [ne_pad]-shaped arrays with real
    rows in [0, n) and padding (src == nv_pad, ckey == 0, w == 0) after.
    Dense engines (and the hash engine's collision-free path) sum
    duplicates in slab order, the sorting engines in sorted order —
    bit-identical wherever run sums are exactly representable
    (unit/dyadic weights; the documented exactness domain, see
    kernels/seg_coalesce.py).  ds32 must use a sorting engine
    ('sort' or 'msd').
    """
    ne_pad = src.shape[0]
    if ne_pad > SLAB_NE_MAX:
        raise ValueError(
            f"coalesced_runs: slab has {ne_pad} rows, over SLAB_NE_MAX "
            f"= {SLAB_NE_MAX}: the int32 run-id/compaction cumsums "
            "would overflow (wrong labels, not a crash) — shard the "
            "slab below the ceiling first")
    if engine in ("pallas", "xla"):
        # The dense accumulators sum in the weight dtype only: a caller
        # that requested ANY explicit accumulator (ds32 pairs or a wider
        # plain dtype) must take the sort path — silently narrowing the
        # requested accumulation would diverge from the sort engine
        # outside the exactness domain.  coalesce_engine() enforces the
        # same rule at policy level.
        assert accum_dtype is None, \
            f"accum_dtype={accum_dtype!r} needs the sort engine (dense " \
            "engines accumulate in the weight dtype only)"
        from cuvite_tpu.kernels.seg_coalesce import coalesce_slab

        return coalesce_slab(src, ckey, w, nv_pad=nv_pad, engine=engine,
                             interpret=interpret)

    if engine == "hash":
        # Hash-slot tables sum in the weight dtype (slab order): an
        # explicit accumulator must take a sorting engine —
        # coalesce_engine routes it to 'msd' before it gets here.
        assert accum_dtype is None, \
            f"accum_dtype={accum_dtype!r} needs a sorting engine (the " \
            "hash tables accumulate in the weight dtype only)"
        from cuvite_tpu.kernels.seg_coalesce import (
            hash_accumulate, hash_emit, hash_slots,
        )

        k = hash_slots(nv_pad, ne_pad)
        wsum, cnt, dmin, dmax = hash_accumulate(
            src, ckey, w, nv_pad=nv_pad, k=k)
        # A slot holding two DISTINCT ckeys cannot emit (dmin carries one
        # ckey, wsum both weights): detect ON DEVICE and retry the whole
        # slab through the msd-sorted tail — same (src, ckey) order as
        # the sort engine, so the retry is bit-identical to it.
        collision = jnp.any((cnt > 0) & (dmin != dmax))

        def _retry_sorted(_):
            src_s, ckey_s, w_s = sort_edges_msd(src, ckey, w,
                                                nv_pad=nv_pad)
            return _runs_from_sorted(src_s, ckey_s, w_s, nv_pad=nv_pad,
                                     accum_dtype=None)

        def _emit_hash(_):
            return hash_emit(wsum, cnt, dmin, nv_pad=nv_pad,
                             ne_pad=ne_pad, k=k, src_dtype=src.dtype,
                             ckey_dtype=ckey.dtype)

        return jax.lax.cond(collision, _retry_sorted, _emit_hash, 0)

    if engine == "msd":
        src_s, ckey_s, w_s = sort_edges_msd(src, ckey, w, nv_pad=nv_pad)
    else:
        # Sanctioned sort fallback: stable (src, ckey) order through the
        # packed-key machinery; dense ids are < nv_pad, padding
        # src == nv_pad sorts to the tail.
        src_s, ckey_s, w_s = sort_edges_by_vertex_comm(
            src, ckey, w, src_bound=nv_pad + 1, key_bound=nv_pad)
    return _runs_from_sorted(src_s, ckey_s, w_s, nv_pad=nv_pad,
                             accum_dtype=accum_dtype)


def run_starts(src_s, ckey_s):
    """Boolean mask marking the first edge of every (src, comm) run in a
    sorted slab."""
    first = jnp.ones((1,), dtype=bool)
    changed = (src_s[1:] != src_s[:-1]) | (ckey_s[1:] != ckey_s[:-1])
    return jnp.concatenate([first, changed])


def run_totals(w_s, starts):
    """Per-edge total weight of the (src, comm) run each edge belongs to.

    At run-start positions this is e_{i->c}, the aggregated weight from vertex
    i to community c — the value the reference stores in ``counter``
    (/root/reference/louvain.cpp:2419-2427).
    """
    ne_pad = w_s.shape[0]
    if ne_pad > SLAB_NE_MAX:
        raise ValueError(
            f"run_totals: slab has {ne_pad} rows, over SLAB_NE_MAX = "
            f"{SLAB_NE_MAX}: the int32 run-id cumsum would overflow")
    run_id = jnp.cumsum(starts.astype(jnp.int32)) - 1
    totals = segment_sum(w_s, run_id, num_segments=w_s.shape[0], sorted_ids=True)
    return jnp.take(totals, run_id), run_id
