"""Pallas TPU kernel: dst-community-tile binned segmented-coalesce for
the inter-phase relabel+coalesce (the device-coarsening sort tax,
ROADMAP open item 4 / ISSUE 8).

Role.  ``coarsen/device.py::device_coarsen_slab`` must turn the
relabeled edge slab (dense endpoint ids < nc, padding src == nv_pad)
into one row per distinct (src, dst) pair, rows in ascending (src, dst)
order compacted into the slab prefix, duplicate weights summed.  The
historical workhorse is a full-slab packed sort + run detection
(ops/segment.py) — and at benchmark scale the (src, dst) key needs
2*log2(nv_pad) > 31 bits, so the int32 packing cannot engage and the
sort degrades to XLA's slowest variadic comparator path: the measured
65 s coarsen_s of BASELINE.md round-7.  GPU Louvain implementations do
this aggregation step by BINNING, not sorting (Naim et al.,
arXiv:1805.10904 bin neighbor weights by community; the shared-memory
line treats aggregation as the dominant phase once moves are fast,
Staudt & Meyerhenke, arXiv:1304.4453).

This module is the TPU translation, same community-range-tile idea as
``heavy_bincount``: the (src, dst) key domain is a dense [nv_pad,
nv_pad] grid; tile the DST RANGE into [t*C, (t+1)*C) slices whose
[nv_pad, C] accumulator fits VMEM, scan the slab once per tile, and
bin-accumulate (weight sum + run presence count) — ascending flat index
order over the accumulator IS the sorted (src, dst) run order, so the
coalesced prefix is emitted directly with one cumsum + scatter and no
sorted copy of the slab ever exists.

Three engines, selected STATICALLY per slab class (``coalesce_engine``):

* ``'pallas'`` — the tile kernel below (``seg_coalesce_pallas``).
  Interpret-proven on CPU; the chip A/B is staged in tools/heavy_ab.py
  + tpu_ladder3.py (the same built-then-chip-proven path
  kernels/heavy_bincount.py and tools/heavy_kernel_design.md took).
* ``'xla'`` — the bit-identical XLA twin (``seg_coalesce_xla``): the
  same dense bin-accumulate as ONE O(ne) scatter-add over the flat key
  domain.  Compiles on every backend; the cheap cross-engine parity
  oracle, and the non-Pallas dense candidate for the chip A/B.
* ``'sort'`` — the sanctioned packed-sort fallback chokepoint
  (ops/segment.py::coalesced_runs), and the DEFAULT until the staged
  chip A/B promotes a dense engine (see ``coalesce_engine`` for the
  measured CPU rationale).  Slab classes whose key domain exceeds the
  accumulator budget (nv_pad > SEG_COALESCE_MAX_NV), and every ds32
  run-sum request (the pair arithmetic needs the sorted segmented
  form), degrade here in every mode — with coverage reported in the
  bench record (``coalesce_kernel``), mirroring the PALLAS_MAX_WIDTH
  degrade-with-coverage pattern.

Exactness.  The dense engines sum duplicate weights in SLAB order
(scatter order), the sort path in sorted-run order; the two are
bit-identical wherever run sums are exactly representable — unit and
dyadic weights, the same documented exactness domain as the host-f64
oracle contract in coarsen/device.py.  Run PRESENCE (the emitted row
set, hence offsets/tails) is exact in every mode, including real
zero-weight edges (counted by presence, never by weight).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128
# dst communities per grid tile: the [nv_pad, C] f32+i32 accumulator pair
# must sit well under v5e VMEM (~16 MB) at the widest eligible class —
# the kernel shrinks C so nv_pad * C never exceeds this element budget
# (8 MiB for the pair), whatever CUVITE_SEG_COALESCE_MAX_NV allows.
ACC_BLOCK_ELEMS = 1 << 20
DEFAULT_C_TILE = 256
# edge slots scanned per inner grid step.
DEFAULT_E_CHUNK = 8192
assert (4096 * DEFAULT_C_TILE * 8) <= (12 << 20)
assert 4096 * DEFAULT_C_TILE == ACC_BLOCK_ELEMS  # default class: no shrink

# Widest slab class the dense accumulator covers: the flat key domain is
# nv_pad^2 slots (f32 + i32), i.e. 128 MiB at the 4096 default — late
# coarsened phases, where the reference's own cost model says binning
# wins (tools/heavy_kernel_design.md).  Raising it quadruples the
# accumulator per step.
DEFAULT_MAX_NV = 4096

# Hard ceiling on the dense engines' vertex space: the flat (src, dst)
# key is packed as (src << kbits) | dst in int32, so 2 * kbits must
# stay <= 31 — nv_pad <= 2^15, a 2^30-slot flat domain.  This is the
# same number _env_max_nv caps CUVITE_SEG_COALESCE_MAX_NV at, restated
# as a fail-loud raise-guard so a caller bypassing coalesce_engine can
# never wrap the packed key (widthcheck R026/R027 read it as the
# eligibility predicate; tools/width_audit.py proves the one-past
# class raises, W002).
FLAT_NV_MAX = 1 << 15


def _env_max_nv() -> int:
    from cuvite_tpu.utils.envknob import env_int

    # 32768^2 flat keys is the int32 packing ceiling (2^30) and an
    # 8 GiB accumulator — anything above is certainly a typo.
    return env_int("CUVITE_SEG_COALESCE_MAX_NV", DEFAULT_MAX_NV,
                   maximum=32768)


def coalesce_engine(nv_pad: int, accum_dtype=None) -> str:
    """THE static engine decision for one slab class: 'pallas', 'xla' or
    'sort'.  Read per CALL by the drivers (not per trace — the result is
    a static argument of device_coarsen_slab, so env toggles take effect
    on the next phase without stale-trace hazards).

    CUVITE_SEG_COALESCE: '' (default) — the packed-sort path; 'xla' /
    'dense' / '1' — the XLA dense twin where the class fits; 'pallas' —
    the tile kernel (interpret off-TPU); 'msd' — the two-pass int32 MSD
    sort (ops/segment.sort_edges_msd: never degrades — ds32-capable,
    no domain cap, and identical to 'sort' below the 31-bit pack
    ceiling); 'hash' — the hash-slot coalesce below (explicit
    accumulators route to 'msd': its tables sum in the weight dtype);
    '0' / 'sort' — explicit sort pin.  Ineligible classes (domain over
    budget, ds32) degrade the DENSE modes to 'sort', with coverage
    reported by the drivers (the PALLAS_MAX_WIDTH
    degrade-with-coverage pattern).

    Why default-off (measured, this rig, 24-core CPU backend): every
    ELIGIBLE class (nv_pad <= 4096 -> 25-bit key) already rides the
    packed int32 single-key sort, which beat the dense engines ~4.7x at
    (nv_pad 4096, ne_pad 2^20) — XLA CPU scatters cost ~micro-seconds
    per element.  The classes paying the real sort tax (nv_pad >= 2^16,
    where kbits+sbits > 31 degrades lax.sort to the variadic comparator)
    have a key domain no dense accumulator can hold.  So on CPU the sort
    IS the best coalesce at every class; the dense engines are the
    TPU-targeted bet (VMEM bin-accumulate vs on-chip sort), following
    the heavy_bincount route: built, interpret-proven in tier-1, chip
    A/B staged in tools/heavy_ab.py + tpu_ladder3.py, promoted when the
    tunnel numbers say so.
    """
    mode = os.environ.get("CUVITE_SEG_COALESCE", "").strip().lower()
    if mode in ("", "0", "false", "sort"):
        return "sort"
    if mode not in ("1", "true", "dense", "xla", "pallas", "msd",
                    "hash"):
        # A typo'd pin must never silently measure the wrong engine
        # (the CUVITE_EXCHANGE_CUTOVER precedent): warn, keep the
        # default.
        import warnings

        warnings.warn(
            f"unrecognized CUVITE_SEG_COALESCE={mode!r} (want sort/0, "
            "xla/dense/1, pallas, msd, or hash); using the default "
            "'sort'", stacklevel=2)
        return "sort"
    if mode == "msd":
        # The msd sort shares the sorted-runs tail with 'sort': every
        # accumulator (ds32 included) and every class is legal.
        return "msd"
    if mode == "hash":
        # Hash tables sum in the weight dtype in slab order: explicit
        # accumulators take the msd SORTING path instead (same order as
        # 'sort', so ds32 pair sums stay exact) rather than plain
        # 'sort' — the operator asked for a big-class engine.
        return "hash" if accum_dtype is None else "msd"
    if accum_dtype is not None:
        # Any explicit accumulator degrades to sort: ds32 needs the
        # sorted segmented pair arithmetic (ops/exactsum), and a wider
        # plain dtype would be silently narrowed by the dense
        # accumulators (they sum in the weight dtype only).
        return "sort"
    if nv_pad > _env_max_nv():
        return "sort"
    if mode == "pallas":
        return "pallas"
    return "xla"


def _kernel(src_ref, dst_ref, w_ref, acc_ref, cnt_ref, *, c_tile: int,
            nv_pad: int):
    t = pl.program_id(0)   # dst-community tile (outer, owns the block)
    k = pl.program_id(1)   # slab chunk (inner, accumulates)

    @pl.when(k == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        cnt_ref[:] = jnp.zeros_like(cnt_ref)

    s = src_ref[:].reshape(-1)
    d = dst_ref[:].reshape(-1)
    w = w_ref[:].reshape(-1)
    lo = t * c_tile
    # Bin by dst tile: rows outside [lo, lo + C) — and padding rows,
    # src == nv_pad — drop via the out-of-bounds scatter row.
    in_tile = (s < nv_pad) & (d >= lo) & (d < lo + c_tile)
    rows = jnp.where(in_tile, s, nv_pad)
    cols = jnp.where(in_tile, d - lo, 0)
    acc_ref[:] = acc_ref[:].at[rows, cols].add(
        jnp.where(in_tile, w, jnp.zeros_like(w)), mode="drop")
    cnt_ref[:] = cnt_ref[:].at[rows, cols].add(
        in_tile.astype(jnp.int32), mode="drop")


@functools.partial(
    jax.jit, static_argnames=("nv_pad", "c_tile", "e_chunk", "interpret"))
def seg_coalesce_pallas(src, dst, w, *, nv_pad: int,
                        c_tile: int = DEFAULT_C_TILE,
                        e_chunk: int = DEFAULT_E_CHUNK,
                        interpret: bool = False):
    """Dense (weight, count) accumulators of the relabeled slab, via the
    dst-tile Pallas kernel.  src/dst: [ne_pad] int ids < nv_pad (padding
    src == nv_pad, w == 0); returns (acc [nv_pad, nv_pad] of w.dtype,
    cnt [nv_pad, nv_pad] int32) — feed :func:`emit_coalesced`."""
    ne_pad = src.shape[0]
    # VMEM guard: the [nv_pad, C] accumulator pair stays within
    # ACC_BLOCK_ELEMS even when CUVITE_SEG_COALESCE_MAX_NV admits wider
    # classes (pow2 operands keep every division exact).
    c_tile = min(c_tile, nv_pad, max(ACC_BLOCK_ELEMS // nv_pad, 1))
    e_chunk = min(e_chunk, ne_pad)
    # Sub-lane slabs (tiny test classes) shrink the lane dim; pow2
    # shapes keep every division exact.
    lane = min(LANE, ne_pad)
    assert nv_pad % c_tile == 0 and ne_pad % e_chunk == 0
    grid = (nv_pad // c_tile, ne_pad // e_chunk)

    rows = e_chunk // lane
    slab_spec = pl.BlockSpec((rows, lane), lambda t, k: (k, 0),
                             memory_space=pltpu.VMEM)
    out_spec = pl.BlockSpec((nv_pad, c_tile), lambda t, k: (0, t),
                            memory_space=pltpu.VMEM)
    kernel = functools.partial(_kernel, c_tile=c_tile, nv_pad=nv_pad)
    acc, cnt = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[slab_spec, slab_spec, slab_spec],
        out_specs=(out_spec, out_spec),
        out_shape=(
            jax.ShapeDtypeStruct((nv_pad, nv_pad), w.dtype),
            jax.ShapeDtypeStruct((nv_pad, nv_pad), jnp.int32),
        ),
        interpret=interpret,
    )(
        src.astype(jnp.int32).reshape(ne_pad // lane, lane),
        dst.astype(jnp.int32).reshape(ne_pad // lane, lane),
        w.reshape(ne_pad // lane, lane),
    )
    return acc, cnt


def seg_coalesce_xla(src, dst, w, *, nv_pad: int):
    """The kernel's bit-identical XLA twin: one O(ne) scatter-add over
    the flat [nv_pad * nv_pad] key domain (the default dense engine —
    compiles on every backend; on CPU this replaces the multi-second
    comparator sort with a linear pass)."""
    assert nv_pad & (nv_pad - 1) == 0, nv_pad  # flat packing needs pow2
    if nv_pad > FLAT_NV_MAX:
        raise ValueError(
            f"seg_coalesce_xla: nv_pad = {nv_pad} over FLAT_NV_MAX = "
            f"{FLAT_NV_MAX}: the int32 flat (src << kbits) | dst key "
            "would overflow — coalesce_engine routes this class to "
            "'sort'")
    kbits = (nv_pad - 1).bit_length()
    real = src < nv_pad
    flat = jnp.where(
        real,
        (src.astype(jnp.int32) << kbits) | dst.astype(jnp.int32),
        jnp.int32(nv_pad * nv_pad),  # out of bounds -> dropped
    )
    acc = jnp.zeros((nv_pad * nv_pad,), dtype=w.dtype).at[flat].add(
        jnp.where(real, w, jnp.zeros_like(w)), mode="drop")
    cnt = jnp.zeros((nv_pad * nv_pad,), dtype=jnp.int32).at[flat].add(
        real.astype(jnp.int32), mode="drop")
    return acc.reshape(nv_pad, nv_pad), cnt.reshape(nv_pad, nv_pad)


def emit_coalesced(acc, cnt, *, ne_pad: int, src_dtype, dst_dtype):
    """Compact the dense accumulators into the coalesced slab prefix.

    Ascending flat (src * nv_pad + dst) order IS the sorted (src, dst)
    run order, so the emitted prefix is bit-identical (offsets, tails —
    and weights on the exactness domain) to the packed-sort path's.
    Returns (src2, dst2, w2, ne2) in the [ne_pad] class: real rows in
    [0, ne2), padding (src == nv_pad, dst == 0, w == 0) after.
    """
    nv_pad = acc.shape[0]
    assert nv_pad & (nv_pad - 1) == 0, nv_pad  # slab classes are pow2
    kbits = (nv_pad - 1).bit_length()
    flat_w = acc.reshape(-1)
    present = cnt.reshape(-1) > 0
    ne2 = jnp.sum(present.astype(jnp.int32))
    pos = jnp.cumsum(present.astype(jnp.int32)) - 1
    slot = jnp.where(present, pos, ne_pad)  # absent keys drop
    idx = jnp.arange(nv_pad * nv_pad, dtype=jnp.int32)  # graftlint: width-ok=flat key domain is caller-gated to nv_pad <= FLAT_NV_MAX = 2^15 (coalesce_engine policy + the seg_coalesce_xla raise-guard), so nv_pad^2 <= 2^30 fits int32
    src2 = jnp.full((ne_pad,), nv_pad, src_dtype).at[slot].set(
        (idx >> kbits).astype(src_dtype), mode="drop")
    dst2 = jnp.zeros((ne_pad,), dst_dtype).at[slot].set(
        (idx & (nv_pad - 1)).astype(dst_dtype), mode="drop")
    w2 = jnp.zeros((ne_pad,), flat_w.dtype).at[slot].set(flat_w,
                                                         mode="drop")
    return src2, dst2, w2, ne2


def coalesce_slab(src, dst, w, *, nv_pad: int, engine: str,
                  interpret: bool | None = None):
    """One dense segmented-coalesce: accumulate + emit.  ``engine`` is
    'pallas' or 'xla' (the 'sort' chokepoint lives in
    ops/segment.coalesced_runs, which dispatches here).  ``interpret``
    defaults to True off-TPU (the heavy_bincount convention)."""
    if engine == "pallas":
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        acc, cnt = seg_coalesce_pallas(src, dst, w, nv_pad=nv_pad,
                                       interpret=interpret)
    else:
        acc, cnt = seg_coalesce_xla(src, dst, w, nv_pad=nv_pad)
    return emit_coalesced(acc, cnt, ne_pad=src.shape[0],
                          src_dtype=src.dtype, dst_dtype=dst.dtype)


# ---------------------------------------------------------------------------
# Hash-slot coalesce (the big-class engine of ISSUE 19): K static slots
# per src — a [nv_pad * K] table instead of the dense [nv_pad^2] domain,
# so classes FLAT_NV_MAX rules out (nv_pad >= 2^16) stay in one O(ne)
# scatter pass.  A slot receiving two distinct dst keys cannot emit;
# collision detection is DEVICE-side (scatter-min/max of dst per slot)
# and the caller (ops/segment.coalesced_runs) retries the slab through
# the msd-sorted tail inside lax.cond — no host sync, bit-identical to
# the sort engines either way.

# Table ceiling: the flat src * K + slot index is int32 and the
# emission cumsum counts table slots, so nv_pad * K stays <= 2^30 (the
# SLAB_NE_MAX discipline); the rank matrix below adds a [nv_pad, K, K]
# transient, so K is further bounded to keep it ~2^28 elements.
HASH_TABLE_MAX = 1 << 30
HASH_RANK_MAX = 1 << 28
_HASH_MULT = 2654435761  # Knuth's 2^32 / phi multiplicative constant


def hash_slots(nv_pad: int, ne_pad: int) -> int:
    """STATIC slot count per src for one slab class: pow2, derived from
    the class's mean degree (~4x headroom so light tails rarely
    collide), floored at 16, capped by nv_pad and the table/rank element
    budgets.  CUVITE_HASH_SLOTS overrides (still clamped pow2) — the
    A/B sweep knob."""
    from cuvite_tpu.utils.envknob import env_int

    k = env_int("CUVITE_HASH_SLOTS", 0, minimum=0, maximum=1 << 12)
    if k <= 0:
        avg = max(ne_pad // max(nv_pad, 1), 1)
        k = min(nv_pad, max(16, 4 * avg))
    k = 1 << max(int(k - 1).bit_length(), 0)  # pow2 ceiling
    while k > 1 and (nv_pad * k > HASH_TABLE_MAX
                     or nv_pad * k * k > HASH_RANK_MAX):
        k >>= 1
    return k


def hash_accumulate(src, dst, w, *, nv_pad: int, k: int):
    """One O(ne) scatter pass over the [nv_pad * K] slot table.  src/dst:
    [ne_pad] ids < nv_pad (padding src == nv_pad, w == 0); returns
    ``(wsum, cnt, dmin, dmax)`` flat [nv_pad * K] tables — weight sum,
    run presence count, and the min/max dst seen per slot (equal iff the
    slot is collision-free)."""
    assert k & (k - 1) == 0, k
    real = src < nv_pad
    if k == 1:
        slot = jnp.zeros(src.shape, jnp.int32)
    else:
        log2k = (k - 1).bit_length()
        slot = (dst.astype(jnp.uint32) * jnp.uint32(_HASH_MULT)
                >> (32 - log2k)).astype(jnp.int32)
    flat = jnp.where(real, src.astype(jnp.int32) * k + slot,
                     jnp.int32(nv_pad * k))  # graftlint: width-ok=hash_slots caps nv_pad * k at HASH_TABLE_MAX = 2^30, int32-safe
    d32 = dst.astype(jnp.int32)
    big = jnp.int32(nv_pad)  # > every real dst
    zero_w = jnp.zeros_like(w)
    wsum = jnp.zeros((nv_pad * k,), w.dtype).at[flat].add(
        jnp.where(real, w, zero_w), mode="drop")
    cnt = jnp.zeros((nv_pad * k,), jnp.int32).at[flat].add(
        real.astype(jnp.int32), mode="drop")
    dmin = jnp.full((nv_pad * k,), big).at[flat].min(
        jnp.where(real, d32, big), mode="drop")
    dmax = jnp.zeros((nv_pad * k,), jnp.int32).at[flat].max(
        jnp.where(real, d32, jnp.int32(0)), mode="drop")
    return wsum, cnt, dmin, dmax


def hash_emit(wsum, cnt, dmin, *, nv_pad: int, ne_pad: int, k: int,
              src_dtype, ckey_dtype):
    """Compact a collision-free slot table into the coalesced slab
    prefix, rows in ascending (src, dst) order — bit-identical (offsets,
    tails, and weights on the exactness domain) to the sorted paths.

    Within one src the occupied slots hold provably DISTINCT dst (equal
    dst hash to one slot), so the dst-ascending order inside each row is
    recovered SORT-FREE by an O(K^2) rank — this module sits inside
    graftlint R013's no-sort scope, and K is a small static constant,
    not a slab dimension.  Empty slots carry the sentinel nv_pad and
    rank after every real dst; sentinel ties break by slot index so the
    ranks form a permutation and the reordering scatter is exact."""
    dst_t = jnp.where(cnt > 0, dmin, jnp.int32(nv_pad)) \
        .reshape(nv_pad, k)
    w_t = jnp.where(cnt.reshape(nv_pad, k) > 0, wsum.reshape(nv_pad, k),
                    jnp.zeros_like(wsum.reshape(nv_pad, k)))
    sl = jnp.arange(k, dtype=jnp.int32)
    before = (dst_t[:, :, None] > dst_t[:, None, :]) | (
        (dst_t[:, :, None] == dst_t[:, None, :])
        & (sl[None, :, None] > sl[None, None, :]))
    rank = jnp.sum(before, axis=2, dtype=jnp.int32)  # [nv_pad, k]
    row = jnp.arange(nv_pad, dtype=jnp.int32)[:, None]
    ordered_d = jnp.full((nv_pad, k), nv_pad, jnp.int32) \
        .at[row, rank].set(dst_t)
    ordered_w = jnp.zeros((nv_pad, k), w_t.dtype).at[row, rank].set(w_t)
    flat_d = ordered_d.reshape(-1)
    flat_w = ordered_w.reshape(-1)
    present = flat_d < nv_pad
    # Ascending (row, rank) order IS ascending (src, dst): the standard
    # cumsum compaction (emit_coalesced) lands the prefix directly.
    # Distinct pairs <= real edges <= ne_pad, so pos never overflows the
    # output class even when nv_pad * k > ne_pad.
    n = jnp.sum(present.astype(jnp.int32))
    pos = jnp.cumsum(present.astype(jnp.int32)) - 1
    slot = jnp.where(present, pos, ne_pad)  # absent keys drop
    srcs = jnp.repeat(jnp.arange(nv_pad, dtype=jnp.int32), k)
    src_c = jnp.full((ne_pad,), nv_pad, src_dtype).at[slot].set(
        srcs.astype(src_dtype), mode="drop")
    ckey_c = jnp.zeros((ne_pad,), ckey_dtype).at[slot].set(
        flat_d.astype(ckey_dtype), mode="drop")
    w_c = jnp.zeros((ne_pad,), flat_w.dtype).at[slot].set(flat_w,
                                                          mode="drop")
    return src_c, ckey_c, w_c, n
