"""Pallas TPU kernels for the Louvain hot ops."""

from cuvite_tpu.kernels.row_argmax import row_argmax_pallas  # noqa: F401
