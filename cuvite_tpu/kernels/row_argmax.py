"""Pallas TPU kernel: neighbor-community dedup + modularity-gain argmax for
one degree bucket of the Louvain sweep.

Role: the narrow-degree classes of the per-vertex inner loop — the TPU
counterpart of the reference GPU's thread-per-vertex dedup/argmax kernels
(distGetMaxIndex, /root/reference/louvain_cuda.cu:1190-1346, and
computeMaxIndex, :641-876).  The XLA fallback (`_row_argmax` in
cuvite_tpu/louvain/bucketed.py) materializes the [rows, D] aggregation
intermediates in HBM; this kernel keeps the whole per-tile computation in
VMEM and writes only the per-row result vectors.

Layout: the bucket is TRANSPOSED to [D, N] so the lane dimension runs
across bucket rows (N = padded row count, a multiple of the 128-lane tile)
and the all-pairs dedup unrolls over the small static D in the sublane
dimension.  Per candidate slot j:

    wagg_j  = sum_k  w_k   where c_k == c_j          (duplicate aggregation)
    dup_j   = any_{k<j} c_k == c_j                   (j is not the leader)
    valid_j = !dup_j and c_j != curr
    gain_j  = 2*(wagg_j - eix) - 2*vdeg*(ay_j - ax)*const
                                   (louvain.cpp:2228 formula; ay pre-gathered)
    best    = running argmax over j, ties -> smaller community id
                                   (louvain.cpp:2230-2238 tie-break)

plus counter0 = sum of weights into the current community (incl. self
edges), which the caller turns into eix for the next stage.

SPMD: the kernel itself is shard-oblivious — the sharded bucketed step
(louvain/bucketed.py) calls it INSIDE its shard_map body on each shard's
[D, N] block.  The sparse ghost exchange additionally needs the SIZE of
the winning community for the singleton-swap guard; ``szT`` (the per-slot
attached community size, same layout as ``ayT``) switches the kernel to a
4-output form that tracks the winning slot's size through the running
argmax.  Every slot holding a community carries that community's size, so
the tracked value equals the XLA path's min-over-chosen-slots — bit-equal
by construction.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128
DEFAULT_TILE_N = 512
# Width above which the candidate loop switches from full unroll to
# lax.fori_loop (bounding compile time; identical arithmetic).  The
# unrolled form lets Mosaic schedule the small widths tightest.
UNROLL_MAX_WIDTH = 32
# Per-tile VMEM budget for the [D, T] operand blocks (c/w/ay (+size) +
# outputs), used to shrink the row tile for wide classes: the f32/int32
# blocks of D x tile_n must fit comfortably under ~16 MB v5e VMEM.
VMEM_BUDGET_BYTES = 6 << 20


def _kernel(const_ref, cT_ref, wT_ref, ayT_ref, curr_ref, vdeg_ref, sl_ref,
            ax_ref, *refs, sentinel: int, width: int, with_size: bool):
    if with_size:
        szT_ref, bc_ref, bg_ref, c0_ref, bs_ref = refs
    else:
        bc_ref, bg_ref, c0_ref = refs
        szT_ref = bs_ref = None
    c = cT_ref[:]          # [D, T] int32 neighbor communities
    w = wT_ref[:]          # [D, T] f32 edge weights
    ay = ayT_ref[:]        # [D, T] f32 comm_deg of each candidate
    sz = szT_ref[:] if with_size else None   # [D, T] int32 candidate size
    curr = curr_ref[:]     # [1, T] int32 current community
    vdeg = vdeg_ref[:]     # [1, T] f32 weighted degree k_i
    sl = sl_ref[:]         # [1, T] f32 self-loop weight of the vertex
    ax = ax_ref[:]         # [1, T] f32 comm_deg[curr] - k_i
    const = const_ref[0]   # f32 1/(2m)

    wdt = w.dtype
    is_cc = c == curr
    zero = jnp.zeros_like(w)
    c0 = jnp.sum(jnp.where(is_cc, w, zero), axis=0, keepdims=True)
    c0_ref[:] = c0
    # A vertex's weight into its current community comes entirely from its
    # own bucket row, so eix (counter0 minus self-loops) is row-local.
    eix = c0 - sl

    neg_inf = jnp.full(curr.shape, -jnp.inf, dtype=wdt)
    bg0 = neg_inf
    bc0 = jnp.full(curr.shape, sentinel, dtype=c.dtype)
    bs0 = jnp.full(curr.shape, sentinel, dtype=c.dtype) if with_size else None
    two_vdeg = 2.0 * vdeg

    def step_j(cj, ayj, szj, eq, dup_j, bc, bg, bs):
        """One candidate slot: aggregate duplicates, gain, running argmax.
        Shared by the unrolled (static j) and fori_loop (traced j) forms —
        identical arithmetic, so the two are bit-identical.  Operand order
        matches the XLA paths exactly (bucketed.py `_row_argmax`:
        ((2*vdeg)*(ay-ax))*const) so engines agree bit-for-bit even on
        non-dyadic constants where f32 association matters.  ``bs`` rides
        the same better/tie updates as ``bc``: any slot of the winning
        community carries the same attached size, so tracking the slot
        that wins the (gain, smaller-id) order IS the XLA min-over-chosen."""
        wagg_j = jnp.sum(jnp.where(eq, w, zero), axis=0, keepdims=True)
        valid_j = (~dup_j) & (cj != curr) if dup_j is not None \
            else (cj != curr)
        gain_j = 2.0 * (wagg_j - eix) - two_vdeg * (ayj - ax) * const
        gain_j = jnp.where(valid_j, gain_j, neg_inf)
        better = gain_j > bg
        tie = valid_j & (gain_j == bg)
        if bs is not None:
            take = better | (tie & (cj < bc))
            bs = jnp.where(take, szj, bs)
        bc = jnp.where(better, cj, jnp.where(tie, jnp.minimum(bc, cj), bc))
        bg = jnp.maximum(bg, gain_j)
        return bc, bg, bs

    if width <= UNROLL_MAX_WIDTH:
        bc, bg, bs = bc0, bg0, bs0
        for j in range(width):
            cj = c[j : j + 1, :]
            eq = c == cj
            dup_j = (jnp.any(eq[:j, :], axis=0, keepdims=True)
                     if j > 0 else None)
            szj = sz[j : j + 1, :] if with_size else None
            bc, bg, bs = step_j(cj, ay[j : j + 1, :], szj, eq, dup_j,
                                bc, bg, bs)
    else:
        # Wide classes: loop over candidate slots with dynamic sublane
        # slices (compile time O(1) in width).  The duplicate-leader test
        # uses a row-index mask (rows k < j) on the full eq matrix.
        D, T = c.shape
        row_idx = jax.lax.broadcasted_iota(jnp.int32, (D, T), 0)

        if with_size:
            def body(j, carry):
                bc, bg, bs = carry
                cj = jax.lax.dynamic_slice_in_dim(c, j, 1, axis=0)
                ayj = jax.lax.dynamic_slice_in_dim(ay, j, 1, axis=0)
                szj = jax.lax.dynamic_slice_in_dim(sz, j, 1, axis=0)
                eq = c == cj
                dup_j = jnp.any(eq & (row_idx < j), axis=0, keepdims=True)
                return step_j(cj, ayj, szj, eq, dup_j, bc, bg, bs)

            bc, bg, bs = jax.lax.fori_loop(0, width, body, (bc0, bg0, bs0))
        else:
            def body(j, carry):
                bc, bg = carry
                cj = jax.lax.dynamic_slice_in_dim(c, j, 1, axis=0)
                ayj = jax.lax.dynamic_slice_in_dim(ay, j, 1, axis=0)
                eq = c == cj
                dup_j = jnp.any(eq & (row_idx < j), axis=0, keepdims=True)
                bc, bg, _ = step_j(cj, ayj, None, eq, dup_j, bc, bg, None)
                return bc, bg

            bc, bg = jax.lax.fori_loop(0, width, body, (bc0, bg0))
            bs = None
    bc_ref[:] = bc
    bg_ref[:] = bg
    if with_size:
        bs_ref[:] = bs


@functools.partial(
    jax.jit,
    static_argnames=("sentinel", "tile_n", "interpret"),
)
def row_argmax_pallas(cT, wT, ayT, curr, vdeg, sl, ax, constant, *,
                      szT=None, sentinel: int, tile_n: int = DEFAULT_TILE_N,
                      interpret: bool = False):
    """Run the bucket kernel.

    cT/wT/ayT: [D, N] transposed bucket matrices; curr/vdeg/sl/ax: [N]
    (sl = per-vertex self-loop weight); constant: scalar.  N must be a
    multiple of the row tile (bucket row counts are padded to powers of
    two >= 128 by the runner for this path).  The tile shrinks below
    ``tile_n`` for wide D so the [D, tile] operand blocks stay inside
    the VMEM budget.  Returns (best_c [N] int, best_gain [N],
    counter0 [N]); with ``szT`` (the [D, N] attached community-size
    matrix of the sparse exchange) additionally best_size [N] int.
    """
    D, N = cT.shape
    with_size = szT is not None
    n_mats = 4 if with_size else 3
    tile = min(tile_n, N)
    # Wide classes: bound n_mats * D * tile * 4B by the VMEM budget (pow2
    # shrink keeps N % tile == 0 — both are powers of two >= 128).
    while tile > LANE and n_mats * D * tile * 4 > VMEM_BUDGET_BYTES:
        tile //= 2
    assert N % tile == 0 and tile % LANE == 0, (N, tile)
    grid = (N // tile,)

    mat_spec = pl.BlockSpec((D, tile), lambda i: (0, i),
                            memory_space=pltpu.VMEM)
    vec_spec = pl.BlockSpec((1, tile), lambda i: (0, i),
                            memory_space=pltpu.VMEM)
    out_shapes = (
        jax.ShapeDtypeStruct((1, N), cT.dtype),
        jax.ShapeDtypeStruct((1, N), wT.dtype),
        jax.ShapeDtypeStruct((1, N), wT.dtype),
    )
    out_specs = (vec_spec, vec_spec, vec_spec)
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        mat_spec, mat_spec, mat_spec,
        vec_spec, vec_spec, vec_spec, vec_spec,
    ]
    operands = [
        jnp.reshape(constant, (1,)).astype(wT.dtype),
        cT, wT, ayT,
        curr.reshape(1, N), vdeg.reshape(1, N), sl.reshape(1, N),
        ax.reshape(1, N),
    ]
    if with_size:
        in_specs.append(mat_spec)
        operands.append(szT)
        out_shapes = out_shapes + (jax.ShapeDtypeStruct((1, N), cT.dtype),)
        out_specs = out_specs + (vec_spec,)
    kernel = functools.partial(_kernel, sentinel=sentinel, width=D,
                               with_size=with_size)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        interpret=interpret,
    )(*operands)
    if with_size:
        bc, bg, c0, bs = out
        return bc.reshape(N), bg.reshape(N), c0.reshape(N), bs.reshape(N)
    bc, bg, c0 = out
    return bc.reshape(N), bg.reshape(N), c0.reshape(N)
