"""Pallas TPU kernel: community-range-tile bincount dedup + gain argmax
for the HEAVY degree class (> 8192 neighbors per vertex).

Role: the TPU counterpart of the reference GPU's huge-class kernel, which
bincounts neighbor weights into a 20M-entry dense per-block scratch
indexed by dense community id (distGetMaxIndex_large_new,
/root/reference/louvain_cuda.cu:878-1022).  An O(nv) dense scratch cannot
live in VMEM (~16 MB on v5e), so this kernel tiles the COMMUNITY RANGE
(tools/heavy_kernel_design.md): for each tile [t*C, (t+1)*C) it one-hot
matmuls the row's weights against `eq(c, cand)` — duplicate aggregation
IS the bincount — and carries a running (best_gain, best_c) across tiles.

Layout: transposed [D, H] rows (H = heavy vertices, D = max heavy degree,
rows padded with c = pad id >= n_tiles*C and w = 0), one vertex per grid
row.  The neighbor-community axis is reduced in Dc-sized chunks inside a
fori_loop so VMEM holds only [Dc, C] one-hot blocks; `comm_deg` (the ay
gather of the narrow kernel) arrives as a contiguous [1, C] block per
community tile — a community-RANGE tile needs no gather at all.

Tie-break matches the narrow kernel (`row_argmax.py`) and the reference
(`louvain.cpp:2230-2238`): max gain, ties -> smaller community id.  Tiles
ascend in community id, so a strict `>` merge keeps the earlier (smaller)
id on cross-tile ties, and the in-tile rule picks the smallest candidate
among equal gains.

Status (ISSUE 8): PROMOTED from interpret-only/default-off.  The
single-shard bucketed/pallas engines route the heavy residual through
this kernel by default on the TPU backend (``heavy_kernel_enabled``;
CUVITE_HEAVY_KERNEL=0 is the kill switch, =1 forces interpret mode on
other backends — how tier-1 pins the compiled-path parity on CPU), with
the per-phase [D, H] row layout built by ``build_heavy_layout`` and the
XLA sorted path kept as the degrade-with-coverage fallback when the
layout exceeds its element budget (CUVITE_HEAVY_ELEMS), when the
exchange is sparse (the kernel has no attached-size channel), or on a
mesh (the layout is single-shard).  Eliminating the per-iteration heavy
sort is the move-phase half of killing the sort tax; the coalesce half
is kernels/seg_coalesce.py.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128
DEFAULT_C_TILE = 512     # communities per tile ([Dc, C] one-hot block)
DEFAULT_D_CHUNK = 1024   # neighbor slots reduced per fori step
# [Dc, C] f32 one-hot + eq intermediates must sit well under v5e VMEM.
assert DEFAULT_C_TILE * DEFAULT_D_CHUNK * 4 <= (4 << 20)

# [D, Hp] layout element budget: the transposed heavy rows live in HBM
# for the whole phase (two arrays, id + weight), so a hub set whose
# padded matrix exceeds this stays on the sorted path instead of
# doubling the slab's footprint.  2^24 slots = 64 MiB per f32 array.
DEFAULT_MAX_LAYOUT_ELEMS = 1 << 24


def heavy_kernel_enabled() -> bool:
    """Default-on policy for the heavy (> 8192 neighbors) degree class
    (ISSUE 8 promotion): the community-range-tile kernel replaces the
    per-iteration heavy sort on the TPU backend.  CUVITE_HEAVY_KERNEL=0
    retains the historical sorted path (the kill switch / A/B lever);
    =1 forces the kernel in interpret mode on other backends — tier-1
    runs the full driver this way to pin parity without a chip.  Read
    per PhaseRunner construction, not at import."""
    v = os.environ.get("CUVITE_HEAVY_KERNEL", "").strip().lower()
    if v in ("0", "false", "off"):
        return False
    if v in ("1", "true", "on"):
        return True
    return jax.default_backend() == "tpu"


def _layout_budget() -> int:
    from cuvite_tpu.utils.envknob import env_int

    return env_int("CUVITE_HEAVY_ELEMS", DEFAULT_MAX_LAYOUT_ELEMS)


def build_heavy_layout(heavy_src, heavy_dst, heavy_w, *, nv_local: int,
                       pad_id: int, d_chunk: int = DEFAULT_D_CHUNK,
                       max_elems: int | None = None):
    """Phase-static [D, Hp] transposed row layout of the heavy residual,
    from the BucketPlan's padded (src, dst, w) triples.

    Returns ``(verts [Hp], dstT [D, Hp], wT [D, Hp])`` — one hub per
    column, columns in ascending vertex id, D a multiple of ``d_chunk``,
    Hp a pow2 >= 8 (stable shapes: phases whose hub geometry pads to the
    same (D, Hp) reuse the compiled step).  Padding slots carry dst ==
    ``pad_id`` (the step masks them to a community >= nv_ceil, so they
    are never candidates) and w == 0; padding columns carry verts ==
    nv_local (dropped at assembly).  Returns None — the caller keeps the
    sorted path, with a coverage warning — when there are no heavy
    edges or the padded layout exceeds ``max_elems``
    (CUVITE_HEAVY_ELEMS; the PALLAS_MAX_WIDTH degrade pattern).
    """
    if max_elems is None:
        max_elems = _layout_budget()
    hs = np.asarray(heavy_src)
    real = hs < nv_local
    s = hs[real].astype(np.int64)
    if len(s) == 0:
        return None
    d = np.asarray(heavy_dst)[real]
    w = np.asarray(heavy_w)[real]
    if len(s) > 1 and np.any(s[:-1] > s[1:]):
        # Plan triples arrive CSR-ordered; color-masked or synthetic
        # inputs may not be.  Stable, so within-row edge order (the f32
        # accumulation order contract) is preserved.
        order = np.argsort(s, kind="stable")
        s, d, w = s[order], d[order], w[order]
    verts, counts = np.unique(s, return_counts=True)
    H = len(verts)
    Hp = max(1 << int(H - 1).bit_length() if H > 1 else 1, 8)
    D = int(-(-int(counts.max()) // d_chunk)) * d_chunk
    if D * Hp > max_elems:
        return None
    row_start = np.searchsorted(s, verts)
    rows = np.arange(D, dtype=np.int64)
    idx = row_start[None, :] + rows[:, None]        # [D, H]
    has = rows[:, None] < counts[None, :]
    idx = np.minimum(idx, len(d) - 1)
    dstT = np.full((D, Hp), pad_id, dtype=np.asarray(heavy_dst).dtype)
    wT = np.zeros((D, Hp), dtype=w.dtype)
    dstT[:, :H] = np.where(has, d[idx], pad_id)
    wT[:, :H] = np.where(has, w[idx], 0)
    verts_out = np.full(Hp, nv_local, dtype=np.int64)
    verts_out[:H] = verts
    return verts_out, dstT, wT


def _kernel(const_ref, cT_ref, wT_ref, ay_ref, curr_ref, vdeg_ref, sl_ref,
            ax_ref, bc_ref, bg_ref, c0_ref, *, c_tile: int, d_chunk: int):
    t = pl.program_id(1)
    c = cT_ref[:]          # [D, 1] int32 neighbor communities (one vertex)
    w = wT_ref[:]          # [D, 1] f32 edge weights (0 on padding)
    ay = ay_ref[:]         # [1, C] f32 comm_deg of this community tile
    curr = curr_ref[0, 0]  # scalars of the vertex
    vdeg = vdeg_ref[0, 0]
    sl = sl_ref[0, 0]
    ax = ax_ref[0, 0]
    const = const_ref[0]
    wdt = w.dtype

    @pl.when(t == 0)
    def _init():
        # counter0 (weight into the current community, incl. self edges)
        # is row-local — one elementwise pass, no tiles involved.
        c0_ref[0, 0] = jnp.sum(jnp.where(c == curr, w, 0.0))
        bg_ref[0, 0] = jnp.asarray(-jnp.inf, dtype=wdt)
        bc_ref[0, 0] = jnp.asarray(jnp.iinfo(cT_ref.dtype).max,
                                   dtype=cT_ref.dtype)

    eix = c0_ref[0, 0] - sl
    cand = t * c_tile + jax.lax.broadcasted_iota(jnp.int32, (1, c_tile), 1)

    def chunk(k, carry):
        wagg, cnt = carry
        ck = jax.lax.dynamic_slice_in_dim(c, k * d_chunk, d_chunk, axis=0)
        wk = jax.lax.dynamic_slice_in_dim(w, k * d_chunk, d_chunk, axis=0)
        eq = (ck == cand).astype(wdt)            # [Dc, C] one-hot
        wagg = wagg + jax.lax.dot_general(        # [1, C] bincount slice
            wk, eq, (((0,), (0,)), ((), ())),
            preferred_element_type=wdt)
        # Presence COUNT, not weight: zero-weight edges are candidates
        # exactly as in the XLA paths (bucketed.py `_row_argmax` — 'No
        # w>0 filter').  Padding slots carry c >= n_tiles*c_tile so eq
        # never matches them.
        cnt = cnt + jnp.sum(eq, axis=0, keepdims=True)
        return wagg, cnt

    n_chunks = cT_ref.shape[0] // d_chunk
    zero = jnp.zeros((1, c_tile), dtype=wdt)
    wagg, cnt = jax.lax.fori_loop(0, n_chunks, chunk, (zero, zero))

    valid = (cnt > 0) & (cand != curr)
    # Operand order matches the XLA paths exactly (bucketed.py:546/633):
    # 2*(wagg-eix) - ((2*vdeg)*(ay-ax))*const.
    gain = 2.0 * (wagg - eix) - 2.0 * vdeg * (ay - ax) * const
    gain = jnp.where(valid, gain, -jnp.inf)
    tile_bg = jnp.max(gain)
    big = jnp.asarray(jnp.iinfo(cT_ref.dtype).max, dtype=cand.dtype)
    tile_bc = jnp.min(jnp.where(gain == tile_bg, cand, big))
    better = tile_bg > bg_ref[0, 0]               # strict: earlier tile
    bc_ref[0, 0] = jnp.where(
        better, tile_bc.astype(cT_ref.dtype), bc_ref[0, 0])
    bg_ref[0, 0] = jnp.where(better, tile_bg, bg_ref[0, 0])


@functools.partial(
    jax.jit,
    static_argnames=("c_tile", "d_chunk", "interpret"),
)
def heavy_argmax_pallas(cT, wT, comm_deg, curr, vdeg, sl, ax, constant, *,
                        c_tile: int = DEFAULT_C_TILE,
                        d_chunk: int = DEFAULT_D_CHUNK,
                        interpret: bool = False):
    """Run the heavy-class tile kernel.

    cT/wT: [D, H] transposed heavy rows (one vertex per column; D a
    multiple of ``d_chunk``; padding slots carry c >= n_tiles*c_tile and
    w = 0).  comm_deg: [nv_ceil] community weighted degrees, nv_ceil a
    multiple of ``c_tile`` (pad with zeros).  curr/vdeg/sl/ax: [H] per
    vertex (sl = self-loop weight, ax = comm_deg[curr] - k_i).  Returns
    (best_c [H] int, best_gain [H], counter0 [H]); best_c is the int-max
    sentinel where no valid move exists (caller keeps such vertices in
    place, same contract as the narrow kernel).
    """
    D, H = cT.shape
    (nv_ceil,) = comm_deg.shape
    assert D % d_chunk == 0, (D, d_chunk)
    assert nv_ceil % c_tile == 0, (nv_ceil, c_tile)
    grid = (H, nv_ceil // c_tile)

    row_spec = pl.BlockSpec((D, 1), lambda r, t: (0, r),
                            memory_space=pltpu.VMEM)
    ay_spec = pl.BlockSpec((1, c_tile), lambda r, t: (0, t),
                           memory_space=pltpu.VMEM)
    scalar_spec = pl.BlockSpec((1, 1), lambda r, t: (0, r),
                               memory_space=pltpu.VMEM)
    out_shapes = (
        jax.ShapeDtypeStruct((1, H), cT.dtype),
        jax.ShapeDtypeStruct((1, H), wT.dtype),
        jax.ShapeDtypeStruct((1, H), wT.dtype),
    )
    kernel = functools.partial(_kernel, c_tile=c_tile, d_chunk=d_chunk)
    bc, bg, c0 = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            row_spec, row_spec, ay_spec,
            scalar_spec, scalar_spec, scalar_spec, scalar_spec,
        ],
        out_specs=(scalar_spec, scalar_spec, scalar_spec),
        out_shape=out_shapes,
        interpret=interpret,
    )(
        jnp.reshape(constant, (1,)).astype(wT.dtype),
        cT, wT, comm_deg.reshape(1, nv_ceil),
        curr.reshape(1, H), vdeg.reshape(1, H), sl.reshape(1, H),
        ax.reshape(1, H),
    )
    return bc.reshape(H), bg.reshape(H), c0.reshape(H)
