"""Pallas TPU kernel: community-range-tile bincount dedup + gain argmax
for the HEAVY degree class (> 8192 neighbors per vertex).

Role: the TPU counterpart of the reference GPU's huge-class kernel, which
bincounts neighbor weights into a 20M-entry dense per-block scratch
indexed by dense community id (distGetMaxIndex_large_new,
/root/reference/louvain_cuda.cu:878-1022).  An O(nv) dense scratch cannot
live in VMEM (~16 MB on v5e), so this kernel tiles the COMMUNITY RANGE
(tools/heavy_kernel_design.md): for each tile [t*C, (t+1)*C) it one-hot
matmuls the row's weights against `eq(c, cand)` — duplicate aggregation
IS the bincount — and carries a running (best_gain, best_c) across tiles.

Layout: transposed [D, H] rows (H = heavy vertices, D = max heavy degree,
rows padded with c = pad id >= n_tiles*C and w = 0), one vertex per grid
row.  The neighbor-community axis is reduced in Dc-sized chunks inside a
fori_loop so VMEM holds only [Dc, C] one-hot blocks; `comm_deg` (the ay
gather of the narrow kernel) arrives as a contiguous [1, C] block per
community tile — a community-RANGE tile needs no gather at all.

Tie-break matches the narrow kernel (`row_argmax.py`) and the reference
(`louvain.cpp:2230-2238`): max gain, ties -> smaller community id.  Tiles
ascend in community id, so a strict `>` merge keeps the earlier (smaller)
id on cross-tile ties, and the in-tile rule picks the smallest candidate
among equal gains.

Status per the design note's decision rule: built for interpret-mode
correctness + the staged chip A/B (tools/heavy_ab.py); the XLA global
sort path remains the default until the chip measurement says otherwise.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128
DEFAULT_C_TILE = 512     # communities per tile ([Dc, C] one-hot block)
DEFAULT_D_CHUNK = 1024   # neighbor slots reduced per fori step
# [Dc, C] f32 one-hot + eq intermediates must sit well under v5e VMEM.
assert DEFAULT_C_TILE * DEFAULT_D_CHUNK * 4 <= (4 << 20)


def _kernel(const_ref, cT_ref, wT_ref, ay_ref, curr_ref, vdeg_ref, sl_ref,
            ax_ref, bc_ref, bg_ref, c0_ref, *, c_tile: int, d_chunk: int):
    t = pl.program_id(1)
    c = cT_ref[:]          # [D, 1] int32 neighbor communities (one vertex)
    w = wT_ref[:]          # [D, 1] f32 edge weights (0 on padding)
    ay = ay_ref[:]         # [1, C] f32 comm_deg of this community tile
    curr = curr_ref[0, 0]  # scalars of the vertex
    vdeg = vdeg_ref[0, 0]
    sl = sl_ref[0, 0]
    ax = ax_ref[0, 0]
    const = const_ref[0]
    wdt = w.dtype

    @pl.when(t == 0)
    def _init():
        # counter0 (weight into the current community, incl. self edges)
        # is row-local — one elementwise pass, no tiles involved.
        c0_ref[0, 0] = jnp.sum(jnp.where(c == curr, w, 0.0))
        bg_ref[0, 0] = jnp.asarray(-jnp.inf, dtype=wdt)
        bc_ref[0, 0] = jnp.asarray(jnp.iinfo(cT_ref.dtype).max,
                                   dtype=cT_ref.dtype)

    eix = c0_ref[0, 0] - sl
    cand = t * c_tile + jax.lax.broadcasted_iota(jnp.int32, (1, c_tile), 1)

    def chunk(k, carry):
        wagg, cnt = carry
        ck = jax.lax.dynamic_slice_in_dim(c, k * d_chunk, d_chunk, axis=0)
        wk = jax.lax.dynamic_slice_in_dim(w, k * d_chunk, d_chunk, axis=0)
        eq = (ck == cand).astype(wdt)            # [Dc, C] one-hot
        wagg = wagg + jax.lax.dot_general(        # [1, C] bincount slice
            wk, eq, (((0,), (0,)), ((), ())),
            preferred_element_type=wdt)
        # Presence COUNT, not weight: zero-weight edges are candidates
        # exactly as in the XLA paths (bucketed.py `_row_argmax` — 'No
        # w>0 filter').  Padding slots carry c >= n_tiles*c_tile so eq
        # never matches them.
        cnt = cnt + jnp.sum(eq, axis=0, keepdims=True)
        return wagg, cnt

    n_chunks = cT_ref.shape[0] // d_chunk
    zero = jnp.zeros((1, c_tile), dtype=wdt)
    wagg, cnt = jax.lax.fori_loop(0, n_chunks, chunk, (zero, zero))

    valid = (cnt > 0) & (cand != curr)
    # Operand order matches the XLA paths exactly (bucketed.py:546/633):
    # 2*(wagg-eix) - ((2*vdeg)*(ay-ax))*const.
    gain = 2.0 * (wagg - eix) - 2.0 * vdeg * (ay - ax) * const
    gain = jnp.where(valid, gain, -jnp.inf)
    tile_bg = jnp.max(gain)
    big = jnp.asarray(jnp.iinfo(cT_ref.dtype).max, dtype=cand.dtype)
    tile_bc = jnp.min(jnp.where(gain == tile_bg, cand, big))
    better = tile_bg > bg_ref[0, 0]               # strict: earlier tile
    bc_ref[0, 0] = jnp.where(
        better, tile_bc.astype(cT_ref.dtype), bc_ref[0, 0])
    bg_ref[0, 0] = jnp.where(better, tile_bg, bg_ref[0, 0])


@functools.partial(
    jax.jit,
    static_argnames=("c_tile", "d_chunk", "interpret"),
)
def heavy_argmax_pallas(cT, wT, comm_deg, curr, vdeg, sl, ax, constant, *,
                        c_tile: int = DEFAULT_C_TILE,
                        d_chunk: int = DEFAULT_D_CHUNK,
                        interpret: bool = False):
    """Run the heavy-class tile kernel.

    cT/wT: [D, H] transposed heavy rows (one vertex per column; D a
    multiple of ``d_chunk``; padding slots carry c >= n_tiles*c_tile and
    w = 0).  comm_deg: [nv_ceil] community weighted degrees, nv_ceil a
    multiple of ``c_tile`` (pad with zeros).  curr/vdeg/sl/ax: [H] per
    vertex (sl = self-loop weight, ax = comm_deg[curr] - k_i).  Returns
    (best_c [H] int, best_gain [H], counter0 [H]); best_c is the int-max
    sentinel where no valid move exists (caller keeps such vertices in
    place, same contract as the narrow kernel).
    """
    D, H = cT.shape
    (nv_ceil,) = comm_deg.shape
    assert D % d_chunk == 0, (D, d_chunk)
    assert nv_ceil % c_tile == 0, (nv_ceil, c_tile)
    grid = (H, nv_ceil // c_tile)

    row_spec = pl.BlockSpec((D, 1), lambda r, t: (0, r),
                            memory_space=pltpu.VMEM)
    ay_spec = pl.BlockSpec((1, c_tile), lambda r, t: (0, t),
                           memory_space=pltpu.VMEM)
    scalar_spec = pl.BlockSpec((1, 1), lambda r, t: (0, r),
                               memory_space=pltpu.VMEM)
    out_shapes = (
        jax.ShapeDtypeStruct((1, H), cT.dtype),
        jax.ShapeDtypeStruct((1, H), wT.dtype),
        jax.ShapeDtypeStruct((1, H), wT.dtype),
    )
    kernel = functools.partial(_kernel, c_tile=c_tile, d_chunk=d_chunk)
    bc, bg, c0 = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            row_spec, row_spec, ay_spec,
            scalar_spec, scalar_spec, scalar_spec, scalar_spec,
        ],
        out_specs=(scalar_spec, scalar_spec, scalar_spec),
        out_shape=out_shapes,
        interpret=interpret,
    )(
        jnp.reshape(constant, (1,)).astype(wT.dtype),
        cT, wT, comm_deg.reshape(1, nv_ceil),
        curr.reshape(1, H), vdeg.reshape(1, H), sl.reshape(1, H),
        ax.reshape(1, H),
    )
    return bc.reshape(H), bg.reshape(H), c0.reshape(H)
