"""cuvite_tpu — a TPU-native distributed Louvain community-detection framework.

A brand-new JAX/XLA implementation with the capabilities of pnnl/cuVite:
multi-phase distributed Louvain modularity optimization over vertex-sharded
CSR graphs, with community exchange via mesh collectives, inter-phase graph
coarsening, and Vite-binary graph I/O.

The compute path is fully jitted: one compiled step per phase, edge-parallel
segment reductions instead of the reference's per-vertex hash maps
(cf. /root/reference/louvain.cpp:2384-2431), and `jax.lax` collectives over a
device mesh instead of MPI (cf. /root/reference/louvain.cpp:2588-3116).
"""

from cuvite_tpu.core.types import Policy, default_policy
from cuvite_tpu.core.graph import Graph
from cuvite_tpu.core.distgraph import DistGraph
from cuvite_tpu.louvain.driver import louvain_phases, LouvainResult

__version__ = "0.1.0"

__all__ = [
    "Policy",
    "default_policy",
    "Graph",
    "DistGraph",
    "louvain_phases",
    "LouvainResult",
    "__version__",
]
