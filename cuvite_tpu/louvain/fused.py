"""Fully fused multi-phase Louvain: the ENTIRE clustering — iteration
loops, convergence checks, coarsening, label composition — as ONE jitted
device program.

Rationale.  The reference's control flow re-enters the host every
iteration (modularity check, louvain.cpp:541-546) and every phase
(renumber + rebuild + redistribute, main.cpp:363-428).  On TPU each host
entry is a device->host sync — expensive always, and catastrophically so
over a remote-device link.  This driver moves the whole multi-phase loop
(main.cpp:218-495) on device:

  * inner iteration loop: lax.while_loop with the threshold check on
    device (same semantics as PhaseRunner.run / _run_phase_loop);
  * coarsening (distbuildNextLevelGraph, rebuild.cpp:430-454) becomes
    RELABEL-ONLY: community ids stay in the padded vertex id space and
    edge endpoints are rewritten to their communities.  No dense
    renumbering is needed on device because renumbering is an
    order-preserving bijection: every id comparison the algorithm makes
    (argmax tie-break to the smaller id, the singleton-swap guard's
    `best > comm`) gives identical results under original or dense ids.
    Parallel edges stay unaggregated — Louvain is multigraph-invariant
    (the (c1,c2) aggregate weight equals the sum over parallel edges),
    which is what keeps every shape static across phases;
  * cross-phase label composition (commAll, main.cpp:374-403) is a
    device gather per phase.

One host sync for the whole clustering: the final labels + per-phase
stats come back in a single transfer.  Single-shard (the coarsened
relabeling would need an edge re-shard collective for SPMD; the sharded
engines in driver.py cover multi-chip).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from cuvite_tpu.core.types import CONV_ROWS_CAP, MAX_TOTAL_ITERATIONS
from cuvite_tpu.louvain.step import louvain_step_local
from cuvite_tpu.ops import segment as seg


@functools.lru_cache(maxsize=None)
def _fused_step_call(nv_pad, accum_dtype):
    """(comm, extra) adapter over louvain_step_local for _run_phase_loop
    (lru-cached for stable static-arg identity)."""

    def call(comm, extra):
        src, dst, w, vdeg, constant = extra
        out = louvain_step_local(
            src, dst, w, comm, vdeg, constant,
            nv_total=nv_pad, axis_name=None, accum_dtype=accum_dtype,
        )
        return out.target, out.modularity, out.n_moved, jnp.zeros((), bool)

    return call


def _phase_iterations(src, dst, w, vdeg, constant, threshold, lower, *,
                      nv_pad, accum_dtype, max_iters):
    """Inner iteration loop of one phase: the same _run_phase_loop the
    per-phase driver uses (single source of the convergence semantics),
    with identity comm0 and the slab as the step extras."""
    from cuvite_tpu.louvain.driver import _run_phase_loop

    comm0 = jnp.arange(nv_pad, dtype=jnp.int32)
    return _run_phase_loop(
        (src, dst, w, vdeg, constant), comm0, threshold, lower,
        call=_fused_step_call(nv_pad, accum_dtype), max_iters=max_iters,
    )


def fused_phase(src, dst, w, constant, threshold, *, nv_pad, accum_dtype,
                max_iters=MAX_TOTAL_ITERATIONS):
    """ONE phase of the fused program as a plain traceable function: the
    weighted-degree pass plus the on-device iteration loop, identity
    start, convergence check inside.  This is the unit the batched
    multi-tenant driver (louvain/batched.py, ISSUE 9) lifts over a
    leading batch axis with ``jax.vmap`` — under vmap the while_loop
    runs until EVERY row's phase converges, with finished rows' updates
    masked, so B tenants' phases share one compiled loop and one
    downstream host sync.  Returns ``(past, mod, iters, ovf,
    (cq, cmoved, covf))`` exactly like ``_run_phase_loop``.

    Deliberately NOT jitted here: callers embed it in their own jitted
    programs (``fused_louvain`` below via ``_phase_iterations``; the
    batched driver via ``vmap``)."""
    vdeg = seg.segment_sum(w, src, num_segments=nv_pad, sorted_ids=True)
    wdt = w.dtype
    lower = jnp.asarray(-1.0, dtype=wdt)
    return _phase_iterations(
        src, dst, w, vdeg, constant, jnp.asarray(threshold, dtype=wdt),
        lower, nv_pad=nv_pad, accum_dtype=accum_dtype, max_iters=max_iters,
    )


@functools.partial(
    jax.jit,
    static_argnames=("nv_pad", "max_phases", "accum_dtype", "cycling"),
)
def fused_louvain(src, dst, w, thresholds, constant, real_mask, *,
                  nv_pad, max_phases, accum_dtype=None, cycling=False,
                  prev_mod0=None, phase_budget=None, phase0=None,
                  iter_budget=None):
    """Run the full multi-phase Louvain on device.

    src/dst: [ne_pad] int32 — local == global ids (single shard), pad
    entries have src == nv_pad, w == 0, and src sorted ascending.
    thresholds: [max_phases] per-phase gain thresholds (the cycling
    schedule or a constant).  real_mask: [nv_pad] bool, true for the
    original graph's real vertices.

    ``prev_mod0`` (traced scalar) seeds the cross-phase modularity carry —
    the multilevel driver passes the previous level's converged value so
    the first phase here must beat it by the threshold, exactly as if the
    phases ran in one program.  ``phase_budget`` (traced int) caps how
    many phases may run without changing the compiled shape; the
    multilevel driver uses budget=1 to stop after one phase on a
    still-large graph and compact it on host before continuing.

    Returns (labels [nv_pad], modularity, n_phases, total_iters,
    mod_hist [max_phases], iter_hist [max_phases], nc_hist [max_phases],
    cq_hist [max_phases, CONV_ROWS_CAP], cmoved_hist [same]) — the last
    two are the per-phase convergence telemetry (ISSUE 6): per-iteration
    modularity and moved-vertex rows accumulated by _run_phase_loop's
    device buffers, scattered into the gaining phase's slot.  They ride
    the same single host sync as the stat vectors.
    """
    wdt = w.dtype
    labels0 = jnp.arange(nv_pad, dtype=jnp.int32)
    mod_hist0 = jnp.zeros(max_phases, dtype=wdt)
    iter_hist0 = jnp.zeros(max_phases, dtype=jnp.int32)
    nc_hist0 = jnp.zeros(max_phases, dtype=jnp.int32)
    cq_hist0 = jnp.zeros((max_phases, CONV_ROWS_CAP), dtype=wdt)
    cmoved_hist0 = jnp.zeros((max_phases, CONV_ROWS_CAP), dtype=jnp.int32)
    lower = jnp.asarray(-1.0, dtype=wdt)
    prev0 = lower if prev_mod0 is None else jnp.asarray(prev_mod0, dtype=wdt)
    budget = (jnp.int32(max_phases) if phase_budget is None
              else jnp.asarray(phase_budget, dtype=jnp.int32))
    # Global phase offset and remaining-iteration budget: traced, so the
    # multilevel driver's calls share one compiled program while the
    # `phase < 10` safety-net guard and the cross-phase iteration cap keep
    # their GLOBAL (whole-run) semantics.
    ph0 = (jnp.int32(0) if phase0 is None
           else jnp.asarray(phase0, dtype=jnp.int32))
    it_budget = (jnp.int32(MAX_TOTAL_ITERATIONS) if iter_budget is None
                 else jnp.asarray(iter_budget, dtype=jnp.int32))

    def count_comms(labels):
        present = jnp.zeros(nv_pad, dtype=jnp.int32).at[
            jnp.where(real_mask, labels, nv_pad)
        ].set(1, mode="drop")
        return jnp.sum(present)

    def cond(state):
        return ~state[-1]

    def body(state):
        (src, dst, w, labels, prev_mod, phase, tot_iters,
         mod_hist, iter_hist, nc_hist, cq_hist, cmoved_hist,
         _, _done) = state
        vdeg = seg.segment_sum(w, src, num_segments=nv_pad, sorted_ids=True)
        th = thresholds[jnp.minimum(phase, max_phases - 1)]
        past, mod, iters, _, (cq, cmoved, _covf) = _phase_iterations(
            src, dst, w, vdeg, constant, th, lower,
            nv_pad=nv_pad, accum_dtype=accum_dtype,
            max_iters=MAX_TOTAL_ITERATIONS,
        )
        tot_iters = tot_iters + iters
        gained = (mod - prev_mod) > th

        # Relabel-only coarsening + label composition (selected only when
        # the phase gained; while_loop bodies are uniform so the work runs
        # either way, at most once wasted).
        new_src = jnp.where(
            src < nv_pad,
            jnp.take(past, jnp.minimum(src, nv_pad - 1)),
            jnp.int32(nv_pad),
        )
        new_dst = jnp.take(past, jnp.minimum(dst, nv_pad - 1))
        order = jnp.argsort(new_src, stable=True)
        new_labels = jnp.take(past, labels)

        src2 = jnp.where(gained, jnp.take(new_src, order), src)
        dst2 = jnp.where(gained, jnp.take(new_dst, order), dst)
        w2 = jnp.where(gained, jnp.take(w, order), w)
        labels2 = jnp.where(gained, new_labels, labels)
        prev_mod2 = jnp.where(gained, jnp.maximum(mod, lower), prev_mod)

        mod_hist = jnp.where(
            gained, mod_hist.at[jnp.minimum(phase, max_phases - 1)].set(mod),
            mod_hist)
        iter_hist = jnp.where(
            gained,
            iter_hist.at[jnp.minimum(phase, max_phases - 1)].set(iters),
            iter_hist)
        nc_hist = jnp.where(
            gained,
            nc_hist.at[jnp.minimum(phase, max_phases - 1)].set(
                count_comms(labels2)),
            nc_hist)
        slot = jnp.minimum(phase, max_phases - 1)
        cq_hist = jnp.where(gained, cq_hist.at[slot].set(cq), cq_hist)
        cmoved_hist = jnp.where(
            gained, cmoved_hist.at[slot].set(cmoved), cmoved_hist)

        phase2 = jnp.where(gained, phase + 1, phase)
        done = (~gained) | (phase2 >= budget) | (tot_iters > it_budget)
        return (src2, dst2, w2, labels2, prev_mod2, phase2, tot_iters,
                mod_hist, iter_hist, nc_hist, cq_hist, cmoved_hist,
                gained, done)

    init = (src, dst, w, labels0, prev0, jnp.int32(0), jnp.int32(0),
            mod_hist0, iter_hist0, nc_hist0, cq_hist0, cmoved_hist0,
            jnp.bool_(False), jnp.bool_(False))
    (src_f, dst_f, w_f, labels, prev_mod, phase, tot_iters,
     mod_hist, iter_hist, nc_hist, cq_hist, cmoved_hist, last_gained,
     _) = jax.lax.while_loop(cond, body, init)

    if cycling:
        # Safety-net final 1e-6 pass, ONLY when the loop exited because a
        # phase failed to gain (main.cpp:432-442) — an exit via the phase
        # or iteration caps after a gaining phase runs no safety pass,
        # matching the per-phase driver.
        th_last = thresholds[jnp.minimum(phase, max_phases - 1)]
        run_extra = (~last_gained) & (ph0 + phase < 10) & (th_last > 1e-6) \
            & (phase < budget)

        def extra(args):
            labels, prev_mod, tot_iters, mod_hist, iter_hist, nc_hist, \
                cq_hist, cmoved_hist, phase = args
            vdeg = seg.segment_sum(w_f, src_f, num_segments=nv_pad,
                                   sorted_ids=True)
            past, mod, iters, _, (cq, cmoved, _covf) = _phase_iterations(
                src_f, dst_f, w_f, vdeg, constant,
                jnp.asarray(1e-6, dtype=wdt), lower,
                nv_pad=nv_pad, accum_dtype=accum_dtype,
                max_iters=MAX_TOTAL_ITERATIONS,
            )
            tot_iters = tot_iters + iters
            gained = (mod - prev_mod) > 1e-6
            labels2 = jnp.where(gained, jnp.take(past, labels), labels)
            slot = jnp.minimum(phase, max_phases - 1)
            return (
                labels2,
                jnp.where(gained, jnp.maximum(mod, lower), prev_mod),
                tot_iters,
                jnp.where(gained, mod_hist.at[slot].set(mod), mod_hist),
                jnp.where(gained, iter_hist.at[slot].set(iters), iter_hist),
                jnp.where(gained, nc_hist.at[slot].set(count_comms(labels2)),
                          nc_hist),
                jnp.where(gained, cq_hist.at[slot].set(cq), cq_hist),
                jnp.where(gained, cmoved_hist.at[slot].set(cmoved),
                          cmoved_hist),
                jnp.where(gained, phase + 1, phase),
            )

        (labels, prev_mod, tot_iters, mod_hist, iter_hist, nc_hist,
         cq_hist, cmoved_hist, phase) = jax.lax.cond(
            run_extra, extra, lambda a: a,
            (labels, prev_mod, tot_iters, mod_hist, iter_hist, nc_hist,
             cq_hist, cmoved_hist, phase),
        )

    return (labels, prev_mod, phase, tot_iters, mod_hist, iter_hist,
            nc_hist, cq_hist, cmoved_hist)
