"""Distance-1 coloring via speculative multi-hash min/max.

Equivalent of distColoringMultiHashMinMax (/root/reference/coloring.cpp:3-72):
each round evaluates nHash hash functions; an uncolored vertex that is the
strict minimum (resp. maximum) of hash t among its uncolored neighbors takes
color 2t+nextColor (resp. 2t+1+nextColor); among multiple surviving slots the
pick is the deterministic (v mod possible) walk (coloring.cpp:171-197).
Rounds repeat with nextColor += 2*nHash until >= target_percent of vertices
are colored or a round makes no progress (coloring.cpp:41-58).

Conflict-freedom is by construction: "<=" / ">=" comparisons mean a hash tie
removes BOTH directions, so two adjacent uncolored vertices can never both
stay min (or both max) for the same hash.  distCheckColoring
(coloring.cpp:447-593) is replicated as `count_conflicts` and used in tests.

TPU-first formulation: the per-round work is one jitted edge-parallel pass —
hashes are vectorized uint32 arithmetic, the per-(vertex, hash) min/max
eliminations are segment reductions, and the deterministic slot walk is a
row cumsum over the [nv, 2*nHash] availability matrix.  No per-vertex loops,
no ghost sets: the sharded variant gathers the replicated color vector the
same way the Louvain step gathers communities.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from cuvite_tpu.ops import segment as seg

UNCOLORED = -1
MAX_COVG = 70  # default target coverage percent (main.cpp:26)


def jenkins_mix(a, seed):
    """The reference's 32-bit integer mix (coloring.cpp:74-85), vectorized.

    Works on uint32 arrays; `seed` may be scalar or array.
    """
    u32 = jnp.uint32
    a = a.astype(u32) ^ jnp.asarray(seed, dtype=u32)
    a = (a + jnp.uint32(0x7ED55D16)) + (a << 12)
    a = (a ^ jnp.uint32(0xC761C23C)) + (a >> 19)
    a = (a + jnp.uint32(0x165667B1)) + (a << 5)
    a = (a ^ jnp.uint32(0xD3A2646C)) + (a << 9)
    a = (a + jnp.uint32(0xFD7046C5)) + (a << 3)
    a = (a ^ jnp.uint32(0xB55A4F09)) + (a >> 16)
    return a


def jenkins_mix_host(a: int, seed: int) -> int:
    """Host scalar version for the round-seed chain (seed = hash(seed, 0))."""
    M = 0xFFFFFFFF
    a = (a ^ seed) & M
    a = ((a + 0x7ED55D16) + (a << 12)) & M
    a = ((a ^ 0xC761C23C) + (a >> 19)) & M
    a = ((a + 0x165667B1) + (a << 5)) & M
    a = ((a ^ 0xD3A2646C) + (a << 9)) & M
    a = ((a + 0xFD7046C5) + (a << 3)) & M
    a = ((a ^ 0xB55A4F09) + (a >> 16)) & M
    return a


@functools.partial(jax.jit, static_argnames=("n_hash", "nv"))
def _coloring_round(src, dst, color, seed, next_color, *, n_hash, nv):
    """One speculative round. `src` local idx (pad >= nv), `dst` global ids
    (single-shard: global == local), `color` [nv] int32."""
    src_c = jnp.minimum(src, nv - 1)
    src_global = src  # single-shard: local == global ids
    uncolored_v = color == UNCOLORED
    neigh_color = jnp.take(color, dst)
    # participate: real edge, not a self-loop, neighbor not colored in a
    # previous round (coloring.cpp:122-145)
    participates = (src < nv) & (dst != src_global) & (neigh_color == UNCOLORED)

    not_min = []
    not_max = []
    for t in range(n_hash):
        hseed = seed + jnp.uint32(1043 * t)
        v_hash = jenkins_mix(src_global.astype(jnp.uint32), hseed)
        j_hash = jenkins_mix(dst.astype(jnp.uint32), hseed)
        # eliminations (coloring.cpp:152-161); ties kill both directions
        nm = participates & (v_hash <= j_hash)
        nn = participates & (v_hash >= j_hash)
        not_max.append(
            seg.segment_max(nm.astype(jnp.int32), src_c, num_segments=nv,
                            sorted_ids=True) > 0)
        not_min.append(
            seg.segment_max(nn.astype(jnp.int32), src_c, num_segments=nv,
                            sorted_ids=True) > 0)

    # availability slots interleaved [min_0, max_0, min_1, max_1, ...]
    # (the color value IS the slot index + next_color, coloring.cpp:180,188)
    avail = jnp.stack(
        [m for pair in zip(not_min, not_max) for m in pair], axis=1
    )
    avail = ~avail & uncolored_v[:, None]
    possible = jnp.sum(avail.astype(jnp.int32), axis=1)
    can_color = uncolored_v & (possible > 0)

    col_id = jnp.where(
        can_color,
        jnp.arange(nv, dtype=jnp.int32) % jnp.maximum(possible, 1),
        0,
    )
    rank = jnp.cumsum(avail.astype(jnp.int32), axis=1) - 1
    pick = avail & (rank == col_id[:, None])
    slot = jnp.argmax(pick, axis=1).astype(jnp.int32)
    new_color = jnp.where(can_color, slot + next_color, color)
    return new_color, jnp.sum((new_color != UNCOLORED).astype(jnp.int32))


def _round_loop(round_fn, nv: int, n_hash: int, target_percent: int,
                single_iteration: bool, seed: int):
    """The shared round loop (coloring.cpp:41-58): stop at >= target_percent
    colored, on no progress, or after one round when ``single_iteration``.
    ``round_fn(color, seed, next_color) -> (color, count)`` runs one
    speculative round; ``color`` is opaque to the loop (the full variant
    keeps it device-resident, the distributed one numpy), only the scalar
    count crosses to the host.  Defined ONCE so the two variants cannot
    drift in stop/seed semantics (their contract is bit-identity)."""
    color = np.full(nv, UNCOLORED, dtype=np.int32)
    next_color = 0
    target = (nv * target_percent) // 100
    last = 0
    while True:
        color, count = round_fn(color, seed, next_color)
        count = int(count)
        next_color += 2 * n_hash
        if single_iteration or count >= target or count == last:
            break
        seed = jenkins_mix_host(seed, 0)
        last = count
    return np.asarray(color), next_color


def multi_hash_coloring(
    src: np.ndarray,
    dst: np.ndarray,
    nv: int,
    n_hash: int = 4,
    target_percent: int = MAX_COVG,
    single_iteration: bool = False,
    seed: int = 1012,
) -> tuple[np.ndarray, int]:
    """Color vertices; returns (colors [nv] with -1 for uncolored,
    num_colors upper bound = final nextColor)."""
    src_j = jnp.asarray(src)
    dst_j = jnp.asarray(dst)

    def round_fn(color, seed_, next_color):
        return _coloring_round(
            src_j, dst_j, jnp.asarray(color), jnp.uint32(seed_),
            jnp.int32(next_color), n_hash=n_hash, nv=nv,
        )

    return _round_loop(round_fn, nv, n_hash, target_percent,
                       single_iteration, seed)


def multi_hash_coloring_dist(
    dv,
    n_hash: int = 4,
    target_percent: int = MAX_COVG,
    single_iteration: bool = False,
    seed: int = 1012,
) -> tuple[np.ndarray, int]:
    """Per-host-ingest distributed coloring, bit-identical to
    `multi_hash_coloring` on the full edge list.

    The reference colors distributed graphs with a per-round ghost color
    exchange (setUpGhostVertices + sendColoredRemoteVertices,
    /root/reference/coloring.cpp:204-420).  The TPU-native analog keeps one
    replicated O(nv) color vector per process (int32 — small even at
    benchmark scale) and, per round, (a) evaluates `_coloring_round` over
    the LOCAL edges only and (b) allgathers each process's owned slice.
    Bit-identity holds because a round's output for vertex v depends only
    on v's own rows (1-D partition: all of an owned vertex's edges are
    local), the replicated colors, and global constants — rows missing on
    this process only affect vertices owned elsewhere, whose slices are
    taken from their owners.

    ``dv`` is an `io.dist_ingest.DistVite`; returns (colors [nv] in
    ORIGINAL id space, num_colors upper bound), identical on every
    process."""
    from cuvite_tpu.comm.multihost import allgather_varlen

    nv = dv.num_vertices
    srcs, dsts = [], []
    for s in range(dv.local_lo, dv.local_hi):
        sh = dv.shards[s]
        real = np.asarray(sh.src) < dv.nv_pad
        srcs.append(np.asarray(sh.src)[real].astype(np.int64)
                    + int(dv.parts[s]))
        dsts.append(dv.pad_to_old[np.asarray(sh.dst)[real].astype(np.int64)])
    src = np.concatenate(srcs) if srcs else np.zeros(0, dtype=np.int64)
    dst = np.concatenate(dsts) if dsts else np.zeros(0, dtype=np.int64)
    lo_v = int(dv.parts[dv.local_lo])
    hi_v = int(dv.parts[dv.local_hi])

    src_j = jnp.asarray(src)
    dst_j = jnp.asarray(dst)

    def round_fn(color, seed_, next_color):
        new_color, _ = _coloring_round(
            src_j, dst_j, jnp.asarray(color), jnp.uint32(seed_),
            jnp.int32(next_color), n_hash=n_hash, nv=nv,
        )
        owned = np.asarray(new_color[lo_v:hi_v])
        # Ghost color exchange analog: processes own contiguous ascending
        # vertex ranges, so the process-ordered allgather IS the full
        # vector.
        full = np.concatenate(allgather_varlen(owned))
        assert len(full) == nv
        return full, np.sum(full != UNCOLORED)

    return _round_loop(round_fn, nv, n_hash, target_percent,
                       single_iteration, seed)


def count_conflicts(src, dst, nv, colors) -> int:
    """Distributed conflict checker analog (coloring.cpp:447-593): number of
    non-self edges whose endpoints share a color != -1."""
    src = np.asarray(src)
    dst = np.asarray(dst)
    colors = np.asarray(colors)
    real = (src < nv) & (dst != src)
    cs = colors[np.minimum(src, nv - 1)]
    cd = colors[dst]
    return int(np.sum(real & (cs == cd) & (cs != UNCOLORED)))
