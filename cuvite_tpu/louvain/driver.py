"""Multi-phase Louvain driver.

Replicates the control flow of the reference application loop
(/root/reference/main.cpp:218-495 and louvain.cpp:425-588) on top of the
jitted step:

  - per-phase iteration loop with the `(currMod - prevMod) < threshold`
    stopping rule and the pastComm/currComm/targetComm rotation semantics
    (the returned assignment is the last one whose modularity improvement
    passed the threshold, louvain.cpp:541-576);
  - threshold cycling 1e-3 -> 1e-6 over a 13-phase cycle when enabled
    (main.cpp:225-239), with the final safety 1e-6 pass (main.cpp:432-442);
  - inter-phase coarsening + cross-phase label composition
    (main.cpp:374-403, :410-428);
  - termination guards: <= 200 phases, <= 10000 total iterations
    (utils.hpp:17-19, main.cpp:486-494).
"""

from __future__ import annotations

import dataclasses
import functools
import os
import time
import warnings

import jax
import jax.numpy as jnp
import numpy as np

from cuvite_tpu.coarsen.device import (
    device_coarsen_enabled,
    device_coarsen_slab,
    maybe_shrink_to_class,
)
from cuvite_tpu.coarsen.rebuild import coarsen_graph, renumber_communities
from cuvite_tpu.comm.mesh import VERTEX_AXIS, make_mesh, shard_1d
from cuvite_tpu.comm.multihost import gather_global
from cuvite_tpu.core.distgraph import DistGraph
from cuvite_tpu.core.graph import Graph
from cuvite_tpu.coarsen.rebin import (
    device_rebin_enabled,
    device_rebin_plan,
    rebin_eligible,
    rebin_geometry,
)
from cuvite_tpu.core.types import (
    CONV_ROWS_CAP,
    ET_CUTOFF,
    MAX_TOTAL_ITERATIONS,
    P_CUTOFF,
    TERMINATION_PHASE_COUNT,
    next_pow2,
)
from cuvite_tpu.louvain.bucketed import (
    DEFAULT_BUCKETS,
    PALLAS_MAX_WIDTH,
    BucketPlan,
    bucketed_step,
    build_assemble_perm,
    build_stacked_plans,
    compress_unit_weights,
    make_sharded_bucketed_step,
)
from cuvite_tpu.louvain.precise import phase_modularity
from cuvite_tpu.louvain.step import make_sharded_step, make_single_step
from cuvite_tpu.obs.convergence import (
    MOVED_UNTRACKED,
    ConvRow,
    PhaseConvergence,
    decode_phase_conv,
)
from cuvite_tpu.utils.upload import aligned_copy, to_device


def threshold_for_phase(short_phase: int) -> float:
    """Threshold-cycling schedule (main.cpp:225-237)."""
    sp = short_phase % 13
    if sp <= 2:
        return 1.0e-3
    if sp <= 6:
        return 1.0e-4
    if sp <= 9:
        return 1.0e-5
    return 1.0e-6


@dataclasses.dataclass
class PhaseStats:
    phase: int
    modularity: float
    iterations: int
    num_vertices: int
    num_edges: int
    seconds: float


@dataclasses.dataclass
class LouvainResult:
    communities: np.ndarray   # [nv original] dense community label per vertex
    modularity: float
    phases: list
    total_iterations: int
    total_seconds: float
    # engine='pallas' kernel-coverage accounting (None on other engines):
    # fraction of TRAVERSED edges (edge mass x iterations, summed over
    # phases) that ran through the Pallas row kernel, and the per-width
    # traversed-edge counts behind it ({width: edges}, width 0 = the
    # heavy class, kernelized widths flagged by workloads/bench.py).
    pallas_coverage: float | None = None
    pallas_width_hits: dict | None = None
    # Per-phase convergence telemetry (ISSUE 6): list of
    # obs.PhaseConvergence — one entry per phase ATTEMPT in run order
    # (the per-phase drivers record non-gaining final attempts too, with
    # ``gained=False``; the fused engine records gaining phases only).
    # None when the run predates telemetry (e.g. deserialized results).
    convergence: list | None = None
    # Phase-1 ExchangePlan.stats() of an SPMD run (ISSUE 18): mode plus
    # — on a two-level run — dcn/ici and the per-device table/ghost
    # bytes.  None on single-shard runs and other engines' paths.
    exchange_stats: dict | None = None

    @property
    def num_communities(self) -> int:
        return int(self.communities.max()) + 1 if len(self.communities) else 0


def _device_dtype(dt: np.dtype) -> np.dtype:
    """Clamp 64-bit host dtypes to 32-bit unless jax_enable_x64 is on, so
    wide (bits64) graphs run on TPU without per-array truncation warnings."""
    if jax.config.jax_enable_x64:
        return dt
    if dt == np.float64:
        return np.dtype(np.float32)
    if dt == np.int64:
        return np.dtype(np.int32)
    return dt


# Compiled-step cache: phases whose pow2-padded shapes coincide reuse the
# same jitted callable (jax.jit caches compilations per callable object, so
# recreating the closure each phase would retrace and recompile every time).
_STEP_CACHE: dict = {}

# 2m above which the IN-LOOP convergence check switches from plain f32 to
# double-single accumulation (ops/exactsum.py): an f32 tree sum of n
# same-sign addends carries worst-case relative error ~log2(n) * 2^-24,
# which crosses the 1e-6 convergence threshold around n = 2^24 — while the
# per-phase REPORTED value was already ds-precise (louvain/precise.py), the
# `(mod - prev_mod) < threshold` decision inside the device loop was not
# (VERDICT r2 weak #3).  Cf. the reference's double accumulation,
# /root/reference/louvain.cpp:2433-2481.
DS_MIN_TOTAL_WEIGHT = float(1 << 24)


def _accum_name(adt, total_weight_twice: float, n_addends: int = 0) -> str:
    """Static accum_dtype tag for the step: the dtype name, or 'ds32' when
    the graph is big enough that plain f32 in-loop sums are threshold-unsafe
    (f64 accumulation — the x64 oracle mode — is already exact enough).

    The f32 tree-sum error scales with the ADDEND COUNT (log2(n) * 2^-24
    relative), and Q's threshold is absolute on an O(1) value, so the gate
    tests both the weight mass AND the reduction length (``n_addends`` =
    max(directed edges, padded vertices)) — a 2^25-edge graph of 1e-3
    weights is exactly as threshold-unsafe as a unit-weight one."""
    if np.dtype(adt) == np.float32 \
            and max(float(total_weight_twice),
                    float(n_addends)) >= DS_MIN_TOTAL_WEIGHT:
        from cuvite_tpu.ops.segment import DS_ACCUM

        return DS_ACCUM
    return np.dtype(adt).name


def _source_fingerprint(graph) -> int:
    """Checkpoint content fingerprint of the ORIGINAL input: full-ingest
    graphs hash their CSR (utils.checkpoint.graph_fingerprint); per-host
    partitions combine per-shard hashes across processes
    (DistVite.content_fingerprint)."""
    if getattr(graph, "local_only", False):
        return graph.content_fingerprint()
    from cuvite_tpu.utils.checkpoint import graph_fingerprint

    return graph_fingerprint(graph)


def _runner_slab(runner):
    """Device-resident (src, dst, w) of a single-shard slab engine, or None
    (bucketed engines hold no slab on device; never upload one just for the
    phase-end modularity pass)."""
    if runner is not None and runner.dg.nshards == 1 \
            and runner.src is not None:
        return (runner.src, runner.dst, runner.w)
    return None


def _get_step(mesh, nv_total: int, accum_dtype) -> object:
    key = (
        None if mesh is None else tuple(d.id for d in mesh.devices.flat),
        nv_total,
        accum_dtype if isinstance(accum_dtype, str)
        else np.dtype(accum_dtype).name if accum_dtype is not None else None,
    )
    step = _STEP_CACHE.get(key)
    if step is None:
        if mesh is not None and np.prod(mesh.devices.shape) > 1:
            step = make_sharded_step(mesh, VERTEX_AXIS, nv_total,
                                     accum_dtype=accum_dtype)
        else:
            step = make_single_step(nv_total, accum_dtype=accum_dtype)
        _STEP_CACHE[key] = step
    return step


@functools.partial(
    jax.jit,
    static_argnames=("nv_total", "sentinel", "accum_dtype", "pallas_flags",
                     "pallas_interpret"),
)
def _bucketed_jit(bucket_arrays, heavy_arrays, self_loop, comm, vdeg,
                  constant, assemble_perm=None, heavy_kernel=None, *,
                  nv_total, sentinel, accum_dtype, pallas_flags=(),
                  pallas_interpret=False):
    call = _bucketed_call(nv_total, sentinel, accum_dtype, pallas_flags,
                          pallas_interpret)
    return call(comm, (bucket_arrays, heavy_arrays, self_loop, vdeg,
                       constant, assemble_perm, heavy_kernel))


@functools.partial(
    jax.jit, static_argnames=("nv_total", "sentinel", "accum_dtype"),
)
def _bucketed_class_jit(bucket_arrays, heavy_arrays, self_loop, comm,
                        info_comm, vdeg, constant, *, nv_total, sentinel,
                        accum_dtype):
    """Class-restricted sweep: the plan covers one color class's vertices;
    ``info_comm`` (may alias comm) freezes the community-info tables for
    the vertex-ordering schedule."""
    from cuvite_tpu.louvain.bucketed import bucketed_step

    return bucketed_step(
        bucket_arrays, heavy_arrays, self_loop, comm, vdeg, constant,
        nv_total=nv_total, sentinel=sentinel, accum_dtype=accum_dtype,
        info_comm=info_comm,
    )


@functools.partial(jax.jit, static_argnames=("nv_total", "accum_dtype"))
def _bucketed_mod_jit(bucket_arrays, heavy_arrays, self_loop, comm, vdeg,
                      constant, *, nv_total, accum_dtype):
    from cuvite_tpu.louvain.bucketed import bucketed_modularity

    return bucketed_modularity(
        bucket_arrays, heavy_arrays, self_loop, comm, vdeg, constant,
        nv_total=nv_total, accum_dtype=accum_dtype,
    )


# ---------------------------------------------------------------------------
# On-device phase loop.
#
# The reference re-checks `(currMod - prevMod) < threshold` on the host every
# iteration (louvain.cpp:541-546) — on TPU that is one blocking device->host
# scalar fetch per iteration, which over a remote device link costs orders of
# magnitude more than the step itself.  The TPU-native driver runs the whole
# iteration loop inside one lax.while_loop, with the convergence check on
# device, and syncs once per phase.  Semantics are identical to
# PhaseRunner.run's Python loop (the returned assignment is `past`, the last
# one whose gain passed the threshold).
#
# Convergence telemetry (ISSUE 6): each iteration also writes one
# (Q, moved, overflow) row into fixed CONV_ROWS_CAP-sized buffers carried
# through the while_loop; rows beyond the cap drop on device (mode="drop"
# scatter — the PhaseConvergence decode flags truncation from the exact
# scalar count).  The buffers return with the scalars and ride the SAME
# per-phase host sync — zero added syncs, and the step's decisions never
# read them, so labels are bit-identical with or without a consumer.

def _conv_init(wdt):
    return (jnp.zeros((CONV_ROWS_CAP,), dtype=wdt),
            jnp.zeros((CONV_ROWS_CAP,), dtype=jnp.int32),
            jnp.zeros((CONV_ROWS_CAP,), dtype=bool))


def _conv_push(conv, iters, mod, moved, step_ovf):
    cq, cmoved, covf = conv
    return (cq.at[iters].set(mod, mode="drop"),
            cmoved.at[iters].set(moved.astype(jnp.int32), mode="drop"),
            covf.at[iters].set(step_ovf, mode="drop"))


@functools.partial(jax.jit, static_argnames=("call", "max_iters"))
def _run_phase_loop(extra, comm0, threshold, lower, *, call, max_iters):
    wdt = lower.dtype

    def cond(c):
        return ~c[4]

    def body(c):
        past, comm, prev_mod, iters, _, ovf, conv = c
        # Uniform step contract: (target, modularity, n_moved, overflow).
        # The overflow flag (sparse-exchange budget) accumulates so the host
        # detects an invalid phase with ONE sync at the end.
        target, mod, moved, step_ovf = call(comm, extra)
        mod = mod.astype(wdt)
        no_gain = (mod - prev_mod) < threshold
        # The no-gain sweep's proposals are rolled back below (new_comm
        # keeps comm): its row records 0 applied moves, not the
        # discarded proposal count — moved_total() must equal real
        # label churn.
        conv = _conv_push(conv, iters, mod,
                          jnp.where(no_gain, 0, moved), step_ovf)
        iters1 = iters + 1
        stop = no_gain | (iters1 >= max_iters)
        new_prev = jnp.where(no_gain, prev_mod, jnp.maximum(mod, lower))
        new_past = jnp.where(no_gain, past, comm)
        new_comm = jnp.where(no_gain, comm, target)
        return (new_past, new_comm, new_prev, iters1, stop, ovf | step_ovf,
                conv)

    init = (comm0, comm0, lower, jnp.int32(0), jnp.bool_(False),
            jnp.zeros((), dtype=bool), _conv_init(wdt))
    past, _, prev_mod, iters, _, ovf, conv = jax.lax.while_loop(
        cond, body, init)
    return past, prev_mod, iters, ovf, conv


@functools.partial(
    jax.jit,
    static_argnames=("call", "max_iters", "et_mode", "nv_real"),
)
def _run_phase_loop_et(extra, comm0, threshold, lower, active0, et_delta,
                       *, call, max_iters, et_mode, nv_real):
    """On-device phase loop with early-termination state in the carry
    (VERDICT round-1 item 10): freeze masks / decay probabilities update on
    device, so ET modes 1-4 cost ONE host sync per phase like the default
    path (the reference syncs per iteration; cf. louvain.cpp:7-423).

    Semantics match PhaseRunner.run's host ET loop exactly: targets masked
    by ``active``; freeze updates applied from iteration 3 on, only when
    the loop continues; modes 3/4 stop once >= ET_CUTOFF of real vertices
    are frozen (checked before the threshold test, like the host loop).
    """
    wdt = lower.dtype
    et_stop = et_mode in (3, 4)
    prob = et_mode in (2, 4)

    def cond(c):
        return ~c[4]

    def body(c):
        past, comm, prev_mod, iters, _, ovf, active, p_act, conv = c
        target, mod, _, step_ovf = call(comm, extra)
        target = jnp.where(active, target, comm)
        mod = mod.astype(wdt)
        # Recount APPLIED moves after the freeze mask: the step's n_moved
        # counts proposals, including frozen vertices whose moves the
        # mask just discarded — the telemetry rows must reflect real
        # label churn (non-movers keep target == comm in every step, so
        # the recount equals sum(active & move)).
        moved = jnp.sum((target != comm).astype(jnp.int32))
        iters1 = iters + 1
        if et_stop:
            frozen = nv_real - jnp.sum(active.astype(jnp.int32))
            frozen_stop = frozen.astype(wdt) >= wdt.type(ET_CUTOFF * nv_real)
        else:
            frozen_stop = jnp.bool_(False)
        no_gain = (mod - prev_mod) < threshold
        stop = no_gain | frozen_stop | (iters1 >= max_iters)
        cont = ~(no_gain | frozen_stop)
        # Like the default loop: a stopping sweep's proposals are rolled
        # back (new_comm keeps comm), so its row records 0 applied moves.
        conv = _conv_push(conv, iters, mod,
                          jnp.where(cont, moved, 0), step_ovf)
        upd = cont & (iters1 > 2)
        if prob:
            decayed = active & (comm == past)
            p_new = jnp.where(upd & decayed, p_act * (1.0 - et_delta),
                              p_act)
            freeze = decayed & (p_new <= P_CUTOFF)
            active_new = jnp.where(upd, active & ~freeze, active)
            p_act = p_new
        else:
            stable = (target == comm) & (comm == past)
            active_new = jnp.where(upd, active & ~stable, active)
        new_prev = jnp.where(cont, jnp.maximum(mod, lower), prev_mod)
        new_past = jnp.where(cont, comm, past)
        new_comm = jnp.where(cont, target, comm)
        return (new_past, new_comm, new_prev, iters1, stop,
                ovf | step_ovf, active_new, p_act, conv)

    p0 = jnp.ones_like(comm0, dtype=wdt)
    init = (comm0, comm0, lower, jnp.int32(0), jnp.bool_(False),
            jnp.zeros((), dtype=bool), active0, p0, _conv_init(wdt))
    past, _, prev_mod, iters, _, ovf, _, _, conv = jax.lax.while_loop(
        cond, body, init)
    return past, prev_mod, iters, ovf, conv


def warm_start_phase(extra, comm0, threshold, active0, *, call,
                     max_iters=MAX_TOTAL_ITERATIONS, nv_real):
    """Public seam for streaming warm starts (stream/session.py, ISSUE
    17): one on-device ET phase loop (mode-1 freeze semantics) whose
    phase-0 labels and active set come from the CALLER — the previous
    run's composed labels and the delta frontier — instead of identity
    and "all".  Phase semantics are exactly :func:`_run_phase_loop_et`:
    a warm assignment whose first improvement sweep gains less than
    ``threshold`` is returned unchanged (the last assignment whose gain
    passed), so a no-op delta re-cluster keeps the warm labels bit-for-
    bit.  Returns ``(labels, modularity, iterations, overflow, conv)``.
    """
    wdt = extra[2].dtype
    lower = jnp.asarray(-1.0, dtype=wdt)
    return _run_phase_loop_et(
        extra, comm0, jnp.asarray(threshold, dtype=wdt), lower, active0,
        jnp.asarray(0.25, dtype=wdt), call=call, max_iters=max_iters,
        et_mode=1, nv_real=nv_real)


def _phase_sync(labels, *rest):
    """THE per-phase device->host sync chokepoint: labels + the scalar/
    telemetry pytree come back in ONE transfer (a single jax.device_get
    of the whole tuple), so the host blocks exactly once per phase — the
    property tests/test_obs.py's sync spy pins.  Multi-host runs need the
    collective allgather for the sharded labels; the replicated scalars
    still batch into one fetch."""
    from cuvite_tpu.comm.multihost import is_distributed

    if not is_distributed():
        out = jax.device_get((labels, rest))  # graftlint: disable=R010 — THE per-phase scalar+label sync chokepoint
        return np.asarray(out[0]), out[1]
    return gather_global(labels), jax.device_get(rest)  # graftlint: disable=R010 — replicated scalars, O(CONV_ROWS_CAP)


@functools.lru_cache(maxsize=None)
def _bucketed_call(nv_total, sentinel, accum_dtype, pallas_flags=(),
                   pallas_interpret=False):
    def call(comm, extra):
        # The trailing heavy_kernel slot is None (sorted heavy path) or
        # the (verts, dstT, wT) layout of the promoted heavy kernel —
        # pytree structure, so each engagement state traces separately.
        buckets, heavy, self_loop, vdeg, constant, perm, hk = extra
        return bucketed_step(
            buckets, heavy, self_loop, comm, vdeg, constant,
            nv_total=nv_total, sentinel=sentinel, accum_dtype=accum_dtype,
            pallas_flags=pallas_flags, pallas_interpret=pallas_interpret,
            assemble_perm=perm, heavy_kernel=hk,
        )

    return call


@functools.lru_cache(maxsize=None)
def _bucketed_sharded_call(step_fn):
    def call(comm, extra):
        buckets, heavy, self_loop, vdeg, constant, perm, *plan = extra
        return step_fn(buckets, heavy, self_loop, comm, vdeg, constant,
                       perm, *plan)

    return call


@functools.lru_cache(maxsize=None)
def _step_call(step):
    """Adapt a cached (src,dst,w,comm,vdeg,constant) step — jitted closure
    or shard_map wrapper — to the (comm, extra) loop convention.  lru_cache
    keeps the wrapper's identity stable so _run_phase_loop's static `call`
    does not retrace on reuse."""

    def call(comm, extra):
        src, dst, w, vdeg, constant = extra
        return step(src, dst, w, comm, vdeg, constant)

    return call


class PhaseRunner:
    """Runs the iteration loop of one phase on a device mesh.

    ``engine``: 'sort' — the edge-slab sort/segment step; 'bucketed' — the
    degree-bucketed engine, the analog of the reference GPU's degree-class
    kernels; 'pallas' — bucketed with the <= PALLAS_MAX_WIDTH classes
    routed through the row-argmax kernel (single-shard AND inside the
    shard_map body on a mesh, both exchanges).  All run single-shard or
    SPMD over a mesh.
    """

    def __init__(self, dg: DistGraph, mesh=None, engine: str = "sort",
                 budget: int | None = None, exchange: str = "sparse",
                 color_local=None, n_color_classes: int = 0,
                 ordering: bool = False, release_slabs: bool = False,
                 tracer=None, device_rebin: bool = False):
        if tracer is None:
            from cuvite_tpu.utils.trace import NullTracer

            tracer = NullTracer()
        if engine not in ("sort", "bucketed", "pallas"):
            raise ValueError(f"unknown engine {engine!r}; use 'sort', "
                             "'bucketed' or 'pallas' ('auto' is resolved "
                             "by louvain_phases)")
        if exchange not in ("sparse", "replicated", "twolevel"):
            raise ValueError(f"unknown exchange {exchange!r}")
        if exchange == "twolevel":
            from cuvite_tpu.comm.mesh import DCN_AXIS, ICI_AXIS

            if mesh is None or mesh.axis_names != (DCN_AXIS, ICI_AXIS):
                raise ValueError(
                    "exchange='twolevel' needs a 2-D hybrid mesh "
                    "(comm.mesh.make_hybrid_mesh)")
            if engine not in ("bucketed", "pallas"):
                raise ValueError(
                    "exchange='twolevel' runs on the bucketed/pallas "
                    "engines only")
            if color_local is not None and n_color_classes > 0:
                raise ValueError(
                    "exchange='twolevel' does not support the coloring/"
                    "ordering schedules yet (use exchange='sparse')")
        self.dg = dg
        self.mesh = mesh
        self.engine = engine
        self.labels_dev = None      # device labels of the last run() phase
        self.convergence = None     # PhaseConvergence of the last run()
        self.budget = None
        self.rebin_device = False   # True when this phase's plan was
                                    # built on device (coarsen/rebin.py)

        def _up(x, dtype=None):
            # Every host->device placement funnels through here so the
            # bench's upload_s stage covers it (runs NESTED inside the
            # driver's plan stage on this path; trace.CANONICAL_STAGES).
            # Device-resident inputs pass through untimed-fast (to_device
            # short-circuits jax arrays).
            with tracer.stage("upload"):
                return to_device(x, dtype)
        self.ghost_counts = None    # per-shard ghost counts (sparse plan)
        self.xplan_stats = None     # ExchangePlan.stats() (sparse plan)
        self._class_plans = None    # per-color-class bucket plans
        self._mod_args = None       # full-plan args for the mod pass
        self._mod_fn = None         # sharded mod fn (SPMD class schedule)
        self._class_sharded = False
        self.ordering = bool(ordering)
        nv_total = dg.total_padded_vertices
        vdeg = dg.padded_weighted_degrees()
        vdt = _device_dtype(dg.graph.policy.vertex_dtype)
        wdt = _device_dtype(dg.graph.policy.weight_dtype)
        vdeg = vdeg.astype(wdt)
        comm0 = np.arange(nv_total, dtype=vdt)
        tw = dg.graph.total_edge_weight_twice()
        adt = _accum_name(_device_dtype(dg.graph.policy.accum_dtype), tw,
                          max(dg.graph.num_edges, nv_total))
        self.accum_name = adt
        multi = mesh is not None and int(np.prod(mesh.devices.shape)) > 1
        if engine in ("bucketed", "pallas") and multi:
            # SPMD bucketed path: per-shard plans padded to common shapes,
            # sharded along the mesh.  Default exchange is the sparse ghost
            # plan (comm volume O(owned + ghosts) per iteration); exchange=
            # 'replicated' keeps the all_gather/psum formulation.
            # engine='pallas' additionally lays the <= PALLAS_MAX_WIDTH
            # classes out transposed and runs them through the row-argmax
            # kernel INSIDE the shard_map body (both exchanges) — the SPMD
            # analog of the reference's per-rank device kernels
            # (/root/reference/louvain.cpp:591-754).  With a color/ordering
            # schedule the iteration runs the per-class plans only (the
            # main step is never swept), so the main plan keeps the XLA
            # layout there — exactly the single-shard pallas contract,
            # where class plans are XLA too.
            sentinel = int(np.iinfo(vdt).max)
            use_twolevel = exchange == "twolevel"
            use_sparse = exchange in ("sparse", "twolevel")
            use_pallas = (engine == "pallas"
                          and not (color_local is not None
                                   and n_color_classes > 0))
            pallas_widths = tuple(
                w for w in DEFAULT_BUCKETS
                if w <= PALLAS_MAX_WIDTH) if use_pallas else ()
            interp = jax.default_backend() != "tpu"
            adt_np = adt  # static accum tag (dtype name or 'ds32')
            S = dg.nshards
            local_only = getattr(dg, "local_only", False)
            if local_only and not use_sparse:
                raise ValueError(
                    "per-host ingest (DistVite) requires exchange='sparse' "
                    "— the replicated exchange needs full host arrays")
            S_rows = (dg.local_hi - dg.local_lo) if local_only else S

            def _place(arr):
                # Plan arrays' leading dim covers S_rows shard rows; the
                # global array covers S.  Fully-resident partitions place
                # the whole array; per-host ingest contributes its block.
                with tracer.stage("upload"):
                    if not local_only:
                        return shard_1d(mesh, arr)
                    from jax.sharding import PartitionSpec as P

                    from cuvite_tpu.comm.multihost import place_block

                    rows = (arr.shape[0] // S_rows) * S
                    return place_block(mesh, arr, rows, P(VERTEX_AXIS))

            if use_twolevel:
                # Two-level (ISSUE 18): grouped plan routed on the DCN
                # axis, community tables gathered to group scale on the
                # ICI axis.  Plan arrays shard over DCN only — each ICI
                # sibling holds its whole group's routing rows.
                from cuvite_tpu.comm.exchange import ExchangePlan
                from cuvite_tpu.comm.mesh import (
                    DCN_AXIS, ICI_AXIS, hybrid_shape, shard_outer)

                n_dcn, n_ici = hybrid_shape(mesh)
                xplan = ExchangePlan.build_grouped(dg, n_dcn)
                self.xplan_stats = xplan.stats(
                    itemsize=np.dtype(vdt).itemsize)
                self.ghost_counts = self.xplan_stats["ghosts_per_shard"]
                if budget is None:
                    budget = max(128, xplan.nv_pad // 4)
                budget = min(int(budget), xplan.nv_pad)
                self.budget = budget
                plan = build_stacked_plans(dg, exchange_plan=xplan,
                                           pallas_widths=pallas_widths,
                                           count_width_edges=use_pallas)
                with tracer.stage("upload"):
                    self._send_idx = shard_outer(mesh, xplan.send_idx.reshape(
                        n_dcn * n_dcn, xplan.block))
                    self._ghost_sel = shard_outer(
                        mesh, xplan.ghost_sel.reshape(
                            n_dcn * xplan.ghost_pad))
                sparse_cfg = (n_dcn, budget)
                # The (dcn, ici) factorization is part of the program —
                # every hybrid shape of one device pool shares the same
                # device-id tuple, so the ids alone would alias steps
                # compiled for different groupings.
                key = ("bucketed-twolevel", (n_dcn, n_ici),
                       tuple(d.id for d in mesh.devices.flat),
                       len(plan.buckets), nv_total, sentinel, adt_np,
                       budget, plan.pallas_flags, interp)
            elif use_sparse:
                from cuvite_tpu.comm.exchange import ExchangePlan

                xplan = ExchangePlan.build(dg)
                self.xplan_stats = xplan.stats(
                    itemsize=np.dtype(vdt).itemsize)
                self.ghost_counts = self.xplan_stats["ghosts_per_shard"]
                if budget is None:
                    budget = max(128, dg.nv_pad // 4)
                budget = min(int(budget), dg.nv_pad)
                self.budget = budget
                plan = build_stacked_plans(dg, exchange_plan=xplan,
                                           pallas_widths=pallas_widths,
                                           count_width_edges=use_pallas)
                self._send_idx = _place(
                    xplan.send_idx.reshape(S_rows * S, xplan.block))
                self._ghost_sel = _place(
                    xplan.ghost_sel.reshape(S_rows * xplan.ghost_pad))
                sparse_cfg = (S, budget)
                key = ("bucketed-sparse",
                       tuple(d.id for d in mesh.devices.flat),
                       len(plan.buckets), nv_total, sentinel, adt_np,
                       budget, plan.pallas_flags, interp)
            else:
                plan = build_stacked_plans(dg, pallas_widths=pallas_widths,
                                           count_width_edges=use_pallas)
                sparse_cfg = None
                key = ("bucketed", tuple(d.id for d in mesh.devices.flat),
                       len(plan.buckets), nv_total, sentinel, adt_np,
                       plan.pallas_flags, interp)
            flags = plan.pallas_flags or (False,) * len(plan.buckets)

            def _tpose(m, nb):
                # Kernel-class layout: [S_rows*Nb, D] -> [S_rows*D, Nb], so
                # the axis-0 sharding hands each shard the [D, Nb] block
                # the row kernel consumes directly (no per-iteration
                # transpose on device).
                rows = m.shape[0] // nb
                return np.ascontiguousarray(
                    m.reshape(rows, nb, m.shape[1]).transpose(0, 2, 1)
                ).reshape(rows * m.shape[1], nb)

            buckets = []
            for i, (v, d, ww) in enumerate(plan.buckets):
                # dtype agreed across hosts via the plan's allreduced
                # unit-weight flags (NOT a per-process decision).
                w8 = np.uint8 if plan.unit_weights[i] else wdt
                if flags[i]:
                    nb = v.shape[0] // S_rows
                    buckets.append((
                        _place(v.astype(vdt)),
                        _place(_tpose(d.astype(vdt), nb)),
                        _place(_tpose(ww.astype(w8), nb)),
                    ))
                else:
                    buckets.append((_place(v.astype(vdt)),
                                    _place(d.astype(vdt)),
                                    _place(ww.astype(w8))))
            buckets = tuple(buckets)
            heavy = tuple(
                _place(a.astype(t))
                for a, t in zip(plan.heavy, (vdt, vdt, wdt))
            )
            self_loop = _place(plan.self_loop.astype(wdt))
            perm_dev = _place(plan.perm)
            if use_pallas:
                self._record_pallas_coverage([
                    (w, int(plan.width_edges[k]), w <= PALLAS_MAX_WIDTH)
                    for k, w in enumerate(DEFAULT_BUCKETS)
                    if plan.width_edges[k]
                ] + ([(0, int(plan.width_edges[-1]), False)]
                     if plan.width_edges[-1] else []))
            step_fn = _STEP_CACHE.get(key)
            if step_fn is None:
                if use_twolevel:
                    from cuvite_tpu.comm.mesh import DCN_AXIS, ICI_AXIS

                    step_fn = make_sharded_bucketed_step(
                        mesh, DCN_AXIS, len(buckets), nv_total, sentinel,
                        accum_dtype=adt_np, sparse=sparse_cfg,
                        pallas_flags=flags, pallas_interpret=interp,
                        ici_axis=ICI_AXIS,
                    )
                else:
                    step_fn = make_sharded_bucketed_step(
                        mesh, VERTEX_AXIS, len(buckets), nv_total, sentinel,
                        accum_dtype=adt_np, sparse=sparse_cfg,
                        pallas_flags=flags, pallas_interpret=interp,
                    )
                _STEP_CACHE[key] = step_fn

            plan_args = ((self._send_idx, self._ghost_sel) if use_sparse
                         else ())

            def _step(src_, dst_, w_, comm, vdeg_, constant):
                return step_fn(buckets, heavy, self_loop, comm, vdeg_,
                               constant, perm_dev, *plan_args)

            self._step = _step
            self._call = _bucketed_sharded_call(step_fn)
            self._bucket_extra = (buckets, heavy, self_loop,
                                  perm_dev) + plan_args
            self.src = self.dst = self.w = None
            if color_local is not None and n_color_classes > 0:
                # Distributed class-restricted sweeps (VERDICT r2 missing
                # #1; sparse support = VERDICT r3 item 5): one stacked plan
                # per color class, each sweeping only its class's vertices
                # on every shard — an iteration costs ~one sweep total
                # instead of n_classes full sweeps (the reference's
                # distributed -c/-d schedule,
                # /root/reference/louvain.cpp:862-901, :1535-1562).  The
                # sparse exchange stacks the per-class plans over the SAME
                # phase-static ghost routing (routing is class-independent);
                # class steps and the mod pass then surface live overflow
                # flags exactly like the plain sparse step.
                from cuvite_tpu.louvain.bucketed import (
                    make_sharded_bucketed_mod,
                    make_sharded_class_step,
                )

                self._class_sharded = True
                self._class_plans = []
                xp = xplan if use_sparse else None
                for c in range(n_color_classes):
                    pc = build_stacked_plans(dg, class_of=color_local,
                                             class_id=c, exchange_plan=xp)
                    bk = tuple(
                        (_place(v.astype(vdt)), _place(d.astype(vdt)),
                         _place(ww.astype(
                             np.uint8 if pc.unit_weights[i] else wdt)))
                        for i, (v, d, ww) in enumerate(pc.buckets)
                    )
                    hv = tuple(_place(a.astype(t))
                               for a, t in zip(pc.heavy, (vdt, vdt, wdt)))
                    slc = _place(pc.self_loop.astype(wdt))
                    pmc = _place(pc.perm)
                    kc = ("bucketed-class",
                          tuple(d.id for d in mesh.devices.flat),
                          len(pc.buckets), nv_total, sentinel, adt_np,
                          self.ordering, sparse_cfg)
                    stepc = _STEP_CACHE.get(kc)
                    if stepc is None:
                        stepc = make_sharded_class_step(
                            mesh, VERTEX_AXIS, len(pc.buckets), nv_total,
                            sentinel, accum_dtype=adt_np,
                            sparse=sparse_cfg, ordering=self.ordering)
                        _STEP_CACHE[kc] = stepc
                    self._class_plans.append((bk, hv, slc, pmc, stepc))
                self._class_plan_args = plan_args
                km = ("bucketed-mod",
                      tuple(d.id for d in mesh.devices.flat),
                      len(buckets), nv_total, adt_np, sparse_cfg)
                modf = _STEP_CACHE.get(km)
                if modf is None:
                    modf = make_sharded_bucketed_mod(
                        mesh, VERTEX_AXIS, len(buckets), nv_total,
                        accum_dtype=adt_np, sparse=sparse_cfg)
                    _STEP_CACHE[km] = modf
                self._mod_fn = modf
                self._mod_args = (buckets, heavy, self_loop)
        elif engine in ("bucketed", "pallas"):
            # The bucket matrices replace the edge slab entirely: don't
            # upload src/dst/w (they would double edge memory on device).
            sh = dg.shards[0]
            sentinel = int(np.iinfo(vdt).max)
            interp = jax.default_backend() != "tpu"
            # With a coloring/ordering schedule the iteration sweeps the
            # per-class plans (XLA) and the mod pass only — the main plan
            # is never executed, so kernelizing it would waste the
            # transposed upload AND report a kernel coverage no sweep ever
            # ran (same exclusion as the SPMD branch above).
            class_sched = (color_local is not None
                           and n_color_classes > 0)
            # Device re-binning (ISSUE 19): coarse phases of the plain
            # bucketed engine build the plan ON DEVICE (coarsen/rebin.py)
            # — no host histogram, no per-phase BucketPlan.build, no
            # per-bucket uploads.  The slab is padded to a pow2 edge
            # class (floor = louvain_phases' min_ne_pad) so the jitted
            # builder compiles once per class across phases.  The
            # pallas / heavy-kernel / coloring paths need the host
            # plan's data-dependent layouts, and ineligible classes
            # (possible heavy residual, element budget) keep the host
            # oracle.
            src_np = np.asarray(sh.src)
            ne_class = max(next_pow2(max(len(src_np), 1)), 16384)
            use_dev_rebin = (device_rebin and engine == "bucketed"
                             and not class_sched
                             and device_rebin_enabled()
                             and rebin_eligible(dg.nv_pad, ne_class))
            self.rebin_device = use_dev_rebin
            if device_rebin and engine == "bucketed" and not class_sched:
                # Bench coverage counters (ISSUE 19): coarse bucketed
                # phases that COULD re-bin on device vs those that did —
                # the record's optional `rebin_device` fraction.
                tracer.count("rebin_phases", 1)
                if use_dev_rebin:
                    tracer.count("rebin_device_phases", 1)
            if use_dev_rebin:
                dst_np = np.asarray(sh.dst)
                w_np = np.asarray(sh.w)
                ne_in = len(src_np)
                if ne_class > ne_in:
                    pad = ne_class - ne_in
                    src_np = np.concatenate(
                        [src_np,
                         np.full(pad, dg.nv_pad, dtype=src_np.dtype)])
                    dst_np = np.concatenate(
                        [dst_np, np.zeros(pad, dtype=dst_np.dtype)])
                    w_np = np.concatenate(
                        [w_np, np.zeros(pad, dtype=w_np.dtype)])
                geom = rebin_geometry(dg.nv_pad, ne_class)
                src_d = _up(src_np, vdt)
                dst_d = _up(dst_np, vdt)
                w_d = _up(w_np, wdt)
                with tracer.stage("rebin"):
                    buckets, heavy, self_loop, perm_dev = \
                        device_rebin_plan(src_d, dst_d, w_d,
                                          nv_pad=dg.nv_pad, base=0,
                                          geometry=geom)
                    jax.block_until_ready(perm_dev)
                flags = (False,) * len(buckets)
                hk_dev = None
                self._heavy_kernel = None
            else:
                plan = BucketPlan.build(
                    np.asarray(sh.src), np.asarray(sh.dst),
                    np.asarray(sh.w), nv_local=dg.nv_pad, base=0,
                )
                use_pallas = engine == "pallas" and not class_sched
                # Promoted heavy-class kernel policy (ISSUE 8), decided up
                # front: it engages on the plain bucketed engine too, and a
                # run that executes ANY Pallas kernel must carry coverage
                # accounting (the engage-with-coverage convention).
                from cuvite_tpu.kernels.heavy_bincount import (
                    build_heavy_layout,
                    heavy_kernel_enabled,
                )

                hk_wanted = (plan.has_heavy and not class_sched
                             and heavy_kernel_enabled())
                want_cov = use_pallas or hk_wanted
                if want_cov:
                    # Per-bucket kernel-coverage accounting (VERDICT r3 weak
                    # #4: a pallas bench must say how much of the edge mass the
                    # kernel actually covers vs the XLA paths).  O(V): the
                    # single-shard slab is the CSR expanded in row order, so
                    # per-vertex degrees come straight off the offsets.
                    deg_all = np.zeros(dg.nv_pad, dtype=np.int64)
                    deg_all[:dg.graph.num_vertices] = dg.graph.degrees()
                    cov = []  # (width, n_edges, kernelized)
                buckets = []
                flags = []
                verts_np = []   # padded host verts, for the assembly perm
                for b in plan.buckets:
                    if want_cov:
                        rv = b.verts[b.verts < dg.nv_pad]
                        cov.append((b.width, int(deg_all[rv].sum()),
                                    use_pallas
                                    and b.width <= PALLAS_MAX_WIDTH))
                    if use_pallas and b.width <= PALLAS_MAX_WIDTH:
                        # Kernel layout: transposed [D, Nb], Nb a multiple of
                        # the 128-lane tile (pad rows with dropped sentinels).
                        nb = len(b.verts)
                        nb_pad = max(nb, 128)
                        verts = np.full(nb_pad, dg.nv_pad, dtype=np.int64)
                        verts[:nb] = b.verts
                        dmat = np.zeros((nb_pad, b.width), dtype=b.dst.dtype)
                        wmat = np.zeros((nb_pad, b.width), dtype=b.w.dtype)
                        dmat[:nb] = b.dst
                        wmat[:nb] = b.w
                        buckets.append((
                            _up(verts, vdt),
                            _up(aligned_copy(
                                dmat.T.astype(vdt, copy=False))),
                            _up(aligned_copy(
                                wmat.T.astype(wdt, copy=False))),
                        ))
                        flags.append(True)
                        verts_np.append(verts)
                    else:
                        buckets.append((_up(b.verts, vdt),
                                        _up(b.dst, vdt),
                                        _up(
                                            compress_unit_weights(b.w, wdt))))
                        flags.append(False)
                        verts_np.append(b.verts)
                buckets = tuple(buckets)
                flags = tuple(flags)
                # Promoted heavy-class kernel (ISSUE 8): replace the
                # per-iteration heavy SORT with the community-range-tile
                # bincount kernel whenever the phase has a heavy residual,
                # the policy says on (default: TPU backend;
                # CUVITE_HEAVY_KERNEL=1 forces interpret mode — how tier-1
                # pins parity on CPU) and the [D, H] layout fits its element
                # budget.  Class-scheduled phases sweep per-class plans (the
                # main plan never runs), so the layout would be dead weight.
                hk_dev = None
                if hk_wanted:
                    lay = build_heavy_layout(
                        np.asarray(plan.heavy_src),
                        np.asarray(plan.heavy_dst),
                        np.asarray(plan.heavy_w),
                        nv_local=dg.nv_pad, pad_id=nv_total)
                    if lay is None:
                        warnings.warn(
                            "heavy-class kernel: the [D, H] hub layout "
                            "exceeds CUVITE_HEAVY_ELEMS; this phase's "
                            "heavy residual degrades to the sorted path",
                            stacklevel=2)
                    else:
                        hv_np, dT_np, wT_np = lay
                        hk_dev = (
                            _up(hv_np, vdt),
                            _up(aligned_copy(dT_np.astype(vdt,
                                                          copy=False))),
                            _up(aligned_copy(wT_np.astype(wdt,
                                                          copy=False))),
                        )
                self._heavy_kernel = hk_dev
                if want_cov:
                    n_heavy = int(deg_all.sum()) - sum(c[1] for c in cov)
                    if n_heavy:
                        # width 0 = heavy class; kernelized when the promoted
                        # heavy kernel engaged for this phase.
                        cov.append((0, n_heavy, hk_dev is not None))
                    # The low-coverage warning is a pallas-engine contract
                    # (XLA classes are its FALLBACK); under plain bucketed
                    # the XLA classes are the engine and only the heavy
                    # kernel's share is reported.
                    self._record_pallas_coverage(cov, warn=use_pallas)
                if hk_dev is not None:
                    # The [D, Hp] layout REPLACES the heavy triples in the
                    # step (bucketed_step's kernel branch never reads them),
                    # and the non-class path never runs the triples-based
                    # mod pass — uploading them anyway would double the
                    # heavy residual's HBM footprint.  Minimal all-padding
                    # placeholders keep the call signature.
                    heavy = (_up(np.full(8, dg.nv_pad, dtype=np.int64), vdt),
                             _up(np.zeros(8, dtype=np.int64), vdt),
                             _up(np.zeros(8, dtype=np.float64), wdt))
                else:
                    heavy = (_up(plan.heavy_src, vdt),
                             _up(plan.heavy_dst, vdt),
                             _up(plan.heavy_w, wdt))
                self_loop = _up(plan.self_loop, wdt)
                perm_dev = _up(
                    build_assemble_perm(verts_np, dg.nv_pad))
            adt_np = adt

            def _step(src_, dst_, w_, comm, vdeg_, constant):
                return _bucketed_jit(
                    buckets, heavy, self_loop, comm, vdeg_, constant,
                    perm_dev, hk_dev,
                    nv_total=nv_total, sentinel=sentinel, accum_dtype=adt_np,
                    pallas_flags=flags, pallas_interpret=interp,
                )

            self._step = _step
            self._call = _bucketed_call(nv_total, sentinel, adt_np, flags,
                                        interp)
            self._hk_slot = True  # _extra carries a heavy_kernel slot
            self._bucket_extra = (buckets, heavy, self_loop, perm_dev)
            self.src = self.dst = self.w = None
            if color_local is not None and n_color_classes > 0:
                # Per-class bucket plans: each color class's sweep touches
                # ONLY its vertices' rows, so one full iteration costs ~one
                # sweep total instead of n_classes full sweeps (the analog
                # of the reference sweeping class vertices only,
                # /root/reference/louvain.cpp:862-901).  Edges of other
                # classes are masked to padding before plan construction.
                src_np = np.asarray(sh.src)
                dst_np = np.asarray(sh.dst)
                w_np = np.asarray(sh.w)
                cls = np.asarray(color_local)
                real = src_np < dg.nv_pad
                src_cls = np.where(
                    real, cls[np.minimum(src_np, dg.nv_pad - 1)], -1)
                self._class_plans = []
                for c in range(n_color_classes):
                    src_c = np.where(src_cls == c, src_np,
                                     dg.nv_pad).astype(src_np.dtype)
                    pc = BucketPlan.build(src_c, dst_np, w_np,
                                          nv_local=dg.nv_pad, base=0)
                    bk = tuple((_up(b.verts, vdt),
                                _up(b.dst, vdt),
                                _up(b.w, wdt))
                               for b in pc.buckets)
                    hv = (_up(pc.heavy_src, vdt),
                          _up(pc.heavy_dst, vdt),
                          _up(pc.heavy_w, wdt))
                    self._class_plans.append(
                        (bk, hv, _up(pc.self_loop, wdt)))
                # Class schedules force use_pallas off (above), so the full
                # plan's buckets are already in the XLA layout the
                # modularity pass needs.
                self._mod_args = (buckets, heavy, self_loop)
                self._nv_total = nv_total
                self._sentinel = sentinel
                self._adt = adt_np
        else:
            self._step = _get_step(mesh, nv_total, adt)
            self._call = _step_call(self._step)
            self._bucket_extra = None
        self.real_mask = dg.vertex_mask()
        slab_engine = self._bucket_extra is None  # bucket matrices replace it
        if multi:
            assert dg.nshards == int(np.prod(mesh.devices.shape))
            with tracer.stage("upload"):
                if slab_engine:
                    src, dst, w = dg.stacked_edges()
                    self.src = shard_1d(mesh, src.astype(vdt))
                    self.dst = shard_1d(mesh, dst.astype(vdt))
                    self.w = shard_1d(mesh, w.astype(wdt))
                self.vdeg = shard_1d(mesh, vdeg)
                self.comm0 = shard_1d(mesh, comm0)
                self.real_mask_dev = shard_1d(mesh, self.real_mask)
        else:
            assert dg.nshards == 1
            if slab_engine:
                src, dst, w = dg.stacked_edges()
                self.src = _up(src, vdt)
                self.dst = _up(dst, vdt)
                self.w = _up(w, wdt)
            self.vdeg = _up(vdeg)
            self.comm0 = _up(comm0)
            self.real_mask_dev = _up(self.real_mask)
        tw = dg.graph.total_edge_weight_twice()
        if multi:
            # Replicated GLOBAL scalar: a committed single-device array would
            # break multi-host jit dispatch (shard_1d handles both modes).
            self.constant = shard_1d(
                mesh, np.asarray(1.0 / tw, dtype=wdt), replicate=True)
        else:
            self.constant = jnp.asarray(1.0 / tw, dtype=wdt)
        if self._bucket_extra is not None:
            b, h, sl = self._bucket_extra[:3]
            self._extra = (b, h, sl, self.vdeg, self.constant) \
                + tuple(self._bucket_extra[3:])
            if getattr(self, "_hk_slot", False):
                # Single-shard bucketed call convention: the trailing
                # extra slot is the heavy-kernel layout (None = sorted
                # heavy path).
                self._extra = self._extra + (self._heavy_kernel,)
        else:
            self._extra = (self.src, self.dst, self.w, self.vdeg,
                           self.constant)
        if release_slabs and self._bucket_extra is not None \
                and dg.nshards == 1:
            # Bucket matrices replaced the slab; at benchmark scale the
            # host slab is tens of GB of dead weight from here on.
            dg.release_slabs()
        # HBM ledger (ISSUE 6): account every device buffer this runner
        # placed, by logical category — slab (edge triples), tables
        # (per-vertex state), plans (bucket matrices + assembly perm,
        # incl. per-class plans), exchange (sparse ghost routing).
        # Callables/None in the pytrees contribute nothing (no .nbytes).
        tracer.ledger_phase_begin()
        if self.src is not None:
            tracer.track("slab", self.src, self.dst, self.w)
        tracer.track("tables", self.vdeg, self.comm0, self.real_mask_dev,
                     self.constant)
        if self._bucket_extra is not None:
            # Layout: (buckets, heavy, self_loop, perm[, send_idx,
            # ghost_sel]) — the tail beyond the perm is the sparse
            # exchange routing.  The grouped (two-level) routing shards
            # over dcn only — every ici sibling holds its group's rows
            # by design — so it books under its own per-axis category
            # (law 'ici_replicated'), not the 1/S-sharded 'exchange'.
            tracer.track("plans", *jax.tree_util.tree_leaves(
                self._bucket_extra[:4]))
            xcat = ("exchange_grouped"
                    if (self.xplan_stats or {}).get("mode") == "twolevel"
                    else "exchange")
            tracer.track(xcat, *jax.tree_util.tree_leaves(
                self._bucket_extra[4:]))
        if self._class_plans is not None:
            tracer.track("plans", *jax.tree_util.tree_leaves(
                self._class_plans))
        if getattr(self, "_heavy_kernel", None) is not None:
            tracer.track("plans", *jax.tree_util.tree_leaves(
                self._heavy_kernel))

    def _record_pallas_coverage(self, cov, warn: bool = True) -> None:
        """Per-width kernel-coverage accounting (VERDICT r3 weak #4): a
        pallas bench must say how much of the edge mass the kernel actually
        covers vs the XLA paths.  ``cov`` is a list of (width, n_edges,
        kernelized) with width 0 standing for the heavy class; shared by
        the single-shard and SPMD upload paths so the report means the
        same thing on any mesh.  ``warn=False``: the bucketed engine with
        the promoted heavy kernel engaged reports coverage too (ISSUE 8 —
        any run executing a Pallas kernel must carry the accounting), but
        its XLA classes are the engine, not a fallback to warn about."""
        total = max(sum(c[1] for c in cov), 1)
        kernelized = sum(c[1] for c in cov if c[2])
        self.pallas_coverage = kernelized / total
        self.pallas_cov_detail = cov
        if warn and self.pallas_coverage < 0.5:
            warnings.warn(
                f"engine='pallas': only "
                f"{100 * self.pallas_coverage:.0f}% of edges are in "
                f"kernel-covered degree classes (<= "
                f"{PALLAS_MAX_WIDTH}); the rest run the XLA paths",
                stacklevel=2)

    def run(
        self,
        threshold: float,
        lower: float,
        et_mode: int = 0,
        et_delta: float = 0.25,
        color_classes=None,
        n_color_classes: int = 0,
    ) -> tuple[np.ndarray, float, int, bool]:
        """One phase: returns (communities in padded space, modularity,
        iters, overflow) — ``overflow`` True means a sparse-exchange budget
        overflow invalidated the sweep and the caller must re-run the phase
        with a larger budget (see louvain_phases' retry loop).

        Semantics of louvain.cpp:471-588: iterate until the modularity gain
        drops below `threshold`; return the assignment *before* the last two
        speculative move rounds (cvect = pastComm) and its modularity.

        Early termination (cf. louvain.cpp:7-423):
          et_mode 1/3 — freeze a vertex once target == curr == past for an
            iteration beyond the second (the *intended* semantics of
            louvain.cpp:172-182; the reference's chained comparison
            `a == b == c` is a C++ accident not replicated here);
          et_mode 2/4 — decay a per-vertex probability by (1 - et_delta)
            whenever curr == past, freeze below P_CUTOFF
            (louvain.cpp:378-395);
          modes 3/4 additionally stop the whole loop once >= ET_CUTOFF of
          all vertices are frozen (louvain.cpp:114-121; the reference
          compares a raw count against the percentage constant — here the
          documented 90% fraction is used).

        Coloring (cf. distLouvainMethodWithColoring, louvain.cpp:756-949):
        when ``color_classes`` (device array, padded id space, class index
        per vertex) is given, each iteration sweeps the color classes in
        order, committing each class's moves before the next class computes
        — the speculative-parallelism schedule that turns the greedy
        sequential sweep into n_color_classes synchronized sub-sweeps.
        Cost note: each sub-sweep currently evaluates the full-graph step
        and keeps only class c's moves, so an iteration costs
        n_color_classes full sweeps (typically fewer iterations in
        exchange); per-class bucket subsets are the planned optimization.
        """
        if et_mode == 0 and color_classes is None \
                and self._class_plans is None:
            # Default path: the whole iteration loop runs on device with the
            # convergence check inside (one host sync per phase instead of
            # one per iteration).
            wdt = np.dtype(self.constant.dtype)
            # Host scalars stay numpy: jit replicates them on any mesh,
            # including multi-host ones where a committed local jnp array
            # could not join a global computation.
            past_d, prev_mod_d, iters_d, ovf_d, conv_d = _run_phase_loop(
                self._extra, self.comm0,
                np.asarray(threshold, dtype=wdt),
                np.asarray(lower, dtype=wdt),
                call=self._call, max_iters=MAX_TOTAL_ITERATIONS,
            )
            self.labels_dev = past_d
            labels, (prev_mod, iters, ovf, cq, cmoved, covf) = _phase_sync(
                past_d, prev_mod_d, iters_d, ovf_d, *conv_d)
            self.convergence = decode_phase_conv(
                -1, int(iters), cq, cmoved, covf)
            return labels, float(prev_mod), int(iters), bool(ovf)
        if color_classes is None and self._class_plans is None:
            # ET modes 1-4 without coloring: freeze state lives in the
            # device loop's carry — one host sync per phase, like the
            # default path.
            wdt = np.dtype(self.constant.dtype)
            past_d, prev_mod_d, iters_d, ovf_d, conv_d = _run_phase_loop_et(
                self._extra, self.comm0,
                np.asarray(threshold, dtype=wdt),
                np.asarray(lower, dtype=wdt),
                self.real_mask_dev,
                np.asarray(et_delta, dtype=wdt),
                call=self._call, max_iters=MAX_TOTAL_ITERATIONS,
                et_mode=et_mode, nv_real=int(self.real_mask.sum()),
            )
            self.labels_dev = past_d
            labels, (prev_mod, iters, ovf, cq, cmoved, covf) = _phase_sync(
                past_d, prev_mod_d, iters_d, ovf_d, *conv_d)
            self.convergence = decode_phase_conv(
                -1, int(iters), cq, cmoved, covf)
            return labels, float(prev_mod), int(iters), bool(ovf)
        comm = self.comm0
        past = comm
        prev_mod = lower
        iters = 0
        overflow = False
        # Host-loop schedules already pay one sync per iteration for the
        # convergence check — the telemetry rows reuse that value; the
        # moved count is NOT fetched (it would add a sync per iteration),
        # so rows carry MOVED_UNTRACKED.
        conv_rows: list = []
        et_stop = et_mode in (3, 4)
        if et_mode:
            active = self.real_mask_dev
            nv_real = int(self.real_mask.sum())
            if et_mode in (2, 4):
                p_act = jnp.ones_like(self.vdeg)
        while True:
            iters += 1
            if color_classes is None and self._class_plans is None:
                target, mod, _, ovf = self._step(
                    self.src, self.dst, self.w, comm, self.vdeg, self.constant
                )
                overflow |= bool(ovf)
            elif self._class_plans is not None:
                # Class-restricted sweeps: each class's step runs on ITS
                # bucket plan only, so the whole iteration costs ~one sweep
                # (plus one cheap counter0-only modularity pass for the
                # convergence check).  Coloring refreshes community info per
                # class commit (louvain.cpp:862-901); vertex ordering
                # freezes it at the iteration start (louvain.cpp:1535-1562)
                # so colors only ORDER the sequential commits.  The SPMD
                # variant runs the same schedule with sharded class plans
                # (one sharded step per class, all_gather exchange inside).
                if self._class_sharded:
                    pargs = self._class_plan_args
                    mod = self._mod_fn(*self._mod_args, comm, self.vdeg,
                                       self.constant, *pargs)
                    ovf_acc = None
                    if pargs:  # sparse: (modularity, overflow)
                        mod, ovf_acc = mod
                    work = comm
                    snapshot = comm
                    for bk, hv, sl, pm, stepf in self._class_plans:
                        info = snapshot if self.ordering else work
                        tgt_c, _mc, _nc, _oc = stepf(
                            bk, hv, sl, work, info, self.vdeg,
                            self.constant, pm, *pargs)
                        if pargs:
                            # Accumulate on device; ONE host sync per
                            # iteration (below), not one per class step.
                            ovf_acc = ovf_acc | _oc
                        if et_mode:
                            tgt_c = jnp.where(active, tgt_c, work)
                        work = tgt_c
                    if ovf_acc is not None:
                        overflow |= bool(ovf_acc)
                    target = work
                else:
                    mod = _bucketed_mod_jit(
                        *self._mod_args, comm, self.vdeg, self.constant,
                        nv_total=self._nv_total, accum_dtype=self._adt,
                    )
                    work = comm
                    snapshot = comm
                    for bk, hv, sl in self._class_plans:
                        info = snapshot if self.ordering else work
                        tgt_c, _mc, _nc, _oc = _bucketed_class_jit(
                            bk, hv, sl, work, info, self.vdeg, self.constant,
                            nv_total=self._nv_total, sentinel=self._sentinel,
                            accum_dtype=self._adt,
                        )
                        if et_mode:
                            tgt_c = jnp.where(active, tgt_c, work)
                        work = tgt_c  # non-class vertices keep `work`
                    target = work
            else:
                # Legacy full-sweep color schedule (multi-shard / slab
                # engines): class c's moves are visible to class c+1 within
                # the same iteration.  Frozen (inactive) vertices must never
                # enter `work`, or later classes would decide against
                # phantom state.
                work = comm
                mod = None
                for c in range(n_color_classes):
                    tgt_c, mod_c, _, ovf = self._step(
                        self.src, self.dst, self.w, work, self.vdeg,
                        self.constant,
                    )
                    overflow |= bool(ovf)
                    if mod is None:
                        mod = mod_c  # modularity of the iteration's input
                    mask = color_classes == c
                    if et_mode:
                        mask = mask & active
                    work = jnp.where(mask, tgt_c, work)
                target = work
            if et_mode and color_classes is None \
                    and self._class_plans is None:
                target = jnp.where(active, target, comm)
            curr_mod = float(mod)
            # Same bound as the device buffers: rows hold at most
            # CONV_ROWS_CAP iterations (MAX_TOTAL_ITERATIONS is 10k —
            # unbounded rows would bloat every trace event/metrics
            # export); the exact count lives in `iterations` and
            # truncation is flagged below, matching decode_phase_conv.
            if len(conv_rows) < CONV_ROWS_CAP:
                conv_rows.append(ConvRow(
                    iteration=iters - 1, q=curr_mod,
                    moved=MOVED_UNTRACKED))
            if et_stop:
                frozen = nv_real - int(jnp.sum(active))
                if frozen >= ET_CUTOFF * nv_real:
                    break
            if (curr_mod - prev_mod) < threshold:
                break
            prev_mod = max(curr_mod, lower)
            if et_mode and iters > 2:
                if et_mode in (1, 3):
                    stable = (target == comm) & (comm == past)
                    active = active & ~stable
                else:
                    decayed = active & (comm == past)
                    p_act = jnp.where(decayed, p_act * (1.0 - et_delta), p_act)
                    active = active & ~(decayed & (p_act <= P_CUTOFF))
            past = comm
            comm = target
            if iters >= MAX_TOTAL_ITERATIONS:
                break
        self.labels_dev = past
        self.convergence = PhaseConvergence(
            phase=-1, rows=conv_rows, iterations=iters,
            truncated=iters > CONV_ROWS_CAP)
        return gather_global(past), prev_mod, iters, overflow


# Edge-slab size above which the fused driver compacts between device
# calls: one fused phase on a big slab, host coarsening (which SHRINKS the
# graph, rebuild.cpp:430-454), repeat — so phase p costs O(E_p), not
# O(E_original).  Below it, relabel-only phases on the resident slab are
# cheaper than extra compiles + transfers.
FUSED_SHRINK_EDGES = 1 << 20

# exchange='auto' cutover — a MEMORY bound, not a speed crossover: the
# replicated exchange (all_gather of the full community vector + full-width
# psums) measured FASTER than the sparse plan at every scale the CPU mesh
# can hold (round-3 re-measure on a 1-core host, tools/exchange_bench.py:
# scale 18: 11s vs 14.8s (1.34x); scale 20: 68s vs 104s (1.52x); scale 22:
# 538s vs 958s (1.78x); round-2 walls were ~2x faster for identical code,
# so cross-round ratios reflect host conditions, not code).  The gap is
# COMPUTE on a CPU mesh — the sparse env's extra per-iteration sort and
# owner-routing — NOT collective transport: the round-8 launch-latency
# microbenchmark (tools/exchange_latency.py, log in
# tools/logs/exchange_latency_r8.log; 8-virtual-device mesh on this host)
# measures ~0.5-1.2 ms per collective launch with all_gather and
# all_to_all within ~1.4x of each other, and its transport-only model
# (3 launches/iter each side, pinned by
# test_sparse_step_lowers_to_three_all_to_all) already crosses to sparse
# at nv ~2^12 — four orders of magnitude BELOW this cutover.  So the
# launch/transport argument cannot justify 2^26 on any measured mesh;
# what does is HBM: the replicated exchange's per-chip state is
# O(nv_total), and at the v5p-64 north star (padded nv_total ~2^29) that
# is several multi-GB replicated arrays per chip per iteration —
# infeasible, which is exactly why the reference built its sparse
# protocol (louvain.cpp:2588-3264).  Above this vertex count the driver
# switches to the sparse O(owned + ghosts) plan; below it the replicated
# arrays cost at most ~1 GB per chip and the (compute-)simpler exchange
# wins end-to-end.  Re-run tools/exchange_latency.py on real ICI when a
# chip window opens — CUVITE_EXCHANGE_CUTOVER (below) retunes the cutover
# without a code edit.
AUTO_SPARSE_MIN_VERTICES = 1 << 26


def exchange_cutover() -> int:
    """The exchange='auto' sparse cutover (padded vertex count at or above
    which the sparse plan is chosen): AUTO_SPARSE_MIN_VERTICES, overridable
    via CUVITE_EXCHANGE_CUTOVER so the constant — a CPU-mesh guess, per the
    comment above — can be re-tuned on real ICI without a code edit
    (VERDICT r5 weak #3).  Accepts a positive integer (0x/0b prefixes ok);
    malformed values warn and fall back to the default.  Read per phase,
    so a toggle takes effect without re-importing."""
    raw = os.environ.get("CUVITE_EXCHANGE_CUTOVER")
    if not raw:
        return AUTO_SPARSE_MIN_VERTICES
    try:
        v = int(raw, 0)
    except ValueError:
        v = -1
    if v <= 0:
        warnings.warn(
            f"malformed CUVITE_EXCHANGE_CUTOVER={raw!r} (want a positive "
            f"integer); using the default {AUTO_SPARSE_MIN_VERTICES}",
            stacklevel=2)
        return AUTO_SPARSE_MIN_VERTICES
    return v


def _run_fused(graph, *, threshold, threshold_cycling, one_phase, balanced,
               max_phases, verbose, tracer):
    """Single-shard fused execution (cuvite_tpu/louvain/fused.py).

    Small graphs: ONE device call for the whole clustering, one host sync.
    Large graphs (>= FUSED_SHRINK_EDGES edges): one fused call per phase
    with DEVICE-RESIDENT compaction in between (coarsen/device.py) until
    the working graph is small, then one fused call for all remaining
    phases.  The slab is uploaded once; between phases it is renumbered,
    relabeled and coalesced in HBM, label composition is a device gather,
    and the host sees only scalars/stat vectors per phase — the coarse
    slab re-enters the same compiled program while it fits the pow2 class,
    and drops to a smaller class (prefix slice, still on device) when the
    per-phase scalar sync shows it fits.  CUVITE_DEVICE_COARSEN=0 restores
    the historical host compaction (device_get labels -> np.unique ->
    host coalesce -> rebuild -> re-upload) for A/B and as an escape hatch.
    ``tracer`` is always supplied by louvain_phases (NullTracer default)."""
    from cuvite_tpu.coarsen.device import (
        device_compose_labels,
        device_renumber,
    )
    from cuvite_tpu.kernels.seg_coalesce import coalesce_engine
    from cuvite_tpu.louvain.fused import fused_louvain

    t_start = time.perf_counter()
    wdt = _device_dtype(graph.policy.weight_dtype)
    adt = _accum_name(_device_dtype(graph.policy.accum_dtype),
                      graph.total_edge_weight_twice(),
                      max(graph.num_edges, graph.num_vertices))
    max_p = 1 if one_phase else int(max_phases)
    cycling = bool(threshold_cycling and not one_phase)

    def _ths(phase0: int) -> np.ndarray:
        # Fixed length max_p regardless of the phase offset: contents are
        # traced, so multilevel calls never retrace on the offset.
        if cycling:
            return np.array(
                [threshold_for_phase(phase0 + k) for k in range(max_p)],
                dtype=wdt)
        return np.full(max_p, threshold, dtype=wdt)

    constant = jnp.asarray(1.0 / graph.total_edge_weight_twice(), dtype=wdt)

    use_dev = device_coarsen_enabled()
    g = graph
    comm_all = np.arange(graph.num_vertices, dtype=np.int64)
    phases: list[PhaseStats] = []
    convergence: list = []  # PhaseConvergence per GAINING fused phase
    tot_iters = 0
    prev_mod = -1.0
    dg = None
    dense = nc = None
    # Device-resident level state: the slab (src/dst/w), the real-vertex
    # mask, the last call's labels and the composed original->current
    # labels all live in HBM; real_nv/real_ne/nv_pad/ne_pad are the host
    # scalars that track them.
    src_d = dst_d = w_d = real_mask_d = None
    labels_d = comm_all_d = None
    renumber_d = None  # (dense_map, nc) of labels_d, reused by the coarsen
    nv_pad = ne_pad = None
    real_nv = graph.num_vertices
    real_ne = graph.num_edges

    def _run_call(ths_arr, budget, cyc):
        """One fused device call on the resident slab; folds its phases
        into the run-level bookkeeping and returns how many it ran."""
        nonlocal tot_iters, prev_mod, comm_all, comm_all_d, labels_d, \
            renumber_d, dense, nc
        t_call = time.perf_counter()
        with tracer.stage("iterate"):
            out = fused_louvain(
                src_d, dst_d, w_d,
                jnp.asarray(ths_arr),
                constant,
                real_mask_d,
                nv_pad=nv_pad,
                max_phases=max_p,
                accum_dtype=adt,
                cycling=cyc,
                prev_mod0=np.asarray(prev_mod, dtype=wdt),
                phase_budget=np.int32(budget),
                phase0=np.int32(len(phases)),
                iter_budget=np.int32(MAX_TOTAL_ITERATIONS - tot_iters),
            )
            # Labels stay in HBM; the per-call host sync fetches only the
            # scalars + O(max_phases) stat vectors.
            labels_d = out[0]
            (loop_mod, n_phases, iters, mod_hist, iter_hist,
             nc_hist) = jax.device_get(out[1:7])  # graftlint: disable=R010 — scalar/stat-only sync, O(max_phases)
            n_phases = int(n_phases)
        # The stat fetch above already blocked on program completion, so
        # the timing window closes HERE: call_s (→ PhaseStats.seconds,
        # the bench/regression-gate number) must not absorb the
        # telemetry readback below.
        call_s = time.perf_counter() - t_call
        # Convergence rows: a second fetch SLICED to the phases this
        # call actually ran — O(n_phases * CONV_ROWS_CAP), still
        # per-call not per-iteration; the full [max_phases, CAP]
        # buffers would put a 25k-element transfer on an otherwise
        # stat-sized sync (the transfer-guard tests cap fetch sizes).
        conv_slices = (out[7][:n_phases], out[8][:n_phases])
        cq_hist, cmoved_hist = jax.device_get(conv_slices)  # graftlint: disable=R010 — conv telemetry, O(n_phases * CONV_ROWS_CAP)
        tot_iters += int(iters)
        tracer.count("traversed_edges", real_ne * int(iters))
        nv_p = real_nv
        for p in range(n_phases):
            phases.append(PhaseStats(
                phase=len(phases), modularity=float(mod_hist[p]),
                iterations=int(iter_hist[p]), num_vertices=nv_p,
                num_edges=real_ne,
                seconds=call_s / n_phases,
            ))
            st = phases[-1]
            pc = decode_phase_conv(
                st.phase, st.iterations, cq_hist[p], cmoved_hist[p],
                gained=True)
            convergence.append(pc)
            if tracer.emitter is not None:  # to_dict is ~CAP row dicts
                tracer.event("convergence", **pc.to_dict())
            nv_p = int(nc_hist[p])
            if verbose:
                print(f"Level {st.phase}, Modularity: {st.modularity:.6f}, "
                      f"Iterations: {st.iterations}, nv: {st.num_vertices}")
        if n_phases:
            nc = int(nc_hist[n_phases - 1])
            if use_dev:
                # Cross-level label composition as a device gather chain;
                # the host copy of comm_all is materialized once, at the
                # end (the allowlisted final label gather).
                dmap, nc_d = device_renumber(labels_d, real_mask_d,
                                             nv_pad=nv_pad)
                renumber_d = (dmap, nc_d)  # the coarsen below reuses it
                if comm_all_d is None:
                    comm_all_d = jnp.arange(graph.num_vertices,
                                            dtype=labels_d.dtype)
                comm_all_d = device_compose_labels(dmap, labels_d,
                                                   comm_all_d)
            else:
                comm_lvl = np.asarray(labels_d)[dg.old_to_pad]  # graftlint: disable=R010 — host-compaction fallback path (CUVITE_DEVICE_COARSEN=0)
                dense, nc = renumber_communities(comm_lvl)
                comm_all = dense[comm_all]
            prev_mod = float(loop_mod)
        tracer.ledger_snapshot(phases[-1].phase if phases else None)
        return n_phases

    while True:
        if src_d is None:
            # First level, or the host-compaction fallback rebuilt g: one
            # host partition + one upload.  On the device path this runs
            # exactly once per clustering.
            with tracer.stage("plan"):
                dg = DistGraph.build(g, 1, balanced=balanced,
                                     min_nv_pad=4096, min_ne_pad=16384)
            nv_pad, ne_pad = dg.nv_pad, dg.ne_pad
            sh = dg.shards[0]
            with tracer.stage("upload"):
                src_d = jnp.asarray(np.asarray(sh.src).astype(np.int32))
                dst_d = jnp.asarray(np.asarray(sh.dst).astype(np.int32))
                w_d = jnp.asarray(np.asarray(sh.w).astype(wdt))
                real_mask_d = jnp.asarray(dg.vertex_mask())
            tracer.ledger_phase_begin()
            tracer.track("slab", src_d, dst_d, w_d)
            tracer.track("tables", real_mask_d)
        remaining = max_p - len(phases)
        # Big slab: run ONE phase, compact, come back.  Small (or final)
        # slab: let the device program run everything remaining (incl.
        # the in-program cycling safety net, main.cpp:432-442).
        one_phase_level = (real_ne >= FUSED_SHRINK_EDGES
                           and remaining > 1)
        budget = 1 if one_phase_level else remaining
        n_phases = _run_call(_ths(len(phases)), budget,
                             cyc=cycling and not one_phase_level)
        if n_phases < budget:
            # Stopped by no-gain (or the iteration cap).  On an
            # intermediate call the in-program safety net was off; when the
            # host can see the pass is still eligible (global phase < 10,
            # cycled threshold above 1e-6, main.cpp:432-442), run JUST the
            # 1e-6 phase — not a rerun of the converged phase.
            if (one_phase_level and cycling
                    and len(phases) < 10
                    and float(_ths(len(phases))[0]) > 1e-6
                    and tot_iters <= MAX_TOTAL_ITERATIONS):
                # The fused body's inner sweep always restarts from
                # lower=-1 while gain-testing against the carried prev_mod
                # — exactly the safety-pass semantics, so a plain 1e-6
                # one-phase call IS the safety net.
                _run_call(np.full(max_p, 1e-6, dtype=wdt), 1, cyc=False)
            break
        if (len(phases) >= max_p or not one_phase_level
                or tot_iters > MAX_TOTAL_ITERATIONS):
            break
        with tracer.stage("coarsen"):
            if use_dev:
                # Renumber + relabel + coalesce in HBM; the slab never
                # crosses to the host.  ONE scalar sync (ne2) decides the
                # pow2 class of the next level.
                dmap, nc_d = renumber_d  # same (labels_d, real_mask_d)
                acc = adt if adt == "ds32" else None
                eng = coalesce_engine(nv_pad, acc)
                ne_in = real_ne
                # Nested stage: coalesce_s (the relabel+coalesce slice,
                # incl. its ne2 scalar sync) SPLITS OUT of coarsen_s so
                # the sort tax is a measured bench field (schema v4).
                with tracer.stage("coalesce"):
                    src_d, dst_d, w_d, _dm, _nc_d, ne2_d = \
                        device_coarsen_slab(
                            src_d, dst_d, w_d, labels_d, real_mask_d,
                            nv_pad=nv_pad, accum_dtype=acc,
                            dense_map=dmap, nc=nc_d, coalesce=eng)
                    real_nv, real_ne = nc, int(ne2_d)
                tracer.count("coalesce_edges", ne_in)
                if eng != "sort":
                    tracer.count("coalesce_dense_edges", ne_in)
                src_d, dst_d, w_d, nv_pad, ne_pad = maybe_shrink_to_class(
                    src_d, dst_d, w_d, nc=real_nv, ne2=real_ne,
                    nv_pad=nv_pad, ne_pad=ne_pad)
                real_mask_d = jnp.arange(nv_pad, dtype=jnp.int32) \
                    < jnp.int32(real_nv)
                tracer.ledger_phase_begin()
                tracer.track("slab", src_d, dst_d, w_d)
                tracer.track("tables", real_mask_d, labels_d)
            else:
                g = coarsen_graph(g, dense, nc)
                real_nv, real_ne = g.num_vertices, g.num_edges
                src_d = None  # force rebuild + re-upload at the loop top

    total_s = time.perf_counter() - t_start
    # Per-call seconds only cover the device calls; rescale so
    # sum(p.seconds) == wall time of the whole loop (plan/coarsen host
    # stages included) — bench.py and the CLI compute TEPS from that sum,
    # which must stay comparable across engines and rounds.
    call_sum = sum(st.seconds for st in phases)
    if call_sum > 0:
        scale = total_s / call_sum
        for st in phases:
            st.seconds *= scale
    # comm_all is already dense: every gaining level composes through dense
    # ids 0..nc-1 with all communities nonempty (and it starts as arange).
    if use_dev and comm_all_d is not None:
        # THE final label gather: the one O(V) device->host transfer of
        # the whole device-resident clustering.
        comm_all = np.asarray(comm_all_d).astype(np.int64)  # graftlint: disable=R010 — the allowlisted final label gather
    dense_all = comm_all
    if phases:
        # Final reported Q: precise recompute of the final labels on the
        # LAST working graph (the fused loop's own history stays f32);
        # multigraph invariance makes it equal to Q on the original graph.
        if use_dev:
            dgq = DistGraph.from_device_slab(
                src_d, dst_d, w_d, num_vertices=real_nv,
                num_edges=real_ne, nv_pad=nv_pad, ne_pad=ne_pad,
                policy=graph.policy,
                total_weight_twice=graph.total_edge_weight_twice())
            final_q = phase_modularity(
                dgq, np.asarray(labels_d),  # graftlint: disable=R010 — final labels, O(V), re-used on device by the ds pass
                device_slab=(src_d, dst_d, w_d))
        else:
            final_q = phase_modularity(dg, np.asarray(labels_d))  # graftlint: disable=R010 — host-compaction fallback path
    else:
        final_q = -1.0
    return LouvainResult(
        communities=dense_all,
        modularity=final_q,
        phases=phases,
        total_iterations=tot_iters,
        total_seconds=total_s,
        convergence=convergence,
    )


def louvain_many(
    graphs,
    threshold: float = 1.0e-6,
    max_phases: int = TERMINATION_PHASE_COUNT,
    b_pad: int | None = None,
    slab_class: tuple | None = None,
    mesh="auto",
    tracer=None,
    verbose: bool = False,
    engine: str = "fused",
    bucket_shape=None,
):
    """Cluster B same-slab-class graphs through ONE compiled per-phase
    program (ISSUE 9): the multi-tenant analog of :func:`louvain_phases`.

    Returns a ``louvain.batched.BatchResult`` whose ``results`` list
    holds one :class:`LouvainResult` per input graph, in order, each
    bit-identical to running this same entry with that graph alone
    (B=1, same engine).  The batch axis pads to the
    ``core.batch.BATCH_SIZES`` ladder; per-graph phase exit is masking,
    not batch splitting, so one compile serves every batch of the same
    ``(class, B, engine)``.

    ``engine`` (ISSUE 10): ``'fused'`` — every phase through the
    vmapped fused sort-formulation loop; ``'bucketed'`` — phase 0
    through the vmapped degree-bucketed sort-free sweep over
    cross-graph-padded plans (``core.batch.batch_bucket_plans``), later
    (small, coarse) phases fused; ``bucket_shape`` optionally pins the
    plan geometry across batches (``core.batch.bucket_shape_for``).
    The serving queue (cuvite_tpu/serve) selects the engine via
    ``ServeConfig.engine``.

    Scope: fixed threshold / plain schedule / single shard per graph —
    the serving configuration.  Heterogeneous classes are the SERVING
    layer's job (cuvite_tpu/serve bins by class before packing); mixed
    classes here raise.
    """
    from cuvite_tpu.louvain.batched import cluster_many

    return cluster_many(graphs, threshold=threshold, max_phases=max_phases,
                        b_pad=b_pad, slab_class=slab_class, mesh=mesh,
                        tracer=tracer, verbose=verbose, engine=engine,
                        bucket_shape=bucket_shape)


def louvain_phases(
    graph: Graph,
    nshards: int = 1,
    mesh=None,
    mesh_shape=None,
    threshold: float = 1.0e-6,
    threshold_cycling: bool = False,
    one_phase: bool = False,
    balanced: bool = False,
    et_mode: int = 0,
    et_delta: float = 0.25,
    engine: str = "auto",
    coloring: int = 0,
    vertex_ordering: int = 0,
    exchange: str = "auto",
    exchange_budget: int | None = None,
    max_phases: int = TERMINATION_PHASE_COUNT,
    verbose: bool = False,
    tracer=None,
    checkpoint_dir: str | None = None,
    resume: bool = False,
    dist_stats: bool = False,
    diag_prefix: str | None = None,
) -> LouvainResult:
    """Full multi-phase Louvain (the main.cpp:218-495 loop).

    ``engine='auto'`` picks the degree-bucketed step (single-shard and
    sharded); ``engine='sort'`` forces the edge-slab sort/segment step;
    ``engine='pallas'`` is the bucketed step with every degree class <=
    PALLAS_MAX_WIDTH routed through the Pallas row-argmax kernel — on a
    mesh the kernel runs inside the shard_map body under either exchange,
    and the result carries the kernel-coverage accounting
    (``pallas_coverage`` / ``pallas_width_hits``).

    ``coloring=N`` (reference -c N): distance-1 color the phase-0 graph with
    N/2 hash functions and run the per-color sub-sweep schedule
    (main.cpp:243-283); on the single-shard bucketed engine each class
    sweeps ONLY its own bucket plan, so an iteration costs ~one sweep
    total.  ``vertex_ordering=N`` (reference -d N): the same per-class
    sequential commits, but with community degree/size tables FROZEN at the
    iteration start — colors only order the sweep, exchanges hoisted out of
    the color loop (louvain.cpp:1535-1562).  Ordering is implemented on the
    single-shard bucketed engine; other engines fall back to the plain
    schedule.

    ``mesh_shape`` (ISSUE 18): ``(dcn, ici)`` tuple or ``"DxI"`` string
    selecting a 2-D hybrid mesh for the two-level exchange — community
    tables replicate only inside each ICI group (O(nv_total / dcn) per
    chip), cross-group traffic rides the sparse ghost protocol on the
    slow DCN axis.  ``dcn == 1`` is bit-compatible with the flat 1-D
    mesh of ``nshards = ici`` (auto = flat); ``dcn > 1`` forces
    ``exchange='twolevel'`` on every phase (the hybrid axes admit no
    other SPMD program) and is restricted to the bucketed/pallas
    engines with the plain schedule."""
    dist_ingest = getattr(graph, "local_only", False)
    if dist_ingest:
        # Per-host sharded ingest (io/dist_ingest.DistVite): phase 0 runs on
        # the pre-partitioned local slabs; later (small) phases on the
        # allgathered coarse graph.  Full-graph host features are
        # unavailable by construction.
        if nshards == 1 and graph.nshards > 1:
            nshards = graph.nshards
        if nshards != graph.nshards:
            raise ValueError(
                f"nshards={nshards} does not match the DistVite partition "
                f"({graph.nshards} shards)")
        if engine not in ("auto", "bucketed", "pallas"):
            raise ValueError(
                "per-host ingest supports only the bucketed/pallas engines")
        if exchange == "auto":
            exchange = "sparse"  # host memory is the constraint here
        if exchange != "sparse":
            raise ValueError("per-host ingest requires exchange='sparse'")
        # coloring/vertex-ordering run the distributed round loop
        # (multi_hash_coloring_dist, bit-identical to full ingest) and
        # checkpoint fingerprints come from per-shard content hashes
        # (DistVite.content_fingerprint) — both VERDICT r4 item 7.
    if exchange == "auto" and exchange_budget is not None:
        # An explicit per-peer budget only means anything on the sparse
        # plan; honor the caller's intent rather than silently ignoring it.
        exchange = "sparse"
    # ---- hybrid-mesh selection (two-level exchange, ISSUE 18) -------------
    from cuvite_tpu.comm.mesh import DCN_AXIS, ICI_AXIS

    n_dcn = 1
    if mesh_shape is not None:
        if isinstance(mesh_shape, str):
            d_s, _, i_s = mesh_shape.lower().replace(
                "×", "x").partition("x")
            mesh_shape = (int(d_s), int(i_s))
        n_dcn, n_ici = int(mesh_shape[0]), int(mesh_shape[1])
        if n_dcn < 1 or n_ici < 1:
            raise ValueError(f"mesh_shape factors must be >= 1, "
                             f"got {n_dcn}x{n_ici}")
        if nshards not in (1, n_dcn * n_ici):
            raise ValueError(
                f"nshards={nshards} conflicts with mesh_shape "
                f"{n_dcn}x{n_ici} ({n_dcn * n_ici} devices)")
        nshards = n_dcn * n_ici
        if n_dcn > 1:
            if dist_ingest:
                raise ValueError("the two-level exchange does not support "
                                 "per-host ingest yet")
            if coloring or vertex_ordering:
                raise ValueError(
                    "the two-level exchange does not support coloring/"
                    "vertex-ordering yet (use a flat mesh)")
            if engine not in ("auto", "bucketed", "pallas"):
                raise ValueError("the two-level exchange runs on the "
                                 "bucketed/pallas engines only")
            if mesh is None:
                from cuvite_tpu.comm.mesh import make_hybrid_mesh

                mesh = make_hybrid_mesh(n_dcn, n_ici)
        # dcn == 1: auto = flat — fall through to make_mesh(nshards),
        # bit-compatible with today's 1-D paths.
    elif mesh is not None and mesh.axis_names == (DCN_AXIS, ICI_AXIS):
        n_dcn = int(mesh.devices.shape[0])
        nshards = int(np.prod(mesh.devices.shape))
    if exchange == "twolevel" and n_dcn <= 1:
        raise ValueError("exchange='twolevel' requires a hybrid mesh with "
                         "|dcn| > 1 (pass mesh_shape=(dcn, ici))")
    if n_dcn > 1:
        if exchange == "replicated":
            raise ValueError("a hybrid mesh runs the two-level exchange; "
                             "exchange='replicated' needs a flat mesh")
        # auto/sparse on hybrid axes resolve to the only SPMD program the
        # 2-D mesh admits; the grouped plan IS the sparse protocol at
        # group scale, so 'sparse' intent is honored, not overridden.
        exchange = "twolevel"
    if mesh is None and (nshards > 1 or dist_ingest):
        mesh = make_mesh(nshards)
    if engine == "auto":
        engine = "bucketed"
    if engine == "fused" and (
        et_mode or coloring or vertex_ordering or mesh is not None
        or nshards > 1 or checkpoint_dir is not None
    ):
        # The fused program covers the default single-shard schedule; the
        # per-phase drivers own the ET/coloring variants, SPMD, and
        # checkpointing (which needs phase boundaries on the host).  Warn so
        # a benchmark of --engine fused on those configs is not
        # misattributed to the fused program.
        warnings.warn(
            "engine='fused' covers only the plain single-shard schedule; "
            "running the 'bucketed' engine for this configuration instead",
            stacklevel=2)
        engine = "bucketed"
    if engine == "sort" and (coloring or vertex_ordering) \
            and not os.environ.get("CUVITE_KEEP_SORT_COLORING"):
        # The sort engine has no class-restricted plans, so coloring on it
        # runs the legacy schedule costing n_classes FULL sweeps per
        # iteration (and ordering degrades to the plain schedule) —
        # effectively unusable at scale (VERDICT r5 weak #4).  The bucketed
        # engine implements both schedules at ~one sweep per iteration on
        # every configuration this driver accepts, so auto-switch instead
        # of only warning; CUVITE_KEEP_SORT_COLORING=1 pins the sort engine
        # (e.g. for an A/B), in which case the genuine can't-do warnings
        # below still fire.
        warnings.warn(
            "engine='sort' with coloring/vertex-ordering would run the "
            "legacy schedule (n_classes full sweeps per iteration); "
            "auto-switching to the class-capable 'bucketed' engine "
            "(set CUVITE_KEEP_SORT_COLORING=1 to keep the sort engine)",
            stacklevel=2)
        engine = "bucketed"
    if engine == "sort" and exchange == "sparse" and nshards > 1:
        # The check sits here, not in PhaseRunner, so it fires only on the
        # USER'S explicit exchange='sparse' — not on an 'auto' resolution
        # (same misattribution standard as the pallas/fused fallbacks).
        warnings.warn(
            "exchange='sparse' is implemented on the bucketed engine only; "
            "the sort engine runs the replicated exchange (O(nv_total) "
            "per-chip state)", stacklevel=2)

    nv0 = graph.num_vertices
    comm_all = np.arange(nv0, dtype=np.int64)
    if graph.num_edges == 0:
        # Edgeless graph: every vertex is its own community, Q = 0.
        return LouvainResult(
            communities=comm_all, modularity=0.0, phases=[],
            total_iterations=0, total_seconds=0.0,
        )
    if tracer is None:
        from cuvite_tpu.utils.trace import NullTracer

        tracer = NullTracer()
    if engine == "fused":
        return _run_fused(
            graph, threshold=threshold, threshold_cycling=threshold_cycling,
            one_phase=one_phase, balanced=balanced, max_phases=max_phases,
            verbose=verbose, tracer=tracer,
        )

    if checkpoint_dir and one_phase:
        raise ValueError(
            "checkpoint_dir is incompatible with one_phase: the run ends "
            "after its single phase, so there is no state to resume "
            "(use max_phases=1 to bound a checkpointed run instead)"
        )

    phases: list[PhaseStats] = []
    convergence: list = []  # PhaseConvergence per phase ATTEMPT (ISSUE 6)
    prev_mod = -1.0
    tot_iters = 0
    # engine='pallas' kernel-coverage accounting, traversed-edge weighted
    # across phases (coarse phases sweep less mass but more often).
    cov_num = cov_den = cov_pending = 0
    width_hits: dict = {}
    # Phase-1 exchange-plan digest (ISSUE 18): the full-scale graph's
    # per-device table/ghost bytes — the number the bench `exchange`
    # block and perf_regress's arm matching report (coarse phases
    # shrink and would understate it).
    exchange_stats = None
    t_start = time.perf_counter()
    phase = 0
    g = graph
    if diag_prefix:
        from cuvite_tpu.utils.trace import ShardDiag

        diag = ShardDiag(diag_prefix, nshards)
    else:
        diag = None
    ck_fp = None  # original-graph fingerprint, computed at most once
    # Sparse-exchange per-peer budget, sticky across phases (grows on
    # overflow retry; None = PhaseRunner's default of max(128, nv_pad/4)).
    budget = exchange_budget
    # Device-resident next-phase DistGraph handed across the phase
    # boundary by the sort engine's on-device coarsening (coarsen/
    # device.py): when set, the loop top consumes it instead of
    # rebuilding from a host graph — the O(E) slab never leaves HBM.
    pending_dg = None

    if resume and checkpoint_dir:
        from cuvite_tpu.utils.checkpoint import load_latest

        ck = load_latest(checkpoint_dir)
        if dist_ingest:
            # Only process 0 writes checkpoints, so every process loading
            # the same SHARED directory sees the same file.  A host-local
            # directory would give ck on process 0 and None elsewhere —
            # mismatched collective participation below would deadlock.
            # One allgather turns that into a loud, consistent error.
            from cuvite_tpu.comm.multihost import allgather_varlen

            mine = np.asarray(
                [ck.phase, ck.fingerprint] if ck is not None else [-1, -1],
                dtype=np.int64)
            seen = np.stack(allgather_varlen(mine))
            if len(np.unique(seen, axis=0)) > 1:
                raise ValueError(
                    "per-host resume: processes loaded different "
                    f"checkpoint states {seen.tolist()} from "
                    f"{checkpoint_dir!r} — the checkpoint directory must "
                    "be shared storage visible to every process")
        if ck is not None and ck.fingerprint != -1:
            ck_fp = _source_fingerprint(graph)  # reused at save time
            if ck.fingerprint != ck_fp:
                # Same directory, different graph content (e.g. same-scale
                # R-MAT with another seed): composing its labels would be
                # silently wrong, and silently restarting would hide it.
                # Per-host ingest note: DistVite.content_fingerprint hashes
                # the PARTITIONED layout, so partition parameters are part
                # of the digest there — a changed nshards/balanced split of
                # the very same graph also lands here, by design (failing
                # closed on partition drift).
                raise ValueError(
                    f"checkpoint in {checkpoint_dir!r} was written for a "
                    "different graph (content fingerprint mismatch). With "
                    "per-host ingest the fingerprint also covers the "
                    "partition parameters (nshards / balanced), so a "
                    "changed partitioning of the SAME graph is reported "
                    "here too, not just different graph content; resume "
                    "with the original partition settings, or use a fresh "
                    "--checkpoint-dir / drop --resume")
        if ck is not None and len(ck.comm_all) == nv0 \
                and ck.orig_ne == graph.num_edges:
            g = ck.graph
            comm_all = ck.comm_all
            prev_mod = ck.prev_mod
            phase = ck.phase
            tot_iters = ck.tot_iters
            phases = [
                PhaseStats(phase=p, modularity=float(ck.mod_hist[p]),
                           iterations=int(ck.iter_hist[p]),
                           num_vertices=int(ck.nv_hist[p]),
                           num_edges=int(ck.ne_hist[p]), seconds=0.0)
                for p in range(ck.phase)
            ]
            if verbose:
                print(f"Resumed from {checkpoint_dir} at phase {phase} "
                      f"(Q={prev_mod:.6f})")

    while True:
        # Top-of-loop guard so a resumed run whose checkpoint already hit
        # max_phases (or the iteration cap) does not execute an extra phase.
        if phase >= max_phases or tot_iters > MAX_TOTAL_ITERATIONS:
            break
        th = threshold_for_phase(phase) if (threshold_cycling and not one_phase) \
            else threshold
        t1 = time.perf_counter()
        g_is_dv = getattr(g, "local_only", False)
        g_nv = g.num_vertices
        g_ne = g.num_edges
        # Flight-recorder phase envelope: stages/events below nest under
        # it; ended at every exit of this loop body (begin_span because
        # the body has breaks a `with` block cannot straddle cleanly).
        tracer.set_phase(phase)
        _phase_sid = tracer.begin_span("phase", index=phase, nv=g_nv,
                                       ne=g_ne, threshold=float(th))
        # Shape floors: every coarsened phase small enough to fit them reuses
        # one compiled step instead of recompiling per phase.
        # Single-shard bucketed engines never upload the edge slab: skip
        # its pow2 padding, alias the CSR as the slab, and release it after
        # plan construction — the footprint work that fits benchmark-scale
        # graphs on one host (tools/scale_model.md).
        slabless = (engine in ("bucketed", "pallas") and nshards == 1
                    and not g_is_dv
                    and not os.environ.get("CUVITE_NO_SLABLESS")
                    and (mesh is None
                         or int(np.prod(mesh.devices.shape)) == 1))
        with tracer.stage("plan"):
            if pending_dg is not None:
                dg = pending_dg           # slab already in HBM, no rebuild
                pending_dg = None
            elif g_is_dv:
                dg = g
            else:
                dg = DistGraph.build(
                    g, nshards, balanced=balanced,
                    min_nv_pad=max(1, 4096 // nshards),
                    min_ne_pad=max(1, 16384 // nshards),
                    pad_edges=not slabless,
                )
        if exchange == "auto":
            # Per PHASE: coarse phases of a huge graph shrink back under
            # the cutover and get the cheaper replicated exchange.
            phase_exchange = ("sparse" if dg.total_padded_vertices
                              >= exchange_cutover() else "replicated")
        else:
            phase_exchange = exchange
        color_dev = None
        n_classes = 0
        # Class-restricted plans (one sweep per iteration) exist on the
        # bucketed engine: single-shard, and SPMD over the replicated
        # exchange (sharded per-class plans, the reference's distributed
        # -c/-d schedule, louvain.cpp:862-901, :1535-1562).  Remaining
        # configurations degrade and must say so (cf. pallas/fused).
        multi_mesh = nshards > 1 or (
            mesh is not None and int(np.prod(mesh.devices.shape)) > 1)
        # Note: engine='pallas' on a mesh runs the SPMD bucketed step with
        # the kernel classes inside the shard_map body; under a coloring/
        # ordering schedule the iteration sweeps the per-class plans, which
        # are XLA on every engine (matching single-shard pallas), so it is
        # class-capable too.
        # Both SPMD exchanges support class-restricted plans (sparse:
        # per-class plans stacked over the phase ghost routing, VERDICT r3
        # item 5), including the per-host-ingest partition (local shard
        # rows only; VERDICT r4 item 7).
        class_capable = engine in ("bucketed", "pallas")
        ordering_fallback = bool(
            vertex_ordering and not coloring and not class_capable)
        if ordering_fallback and phase == 0:
            # Plain schedule: skip the coloring entirely — computing colors
            # nobody consumes would waste an O(E) multi-hash pass on the
            # largest graph of the run.
            warnings.warn(
                "vertex_ordering needs class-restricted plans (bucketed "
                "engine; replicated exchange on a mesh); this "
                "configuration falls back to the PLAIN schedule",
                stacklevel=2)
        if (coloring or vertex_ordering) and phase == 0 \
                and not ordering_fallback:
            from cuvite_tpu.louvain.coloring import multi_hash_coloring

            if coloring and not class_capable:
                warnings.warn(
                    "class-restricted color sweeps need the bucketed "
                    "engine (replicated exchange on a mesh); this "
                    "configuration runs the legacy schedule costing "
                    "n_classes full sweeps per iteration", stacklevel=2)

            n_hash = max((coloring or vertex_ordering) // 2, 1)
            if g_is_dv:
                # Per-host ingest: distributed rounds over local edges +
                # per-round owned-slice allgather, bit-identical to the
                # full-edge-list call (the reference's ghost color
                # exchange, /root/reference/coloring.cpp:204-420).
                from cuvite_tpu.louvain.coloring import (
                    multi_hash_coloring_dist,
                )

                colors, n_colors = multi_hash_coloring_dist(
                    g, n_hash=n_hash)
            else:
                colors, n_colors = multi_hash_coloring(
                    g.sources().astype(np.int32),
                    g.tails.astype(np.int32),
                    g.num_vertices,
                    n_hash=n_hash,
                )
            if verbose:
                print(f"Number of colors (2*nHash rounds): {n_colors}, "
                      f"colored {int((colors >= 0).sum())}/{g.num_vertices}")
            # Compress to dense class ids (order preserved); uncolored
            # vertices form the last class (the reference passes
            # numColors+1 classes, main.cpp:259).
            used = np.unique(colors[colors >= 0])
            remap = np.zeros(max(int(used.max()) + 1, 1), dtype=np.int64)
            remap[used] = np.arange(len(used))
            dense = np.where(colors >= 0, remap[np.maximum(colors, 0)],
                             len(used))
            n_classes = len(used) + 1
            color_np = np.full(dg.total_padded_vertices, n_classes - 1,
                               dtype=np.int32)
            color_np[dg.old_to_pad] = dense
            if coloring:
                color_dev = (shard_1d(mesh, color_np) if mesh is not None
                             else jnp.asarray(color_np))
        else:
            color_np = None

        runner = None

        def _run_with_budget(run_threshold, **run_kw):
            # Sparse-exchange phases whose per-peer community budget
            # overflows are re-run with a grown budget; budget == nv_pad
            # covers the worst case, so the retry always terminates.  The
            # runner (plans + device uploads) is reused across calls within
            # a phase and rebuilt only when the budget actually grew.
            nonlocal budget, runner
            while True:
                if runner is None:
                    with tracer.stage("plan"):
                        runner = PhaseRunner(
                            dg, mesh=mesh, engine=engine,
                            budget=budget, exchange=phase_exchange,
                            color_local=color_np,
                            n_color_classes=n_classes,
                            ordering=bool(vertex_ordering and not coloring),
                            release_slabs=slabless,
                            tracer=tracer,
                            device_rebin=(phase >= 1),
                        )
                with tracer.stage("iterate"):
                    cp, cm, it, ovf = runner.run(run_threshold, **run_kw)
                if not ovf:
                    return cp, cm, it
                # Budget ceiling = the plan's owned window: the group
                # window under the two-level exchange, the shard window
                # otherwise (at the ceiling the owner-route cannot
                # overflow, so the retry terminates).
                cap = dg.nv_pad * (nshards // n_dcn
                                   if phase_exchange == "twolevel" else 1)
                budget = min(cap, max(4 * (runner.budget or 128), 512))
                runner = None
                if verbose:
                    print(f"sparse-exchange budget overflow; retrying phase "
                          f"{phase} with budget {budget}")

        comm_pad, curr_mod, iters = _run_with_budget(
            th, lower=-1.0, et_mode=et_mode, et_delta=et_delta,
            color_classes=color_dev, n_color_classes=n_classes,
        )
        # Capture BEFORE the slabless branch drops the runner; gained is
        # stamped (and the event emitted) once it is known below.
        phase_conv = getattr(runner, "convergence", None)
        tracer.event("exchange", mode=phase_exchange,
                     nshards=dg.nshards, budget=runner.budget,
                     plan=runner.xplan_stats)
        if exchange_stats is None and multi_mesh:
            exchange_stats = dict(runner.xplan_stats or
                                  {"mode": phase_exchange})
        if getattr(runner, "pallas_coverage", None) is not None:
            if engine != "pallas" and cov_den == 0:
                # Bucketed run, first kernel engagement: the phases
                # already processed WITHOUT coverage count as
                # non-kernelized mass, or the run-level fraction would
                # overstate itself (same rule as the class-schedule
                # case below).
                cov_den += cov_pending
            for w, n, k in runner.pallas_cov_detail:
                t = n * iters
                cov_den += t
                if k:
                    cov_num += t
                    width_hits[w] = width_hits.get(w, 0) + t
            if verbose:
                det = " ".join(
                    f"{'heavy' if w == 0 else w}:{n}{'*' if k else ''}"
                    for w, n, k in runner.pallas_cov_detail)
                print(f"pallas kernel coverage: "
                      f"{100 * runner.pallas_coverage:.1f}% of edges "
                      f"(per-width, * = kernel: {det})")
        elif engine == "pallas" or cov_den:
            # Class-scheduled phases (coloring/ordering — typically phase
            # 0, the bulk of the run's edge mass) sweep the XLA per-class
            # plans, never the kernel: their traversed mass counts as
            # NON-kernelized, or the run-level coverage would report only
            # the later plain phases and overstate itself.  Same rule for
            # a bucketed run whose heavy kernel engaged earlier (cov_den
            # nonzero) but whose coarser phases have no heavy residual.
            cov_den += g_ne * iters
        else:
            # No coverage recorded yet: remember this phase's mass so a
            # LATER heavy-kernel engagement (bucketed engine) folds it
            # into the denominator.  Engines that never engage leave
            # cov_den at 0 and report no coverage at all.
            cov_pending += g_ne * iters
        # The loop's f32 modularity decided convergence; the REPORTED value
        # is recomputed once per phase with f64-class accuracy
        # (louvain/precise.py) — the analog of the reference's double
        # accumulation (louvain.cpp:2433-2481).  The device ds pass is used
        # only when the slab is already resident (sort engine).
        with tracer.stage("evaluate"):
            if g_is_dv:
                # Per-host ingest: f64 e-term from local slabs + host
                # allreduce (no full graph exists anywhere).
                curr_mod = dg.modularity(comm_pad)
            else:
                curr_mod = phase_modularity(
                    dg, comm_pad, device_slab=_runner_slab(runner))
        t2 = time.perf_counter()
        tot_iters += iters
        tracer.count("traversed_edges", g_ne * iters)
        tracer.ledger_snapshot(phase)
        if dist_stats:
            from cuvite_tpu.utils.trace import dist_stats_report

            print(dist_stats_report(
                dg, getattr(runner, "ghost_counts", None)))
            dist_stats = False  # first executed phase only (resume-safe)
        if diag:
            gc = getattr(runner, "ghost_counts", None)
            for s, sh in enumerate(dg.shards):
                diag.write(s, f"phase {phase}: owned="
                           f"{sh.bound - sh.base} edges={sh.n_real_edges}"
                           f"{f' ghosts={gc[s]}' if gc else ''}"
                           f" iters={iters} Q={curr_mod:.6f}"
                           f" t={t2 - t1:.3f}s")

        # Map padded-space communities back to original-id labels for the
        # real vertices of this phase's graph.
        comm_old = comm_pad[dg.old_to_pad]  # label (padded id) per real vertex

        gained = (curr_mod - prev_mod) > th
        if phase_conv is not None:
            phase_conv.phase = phase
            phase_conv.gained = gained
            convergence.append(phase_conv)
            if tracer.emitter is not None:  # to_dict is ~CAP row dicts
                tracer.event("convergence", **phase_conv.to_dict())
        if gained:
            dense, nc = renumber_communities(comm_old)
            comm_all = dense[comm_all]
            phases.append(PhaseStats(
                phase=phase, modularity=curr_mod, iterations=iters,
                num_vertices=g_nv, num_edges=g_ne,
                seconds=t2 - t1,
            ))
            if verbose:
                print(f"Level {phase}, Modularity: {curr_mod:.6f}, "
                      f"Iterations: {iters}, nv: {g_nv}, "
                      f"time: {t2 - t1:.3f}s")
            if one_phase:
                prev_mod = curr_mod
                tracer.end_span(_phase_sid, gained=True)
                break
            if slabless:
                # Device plans + old phase state die before the coarsen
                # transient peaks (the runner holds the only refs to the
                # uploaded bucket matrices; dg holds the released slabs +
                # the remap tables).  comm_pad/dense survive via comm_old.
                runner = None
                comm_pad = None
                dg = None
            # Device-resident transition (the sort engine keeps the slab
            # in HBM): renumber + relabel + coalesce on device and hand
            # the coarse slab to the next phase through from_device_slab
            # — zero O(E) host transfers at the boundary.  Everything
            # else (bucketed plans are host-built; checkpoints serialize
            # host graphs; SPMD re-shards on host) keeps the oracle path.
            dev_transition = (
                engine == "sort" and dg.nshards == 1 and not g_is_dv
                and not checkpoint_dir
                and (mesh is None
                     or int(np.prod(mesh.devices.shape)) == 1)
                and runner is not None and runner.labels_dev is not None
                and runner.src is not None
                and device_coarsen_enabled())
            with tracer.stage("coarsen"):
                if g_is_dv:
                    # send_newEdges analog: local coarse triples,
                    # allgathered, rebuilt identically on every process.
                    dense_pad = np.zeros(dg.total_padded_vertices,
                                         dtype=np.int64)
                    dense_pad[dg.old_to_pad] = dense
                    cs, cd, cw = dg.coarse_edges(dense_pad, nc)
                    g = Graph.from_edges(
                        nc, cs, cd, weights=cw, symmetrize=False,
                        policy=dg.graph.policy)
                elif dev_transition:
                    from cuvite_tpu.kernels.seg_coalesce import (
                        coalesce_engine,
                    )

                    acc = (runner.accum_name
                           if runner.accum_name == "ds32" else None)
                    eng = coalesce_engine(dg.nv_pad, acc)
                    with tracer.stage("coalesce"):
                        src2, dst2, w2, _dm, _nc_d, ne2_d = \
                            device_coarsen_slab(
                                runner.src, runner.dst, runner.w,
                                runner.labels_dev, runner.real_mask_dev,
                                nv_pad=dg.nv_pad, accum_dtype=acc,
                                coalesce=eng)
                        # The one scalar-per-phase host sync (nc is
                        # already on the host from the renumber above):
                        # decides whether the coarse graph fits a
                        # smaller pow2 slab class.
                        ne2 = int(ne2_d)
                    tracer.count("coalesce_edges", g_ne)
                    if eng != "sort":
                        tracer.count("coalesce_dense_edges", g_ne)
                    pol = dg.graph.policy
                    tw2 = dg.graph.total_edge_weight_twice()
                    src2, dst2, w2, new_nv_pad, new_ne_pad = \
                        maybe_shrink_to_class(
                            src2, dst2, w2, nc=nc, ne2=ne2,
                            nv_pad=dg.nv_pad, ne_pad=dg.ne_pad)
                    pending_dg = DistGraph.from_device_slab(
                        src2, dst2, w2, num_vertices=nc, num_edges=ne2,
                        nv_pad=new_nv_pad, ne_pad=new_ne_pad, policy=pol,
                        total_weight_twice=tw2)
                    g = pending_dg.graph  # SlabMeta: scalar facts only
                else:
                    g = coarsen_graph(g, dense, nc)
            tracer.event("coarsen", nv_from=g_nv, ne_from=g_ne, nv_to=nc,
                         device=bool(dev_transition))
            prev_mod = curr_mod
            phase += 1
            if checkpoint_dir:
                from cuvite_tpu.utils.checkpoint import (
                    PhaseCheckpoint, save_phase,
                )

                if ck_fp is None:  # O(ne) scan once per run, not per phase
                    ck_fp = _source_fingerprint(graph)
                # Per-host ingest: the fingerprint allgather above is
                # collective (every process participates); the write is
                # process 0's alone so concurrent writers cannot race on
                # one shared checkpoint directory.
                if not dist_ingest or jax.process_index() == 0:
                    save_phase(checkpoint_dir, PhaseCheckpoint(
                        phase=phase, comm_all=comm_all, graph=g,
                        prev_mod=prev_mod, tot_iters=tot_iters,
                        mod_hist=np.array([p.modularity for p in phases]),
                        iter_hist=np.array([p.iterations for p in phases]),
                        nv_hist=np.array([p.num_vertices for p in phases]),
                        ne_hist=np.array([p.num_edges for p in phases]),
                        orig_ne=graph.num_edges,
                        fingerprint=ck_fp,
                    ))
            tracer.end_span(_phase_sid, gained=True)
        else:
            # Safety net: when cycling exits early, run one final 1e-6 pass
            # (main.cpp:432-442).  Note: lower must be -1 (not prev_mod), or
            # the restarted sweep — whose first-iteration modularity is that
            # of the identity assignment — terminates immediately and the
            # pass is dead.
            if threshold_cycling and not one_phase and phase < 10 and th > 1.0e-6:
                comm_pad, curr_mod, iters = _run_with_budget(
                    1.0e-6, lower=-1.0)
                with tracer.stage("evaluate"):
                    if g_is_dv:
                        curr_mod = dg.modularity(comm_pad)
                    else:
                        curr_mod = phase_modularity(
                            dg, comm_pad, device_slab=_runner_slab(runner))
                tot_iters += iters
                comm_old = comm_pad[dg.old_to_pad]
                final_gained = (curr_mod - prev_mod) > 1.0e-6
                pc_final = getattr(runner, "convergence", None)
                if pc_final is not None:
                    pc_final.phase = phase
                    pc_final.gained = final_gained
                    convergence.append(pc_final)
                    if tracer.emitter is not None:
                        tracer.event("convergence", **pc_final.to_dict())
                if final_gained:
                    dense, nc = renumber_communities(comm_old)
                    comm_all = dense[comm_all]
                    prev_mod = curr_mod
                    phases.append(PhaseStats(
                        phase=phase, modularity=curr_mod, iterations=iters,
                        num_vertices=g_nv, num_edges=g_ne,
                        seconds=time.perf_counter() - t1,
                    ))
            tracer.end_span(_phase_sid, gained=False)
            break

    if diag:
        diag.close()
    tracer.set_phase(None)
    # Final contiguous renumber of the composed labels (main.cpp:374-394).
    dense_all, _ = renumber_communities(comm_all)
    return LouvainResult(
        communities=dense_all,
        modularity=prev_mod,
        phases=phases,
        total_iterations=tot_iters,
        total_seconds=time.perf_counter() - t_start,
        pallas_coverage=(cov_num / cov_den) if cov_den else None,
        pallas_width_hits=width_hits or None,
        convergence=convergence,
        exchange_stats=exchange_stats,
    )
