"""Per-phase precise modularity (double-single accumulation on device).

The reference reports per-phase modularity accumulated in C++ double
(/root/reference/louvain.cpp:2433-2481).  Here the per-ITERATION
convergence check stays f32 (error ~6e-8, far under every threshold), and
the value REPORTED per phase is recomputed once on the phase's final
assignment with double-single arithmetic (cuvite_tpu/ops/exactsum.py):
~2^-43 relative error using only f32 ops, no x64 mode, no extra memory
beyond one O(E) pass.

Two execution paths, chosen by where the edge slab already lives:

- device (``device_slab`` given, single shard): one jitted ds pass over the
  RESIDENT slab — used by the 'sort' engine, whose src/dst/w are already on
  device; only the [nv_pad] assignment is uploaded.  NOTE: the pass's
  transients are O(E); callers must not upload a second slab copy just for
  this (the bucketed engine deliberately keeps no slab on device).
- host (default): the phase-end assignment is already host-side, so the
  f64 numpy oracle (evaluate/modularity.py) computes the identical value
  with zero device memory — O(E) host work once per phase.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from cuvite_tpu.ops import exactsum as ds


@functools.partial(jax.jit, static_argnames=("nv_pad",))
def _precise_mod_device(src, dst, w, comm, c_hi, c_lo, *, nv_pad):
    """Q = le*c - la2*c^2 in ds arithmetic over one shard's edge slab.

    ``src`` local indices, src-SORTED (CSR order; pad = nv_pad sorts last),
    ``dst`` indices into ``comm``'s id space, ``w`` zero on padding;
    ``comm`` the [nv_pad] assignment.  Vertex degrees are accumulated in ds
    from the slab itself, so even non-integral f32 weights keep f64-class
    totals end to end.
    """
    safe_src = jnp.minimum(src, nv_pad - 1)
    csrc = jnp.take(comm, safe_src)
    ck = jnp.take(comm, dst)
    internal = (csrc == ck) & (src < nv_pad)
    le = ds.ds_tree_sum(jnp.where(internal, w, jnp.zeros_like(w)))

    # per-vertex weighted degree (ds) from the src-sorted slab
    vd_hi, vd_lo, last = ds.ds_segment_sums_sorted(src, w)
    scat = jnp.where(last & (src < nv_pad), safe_src, nv_pad)
    deg_hi = jnp.zeros((nv_pad,), w.dtype).at[scat].set(vd_hi, mode="drop")
    deg_lo = jnp.zeros((nv_pad,), w.dtype).at[scat].set(vd_lo, mode="drop")

    # group by community, ds-pair segment sums, square, reduce
    cs, dh, dl = jax.lax.sort((comm, deg_hi, deg_lo), num_keys=1)
    run_hi, run_lo, _ = ds.ds_segment_sums_sorted(cs, dh, dl)
    sq_hi, sq_lo = ds.ds_mul((run_hi, run_lo), (run_hi, run_lo))
    la2 = ds.ds_tree_sum(sq_hi, sq_lo)

    c = (c_hi, c_lo)
    q = ds.ds_add(ds.ds_mul(le, c),
                  ds.ds_neg(ds.ds_mul(la2, ds.ds_mul(c, c))))
    return q[0], q[1]


def phase_modularity(dg, comm_pad: np.ndarray, device_slab=None) -> float:
    """Precise modularity of ``comm_pad`` (padded-space labels) for the
    DistGraph's underlying graph, as a python float with f64-class accuracy.

    ``device_slab``: optional (src, dst, w) jax arrays ALREADY resident on
    device (single-shard layout) — the ds pass then runs on device with no
    O(E) upload.  Without it the host f64 oracle is used.
    """
    g = dg.graph
    if device_slab is not None and dg.nshards == 1:
        src, dst, w = device_slab
        c_hi, c_lo = ds.ds_from_f64(1.0 / g.total_edge_weight_twice())
        q = _precise_mod_device(
            src, dst, w.astype(jnp.float32),
            jnp.asarray(np.asarray(comm_pad).astype(src.dtype)),
            c_hi.astype(jnp.float32), c_lo.astype(jnp.float32),
            nv_pad=dg.nv_pad,
        )
        return ds.ds_to_f64(q)
    # Assignment is on host at phase end; f64 numpy oracle.
    from cuvite_tpu.evaluate.modularity import modularity

    comm_old = np.asarray(comm_pad)[dg.old_to_pad]
    return modularity(g, comm_old)
