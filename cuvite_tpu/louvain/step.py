"""One Louvain iteration as a pure, jittable SPMD function.

Maps the reference's per-iteration pipeline
(/root/reference/louvain.cpp:471-574) onto dense TPU ops:

  fillRemoteCommunities  (louvain.cpp:2588-2959)  -> lax.all_gather of the
      sharded community vector (communities of ghost tails become plain
      gathers from the replicated copy)
  distExecuteLouvainIteration (louvain.cpp:2246-2382) -> edge-parallel
      sort + segment-reduce + segment-argmax
  distUpdateLocalCinfo / updateRemoteCommunities (louvain.cpp:2539-2552,
      :2983-3116) -> community size/degree are *recomputed* each step as
      segment sums + psum, which is cheaper than replaying the reference's
      4-case atomic delta protocol and cannot drift
  distComputeModularity (louvain.cpp:2433-2481) -> two sums + psum

Gain formula, argmax tie-breaks and the singleton-swap guard replicate
distGetMaxIndex exactly (/root/reference/louvain.cpp:2185-2244):

    gain(i -> y) = 2*(e_{i->y} - e_{i->x}) - 2*k_i*(a_y - a_x) / (2m)

with e_{i->x} excluding self-loops, a_x = deg(x) - k_i, a_y = deg(y); only
strictly positive gains move a vertex; ties break to the smaller community id;
two singletons never merge "upward" (maxIndex > currComm blocked).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from cuvite_tpu.comm.mesh import shard_map
from cuvite_tpu.ops import segment as seg


class StepOut(NamedTuple):
    target: jax.Array     # [nv_local] new community per owned vertex
    modularity: jax.Array  # scalar: modularity of the INPUT assignment
    n_moved: jax.Array     # scalar int32: vertices that changed community


def louvain_step_local(
    src,          # [ne_pad] int: LOCAL source index; pad = nv_local
    dst,          # [ne_pad] int: GLOBAL (padded-space) tail id; pad = 0, w = 0
    w,            # [ne_pad] weight
    comm_local,   # [nv_local] int: community id (padded-global space)
    vdeg_local,   # [nv_local] weight: k_i
    constant,     # scalar: 1 / (2m)
    *,
    nv_total: int,
    axis_name: str | None = None,
    accum_dtype=None,
) -> StepOut:
    """One synchronous Louvain sweep over this shard's vertices.

    Pure SPMD: when ``axis_name`` is given the function runs inside
    shard_map over a 1-D mesh and communicates via all_gather/psum; with
    ``axis_name=None`` it is the single-shard program (comm_local is the full
    community vector).
    """
    nv_local = comm_local.shape[0]
    wdt = w.dtype
    vdt = comm_local.dtype
    sentinel = jnp.iinfo(vdt).max

    comm_full, gsum = seg.spmd_env(comm_local, axis_name)
    if axis_name is None:
        base = 0
    else:
        base = jax.lax.axis_index(axis_name).astype(vdt) * nv_local

    # --- community info: size + weighted degree, recomputed fresh ---------
    comm_deg = gsum(
        seg.segment_sum(vdeg_local, comm_local, num_segments=nv_total)  # graftlint: replicated-ok=scope=ici; replicated-exchange community degree table (sort engine is flat-mesh-only; a flat mesh is one ICI group)
    )
    comm_size = gsum(
        seg.segment_sum(  # graftlint: replicated-ok=scope=ici; replicated-exchange community size table (sort engine is flat-mesh-only; a flat mesh is one ICI group)
            jnp.ones((nv_local,), dtype=vdt), comm_local, num_segments=nv_total
        )
    )

    # --- per-edge community keys ------------------------------------------
    src_c = jnp.minimum(src, nv_local - 1)  # clamp padding for safe gathers
    csrc = jnp.take(comm_local, src_c)              # community of edge source
    ckey = jnp.take(comm_full, dst)                 # community of edge tail
    src_global = src.astype(vdt) + base

    # weight to current community (incl. self-loops) and self-loop weight
    # (cf. counter[0] / selfLoop, louvain.cpp:2288-2296, :2396-2427)
    to_curr = jnp.where(ckey == csrc, w, jnp.zeros_like(w))
    counter0 = seg.segment_sum(to_curr, src, num_segments=nv_local, sorted_ids=True)
    self_w = jnp.where(dst == src_global, w, jnp.zeros_like(w))
    self_loop = seg.segment_sum(self_w, src, num_segments=nv_local, sorted_ids=True)
    eix = counter0 - self_loop

    # --- neighbor-community aggregation: sort + run segment sums ----------
    src_s, ckey_s, w_s = seg.sort_edges_by_vertex_comm(
        src, ckey, w, src_bound=nv_local + 1, key_bound=nv_total)
    starts = seg.run_starts(src_s, ckey_s)
    eiy, _ = seg.run_totals(w_s, starts)

    i_s = jnp.minimum(src_s, nv_local - 1)
    comm_i = jnp.take(comm_local, i_s)
    valid = starts & (src_s < nv_local) & (ckey_s != comm_i)

    # --- dQ for every candidate run ---------------------------------------
    k_i = jnp.take(vdeg_local, i_s)
    a_y = jnp.take(comm_deg, ckey_s)
    a_x = jnp.take(comm_deg, comm_i) - k_i
    gain = 2.0 * (eiy - jnp.take(eix, i_s)) - 2.0 * k_i * (a_y - a_x) * constant
    neg_inf = jnp.array(-jnp.inf, dtype=wdt)
    gain = jnp.where(valid, gain, neg_inf)

    # --- per-vertex argmax with tie-break to smaller community id ---------
    best_gain = seg.segment_max(gain, src_s, num_segments=nv_local, sorted_ids=True)
    is_best = valid & (gain == jnp.take(best_gain, i_s))
    cand_c = jnp.where(is_best, ckey_s, jnp.full_like(ckey_s, sentinel))
    best_c = seg.segment_min(cand_c, src_s, num_segments=nv_local, sorted_ids=True)

    move = best_gain > 0.0
    best_c_safe = jnp.minimum(best_c, jnp.array(nv_total - 1, dtype=vdt))
    # singleton-swap guard (louvain.cpp:2240-2241)
    t_size = jnp.take(comm_size, best_c_safe)
    c_size = jnp.take(comm_size, comm_local)
    guard = (t_size == 1) & (c_size == 1) & (best_c_safe > comm_local)
    move = move & ~guard
    target = jnp.where(move, best_c_safe, comm_local)

    # --- modularity of the INPUT assignment (louvain.cpp:2433-2481) -------
    modularity = seg.modularity_terms(counter0, comm_deg, constant, gsum,
                                      accum_dtype, axis_name=axis_name)

    n_moved = gsum(jnp.sum(move.astype(jnp.int32)))  # graftlint: width-ok=move is per-VERTEX (nv_pad <= 2^28 rows, sum <= 2^28 < 2^31); the slab-extent tag is argmax-index over-approximation, not a real edge-extent reduction
    return StepOut(target=target, modularity=modularity, n_moved=n_moved)


def make_sharded_step(mesh: Mesh, axis_name: str, nv_total: int,
                      accum_dtype=None):
    """Build the jitted multi-chip step: edges + state sharded over
    ``axis_name``, modularity replicated."""

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis_name), P(axis_name), P(axis_name), P(axis_name),
                  P(axis_name), P()),
        out_specs=(P(axis_name), P(), P(), P()),
        check_vma=False,
    )
    def step(src, dst, w, comm, vdeg, constant):
        out = louvain_step_local(
            src, dst, w, comm, vdeg, constant,
            nv_total=nv_total, axis_name=axis_name, accum_dtype=accum_dtype,
        )
        # Uniform step contract: (target, modularity, n_moved, overflow);
        # the replicated exchange can never overflow.
        return out.target, out.modularity, out.n_moved, jnp.zeros((), bool)

    return jax.jit(step)


def make_single_step(nv_total: int, accum_dtype=None):
    """Jitted single-device step (mesh of one)."""

    def step(src, dst, w, comm, vdeg, constant):
        out = louvain_step_local(
            src, dst, w, comm, vdeg, constant,
            nv_total=nv_total, axis_name=None, accum_dtype=accum_dtype,
        )
        return out.target, out.modularity, out.n_moved, jnp.zeros((), bool)

    return jax.jit(step)
