"""Batched multi-tenant Louvain: B same-class graphs, ONE compiled step
per phase (ISSUE 9).

Serving "millions of users" means thousands of small graphs (per-user
neighborhoods, per-session interaction graphs) arriving concurrently —
and for slab-class-canonicalized graphs the dominant cost of serving
them one at a time is per-job dispatch: the compiled-program launch,
the per-phase host sync, the Python driver overhead.  All of it is
amortizable, because every graph of one ``(nv_pad, ne_pad)`` class runs
the *same program on the same shapes*.  This driver stacks B such
slabs on a leading batch axis (core/batch.py) and runs the whole batch
through one jitted per-phase program:

  * ``jax.vmap`` of the fused phase loop (louvain/fused.py::fused_phase):
    under vmap the ``lax.while_loop`` iterates until EVERY row's phase
    converges, masking finished rows — so B phase loops cost
    max(iters_b) batched sweeps, not sum(iters_b) sequential ones;
  * the vmapped device coarsener (coarsen/device.py::batched_renumber /
    batched_compose_labels / batched_coarsen_slab): per-row dense
    renumbering, label composition and slab relabel+coalesce, all in
    HBM, landing every row's coarse graph back in the SAME class;
  * per-graph phase exit by MASKING, not batch splitting: a row whose
    phase fails the gain threshold keeps its composed labels and has
    its slab overwritten with padding — trailing phases cost it two
    masked sweeps, and the batch shape (the compile key) never changes.

One host sync per phase for the whole batch (driver._phase_sync — the
same chokepoint the per-graph drivers use, so the sync-spy tests cover
both), one compile per ``(class, B)``, and one final O(B * nv_pad)
label gather.  Labels and per-row Q are bit-identical to running the
same driver at B=1 — vmap lifts every op row-wise, and nothing in the
program mixes rows.

Batch-axis data parallelism.  The program is row-independent by
construction, so the batch axis shards over a 1-D device mesh with NO
collectives (``shard_map`` with every spec ``P('b')``): on a TPU slice
tenants spread across chips; on CPU the same split over
``--xla_force_host_platform_device_count`` virtual devices is what
makes batching pay — XLA:CPU executes a batched ``lax.sort`` serially
(measured: a [64, 16384] two-channel sort costs exactly 64x the
single-row sort on a 24-core host; sharded over 8 virtual devices it
drops 7.3x), so without the mesh a CPU batch amortizes dispatch but
serializes compute.  Each shard's ``while_loop`` trip count follows its
OWN rows (no collectives inside), so a shard whose tenants converge
early goes idle instead of pacing the batch.

Scope: fixed threshold, no cycling (the cycling safety-net pass
re-enters rows at different phases, which would fragment the batch; the
serving default is the reference's final threshold 1e-6 anyway), plain
schedule (no ET/coloring), single shard per row.  The per-graph drivers
in driver.py keep every other configuration.
"""

from __future__ import annotations

import dataclasses
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from cuvite_tpu.coarsen.device import (
    batched_coarsen_slab,
    batched_compose_labels,
    batched_renumber,
    batched_subrow_compose,
    batched_subrow_renumber,
)
from cuvite_tpu.core.batch import (
    BATCH_ENGINES,
    BatchedSlab,
    PackedSubRows,
    batch_slabs,
)
from cuvite_tpu.core.types import (
    MAX_TOTAL_ITERATIONS,
    TERMINATION_PHASE_COUNT,
)
from cuvite_tpu.louvain.fused import fused_phase
from cuvite_tpu.obs.convergence import decode_phase_conv
from cuvite_tpu.ops import segment as seg
from cuvite_tpu.utils.upload import to_device

# Batched engines (canonical tuple: core.batch.BATCH_ENGINES, re-
# exported above): 'fused' — vmapped fused phase loop (the packed
# 2-channel lax.sort sweep) every phase; 'bucketed' — phase 0 runs the
# vmapped BUCKETED sweep over cross-graph-padded plans (ISSUE 10; the
# sort-free formulation every per-graph benchmark shows is the fast
# one), and phases >= 1 RE-BIN ON DEVICE (ISSUE 19): the coarse slab is
# re-bucketed inside the phase program by coarsen/rebin.py's histogram +
# gather builder, so coarse phases stay on the sort-free formulation
# too.  Classes the re-binner cannot certify (possible heavy residual,
# element budget — coarsen/rebin.py::rebin_eligible) and
# CUVITE_DEVICE_REBIN=0 fall back to the fused loop, the pre-ISSUE-19
# downgrade.  The per-phase engine actually used is recorded in
# BatchResult.phase_engines.


def _phase_body(src, dst, w, comm_all, real_mask, prev_mod, active,
                constant, threshold, *, nv_pad, accum_dtype, coalesce,
                max_iters=MAX_TOTAL_ITERATIONS):
    """One Louvain phase for the whole batch: vmapped fused phase loop +
    gain test + vmapped device coarsening, converged rows masked.

    Row state (all leading-axis B): ``src/dst/w`` — the current coarse
    slab (dense ids, same class every phase); ``comm_all`` — original
    vertex -> current dense community id; ``real_mask`` — current real-
    vertex mask; ``prev_mod`` — last gaining phase's Q (or -1);
    ``active`` — row still clustering.  Returns the updated state plus
    per-row ``(gained, mod, iters, nc, ne2)`` scalars and the
    convergence telemetry buffers ``(cq, cmoved, covf)`` [B, CAP].

    Shape-polymorphic in the leading axis: jitted whole for the
    single-device program, or wrapped per-shard by
    :func:`_get_batched_phase` when the batch axis is sharded.
    """
    adt = accum_dtype

    past, mod, iters, _ovf, (cq, cmoved, covf) = jax.vmap(
        lambda s, d, ww, c: fused_phase(
            s, d, ww, c, threshold, nv_pad=nv_pad, accum_dtype=adt,
            max_iters=max_iters)
    )(src, dst, w, constant)

    return _phase_tail(
        src, dst, w, comm_all, real_mask, prev_mod, active, threshold,
        past, mod, iters, cq, cmoved, covf,
        nv_pad=nv_pad, accum_dtype=accum_dtype, coalesce=coalesce)


def _bucketed_phase_body(buckets, heavy, self_loop, perm, src, dst, w,
                         comm_all, real_mask, prev_mod, active, constant,
                         threshold, *, nv_pad, accum_dtype, coalesce,
                         max_iters=MAX_TOTAL_ITERATIONS):
    """The sort-free phase: the per-graph BUCKETED sweep lifted over the
    batch axis (ISSUE 10).  Same contract as :func:`_phase_body`, plus
    the batched plan arrays (core/batch.py::batch_bucket_plans) ahead of
    the slab state.

    The row sweep is literally the per-graph bucketed driver's phase
    loop — ``driver._run_phase_loop`` over ``driver._bucketed_call``
    (identity start, on-device convergence check, the degree-bucketed
    dense row formulation of Naim et al., arXiv:1805.10904) — vmapped,
    so per-tenant labels stay bit-identical to a B=1 run.  Engine
    degradations under vmap: no Pallas row-argmax flags and no promoted
    heavy-kernel layout (their grids do not lift over a batch axis; the
    XLA paths they degrade to are bit-identical, the batched-coalesce
    precedent), and the heavy residual runs the sorted path on its
    (usually 8-slot padding) slab.  The slab itself is swept ONLY for
    the per-row weighted degrees — no per-iteration ne_pad-sized sort.

    The coarsen + masked-exit tail is shared with the fused body, so
    phase transitions cannot drift between engines.
    """
    from cuvite_tpu.louvain.driver import _bucketed_call, _run_phase_loop

    wdt = w.dtype
    sentinel = int(np.iinfo(np.int32).max)
    call = _bucketed_call(nv_pad, sentinel, accum_dtype)
    lower = jnp.asarray(-1.0, dtype=wdt)
    th = jnp.asarray(threshold, dtype=wdt)

    def one(bk, hv, sl, pm, s, ww, c):
        vdeg = seg.segment_sum(ww, s, num_segments=nv_pad,
                               sorted_ids=True)
        comm0 = jnp.arange(nv_pad, dtype=jnp.int32)
        # The trailing None is the heavy-kernel slot of the single-shard
        # bucketed call convention (sorted heavy path).
        extra = (bk, hv, sl, vdeg, c, pm, None)
        return _run_phase_loop(extra, comm0, th, lower, call=call,
                               max_iters=max_iters)

    past, mod, iters, _ovf, (cq, cmoved, covf) = jax.vmap(one)(
        buckets, heavy, self_loop, perm, src, w, constant)

    return _phase_tail(
        src, dst, w, comm_all, real_mask, prev_mod, active, threshold,
        past, mod, iters, cq, cmoved, covf,
        nv_pad=nv_pad, accum_dtype=accum_dtype, coalesce=coalesce)


def _rebinned_phase_body(src, dst, w, comm_all, real_mask, prev_mod,
                         active, constant, threshold, *, nv_pad,
                         accum_dtype, coalesce,
                         max_iters=MAX_TOTAL_ITERATIONS):
    """The sort-free COARSE phase (ISSUE 19): same 9-operand contract as
    :func:`_phase_body`, but the row sweep is the bucketed formulation
    over a plan built ON DEVICE from the coarse slab by
    :func:`cuvite_tpu.coarsen.rebin.rebin_plan` — degree histogram,
    static-ladder class assignment, gather into the stacked
    ``[rows, width]`` layout — vmapped over the batch.  The coarse slab
    rows satisfy the re-binner's contract by construction: the vmapped
    coalesce emits ascending compacted runs with a padding tail, the
    masked-exit rows are pure padding, and ``_shrink_batch`` preserves
    the prefix.  Plan geometry is derived from the static slab class
    (``src.shape[-1]``), so the program is one compile per (class, B)
    like the fused body it replaces; eligibility (no heavy residual
    possible, element budget) is the CALLER's gate —
    ``rebin_eligible`` must hold for this body's class.

    The coarsen + masked-exit tail is shared with the other bodies, so
    phase transitions cannot drift between engines.
    """
    from cuvite_tpu.coarsen.rebin import rebin_geometry, rebin_plan
    from cuvite_tpu.louvain.driver import _bucketed_call, _run_phase_loop

    wdt = w.dtype
    ne_pad = src.shape[-1]
    geom = rebin_geometry(nv_pad, ne_pad)
    sentinel = int(np.iinfo(np.int32).max)
    call = _bucketed_call(nv_pad, sentinel, accum_dtype)
    lower = jnp.asarray(-1.0, dtype=wdt)
    th = jnp.asarray(threshold, dtype=wdt)

    def one(s, d, ww, c):
        bk, hv, sl, pm = rebin_plan(s, d, ww, nv_pad=nv_pad, base=0,
                                    geometry=geom)
        vdeg = seg.segment_sum(ww, s, num_segments=nv_pad,
                               sorted_ids=True)
        comm0 = jnp.arange(nv_pad, dtype=jnp.int32)
        # The trailing None is the heavy-kernel slot of the single-shard
        # bucketed call convention (sorted heavy path — here the static
        # 8-slot padding placeholder the re-binner certifies).
        extra = (bk, hv, sl, vdeg, c, pm, None)
        return _run_phase_loop(extra, comm0, th, lower, call=call,
                               max_iters=max_iters)

    past, mod, iters, _ovf, (cq, cmoved, covf) = jax.vmap(one)(
        src, dst, w, constant)

    return _phase_tail(
        src, dst, w, comm_all, real_mask, prev_mod, active, threshold,
        past, mod, iters, cq, cmoved, covf,
        nv_pad=nv_pad, accum_dtype=accum_dtype, coalesce=coalesce)


def _subrow_phase_body(src, dst, w, comm_all, real_mask, prev_mod, active,
                       constants, threshold, *, nv_pad, n_sub, accum_dtype,
                       coalesce, max_iters=MAX_TOTAL_ITERATIONS):
    """The PACKED phase (ISSUE 20): ``n_sub`` fenced small graphs per
    row, the whole batch through the vmapped sub-row sweep
    (louvain/subrow.py).  Same 9-operand contract as :func:`_phase_body`
    except everything per-GRAPH is ``[B, n_sub]`` instead of ``[B]``:
    ``prev_mod``/``active``/``constants`` in, and the tail's
    ``(gained, mod, iters, nc, ne2)`` out (telemetry ``cq``/``cmoved``
    are ``[B, n_sub, CAP]``).  ``n_sub`` is the STATIC layout class —
    which tenants occupy which sub-row is batch content and never
    reaches a static (the B002 audit pins this for a packed batch).

    ``comm_all`` keeps the ORIGINAL row width even after the slab
    class shrinks — its trailing dim fixes the pack-time ``nv_sub0``
    for the two-offset-space coarsening (coarsen/device.py)."""
    from cuvite_tpu.louvain.subrow import subrow_phase

    past, mod, iters, _ovf, (cq, cmoved, covf) = jax.vmap(
        lambda s, d, ww, c: subrow_phase(
            s, d, ww, c, threshold, nv_pad=nv_pad, n_sub=n_sub,
            accum_dtype=accum_dtype, max_iters=max_iters)
    )(src, dst, w, constants)

    return _subrow_phase_tail(
        src, dst, w, comm_all, real_mask, prev_mod, active, threshold,
        past, mod, iters, cq, cmoved, covf,
        nv_pad=nv_pad, n_sub=n_sub, coalesce=coalesce)


def _subrow_phase_tail(src, dst, w, comm_all, real_mask, prev_mod, active,
                       threshold, past, mod, iters, cq, cmoved, covf, *,
                       nv_pad, n_sub, coalesce):
    """Phase epilogue of the packed engine: the gain test, coarsening
    and masked exit of :func:`_phase_tail`, all at SUB-row granularity.
    Retired sub-rows' edges are masked to the row sentinel BEFORE the
    whole-row coalesce (so they compact away and batch-mates inherit a
    pure padding tail), and ``comm_all`` is composed through the
    ORIGINAL-offset dense map so final labels always live in pack-time
    offsets — unpack stays a fence slice regardless of when each
    sub-row retired or whether the slab class shrank."""
    wdt = w.dtype
    nv_sub = nv_pad // n_sub
    nv_sub0 = comm_all.shape[-1] // n_sub
    mod = mod.astype(wdt)
    gained = active & ((mod - prev_mod) > threshold)      # [B, n_sub]

    dmap_cur, dmap_orig, nc = batched_subrow_renumber(
        past, real_mask, nv_pad=nv_pad, n_sub=n_sub, nv_sub0=nv_sub0)
    comm_all2 = batched_subrow_compose(
        dmap_orig, past, comm_all, nv_pad=nv_pad, n_sub=n_sub,
        nv_sub0=nv_sub0)

    # Pre-coalesce retire: non-gaining sub-rows' edges -> row sentinel.
    seg_e = jnp.minimum(jnp.minimum(src, nv_pad - 1) // nv_sub, n_sub - 1)
    keep = (src < nv_pad) & jnp.take_along_axis(gained, seg_e, axis=1)
    src_m = jnp.where(keep, src, jnp.asarray(nv_pad, src.dtype))
    dst_m = jnp.where(keep, dst, jnp.zeros_like(dst))
    w_m = jnp.where(keep, w, jnp.zeros_like(w))

    # Relabel through the CURRENT-offset segment-local map + whole-row
    # coalesce — the device_coarsen_slab body with subrow maps (fences
    # keep every run single-sub-row, so run sums are bit-identical to
    # the solo slab's).  Packed rows are f32-only: accum stays None.
    def one(s, d, ww, c, dm):
        pad = s >= nv_pad
        cs = jnp.take(dm, jnp.take(c, jnp.minimum(s, nv_pad - 1)))
        cd = jnp.take(dm, jnp.take(c, d))
        ns = jnp.where(pad, jnp.asarray(nv_pad, s.dtype), cs.astype(s.dtype))
        nd = jnp.where(pad, jnp.zeros((), d.dtype), cd.astype(d.dtype))
        wi = jnp.where(pad, jnp.zeros_like(ww), ww)
        s2, d2, w2, _ = seg.coalesced_runs(
            ns, nd, wi, nv_pad=nv_pad, accum_dtype=None, engine=coalesce)
        return s2, d2, w2.astype(wdt)

    src2, dst2, w2 = jax.vmap(one)(src_m, dst_m, w_m, past, dmap_cur)

    # Per-sub-row coarse edge count (the shrink decision's ne2).
    seg2 = jnp.minimum(jnp.minimum(src2, nv_pad - 1) // nv_sub, n_sub - 1)
    ne2 = jax.vmap(
        lambda sid, rr: seg.segment_sum(rr, sid, num_segments=n_sub)
    )(seg2, (src2 < nv_pad).astype(jnp.int32))

    # Masked per-SUB-row exit: gaining sub-rows advance to per-segment
    # real-mask prefixes; retired ones go dark (labels already frozen
    # in comm_all at original offsets).
    segv = jnp.arange(nv_pad, dtype=jnp.int32) // nv_sub
    rloc = jnp.arange(nv_pad, dtype=jnp.int32) % nv_sub
    rm_o = (rloc[None, :] < jnp.take(nc, segv, axis=1)) \
        & jnp.take(gained, segv, axis=1)
    segp = jnp.arange(comm_all.shape[-1], dtype=jnp.int32) // nv_sub0
    gp = jnp.take(gained, segp, axis=1)
    comm_all_o = jnp.where(gp, comm_all2, comm_all)
    lower = jnp.asarray(-1.0, dtype=wdt)
    prev_o = jnp.where(gained, jnp.maximum(mod, lower), prev_mod)

    return (src2, dst2, w2, comm_all_o, rm_o, prev_o,
            gained, mod, iters, nc, ne2, cq, cmoved, covf)


def _phase_tail(src, dst, w, comm_all, real_mask, prev_mod, active,
                threshold, past, mod, iters, cq, cmoved, covf, *,
                nv_pad, accum_dtype, coalesce):
    """Shared phase epilogue (every batched engine): gain test, vmapped
    device coarsening, masked per-row phase exit.  One definition so the
    fused and bucketed phases retire rows and advance slabs
    identically."""
    wdt = w.dtype
    mod = mod.astype(wdt)
    gained = active & ((mod - prev_mod) > threshold)

    # Vmapped device coarsener: dense renumber (reused by the label
    # composition), relabel+coalesce back into the same slab class.
    # Run sums accumulate in ds32 pairs exactly when the in-loop Q does
    # (the same scale gate the per-graph drivers apply).
    acc = "ds32" if accum_dtype == "ds32" else None
    dmap, nc = batched_renumber(past, real_mask, nv_pad=nv_pad)
    comm_all2 = batched_compose_labels(dmap, past, comm_all)
    src2, dst2, w2, _dm, _nc, ne2 = batched_coarsen_slab(
        src, dst, w, past, real_mask, dmap, nc,
        nv_pad=nv_pad, accum_dtype=acc, coalesce=coalesce)
    rm2 = jnp.arange(nv_pad, dtype=jnp.int32)[None, :] < nc[:, None]

    # Masked phase exit: a gaining row advances to its coarse slab; a
    # non-gaining (or already-inactive) row keeps its labels and has its
    # slab retired to pure padding — trailing phases then cost it two
    # masked sweeps, and the batch never splits or changes shape.
    g2 = gained[:, None]
    src_o = jnp.where(g2, src2, jnp.full_like(src, nv_pad))
    dst_o = jnp.where(g2, dst2, jnp.zeros_like(dst))
    w_o = jnp.where(g2, w2, jnp.zeros_like(w))
    rm_o = jnp.where(g2, rm2, jnp.zeros_like(real_mask))
    comm_all_o = jnp.where(g2, comm_all2, comm_all)
    lower = jnp.asarray(-1.0, dtype=wdt)
    prev_o = jnp.where(gained, jnp.maximum(mod, lower), prev_mod)

    return (src_o, dst_o, w_o, comm_all_o, rm_o, prev_o,
            gained, mod, iters, nc, ne2, cq, cmoved, covf)


# The batch-axis mesh dimension name (tenant-parallel; orthogonal to the
# vertex-sharding axis the SPMD engines use for ONE big graph).
BATCH_AXIS = "b"

# Serving-coarse slab-class floors (engine='bucketed', ISSUE 10).  The
# per-graph drivers shrink every coarse slab to its pow2 class
# (coarsen/device.py::maybe_shrink_to_class); PR 9's batched driver kept
# the PHASE-0 class for every phase, so coarse phases swept mostly
# padding — at the serving class (4096, 16384) a 7-community coarse
# graph still paid a [16384] 2-channel sort per iteration.  The
# bucketed engine lifts the shrink to the batch: ONE notch, decided
# after phase 0 from the (nc, ne2) scalars the per-phase sync already
# carries — the whole batch drops to `_coarse_class` iff every active
# row fits, else it stays put.  Binary decision -> at most two compiled
# fused-phase programs per (class, B), and B=1 decides identically, so
# served == solo bit-identity is preserved by construction.
BATCH_COARSE_MIN_NV = 1024
BATCH_COARSE_MIN_NE = 4096


def _coarse_class(nv_pad: int, ne_pad: int) -> tuple:
    """The one-notch serving-coarse class of a phase-0 slab class:
    divide by 4 (one pow2 class per dimension is too timid — measured:
    phase-0 coarsening collapses synth/R-MAT tenants far below it),
    floored at the serving-coarse minima."""
    return (max(nv_pad // 4, BATCH_COARSE_MIN_NV),
            max(ne_pad // 4, BATCH_COARSE_MIN_NE))


def _batched_coalesce_engine(nv_pad: int, adt: str) -> str:
    """The coalesce engine of a batched phase at one slab class: the
    env-resolved per-graph policy, with 'pallas' downgraded to its
    bit-identical XLA twin — the Pallas seg-coalesce grid does not lift
    over vmap (kernels/seg_coalesce.py) — and 'hash' downgraded to
    'msd': the hash engine's collision retry is a ``lax.cond`` whose
    branches BOTH execute under vmap, so its fallback path would run
    for every row of every batch (coarsen/device.py).  One definition
    for the phase-0 class and the serving-coarse class, so the
    downgrade rule cannot drift between them."""
    from cuvite_tpu.kernels.seg_coalesce import coalesce_engine

    eng = coalesce_engine(nv_pad, "ds32" if adt == "ds32" else None)
    return {"pallas": "xla", "hash": "msd"}.get(eng, eng)


@functools.partial(jax.jit, static_argnames=("cnv", "cne"))
def _shrink_batch(src, dst, w, real_mask, *, cnv: int, cne: int):
    """Device-side batched slab-class shrink: per-row prefix slice +
    padding-sentinel rewrite (coarse ids are dense and < nc <= cnv, so
    only old sentinels move — the vmapped analog of
    coarsen/device.py::shrink_slab) plus the real-mask prefix."""
    s = src[:, :cne]
    s = jnp.where(s >= cnv, jnp.asarray(cnv, s.dtype), s)
    return s, dst[:, :cne], w[:, :cne], real_mask[:, :cnv]


@functools.partial(jax.jit,
                   static_argnames=("n_sub", "nv_sub", "cnv_sub", "cne_sub"))
def _shrink_subrow_batch(src, dst, w, real_mask, *, n_sub: int,
                         nv_sub: int, cnv_sub: int, cne_sub: int):
    """Sub-row analog of :func:`_shrink_batch`: every FENCE interval
    shrinks from ``nv_sub`` to ``cnv_sub`` vertices, so dense coarse ids
    remap ``s*nv_sub + r -> s*cnv_sub + r`` (each sub-row's ids are
    dense < its nc <= cnv_sub, so the remap is exact) and the real mask
    keeps each segment's prefix.  Edges slice to the row prefix — the
    coalesce compacts real runs there and the caller's per-sub-row ne2
    gate bounds their total by ``n_sub * cne_sub``.  ``comm_all`` is
    NOT remapped: it lives in pack-time offsets by construction."""
    nv_pad = n_sub * nv_sub
    cnv = n_sub * cnv_sub
    cne = n_sub * cne_sub

    def remap(x):
        return ((x // nv_sub) * cnv_sub
                + jnp.minimum(x % nv_sub, cnv_sub - 1)).astype(x.dtype)

    s = src[:, :cne]
    s = jnp.where(s >= nv_pad, jnp.asarray(cnv, s.dtype),
                  remap(jnp.minimum(s, nv_pad - 1)))
    d = remap(dst[:, :cne])
    B = real_mask.shape[0]
    rm = real_mask.reshape(B, n_sub, nv_sub)[:, :, :cnv_sub]
    return s, d, w[:, :cne], rm.reshape(B, cnv)


# Compiled batched-phase programs, keyed by (mesh devices, statics) —
# the "one compile per (class, B)" cache.  jax.jit already caches per
# callable+shapes; this table keeps the CALLABLE identity stable across
# batches so that cache engages (same pattern as driver._STEP_CACHE).
_PHASE_CACHE: dict = {}


def _get_batched_phase(mesh, nv_pad, accum_dtype, coalesce, max_iters,
                       engine: str = "fused", n_buckets: int = 0,
                       n_sub: int = 0):
    """The compiled batched-phase program for one ``(mesh, class
    statics, engine)`` — ``engine='bucketed'`` adds the plan pytree
    (``n_buckets`` triples + heavy/self_loop/perm) ahead of the slab
    state; ``engine='rebinned'`` keeps the fused 9-operand signature
    (its plan is built inside the program); ``engine='subrow'`` also
    keeps it, with the per-graph operands widened to ``[B, n_sub]``
    (ISSUE 20 — ``n_sub`` is the static LAYOUT class; sub-row occupancy
    stays batch content).  jax.jit still caches per shapes, so a
    bucketed program is one compile per (class, B, bucket geometry)."""
    key = (
        None if mesh is None else tuple(d.id for d in mesh.devices.flat),
        nv_pad, accum_dtype, coalesce, max_iters, engine, n_buckets,
        n_sub,
    )
    fn = _PHASE_CACHE.get(key)
    if fn is not None:
        return fn
    bucketed = engine == "bucketed"
    if engine == "subrow":
        body = functools.partial(
            _subrow_phase_body, nv_pad=nv_pad, n_sub=n_sub,
            accum_dtype=accum_dtype, coalesce=coalesce,
            max_iters=max_iters)
    else:
        body = functools.partial(
            {"bucketed": _bucketed_phase_body,
             "rebinned": _rebinned_phase_body,
             "fused": _phase_body}[engine],
            nv_pad=nv_pad, accum_dtype=accum_dtype,
            coalesce=coalesce, max_iters=max_iters)
    if mesh is None:
        fn = jax.jit(body)
    else:
        from jax.sharding import PartitionSpec as P

        from cuvite_tpu.comm.mesh import shard_map

        b = P(BATCH_AXIS)
        # Row-independent SPMD: every batched operand/output splits on
        # the batch axis, the threshold scalar replicates, and the body
        # contains NO collectives — each shard's while_loop paces only
        # its own rows (check_vma off: nothing is replicated to check).
        if bucketed:
            bspec = tuple((b, b, b) for _ in range(n_buckets))
            in_specs = (bspec, (b, b, b)) + (b,) * 10 + (P(),)
        else:
            in_specs = (b,) * 8 + (P(),)
        fn = jax.jit(shard_map(
            body, mesh=mesh,
            in_specs=in_specs,
            out_specs=(b,) * 14,
            check_vma=False,
        ))
    _PHASE_CACHE[key] = fn
    return fn


def make_batch_mesh(b_pad: int, devices=None):
    """A 1-D batch-axis mesh over the largest pow2 device count that
    DIVIDES ``b_pad`` (shard_map needs the batch axis divisible by the
    mesh; ladder-rung b_pads are pow2 so every pow2 <= them divides,
    but an explicit caller b_pad may not be).  Returns None when one
    device (or one row) makes sharding pointless — the caller then
    runs the plain jitted program.
    """
    import numpy as _np

    devs = list(jax.devices()) if devices is None else list(devices)
    if b_pad <= 1 or len(devs) <= 1:
        return None
    from jax.sharding import Mesh

    cap = 1 << (len(devs).bit_length() - 1)     # largest pow2 <= ndev
    nd = min(b_pad & -b_pad, cap)               # largest pow2 | b_pad
    if nd <= 1:
        return None
    return Mesh(_np.array(devs[:nd]), (BATCH_AXIS,))


@dataclasses.dataclass
class BatchResult:
    """Per-tenant results plus the batch-level serving telemetry."""

    results: list          # list[LouvainResult], one per REAL job, in order
    wall_s: float          # whole-batch wall time (upload -> final gather)
    n_phases: int          # batch phase count (max over rows)
    b_pad: int
    n_jobs: int
    slab_class: tuple      # (nv_pad, ne_pad)
    # Engine telemetry (ISSUE 10/19): the engine each batch phase
    # actually ran — ['bucketed', 'rebinned', ...] under
    # engine='bucketed' (phase 0 sort-free over pack-time plans, coarse
    # phases over device-rebuilt plans; 'fused' where the re-binner
    # cannot certify the class or CUVITE_DEVICE_REBIN=0), all-'fused'
    # otherwise.
    phase_engines: list = dataclasses.field(default_factory=list)
    # The serving-coarse class phases >= 1 ran at (engine='bucketed'
    # whose post-phase-0 batch fit `_coarse_class`), else None.
    coarse_class: tuple | None = None
    # Pipeline-stage split of wall_s (ISSUE 14): host pack + upload vs
    # compiled-program execution — the two stages the pipelined
    # dispatcher overlaps (steady-state batch period = max, not sum).
    pack_s: float = 0.0
    device_s: float = 0.0
    # Sub-rows per batch row (ISSUE 20): 1 for plain batches, the
    # layout's n_sub for a packed batch (phase_engines then reads
    # ['subrow', ...]).
    n_sub: int = 1

    @property
    def pack_util(self) -> float:
        """Row occupancy — saturates at 1.0 the moment every row holds
        one tenant; see ``subrow_util`` for merged-batch honesty."""
        return min(self.n_jobs, self.b_pad) / max(self.b_pad, 1)

    @property
    def subrow_util(self) -> float:
        """Real graphs over total SUB-row capacity (== pack_util for
        plain batches, where n_sub == 1)."""
        return self.n_jobs / max(self.b_pad * self.n_sub, 1)

    @property
    def jobs_per_s(self) -> float:
        return self.n_jobs / max(self.wall_s, 1e-9)


def accum_class_of(graph, nv_pad: int | None = None) -> str:
    """The in-loop accumulator tag this graph runs solo THROUGH THE
    BATCHED DRIVER (``louvain_many([g])``; 'float32', or 'ds32' past
    the DS_MIN_TOTAL_WEIGHT scale gate) — the second half of the
    serving bin key.  Rows of one batch must share it: the accumulator
    is a per-PROGRAM static, so a batch mixing a ds32-scale tenant with
    f32 ones would run every row ds32 and silently break the
    served-equals-solo bit-identity contract for the small rows.

    The addend count floors at ``nv_pad`` (the padded reduction length
    the batched program actually sums over) where the per-graph fused
    driver floors at the REAL vertex count — deliberately one notch
    more conservative: a graph whose padding alone crosses the gate
    runs ds32 here, consistently at every B, while its
    ``louvain_phases`` run may stay f32."""
    from cuvite_tpu.core.batch import slab_class_of
    from cuvite_tpu.louvain.driver import _accum_name

    if nv_pad is None:
        nv_pad = slab_class_of(graph)[0]
    return _accum_name(np.float32, graph.total_edge_weight_twice(),
                       max(graph.num_edges, nv_pad))


def _batch_accum_name(batch: BatchedSlab) -> str:
    """Static accumulator tag for the whole batch — rows must agree
    (see :func:`accum_class_of`; the serving queue bins by it, so a
    mixed batch here is a caller bug, not a degradable state)."""
    from cuvite_tpu.louvain.driver import _accum_name

    names = {
        _accum_name(np.float32, float(batch.tw2[i]),
                    max(int(batch.ne_real[i]), batch.nv_pad))
        for i in range(batch.b_pad) if batch.row_valid[i]
    }
    if len(names) > 1:
        raise ValueError(
            f"mixed accumulator classes {sorted(names)} in one batch: "
            "a per-program static accumulator would silently change "
            "the f32 rows' results vs their solo runs — bin jobs by "
            "(slab_class_of, accum_class_of) before packing "
            "(serve/queue.py does)")
    return names.pop() if names else "float32"


@dataclasses.dataclass
class PreparedBatch:
    """A packed batch with its device buffers ALREADY uploaded — the
    handoff unit of the pipelined dispatcher (ISSUE 14): the packer
    stage builds one of these (host pack + plan build + upload) while
    the executor stage runs the previous batch's compiled program
    (:func:`execute_prepared`).  The initial device refs are never
    mutated by execution, so a transient device fault can re-run
    ``execute_prepared`` on the same PreparedBatch and get bit-identical
    results without re-packing."""

    # Host metadata (what the phase loop needs from the BatchedSlab).
    b_pad: int
    nv_pad: int
    ne_pad: int
    n_jobs: int
    slab_class: tuple
    nv_real: np.ndarray
    ne_real: np.ndarray
    row_valid: np.ndarray
    # Statics of the compiled program set.
    adt: str
    coalesce: str
    mesh: object
    engine: str
    n_buckets: int
    # Device refs (phase-0 state; plans None for engine='fused').
    src_d: object = None
    dst_d: object = None
    w_d: object = None
    rm_d: object = None
    const_d: object = None
    comm_all_d: object = None
    prev_d: object = None
    plan_d: object = None
    # Host pack + upload wall seconds (the packer-stage cost).
    pack_s: float = 0.0
    # Sub-row layout (engine='subrow', ISSUE 20): n_sub > 1 widens the
    # per-graph metadata — nv_real/ne_real/sub_valid and the prev/const
    # device refs are [B, n_sub]; row_valid stays the [B] row-level OR.
    n_sub: int = 1
    sub_valid: np.ndarray | None = None


def prepare_batch(batch: BatchedSlab, *, mesh="auto", engine: str = "fused",
                  bucket_shape=None, tracer=None) -> PreparedBatch:
    """The PACK half of :func:`run_batched`: validate the batch's
    statics, build the bucket plans (engine='bucketed'), resolve the
    batch mesh, and upload every device buffer (``plan``/``upload``
    stages, HBM-ledger tracked).  Contains no compiled-program
    execution — in the pipelined dispatcher this runs on the packer
    thread while the executor thread runs the previous batch."""
    from cuvite_tpu.core.batch import batch_bucket_plans

    if engine not in BATCH_ENGINES:
        raise ValueError(f"unknown batched engine {engine!r}; "
                         f"use one of {BATCH_ENGINES}")
    if tracer is None:
        from cuvite_tpu.utils.trace import NullTracer

        tracer = NullTracer()

    t0 = time.perf_counter()
    B = batch.b_pad
    nv_pad = batch.nv_pad
    wdt = np.dtype(np.float32)
    adt = _batch_accum_name(batch)
    eng = _batched_coalesce_engine(nv_pad, adt)
    if mesh == "auto":
        mesh = make_batch_mesh(B)
    bplan = None
    n_buckets = 0
    if engine == "bucketed":
        # Plans are built AT PACK TIME, before any device work — the
        # plan-per-job trap (building them inside a dispatch loop) is
        # what graftlint R015 guards against in serve/.
        with tracer.stage("plan"):
            bplan = batch_bucket_plans(batch, shape=bucket_shape)
        n_buckets = len(bplan.buckets)

    def _place(x):
        if mesh is None:
            return to_device(x)
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.device_put(x, NamedSharding(mesh, P(BATCH_AXIS)))

    with tracer.stage("upload"):
        src_d = _place(batch.src)
        dst_d = _place(batch.dst)
        w_d = _place(batch.w)
        rm_d = _place(batch.real_mask)
        const_d = _place(batch.constant)
        comm_all_d = _place(np.broadcast_to(
            np.arange(nv_pad, dtype=np.int32)[None, :],
            (B, nv_pad)).copy())
        prev_d = _place(np.full((B,), -1.0, dtype=wdt))
        plan_d = None
        if bplan is not None:
            # verts cast to the device vertex dtype; weights stay f32
            # (the plan builder's stable-compile-key contract — see
            # core/batch.py); every array shards on the batch axis like
            # the slab.  The execute loop drops ITS plan reference after
            # phase 0; the PreparedBatch keeps this one so a transient
            # device fault can re-run execution without re-uploading.
            plan_d = (
                tuple((_place(v.astype(np.int32)), _place(d), _place(ww))
                      for v, d, ww in bplan.buckets),
                tuple(_place(a) for a in bplan.heavy),
                _place(bplan.self_loop),
                _place(bplan.perm),
            )
            bplan = None  # the host-side plan copy is dead weight too

    return PreparedBatch(
        b_pad=B, nv_pad=nv_pad, ne_pad=batch.ne_pad, n_jobs=batch.n_jobs,
        slab_class=batch.slab_class, nv_real=batch.nv_real.copy(),
        ne_real=batch.ne_real.copy(),
        row_valid=np.asarray(batch.row_valid).copy(),
        adt=adt, coalesce=eng, mesh=mesh, engine=engine,
        n_buckets=n_buckets,
        src_d=src_d, dst_d=dst_d, w_d=w_d, rm_d=rm_d, const_d=const_d,
        comm_all_d=comm_all_d, prev_d=prev_d, plan_d=plan_d,
        pack_s=time.perf_counter() - t0,
    )


def prepare_packed(packed: PackedSubRows, *, mesh="auto",
                   tracer=None) -> PreparedBatch:
    """The PACK half of a sub-row merged batch (ISSUE 20): accumulator
    gate + mesh resolve + device upload, the packed analog of
    :func:`prepare_batch` (``engine='subrow'``, no plans).  The gate
    re-evaluates every tenant's accumulator class AT THE ROW CLASS —
    ``accum_class_of(g, nv_pad=row_nv_pad)`` — because the packed
    program's reductions run over the row's padded length: a tenant f32
    at its own class can cross the ds32 scale gate at the row class, and
    a per-program accumulator flip would change its batch-mates' bits.
    The serving merge packer applies the same gate before merging; this
    raise is the backstop for direct callers."""
    from cuvite_tpu.louvain.driver import _accum_name

    if tracer is None:
        from cuvite_tpu.utils.trace import NullTracer

        tracer = NullTracer()

    t0 = time.perf_counter()
    B = packed.b_pad
    nv_pad = packed.nv_pad
    n_sub = packed.layout.n_sub
    wdt = np.dtype(np.float32)
    bad = sorted({
        _accum_name(np.float32, float(packed.tw2[i, s]),
                    max(int(packed.ne_real[i, s]), nv_pad))
        for i in range(B) for s in range(n_sub) if packed.sub_valid[i, s]
    } - {"float32"})
    if bad:
        raise ValueError(
            f"prepare_packed: accumulator classes {bad} at the row "
            f"class nv_pad={nv_pad} — packed rows are f32-only; gate "
            "tenants with accum_class_of(g, nv_pad=row_nv_pad) before "
            "merging (serve/queue.py does)")
    adt = "float32"
    eng = _batched_coalesce_engine(nv_pad, adt)
    if mesh == "auto":
        mesh = make_batch_mesh(B)

    def _place(x):
        if mesh is None:
            return to_device(x)
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.device_put(x, NamedSharding(mesh, P(BATCH_AXIS)))

    with tracer.stage("upload"):
        src_d = _place(packed.src)
        dst_d = _place(packed.dst)
        w_d = _place(packed.w)
        rm_d = _place(packed.real_mask)
        const_d = _place(packed.constants)
        comm_all_d = _place(np.broadcast_to(
            np.arange(nv_pad, dtype=np.int32)[None, :],
            (B, nv_pad)).copy())
        prev_d = _place(np.full((B, n_sub), -1.0, dtype=wdt))

    return PreparedBatch(
        b_pad=B, nv_pad=nv_pad, ne_pad=packed.ne_pad,
        n_jobs=packed.n_jobs, slab_class=packed.slab_class,
        nv_real=packed.nv_real.copy(), ne_real=packed.ne_real.copy(),
        row_valid=np.asarray(packed.row_valid).copy(),
        adt=adt, coalesce=eng, mesh=mesh, engine="subrow", n_buckets=0,
        src_d=src_d, dst_d=dst_d, w_d=w_d, rm_d=rm_d, const_d=const_d,
        comm_all_d=comm_all_d, prev_d=prev_d,
        pack_s=time.perf_counter() - t0,
        n_sub=n_sub, sub_valid=packed.sub_valid.copy(),
    )


def execute_prepared(prep: PreparedBatch, *, threshold: float = 1.0e-6,
                     max_phases: int = TERMINATION_PHASE_COUNT,
                     tracer=None, verbose: bool = False) -> BatchResult:
    """The EXECUTE half of :func:`run_batched`: run the compiled
    per-phase programs over an uploaded batch (one host sync per phase,
    one final label gather).  Re-runnable: the PreparedBatch's device
    refs are read-only here, so a retry restarts from phase 0 with
    bit-identical results."""
    from cuvite_tpu.louvain.driver import (
        LouvainResult,
        PhaseStats,
        _phase_sync,
    )

    if prep.engine == "subrow":
        return _execute_subrow(prep, threshold=threshold,
                               max_phases=max_phases, tracer=tracer,
                               verbose=verbose)

    if tracer is None:
        from cuvite_tpu.utils.trace import NullTracer

        tracer = NullTracer()

    t0 = time.perf_counter()
    B = prep.b_pad
    nv_pad = prep.nv_pad
    cur_nv, cur_ne = nv_pad, prep.ne_pad  # slab class of the NEXT phase
    coarse_class = None
    wdt = np.dtype(np.float32)
    adt = prep.adt
    eng = prep.coalesce
    mesh = prep.mesh
    def _coarse_fn(nv, ne, engc):
        # Coarse-phase program of the current slab class: under
        # engine='bucketed', device re-binning (ISSUE 19) keeps coarse
        # phases on the sort-free bucketed formulation whenever the
        # re-binner can certify the class (no heavy residual possible,
        # element budget) and CUVITE_DEVICE_REBIN is on; otherwise the
        # pre-ISSUE-19 fused downgrade.
        from cuvite_tpu.coarsen.rebin import (
            device_rebin_enabled,
            rebin_eligible,
        )

        if (prep.engine == "bucketed" and device_rebin_enabled()
                and rebin_eligible(nv, ne)):
            return _get_batched_phase(
                mesh, nv, adt, engc, MAX_TOTAL_ITERATIONS,
                engine="rebinned"), "rebinned"
        return _get_batched_phase(mesh, nv, adt, engc,
                                  MAX_TOTAL_ITERATIONS), "fused"

    phase_fn, coarse_engine = _coarse_fn(nv_pad, prep.ne_pad, eng)
    phase0_fn = None
    if prep.engine == "bucketed":
        phase0_fn = _get_batched_phase(
            mesh, nv_pad, adt, eng, MAX_TOTAL_ITERATIONS,
            engine="bucketed", n_buckets=prep.n_buckets)
    src_d, dst_d, w_d = prep.src_d, prep.dst_d, prep.w_d
    rm_d, const_d = prep.rm_d, prep.const_d
    comm_all_d, prev_d, plan_d = prep.comm_all_d, prep.prev_d, prep.plan_d

    active = prep.row_valid.copy()

    # Host-side per-row bookkeeping.
    nv_cur = prep.nv_real.copy()
    ne_cur = prep.ne_real.copy()
    tot_iters = np.zeros(B, dtype=np.int64)
    row_phases: list = [[] for _ in range(B)]
    row_conv: list = [[] for _ in range(B)]
    phase_engines: list = []
    phase = 0

    while active.any() and phase < max_phases:
        t1 = time.perf_counter()
        active_at_start = active.copy()
        # Phase 0 under engine='bucketed' runs the sort-free vmapped
        # bucketed sweep over the pack-time plans; coarse phases re-bin
        # their plans on device when eligible ('rebinned', ISSUE 19),
        # else run the fused loop (also every phase of engine='fused').
        # The engine per phase is recorded for telemetry/bench
        # provenance.
        bucketed_phase = phase == 0 and phase0_fn is not None
        phase_engines.append("bucketed" if bucketed_phase
                             else coarse_engine)
        # HBM ledger: re-track the live set per phase, so the phase-0
        # plan buffers leave the accounting once dropped and the slab
        # bytes follow the serving-coarse shrink (the snapshot below
        # must report what is actually resident, not the upload-time
        # high-water).
        tracer.ledger_phase_begin()
        tracer.track("slab", src_d, dst_d, w_d)
        tracer.track("tables", rm_d, const_d)
        if plan_d is not None:
            tracer.track("plans", *jax.tree_util.tree_leaves(plan_d))
        with tracer.stage("iterate"):
            if bucketed_phase:
                (src_d, dst_d, w_d, comm_all_d, rm_d, prev_d,
                 gained_d, mod_d, iters_d, nc_d, ne2_d,
                 cq_d, cmoved_d, covf_d) = phase0_fn(
                    *plan_d,
                    src_d, dst_d, w_d, comm_all_d, rm_d, prev_d,
                    active_at_start, const_d,
                    np.asarray(threshold, dtype=wdt),
                )
            else:
                (src_d, dst_d, w_d, comm_all_d, rm_d, prev_d,
                 gained_d, mod_d, iters_d, nc_d, ne2_d,
                 cq_d, cmoved_d, covf_d) = phase_fn(
                    src_d, dst_d, w_d, comm_all_d, rm_d, prev_d,
                    active_at_start, const_d,
                    np.asarray(threshold, dtype=wdt),
                )
            # THE one device->host sync of this phase: every per-row
            # scalar + the telemetry buffers in a single transfer.
            gained, (mod_h, iters_h, nc_h, ne2_h, cq_h, cmoved_h,
                     covf_h) = _phase_sync(
                gained_d, mod_d, iters_d, nc_d, ne2_d,
                cq_d, cmoved_d, covf_d)
        gained = np.asarray(gained, dtype=bool)
        phase_wall = time.perf_counter() - t1
        n_active = max(int(active_at_start.sum()), 1)
        share = phase_wall / n_active

        traversed = 0
        for i in np.flatnonzero(active_at_start):
            it = int(iters_h[i])
            tot_iters[i] += it
            traversed += int(ne_cur[i]) * it
            pc = decode_phase_conv(phase, it, cq_h[i], cmoved_h[i],
                                   covf_h[i], gained=bool(gained[i]))
            row_conv[i].append(pc)
            if gained[i]:
                row_phases[i].append(PhaseStats(
                    phase=len(row_phases[i]),
                    modularity=float(mod_h[i]), iterations=it,
                    num_vertices=int(nv_cur[i]),
                    num_edges=int(ne_cur[i]), seconds=share))
                nv_cur[i] = int(nc_h[i])
                ne_cur[i] = int(ne2_h[i])
        tracer.count("traversed_edges", traversed)
        active = active_at_start & gained \
            & (tot_iters <= MAX_TOTAL_ITERATIONS)
        if verbose:
            print(f"batched phase {phase}: active {int(active.sum())}/"
                  f"{prep.n_jobs}, iters {iters_h[:prep.n_jobs]}")
        tracer.ledger_snapshot(phase)
        if bucketed_phase:
            # The phase-0 plans are dead weight from here on (coarse
            # phases re-bin on device or run fused); drop the device
            # refs so HBM frees.
            plan_d = None
            # One-notch coarse-class shrink (see _coarse_class): iff
            # every row still clustering fits, the batch drops to the
            # serving-coarse class — the decision reads only the (nc,
            # ne2) scalars this phase's sync already fetched, and the
            # fused phases then sweep/coalesce 4-16x less padding.
            cnv, cne = _coarse_class(cur_nv, cur_ne)
            if active.any() and (cnv, cne) != (cur_nv, cur_ne) \
                    and int(nc_h[active].max()) <= cnv \
                    and int(ne2_h[active].max()) <= cne:
                src_d, dst_d, w_d, rm_d = _shrink_batch(
                    src_d, dst_d, w_d, rm_d, cnv=cnv, cne=cne)
                cur_nv, cur_ne = cnv, cne
                coarse_class = (cnv, cne)
                phase_fn, coarse_engine = _coarse_fn(
                    cnv, cne, _batched_coalesce_engine(cnv, adt))
        phase += 1

    # THE final label gather: one O(B * nv_pad) transfer for the whole
    # batch; comm_all rows are already dense (composed through the
    # per-phase device renumber).
    comm_all_h, prev_h = jax.device_get((comm_all_d, prev_d))  # graftlint: disable=R010 — the allowlisted final label gather (batched)
    device_s = time.perf_counter() - t0

    results = []
    for i in range(prep.n_jobs):
        nv = int(prep.nv_real[i])
        results.append(LouvainResult(
            communities=np.asarray(comm_all_h[i, :nv], dtype=np.int64),
            modularity=float(prev_h[i]),
            phases=row_phases[i],
            total_iterations=int(tot_iters[i]),
            total_seconds=sum(p.seconds for p in row_phases[i]),
            convergence=row_conv[i],
        ))
    return BatchResult(
        results=results, wall_s=prep.pack_s + device_s, n_phases=phase,
        b_pad=B, n_jobs=prep.n_jobs, slab_class=prep.slab_class,
        phase_engines=phase_engines, coarse_class=coarse_class,
        pack_s=prep.pack_s, device_s=device_s,
    )


def _execute_subrow(prep: PreparedBatch, *, threshold: float,
                    max_phases: int, tracer=None,
                    verbose: bool = False) -> BatchResult:
    """The EXECUTE half of a packed batch (ISSUE 20): the
    :func:`execute_prepared` phase loop with every per-graph scalar
    widened to ``[B, n_sub]`` — per-SUB-row masked exit, the one-notch
    coarse shrink decided on the MAX over sub-rows still active, and the
    final gather unpacked per fence (labels slice at the sub-row's
    pack-time offset, minus the offset).  One host sync per phase, one
    compiled program per (row class, B, n_sub), re-runnable like the
    plain path."""
    from cuvite_tpu.louvain.driver import (
        LouvainResult,
        PhaseStats,
        _phase_sync,
    )

    if tracer is None:
        from cuvite_tpu.utils.trace import NullTracer

        tracer = NullTracer()

    t0 = time.perf_counter()
    B = prep.b_pad
    n_sub = prep.n_sub
    nv_pad0 = prep.nv_pad
    nv_sub0 = nv_pad0 // n_sub
    cur_nv, cur_ne = nv_pad0, prep.ne_pad
    coarse_class = None
    wdt = np.dtype(np.float32)
    adt = prep.adt
    mesh = prep.mesh

    phase_fn = _get_batched_phase(
        mesh, nv_pad0, adt, prep.coalesce, MAX_TOTAL_ITERATIONS,
        engine="subrow", n_sub=n_sub)
    src_d, dst_d, w_d = prep.src_d, prep.dst_d, prep.w_d
    rm_d, const_d = prep.rm_d, prep.const_d
    comm_all_d, prev_d = prep.comm_all_d, prep.prev_d

    active = prep.sub_valid.copy()                  # [B, n_sub]

    nv_cur = prep.nv_real.astype(np.int64).copy()   # [B, n_sub]
    ne_cur = prep.ne_real.astype(np.int64).copy()
    tot_iters = np.zeros((B, n_sub), dtype=np.int64)
    sub_phases: list = [[[] for _ in range(n_sub)] for _ in range(B)]
    sub_conv: list = [[[] for _ in range(n_sub)] for _ in range(B)]
    phase_engines: list = []
    phase = 0

    while active.any() and phase < max_phases:
        t1 = time.perf_counter()
        active_at_start = active.copy()
        phase_engines.append("subrow")
        tracer.ledger_phase_begin()
        tracer.track("slab", src_d, dst_d, w_d)
        tracer.track("tables", rm_d, const_d)
        with tracer.stage("iterate"):
            (src_d, dst_d, w_d, comm_all_d, rm_d, prev_d,
             gained_d, mod_d, iters_d, nc_d, ne2_d,
             cq_d, cmoved_d, covf_d) = phase_fn(
                src_d, dst_d, w_d, comm_all_d, rm_d, prev_d,
                active_at_start, const_d,
                np.asarray(threshold, dtype=wdt),
            )
            gained, (mod_h, iters_h, nc_h, ne2_h, cq_h, cmoved_h,
                     covf_h) = _phase_sync(
                gained_d, mod_d, iters_d, nc_d, ne2_d,
                cq_d, cmoved_d, covf_d)
        gained = np.asarray(gained, dtype=bool)     # [B, n_sub]
        phase_wall = time.perf_counter() - t1
        n_active = max(int(active_at_start.sum()), 1)
        share = phase_wall / n_active

        traversed = 0
        for i, s in zip(*np.nonzero(active_at_start)):
            it = int(iters_h[i, s])
            tot_iters[i, s] += it
            traversed += int(ne_cur[i, s]) * it
            pc = decode_phase_conv(phase, it, cq_h[i, s], cmoved_h[i, s],
                                   covf_h[i], gained=bool(gained[i, s]))
            sub_conv[i][s].append(pc)
            if gained[i, s]:
                sub_phases[i][s].append(PhaseStats(
                    phase=len(sub_phases[i][s]),
                    modularity=float(mod_h[i, s]), iterations=it,
                    num_vertices=int(nv_cur[i, s]),
                    num_edges=int(ne_cur[i, s]), seconds=share))
                nv_cur[i, s] = int(nc_h[i, s])
                ne_cur[i, s] = int(ne2_h[i, s])
        tracer.count("traversed_edges", traversed)
        active = active_at_start & gained \
            & (tot_iters <= MAX_TOTAL_ITERATIONS)
        if verbose:
            print(f"packed phase {phase}: active "
                  f"{int(active.sum())}/{prep.n_jobs} sub-rows, "
                  f"iters max {int(iters_h.max())}")
        tracer.ledger_snapshot(phase)
        if phase == 0:
            # One-notch coarse shrink, decided on the MAX over sub-rows
            # still active (ISSUE 20): every fence interval drops to the
            # SUB class's serving-coarse class iff every active sub-row
            # fits — same scalars, same one-binary-decision shape as the
            # plain batched shrink, so a packed batch compiles at most
            # two (class, B, n_sub) programs.
            nv_s, ne_s = cur_nv // n_sub, cur_ne // n_sub
            cnv_s, cne_s = _coarse_class(nv_s, ne_s)
            if active.any() and (cnv_s, cne_s) != (nv_s, ne_s) \
                    and int(nc_h[active].max()) <= cnv_s \
                    and int(ne2_h[active].max()) <= cne_s:
                src_d, dst_d, w_d, rm_d = _shrink_subrow_batch(
                    src_d, dst_d, w_d, rm_d, n_sub=n_sub, nv_sub=nv_s,
                    cnv_sub=cnv_s, cne_sub=cne_s)
                cur_nv, cur_ne = n_sub * cnv_s, n_sub * cne_s
                coarse_class = (cur_nv, cur_ne)
                phase_fn = _get_batched_phase(
                    mesh, cur_nv, adt,
                    _batched_coalesce_engine(cur_nv, adt),
                    MAX_TOTAL_ITERATIONS, engine="subrow", n_sub=n_sub)
        phase += 1

    comm_all_h, prev_h = jax.device_get((comm_all_d, prev_d))  # graftlint: disable=R010 — the allowlisted final label gather (packed batch)
    device_s = time.perf_counter() - t0

    results = []
    for j in range(prep.n_jobs):
        i, s = divmod(j, n_sub)
        nv = int(prep.nv_real[i, s])
        voff = s * nv_sub0
        results.append(LouvainResult(
            communities=np.asarray(
                comm_all_h[i, voff:voff + nv], dtype=np.int64) - voff,
            modularity=float(prev_h[i, s]),
            phases=sub_phases[i][s],
            total_iterations=int(tot_iters[i, s]),
            total_seconds=sum(p.seconds for p in sub_phases[i][s]),
            convergence=sub_conv[i][s],
        ))
    return BatchResult(
        results=results, wall_s=prep.pack_s + device_s, n_phases=phase,
        b_pad=B, n_jobs=prep.n_jobs, slab_class=prep.slab_class,
        phase_engines=phase_engines, coarse_class=coarse_class,
        pack_s=prep.pack_s, device_s=device_s, n_sub=n_sub,
    )


def run_batched(batch: BatchedSlab, *, threshold: float = 1.0e-6,
                max_phases: int = TERMINATION_PHASE_COUNT,
                mesh="auto", tracer=None, verbose: bool = False,
                engine: str = "fused", bucket_shape=None) -> BatchResult:
    """Cluster every row of a packed batch; one compile per
    (class, B, engine), one host sync per phase, one final label gather.
    Composition of the two pipeline halves —
    ``execute_prepared(prepare_batch(batch))`` — so the serial path and
    the pipelined dispatcher run the exact same code (ISSUE 14).

    Per-row semantics match the fused single-shard driver's plain
    schedule at a fixed ``threshold``: phases run until a row's gain
    drops below it (that row masks out), every row's reported Q is its
    last gaining phase's in-loop value.  ``PhaseStats.seconds`` is the
    batch phase wall split evenly over the rows active in that phase —
    per-tenant wall is an AMORTIZED share, which is the serving-truth
    number (the batch really did cost one wall interval).

    ``engine``: ``'fused'`` — every phase through the vmapped fused
    loop; ``'bucketed'`` — phase 0 (the bulk of the per-row edge mass)
    through the vmapped sort-free bucketed step over cross-graph-padded
    plans built at pack time (``batch_bucket_plans``); later phases
    keep the fused loop.  ``bucket_shape`` pins the plan geometry
    (``core.batch.BucketShape``) so many batches share one compiled
    phase-0 program; None derives it from this batch.

    ``mesh``: ``'auto'`` shards the batch axis over the largest usable
    pow2 device count (:func:`make_batch_mesh`); ``None`` pins the
    single-device program; or pass an explicit 1-D ``Mesh`` over
    ``BATCH_AXIS``.  Sharding never changes per-row results — the
    program has no cross-row op — only which device runs which rows.
    """
    prep = prepare_batch(batch, mesh=mesh, engine=engine,
                         bucket_shape=bucket_shape, tracer=tracer)
    return execute_prepared(prep, threshold=threshold,
                            max_phases=max_phases, tracer=tracer,
                            verbose=verbose)


@dataclasses.dataclass
class PreparedMany:
    """A :func:`cluster_many` job set after the PACK stage: the
    edgeless jobs' inline answers plus the uploaded PreparedBatch for
    the rest (None when every job was edgeless).  ``execute_many``
    turns it into the full in-order BatchResult."""

    graphs_nv: list          # num_vertices per input, in order
    edgeless: set            # input indices answered inline
    prep: PreparedBatch | None

    @property
    def pack_s(self) -> float:
        return self.prep.pack_s if self.prep is not None else 0.0


def pack_many(graphs, *, b_pad: int | None = None,
              slab_class: tuple | None = None, mesh="auto",
              engine: str = "fused", bucket_shape=None,
              tracer=None) -> PreparedMany:
    """The PACK stage of :func:`cluster_many`: edgeless split + slab
    stacking + plan build + device upload.  Jax work is upload-only —
    no compiled program runs here, which is what lets the pipelined
    dispatcher overlap this with the previous batch's execution."""
    if tracer is None:
        from cuvite_tpu.utils.trace import NullTracer

        tracer = NullTracer()
    edgeless = {i for i, g in enumerate(graphs) if g.num_edges == 0}
    packed = [g for i, g in enumerate(graphs) if i not in edgeless]
    prep = None
    if packed:
        with tracer.stage("plan"):
            batch = batch_slabs(packed, b_pad=b_pad,
                                slab_class=slab_class)
        prep = prepare_batch(batch, mesh=mesh, engine=engine,
                             bucket_shape=bucket_shape, tracer=tracer)
    return PreparedMany(graphs_nv=[g.num_vertices for g in graphs],
                        edgeless=edgeless, prep=prep)


def pack_subrow_many(graphs, layout, *, b_pad: int | None = None,
                     mesh="auto", tracer=None) -> PreparedMany:
    """The PACK stage of a MERGED batch (ISSUE 20): edgeless split +
    sub-row packing (core/batch.py::pack_subrows) + device upload.
    Returns the same :class:`PreparedMany` handoff unit as
    :func:`pack_many` — ``execute_many`` dispatches on the prepared
    engine, so the pipelined dispatcher runs merged and plain batches
    through identical stages."""
    if tracer is None:
        from cuvite_tpu.utils.trace import NullTracer

        tracer = NullTracer()
    from cuvite_tpu.core.batch import pack_subrows

    edgeless = {i for i, g in enumerate(graphs) if g.num_edges == 0}
    packed_graphs = [g for i, g in enumerate(graphs) if i not in edgeless]
    prep = None
    if packed_graphs:
        with tracer.stage("plan"):
            packed = pack_subrows(packed_graphs, layout, b_pad=b_pad)
        prep = prepare_packed(packed, mesh=mesh, tracer=tracer)
    return PreparedMany(graphs_nv=[g.num_vertices for g in graphs],
                        edgeless=edgeless, prep=prep)


def cluster_packed(graphs, layout, *, threshold: float = 1.0e-6,
                   max_phases: int = TERMINATION_PHASE_COUNT,
                   b_pad: int | None = None, mesh="auto", tracer=None,
                   verbose: bool = False) -> BatchResult:
    """Sub-row-pack small-class graphs and run them as ONE merged batch
    of ``layout.row_class`` rows — the packed analog of
    :func:`cluster_many` (in-order results, edgeless answered inline).
    Per-tenant labels and Q are bit-identical to each graph's B=1 run:
    the fences make every per-run float content-local
    (louvain/subrow.py's module note carries the argument)."""
    pm = pack_subrow_many(graphs, layout, b_pad=b_pad, mesh=mesh,
                          tracer=tracer)
    return execute_many(pm, threshold=threshold, max_phases=max_phases,
                        tracer=tracer, verbose=verbose)


def execute_many(pm: PreparedMany, *, threshold: float = 1.0e-6,
                 max_phases: int = TERMINATION_PHASE_COUNT,
                 tracer=None, verbose: bool = False) -> BatchResult:
    """The EXECUTE stage of :func:`cluster_many`: run the prepared
    batch and reassemble the in-order results list (edgeless jobs
    answered inline, costing no batch rows)."""
    from cuvite_tpu.louvain.driver import LouvainResult

    if pm.prep is not None:
        br = execute_prepared(pm.prep, threshold=threshold,
                              max_phases=max_phases, tracer=tracer,
                              verbose=verbose)
    else:
        br = BatchResult(results=[], wall_s=0.0, n_phases=0, b_pad=0,
                         n_jobs=0, slab_class=(0, 0))
    out = []
    packed_iter = iter(br.results)
    for i, nv in enumerate(pm.graphs_nv):
        if i in pm.edgeless:
            out.append(LouvainResult(
                communities=np.arange(nv, dtype=np.int64),
                modularity=0.0, phases=[], total_iterations=0,
                total_seconds=0.0))
        else:
            out.append(next(packed_iter))
    br.results = out
    return br


def cluster_many(graphs, *, threshold: float = 1.0e-6,
                 max_phases: int = TERMINATION_PHASE_COUNT,
                 b_pad: int | None = None, slab_class: tuple | None = None,
                 mesh="auto", tracer=None, verbose: bool = False,
                 engine: str = "fused", bucket_shape=None) -> BatchResult:
    """Pack same-class graphs and run them as one batch (edgeless graphs
    are answered inline — every vertex its own community, Q = 0 — and
    never enter the packed batch, mirroring louvain_phases).  The
    returned ``results`` list covers EVERY input in order;
    ``n_jobs``/``pack_util``/``jobs_per_s`` describe only the PACKED
    batch (inline-answered edgeless jobs cost no batch rows).
    Composition of :func:`pack_many` + :func:`execute_many` — the two
    stages the pipelined dispatcher runs on separate threads.
    ``engine``/``bucket_shape``: see :func:`run_batched`."""
    if tracer is None:
        from cuvite_tpu.utils.trace import NullTracer

        tracer = NullTracer()
    pm = pack_many(graphs, b_pad=b_pad, slab_class=slab_class, mesh=mesh,
                   engine=engine, bucket_shape=bucket_shape, tracer=tracer)
    return execute_many(pm, threshold=threshold, max_phases=max_phases,
                        tracer=tracer, verbose=verbose)
