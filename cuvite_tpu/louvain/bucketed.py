"""Degree-bucketed Louvain step: the TPU analog of the reference GPU's
degree-class specialization.

The reference partitions vertices into three degree classes and runs a
different CUDA kernel per class (count_size_clmap,
/root/reference/louvain_cuda.cu:1426-1592; distGetMaxIndex variants
:878-1346; computeMaxIndex variants :230-876).  The equivalent TPU-first
move: bucket vertices by degree into FIXED-WIDTH padded rows
[n_bucket, D] whose edge gather indices are computed once per phase
(static shapes, one compile), and do the neighbor-community dedup +
gain + argmax as dense row-local ops that XLA fuses — no per-iteration
global sort, no hash maps.

Per row of width D the dedup is the O(D^2) all-pairs compare
(eq[j,k] = C[j]==C[k]); cheap for D <= ~64 and perfectly vectorized.
Vertices with degree > the largest bucket width go down the sort-based
path (cuvite_tpu/louvain/step.py machinery) restricted to THEIR edges
only — the analog of the reference's "huge" class using a different
algorithm entirely (dense scratch bincount, louvain_cuda.cu:878-1022).

Orchestration (what is static per phase vs dynamic per iteration):

  static per phase:  bucket membership, per-row dst/weight matrices,
                     per-vertex self-loop weight, heavy-edge subset
  per iteration:     one gather of comm[dst] per bucket, row-local
                     dedup/gain/argmax, community size/degree refresh
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from cuvite_tpu.comm.mesh import shard_map
from cuvite_tpu.ops import segment as seg

# Width ladder: ~1.5-2x steps bound the padded-slot inflation (a row of
# degree d occupies the next width up, so coarse factor-4 steps cost up to
# 4x the HBM traffic of the real edges — measured 1.75x faster step at
# scale-18 with this ladder vs (8,32,128,512,2048,8192)).  Every width
# >= 128 is a multiple of the TPU lane count so wide rows tile cleanly;
# the <=128 classes are lane-padded either way and stay cheap.
DEFAULT_BUCKETS = (8, 16, 32, 64, 128, 256, 384, 512, 768, 1024, 1536,
                   2048, 3072, 4096, 6144, 8192)


def _env_int(name: str, default: int) -> int:
    import os

    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return int(raw)
    except ValueError:
        # A malformed knob (typo'd digit, stray unicode) must not silently
        # measure the baseline while the operator believes it changed.
        import warnings

        warnings.warn(
            f"{name}={raw!r} is not an integer; using default {default}",
            stacklevel=2)
        return default


# Dedup-kernel cutover (env-tunable for on-chip A/B): rows of width <=
# QUADRATIC_MAX_WIDTH dedup by the all-pairs compare (VPU/MXU-friendly
# O(D^2) with zero sorts/scans/gathers); wider rows take the packed
# per-row sort.  The crossover is hardware-dependent — the TPU vector
# units tolerate much larger D^2 than a scalar CPU does — so it is a
# load-time knob rather than a constant.
QUADRATIC_MAX_WIDTH = _env_int("CUVITE_QUAD_MAX", 32)
# Widest degree class routed through the Pallas row-argmax kernel by
# engine='pallas' (the XLA paths handle anything wider).  The kernel
# switches from an unrolled candidate loop to lax.fori_loop above
# kernels.row_argmax.UNROLL_MAX_WIDTH and shrinks its row tile to honor
# VMEM; 2048 keeps the [D, tile] blocks comfortably resident.  Knob for
# on-chip A/B ladders.
PALLAS_MAX_WIDTH = _env_int("CUVITE_PALLAS_MAX", 2048)
ROW_CHUNK = _env_int("CUVITE_ROW_CHUNK", 8192)  # rows/lax.map step (quad)
# rows*width per lax.map step for the sorted dedup classes:
ROW_ELEMS_CHUNK = _env_int("CUVITE_ROW_ELEMS", 1 << 22)
# rows*width^2 bound for quad classes wider than the default 32 (the eq
# matrix is the transient that matters there):
ROW_QUAD_ELEMS_CHUNK = _env_int("CUVITE_QUAD_ELEMS", 1 << 26)


def chunk_for_width(width: int) -> int:
    """Rows per lax.map step — shared by the plan builder (row padding) and
    the step (chunk dispatch); a mismatch would silently disable chunking.
    Rounded DOWN to a power of two: row counts are pow2-padded, and pow2
    rows divide evenly only by pow2 chunks (a non-pow2 chunk — e.g. from
    the 384/768/... widths — would make every large bucket fall back to
    the unchunked path and blow the transient-memory bound)."""
    def pow2_floor(c: int) -> int:
        c = max(c, 1)
        return 1 << (c.bit_length() - 1)

    if width <= QUADRATIC_MAX_WIDTH:
        # Quad classes: the [chunk, D, D] eq matrix is the transient that
        # matters — bound rows*D^2, capped by the fixed row-count knob.
        # (For the default widths <= 32 the row-count cap always wins, so
        # this reproduces the historical ROW_CHUNK=8192 chunks exactly.)
        return min(pow2_floor(ROW_CHUNK),
                   pow2_floor(ROW_QUAD_ELEMS_CHUNK // (width * width)))
    return pow2_floor(ROW_ELEMS_CHUNK // width)


@dataclasses.dataclass
class Bucket:
    width: int
    verts: np.ndarray    # [Nb] local vertex indices
    dst: np.ndarray      # [Nb, D] GLOBAL (padded-space) tail ids; pad -> self
    w: np.ndarray        # [Nb, D] weights; pad -> 0


@dataclasses.dataclass
class BucketPlan:
    """Phase-static layout for one shard's edge slab."""

    nv_local: int
    buckets: list            # list[Bucket]
    heavy_src: np.ndarray    # [NEh_pad] local src idx of heavy edges (pad nv)
    heavy_dst: np.ndarray    # [NEh_pad] global tail ids (pad 0)
    heavy_w: np.ndarray      # [NEh_pad] weights (pad 0)
    self_loop: np.ndarray    # [nv_local] per-vertex self-loop weight
    has_heavy: bool

    @staticmethod
    def build(
        src: np.ndarray,
        dst: np.ndarray,
        w: np.ndarray,
        nv_local: int,
        base: int,
        widths: tuple = DEFAULT_BUCKETS,
    ) -> "BucketPlan":
        """`src` holds local indices (pad = nv_local); `dst` global padded
        ids; `base` is this shard's first global id (for self-loop
        detection)."""
        plan = _build_native(src, dst, w, nv_local, base, widths)
        if plan is not None:
            return plan
        real = src < nv_local
        s = src[real].astype(np.int64)
        d = dst[real].astype(np.int64)
        ww = w[real].astype(np.float64)
        deg = np.bincount(s, minlength=nv_local)
        # Slabs cut from a CSR arrive row-ordered (DistGraph.build expands
        # offsets in vertex order), so the O(ne log ne) stable sort is
        # usually a no-op — skip it after an O(ne) check.  Color-class
        # plans mask rows to nv_local and DO need the sort.
        if len(s) and np.any(s[:-1] > s[1:]):
            order = np.argsort(s, kind="stable")
            s, d, ww = s[order], d[order], ww[order]
        row_start = np.concatenate([[0], np.cumsum(deg)[:-1]]).astype(np.int64)

        self_loop = np.zeros(nv_local, dtype=np.float64)
        is_self = d == (s + base)
        np.add.at(self_loop, s[is_self], ww[is_self])

        # Unit-weight graphs (R-MAT, unweighted inputs): every real edge
        # weighs exactly 1, so the per-bucket weight matrix IS the has-edge
        # mask — skip the [nb, width] f64 weight gather entirely and emit
        # uint8 (the dtype the device upload wants anyway, see
        # compress_unit_weights).  Deliberately NARROWER than
        # is_unit_weights: that predicate admits {0, 1} mixtures (safe for
        # dtype compression of an already-built matrix), but the mask
        # substitution here requires every real edge to weigh exactly 1 —
        # a real 0-weight edge would be promoted to 1 by the mask.
        unit = len(ww) == 0 or bool(np.all(ww == 1.0))

        buckets = []
        prev = 0
        for width in widths:
            sel = np.nonzero((deg > prev) & (deg <= width))[0]
            prev = width
            if len(sel) == 0:
                continue
            nb = len(sel)
            # Pad the row count to the next power of two: stable shapes let
            # successive coarsened phases reuse the compiled step (pow2 >
            # chunk is automatically a multiple of the pow2 chunk, so
            # lax.map chunking stays exact).  Padding rows use local index
            # nv_local (dropped by out-of-bounds scatter).
            nb_pad = 1 << int(nb - 1).bit_length() if nb > 1 else 1
            verts = np.full(nb_pad, nv_local, dtype=np.int64)
            verts[:nb] = sel
            dmat = np.zeros((nb_pad, width), dtype=dst.dtype)
            # One vectorized gather per bucket; column padding uses the
            # vertex's own global id with weight 0 (a zero-weight self-edge
            # never becomes a candidate and adds 0 to counter0).
            cols = np.arange(width)
            idx = row_start[sel][:, None] + cols[None, :]
            has = cols[None, :] < deg[sel][:, None]
            idx = np.minimum(idx, max(len(d) - 1, 0))
            dmat[:nb] = np.where(has, d[idx], (sel + base)[:, None])
            if unit:
                wmat = np.zeros((nb_pad, width), dtype=np.uint8)
                wmat[:nb] = has
            else:
                wmat = np.zeros((nb_pad, width), dtype=w.dtype)
                wmat[:nb] = np.where(has, ww[idx], 0.0)
            buckets.append(Bucket(width=width, verts=verts, dst=dmat, w=wmat))

        heavy_v = np.nonzero(deg > widths[-1])[0]
        if len(heavy_v):
            # Boolean-table lookup instead of np.isin: O(ne) vs isin's
            # sort-based O(ne log ne) (~0.1 s/phase at scale 18).
            is_heavy = np.zeros(nv_local + 1, dtype=bool)
            is_heavy[heavy_v] = True
            hmask = is_heavy[s]
            hs, hd, hw = s[hmask], d[hmask], ww[hmask]
            n = len(hs)
            npad = max(int(2 ** np.ceil(np.log2(max(n, 1)))), 8)
            heavy_src = np.full(npad, nv_local, dtype=src.dtype)
            heavy_dst = np.zeros(npad, dtype=dst.dtype)
            heavy_w = np.zeros(npad, dtype=w.dtype)
            heavy_src[:n] = hs
            heavy_dst[:n] = hd
            heavy_w[:n] = hw
            has_heavy = True
        else:
            heavy_src = np.full(8, nv_local, dtype=src.dtype)
            heavy_dst = np.zeros(8, dtype=dst.dtype)
            heavy_w = np.zeros(8, dtype=w.dtype)
            has_heavy = False
        return BucketPlan(
            nv_local=nv_local,
            buckets=buckets,
            heavy_src=heavy_src,
            heavy_dst=heavy_dst,
            heavy_w=heavy_w,
            self_loop=self_loop.astype(w.dtype),
            has_heavy=has_heavy,
        )


def _build_native(src, dst, w, nv_local, base, widths):
    """Native-streamed BucketPlan (cv_plan_scan + cv_bucket_fill): two O(E)
    C++ passes with no transient larger than O(nv), vs the numpy path's
    multi-gigabyte int64 copies and per-class gather matrices at benchmark
    scales (VERDICT r2 item 3).  Returns None — caller falls back to numpy
    — when the library is unavailable, the slab is small, dtypes are mixed,
    or the slab is not CSR-sorted with tail padding (e.g. the color-class
    masked plans).  Output is bit-identical to the numpy path (pinned by
    tests/test_native.py)."""
    from cuvite_tpu import native as cvn

    if (not cvn.available() or len(src) < cvn.MIN_NATIVE_EDGES
            or src.dtype != dst.dtype
            or src.dtype not in (np.int32, np.int64)
            or w.dtype not in (np.float32, np.float64)
            or not (src.flags.c_contiguous and dst.flags.c_contiguous
                    and w.flags.c_contiguous)):
        return None
    self_loop64, sorted_, unit, tail_ok = cvn.plan_scan(
        src, dst, w, nv_local, base)
    if not (sorted_ and tail_ok):
        return None
    deg = np.bincount(src, minlength=nv_local + 1)[:nv_local]
    widths_arr = np.asarray(widths, dtype=np.int64)
    nw = len(widths_arr)
    cls_idx = np.searchsorted(widths_arr, deg, side="left")
    heavy_mask = deg > widths_arr[-1]
    in_bucket = (deg > 0) & ~heavy_mask
    full_counts = np.bincount(cls_idx[in_bucket], minlength=nw)
    kept = np.nonzero(full_counts)[0]
    remap = np.full(nw + 1, 255, dtype=np.uint8)
    remap[kept] = np.arange(len(kept), dtype=np.uint8)
    cls = np.full(nv_local, 255, dtype=np.uint8)
    cls[in_bucket] = remap[cls_idx[in_bucket]]
    cls[heavy_mask] = 254
    row_start = np.zeros(nv_local, dtype=np.int64)
    np.cumsum(deg[:-1], out=row_start[1:])

    nb = full_counts[kept]
    nb_pad = np.array(
        [1 << int(n - 1).bit_length() if n > 1 else 1 for n in nb],
        dtype=np.int64)
    widths_kept = widths_arr[kept]
    wm_dtype = np.uint8 if unit else w.dtype
    # O(E) plan arrays are allocated 64-byte aligned so the cpu-backend
    # upload aliases them instead of duplicating (utils/upload.py).
    from cuvite_tpu.utils.upload import aligned_full, aligned_zeros

    verts_list, dmat_list, wmat_list = [], [], []
    for np_, width in zip(nb_pad, widths_kept):
        verts_list.append(aligned_full(np_, nv_local, np.int64))
        dmat_list.append(aligned_zeros((np_, width), dst.dtype))
        wmat_list.append(aligned_zeros((np_, width), wm_dtype))
    n_h = int(deg[heavy_mask].sum())
    if n_h:
        heavy_pad = max(int(2 ** np.ceil(np.log2(max(n_h, 1)))), 8)
    else:
        heavy_pad = 8
    heavy_src = aligned_full(heavy_pad, nv_local, src.dtype)
    heavy_dst = aligned_zeros(heavy_pad, dst.dtype)
    heavy_w = aligned_zeros(heavy_pad, w.dtype)
    cvn.bucket_fill(dst, w, nv_local, base, row_start,
                    deg.astype(np.int64), cls, widths_kept, nb_pad,
                    verts_list, dmat_list, wmat_list, unit, heavy_pad,
                    heavy_src, heavy_dst, heavy_w)
    buckets = [
        Bucket(width=int(width), verts=v, dst=d, w=ww)
        for width, v, d, ww in zip(widths_kept, verts_list, dmat_list,
                                   wmat_list)
    ]
    return BucketPlan(
        nv_local=nv_local,
        buckets=buckets,
        heavy_src=heavy_src,
        heavy_dst=heavy_dst,
        heavy_w=heavy_w,
        self_loop=self_loop64.astype(w.dtype),
        has_heavy=n_h > 0,
    )


@dataclasses.dataclass
class StackedPlan:
    """Per-shard BucketPlans padded to COMMON shapes and stacked shard-major,
    ready to be sharded along axis 0 of a 1-D mesh (every shard must present
    identical bucket geometry to the SPMD step — the analog of the
    reference's per-rank symmetric kernel launches)."""

    buckets: list            # list of (verts [S*Nb], dst [S*Nb, D], w [S*Nb, D])
    heavy: tuple             # (src [S*H], dst [S*H], w [S*H])
    self_loop: np.ndarray    # [S*nv_pad]
    perm: np.ndarray         # [S*nv_pad] per-shard assembly permutation
    unit_weights: np.ndarray  # [n_buckets] bool: w is {0,1} on EVERY host
    # Kernel routing (engine='pallas' on a mesh): per kept bucket, True if
    # its width class is laid out for the Pallas row kernel (row count
    # padded to >= LANE so the per-shard [D, Nb] block tiles cleanly).
    pallas_flags: tuple = ()
    # Per-width real (directed) edge counts, [len(widths) + 1] with the
    # trailing slot the heavy residual — allreduced across hosts under
    # per-host ingest.  Only populated when ``pallas_widths`` was given
    # (coverage accounting costs one O(E) bincount per shard).
    width_edges: np.ndarray | None = None


def build_stacked_plans(dg, widths: tuple = DEFAULT_BUCKETS,
                        exchange_plan=None, class_of=None,
                        class_id: int = -1,
                        pallas_widths: tuple = (),
                        count_width_edges: bool = False) -> StackedPlan:
    """Build one BucketPlan per shard of ``dg`` and pad them to common
    shapes.  A width class appears iff some shard has vertices in it; shards
    without rows in a kept class contribute all-padding rows.

    With ``exchange_plan`` (a comm.exchange.ExchangePlan) dst ids are
    remapped into each shard's extended-local space [0, nv_pad + ghost_pad)
    — the layout the sparse-exchange step gathers from — and self-loop
    detection switches to the local formulation (base=0: remapped self edge
    has dst == src local index).

    Per-host-ingest partitions (``dg.local_only``, io/dist_ingest.py) build
    plans for THIS process's shard rows only; the padded shapes (which must
    be identical on every process for one SPMD program) are agreed by a
    host max-allreduce, and the returned arrays' leading dim covers local
    shards only — place them with comm.multihost.place_block.

    ``class_of`` (padded GLOBAL id space, [S*nv_pad]) with ``class_id``
    restricts each shard's plan to the vertices of one color class (other
    rows masked to padding) — the SPMD analog of the single-shard
    class-restricted plans (the reference sweeps only the class's vertices
    on every rank, /root/reference/louvain.cpp:862-901).

    ``pallas_widths`` (engine='pallas' on a mesh): width classes to lay
    out for the Pallas row kernel — their COMMON row counts are padded up
    to >= 128 (the kernel's lane tile; counts are pow2 already, so this
    only lifts the sub-128 classes) and flagged in ``pallas_flags``; the
    runner transposes those classes to [S*D, Nb] at placement.  Also
    triggers the per-width edge accounting (``width_edges``) behind the
    engine's kernel-coverage report; ``count_width_edges`` forces that
    accounting even when no width qualifies (a CUVITE_PALLAS_MAX tuned
    below the smallest bucket width must still report ITS coverage: 0)."""
    nshards = dg.nshards
    nvl = dg.nv_pad
    local_only = getattr(dg, "local_only", False)
    lo, hi = (dg.local_lo, dg.local_hi) if local_only else (0, nshards)
    sids = range(lo, hi)

    def _mask_src(s):
        src = np.asarray(dg.shards[s].src)
        if class_of is None:
            return src
        cls_local = np.asarray(class_of)[s * nvl:(s + 1) * nvl]
        in_cls = cls_local[np.minimum(src, nvl - 1)] == class_id
        return np.where((src < nvl) & in_cls, src, nvl).astype(src.dtype)

    if exchange_plan is not None:
        # Class-restricted sparse plans (reference's distributed -c/-d,
        # /root/reference/louvain.cpp:862-901): the ghost ROUTING is
        # class-independent — every class plan shares the phase's
        # send_idx/ghost_sel and extended-local dst space — only the row
        # masking differs.  remap_dst sees the MASKED src, so masked-out
        # edges map to dst 0 and are dropped as padding.
        # Grouped (two-level) plans remap dst into GROUP-local space, so
        # shard s's self edge lands at (s % ici)*nvl + src, not src: the
        # base is the shard's offset within its dcn group (0 for flat
        # plans, where ici == 1 and the two formulations coincide).
        grp_ici = getattr(exchange_plan, "ici", 1) or 1

        def _sparse_plan(s):
            ms = _mask_src(s)   # one O(E) masking pass, shared
            return BucketPlan.build(
                ms,
                exchange_plan.remap_dst(
                    s, ms, np.asarray(dg.shards[s].dst)
                ).astype(np.asarray(dg.shards[s].dst).dtype),
                np.asarray(dg.shards[s].w),
                nv_local=nvl, base=(s % grp_ici) * nvl, widths=widths,
            )

        plans = [_sparse_plan(s) for s in sids]
    else:
        plans = [
            BucketPlan.build(
                _mask_src(s), np.asarray(dg.shards[s].dst),
                np.asarray(dg.shards[s].w),
                nv_local=nvl, base=s * nvl, widths=widths,
            )
            for s in sids
        ]
    n_rows = len(plans)
    by_width = [{b.width: b for b in p.buckets} for p in plans]
    shape_req = np.array(
        [max((len(bw[w].verts) for bw in by_width if w in bw), default=0)
         for w in widths]
        + [max(len(p.heavy_src) for p in plans)], dtype=np.int64)
    if local_only:
        from cuvite_tpu.comm.multihost import allreduce_max_host

        shape_req = allreduce_max_host(shape_req)
    width_edges = None
    if pallas_widths or count_width_edges:
        # Kernel-coverage accounting: real directed edges per width class
        # (+ heavy residual).  One O(E) bincount per local shard, summed
        # across hosts — deterministic, so every process reports the same
        # coverage.
        widths_arr = np.asarray(widths, dtype=np.int64)
        width_edges = np.zeros(len(widths) + 1, dtype=np.int64)
        for s in sids:
            ms = _mask_src(s)
            deg = np.bincount(ms[ms < nvl], minlength=nvl)
            heavy_m = deg > widths_arr[-1]
            in_b = (deg > 0) & ~heavy_m
            cls = np.searchsorted(widths_arr, deg[in_b], side="left")
            width_edges[: len(widths)] += np.bincount(
                cls, weights=deg[in_b], minlength=len(widths)
            ).astype(np.int64)
            width_edges[-1] += int(deg[heavy_m].sum())
        if local_only:
            from cuvite_tpu.comm.multihost import allreduce_sum_host

            width_edges = np.asarray(allreduce_sum_host(width_edges))
    stacked_buckets = []
    pallas_flags = []
    for wi, width in enumerate(widths):
        nb = int(shape_req[wi])
        if nb == 0:
            continue
        if width in pallas_widths:
            # The kernel's row dimension must be a multiple of its 128-lane
            # tile; counts are pow2 (see BucketPlan.build), so only the
            # sub-128 classes grow.  max keeps every process's agreed
            # shape_req deterministic.
            nb = max(nb, 128)
        pallas_flags.append(width in pallas_widths)
        verts = np.full((n_rows, nb), nvl, dtype=np.int64)
        dmat = np.zeros((n_rows, nb, width), dtype=plans[0].heavy_dst.dtype)
        wmat = np.zeros((n_rows, nb, width), dtype=plans[0].heavy_w.dtype)
        for r, bw in enumerate(by_width):
            if width in bw:
                b = bw[width]
                verts[r, : len(b.verts)] = b.verts
                dmat[r, : len(b.verts)] = b.dst
                wmat[r, : len(b.verts)] = b.w
        stacked_buckets.append(
            (verts.reshape(-1), dmat.reshape(-1, width),
             wmat.reshape(-1, width))
        )
    hn = int(shape_req[-1])
    hsrc = np.full((n_rows, hn), nvl, dtype=plans[0].heavy_src.dtype)
    hdst = np.zeros((n_rows, hn), dtype=plans[0].heavy_dst.dtype)
    hw = np.zeros((n_rows, hn), dtype=plans[0].heavy_w.dtype)
    for r, p in enumerate(plans):
        hsrc[r, : len(p.heavy_src)] = p.heavy_src
        hdst[r, : len(p.heavy_dst)] = p.heavy_dst
        hw[r, : len(p.heavy_w)] = p.heavy_w
    self_loop = np.concatenate([p.self_loop for p in plans])
    # Per-shard assembly permutation over the COMMON padded layout (every
    # shard's concat space has identical extent, so one [nv_pad] perm per
    # shard row, stacked like the other plan arrays).
    perm = np.stack([
        build_assemble_perm([sb[0].reshape(n_rows, -1)[r]
                             for sb in stacked_buckets], nvl)
        for r in range(n_rows)
    ]) if n_rows else np.zeros((0, nvl), dtype=np.int32)
    # Per-bucket unit-weight flags (uint8 upload eligibility) must agree on
    # every process under per-host ingest — a weighted shard on one host
    # and an all-padding block on another would otherwise build the same
    # global array with different dtypes.  Min-allreduce the local verdicts
    # (min == negated max).
    unit = np.array([is_unit_weights(sb[2]) for sb in stacked_buckets],
                    dtype=np.int64)
    if local_only:
        from cuvite_tpu.comm.multihost import allreduce_max_host

        unit = -allreduce_max_host(-unit)
    return StackedPlan(
        buckets=stacked_buckets,
        heavy=(hsrc.reshape(-1), hdst.reshape(-1), hw.reshape(-1)),
        self_loop=self_loop,
        perm=perm.reshape(-1),
        unit_weights=unit.astype(bool),
        pallas_flags=tuple(pallas_flags),
        width_edges=width_edges,
    )


def is_unit_weights(w: np.ndarray) -> bool:
    """True when every entry is exactly 0 or 1 — the uint8 DTYPE-compression
    eligibility rule for already-built weight matrices (single-shard and
    stacked upload paths).  Distinct from BucketPlan.build's stricter
    mask-substitution predicate (all real weights exactly 1), which must
    reject {0, 1} mixtures."""
    return bool(w.size) and bool(np.all((w == 0) | (w == 1)))


def compress_unit_weights(w: np.ndarray, wdt) -> np.ndarray:
    """Return ``w`` as uint8 when :func:`is_unit_weights`, else as ``wdt``.

    uint8 bucket weights cost 4x less host->device upload and 4x less HBM
    read per iteration; the step casts back to the weight dtype on use
    (fused by XLA), and 0/1 cast exactly, so results are bit-identical."""
    if is_unit_weights(w):
        return w.astype(np.uint8)
    return w.astype(wdt)


def build_assemble_perm(verts_list, nv_local: int) -> np.ndarray:
    """Vertex -> position in the concatenated bucket-row space.

    ``verts_list``: the PADDED per-bucket vertex arrays exactly as uploaded
    (padding entries hold >= nv_local and are skipped).  Vertices in no
    bucket (heavy / degree-0) map to the trailing default slot.  Bucket
    membership is disjoint, so the map is a pure (partial) permutation —
    this is what lets the step assemble results with gathers instead of
    scatters."""
    total = sum(len(v) for v in verts_list)
    perm = np.full(nv_local, total, dtype=np.int32)
    off = 0
    for v in verts_list:
        v = np.asarray(v)
        real = np.nonzero(v < nv_local)[0]
        perm[v[real]] = (off + real).astype(np.int32)
        off += len(v)
    return perm


class RowResult(NamedTuple):
    best_c: jax.Array    # [Nb] best candidate community (sentinel if none)
    best_gain: jax.Array  # [Nb]
    counter0: jax.Array  # [Nb] weight to current community (incl self-loops)
    best_size: jax.Array | None  # [Nb] size of best community (sparse mode)


def _row_argmax(cmat, wmat, aymat, smat, curr_comm, vdeg_v, sl_v, ax_v,
                constant, sentinel):
    """Dedup + dQ + argmax for one chunk of bucket rows.

    cmat [T, D] neighbor communities; wmat [T, D] weights; aymat [T, D] the
    candidate community's degree a_y per slot; smat [T, D] (or None) the
    candidate community's size per slot; sl_v [T] the vertex's self-loop
    weight (e_ix = counter0 - sl is row-local: every edge of a bucket
    vertex lives in its row); ax_v [T] = a_x = deg(curr) - k_i.
    Replicates distGetMaxIndex (/root/reference/louvain.cpp:2185-2244):
    gain = 2*(e_iy - e_ix) - 2*k_i*(a_y - a_x)/2m, ties to smaller id.
    """
    wdt = wmat.dtype
    # all-pairs equality within the row: eq[t, j, k] = C[j] == C[k]
    eq = cmat[:, :, None] == cmat[:, None, :]
    # aggregated weight per slot: sum over duplicates
    wagg = jnp.einsum("tjk,tk->tj", eq.astype(wdt), wmat)
    # leader slot = first occurrence of its community
    tri = jnp.tril(jnp.ones((cmat.shape[1], cmat.shape[1]), dtype=bool), k=-1)
    dup = jnp.any(eq & tri[None, :, :], axis=2)
    is_cc = cmat == curr_comm[:, None]
    counter0 = jnp.sum(jnp.where(is_cc, wmat, 0.0), axis=1)
    eix_v = counter0 - sl_v
    # No w>0 filter: zero-weight edges are candidates exactly as in the sort
    # engine.  Padding slots are safe without it — they point at the row's
    # own vertex, whose community always equals curr_comm, so is_cc masks
    # them out of the candidate set.
    valid = (~dup) & (~is_cc)

    gain = 2.0 * (wagg - eix_v[:, None]) \
        - 2.0 * vdeg_v[:, None] * (aymat - ax_v[:, None]) * constant
    neg_inf = jnp.array(-jnp.inf, dtype=wdt)
    gain = jnp.where(valid, gain, neg_inf)
    best_gain = jnp.max(gain, axis=1)
    at_best = valid & (gain == best_gain[:, None])
    best_c = jnp.min(
        jnp.where(at_best, cmat, jnp.full_like(cmat, sentinel)), axis=1
    )
    best_size = None
    if smat is not None:
        # size of the winning community: any slot with that community id
        # carries the same attached size.
        chosen = cmat == best_c[:, None]
        best_size = jnp.min(
            jnp.where(chosen, smat, jnp.full_like(smat, sentinel)), axis=1
        )
    return RowResult(best_c=best_c, best_gain=best_gain, counter0=counter0,
                     best_size=best_size)


def _row_argmax_sorted(cmat, wmat, aymat, smat, curr_comm, vdeg_v, sl_v,
                       ax_v, constant, sentinel, id_bound=None):
    """Dedup + dQ + argmax for wide rows via a per-row sort.

    O(D log^2 D) per row instead of the all-pairs O(D^2): sort each row by
    community id, detect runs, and compute run sums with a reverse cumsum +
    next-leader index (reverse cummin) — all lane-parallel scans.  This is
    the TPU counterpart of the reference's medium/large GPU kernels
    (/root/reference/louvain_cuda.cu:1024-1346).

    When every community id provably fits in ``31 - bits(D)`` bits
    (``id_bound``, static), the sort runs on ONE packed int32 key
    ``(c << bits) | slot`` and the payloads follow by take_along_axis —
    measured 4-5x faster than the multi-operand comparator sort, with
    bit-identical results (packed keys are unique, so the stable order by
    (c, slot) equals the stable order by c).
    """
    wdt = wmat.dtype
    D = cmat.shape[1]
    # counter0 in UNSORTED slot order (the historical outer-pass order, so
    # modularity and e_ix stay bit-identical to the two-pass formulation).
    counter0 = jnp.sum(
        jnp.where(cmat == curr_comm[:, None], wmat, 0.0), axis=1
    ).astype(wdt)
    eix_v = counter0 - sl_v
    bits = (D - 1).bit_length()
    packable = (
        id_bound is not None
        and cmat.dtype == jnp.int32
        and (int(id_bound) << bits) <= (1 << 31)
    )
    if packable:
        iota = jax.lax.broadcasted_iota(jnp.int32, cmat.shape, 1)
        k_s = jax.lax.sort((cmat << bits) | iota, dimension=1)
        slot = k_s & ((1 << bits) - 1)
        c_s = k_s >> bits
        w_s = jnp.take_along_axis(wmat, slot, axis=1)
        ay_s = jnp.take_along_axis(aymat, slot, axis=1)
        s_s = (jnp.take_along_axis(smat, slot, axis=1)
               if smat is not None else None)
    elif smat is not None:
        c_s, w_s, ay_s, s_s = jax.lax.sort(
            (cmat, wmat, aymat, smat), dimension=1, num_keys=1)
    else:
        c_s, w_s, ay_s = jax.lax.sort(
            (cmat, wmat, aymat), dimension=1, num_keys=1)
    leader = jnp.concatenate(
        [jnp.ones_like(c_s[:, :1], dtype=bool), c_s[:, 1:] != c_s[:, :-1]],
        axis=1,
    )
    pos = jax.lax.broadcasted_iota(jnp.int32, c_s.shape, 1)
    leaderpos = jnp.where(leader, pos, D)
    # next leader strictly to the right of j (D if none)
    nxt = jnp.flip(jax.lax.cummin(jnp.flip(leaderpos, 1), axis=1), 1)
    nxt = jnp.concatenate(
        [nxt[:, 1:], jnp.full_like(nxt[:, :1], D)], axis=1
    )
    # suffix sums S[j] = sum_{k >= j} w; S_ext has trailing 0 column
    suf = jnp.flip(jnp.cumsum(jnp.flip(w_s, 1), axis=1), 1)
    suf_ext = jnp.concatenate([suf, jnp.zeros_like(suf[:, :1])], axis=1)
    run_sum = suf - jnp.take_along_axis(suf_ext, nxt, axis=1)

    is_cc = c_s == curr_comm[:, None]
    # No w>0 filter — see _row_argmax; padding self-slots are is_cc-masked.
    valid = leader & (~is_cc)

    gain = 2.0 * (run_sum - eix_v[:, None]) \
        - 2.0 * vdeg_v[:, None] * (ay_s - ax_v[:, None]) * constant
    neg_inf = jnp.array(-jnp.inf, dtype=wdt)
    gain = jnp.where(valid, gain, neg_inf)
    best_gain = jnp.max(gain, axis=1)
    at_best = valid & (gain == best_gain[:, None])
    best_c = jnp.min(
        jnp.where(at_best, c_s, jnp.full_like(c_s, sentinel)), axis=1
    )
    best_size = None
    if smat is not None:
        chosen = c_s == best_c[:, None]
        best_size = jnp.min(
            jnp.where(chosen, s_s, jnp.full_like(s_s, sentinel)), axis=1
        )
    return RowResult(best_c=best_c, best_gain=best_gain, counter0=counter0,
                     best_size=best_size)


def _map_chunks(fn, nb, chunk, row_arrays):
    """Shared chunk dispatch: run ``fn`` over [chunk]-row slices of
    ``row_arrays`` via lax.map, or in one piece when ``nb`` doesn't divide
    (row counts are pow2-padded and ``chunk_for_width`` returns pow2, so
    the divisibility check only fails for sub-chunk buckets).  Returns the
    lax.map-stacked pytree — callers reshape leading dims back to [nb].
    One definition so the dispatch rule cannot drift between the argmax
    pass and the modularity c0 pass."""
    if nb <= chunk or nb % chunk != 0:
        return fn(*row_arrays)
    nchunk = nb // chunk
    return jax.lax.map(
        lambda args: fn(*args),
        tuple(a.reshape((nchunk, chunk) + a.shape[1:]) for a in row_arrays),
    )


def _rows_chunked(w_mat, dst_mat, curr, vdeg_v, sl_v, ax_v,
                  constant, sentinel, gather_cm, gather_ay, gather_sz,
                  wdt, id_bound=None):
    """Dispatch rows to the right dedup variant, chunked with lax.map to
    bound intermediate memory.  Every O(rows x D) operand that is not a
    phase-static plan constant is produced INSIDE the chunk body:
    ``gather_cm`` maps a dst chunk to its community matrix, ``gather_ay``/
    ``gather_sz`` produce the per-slot community degree / size matrices,
    and uint8-compressed unit weights widen to ``wdt`` per chunk.  XLA
    cannot fuse producers into a lax.map (scan) body, so a full-bucket
    cmat gather or weight cast at the caller would materialize the whole
    O(E) matrix — at benchmark scale, tens of GB of step-resident
    buffers (the scale-26 attempt-1 OOM, tools/scale26_attempts.md).
    ``gather_sz`` may return None in replicated mode."""
    nb, width = dst_mat.shape
    kernel = (_row_argmax if width <= QUADRATIC_MAX_WIDTH
              else functools.partial(_row_argmax_sorted, id_bound=id_bound))

    def run(wm, dm, cu, vd, sl, ax):
        if wm.dtype != wdt:  # uint8-compressed unit weights
            wm = wm.astype(wdt)
        cm = gather_cm(dm)
        return kernel(cm, wm, gather_ay(dm, cm), gather_sz(dm, cm),
                      cu, vd, sl, ax, constant, sentinel)

    res = _map_chunks(run, nb, chunk_for_width(width),
                      (w_mat, dst_mat, curr, vdeg_v, sl_v, ax_v))
    return RowResult(
        best_c=res.best_c.reshape(nb),
        best_gain=res.best_gain.reshape(nb),
        counter0=res.counter0.reshape(nb),
        best_size=(None if res.best_size is None
                   else res.best_size.reshape(nb)),
    )


def bucketed_modularity(bucket_arrays, heavy_arrays, self_loop, comm, vdeg,
                        constant, *, nv_total, accum_dtype=None,
                        axis_name=None, sparse_plan=None, nshards=1,
                        budget=0, ici_axis=None):
    """Modularity of ``comm`` alone (no argmax): one cheap masked-sum pass
    over the bucket rows + heavy slab.  Used by the color-scheduled
    iteration, whose per-class steps see partial states — this gives the
    iteration's Q at its START state for the convergence check at ~the cost
    of the counter0 pass.  With ``axis_name`` it runs SPMD inside shard_map
    (replicated exchange: all_gather'ed community vector, psum'd terms).

    With ``sparse_plan`` the pass rides the sparse ghost exchange instead
    (dst ids extended-local, owner-sharded a² term) and RETURNS
    ``(modularity, overflow)`` — the budgeted owner-reduce behind the a²
    term can overflow exactly like the step's.  ``ici_axis`` upgrades the
    sparse exchange to the two-level scheme: ``axis_name`` is then the
    slow DCN axis, the plan a grouped one, and the per-edge terms reduce
    over BOTH axes while the a² term stays on the DCN axis only (the
    group tables are ICI-replicated)."""
    nv_local = comm.shape[0]
    wdt = vdeg.dtype
    use_sparse = sparse_plan is not None
    red_axes = (axis_name if ici_axis is None else (axis_name, ici_axis))
    if use_sparse:
        from cuvite_tpu.comm.exchange import (
            sparse_env, sparse_modularity, twolevel_env)

        assert axis_name is not None, "sparse exchange requires a mesh axis"
        if ici_axis is not None:
            env = twolevel_env(comm, vdeg, sparse_plan[0], sparse_plan[1],
                               axis_name, ici_axis, n_dcn=nshards,
                               budget=budget)
        else:
            env = sparse_env(comm, vdeg, sparse_plan[0], sparse_plan[1],
                             axis_name, nshards=nshards, budget=budget)
        comm_full = env.comm_ext
    else:
        comm_full, gsum = seg.spmd_env(comm, axis_name)
        comm_deg = gsum(seg.segment_sum(vdeg, comm, num_segments=nv_total))  # graftlint: replicated-ok=scope=ici; replicated-exchange mod pass, flat-mesh-only (hybrid meshes take the sparse/two-level branch above)
    counter0 = jnp.zeros((nv_local,), dtype=wdt)
    hs, hd, hw = heavy_arrays
    ckey_h = jnp.take(comm_full, hd)
    csrc_h = jnp.take(comm, jnp.minimum(hs, nv_local - 1))
    counter0 = counter0 + seg.segment_sum(
        jnp.where(ckey_h == csrc_h, hw, jnp.zeros_like(hw)), hs,
        num_segments=nv_local,
    )
    for verts, dst_mat, w_mat in bucket_arrays:
        safe_v = jnp.minimum(verts, nv_local - 1)
        curr = jnp.take(comm, safe_v)

        def c0_of(wm, dm, cu):
            # Gather + uint8 widening INSIDE the chunk (same reasoning as
            # _rows_chunked: producers can't fuse into a lax.map body, so
            # doing this at full bucket size materializes O(E) buffers).
            if wm.dtype != wdt:
                wm = wm.astype(wdt)
            cm = jnp.take(comm_full, dm)
            return jnp.sum(
                jnp.where(cm == cu[:, None], wm, 0.0), axis=1
            ).astype(wdt)

        nb, width = dst_mat.shape
        c0_rows = _map_chunks(c0_of, nb, chunk_for_width(width),
                              (w_mat, dst_mat, curr)).reshape(nb)
        counter0 = counter0.at[verts].add(c0_rows, mode="drop")
    if use_sparse:
        mod = sparse_modularity(counter0, env.deg_local, constant,
                                red_axes, accum_dtype,
                                deg_axis_name=axis_name)
        overflow = jax.lax.psum(env.overflow.astype(jnp.int32),
                                red_axes) > 0
        return mod, overflow
    return seg.modularity_terms(counter0, comm_deg, constant,
                                gsum, accum_dtype, axis_name=axis_name)


def bucketed_step(bucket_arrays, heavy_arrays, self_loop, comm, vdeg,
                  constant, *, nv_total, sentinel, accum_dtype=None,
                  axis_name=None, pallas_flags=(), pallas_interpret=False,
                  sparse_plan=None, nshards=1, budget=0, ici_axis=None,
                  info_comm=None, assemble_perm=None, heavy_kernel=None):
    """Full Louvain sweep over one shard using the bucketed engine.

    ``assemble_perm`` (phase-static [nv_local] int32, vertex -> index into
    the bucket-row concat space, trailing index = "in no bucket"): enables
    the scatter-free assembly of per-vertex results — TPU scatters are
    serialization hazards; a static permutation gather is not.  Semantics
    are identical with or without it.

    ``bucket_arrays`` is a tuple of (verts, dst_mat, w_mat) triples (one per
    degree class); ``heavy_arrays`` is (src, dst, w) for the residual
    heavy-vertex edges (may be empty-padded).  Returns (target, modularity,
    n_moved, overflow) with step semantics identical to louvain_step_local —
    the two engines are interchangeable and tested for equal outputs.
    ``overflow`` is the sparse-exchange budget flag (constant False under
    the replicated exchange).

    ``pallas_flags`` (one bool per bucket) routes flagged degree classes
    through the Pallas row-argmax kernel (cuvite_tpu/kernels/row_argmax.py);
    those buckets' dst/w matrices must be stored TRANSPOSED [D, Nb] with Nb
    a multiple of 128 (the runner's ``engine='pallas'`` upload does this,
    single-shard and SPMD alike — on a mesh the kernel runs INSIDE the
    shard_map body on each shard's block, under either exchange: the
    replicated mode feeds it the psum'd community-degree table, the sparse
    mode the vertex-attached cdeg/csize extended-local tables, with the
    winning community's size tracked in-kernel for the singleton guard).

    With ``axis_name`` the function runs SPMD inside shard_map: ``comm`` /
    ``vdeg`` / ``self_loop`` are this shard's slices.  Two exchange modes
    implement the cross-shard community pull (the analog of
    fillRemoteCommunities, /root/reference/louvain.cpp:2588-2959):

    - replicated (``sparse_plan=None``): dst ids are global (padded space);
      an all_gather replicates the community vector and full-width
      psum-reduced comm_deg/comm_size tables — O(nv_total) per chip.
    - sparse (``sparse_plan=(send_idx, ghost_sel)``): dst ids are
      extended-local (owned + ghost table); community values and attached
      community degree/size ride the phase-static ghost routing, community
      info is sharded by owner and resolved through the budgeted
      owner-reduce (cuvite_tpu/comm/exchange.py) — O(owned + ghosts).
    - two-level (``sparse_plan`` + ``ici_axis``, ISSUE 18): ``axis_name``
      is the slow DCN axis of a 2-D hybrid mesh, the plan a GROUPED one
      (``ExchangePlan.build_grouped``); community state is gathered to
      group scale on the fast ICI axis — O(nv_total / n_dcn) per chip —
      and the sparse protocol runs between groups on the DCN axis.
      Scalars reduce over both axes; the a² modularity term over DCN
      only (the group tables are ICI-replicated).

    ``info_comm``: optional FROZEN assignment used only for the community
    degree/size tables — the vertex-ordering schedule (reference -d,
    /root/reference/louvain.cpp:1535-1562) hoists the community-info
    exchange out of the color loop, so later classes see earlier classes'
    ``comm`` updates but iteration-start community info.  Replicated
    exchange only (single-shard, or SPMD via make_sharded_class_step).

    ``heavy_kernel``: optional ``(verts [Hp], dstT [D, Hp], wT [D, Hp])``
    phase-static layout (kernels/heavy_bincount.build_heavy_layout) —
    the heavy (> widths[-1] degree) residual then runs the
    community-range-tile bincount kernel instead of the per-iteration
    global sort (the ISSUE 8 promotion; single-shard replicated only —
    the kernel has no attached-size channel for the sparse exchange).
    Same gain formula, tie-break and counter0 accumulation order as the
    sorted path: labels are bit-identical on the exactness domain.
    """
    nv_local = comm.shape[0]
    wdt = vdeg.dtype
    vdt = comm.dtype

    use_sparse = sparse_plan is not None
    red_axes = (axis_name if ici_axis is None else (axis_name, ici_axis))
    if use_sparse:
        from cuvite_tpu.comm.exchange import (
            sparse_env, sparse_modularity, twolevel_env)

        assert axis_name is not None, "sparse exchange requires a mesh axis"
        if ici_axis is not None:
            env = twolevel_env(comm, vdeg, sparse_plan[0], sparse_plan[1],
                               axis_name, ici_axis, n_dcn=nshards,
                               budget=budget, info=info_comm)
        else:
            env = sparse_env(comm, vdeg, sparse_plan[0], sparse_plan[1],
                             axis_name, nshards=nshards, budget=budget,
                             info=info_comm)
        comm_ref = env.comm_ext      # gather table for dst indices

        def gsum(x):
            return jax.lax.psum(x, red_axes)

        overflow = jax.lax.psum(env.overflow.astype(jnp.int32),
                                red_axes) > 0
    else:
        env = None
        comm_ref, gsum = seg.spmd_env(comm, axis_name)
        info = comm if info_comm is None else info_comm
        comm_deg = gsum(seg.segment_sum(vdeg, info, num_segments=nv_total))  # graftlint: replicated-ok=scope=ici; replicated-exchange community degree table, flat-mesh-only (one ICI group); sparse/two-level modes ride the ghost plan instead
        comm_size = gsum(seg.segment_sum(  # graftlint: replicated-ok=scope=ici; replicated-exchange community size table, flat-mesh-only (one ICI group); sparse/two-level modes attach sizes to ghosts instead
            jnp.ones((nv_local,), dtype=vdt), info, num_segments=nv_total
        ))
        overflow = jnp.zeros((), dtype=bool)  # replicated: can't overflow

    # Community-info lookups.  Sparse mode reads values ATTACHED to the
    # referenced vertex (indexed by dst in the extended-local table);
    # replicated mode looks the community id up in the full tables.
    def slot_ay(dst_idx, ck):
        return (jnp.take(env.cdeg_ext, dst_idx) if use_sparse
                else jnp.take(comm_deg, ck))

    def slot_size(dst_idx, ck):
        return jnp.take(env.csize_ext, dst_idx) if use_sparse else None

    def own_deg(v_safe):   # comm_deg[comm[v]] for owned v
        return (jnp.take(env.cdeg_v, v_safe) if use_sparse
                else jnp.take(comm_deg, jnp.take(comm, v_safe)))

    neg_inf = jnp.array(-jnp.inf, dtype=wdt)

    # Heavy-vertex current-community weight (also their e_ix source).
    hs, hd, hw = heavy_arrays
    use_heavy_kernel = heavy_kernel is not None
    if use_heavy_kernel:
        # Promoted heavy path (ISSUE 8): ONE community-range-tile kernel
        # pass per iteration — no heavy sort, no per-iteration triples
        # gather.  Replicated/single-shard only: the kernel consumes the
        # dense comm_deg table (and the sparse singleton guard needs an
        # attached-size channel it does not have).
        assert not use_sparse and axis_name is None, \
            "heavy_kernel is a single-shard replicated-path layout"
        from cuvite_tpu.kernels.heavy_bincount import heavy_argmax_pallas

        hk_verts, hk_dT, hk_wT = heavy_kernel
        safe_hv = jnp.minimum(hk_verts, nv_local - 1)
        curr_h = jnp.take(comm, safe_hv)
        vdeg_h = jnp.take(vdeg, safe_hv)
        # Padding slots (dst == pad id >= nv_local) mask to nv_total: >=
        # every candidate tile's range, so they are never candidates and
        # never touch counter0 (w == 0 there anyway).
        hk_pad = hk_dT >= jnp.asarray(nv_local, hk_dT.dtype)
        cT = jnp.where(
            hk_pad, jnp.asarray(nv_total, hk_dT.dtype),
            jnp.take(comm_ref, jnp.minimum(hk_dT, nv_local - 1)))
        hk_bc, hk_bg, hk_c0 = heavy_argmax_pallas(
            cT, hk_wT.astype(wdt), comm_deg, curr_h, vdeg_h,
            jnp.take(self_loop, safe_hv), own_deg(safe_hv) - vdeg_h,
            constant, interpret=pallas_interpret)
        c0_heavy = jnp.zeros((nv_local,), dtype=wdt).at[hk_verts].set(
            hk_c0, mode="drop")
    else:
        ckey_h = jnp.take(comm_ref, hd)
        csrc_h = jnp.take(comm, jnp.minimum(hs, nv_local - 1))
        c0_heavy = seg.segment_sum(
            jnp.where(ckey_h == csrc_h, hw, jnp.zeros_like(hw)), hs,
            num_segments=nv_local,
        )

    # One pass per bucket: e_ix is row-local (every edge of a bucket vertex
    # lives in its row), so dedup + counter0 + gain + argmax all happen in a
    # single kernel over each bucket — no global counter0 prepass.
    is_pallas = (list(pallas_flags) if pallas_flags
                 else [False] * len(bucket_arrays))
    parts = []   # (verts, best_c, best_gain, counter0, best_size|None)
    for i, (verts, dst_mat, w_mat) in enumerate(bucket_arrays):
        safe_v = jnp.minimum(verts, nv_local - 1)
        curr = jnp.take(comm, safe_v)
        if is_pallas[i]:
            # Kernel classes arrive TRANSPOSED [D, Nb]; the gathers below
            # stay index-shaped, so the community/ay/size matrices come out
            # [D, Nb] too.  Works identically single-shard and inside the
            # shard_map body: replicated mode looks candidate info up in
            # the psum'd full tables, sparse mode reads the values ATTACHED
            # to the referenced vertex (extended-local dst indices) and the
            # kernel additionally tracks the winning community's size for
            # the singleton guard.
            from cuvite_tpu.kernels.row_argmax import row_argmax_pallas

            if w_mat.dtype != wdt:   # uint8-compressed unit weights
                w_mat = w_mat.astype(wdt)
            cmat_t = jnp.take(comm_ref, dst_mat)   # [D, Nb]
            vdeg_v = jnp.take(vdeg, safe_v)
            ayT = (jnp.take(env.cdeg_ext, dst_mat) if use_sparse
                   else jnp.take(comm_deg, cmat_t))
            szT = jnp.take(env.csize_ext, dst_mat) if use_sparse else None
            out = row_argmax_pallas(
                cmat_t, w_mat, ayT,
                curr, vdeg_v, jnp.take(self_loop, safe_v),
                own_deg(safe_v) - vdeg_v, constant, szT=szT,
                sentinel=sentinel, interpret=pallas_interpret,
            )
            if use_sparse:
                bc, bg, c0_rows, bs = out
                parts.append((verts, bc.astype(vdt), bg, c0_rows,
                              bs.astype(vdt)))
            else:
                bc, bg, c0_rows = out
                parts.append((verts, bc.astype(vdt), bg, c0_rows, None))
            continue
        vdeg_v = jnp.take(vdeg, safe_v)
        res = _rows_chunked(w_mat, dst_mat,
                            curr, vdeg_v, jnp.take(self_loop, safe_v),
                            own_deg(safe_v) - vdeg_v,
                            constant, sentinel,
                            lambda dm: jnp.take(comm_ref, dm),
                            slot_ay, slot_size, wdt,
                            id_bound=nv_total)
        parts.append((verts, res.best_c, res.best_gain, res.counter0,
                      res.best_size))

    # Assemble per-vertex results from the per-bucket row vectors.  Bucket
    # membership is phase-static and disjoint, so with ``assemble_perm``
    # (vertex -> position in the concatenated row space; the trailing slot
    # holds the no-bucket default) assembly is three pure gathers — the
    # scatter-free path.  Without a perm (class-restricted plans) fall back
    # to scatters.
    if assemble_perm is not None and parts:
        cat = lambda xs, d: jnp.concatenate(xs + [d])  # noqa: E731
        d1 = lambda v, dt: jnp.full((1,), v, dtype=dt)  # noqa: E731
        best_c = jnp.take(
            cat([p[1] for p in parts], d1(sentinel, vdt)), assemble_perm)
        best_gain = jnp.take(
            cat([p[2] for p in parts], neg_inf[None]), assemble_perm)
        counter0 = c0_heavy + jnp.take(
            cat([p[3] for p in parts], d1(0, wdt)), assemble_perm)
        if use_sparse:
            best_size = jnp.take(
                cat([p[4] for p in parts], d1(0, vdt)), assemble_perm)
        else:
            best_size = None
    else:
        best_c = jnp.full((nv_local,), sentinel, dtype=vdt)
        best_gain = jnp.full((nv_local,), neg_inf, dtype=wdt)
        counter0 = c0_heavy
        best_size = jnp.zeros((nv_local,), dtype=vdt) if use_sparse else None
        for verts, bc, bg, c0, bs in parts:
            best_c = best_c.at[verts].set(bc, mode="drop")
            best_gain = best_gain.at[verts].set(bg, mode="drop")
            counter0 = counter0.at[verts].add(c0, mode="drop")
            if use_sparse and bs is not None:
                best_size = best_size.at[verts].set(bs, mode="drop")
    eix = counter0 - self_loop

    # ---- heavy vertices ---------------------------------------------------
    if use_heavy_kernel:
        # Kernel results scatter to their vertices; everything else keeps
        # -inf/sentinel so the merge below is a no-op there.  The kernel's
        # no-candidate sentinel (int max of the id dtype) IS `sentinel`.
        hg = jnp.full((nv_local,), neg_inf, dtype=wdt).at[hk_verts].set(
            hk_bg, mode="drop")
        hc = jnp.full((nv_local,), sentinel, dtype=vdt).at[hk_verts].set(
            hk_bc.astype(vdt), mode="drop")
    else:
        # Sort-based candidates on the heavy edges only (the historical
        # path; sparse exchange and oversized layouts stay here).
        if use_sparse:
            src_s, ckey_s, w_s, ay_s, ts_s = seg.sort_edges_by_vertex_comm(
                hs, ckey_h, hw, jnp.take(env.cdeg_ext, hd),
                jnp.take(env.csize_ext, hd),
                src_bound=nv_local + 1, key_bound=nv_total)
        else:
            src_s, ckey_s, w_s = seg.sort_edges_by_vertex_comm(
                hs, ckey_h, hw, src_bound=nv_local + 1, key_bound=nv_total)
        starts = seg.run_starts(src_s, ckey_s)
        eiy, _ = seg.run_totals(w_s, starts)
        i_s = jnp.minimum(src_s, nv_local - 1)
        comm_i = jnp.take(comm, i_s)
        valid = starts & (src_s < nv_local) & (ckey_s != comm_i)
        k_i = jnp.take(vdeg, i_s)
        a_y = ay_s if use_sparse else jnp.take(comm_deg, ckey_s)
        a_x = own_deg(i_s) - k_i
        gain = 2.0 * (eiy - jnp.take(eix, i_s)) \
            - 2.0 * k_i * (a_y - a_x) * constant
        gain = jnp.where(valid, gain, neg_inf)
        hg = seg.segment_max(gain, src_s, num_segments=nv_local,
                             sorted_ids=True)
        at_best = valid & (gain == jnp.take(hg, i_s))
        cand_c = jnp.where(at_best, ckey_s, jnp.full_like(ckey_s, sentinel))
        hc = seg.segment_min(cand_c, src_s, num_segments=nv_local,
                             sorted_ids=True)
    heavy_better = hg > best_gain
    best_gain = jnp.where(heavy_better, hg, best_gain)
    best_c = jnp.where(heavy_better, hc, best_c)
    if use_sparse:
        chosen = at_best & (ckey_s == jnp.take(hc, i_s))
        ts_cand = jnp.where(chosen, ts_s, jnp.full_like(ts_s, sentinel))
        h_tsize = seg.segment_min(ts_cand, src_s, num_segments=nv_local,
                                  sorted_ids=True)
        best_size = jnp.where(heavy_better, h_tsize, best_size)

    # ---- select + singleton guard (louvain.cpp:2230-2241) ----------------
    move = best_gain > 0.0
    best_c_safe = jnp.minimum(best_c, jnp.array(nv_total - 1, dtype=vdt))
    if use_sparse:
        t_size = best_size               # propagated from the winning slot
        c_size = env.csize_v
    else:
        t_size = jnp.take(comm_size, best_c_safe)
        c_size = jnp.take(comm_size, comm)
    guard = (t_size == 1) & (c_size == 1) & (best_c_safe > comm)
    move = move & ~guard
    target = jnp.where(move, best_c_safe, comm)

    if use_sparse:
        modularity = sparse_modularity(counter0, env.deg_local, constant,
                                       red_axes, accum_dtype,
                                       deg_axis_name=axis_name)
    else:
        modularity = seg.modularity_terms(counter0, comm_deg, constant, gsum,
                                          accum_dtype, axis_name=axis_name)
    n_moved = gsum(jnp.sum(move.astype(jnp.int32)))
    return target, modularity, n_moved, overflow


def make_sharded_class_step(mesh, axis_name: str, n_buckets: int,
                            nv_total: int, sentinel: int, accum_dtype=None,
                            sparse=None, ordering: bool = False):
    """Jit one color class's restricted sweep as a shard_map: like
    make_sharded_bucketed_step but taking a separate ``info_comm`` — the
    community-info state the class's gains are computed against.  Coloring
    passes the committed work vector (info refreshed per class,
    /root/reference/louvain.cpp:862-901); vertex ordering passes the
    iteration-start snapshot (exchanges hoisted out of the color loop,
    louvain.cpp:1535-1562).

    ``sparse=(nshards, budget)`` runs the class sweep over the sparse ghost
    exchange (two trailing plan arrays, exactly as in
    make_sharded_bucketed_step); the 4th output is then the live
    budget-overflow flag.  Ordering's frozen info rides the exchange's
    ``info`` mode (one extra collective per class sweep)."""
    bspec = tuple((P(axis_name), P(axis_name), P(axis_name))
                  for _ in range(n_buckets))
    hspec = (P(axis_name), P(axis_name), P(axis_name))
    in_specs = [bspec, hspec, P(axis_name), P(axis_name), P(axis_name),
                P(axis_name), P(), P(axis_name)]
    out_specs = (P(axis_name), P(), P(), P())
    if sparse is not None:
        nshards, budget = sparse
        in_specs += [P(axis_name), P(axis_name)]
    else:
        nshards, budget = 1, 0

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=out_specs,
        check_vma=False,
    )
    def step(bucket_arrays, heavy_arrays, self_loop, comm, info_comm, vdeg,
             constant, perm, *plan):
        # ``ordering`` is a STATIC trait of the schedule: coloring passes
        # info == work (community info refreshed per class), so the frozen
        # info plumbing — and the sparse env's extra collective — is
        # compiled out entirely rather than detected at trace time.
        return bucketed_step(
            bucket_arrays, heavy_arrays, self_loop, comm, vdeg, constant,
            nv_total=nv_total, sentinel=sentinel, accum_dtype=accum_dtype,
            axis_name=axis_name,
            info_comm=info_comm if ordering else None,
            sparse_plan=plan if plan else None,
            nshards=nshards, budget=budget,
            assemble_perm=perm,
        )

    return jax.jit(step)


def make_sharded_bucketed_mod(mesh, axis_name: str, n_buckets: int,
                              nv_total: int, accum_dtype=None, sparse=None,
                              ici_axis=None):
    """Jit the counter0-only modularity pass as a shard_map (the SPMD
    convergence check for the class-scheduled iteration).  With
    ``sparse=(nshards, budget)`` it rides the sparse exchange and returns
    ``(modularity, overflow)``.  ``ici_axis`` (with ``sparse``) selects
    the two-level exchange on a hybrid mesh: vertex state shards over
    both axes, the grouped plan over the DCN axis only (each ICI sibling
    reads its whole group's routing rows)."""
    vspec = P(axis_name) if ici_axis is None else P((axis_name, ici_axis))
    bspec = tuple((vspec, vspec, vspec) for _ in range(n_buckets))
    hspec = (vspec, vspec, vspec)
    in_specs = [bspec, hspec, vspec, vspec, vspec, P()]
    if sparse is not None:
        nshards, budget = sparse
        in_specs += [P(axis_name), P(axis_name)]
        out_specs = (P(), P())
    else:
        nshards, budget = 1, 0
        out_specs = P()

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=out_specs,
        check_vma=False,
    )
    def mod(bucket_arrays, heavy_arrays, self_loop, comm, vdeg, constant,
            *plan):
        return bucketed_modularity(
            bucket_arrays, heavy_arrays, self_loop, comm, vdeg, constant,
            nv_total=nv_total, accum_dtype=accum_dtype, axis_name=axis_name,
            sparse_plan=plan if plan else None,
            nshards=nshards, budget=budget, ici_axis=ici_axis,
        )

    return jax.jit(mod)


def make_sharded_bucketed_step(mesh, axis_name: str, n_buckets: int,
                               nv_total: int, sentinel: int,
                               accum_dtype=None, sparse=None,
                               pallas_flags=(), pallas_interpret=False,
                               ici_axis=None):
    """Jit the bucketed sweep as a shard_map over ``axis_name``: bucket
    matrices, heavy slab and vertex state sharded along axis 0, modularity
    and move count replicated.

    ``sparse``: None for the replicated all_gather exchange, or
    ``(nshards, budget)`` to run the sparse ghost exchange — the step then
    takes two trailing plan arrays (send_idx stacked [S*S, B] and ghost_sel
    stacked [S*G], both sharded along axis 0).  The 4th output is the
    replicated budget-overflow flag (constant False without sparse).

    ``pallas_flags`` (one bool per bucket, static): flagged classes run the
    Pallas row-argmax kernel inside the shard_map body — their stacked
    dst/w matrices must be placed TRANSPOSED [S*D, Nb] (still sharded
    along axis 0, so each shard's block is the kernel's [D, Nb] layout);
    see StackedPlan.pallas_flags.  ``pallas_interpret`` runs the kernel in
    interpret mode (non-TPU backends).

    ``ici_axis`` (with ``sparse``): the two-level exchange over a hybrid
    ``(axis_name, ici_axis)`` mesh — ``axis_name`` is then the slow DCN
    axis, ``sparse=(n_dcn, budget)`` carries the GROUP count, vertex
    state shards over both axes (dcn-major, identical per-device blocks
    to the flat mesh), and the grouped plan arrays shard over the DCN
    axis only so every ICI sibling drives the same group-scale
    protocol."""
    vspec = P(axis_name) if ici_axis is None else P((axis_name, ici_axis))
    bspec = tuple((vspec, vspec, vspec) for _ in range(n_buckets))
    hspec = (vspec, vspec, vspec)
    in_specs = [bspec, hspec, vspec, vspec, vspec, P(), vspec]
    out_specs = (vspec, P(), P(), P())
    if sparse is not None:
        nshards, budget = sparse
        in_specs += [P(axis_name), P(axis_name)]
    else:
        nshards, budget = 1, 0

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=out_specs,
        check_vma=False,
    )
    def step(bucket_arrays, heavy_arrays, self_loop, comm, vdeg, constant,
             perm, *plan):
        return bucketed_step(
            bucket_arrays, heavy_arrays, self_loop, comm, vdeg, constant,
            nv_total=nv_total, sentinel=sentinel, accum_dtype=accum_dtype,
            axis_name=axis_name,
            pallas_flags=pallas_flags, pallas_interpret=pallas_interpret,
            sparse_plan=plan if plan else None,
            nshards=nshards, budget=budget, ici_axis=ici_axis,
            assemble_perm=perm,
        )

    return jax.jit(step)
