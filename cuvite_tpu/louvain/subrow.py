"""Sub-row packed Louvain: 2^k fenced small graphs per compiled row
(ISSUE 20).

A packed row (core/batch.py::pack_subrows) embeds ``n_sub`` disjoint
small-class graphs in one row of the ``n_sub``-times-larger class:
sub-row ``s`` owns vertex ids ``[s*nv_sub, (s+1)*nv_sub)`` and no edge
crosses a seam.  The whole-row step below is
louvain/step.py::louvain_step_local with exactly three generalizations,
each an identity when ``n_sub == 1``:

  * the gain's ``1/(2m)`` scalar becomes a PER-SUB-ROW constant,
    gathered per candidate run by its source vertex's sub-row;
  * modularity/Q is a ``[n_sub]`` vector — the whole-row sums reshape
    to ``[n_sub, nv_sub]`` and reduce the minor axis, which is the SAME
    reduction shape ``jax.vmap`` gives a B=1 batched row (the existing
    served==solo precedent), so per-sub-row Q is bit-identical to the
    solo run's scalar;
  * the phase loop freezes each sub-row's labels the iteration ITS OWN
    ``(mod - prev) < threshold`` criterion fires — extra iterations run
    for a packed neighbor never touch a converged sub-row's labels.

Everything else — community tables, neighbor-community sort, run sums,
argmax tie-breaks, the singleton-swap guard — is the whole-row op it
always was: fences guarantee per-community and per-vertex segment sums
only ever mix one sub-row's values, and the packed sort preserves each
sub-row's relative edge order, so every per-run float is bit-identical
to the solo slab's.  Packed rows are f32-only: the serving queue's
``accum_class_of`` gate refuses ds32-scale tenants into a merged row
(a per-program accumulator flip would change batchmates' bits).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from cuvite_tpu.core.types import CONV_ROWS_CAP, MAX_TOTAL_ITERATIONS
from cuvite_tpu.louvain.step import StepOut
from cuvite_tpu.ops import segment as seg

# Accumulator tags a packed row may run (see module note): plain f32
# only.  'ds32' needs per-sub-row double-single pair reductions, which
# the serving merge gate makes unreachable — refuse loudly instead of
# silently changing batchmates' accumulation.
SUBROW_ACCUM_OK = (None, "float32")


def _check_accum(accum_dtype):
    if accum_dtype not in SUBROW_ACCUM_OK:
        raise ValueError(
            f"subrow step: accum_dtype={accum_dtype!r} unsupported — "
            "packed rows are f32-only (the serve merge gate refuses "
            "ds32-scale tenants via accum_class_of)")


def subrow_modularity(counter0, comm_deg, constants, *, n_sub,
                      accum_dtype=None):
    """Per-sub-row Q from whole-row per-vertex/per-community tables:
    ``modularity_terms``'s two sums reshaped to ``[n_sub, nv_sub]`` and
    reduced over the minor axis (fences make every column of segment
    ``s`` a value of graph ``s`` alone).  Same multiply association as
    the scalar path, so bits match the solo run's."""
    _check_accum(accum_dtype)
    acc = counter0.dtype if accum_dtype is None else accum_dtype
    le = jnp.sum(counter0.astype(acc).reshape(n_sub, -1), axis=-1)
    la2 = jnp.sum(jnp.square(comm_deg.astype(acc)).reshape(n_sub, -1),
                  axis=-1)
    c = constants.astype(acc)
    return le * c - la2 * c * c


def subrow_step_local(
    src,          # [ne_pad] int32: row-local source; pad = nv_total
    dst,          # [ne_pad] int32: row-local tail id; pad = 0, w = 0
    w,            # [ne_pad] weight
    comm,         # [nv_total] community ids (fenced: in-sub-row)
    vdeg,         # [nv_total] k_i
    constants,    # [n_sub] 1/(2m) per sub-row (0 on empty sub-rows)
    *,
    nv_total: int,
    n_sub: int,
    accum_dtype=None,
) -> StepOut:
    """One synchronous sweep over a packed row — single-shard only (the
    batched driver vmaps this; packed rows never vertex-shard).
    ``StepOut.modularity``/``n_moved`` are ``[n_sub]`` vectors."""
    _check_accum(accum_dtype)
    nv_sub = nv_total // n_sub
    wdt = w.dtype
    vdt = comm.dtype
    sentinel = jnp.iinfo(vdt).max

    # --- community info: size + degree over the whole row ----------------
    comm_deg = seg.segment_sum(vdeg, comm, num_segments=nv_total)
    comm_size = seg.segment_sum(
        jnp.ones((nv_total,), dtype=vdt), comm, num_segments=nv_total)

    # --- per-edge community keys ------------------------------------------
    src_c = jnp.minimum(src, nv_total - 1)
    csrc = jnp.take(comm, src_c)
    ckey = jnp.take(comm, dst)

    to_curr = jnp.where(ckey == csrc, w, jnp.zeros_like(w))
    counter0 = seg.segment_sum(to_curr, src, num_segments=nv_total,
                               sorted_ids=True)
    self_w = jnp.where(dst == src, w, jnp.zeros_like(w))
    self_loop = seg.segment_sum(self_w, src, num_segments=nv_total,
                                sorted_ids=True)
    eix = counter0 - self_loop

    # --- neighbor-community aggregation: sort + run segment sums ----------
    src_s, ckey_s, w_s = seg.sort_edges_by_vertex_comm(
        src, ckey, w, src_bound=nv_total + 1, key_bound=nv_total)
    starts = seg.run_starts(src_s, ckey_s)
    eiy, _ = seg.run_totals(w_s, starts)

    i_s = jnp.minimum(src_s, nv_total - 1)
    comm_i = jnp.take(comm, i_s)
    valid = starts & (src_s < nv_total) & (ckey_s != comm_i)

    # --- dQ per candidate run, with the run's OWN sub-row constant --------
    const_v = jnp.repeat(constants, nv_sub, total_repeat_length=nv_total)
    const_i = jnp.take(const_v, i_s)
    k_i = jnp.take(vdeg, i_s)
    a_y = jnp.take(comm_deg, ckey_s)
    a_x = jnp.take(comm_deg, comm_i) - k_i
    gain = 2.0 * (eiy - jnp.take(eix, i_s)) - 2.0 * k_i * (a_y - a_x) * const_i
    neg_inf = jnp.array(-jnp.inf, dtype=wdt)
    gain = jnp.where(valid, gain, neg_inf)

    # --- per-vertex argmax, tie-break to smaller community id -------------
    best_gain = seg.segment_max(gain, src_s, num_segments=nv_total,
                                sorted_ids=True)
    is_best = valid & (gain == jnp.take(best_gain, i_s))
    cand_c = jnp.where(is_best, ckey_s, jnp.full_like(ckey_s, sentinel))
    best_c = seg.segment_min(cand_c, src_s, num_segments=nv_total,
                             sorted_ids=True)

    move = best_gain > 0.0
    best_c_safe = jnp.minimum(best_c, jnp.array(nv_total - 1, dtype=vdt))
    t_size = jnp.take(comm_size, best_c_safe)
    c_size = jnp.take(comm_size, comm)
    guard = (t_size == 1) & (c_size == 1) & (best_c_safe > comm)
    move = move & ~guard
    target = jnp.where(move, best_c_safe, comm)

    modularity = subrow_modularity(counter0, comm_deg, constants,
                                   n_sub=n_sub, accum_dtype=accum_dtype)
    n_moved = jnp.sum(move.astype(jnp.int32).reshape(n_sub, -1), axis=-1)  # graftlint: width-ok=move is per-VERTEX (nv_total <= 2^28 rows, per-sub-row sum <= 2^28 < 2^31); the slab-extent tag is argmax-index over-approximation, not a real edge-extent reduction
    return StepOut(target=target, modularity=modularity, n_moved=n_moved)


@functools.lru_cache(maxsize=None)
def _subrow_call(nv_pad, n_sub, accum_dtype):
    """(comm, extra) adapter over subrow_step_local for the sub-row
    phase loop (lru-cached for stable static-arg identity, like
    fused._fused_step_call)."""

    def call(comm, extra):
        src, dst, w, vdeg, constants = extra
        out = subrow_step_local(
            src, dst, w, comm, vdeg, constants,
            nv_total=nv_pad, n_sub=n_sub, accum_dtype=accum_dtype,
        )
        return out.target, out.modularity, out.n_moved, jnp.zeros((), bool)

    return call


@functools.partial(jax.jit, static_argnames=("call", "max_iters", "n_sub"))
def _run_subrow_phase_loop(extra, comm0, threshold, lower, *, call,
                           max_iters, n_sub):
    """driver._run_phase_loop with a ``[n_sub]`` convergence carry: a
    sub-row's labels advance only while ITS criterion keeps gaining,
    and its no-gain sweep rolls back exactly like the solo loop's (its
    ``past`` freezes at the last assignment whose gain passed).  All
    sub-rows start at iteration 0 together, so each one's trajectory —
    including the ``max_iters`` cap — aligns 1:1 with its solo loop.

    Returns ``(past [nv], prev_mod [n_sub], iters [n_sub], ovf,
    (cq [n_sub, CAP], cmoved [n_sub, CAP], covf [CAP]))``.
    """
    wdt = lower.dtype
    nv = comm0.shape[0]
    nv_sub = nv // n_sub

    def cond(c):
        return ~c[4]

    def body(c):
        past, comm, prev_mod, iters, _, ovf, active, sub_iters, conv = c
        target, mod, moved, step_ovf = call(comm, extra)
        mod = mod.astype(wdt)
        no_gain = (mod - prev_mod) < threshold      # [n_sub]
        adv = active & ~no_gain
        # Per-sub-row telemetry rows: a sub-row records its own sweeps
        # only (0 moves on its rollback sweep, like the solo loop);
        # frozen sub-rows' later columns stay 0 and decode slices by
        # the per-sub-row iteration count.
        cq, cmoved, covf = conv
        cq = cq.at[:, iters].set(
            jnp.where(active, mod, jnp.zeros_like(mod)), mode="drop")
        cmoved = cmoved.at[:, iters].set(
            jnp.where(adv, moved.astype(jnp.int32), 0), mode="drop")
        covf = covf.at[iters].set(step_ovf, mode="drop")
        iters1 = iters + 1
        sub_iters = jnp.where(active, iters1, sub_iters)
        advv = jnp.repeat(adv, nv_sub, total_repeat_length=nv)
        new_past = jnp.where(advv, comm, past)
        new_comm = jnp.where(advv, target, comm)
        new_prev = jnp.where(adv, jnp.maximum(mod, lower), prev_mod)
        stop = (~jnp.any(adv)) | (iters1 >= max_iters)
        return (new_past, new_comm, new_prev, iters1, stop,
                ovf | step_ovf, adv, sub_iters, (cq, cmoved, covf))

    conv0 = (jnp.zeros((n_sub, CONV_ROWS_CAP), dtype=wdt),
             jnp.zeros((n_sub, CONV_ROWS_CAP), dtype=jnp.int32),
             jnp.zeros((CONV_ROWS_CAP,), dtype=bool))
    prev0 = jnp.full((n_sub,), lower, dtype=wdt)
    init = (comm0, comm0, prev0, jnp.int32(0), jnp.bool_(False),
            jnp.zeros((), dtype=bool), jnp.ones((n_sub,), dtype=bool),
            jnp.zeros((n_sub,), dtype=jnp.int32), conv0)
    past, _, prev_mod, _, _, ovf, _, sub_iters, conv = jax.lax.while_loop(
        cond, body, init)
    return past, prev_mod, sub_iters, ovf, conv


def subrow_phase(src, dst, w, constants, threshold, *, nv_pad, n_sub,
                 accum_dtype=None, max_iters=MAX_TOTAL_ITERATIONS):
    """ONE phase of a packed row: weighted-degree pass + the per-sub-row
    iteration loop, identity start.  The batched driver lifts this over
    the batch axis with ``jax.vmap`` exactly like ``fused_phase`` —
    deliberately not jitted here."""
    vdeg = seg.segment_sum(w, src, num_segments=nv_pad, sorted_ids=True)
    wdt = w.dtype
    lower = jnp.asarray(-1.0, dtype=wdt)
    comm0 = jnp.arange(nv_pad, dtype=jnp.int32)
    return _run_subrow_phase_loop(
        (src, dst, w, vdeg, constants), comm0,
        jnp.asarray(threshold, dtype=wdt), lower,
        call=_subrow_call(nv_pad, n_sub,
                          None if accum_dtype is None else str(accum_dtype)),
        max_iters=max_iters, n_sub=n_sub)
