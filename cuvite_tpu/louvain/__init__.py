"""cuvite_tpu.louvain"""
