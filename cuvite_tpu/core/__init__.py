"""cuvite_tpu.core"""
