"""Batched multi-tenant slab packing (ISSUE 9).

The serving traffic shape for "millions of users" is thousands of
small-to-medium graphs arriving concurrently, not one giant graph.  The
pow2 slab-class discipline (DistGraph.build's padded single-shard
layout) already canonicalizes every graph into one of ~16 static
``(nv_pad, ne_pad)`` shapes — which means B graphs of one class can be
STACKED along a leading batch axis and pushed through ONE compiled
Louvain program (louvain/batched.py), amortizing the compile and every
kernel launch across tenants.  The same amortize-across-instances
insight as the reference's bucketed per-degree-class kernels and
PASCO's run-K-clusterings-in-parallel overlay (arXiv:2412.13592),
applied at graph granularity.

The batch size is itself padded to a small pow2 ladder (``BATCH_SIZES``)
so ``(class, B_pad)`` is a static compiled shape too: a queue serving
mixed batch sizes compiles at most ``len(BATCH_SIZES)`` programs per
slab class, not one per arrival count.  Padding rows are all-padding
slabs (every edge slot carries the ``src == nv_pad`` sentinel, zero
weight, an all-false vertex mask and a zero gain constant) — they
converge in two sweeps of the device loop and are dropped at unpack.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from cuvite_tpu.core.distgraph import DistGraph
from cuvite_tpu.core.types import next_pow2

# Slab-class floors: MUST match the single-shard floors the per-graph
# drivers use (DistGraph.build(min_nv_pad=4096, min_ne_pad=16384) in
# driver._run_fused / coarsen.device.maybe_shrink_to_class), so a graph
# lands in the same class whether it is served batched or alone.
MIN_NV_PAD = 4096
MIN_NE_PAD = 16384

# The batch-size ladder: B pads to the smallest member >= n_jobs (counts
# above the top rung pad to the next pow2).  Small and pow2 so a serving
# queue's compile footprint stays bounded per slab class.
BATCH_SIZES = (1, 2, 4, 8, 16, 32, 64)


def slab_class_of(graph) -> tuple:
    """The pow2 slab class ``(nv_pad, ne_pad)`` this graph canonicalizes
    to under the single-shard floors — the serving queue's binning key.
    Pure host arithmetic: no slab is built."""
    return (
        max(next_pow2(max(graph.num_vertices, 1)), MIN_NV_PAD),
        max(next_pow2(max(graph.num_edges, 1)), MIN_NE_PAD),
    )


def batch_pad(n_jobs: int) -> int:
    """Smallest BATCH_SIZES rung >= n_jobs (pow2 beyond the ladder)."""
    if n_jobs < 1:
        raise ValueError("need at least one job")
    for b in BATCH_SIZES:
        if n_jobs <= b:
            return b
    return next_pow2(n_jobs)


@dataclasses.dataclass
class BatchedSlab:
    """B same-class single-shard slabs stacked on a leading batch axis.

    Row layout per graph matches DistGraph.build's single-shard slab
    (src ascending with padding ``src == nv_pad`` at the tail, dst pad
    0, w pad 0); the single-shard padded id space IS the original id
    space (old_to_pad identity), so per-tenant labels unpack by a plain
    prefix slice.  Rows in ``[n_jobs, b_pad)`` are batch padding.
    """

    src: np.ndarray        # [b_pad, ne_pad] int32
    dst: np.ndarray        # [b_pad, ne_pad] int32
    w: np.ndarray          # [b_pad, ne_pad] weight dtype
    real_mask: np.ndarray  # [b_pad, nv_pad] bool (all-false on pad rows)
    constant: np.ndarray   # [b_pad] 1/(2m) per graph (0.0 on pad rows)
    row_valid: np.ndarray  # [b_pad] bool
    nv_real: np.ndarray    # [b_pad] int64 real vertex counts (0 on pad)
    ne_real: np.ndarray    # [b_pad] int64 real directed edge counts
    tw2: np.ndarray        # [b_pad] float64 total weight (2m) per graph
    nv_pad: int
    ne_pad: int
    n_jobs: int

    @property
    def b_pad(self) -> int:
        return int(self.src.shape[0])

    @property
    def slab_class(self) -> tuple:
        return (self.nv_pad, self.ne_pad)

    @property
    def pack_util(self) -> float:
        """Fraction of batch rows carrying a real job."""
        return self.n_jobs / self.b_pad


def batch_slabs(graphs, *, b_pad: int | None = None,
                slab_class: tuple | None = None) -> BatchedSlab:
    """Stack B same-class graphs into one :class:`BatchedSlab`.

    Every graph must canonicalize to the SAME slab class (the queue in
    cuvite_tpu/serve bins jobs by :func:`slab_class_of` before packing;
    mixing classes here is a caller bug and raises) — unless
    ``slab_class`` pins an explicit (pow2) ``(nv_pad, ne_pad)``: then
    every graph pads UP into that class (any graph can occupy a larger
    class; one too big for it raises).  The bench uses the pin so a job
    set whose per-seed edge counts straddle a pow2 boundary still runs
    one compiled program.  ``b_pad`` pads the batch axis (default:
    :func:`batch_pad`); padding rows are all-padding slabs that cost
    two masked device sweeps each.
    """
    if not graphs:
        raise ValueError("batch_slabs: empty graph list")
    classes = {slab_class_of(g) for g in graphs}
    if slab_class is not None:
        nv_pad, ne_pad = slab_class
        too_big = [c for c in sorted(classes)
                   if c[0] > nv_pad or c[1] > ne_pad]
        if too_big:
            raise ValueError(
                f"batch_slabs: graphs of classes {too_big} do not fit "
                f"the pinned slab class {tuple(slab_class)}")
    elif len(classes) > 1:
        raise ValueError(
            f"batch_slabs: mixed slab classes {sorted(classes)} — bin "
            "jobs by slab_class_of before packing (serve/queue.py "
            "does), or pin a common class via slab_class=")
    else:
        nv_pad, ne_pad = classes.pop()
    n = len(graphs)
    bp = batch_pad(n) if b_pad is None else int(b_pad)
    if bp < n:
        raise ValueError(f"b_pad={bp} < {n} jobs")

    # The batched program packs the TPU-default f32/int32 device dtypes.
    # With x64 OFF that matches the per-graph drivers exactly (their
    # _device_dtype clamps wide policies to 32-bit too, so served ==
    # solo holds for bits64 files as well); with x64 ON a wide-policy
    # graph WOULD keep f64 solo, so packing it here would silently
    # change its results — refuse instead of diverging.
    import jax

    if jax.config.jax_enable_x64 and any(
            np.dtype(g.policy.weight_dtype) == np.float64 for g in graphs):
        raise ValueError(
            "batch_slabs: wide-policy (f64-weight) graphs under "
            "jax_enable_x64 keep f64 on the per-graph drivers; packing "
            "them into the f32 batched slabs would silently change "
            "their labels/Q — serve them through louvain_phases")
    wdt = np.dtype(np.float32)
    src = np.full((bp, ne_pad), nv_pad, dtype=np.int32)
    dst = np.zeros((bp, ne_pad), dtype=np.int32)
    w = np.zeros((bp, ne_pad), dtype=wdt)
    real_mask = np.zeros((bp, nv_pad), dtype=bool)
    constant = np.zeros(bp, dtype=wdt)
    row_valid = np.zeros(bp, dtype=bool)
    nv_real = np.zeros(bp, dtype=np.int64)
    ne_real = np.zeros(bp, dtype=np.int64)
    tw2 = np.zeros(bp, dtype=np.float64)

    for i, g in enumerate(graphs):
        # The class floors ARE the target shape: a pinned larger class
        # raises the floors, and DistGraph.build pads up to them.
        dg = DistGraph.build(g, 1, min_nv_pad=nv_pad,
                             min_ne_pad=ne_pad)
        assert (dg.nv_pad, dg.ne_pad) == (nv_pad, ne_pad)
        sh = dg.shards[0]
        src[i] = np.asarray(sh.src, dtype=np.int32)
        dst[i] = np.asarray(sh.dst, dtype=np.int32)
        w[i] = np.asarray(sh.w, dtype=wdt)
        real_mask[i] = dg.vertex_mask()
        t2 = g.total_edge_weight_twice()
        if t2 <= 0:
            raise ValueError(
                f"batch_slabs: graph {i} has no edge weight (edgeless "
                "graphs short-circuit in louvain_many, not here)")
        constant[i] = wdt.type(1.0 / t2)
        row_valid[i] = True
        nv_real[i] = g.num_vertices
        ne_real[i] = g.num_edges
        tw2[i] = t2

    return BatchedSlab(
        src=src, dst=dst, w=w, real_mask=real_mask, constant=constant,
        row_valid=row_valid, nv_real=nv_real, ne_real=ne_real, tw2=tw2,
        nv_pad=nv_pad, ne_pad=ne_pad, n_jobs=n,
    )
