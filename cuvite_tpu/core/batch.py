"""Batched multi-tenant slab packing (ISSUE 9).

The serving traffic shape for "millions of users" is thousands of
small-to-medium graphs arriving concurrently, not one giant graph.  The
pow2 slab-class discipline (DistGraph.build's padded single-shard
layout) already canonicalizes every graph into one of ~16 static
``(nv_pad, ne_pad)`` shapes — which means B graphs of one class can be
STACKED along a leading batch axis and pushed through ONE compiled
Louvain program (louvain/batched.py), amortizing the compile and every
kernel launch across tenants.  The same amortize-across-instances
insight as the reference's bucketed per-degree-class kernels and
PASCO's run-K-clusterings-in-parallel overlay (arXiv:2412.13592),
applied at graph granularity.

The batch size is itself padded to a small pow2 ladder (``BATCH_SIZES``)
so ``(class, B_pad)`` is a static compiled shape too: a queue serving
mixed batch sizes compiles at most ``len(BATCH_SIZES)`` programs per
slab class, not one per arrival count.  Padding rows are all-padding
slabs (every edge slot carries the ``src == nv_pad`` sentinel, zero
weight, an all-false vertex mask and a zero gain constant) — they
converge in two sweeps of the device loop and are dropped at unpack.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from cuvite_tpu.core.distgraph import DistGraph
from cuvite_tpu.core.types import next_pow2

# Slab-class floors: MUST match the single-shard floors the per-graph
# drivers use (DistGraph.build(min_nv_pad=4096, min_ne_pad=16384) in
# driver._run_fused / coarsen.device.maybe_shrink_to_class), so a graph
# lands in the same class whether it is served batched or alone.
MIN_NV_PAD = 4096
MIN_NE_PAD = 16384

# The batch-size ladder: B pads to the smallest member >= n_jobs (counts
# above the top rung pad to the next pow2).  Small and pow2 so a serving
# queue's compile footprint stays bounded per slab class.
BATCH_SIZES = (1, 2, 4, 8, 16, 32, 64)

# THE batched per-phase engine vocabulary (ISSUE 10), defined here —
# the one jax-free module every consumer (louvain/batched.py driver,
# serve/queue.py config validation, workloads/bench.py record schema +
# CLI, serve/__main__.py CLI) already can import before jax initializes
# — so the list cannot drift across its four call sites.  Semantics
# live with the driver: see louvain/batched.py.
BATCH_ENGINES = ("fused", "bucketed")


def slab_class_of(graph) -> tuple:
    """The pow2 slab class ``(nv_pad, ne_pad)`` this graph canonicalizes
    to under the single-shard floors — the serving queue's binning key.
    Pure host arithmetic: no slab is built."""
    return (
        max(next_pow2(max(graph.num_vertices, 1)), MIN_NV_PAD),
        max(next_pow2(max(graph.num_edges, 1)), MIN_NE_PAD),
    )


def batch_pad(n_jobs: int) -> int:
    """Smallest BATCH_SIZES rung >= n_jobs (pow2 beyond the ladder)."""
    if n_jobs < 1:
        raise ValueError("need at least one job")
    for b in BATCH_SIZES:
        if n_jobs <= b:
            return b
    return next_pow2(n_jobs)


@dataclasses.dataclass
class BatchedSlab:
    """B same-class single-shard slabs stacked on a leading batch axis.

    Row layout per graph matches DistGraph.build's single-shard slab
    (src ascending with padding ``src == nv_pad`` at the tail, dst pad
    0, w pad 0); the single-shard padded id space IS the original id
    space (old_to_pad identity), so per-tenant labels unpack by a plain
    prefix slice.  Rows in ``[n_jobs, b_pad)`` are batch padding.
    """

    src: np.ndarray        # [b_pad, ne_pad] int32
    dst: np.ndarray        # [b_pad, ne_pad] int32
    w: np.ndarray          # [b_pad, ne_pad] weight dtype
    real_mask: np.ndarray  # [b_pad, nv_pad] bool (all-false on pad rows)
    constant: np.ndarray   # [b_pad] 1/(2m) per graph (0.0 on pad rows)
    row_valid: np.ndarray  # [b_pad] bool
    nv_real: np.ndarray    # [b_pad] int64 real vertex counts (0 on pad)
    ne_real: np.ndarray    # [b_pad] int64 real directed edge counts
    tw2: np.ndarray        # [b_pad] float64 total weight (2m) per graph
    nv_pad: int
    ne_pad: int
    n_jobs: int

    @property
    def b_pad(self) -> int:
        return int(self.src.shape[0])

    @property
    def slab_class(self) -> tuple:
        return (self.nv_pad, self.ne_pad)

    @property
    def pack_util(self) -> float:
        """Fraction of batch rows carrying a real job."""
        return self.n_jobs / self.b_pad


def batch_slabs(graphs, *, b_pad: int | None = None,
                slab_class: tuple | None = None) -> BatchedSlab:
    """Stack B same-class graphs into one :class:`BatchedSlab`.

    Every graph must canonicalize to the SAME slab class (the queue in
    cuvite_tpu/serve bins jobs by :func:`slab_class_of` before packing;
    mixing classes here is a caller bug and raises) — unless
    ``slab_class`` pins an explicit (pow2) ``(nv_pad, ne_pad)``: then
    every graph pads UP into that class (any graph can occupy a larger
    class; one too big for it raises).  The bench uses the pin so a job
    set whose per-seed edge counts straddle a pow2 boundary still runs
    one compiled program.  ``b_pad`` pads the batch axis (default:
    :func:`batch_pad`); padding rows are all-padding slabs that cost
    two masked device sweeps each.
    """
    if not graphs:
        raise ValueError("batch_slabs: empty graph list")
    classes = {slab_class_of(g) for g in graphs}
    if slab_class is not None:
        nv_pad, ne_pad = slab_class
        too_big = [c for c in sorted(classes)
                   if c[0] > nv_pad or c[1] > ne_pad]
        if too_big:
            raise ValueError(
                f"batch_slabs: graphs of classes {too_big} do not fit "
                f"the pinned slab class {tuple(slab_class)}")
    elif len(classes) > 1:
        raise ValueError(
            f"batch_slabs: mixed slab classes {sorted(classes)} — bin "
            "jobs by slab_class_of before packing (serve/queue.py "
            "does), or pin a common class via slab_class=")
    else:
        nv_pad, ne_pad = classes.pop()
    n = len(graphs)
    bp = batch_pad(n) if b_pad is None else int(b_pad)
    if bp < n:
        raise ValueError(f"b_pad={bp} < {n} jobs")

    # The batched program packs the TPU-default f32/int32 device dtypes.
    # With x64 OFF that matches the per-graph drivers exactly (their
    # _device_dtype clamps wide policies to 32-bit too, so served ==
    # solo holds for bits64 files as well); with x64 ON a wide-policy
    # graph WOULD keep f64 solo, so packing it here would silently
    # change its results — refuse instead of diverging.
    import jax

    if jax.config.jax_enable_x64 and any(
            np.dtype(g.policy.weight_dtype) == np.float64 for g in graphs):
        raise ValueError(
            "batch_slabs: wide-policy (f64-weight) graphs under "
            "jax_enable_x64 keep f64 on the per-graph drivers; packing "
            "them into the f32 batched slabs would silently change "
            "their labels/Q — serve them through louvain_phases")
    wdt = np.dtype(np.float32)
    src = np.full((bp, ne_pad), nv_pad, dtype=np.int32)
    dst = np.zeros((bp, ne_pad), dtype=np.int32)
    w = np.zeros((bp, ne_pad), dtype=wdt)
    real_mask = np.zeros((bp, nv_pad), dtype=bool)
    constant = np.zeros(bp, dtype=wdt)
    row_valid = np.zeros(bp, dtype=bool)
    nv_real = np.zeros(bp, dtype=np.int64)
    ne_real = np.zeros(bp, dtype=np.int64)
    tw2 = np.zeros(bp, dtype=np.float64)

    for i, g in enumerate(graphs):
        # The class floors ARE the target shape: a pinned larger class
        # raises the floors, and DistGraph.build pads up to them.
        dg = DistGraph.build(g, 1, min_nv_pad=nv_pad,
                             min_ne_pad=ne_pad)
        assert (dg.nv_pad, dg.ne_pad) == (nv_pad, ne_pad)
        sh = dg.shards[0]
        src[i] = np.asarray(sh.src, dtype=np.int32)
        dst[i] = np.asarray(sh.dst, dtype=np.int32)
        w[i] = np.asarray(sh.w, dtype=wdt)
        real_mask[i] = dg.vertex_mask()
        t2 = g.total_edge_weight_twice()
        if t2 <= 0:
            raise ValueError(
                f"batch_slabs: graph {i} has no edge weight (edgeless "
                "graphs short-circuit in louvain_many, not here)")
        constant[i] = wdt.type(1.0 / t2)
        row_valid[i] = True
        nv_real[i] = g.num_vertices
        ne_real[i] = g.num_edges
        tw2[i] = t2

    return BatchedSlab(
        src=src, dst=dst, w=w, real_mask=real_mask, constant=constant,
        row_valid=row_valid, nv_real=nv_real, ne_real=ne_real, tw2=tw2,
        nv_pad=nv_pad, ne_pad=ne_pad, n_jobs=n,
    )


# --- mixed-class sub-row packing (ISSUE 20) --------------------------------
# Under a skewed serving mix the small class queues behind its own
# BATCH_SIZES row cap while the big class's rows linger underfull.  A
# SubRowLayout packs 2^k small-class graphs into ONE row of the
# k-notches-larger class's slab SHAPES: sub-row s owns the vertex ids
# [s*nv_sub, (s+1)*nv_sub) and (at pack time) the edge slots
# [s*ne_sub, (s+1)*ne_sub).  The vertex-offset algebra IS the fence:
# packed graphs share no edges across a seam, community ids start at
# identity (in-segment) and the Louvain move step only ever proposes
# NEIGHBOR communities, so no id can cross a seam at any phase — which
# is what makes per-tenant labels bit-identical to the B=1 run by
# construction (louvain/subrow.py carries the per-sub-row constants,
# Q and convergence masks through the compiled loop).


@dataclasses.dataclass(frozen=True)
class SubRowLayout:
    """Static sub-row geometry of a packed row: the ONLY layout fact
    that may enter a compile key (``n_sub`` — which tenants occupy
    which sub-row is batch CONTENT and must never become a static)."""

    n_sub: int        # pow2 >= 2 sub-rows per packed row
    sub_class: tuple  # (nv_sub, ne_sub) — the small class being packed

    def __post_init__(self):
        n = self.n_sub
        if n < 2 or (n & (n - 1)):
            raise ValueError(f"SubRowLayout: n_sub={n} must be a pow2 >= 2")

    @property
    def nv_sub(self) -> int:
        return int(self.sub_class[0])

    @property
    def ne_sub(self) -> int:
        return int(self.sub_class[1])

    @property
    def row_class(self) -> tuple:
        """The packed row's slab class: exactly ``n_sub`` times the sub
        class in BOTH dimensions (the "ne_pad differs by exactly the
        class ratio" rule — pow2 classes make the ratio exact)."""
        return (self.n_sub * self.nv_sub, self.n_sub * self.ne_sub)

    def vertex_offset(self, s: int) -> int:
        return s * self.nv_sub

    def edge_offset(self, s: int) -> int:
        return s * self.ne_sub

    def vertex_fences(self) -> tuple:
        """The ``n_sub + 1`` vertex-id seam boundaries; sub-row ``s``
        owns ids in ``[fences[s], fences[s+1])``.  Community ids of a
        packed row must stay inside their sub-row's fence interval at
        every phase (tests/test_subrow.py pins this adversarially)."""
        return tuple(s * self.nv_sub for s in range(self.n_sub + 1))


def subrow_layout_for(sub_class: tuple, row_class: tuple) -> SubRowLayout | None:
    """The layout packing ``sub_class`` rows into ``row_class`` rows, or
    None when the classes are not an exact pow2 ratio in BOTH dimensions
    (per-dimension ratios that disagree cannot fence cleanly)."""
    nv_s, ne_s = sub_class
    nv_r, ne_r = row_class
    if nv_s <= 0 or ne_s <= 0 or nv_r % nv_s or ne_r % ne_s:
        return None
    n = nv_r // nv_s
    if n < 2 or (n & (n - 1)) or ne_r // ne_s != n:
        return None
    return SubRowLayout(n_sub=n, sub_class=(int(nv_s), int(ne_s)))


@dataclasses.dataclass
class PackedSubRows:
    """B packed rows of ``layout.row_class``, each holding up to
    ``layout.n_sub`` small-class graphs at the layout's offsets.

    Slab conventions match :class:`BatchedSlab` at the ROW class (src
    padding sentinel == row nv_pad, dst/w pad 0) so the packed batch
    flows through the same upload/mesh machinery; everything per-GRAPH
    (constants, real counts, validity) is ``[b_pad, n_sub]``.  Jobs
    occupy sub-rows in row-major order: job j sits at
    ``(j // n_sub, j % n_sub)``."""

    src: np.ndarray        # [b_pad, ne_pad] int32 (row class)
    dst: np.ndarray        # [b_pad, ne_pad] int32
    w: np.ndarray          # [b_pad, ne_pad] float32
    real_mask: np.ndarray  # [b_pad, nv_pad] bool
    constants: np.ndarray  # [b_pad, n_sub] 1/(2m) per sub-row (0 on pads)
    sub_valid: np.ndarray  # [b_pad, n_sub] bool
    nv_real: np.ndarray    # [b_pad, n_sub] int64
    ne_real: np.ndarray    # [b_pad, n_sub] int64
    tw2: np.ndarray        # [b_pad, n_sub] float64
    layout: SubRowLayout
    n_jobs: int

    @property
    def b_pad(self) -> int:
        return int(self.src.shape[0])

    @property
    def nv_pad(self) -> int:
        return int(self.layout.row_class[0])

    @property
    def ne_pad(self) -> int:
        return int(self.layout.row_class[1])

    @property
    def slab_class(self) -> tuple:
        return self.layout.row_class

    @property
    def row_valid(self) -> np.ndarray:
        return self.sub_valid.any(axis=1)

    @property
    def pack_util(self) -> float:
        """Fraction of batch ROWS carrying at least one real job."""
        return float(self.row_valid.sum()) / max(self.b_pad, 1)

    @property
    def subrow_util(self) -> float:
        """Real graphs over TOTAL sub-row capacity — the honest
        occupancy of a merged batch (``pack_util`` saturates at 1.0 the
        moment every row holds one tenant)."""
        return self.n_jobs / max(self.b_pad * self.layout.n_sub, 1)


def pack_subrows(graphs, layout: SubRowLayout, *,
                 b_pad: int | None = None) -> PackedSubRows:
    """Pack small-class graphs into sub-rows of ``layout.row_class``
    rows (job j -> row ``j // n_sub``, sub-row ``j % n_sub``).

    Every graph must canonicalize INTO ``layout.sub_class`` (its own
    class may be smaller — it pads up, exactly as a pinned
    :func:`batch_slabs` class would).  Each sub-row is built by the SAME
    ``DistGraph.build`` call its solo slab uses, then embedded at the
    layout offsets with vertex ids shifted by ``vertex_offset(s)`` and
    its padding edges rewritten to the ROW sentinel — the only
    transformations are an id shift and a sentinel rename, which is the
    fence-construction half of the bit-identity argument."""
    if not graphs:
        raise ValueError("pack_subrows: empty graph list")
    nv_sub, ne_sub = layout.sub_class
    nv_pad, ne_pad = layout.row_class
    n_sub = layout.n_sub
    too_big = [c for c in sorted({slab_class_of(g) for g in graphs})
               if c[0] > nv_sub or c[1] > ne_sub]
    if too_big:
        raise ValueError(
            f"pack_subrows: graphs of classes {too_big} do not fit the "
            f"sub class {layout.sub_class}")

    import jax

    if jax.config.jax_enable_x64 and any(
            np.dtype(g.policy.weight_dtype) == np.float64 for g in graphs):
        raise ValueError(
            "pack_subrows: wide-policy (f64-weight) graphs under "
            "jax_enable_x64 keep f64 on the per-graph drivers — serve "
            "them through louvain_phases (same refusal as batch_slabs)")

    n = len(graphs)
    rows = -(-n // n_sub)
    bp = batch_pad(rows) if b_pad is None else int(b_pad)
    if bp < rows:
        raise ValueError(f"pack_subrows: b_pad={bp} < {rows} packed rows")
    wdt = np.dtype(np.float32)
    src = np.full((bp, ne_pad), nv_pad, dtype=np.int32)
    dst = np.zeros((bp, ne_pad), dtype=np.int32)
    w = np.zeros((bp, ne_pad), dtype=wdt)
    real_mask = np.zeros((bp, nv_pad), dtype=bool)
    constants = np.zeros((bp, n_sub), dtype=wdt)
    sub_valid = np.zeros((bp, n_sub), dtype=bool)
    nv_real = np.zeros((bp, n_sub), dtype=np.int64)
    ne_real = np.zeros((bp, n_sub), dtype=np.int64)
    tw2 = np.zeros((bp, n_sub), dtype=np.float64)

    for j, g in enumerate(graphs):
        i, s = j // n_sub, j % n_sub
        dg = DistGraph.build(g, 1, min_nv_pad=nv_sub, min_ne_pad=ne_sub)
        assert (dg.nv_pad, dg.ne_pad) == (nv_sub, ne_sub)
        sh = dg.shards[0]
        voff, eoff = layout.vertex_offset(s), layout.edge_offset(s)
        s_src = np.asarray(sh.src, dtype=np.int32)
        s_dst = np.asarray(sh.dst, dtype=np.int32)
        s_w = np.asarray(sh.w, dtype=wdt)
        pad = s_src >= nv_sub
        # Real edges shift into the sub-row's fence interval; the sub
        # slab's padding rows rename their sentinel to the ROW sentinel
        # (dst/w already carry the 0-pad convention).
        src[i, eoff:eoff + ne_sub] = np.where(
            pad, np.int32(nv_pad), s_src + np.int32(voff))
        dst[i, eoff:eoff + ne_sub] = np.where(pad, 0, s_dst + np.int32(voff))
        w[i, eoff:eoff + ne_sub] = np.where(pad, wdt.type(0), s_w)
        real_mask[i, voff:voff + nv_sub] = dg.vertex_mask()
        t2 = g.total_edge_weight_twice()
        if t2 <= 0:
            raise ValueError(
                f"pack_subrows: graph {j} has no edge weight (edgeless "
                "graphs short-circuit before packing, as in louvain_many)")
        constants[i, s] = wdt.type(1.0 / t2)
        sub_valid[i, s] = True
        nv_real[i, s] = g.num_vertices
        ne_real[i, s] = g.num_edges
        tw2[i, s] = t2

    return PackedSubRows(
        src=src, dst=dst, w=w, real_mask=real_mask, constants=constants,
        sub_valid=sub_valid, nv_real=nv_real, ne_real=ne_real, tw2=tw2,
        layout=layout, n_jobs=n,
    )


def unpack_subrows(packed: PackedSubRows, comm_all: np.ndarray,
                   prev_mod: np.ndarray):
    """Per-tenant label/Q extraction from a packed run's final state:
    ``comm_all`` [b_pad, nv_pad] composed labels in ORIGINAL layout
    offsets, ``prev_mod`` [b_pad, n_sub] per-sub-row Q.  Returns a list
    of ``(labels int64 [nv_real], q float)`` in job order — labels are
    the sub-row slice minus its vertex offset, exactly the prefix-slice
    unpack of the plain batched driver shifted by the fence base."""
    out = []
    lay = packed.layout
    for j in range(packed.n_jobs):
        i, s = j // lay.n_sub, j % lay.n_sub
        voff = lay.vertex_offset(s)
        nv = int(packed.nv_real[i, s])
        labels = np.asarray(
            comm_all[i, voff:voff + nv], dtype=np.int64) - voff
        out.append((labels, float(prev_mod[i, s])))
    return out


# --- batched bucket plans (ISSUE 10) ---------------------------------------
# The fused batched program sweeps via the packed 2-channel lax.sort — the
# exact per-row cost the per-graph bucketed engine exists to avoid.  To run
# B tenants through ONE vmapped bucketed step, the per-graph BucketPlans
# (per-graph kept widths, per-graph pow2 row counts) must be padded to a
# COMMON cross-graph geometry: kept widths = the union across the batch,
# each width's row count = the batch max (counts are pow2 already, so the
# max is pow2), absent rows flag-masked with the same verts == nv_pad
# sentinel that retires converged rows' slabs.  The result stacks to
# [B, rows, width] per-width matrices — the multi-tenant analog of
# louvain/bucketed.py::build_stacked_plans' per-SHARD common padding.


@dataclasses.dataclass(frozen=True)
class BucketShape:
    """Static geometry of a batched bucket plan: the compile key of the
    batched bucketed phase program beyond ``(class, B)``.  Pinning one
    shape across many batches (``bucket_shape_for`` over the whole job
    set — the bench does) keeps every batch on one compiled program even
    when per-batch degree histograms differ."""

    widths: tuple    # kept bucket widths, ascending
    rows: tuple      # per-width common padded row count (pow2)
    heavy_pad: int   # heavy-residual slab length (pow2, >= 8)

    def fits(self, other: "BucketShape") -> bool:
        """True when every requirement of ``other`` fits inside self."""
        mine = dict(zip(self.widths, self.rows))
        return (all(w in mine and r <= mine[w]
                    for w, r in zip(other.widths, other.rows))
                and other.heavy_pad <= self.heavy_pad)


def union_shapes(a: BucketShape, b: BucketShape) -> BucketShape:
    """The smallest geometry covering both ``a`` and ``b`` (union of
    kept widths, per-width max rows, max heavy pad).  The serving queue
    pins each bin's geometry to the grow-only union of every batch it
    has dispatched: a repeat of any seen geometry then reuses the
    compiled phase-0 program, and because shapes only grow — and are
    bounded by the slab class — the compile count per bin converges
    instead of churning with per-batch degree histograms."""
    rows: dict = {}
    for shape in (a, b):
        for w, r in zip(shape.widths, shape.rows):
            rows[w] = max(rows.get(w, 0), r)
    ws = tuple(sorted(rows))
    return BucketShape(widths=ws, rows=tuple(rows[w] for w in ws),
                       heavy_pad=max(a.heavy_pad, b.heavy_pad))


@dataclasses.dataclass
class BatchedBucketPlan:
    """Per-graph BucketPlans padded to one cross-graph geometry and
    stacked on the batch axis, ready for the vmapped bucketed step.

    Pad rows (``row_valid`` false) and absent (graph, width) pairs carry
    pure plan padding: ``verts == nv_pad`` rows that every scatter drops
    and the assembly perm never points at — bit-for-bit the same masking
    contract as the retired-slab rows of the fused batched phase."""

    buckets: list            # (verts [B, Nb], dst [B, Nb, D], w [B, Nb, D])
    heavy: tuple             # (src [B, H], dst [B, H], w [B, H])
    self_loop: np.ndarray    # [B, nv_pad]
    perm: np.ndarray         # [B, nv_pad] int32 assembly permutation
    shape: BucketShape
    nv_pad: int


def _plan_shape_req(deg: np.ndarray, widths: tuple) -> tuple:
    """(per-width padded row counts [len(widths)], heavy_pad) that
    BucketPlan.build would produce for a vertex-degree vector — the
    slab-free derivation behind ``bucket_shape_for``.  It REPLICATES
    BucketPlan.build's binning/padding rules (width bins, pow2 row
    rounding, the heavy pow2-with-floor-8 pad) rather than calling
    them, so the parity is pinned by test, not construction:
    tests/test_batched.py::test_batch_bucket_plans_geometry asserts the
    degree-derived shape equals the one batch_bucket_plans reads off
    the built plans — a padding-rule change that edits only one side
    fails there."""
    widths_arr = np.asarray(widths, dtype=np.int64)
    rows = np.zeros(len(widths), dtype=np.int64)
    prev = 0
    for k, width in enumerate(widths):
        nb = int(np.count_nonzero((deg > prev) & (deg <= width)))
        prev = width
        if nb:
            rows[k] = 1 << int(nb - 1).bit_length() if nb > 1 else 1
    n_h = int(deg[deg > widths_arr[-1]].sum())
    heavy_pad = max(int(2 ** np.ceil(np.log2(max(n_h, 1)))), 8) if n_h else 8
    return rows, heavy_pad


def bucket_shape_for(graphs, widths: tuple | None = None) -> BucketShape:
    """The common :class:`BucketShape` covering every graph of a job set
    — pure host degree arithmetic (no slab or plan is built), so a bench
    or a shape-pinning caller can compute it over thousands of jobs
    cheaply.  Width binning depends only on vertex degrees, which the
    packed slab preserves, so this matches what ``batch_bucket_plans``
    derives from the slabs themselves (shared ``_plan_shape_req``)."""
    from cuvite_tpu.louvain.bucketed import DEFAULT_BUCKETS

    widths = DEFAULT_BUCKETS if widths is None else tuple(widths)
    rows = np.zeros(len(widths), dtype=np.int64)
    heavy_pad = 8
    for g in graphs:
        r, h = _plan_shape_req(np.asarray(g.degrees(), dtype=np.int64),
                               widths)
        rows = np.maximum(rows, r)
        heavy_pad = max(heavy_pad, h)
    kept = rows > 0
    return BucketShape(
        widths=tuple(int(w) for w, k in zip(widths, kept) if k),
        rows=tuple(int(r) for r in rows[kept]),
        heavy_pad=int(heavy_pad),
    )


def batch_bucket_plans(batch: BatchedSlab,
                       shape: BucketShape | None = None
                       ) -> BatchedBucketPlan:
    """Build one :class:`BucketPlan` per batch row AT PACK TIME and pad
    them to a common cross-graph geometry (see module note above).

    ``shape``: pin an explicit geometry (every row pads UP into it; a
    row needing a width/row-count/heavy-pad the shape lacks raises) —
    the bench pins the job-set union so every chunk reuses one compiled
    phase-0 program.  Default: the union/batch-max geometry of THIS
    batch.  Pad rows are all-padding slabs, so their plans are empty —
    they contribute only sentinel rows that cost two masked sweeps."""
    from cuvite_tpu.louvain.bucketed import (
        DEFAULT_BUCKETS,
        BucketPlan,
        build_assemble_perm,
    )

    nv = batch.nv_pad
    B = batch.b_pad
    widths = DEFAULT_BUCKETS
    # Pad rows included: BucketPlan.build on an all-padding slab is the
    # empty plan (no buckets, padding heavy, zero self-loops) — uniform
    # construction keeps the stacking loop branch-free.
    plans = [
        BucketPlan.build(batch.src[i], batch.dst[i], batch.w[i],
                         nv_local=nv, base=0, widths=widths)
        for i in range(B)
    ]
    by_width = [{b.width: b for b in p.buckets} for p in plans]
    req = np.zeros(len(widths), dtype=np.int64)
    for bw in by_width:
        for k, w in enumerate(widths):
            if w in bw:
                req[k] = max(req[k], len(bw[w].verts))
    heavy_req = max(max((len(p.heavy_src) for p in plans), default=8), 8)
    kept = req > 0
    need = BucketShape(
        widths=tuple(int(w) for w, k in zip(widths, kept) if k),
        rows=tuple(int(r) for r in req[kept]),
        heavy_pad=int(heavy_req),
    )
    if shape is None:
        shape = need
    elif not shape.fits(need):
        raise ValueError(
            f"batch_bucket_plans: batch needs geometry {need} which does "
            f"not fit the pinned shape {shape} — pin a shape covering "
            "the whole job set (core.batch.bucket_shape_for)")

    # Weights stay f32 — deliberately NOT the per-graph upload's uint8
    # unit-weight compression: that eligibility is a property of batch
    # CONTENT, and a per-bucket dtype flip would fold content into the
    # compile key (measured: one mixed-weight tenant in an otherwise
    # unit-weight class recompiles the whole phase-0 program).  Serving
    # wants a stable (class, B, geometry) key more than the 4x upload
    # saving on unit-weight buckets.
    wdt = np.dtype(np.float32)
    buckets = []
    for width, nb in zip(shape.widths, shape.rows):
        verts = np.full((B, nb), nv, dtype=np.int64)
        dmat = np.zeros((B, nb, width), dtype=np.int32)
        wmat = np.zeros((B, nb, width), dtype=wdt)
        for i, bw in enumerate(by_width):
            if width in bw:
                b = bw[width]
                n = len(b.verts)
                verts[i, :n] = b.verts
                dmat[i, :n] = b.dst
                wmat[i, :n] = b.w
        buckets.append((verts, dmat, wmat))
    hs = np.full((B, shape.heavy_pad), nv, dtype=np.int32)
    hd = np.zeros((B, shape.heavy_pad), dtype=np.int32)
    hw = np.zeros((B, shape.heavy_pad), dtype=wdt)
    self_loop = np.zeros((B, nv), dtype=wdt)
    for i, p in enumerate(plans):
        hs[i, : len(p.heavy_src)] = p.heavy_src
        hd[i, : len(p.heavy_dst)] = p.heavy_dst
        hw[i, : len(p.heavy_w)] = p.heavy_w
        self_loop[i] = p.self_loop
    perm = np.stack([
        build_assemble_perm([bk[0][i] for bk in buckets], nv)
        for i in range(B)
    ]) if B else np.zeros((0, nv), dtype=np.int32)
    return BatchedBucketPlan(
        buckets=buckets, heavy=(hs, hd, hw), self_loop=self_loop,
        perm=perm, shape=shape, nv_pad=nv,
    )
