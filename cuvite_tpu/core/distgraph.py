"""Vertex-sharded distributed graph.

Equivalent of the reference `DistGraph` (/root/reference/distgraph.hpp:27-57):
global sizes + a partition table ``parts[nshards+1]`` of contiguous vertex
ranges, with owner lookup and local<->global translation
(/root/reference/distgraph.hpp:180-222).

The TPU-native difference: instead of per-rank local CSR objects, the
partition materializes one set of **equal-size padded device slabs** — an
edge-parallel struct-of-arrays `(src, dst, w, mask)` per shard, all shards the
same shape — so a single `shard_map`-jitted step runs the whole mesh SPMD with
static shapes.  Padding edges carry ``src == nv_pad`` (an out-of-range segment
id, dropped by segment sums) and zero weight.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from cuvite_tpu.core.graph import Graph
from cuvite_tpu.core.types import Policy, next_pow2


def uniform_parts(num_vertices: int, nshards: int) -> np.ndarray:
    """Contiguous near-equal vertex ranges (cf. /root/reference/distgraph.cpp:115-121)."""
    chunk = num_vertices // nshards
    rem = num_vertices % nshards
    sizes = np.full(nshards, chunk, dtype=np.int64)
    sizes[:rem] += 1
    parts = np.zeros(nshards + 1, dtype=np.int64)
    np.cumsum(sizes, out=parts[1:])
    return parts


def balanced_parts_from_offsets(offsets, nv: int, ne: int,
                                nshards: int) -> np.ndarray:
    """Edge-balanced contiguous ranges from a CSR offset array — works on a
    memmap, so the per-host ingest path shares the exact cut rule."""
    targets = (np.arange(1, nshards, dtype=np.int64) * ne) // nshards
    cuts = np.searchsorted(offsets[1:], targets, side="left") + 1
    parts = np.concatenate([[0], np.clip(cuts, 0, nv), [nv]]).astype(np.int64)
    # Enforce monotonicity if some shard would be empty.
    np.maximum.accumulate(parts, out=parts)
    return parts


def balanced_parts(graph: Graph, nshards: int) -> np.ndarray:
    """Edge-balanced contiguous ranges: each shard owns ~ne/nshards edges
    (cf. balanceEdges, /root/reference/distgraph.cpp:22-66, the `-b` flag)."""
    return balanced_parts_from_offsets(
        graph.offsets, graph.num_vertices, graph.num_edges, nshards)


@dataclasses.dataclass
class Shard:
    """One device's padded edge slab plus its owned vertex range.

    Slab arrays are host numpy on the ingest path; on the device-resident
    coarsening path (:meth:`DistGraph.from_device_slab`) they are jax
    arrays already living in device memory."""

    base: int       # first owned global vertex id
    bound: int      # one past last owned global vertex id
    src: np.ndarray   # [ne_pad] LOCAL source index in [0, nv_pad); pad = nv_pad
    dst: np.ndarray   # [ne_pad] GLOBAL tail vertex id; pad = 0
    w: np.ndarray     # [ne_pad] weight; pad = 0
    n_real_edges: int


@dataclasses.dataclass
class SlabMeta:
    """Stands in for ``DistGraph.graph`` when the graph exists only as a
    device-resident slab (no host CSR was ever built): the scalar facts
    the drivers actually consult, and nothing that would imply O(E) host
    data.  ``total_edge_weight_twice`` is carried through coarsening
    unchanged — community aggregation preserves 2m exactly
    (rebuild.cpp:430-454), which is what keeps the gain constant and the
    modularity scale identical across phases."""

    num_vertices: int
    num_edges: int
    policy: Policy
    tw2: float

    def total_edge_weight_twice(self) -> float:
        return self.tw2


@dataclasses.dataclass
class DistGraph:
    """Global graph + partition into equal-shape shards.

    `nv_pad` is the per-shard owned-vertex count after padding (same for every
    shard); `ne_pad` is the per-shard edge-slab length.  Total padded vertex
    space is ``nshards * nv_pad``; global ids are remapped so shard s owns
    ``[s*nv_pad, s*nv_pad + (parts[s+1]-parts[s]))`` — i.e. padding vertices
    are interleaved at the tail of each shard's range, and arrays for the
    padded id space concatenate shard slices directly.
    """

    graph: Graph             # host CSR, or SlabMeta on the device path
    parts: np.ndarray        # [nshards+1] original-id partition table
    nshards: int
    nv_pad: int              # owned vertices per shard, padded
    ne_pad: int              # edge slots per shard, padded
    shards: list              # list[Shard]
    old_to_pad: np.ndarray   # [nv] original global id -> padded global id
    pad_to_old: np.ndarray   # [nshards*nv_pad] padded id -> original id (or -1)
    device_resident: bool = False  # slab arrays are jax device arrays

    @property
    def total_vertices(self) -> int:
        return self.graph.num_vertices

    @property
    def total_padded_vertices(self) -> int:
        return self.nshards * self.nv_pad

    @property
    def total_edges(self) -> int:
        return self.graph.num_edges

    def owner_of_padded(self, v: int) -> int:
        return v // self.nv_pad

    def release_slabs(self) -> None:
        """Drop the O(E) edge-slab arrays, keeping Shard metadata.

        The single-shard bucketed engines consume the slab only during
        plan construction (the bucket matrices replace it on device), so
        after PhaseRunner init the slab is dead weight — at benchmark
        scale, tens of GB of it (tools/scale_model.md).  Callers that
        still need the edges (sort/fused engines, exchange-plan builds,
        per-host coarse_edges) simply never call this."""
        for sh in self.shards:
            sh.src = sh.dst = sh.w = None

    @staticmethod
    def build(
        graph: Graph,
        nshards: int,
        balanced: bool = False,
        pad_pow2: bool = True,
        min_nv_pad: int = 1,
        min_ne_pad: int = 1,
        pad_edges: bool = True,
    ) -> "DistGraph":
        """``min_nv_pad``/``min_ne_pad`` set a floor on the padded shapes so
        successive coarsened phases (whose graphs shrink fast) land on the
        same compiled executable instead of recompiling per phase.

        ``pad_edges=False`` (single shard only): skip the pow2/floor
        padding of the edge slab and ALIAS the CSR's tails/weights arrays
        as the slab's dst/w.  Correct only for consumers that never
        upload the slab and never rely on tail padding — i.e. the
        bucketed engines, whose plan builder streams the slab once.  The
        pow2 slab pad exists for the sort engine's executable reuse and
        costs up to 2x the real edge bytes (measured 2.06x at R-MAT 24),
        so the slab-free path is what lets benchmark-scale graphs fit a
        single host (tools/scale_model.md)."""
        if not pad_edges and nshards != 1:
            raise ValueError(
                "pad_edges=False is the single-shard slab-free layout; "
                "multi-shard slabs must share padded shapes")
        nv = graph.num_vertices
        parts = balanced_parts(graph, nshards) if balanced else uniform_parts(nv, nshards)
        owned = np.diff(parts)
        nv_pad = int(owned.max()) if len(owned) else 1
        nv_pad = max(nv_pad, min_nv_pad)
        if pad_pow2:
            nv_pad = next_pow2(max(nv_pad, 1))

        # Remap original ids -> padded id space (shard-contiguous).
        old_to_pad = np.empty(nv, dtype=np.int64)
        pad_to_old = np.full(nshards * nv_pad, -1, dtype=np.int64)
        for s in range(nshards):
            lo, hi = int(parts[s]), int(parts[s + 1])
            old_to_pad[lo:hi] = s * nv_pad + np.arange(hi - lo)
            pad_to_old[s * nv_pad : s * nv_pad + (hi - lo)] = np.arange(lo, hi)

        counts = [
            int(graph.offsets[parts[s + 1]] - graph.offsets[parts[s]])
            for s in range(nshards)
        ]
        ne_pad = max(max(counts) if counts else 1, 1, min_ne_pad)
        if pad_edges:
            if pad_pow2:
                ne_pad = next_pow2(ne_pad)
        elif nshards == 1:
            ne_pad = max(graph.num_edges, 1)

        vdt = graph.policy.vertex_dtype
        wdt = graph.policy.weight_dtype
        shards = []
        if nshards == 1 and not pad_edges and graph.num_edges == ne_pad:
            # Slab-free layout: dst/w alias the CSR arrays (policy dtypes
            # already match; astype(copy=False) is a no-op view), only the
            # expanded src is materialized.  No padding tail exists.
            n = graph.num_edges
            shards.append(Shard(
                base=0, bound=nv,
                src=np.repeat(np.arange(nv, dtype=vdt), graph.degrees()),
                dst=graph.tails.astype(vdt, copy=False),
                w=graph.weights.astype(wdt, copy=False),
                n_real_edges=n))
        elif nshards == 1:
            # Single shard: the padded id space IS the original id space
            # (old_to_pad = identity), so the generic path's O(E) int64
            # expand + two fancy-index remaps reduce to plain copies in the
            # device dtype — this runs once per phase and was a visible
            # slice of benchmark-scale host time.
            n = graph.num_edges
            src_l = np.full(ne_pad, nv_pad, dtype=vdt)
            dst_g = np.zeros(ne_pad, dtype=vdt)
            w = np.zeros(ne_pad, dtype=wdt)
            src_l[:n] = np.repeat(
                np.arange(nv, dtype=vdt), graph.degrees())
            dst_g[:n] = graph.tails
            w[:n] = graph.weights
            shards.append(Shard(base=0, bound=nv, src=src_l, dst=dst_g,
                                w=w, n_real_edges=n))
        else:
            sources = graph.sources().astype(np.int64)
            for s in range(nshards):
                e0 = int(graph.offsets[parts[s]])
                e1 = int(graph.offsets[parts[s + 1]])
                n = e1 - e0
                src_l = np.full(ne_pad, nv_pad, dtype=vdt)  # out-of-range pad
                dst_g = np.zeros(ne_pad, dtype=vdt)
                w = np.zeros(ne_pad, dtype=wdt)
                src_l[:n] = (old_to_pad[sources[e0:e1]] - s * nv_pad).astype(vdt)
                dst_g[:n] = old_to_pad[graph.tails[e0:e1].astype(np.int64)].astype(vdt)
                w[:n] = graph.weights[e0:e1]
                shards.append(
                    Shard(
                        base=int(parts[s]),
                        bound=int(parts[s + 1]),
                        src=src_l,
                        dst=dst_g,
                        w=w,
                        n_real_edges=n,
                    )
                )
        return DistGraph(
            graph=graph,
            parts=parts,
            nshards=nshards,
            nv_pad=nv_pad,
            ne_pad=ne_pad,
            shards=shards,
            old_to_pad=old_to_pad,
            pad_to_old=pad_to_old,
        )

    @staticmethod
    def from_device_slab(
        src, dst, w, *,
        num_vertices: int,
        num_edges: int,
        nv_pad: int,
        ne_pad: int,
        policy: Policy,
        total_weight_twice: float,
    ) -> "DistGraph":
        """Re-derive single-shard metadata around an ALREADY device-resident
        padded slab — the output of coarsen/device.py — without a host
        rebuild.  The O(E) arrays never leave HBM: only the O(V) id-space
        tables (identity here: a coarse graph's vertex ids ARE the dense
        community ids 0..nc-1) and the scalar facts live on the host.

        src/dst/w: jax arrays of shape [ne_pad], same layout contract as
        :meth:`build`'s single-shard slab (src sorted ascending, pad rows
        src == nv_pad / w == 0).  ``total_weight_twice`` is the ORIGINAL
        graph's 2m (invariant under coarsening)."""
        meta = SlabMeta(num_vertices=num_vertices, num_edges=num_edges,
                        policy=policy, tw2=float(total_weight_twice))
        shard = Shard(base=0, bound=num_vertices, src=src, dst=dst, w=w,
                      n_real_edges=num_edges)
        old_to_pad = np.arange(num_vertices, dtype=np.int64)
        pad_to_old = np.full(nv_pad, -1, dtype=np.int64)
        pad_to_old[:num_vertices] = old_to_pad
        return DistGraph(
            graph=meta,
            parts=np.asarray([0, num_vertices], dtype=np.int64),
            nshards=1,
            nv_pad=nv_pad,
            ne_pad=ne_pad,
            shards=[shard],
            old_to_pad=old_to_pad,
            pad_to_old=pad_to_old,
            device_resident=True,
        )

    # ---- stacked views for device placement -------------------------------

    def stacked_edges(self):
        """Return (src, dst, w) each of shape [nshards*ne_pad], shard-major,
        ready to be sharded along axis 0 of a 1-D mesh.  On the
        device-resident path the single shard's jax arrays are returned
        as-is (no host concatenate, no transfer)."""
        if self.device_resident:
            sh = self.shards[0]
            return sh.src, sh.dst, sh.w
        src = np.concatenate([sh.src for sh in self.shards])
        dst = np.concatenate([sh.dst for sh in self.shards])
        w = np.concatenate([sh.w for sh in self.shards])
        return src, dst, w

    def padded_weighted_degrees(self) -> np.ndarray:
        """vDegree in the padded id space (padding vertices get 0).  On the
        device-resident path this is one jitted segment sum over the slab
        in HBM (a jax array comes back, not numpy)."""
        if self.device_resident:
            from cuvite_tpu.coarsen.device import device_weighted_degrees

            sh = self.shards[0]
            return device_weighted_degrees(sh.src, sh.w, nv_pad=self.nv_pad)
        wd = self.graph.weighted_degrees().astype(np.float64)
        out = np.zeros(self.total_padded_vertices, dtype=np.float64)
        out[self.old_to_pad] = wd
        return out.astype(self.graph.policy.weight_dtype)

    def vertex_mask(self) -> np.ndarray:
        """Boolean mask over the padded id space marking real vertices."""
        return self.pad_to_old >= 0
