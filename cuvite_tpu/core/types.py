"""Scalar type policy.

The reference switches vertex-id and weight width with a single compile-time
macro `USE_32_BIT_GRAPH` (/root/reference/edge.hpp:10-20).  Here the same
choice is a runtime `Policy` object threaded through graph construction and
kernels.  Defaults are TPU-friendly: int32 ids (graphs up to 2^31-1 vertices
per shard) and float32 weights; float64 accumulation is available on CPU for
oracle tests when `jax_enable_x64` is set.
"""

from __future__ import annotations

import dataclasses

import numpy as np

# Driver safety nets (cf. /root/reference/utils.hpp:17-19, main.cpp:486-494).
TERMINATION_PHASE_COUNT = 200
MAX_TOTAL_ITERATIONS = 10_000

# Per-phase convergence telemetry: the device phase loops accumulate one
# (dQ, moved, overflow) row per iteration into fixed-size buffers of this
# many rows, synced to the host ONCE at phase end together with the
# existing convergence scalars (obs/convergence.py).  Phases running more
# iterations than this drop the tail rows (the PhaseConvergence carries a
# ``truncated`` flag); real phases converge in well under 128 iterations
# (the reference caps a whole RUN at MAX_TOTAL_ITERATIONS).  Static, so
# every phase shares one compiled loop regardless of its iteration count.
CONV_ROWS_CAP = 128

# Early-termination constants (cf. /root/reference/louvain.hpp:74-80).
ET_CUTOFF = 0.90  # fraction of frozen vertices that stops the iteration loop
P_CUTOFF = 0.02   # probability floor below which a vertex freezes (ET modes 2/4)


@dataclasses.dataclass(frozen=True)
class Policy:
    """Dtype policy for graph arrays and kernel accumulators."""

    vertex_dtype: np.dtype = np.dtype(np.int32)
    weight_dtype: np.dtype = np.dtype(np.float32)
    # Dtype used for global scalar reductions (modularity terms). float32 is
    # fine up to ~10^7 edges; large graphs should use float64 on CPU oracles
    # and pairwise/tree summation on TPU (jnp.sum is tree-based on TPU).
    accum_dtype: np.dtype = np.dtype(np.float32)

    @property
    def vertex_np(self) -> np.dtype:
        return self.vertex_dtype

    @property
    def weight_np(self) -> np.dtype:
        return self.weight_dtype

    def sentinel_vertex(self) -> int:
        """Max value of the vertex dtype, used as +inf for segment-min."""
        return int(np.iinfo(self.vertex_dtype).max)


def default_policy() -> Policy:
    return Policy()


def wide_policy() -> Policy:
    """64-bit ids + weights: the `USE_32_BIT_GRAPH`-off configuration."""
    return Policy(
        vertex_dtype=np.dtype(np.int64),
        weight_dtype=np.dtype(np.float64),
        accum_dtype=np.dtype(np.float64),
    )


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (>=1). Used to pad shapes so phases with
    shrinking graphs reuse compiled executables instead of recompiling."""
    if n <= 1:
        return 1
    return 1 << (int(n - 1).bit_length())
