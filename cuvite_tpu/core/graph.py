"""Host-side CSR graph container.

Equivalent of the reference `Graph` (/root/reference/graph.hpp:27-57): an
adjacency structure `edgeListIndexes[nv+1]` plus an edge array of
`{tail, weight}` pairs.  Here the struct-of-arrays layout is native: separate
`offsets`, `tails`, `weights` numpy arrays, which is also exactly the layout
device kernels want.

Graphs are undirected and stored with both directions present (the Vite
binary format stores each undirected edge twice, once per endpoint), so
``sum(weights) == 2m`` and per-vertex weighted degree is a plain segment sum.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from cuvite_tpu.core.types import Policy, default_policy


@dataclasses.dataclass
class Graph:
    """CSR graph: ``offsets[nv+1]``, ``tails[ne]``, ``weights[ne]``."""

    offsets: np.ndarray  # [nv+1] vertex dtype
    tails: np.ndarray    # [ne]   vertex dtype (global ids)
    weights: np.ndarray  # [ne]   weight dtype
    policy: Policy = dataclasses.field(default_factory=default_policy)

    def __post_init__(self) -> None:
        self.offsets = np.ascontiguousarray(self.offsets, dtype=np.int64)
        self.tails = np.ascontiguousarray(self.tails, dtype=self.policy.vertex_dtype)
        self.weights = np.ascontiguousarray(self.weights, dtype=self.policy.weight_dtype)

    @property
    def num_vertices(self) -> int:
        return len(self.offsets) - 1

    @property
    def num_edges(self) -> int:
        """Number of directed edge slots (2x the undirected edge count)."""
        return len(self.tails)

    def degrees(self) -> np.ndarray:
        """Per-vertex edge counts."""
        return np.diff(self.offsets)

    def weighted_degrees(self) -> np.ndarray:
        """Per-vertex sum of incident edge weights, self-loops included
        (cf. distSumVertexDegree, /root/reference/louvain.cpp:2126-2151)."""
        from cuvite_tpu import native

        if self.num_edges >= native.MIN_NATIVE_EDGES and native.available():
            # Same f64 slab-order accumulation, without materializing the
            # expanded O(E) source array + f64 weight copy.
            return native.weighted_degrees(
                self.offsets, self.weights).astype(self.policy.weight_dtype)
        return np.bincount(
            self.sources(), weights=self.weights.astype(np.float64),
            minlength=self.num_vertices,
        ).astype(self.policy.weight_dtype)

    def sources(self) -> np.ndarray:
        """Per-edge source vertex id (the CSR row expanded)."""
        return np.repeat(
            np.arange(self.num_vertices, dtype=self.policy.vertex_dtype),
            self.degrees(),
        )

    def total_edge_weight_twice(self) -> float:
        """Sigma of all weighted degrees = 2m; the reciprocal is the gain
        constant (cf. distCalcConstantForSecondTerm,
        /root/reference/louvain.cpp:2153-2183)."""
        return float(self.weights.sum(dtype=np.float64))

    @staticmethod
    def from_edges(
        num_vertices: int,
        src: np.ndarray,
        dst: np.ndarray,
        weights: np.ndarray | None = None,
        symmetrize: bool = True,
        policy: Policy | None = None,
    ) -> "Graph":
        """Build a CSR graph from an edge list.

        With ``symmetrize=True`` each input edge (u, v), u != v, is inserted
        in both directions; self-loops are inserted once.  Duplicate edges are
        coalesced by summing weights.
        """
        policy = policy or default_policy()
        from cuvite_tpu import native

        # Unit-weight fast path (weights=None: R-MAT, unweighted inputs):
        # the int32 native builder counts duplicates instead of summing f64
        # ones — no 8-byte array exists anywhere, which is what makes
        # single-host ingest of billion-edge unweighted graphs fit
        # (tools/scale_model.md).  Output is bit-identical to the generic
        # path after the policy cast (exact integer counts, rounded once)
        # — which requires the policy weight dtype to BE f32: a wide
        # (f64) policy must keep the generic f64 path or duplicate counts
        # above 2^24 would round.
        if (weights is None and len(src) >= native.MIN_NATIVE_EDGES
                and native.available() and num_vertices <= 1 << 31
                and policy.weight_dtype == np.float32):
            offsets, tails, wcnt = native.build_csr_unit(
                num_vertices, src, dst, symmetrize
            )
            return Graph(
                offsets=offsets,
                tails=tails.astype(policy.vertex_dtype, copy=False),
                weights=wcnt.astype(policy.weight_dtype, copy=False),
                policy=policy,
            )
        # Weighted low-footprint path (benchmark-scale weighted ingest,
        # VERDICT r3 item 8): the sort carries an int32 original-edge
        # index, never the f64 weights, and emits int32/f32 directly —
        # ~24 B/slot transient vs the generic path's 32, with int64
        # src/dst accepted as-is (no width conversion).  Output is
        # bit-identical to the generic path + policy cast (accumulation
        # order preserved by sort stability).  Small nv keeps the generic
        # route, whose dense counting path wins there.
        if (weights is not None and len(src) >= native.MIN_NATIVE_EDGES
                and native.available()
                and (1 << 22) < num_vertices <= (1 << 31)
                and policy.weight_dtype == np.float32
                and (2 * len(src) if symmetrize else len(src))
                < (1 << 31)):
            offsets, tails, w32 = native.build_csr_w(
                num_vertices, src, dst, weights, symmetrize
            )
            return Graph(
                offsets=offsets,
                tails=tails.astype(policy.vertex_dtype, copy=False),
                weights=w32,
                policy=policy,
            )
        src = np.asarray(src, dtype=np.int64)
        dst = np.asarray(dst, dtype=np.int64)
        # Accumulate duplicate-edge sums from the raw f64 weights; the cast
        # to the policy dtype happens once, on the coalesced result (same
        # contract as the native builder, native/cuvite_native.cpp).
        if weights is None:
            w = np.ones(len(src), dtype=np.float64)
        else:
            w = np.asarray(weights, dtype=np.float64)

        # The native builder's composite radix key src*nv+dst only fits
        # uint64 for nv <= 2^32; beyond that use the numpy path.
        if (len(src) >= native.MIN_NATIVE_EDGES and native.available()
                and num_vertices <= 1 << 32):
            offsets, tails, wsum = native.build_csr(
                num_vertices, src, dst, w, symmetrize
            )
            return Graph(
                offsets=offsets,
                tails=tails.astype(policy.vertex_dtype),
                weights=wsum.astype(policy.weight_dtype),
                policy=policy,
            )
        if symmetrize:
            keep = src != dst
            src2 = np.concatenate([src, dst[keep]])
            dst2 = np.concatenate([dst, src[keep]])
            w2 = np.concatenate([w, w[keep]])
        else:
            src2, dst2, w2 = src, dst, w
        # Coalesce duplicates and sort into CSR order.
        key = src2 * np.int64(num_vertices) + dst2
        order = np.argsort(key, kind="stable")
        key, src2, dst2, w2 = key[order], src2[order], dst2[order], w2[order]
        uniq_mask = np.ones(len(key), dtype=bool)
        uniq_mask[1:] = key[1:] != key[:-1]
        seg_ids = np.cumsum(uniq_mask) - 1
        n_uniq = int(seg_ids[-1]) + 1 if len(seg_ids) else 0
        w_out = np.zeros(n_uniq, dtype=np.float64)
        np.add.at(w_out, seg_ids, w2.astype(np.float64))
        src_u = src2[uniq_mask]
        dst_u = dst2[uniq_mask]
        counts = np.bincount(src_u, minlength=num_vertices)
        offsets = np.zeros(num_vertices + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        return Graph(
            offsets=offsets,
            tails=dst_u.astype(policy.vertex_dtype),
            weights=w_out.astype(policy.weight_dtype),
            policy=policy,
        )
