"""Shared integer env-knob parser for the kernel budget/eligibility
knobs (CUVITE_SEG_COALESCE_MAX_NV, CUVITE_HEAVY_ELEMS, ...).

One definition so the parse/warn/default behavior cannot drift between
copies: accepts 0x/0b prefixes (``int(raw, 0)``), warns loudly on
malformed or out-of-range values and falls back to the default — a
typo'd knob must never silently measure the baseline while the
operator believes it changed (the CUVITE_EXCHANGE_CUTOVER precedent).

Note: ``louvain/bucketed.py::_env_int`` (the historical width-ladder
knob parser) predates this helper with slightly different semantics
(base-10 only, no range check) and keeps them for compatibility; new
knobs should use this one.
"""

from __future__ import annotations

import os
import warnings


def request_host_devices(n: int) -> None:
    """Ask XLA for ``n`` virtual CPU devices (batch-axis sharding,
    ISSUE 9).  Must run BEFORE jax backend init — the flag is read once
    at first backend touch — so CLI entry points call this right after
    argument parsing and before any jax import.  No-op when ``n <= 1``
    or when a device-count flag is already present (the test conftest,
    an operator's explicit XLA_FLAGS): never silently override an
    existing request."""
    if n <= 1:
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" in flags:
        return
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n}").strip()


def env_int(name: str, default: int, *, minimum: int = 1,
            maximum: int | None = None) -> int:
    """``int(os.environ[name], 0)`` clamped to [minimum, maximum], or
    ``default`` (with a warning) when unset-empty, malformed, or out of
    range."""
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        v = int(raw, 0)
    except ValueError:
        v = None
    if v is None or v < minimum or (maximum is not None and v > maximum):
        bound = (f" <= {maximum}" if maximum is not None else "")
        warnings.warn(
            f"malformed {name}={raw!r} (want an integer >= {minimum}"
            f"{bound}); using the default {default}", stacklevel=2)
        return default
    return v
