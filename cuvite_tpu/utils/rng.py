"""Parallel linear congruential generator with reference parity.

Replicates the reference LCG (/root/reference/utils.hpp:76-271):
Park-Miller MINSTD, x[i] = (16807 * x[i-1]) mod (2^31 - 1), seeded through a
C++11 std::seed_seq of one word (utils.hpp:104-111), with the rank-0 seed
distributed by a parallel-prefix jump so each shard owns a contiguous slice
of ONE global stream (utils.hpp:151-189).

The reference computes the per-rank jump with log2(p) rounds of 2x2 matrix
exchanges; a closed-form modular power gives the identical values without
communication (the matrix [[a,0],[b,1]]^k encodes x -> a^k x + b*(a^(k-1)+
...+1); with b=0 this is a plain modpow).

All parity-sensitive paths are host-side numpy (generation happens once per
run; devices only consume the resulting coordinate arrays).
"""

from __future__ import annotations

import numpy as np

MLCG = 2147483647  # 2^31 - 1 (utils.hpp:25)
ALCG = 16807       # 7^5      (utils.hpp:26)
BLCG = 0           # utils.hpp:27


def seed_seq_generate(seeds: list[int], n: int) -> list[int]:
    """C++11 std::seed_seq::generate ([rand.util.seedseq]) for 32-bit words."""
    M32 = 0xFFFFFFFF
    if n == 0:
        return []
    b = [0x8B8B8B8B] * n
    s = len(seeds)
    t = 11 if n >= 623 else 7 if n >= 68 else 5 if n >= 39 else 3 if n >= 7 \
        else (n - 1) // 2
    p = (n - t) // 2
    q = p + t
    m = max(s + 1, n)

    def T(x: int) -> int:
        return (x ^ (x >> 27)) & M32

    for k in range(m):
        r1 = (1664525 * T(b[k % n] ^ b[(k + p) % n] ^ b[(k - 1) % n])) & M32
        if k == 0:
            r2 = (r1 + s) & M32
        elif k <= s:
            r2 = (r1 + (k % n) + seeds[k - 1]) & M32
        else:
            r2 = (r1 + (k % n)) & M32
        b[(k + p) % n] = (b[(k + p) % n] + r1) & M32
        b[(k + q) % n] = (b[(k + q) % n] + r2) & M32
        b[k % n] = r2
    for k in range(m, m + n):
        r3 = (1566083941 * T((b[k % n] + b[(k + p) % n] + b[(k - 1) % n]) & M32)) & M32
        r4 = (r3 - (k % n)) & M32
        b[(k + p) % n] ^= r3
        b[(k + q) % n] ^= r4
        b[k % n] = r4
    return b


def reseeder(initseed: int) -> int:
    """utils.hpp:104-111: one seed_seq word from the user seed."""
    return seed_seq_generate([initseed & 0xFFFFFFFF], 1)[0]


def lcg_jump(x0: int, k: int) -> int:
    """x_k given x_0: closed form of the reference's 2x2 matrix power
    (utils.hpp:136-189).  With b=0 this is x0 * a^k mod M."""
    a_k = pow(ALCG, k, MLCG)
    if BLCG == 0:
        return (x0 * a_k) % MLCG
    # geometric series b * (a^(k-1) + ... + 1) mod M
    geo = (a_k - 1) * pow(ALCG - 1, MLCG - 2, MLCG) % MLCG
    return (x0 * a_k + BLCG * geo) % MLCG


def lcg_stream(seed: int, total: int, lo: int = 0, hi: int | None = None) -> np.ndarray:
    """Slice [lo, hi) of the global LCG stream for `seed`, as uniforms in
    [0, 1) — matching LCG::generate's scaling (utils.hpp:216-234).

    Stream convention (utils.hpp:91-98, :183-188): element 0 IS x0 (the
    reseeded seed); element i is the i-th LCG successor.
    """
    hi = total if hi is None else hi
    n = hi - lo
    if n <= 0:
        return np.empty(0, dtype=np.float64)
    # Vectorized: x_{base+j} = x_base * a^j mod M.  Both factors are < 2^31,
    # so the products fit int64 exactly.  Walk base points in blocks of B.
    B = 1024
    a_pows = np.empty(B, dtype=np.int64)
    a_pows[0] = 1
    for j in range(1, B):
        a_pows[j] = (a_pows[j - 1] * ALCG) % MLCG
    a_B = pow(ALCG, B, MLCG)
    out = np.empty(n, dtype=np.int64)
    x0 = reseeder(seed)
    x = lcg_jump(x0, lo)
    for b0 in range(0, n, B):
        blk = min(B, n - b0)
        out[b0 : b0 + blk] = (x * a_pows[:blk]) % MLCG
        x = (x * a_B) % MLCG
    if lo == 0:
        # Reference quirk (utils.hpp:185-186): rank 0 uses the raw reseeded
        # x0 without the mod, so a 32-bit x0 >= MLCG yields a uniform > 1.0.
        # Replicated for stream parity.
        out[0] = x0
    mult = 1.0 / float(MLCG)  # 1/(1 + (MLCG-1)) (utils.hpp:216)
    return out.astype(np.float64) * mult


def minstd0_uniform_real(seed32: np.ndarray, lo: float, hi: float) -> np.ndarray:
    """Vectorized libstdc++ `uniform_real_distribution<double>(lo, hi)`
    drawn from a freshly-seeded `minstd_rand0` — the reference's
    deterministic far-edge weight function
    (/root/reference/distgraph.cpp:755-757: `std::hash` of an integral is
    the identity in libstdc++, truncated to `unsigned`, so the weight is a
    pure function of the endpoint pair; replicated here bit-for-bit).

    libstdc++ mechanics: engine seed x0 = seed mod M (0 -> 1); two draws
    d = 16807*x mod M; generate_canonical<double, 53> with k = 2, r = M-1:
    ret = ((d1-1) + (d2-1)*r) / r^2, accumulated in double; result
    lo + ret*(hi-lo) ... note libstdc++ computes (hi-lo)*ret + lo.
    """
    x = (np.asarray(seed32, dtype=np.uint64) & np.uint64(0xFFFFFFFF)) \
        % np.uint64(MLCG)
    x = np.where(x == 0, np.uint64(1), x).astype(np.int64)
    d1 = (x * ALCG) % MLCG
    d2 = (d1 * ALCG) % MLCG
    r = np.float64(MLCG - 1)
    canon = ((d1 - 1).astype(np.float64)
             + (d2 - 1).astype(np.float64) * r) / (r * r)
    return (hi - lo) * canon + lo


# ---------------------------------------------------------------------------
# Counter-based RNG (SplitMix64): stateless hash RNG used by the synthetic
# graph generators.  Trivially parallel (no stream to split), and the exact
# same integer recurrence is implemented in native/cuvite_native.cpp, so the
# numpy fallback and the native fast path generate bit-identical graphs.

_SM_GOLDEN = np.uint64(0x9E3779B97F4A7C15)
_SM_C1 = np.uint64(0xBF58476D1CE4E5B9)
_SM_C2 = np.uint64(0x94D049BB133111EB)


def splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized SplitMix64 finalizer over a uint64 array (wrapping)."""
    with np.errstate(over="ignore"):  # modular arithmetic is the point
        x = (np.asarray(x, dtype=np.uint64) + _SM_GOLDEN)
        x ^= x >> np.uint64(30)
        x *= _SM_C1
        x ^= x >> np.uint64(27)
        x *= _SM_C2
        x ^= x >> np.uint64(31)
    return x


def u01(x: np.ndarray) -> np.ndarray:
    """uint64 -> float64 uniform in [0, 1) with 53 random bits."""
    return (np.asarray(x, dtype=np.uint64) >> np.uint64(11)).astype(
        np.float64) * (1.0 / 9007199254740992.0)


def scramble_ids(x: np.ndarray, bits: int, seed: int) -> np.ndarray:
    """Deterministic bijection on [0, 2^bits): two rounds of (odd multiply
    mod 2^bits, xor own high half).  Breaks the R-MAT id/degree correlation
    in place of a materialized random permutation; mirrored in
    native/cuvite_native.cpp:scramble."""
    mask = np.uint64(0xFFFFFFFFFFFFFFFF if bits >= 64 else (1 << bits) - 1)
    s = np.uint64(seed)
    odd1 = splitmix64(s ^ np.uint64(0xA5A5A5A5)) | np.uint64(1)
    odd2 = splitmix64(s ^ np.uint64(0x5A5A5A5A)) | np.uint64(1)
    h = np.uint64(max(bits // 2, 1))
    x = np.asarray(x, dtype=np.uint64)
    with np.errstate(over="ignore"):
        x = (x * odd1) & mask
        x = x ^ (x >> h)
        x = (x * odd2) & mask
        x = x ^ (x >> h)
    return x & mask
