"""cuvite_tpu.utils"""
