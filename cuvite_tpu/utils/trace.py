"""Tracing / profiling / diagnostics.

The reference instruments every stage with MPI_Wtime pairs printed per
rank (/root/reference/main.cpp:241-258, :353-358, :411-426, the
DEBUG_PRINTF stage breakdowns in louvain.cpp:472-538, and the
PRINT_TIMEDS GPU timers, louvain_cuda.cu:2456-2461), tracks the memory
high-water with getrusage (main.cpp:142-150), and routes diagnostics to
per-rank `dat.out.<rank>` files (main.cpp:101-110).

Here that collapses into one Tracer object: named accumulating stage
timers (wall clock; device work is timed around blocking host syncs, the
only boundaries that exist under jit), RSS high-water, TEPS accounting
(main.cpp:448, :509), and optional per-shard diag files.
"""

from __future__ import annotations

import contextlib
import math
import os
import resource
import time


def rss_high_water_mb() -> float:
    """Peak resident set size of this process in MiB (the reference prints
    getrusage ru_maxrss the same way, main.cpp:142-150)."""
    ru = resource.getrusage(resource.RUSAGE_SELF)
    # ru_maxrss is KiB on Linux.
    return ru.ru_maxrss / 1024.0


class Tracer:
    """Accumulating named stage timers + counters, and (since ISSUE 6) a
    thin facade over the obs flight recorder: attach a
    ``cuvite_tpu.obs.FlightRecorder`` and every ``stage()`` window also
    becomes a nested span in the structured trace, ``event()`` /
    ``begin_span()`` / ``track()`` forward to the emitter/HBM ledger, and the
    drivers' telemetry (convergence rows, exchange-plan stats, memory
    snapshots) lands in the record stream.  Without a recorder those
    calls are no-ops — the drivers thread them unconditionally at zero
    cost.

    Usage::

        tr = Tracer()
        with tr.stage("load"):
            ...
        tr.count("iterations", n)
        print(tr.report())
    """

    def __init__(self, enabled: bool = True, recorder=None):
        # A recorder implies recording: --trace-out without --trace must
        # still time the stages its spans report.
        self.enabled = enabled or recorder is not None
        self.recorder = recorder
        self.emitter = recorder.emitter if recorder is not None else None
        self.times: dict[str, float] = {}
        self.calls: dict[str, int] = {}
        self.counters: dict[str, float] = {}

    @contextlib.contextmanager
    def stage(self, name: str):
        if not self.enabled:
            yield
            return
        em = self.emitter
        sid = em.begin(name) if em is not None else None
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            if em is not None:
                em.end(sid, dur_s=dt)
            self.times[name] = self.times.get(name, 0.0) + dt
            self.calls[name] = self.calls.get(name, 0) + 1

    def count(self, name: str, value: float = 1) -> None:
        if self.enabled:
            self.counters[name] = self.counters.get(name, 0) + value

    # -- flight-recorder facade (no-ops without an attached recorder) -------

    def event(self, name: str, **attrs) -> None:
        """Point event in the structured trace."""
        if self.emitter is not None:
            self.emitter.event(name, **attrs)

    def begin_span(self, name: str, **attrs):
        """Open a span whose extent cannot be a ``with`` block (the
        driver's per-phase envelope spans a loop body with breaks).
        Returns an opaque handle for :meth:`end_span`."""
        if self.emitter is not None:
            return self.emitter.begin(name, **attrs)
        return None

    def end_span(self, handle, **attrs) -> None:
        if self.emitter is not None and handle is not None:
            self.emitter.end(handle, **attrs)

    def set_phase(self, phase) -> None:
        """Tag subsequent records with the running phase index."""
        if self.emitter is not None:
            self.emitter.phase = phase

    def track(self, category: str, *arrays) -> None:
        """Account device buffers to the HBM ledger by category."""
        if self.recorder is not None:
            self.recorder.ledger.track(category, *arrays)

    def ledger_phase_begin(self) -> None:
        if self.recorder is not None:
            self.recorder.ledger.begin_phase()

    def ledger_snapshot(self, phase=None) -> None:
        """Snapshot the ledger at a phase boundary and emit it."""
        if self.recorder is not None:
            snap = self.recorder.ledger.snapshot(phase)
            self.event("hbm", **snap)

    # Stage names the drivers use, in pipeline order.  These are the
    # bench record's REQUIRED per-stage fields (ISSUE 3 satellite;
    # coalesce since ISSUE 8, rebin since ISSUE 19): coarsen_s —
    # inter-phase graph rebuild (host or device); coalesce_s — the
    # device relabel+coalesce slice, NESTED inside coarsen_s (coarsen_s
    # CONTAINS coalesce_s; 0.0 on the host-compaction path), split out
    # so the round-7 sort tax is a measured field; rebin_s — the device
    # plan re-bin of a coarse phase (coarsen/rebin.py; runs NESTED
    # inside the driver's plan stage, so plan_s CONTAINS rebin_s; 0.0
    # on the host BucketPlan.build path and on non-bucketed engines);
    # upload_s — host->device placement of slabs/plans; iterate_s — the
    # jitted phase loops.  Note upload runs NESTED inside the driver's
    # plan stage on the per-phase engine path, so there plan_s CONTAINS
    # upload_s (the fused driver's stages are disjoint).
    CANONICAL_STAGES = ("coarsen", "coalesce", "rebin", "upload",
                        "iterate")

    def breakdown(self) -> dict:
        """Per-stage seconds for machine consumers (the bench JSON's
        ``stages`` field): always carries ``<stage>_s`` for every
        CANONICAL_STAGES entry (0.0 when the stage never ran), plus any
        other recorded stage under the same naming.

        FULL precision: rounding here (the historical ``round(v, 3)``)
        erased sub-millisecond stages outright — upload on a tiny graph
        reported 0.0, making real-vs-absent indistinguishable to the
        regression gate.  Human-facing rounding lives in ``report()``."""
        out = {k + "_s": self.times.get(k, 0.0)
               for k in self.CANONICAL_STAGES}
        for k, v in sorted(self.times.items()):
            out.setdefault(k + "_s", v)
        return out

    def teps(self) -> float:
        """Traversed edges per second: counter 'traversed_edges' over the
        'iterate' stage WALL time.  Unlike the steady-state bench metric
        (bench.py warm-up excludes compilation, cf. main.cpp:499-518),
        this includes any one-time XLA compile that ran inside the stage —
        the report labels it accordingly."""
        t = self.times.get("iterate", 0.0)
        return self.counters.get("traversed_edges", 0.0) / t if t else 0.0

    def report(self) -> str:
        lines = ["stage breakdown (s):"]
        total = sum(self.times.values())
        for name, t in sorted(self.times.items(), key=lambda kv: -kv[1]):
            lines.append(
                f"  {name:<16} {t:9.3f}  ({self.calls[name]}x, "
                f"{100.0 * t / total if total else 0.0:4.1f}%)"
            )
        for name, v in sorted(self.counters.items()):
            lines.append(f"  {name:<16} {v:g}")
        if self.counters.get("traversed_edges"):
            lines.append(
                f"  TEPS (wall, incl. compile) {self.teps():.4g}"
            )
        lines.append(f"  rss high-water   {rss_high_water_mb():.0f} MiB")
        return "\n".join(lines)


def dist_stats_report(dg, ghost_counts=None) -> str:
    """Edge-distribution characteristics of a DistGraph partition: the
    analog of the reference's PRINT_DIST_STATS block
    (/root/reference/distgraph.hpp:100-149), which Allreduces per-rank
    local edge counts and prints min/max/mean/variance/stddev on rank 0.
    Here the partition is host-resident, so the moments are computed
    directly; ghost counts (from the phase ExchangePlan) are appended when
    available — the piece the reference's stats lack."""
    counts = [sh.n_real_edges for sh in dg.shards]
    n = max(len(counts), 1)
    mean = sum(counts) / n
    avg_sq = sum(c * c for c in counts) / n
    var = abs(avg_sq - mean * mean)
    lines = [
        "-" * 55,
        "Graph edge distribution characteristics",
        "-" * 55,
        f"Number of vertices: {dg.total_vertices}",
        f"Number of edges: {dg.total_edges}",
        f"Number of shards: {dg.nshards}",
        f"Maximum number of edges: {max(counts)}",
        f"Minimum number of edges: {min(counts)}",
        f"Mean number of edges: {mean:g}",
        f"Variance: {var:g}",
        f"Standard deviation: {math.sqrt(var):g}",
    ]
    if ghost_counts is not None:
        lines.append(
            f"Ghost vertices per shard: max {max(ghost_counts)}, "
            f"min {min(ghost_counts)}, "
            f"mean {sum(ghost_counts) / max(len(ghost_counts), 1):g}")
    lines.append("-" * 55)
    return "\n".join(lines)


class ShardDiag:
    """Per-shard diagnostic text files: the analog of the reference's
    per-rank `dat.out.<rank>` streams (/root/reference/main.cpp:101-110).
    One `<prefix>.<shard>` file per shard, appended a line per call; files
    open lazily on first write."""

    def __init__(self, prefix: str, nshards: int):
        self.prefix = prefix
        self.nshards = nshards
        self._files: dict[int, object] = {}

    def write(self, shard: int, line: str) -> None:
        f = self._files.get(shard)
        if f is None:
            d = os.path.dirname(self.prefix)
            if d:
                os.makedirs(d, exist_ok=True)
            # Truncate on first open (like the reference's per-rank
            # ofstreams) so reruns don't mix stale lines into the files.
            f = open(f"{self.prefix}.{shard}", "w")
            self._files[shard] = f
        f.write(line.rstrip("\n") + "\n")

    def close(self) -> None:
        for f in self._files.values():
            f.close()
        self._files.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class NullTracer(Tracer):
    def __init__(self):
        super().__init__(enabled=False)
