"""Copy-free device upload for phase-static host arrays.

The bucketed engine uploads O(E) plan matrices once per phase.  On a real
TPU that is an unavoidable host->device DMA, but on the cpu backend (the
virtual-mesh test rig and the single-host benchmark fallback) a plain
``jnp.asarray(x.astype(dt))`` costs up to TWO extra copies of an
already-multi-GB array: ``astype`` copies even when the dtype matches,
and the cpu "device" buffer is a second host allocation.  At benchmark
scale (R-MAT 26: ~14 GB of plan matrices) that duplication is the
difference between fitting this host and OOM (tools/scale_model.md).

``to_device`` removes both: ``astype(copy=False)`` and, on the cpu
backend, a DLPack import (``jnp.from_dlpack``) that ALIASES the numpy
buffer — zero bytes moved.  XLA:CPU only aliases an imported buffer that
is 64-byte aligned (measured under jax 0.9: unaligned imports silently
copy), and numpy's own allocator gives no such guarantee, so the plan
builders allocate their O(E) arrays with ``aligned_empty``/friends below
and ``to_device`` attempts the import only when the pointer is aligned
(an unaligned source would just pay the same one copy as ``asarray``).

Contract for zero-copy sources: the caller must treat the numpy array as
frozen afterwards (the jax array reads the same memory; XLA never writes
to non-donated inputs, and none of these uploads are donated).  All call
sites pass freshly built, write-once plan/slab arrays.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

ALIGN = 64  # XLA:CPU zero-copy import requires 64-byte aligned buffers


def aligned_empty(shape, dtype) -> np.ndarray:
    """np.empty whose data pointer is ALIGN-byte aligned (see module doc)."""
    shape = (shape,) if np.isscalar(shape) else tuple(shape)
    dt = np.dtype(dtype)
    nbytes = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
    buf = np.empty(nbytes + ALIGN, dtype=np.uint8)
    off = (-buf.ctypes.data) % ALIGN
    return buf[off:off + nbytes].view(dt).reshape(shape)


def aligned_zeros(shape, dtype) -> np.ndarray:
    out = aligned_empty(shape, dtype)
    out[...] = 0
    return out


def aligned_full(shape, fill, dtype) -> np.ndarray:
    out = aligned_empty(shape, dtype)
    out[...] = fill
    return out


def aligned_copy(a: np.ndarray) -> np.ndarray:
    """C-contiguous ALIGN-aligned copy (use instead of ascontiguousarray
    when the result will be uploaded with ``to_device``)."""
    out = aligned_empty(a.shape, a.dtype)
    np.copyto(out, a)
    return out


def to_device(x, dtype=None):
    """jnp.asarray with the copies removed where legal (see module doc).

    SIDE EFFECT on the zero-copy path: the source numpy array — and its
    ``.base`` chain when it is a view — is frozen (``writeable=False``)
    before returning, because the jax array aliases that exact memory.
    A later host write through ``x`` or its bases then raises instead of
    silently corrupting device state.  Best-effort, not a guarantee:
    numpy captures writeability per-array at view creation, so a SIBLING
    view taken before this call still writes into the aliased buffer
    unchecked — don't keep other views of an uploaded array around.
    Callers that need to keep mutating the source must pass a copy (or
    set CUVITE_NO_ALIAS_UPLOAD=1).

    EVERY return path yields a COMMITTED array (an explicit
    SingleDeviceSharding): ``jnp.from_dlpack`` commits inherently, and the
    copy path commits via ``jax.device_put``.  This is a correctness
    property, not a nicety — jit's lowering cache keys on each argument's
    committed-vs-unspecified sharding, and whether a given numpy source
    takes the zero-copy path depends on an ALIGNMENT LOTTERY (glibc malloc
    only 16-aligns small allocations).  Mixing committed and uncommitted
    uploads made the ~50-operand phase-loop cache key flip per run and
    per phase, recompiling up to every phase of every run — the judge's
    round-4 7x bench regression (VERDICT r4 weak #1)."""
    if isinstance(x, jax.Array):
        # Already device-resident (the coarsen/device.py path hands jit
        # outputs — committed by construction — straight back to the next
        # phase's runner): never round-trip it through numpy.
        if dtype is not None and x.dtype != np.dtype(dtype):
            return x.astype(dtype)
        return x
    x = np.asarray(x)
    if dtype is not None:
        x = x.astype(dtype, copy=False)
    if (not os.environ.get("CUVITE_NO_ALIAS_UPLOAD")
            and jax.default_backend() == "cpu" and x.size
            and x.flags.c_contiguous and x.ctypes.data % ALIGN == 0):
        try:
            out = jnp.from_dlpack(x)
        except Exception:
            pass  # exotic dtype: fall through to the copy path
        else:
            # The jax array reads this exact memory from now on: freeze the
            # numpy side so a later host mutation raises instead of silently
            # corrupting device state.  Freezing x alone is NOT enough when
            # x is a view (every aligned_* allocator above returns a view
            # of a uint8 buffer): a write through the base would still land
            # in the aliased memory with x.flags untouched.  Freeze the
            # whole .base chain.  (Sibling views created BEFORE this call
            # keep their own writeable flag — numpy offers no way to reach
            # them — so the guard is best-effort; see docstring.)
            b = x
            while isinstance(b, np.ndarray):
                b.flags.writeable = False
                b = b.base
            return out
    # local_devices, not devices: in a multi-process run devices()[0] is
    # process 0's (non-addressable elsewhere), and the two paths would
    # commit to different devices — the instability this fix removes.
    return jax.device_put(x, jax.local_devices()[0])
