"""Shared persistent-XLA-compile-cache setup.

Every entry point that benefits from cached executables (bench.py, the
driver artifacts in __graft_entry__.py, the tools/ scripts) enables the
SAME repo-local cache through this one helper, so the cache directory,
the min-compile-time knob, and the CUVITE_NO_COMPILE_CACHE opt-out cannot
drift apart.  Compiles dominate first-run wall time (~30s per distinct
phase shape on v5e); cached reruns skip them entirely — which also means
a short TPU-tunnel-alive window is enough for a full bench run.
"""

from __future__ import annotations

import os


def enable_compile_cache(root: str | None = None) -> None:
    """Point jax at ``<root>/.jax_cache`` (default: the repo root) unless
    CUVITE_NO_COMPILE_CACHE is set.  Call before the first compilation;
    safe to call more than once."""
    if os.environ.get("CUVITE_NO_COMPILE_CACHE"):
        return
    import jax

    if root is None:
        root = os.environ.get("CUVITE_COMPILE_CACHE_DIR")
    if root is None:
        # Repo-root heuristic: three dirs up from this file.  For a
        # site-packages install that lands somewhere unwritable/shared, so
        # fall back to a per-user cache dir.
        cand = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        root = cand if os.access(cand, os.W_OK) else os.path.join(
            os.environ.get("XDG_CACHE_HOME",
                           os.path.expanduser("~/.cache")), "cuvite")
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(root, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
