"""Phase-granular checkpoint / resume for the multi-phase driver.

The reference has NO mid-run persistence — a failed 200-phase run on a
billion-edge graph starts over ("resume = re-run"; its only outputs are
the final .communities file, main.cpp:521-550, and generator write-out).
This framework checkpoints the inter-phase state, which is tiny compared
to the input graph: the composed per-vertex labels, the current coarse
graph, and the driver counters.  Each phase's file is self-contained and
atomic (write-to-temp + rename), so a run killed at any point resumes
from the last completed phase.

Format: one `phase_NNNN.npz` per completed phase in the checkpoint
directory; the highest-numbered complete file wins.
"""

from __future__ import annotations

import dataclasses
import os
import zipfile

import numpy as np

from cuvite_tpu.core.graph import Graph
from cuvite_tpu.core.types import Policy


@dataclasses.dataclass
class PhaseCheckpoint:
    phase: int               # next phase index to run
    comm_all: np.ndarray     # composed labels for the ORIGINAL vertices
    graph: Graph             # current coarse graph
    prev_mod: float
    tot_iters: int
    mod_hist: np.ndarray     # per completed phase
    iter_hist: np.ndarray
    nv_hist: np.ndarray      # vertices/edges of each completed phase's graph
    ne_hist: np.ndarray
    orig_ne: int = -1        # edge count of the ORIGINAL graph
    fingerprint: int = -1    # content fingerprint of the ORIGINAL graph


def graph_fingerprint(graph: Graph) -> int:
    """Cheap content fingerprint: CRC of the CSR offsets plus the total edge
    weight.  Distinguishes graphs that share (nv, ne) — e.g. same-scale
    R-MATs with different seeds — so a resume in a reused checkpoint
    directory cannot silently compose labels for the wrong graph."""
    import zlib

    h = zlib.crc32(np.ascontiguousarray(graph.offsets).view(np.uint8))
    h = zlib.crc32(np.ascontiguousarray(graph.tails).view(np.uint8), h)
    tw = float(np.sum(graph.weights, dtype=np.float64))
    h = zlib.crc32(np.float64(tw).tobytes(), h)
    return (h << 16) ^ (graph.num_vertices & 0xFFFF)


def _phase_num(name: str) -> int | None:
    """Parse N from 'phase_<N>.npz' (any digit count; None if malformed)."""
    stem = name[len("phase_"):-len(".npz")]
    return int(stem) if stem.isdigit() else None


def _path(ckpt_dir: str, phase: int) -> str:
    return os.path.join(ckpt_dir, f"phase_{phase:04d}.npz")


def save_phase(ckpt_dir: str, ck: PhaseCheckpoint) -> str:
    os.makedirs(ckpt_dir, exist_ok=True)
    path = _path(ckpt_dir, ck.phase)
    tmp = path + ".tmp"
    g = ck.graph
    with open(tmp, "wb") as f:
        np.savez(
            f,
            phase=np.int64(ck.phase),
            comm_all=ck.comm_all,
            offsets=g.offsets,
            tails=g.tails,
            weights=g.weights,
            vertex_dtype=np.str_(np.dtype(g.policy.vertex_dtype).name),
            weight_dtype=np.str_(np.dtype(g.policy.weight_dtype).name),
            accum_dtype=np.str_(np.dtype(g.policy.accum_dtype).name),
            prev_mod=np.float64(ck.prev_mod),
            tot_iters=np.int64(ck.tot_iters),
            mod_hist=np.asarray(ck.mod_hist, dtype=np.float64),
            iter_hist=np.asarray(ck.iter_hist, dtype=np.int64),
            nv_hist=np.asarray(ck.nv_hist, dtype=np.int64),
            ne_hist=np.asarray(ck.ne_hist, dtype=np.int64),
            orig_ne=np.int64(ck.orig_ne),
            fingerprint=np.int64(ck.fingerprint),
        )
    os.replace(tmp, path)
    # Runs advance monotonically, so any higher-numbered file is leftover
    # state from a PREVIOUS run in the same directory; clear it or a later
    # --resume would pick the stale run's final phase over this one.
    for name in os.listdir(ckpt_dir):
        if name.startswith("phase_") and name.endswith(".npz"):
            num = _phase_num(name)
            if num is not None and num > ck.phase:
                os.remove(os.path.join(ckpt_dir, name))
    return path


def load_latest(ckpt_dir: str) -> PhaseCheckpoint | None:
    if not os.path.isdir(ckpt_dir):
        return None
    names = sorted(
        (n for n in os.listdir(ckpt_dir)
         if n.startswith("phase_") and n.endswith(".npz")
         and _phase_num(n) is not None),
        key=_phase_num,
    )
    for name in reversed(names):
        path = os.path.join(ckpt_dir, name)
        try:
            with np.load(path, allow_pickle=False) as z:
                policy = Policy(
                    vertex_dtype=np.dtype(str(z["vertex_dtype"])),
                    weight_dtype=np.dtype(str(z["weight_dtype"])),
                    accum_dtype=np.dtype(str(z["accum_dtype"])),
                )
                graph = Graph(
                    offsets=z["offsets"], tails=z["tails"],
                    weights=z["weights"], policy=policy,
                )
                return PhaseCheckpoint(
                    phase=int(z["phase"]),
                    comm_all=np.asarray(z["comm_all"]),
                    graph=graph,
                    prev_mod=float(z["prev_mod"]),
                    tot_iters=int(z["tot_iters"]),
                    mod_hist=np.asarray(z["mod_hist"]),
                    iter_hist=np.asarray(z["iter_hist"]),
                    nv_hist=np.asarray(z["nv_hist"]),
                    ne_hist=np.asarray(z["ne_hist"]),
                    orig_ne=int(z["orig_ne"]),
                    fingerprint=(int(z["fingerprint"])
                                 if "fingerprint" in z else -1),
                )
        except (OSError, ValueError, KeyError, zipfile.BadZipFile):
            continue  # truncated/corrupt file: fall back to the previous one
    return None
