"""graftlint CLI.

Usage:
    python -m cuvite_tpu.analysis [paths...] [--format text|json]
        [--baseline FILE] [--write-baseline] [--fail-on high|medium|low]
        [--list-rules]

Exit status: 0 when no NON-BASELINED finding at or above the gate
severity (default: high) remains; 1 otherwise; 2 on usage errors.
The repo's canonical invocation (what tests/test_analysis.py and
tools/lint.sh run) is:

    python -m cuvite_tpu.analysis cuvite_tpu tools tests \
        --baseline tools/graftlint_baseline.json
"""

from __future__ import annotations

import argparse
import json
import sys

from cuvite_tpu.analysis.engine import (
    SEVERITIES,
    all_rules,
    apply_baseline,
    gate_failures,
    load_baseline,
    run_paths,
    write_baseline,
)
from cuvite_tpu.analysis import rules as _rules  # noqa: F401 (registry)

DEFAULT_PATHS = ["cuvite_tpu", "tools", "tests"]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cuvite_tpu.analysis",
        description="graftlint: TPU/JAX static analysis for cuvite_tpu")
    ap.add_argument("paths", nargs="*", default=None,
                    help=f"files/directories to lint (default: "
                         f"{' '.join(DEFAULT_PATHS)})")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", metavar="FILE", default=None,
                    help="JSON baseline of grandfathered findings")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write ALL current findings to --baseline and "
                         "exit 0 (requires --baseline)")
    ap.add_argument("--fail-on", choices=SEVERITIES, default="high",
                    help="lowest severity that fails the gate "
                         "(default: high)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.id}  [{rule.severity:6s}] {rule.title}")
        return 0

    paths = args.paths or DEFAULT_PATHS
    findings = run_paths(paths)

    if args.write_baseline:
        if not args.baseline:
            ap.error("--write-baseline requires --baseline FILE")
        write_baseline(args.baseline, findings)
        errors = [f for f in findings if f.rule == "E000"]
        print(f"wrote {len(findings) - len(errors)} finding(s) to "
              f"{args.baseline}")
        if errors:
            # E000 is never baselineable (engine.write_baseline drops
            # it); pretending the rebaseline captured it would surprise
            # the operator on the very next gated run.
            for f in errors:
                print(f.format())
            print(f"graftlint: {len(errors)} unprocessable input(s) NOT "
                  "baselined; E000 always fails the gate")
            return 1
        return 0

    baseline = load_baseline(args.baseline) if args.baseline else {}
    new, grandfathered = apply_baseline(findings, baseline)
    failures = gate_failures(new, args.fail_on)

    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_dict() for f in new],
            "baselined": len(grandfathered),
            "gate": {"fail_on": args.fail_on,
                     "failures": len(failures)},
        }, indent=2))
    else:
        for f in new:
            print(f.format())
        counts = {}
        for f in new:
            counts[f.severity] = counts.get(f.severity, 0) + 1
        summary = ", ".join(f"{counts[s]} {s}" for s in SEVERITIES
                            if s in counts) or "0"
        print(f"graftlint: {len(new)} finding(s) ({summary}); "
              f"{len(grandfathered)} baselined; "
              f"gate fail-on={args.fail_on}: "
              f"{'FAIL' if failures else 'ok'}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
