"""graftlint CLI.

Usage:
    python -m cuvite_tpu.analysis [paths...] [--format text|json|sarif]
        [--baseline FILE] [--write-baseline] [--prune-baseline]
        [--fail-on high|medium|low] [--cache FILE] [--list-rules]

Exit status: 0 when no NON-BASELINED finding at or above the gate
severity (default: high) remains; 1 otherwise; 2 on usage errors.
The repo's canonical invocation (what tests/test_analysis.py and
tools/lint.sh run) is:

    python -m cuvite_tpu.analysis cuvite_tpu tools tests \
        --baseline tools/graftlint_baseline.json \
        --cache tools/.graftlint_cache.json

``--format sarif`` emits SARIF 2.1.0 for CI annotation (one result per
non-baselined finding, rule metadata included, snippet-hash partial
fingerprints).  ``--prune-baseline`` rewrites the baseline dropping
entries whose fingerprint matches no current finding (each dead entry
silently admits one future regression); a staleness count is reported
on every text run regardless.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import sys

from cuvite_tpu.analysis.engine import (
    SEVERITIES,
    all_rules,
    apply_baseline,
    gate_failures,
    linted_rels,
    load_baseline,
    prune_baseline,
    run_paths,
    stale_baseline_entries,
    write_baseline,
)
from cuvite_tpu.analysis import rules as _rules        # noqa: F401
from cuvite_tpu.analysis import callgraph as _cg       # noqa: F401
from cuvite_tpu.analysis import lockset as _lockset    # noqa: F401
from cuvite_tpu.analysis import lockorder as _lockord  # noqa: F401
from cuvite_tpu.analysis import meshspec as _meshspec  # noqa: F401
from cuvite_tpu.analysis import widthcheck as _widthcheck  # noqa: F401

DEFAULT_PATHS = ["cuvite_tpu", "tools", "tests"]

_SARIF_LEVEL = {"high": "error", "medium": "warning", "low": "note"}


def to_sarif(findings, baselined: int = 0) -> dict:
    """SARIF 2.1.0 document for a finding list.  Fingerprints hash the
    same (path, rule, snippet) triple the baseline keys on, so CI-side
    dedup tracks findings across line drift exactly like the gate."""
    rules_meta = [{
        "id": r.id,
        "name": type(r).__name__,
        "shortDescription": {"text": r.title},
        "defaultConfiguration": {"level": _SARIF_LEVEL[r.severity]},
    } for r in all_rules()]
    rules_meta.append({
        "id": "E000",
        "name": "UnprocessableInput",
        "shortDescription": {"text": "unreadable or unparsable input"},
        "defaultConfiguration": {"level": "error"},
    })
    results = []
    for f in findings:
        fp = hashlib.sha256(
            "\x1f".join((f.path, f.rule, f.snippet)).encode()).hexdigest()
        results.append({
            "ruleId": f.rule,
            "level": _SARIF_LEVEL.get(f.severity, "warning"),
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {
                        "startLine": max(f.line, 1),
                        "snippet": {"text": f.snippet},
                    },
                },
            }],
            "partialFingerprints": {"graftlintFingerprint/v1": fp},
        })
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                "informationUri":
                    "https://example.invalid/cuvite_tpu/ANALYSIS.md",
                "rules": rules_meta,
            }},
            "results": results,
            "properties": {"baselinedFindings": baselined},
        }],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cuvite_tpu.analysis",
        description="graftlint: TPU/JAX static analysis for cuvite_tpu")
    ap.add_argument("paths", nargs="*", default=None,
                    help=f"files/directories to lint (default: "
                         f"{' '.join(DEFAULT_PATHS)})")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text")
    ap.add_argument("--baseline", metavar="FILE", default=None,
                    help="JSON baseline of grandfathered findings")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write ALL current findings to --baseline and "
                         "exit 0 (requires --baseline)")
    ap.add_argument("--prune-baseline", action="store_true",
                    help="drop baseline entries whose fingerprint "
                         "matches no current finding (requires "
                         "--baseline)")
    ap.add_argument("--fail-on", choices=SEVERITIES, default="high",
                    help="lowest severity that fails the gate "
                         "(default: high)")
    ap.add_argument("--cache", metavar="FILE", default=None,
                    help="incremental lint cache (per-file findings + "
                         "tier-2 summaries keyed on content sha256 + "
                         "rules version); bit-identical to a cold run")
    ap.add_argument("--no-project", action="store_true",
                    help="skip the tier-2 cross-module pass "
                         "(R017/R018) — per-file rules only")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.id}  [{rule.severity:6s}] {rule.title}")
        return 0

    paths = args.paths or DEFAULT_PATHS
    findings = run_paths(paths, project=not args.no_project,
                         cache=args.cache)

    if args.write_baseline:
        if not args.baseline:
            ap.error("--write-baseline requires --baseline FILE")
        write_baseline(args.baseline, findings)
        errors = [f for f in findings if f.rule == "E000"]
        print(f"wrote {len(findings) - len(errors)} finding(s) to "
              f"{args.baseline}")
        if errors:
            # E000 is never baselineable (engine.write_baseline drops
            # it); pretending the rebaseline captured it would surprise
            # the operator on the very next gated run.
            for f in errors:
                print(f.format())
            print(f"graftlint: {len(errors)} unprocessable input(s) NOT "
                  "baselined; E000 always fails the gate")
            return 1
        return 0

    # Baseline hygiene is SCOPED to the files this run actually linted:
    # a subset run (lint.sh --changed, explicit path args) must neither
    # report nor prune another file's live grandfathered entries.
    linted = linted_rels(paths)

    if args.prune_baseline:
        if not args.baseline:
            ap.error("--prune-baseline requires --baseline FILE")
        if args.no_project:
            # R017/R018 entries would look dead with the tier switched
            # off and be silently deleted.
            ap.error("--prune-baseline cannot run with --no-project")
        dropped = prune_baseline(args.baseline, findings, linted=linted)
        print(f"pruned {dropped} stale baseline slot(s) from "
              f"{args.baseline}")

    baseline = load_baseline(args.baseline) if args.baseline else {}
    new, grandfathered = apply_baseline(findings, baseline)
    failures = gate_failures(new, args.fail_on)
    stale = stale_baseline_entries(findings, baseline, linted=linted) \
        if baseline else []

    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_dict() for f in new],
            "baselined": len(grandfathered),
            "stale_baseline": len(stale),
            "gate": {"fail_on": args.fail_on,
                     "failures": len(failures)},
        }, indent=2))
    elif args.format == "sarif":
        print(json.dumps(to_sarif(new, baselined=len(grandfathered)),
                         indent=2))
    else:
        for f in new:
            print(f.format())
        counts = {}
        for f in new:
            counts[f.severity] = counts.get(f.severity, 0) + 1
        summary = ", ".join(f"{counts[s]} {s}" for s in SEVERITIES
                            if s in counts) or "0"
        print(f"graftlint: {len(new)} finding(s) ({summary}); "
              f"{len(grandfathered)} baselined; "
              f"gate fail-on={args.fail_on}: "
              f"{'FAIL' if failures else 'ok'}")
        if stale:
            slots = sum(n for _k, n in stale)
            print(f"graftlint: {slots} stale baseline slot(s) match no "
                  "current finding (each silently admits one future "
                  "regression; --prune-baseline removes them)")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
