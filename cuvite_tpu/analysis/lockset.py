"""Tier 2b — lockset concurrency checker for serve/ (R019).

The PR-11 bug class this gates: the async daemon runs intake on reader
threads and dispatch on one dispatcher thread, so every shared counter
(``ServeStats``) and routing table mutated from both sides must hold its
lock — and the bugs that slipped through were exactly the mutations that
DIDN'T, which no correctness test catches because the race only loses
updates under real concurrency.

The checker is class-local lockset inference over one file at a time:

  * a **lock** is any ``with X:`` context whose dotted expression ends
    in a ``*lock*``-named attribute (``self._lock``, ``self.wlock``,
    ``self.stats.lock``); the lock's *owner* is the expression minus
    that last attribute (``self.stats.lock`` guards fields of
    ``self.stats``);
  * a field is **inferred guarded** when any mutation of it in the class
    happens under the owner's lock — assignments (``owner.f = ...``,
    ``owner.f[k] = ...``, ``owner.f += ...``) and mutating method calls
    (``owner.f.append(...)``, ``.pop``, ``.clear``, ...);
  * an explicit ``# graftlint: guarded-by=<lock>`` comment on a field's
    class-body declaration (or any mutation line) declares the guard
    where inference is ambiguous — e.g. a field whose only in-class
    mutations all forgot the lock;
  * every OTHER mutation of a guarded field that does not hold the lock
    is an R019 finding.  ``__init__``/``__post_init__``/``__new__`` are
    exempt (construction happens-before sharing), as are class-body
    defaults (they are declarations, not mutations).

Known limits (documented in ANALYSIS.md): aliases (``s = self.stats;
s.x += 1``) and cross-class views of the same lock object are invisible
— each class is checked against its own spelling of the lock, which is
exactly how the serve/ code is written.  Scope is ``cuvite_tpu/serve/``
only; elsewhere single-threaded mutation is the norm and the rule would
be noise.
"""

from __future__ import annotations

import ast
import re

from cuvite_tpu.analysis.engine import Rule, dotted, register

LOCKSET_SCOPE = ("cuvite_tpu/serve/",)

# Method names that mutate their receiver (list/deque/dict/set APIs).
MUTATING_METHODS = {
    "append", "appendleft", "extend", "extendleft", "insert",
    "pop", "popleft", "popitem", "remove", "discard", "clear",
    "add", "update", "setdefault", "sort", "reverse", "rotate", "fill",
}

_GUARDED_BY_RE = re.compile(
    r"#\s*graftlint:\s*guarded-by=([A-Za-z_][A-Za-z0-9_.]*)")

_CTOR_NAMES = {"__init__", "__post_init__", "__new__"}


def _lock_of_with_item(expr: ast.AST) -> tuple | None:
    """(lock_id, owner) when ``expr`` is a dotted chain whose last
    attribute names a lock; else None."""
    name = dotted(expr)
    if not name or "." not in name:
        return None
    owner, last = name.rsplit(".", 1)
    if "lock" not in last.lower():
        return None
    return name, owner


def _mutation_of(node: ast.AST) -> tuple | None:
    """(owner, field, verb) when ``node`` mutates a dotted attribute
    chain, else None.  The owner/field split mirrors the lock-owner
    convention: ``self.stats.jobs_done += 1`` mutates field
    ``jobs_done`` of owner ``self.stats``."""

    def split(attr_node) -> tuple | None:
        name = dotted(attr_node)
        if not name or "." not in name:
            return None
        owner, field = name.rsplit(".", 1)
        return owner, field

    if isinstance(node, ast.Assign):
        for tgt in node.targets:
            if isinstance(tgt, ast.Attribute):
                hit = split(tgt)
                if hit:
                    return (*hit, "=")
            elif isinstance(tgt, ast.Subscript) \
                    and isinstance(tgt.value, ast.Attribute):
                hit = split(tgt.value)
                if hit:
                    return (*hit, "[...]=")
    elif isinstance(node, ast.AugAssign):
        tgt = node.target
        if isinstance(tgt, ast.Attribute):
            hit = split(tgt)
            if hit:
                return (*hit, "+=")
        elif isinstance(tgt, ast.Subscript) \
                and isinstance(tgt.value, ast.Attribute):
            hit = split(tgt.value)
            if hit:
                return (*hit, "[...]+=")
    elif isinstance(node, ast.Call) \
            and isinstance(node.func, ast.Attribute) \
            and node.func.attr in MUTATING_METHODS \
            and isinstance(node.func.value, ast.Attribute):
        hit = split(node.func.value)
        if hit:
            return (*hit, f".{node.func.attr}()")
    return None


def _annotations(sf) -> dict:
    """# graftlint: guarded-by=<lock> pragmas -> {lineno: lock_id}.
    Read from real comment tokens (same reason the engine's
    suppressions are: ANALYSIS.md quotes the syntax in prose)."""
    out = {}
    for lineno, comment in sf._iter_comments():
        m = _GUARDED_BY_RE.search(comment)
        if m:
            out[lineno] = m.group(1)
    return out


class _ClassFacts:
    """Lock regions, mutations, reads, and declared fields of one
    class.  Shared infrastructure: R019 consumes the mutations, R021
    (analysis/lockorder.py) additionally consumes the reads-in-test and
    the retained held-map, and concheck's runtime instrumentation seeds
    its shared-field inventory from :func:`lockset_summary` built on
    these facts."""

    def __init__(self, sf, cls: ast.ClassDef, annotations: dict):
        self.cls = cls
        # Nodes belonging to NESTED classes are excluded wholesale: the
        # rule analyzes every ClassDef separately, and double-attributing
        # an inner class's mutations to the outer class would both
        # duplicate findings and cross-pollute the inferred guards.
        nested: set = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.ClassDef) and node is not cls:
                nested.update(id(n) for n in ast.walk(node))
        self._nested = nested
        # node-id -> set of lock ids held (lexically) at that node.
        held: dict = {}
        self.mutations: list = []   # (owner, field, verb, node, held, ctor)
        self.guards: dict = {}      # (owner, field) -> set of lock ids
        self.declared: set = set()  # (owner, field) guards from pragmas
        for node in ast.walk(cls):
            if id(node) in nested:
                continue
            if isinstance(node, ast.With):
                locks = set()
                for item in node.items:
                    hit = _lock_of_with_item(item.context_expr)
                    if hit:
                        locks.add(hit[0])
                if not locks:
                    continue
                for inner in ast.walk(node):
                    if inner is node:
                        continue
                    held.setdefault(id(inner), set()).update(locks)
        self.held = held
        body_nodes = {id(n) for n in cls.body}  # class-body declarations
        for node in ast.walk(cls):
            if id(node) in nested:
                continue
            mut = _mutation_of(node)
            if mut is None:
                continue
            owner, field, verb = mut
            if id(node) in body_nodes:
                continue  # dataclass defaults / class attrs: declarations
            fn = sf.enclosing_function(node)
            in_ctor = fn is not None and fn.name in _CTOR_NAMES
            locks_held = held.get(id(node), set())
            self.mutations.append((owner, field, verb, node, locks_held,
                                   in_ctor))
            for lock in locks_held:
                lowner = lock.rsplit(".", 1)[0]
                if lowner == owner:
                    self.guards.setdefault((owner, field), set()).add(lock)
        # Explicit annotations: on a class-body declaration the owner is
        # 'self' (the instance the lock lives on); on a mutation line the
        # owner comes from the mutation itself.
        decl_fields = {}
        for stmt in cls.body:
            if isinstance(stmt, ast.AnnAssign) \
                    and isinstance(stmt.target, ast.Name):
                decl_fields[stmt.lineno] = stmt.target.id
            elif isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        decl_fields[stmt.lineno] = t.id
        lo, hi = cls.lineno, max(getattr(cls, "end_lineno", cls.lineno),
                                 cls.lineno)
        for lineno, lock in annotations.items():
            if not (lo <= lineno <= hi):
                continue
            if lineno in decl_fields:
                self.guards.setdefault(
                    ("self", decl_fields[lineno]), set()).add(lock)
                self.declared.add(("self", decl_fields[lineno]))
                continue
            for owner, field, _verb, node, _held, _ctor in self.mutations:
                if node.lineno == lineno:
                    self.guards.setdefault((owner, field), set()).add(lock)
                    self.declared.add((owner, field))

    def reads_in_test(self, sf) -> list:
        """(owner, field, node, held, func) for every Load of a dotted
        ``owner.field`` inside an ``if``/``while`` TEST expression of
        this class — the check-then-act shape R021 polices."""
        out = []
        for node in ast.walk(self.cls):
            if id(node) in self._nested:
                continue
            if not isinstance(node, (ast.If, ast.While)):
                continue
            for sub in ast.walk(node.test):
                if not isinstance(sub, ast.Attribute) \
                        or not isinstance(sub.ctx, ast.Load):
                    continue
                name = dotted(sub)
                if not name or "." not in name:
                    continue
                owner, field = name.rsplit(".", 1)
                out.append((owner, field, sub,
                            self.held.get(id(sub), set()),
                            sf.enclosing_function(node)))
        return out


def lockset_summary(sf) -> list:
    """The file's guarded-field inventory as plain JSON: one entry per
    (class, owner, field) whose lock discipline R019 establishes —
    inferred from locked mutations or declared via ``guarded-by``
    pragmas.  This is the shared-field inventory concheck's dynamic
    instrumentation is seeded from (ISSUE 13), and the declared bit is
    what its stale-annotation cross-check keys on."""
    out = []
    annotations = _annotations(sf)
    for cls in sf.walk():
        if not isinstance(cls, ast.ClassDef):
            continue
        facts = _ClassFacts(sf, cls, annotations)
        for (owner, field), locks in sorted(facts.guards.items()):
            out.append({
                "class": cls.name,
                "owner": owner,
                "field": field,
                "locks": sorted(locks),
                "declared": (owner, field) in facts.declared,
            })
    return out


@register
class UnguardedLockedField(Rule):
    id = "R019"
    severity = "high"
    title = "mutation of a lock-guarded field outside the lock in serve/"

    def check(self, sf):
        if not sf.rel.startswith(LOCKSET_SCOPE):
            return
        annotations = _annotations(sf)
        for cls in sf.walk():
            if not isinstance(cls, ast.ClassDef):
                continue
            facts = _ClassFacts(sf, cls, annotations)
            for owner, field, verb, node, held, in_ctor in facts.mutations:
                if in_ctor:
                    continue
                locks = facts.guards.get((owner, field))
                if not locks:
                    continue
                if held & locks:
                    continue
                want = " or ".join(sorted(locks))
                yield self.finding(
                    sf, node,
                    f"'{owner}.{field}' {verb} without holding {want}: "
                    f"other mutations in class '{cls.name}' (or an "
                    "explicit guarded-by annotation) establish the "
                    "lock discipline for this field, so this write can "
                    "race the locked ones (lost update / torn read — "
                    "the PR-11 ServeStats class of bug); take the lock, "
                    "or justify with an inline disable")
