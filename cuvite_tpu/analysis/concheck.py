"""Tier 4 (dynamic half) — concheck: deterministic-schedule concurrency
checking for the serving daemon.

R019's lockset inference sees which fields hold their lock; it cannot
see happens-before, lock ordering, or interleavings — it caught the
PR-12 ``_routes`` race by the luck of a syntactic pattern.  concheck
closes that gap by RUNNING the real daemon code under the cooperative
scheduler in serve/sync.py and judging what it observes:

  * **Inventory** — the shared fields to watch come from the R019
    lockset summaries (:func:`cuvite_tpu.analysis.lockset.
    lockset_summary` over ``cuvite_tpu/serve/``): every field whose
    lock discipline the static tier establishes is instrumented at
    runtime (attribute interception for scalar counters, tracked
    proxies for dict/deque fields), so the static and dynamic tiers
    can never watch different field sets.
  * **Race detection** — a vector-clock happens-before detector
    (FastTrack-style epochs): two accesses to one field, at least one
    a write, unordered by the happens-before edges the scheduler
    derives from lock release→acquire, event set→wait, and thread
    start/join, is a race — reported with BOTH access stacks.  Because
    the judgment is happens-before (not "did the bad interleaving
    fire"), a single schedule can convict a race whose loss window is
    nanoseconds wide.
  * **Annotation cross-check** — fields carrying an explicit
    ``# graftlint: guarded-by=X`` pragma are compared against the lock
    ownership the schedules actually observe; a declared lock never
    held at any access is a *stale annotation* warning (the static
    tier is being lied to).
  * **Exploration** — seeded random-walk and PCT schedules
    (serve/sync.py); every failing schedule replays from its
    ``(strategy, seed)`` pair.  ``CUVITE_SCHED_BUDGET`` tunes the
    per-run schedule count (utils/envknob.py validation).
  * **Scenarios** — the daemon's submit/dispatch/drain/stats state
    machine driven end to end with the stub runner and the virtual
    clock: intake threads call the real ``ServeDaemon.handle``,
    the real ``_dispatch_loop`` runs on a managed thread, a drainer
    races SIGTERM-style drain against in-flight work, and a stats
    poller hammers the snapshot path.  After every schedule the job
    conservation ledger (``done+failed+shed+pending == submitted``)
    and wire-level exactly-once delivery are asserted.  The harness's
    fake clients also assert the PR-12 claim that **no lock is held
    across a socket send** (only the client's own wlock may be held).

Dynamic exploration results are never cached — only the static tier's
summaries ride the incremental lint cache.  Self-check CLI (wired as
``tools/lint.sh --sched-smoke``)::

    python -m cuvite_tpu.analysis.concheck [--budget N] [--seed S]
        [--scenario NAME] [--format text|json] [--list]

runs the clean scenarios expecting zero findings AND the known-bug
fixtures (the resurrected ``_routes`` race, a send-under-lock daemon)
expecting detection — exit 1 if either side surprises.
"""

from __future__ import annotations

import collections
import os
import traceback
import types

from cuvite_tpu.serve import sync

SERVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "serve")

# Schedule budget: how many seeded schedules one explore() run walks.
BUDGET_ENV = "CUVITE_SCHED_BUDGET"
DEFAULT_BUDGET = 240


def schedule_budget(default: int = DEFAULT_BUDGET) -> int:
    from cuvite_tpu.utils.envknob import env_int

    return env_int(BUDGET_ENV, default, minimum=1, maximum=1_000_000)


# ---------------------------------------------------------------------------
# Shared-field inventory (seeded from the R019 lockset summaries)


def serve_inventory(serve_dir: str = SERVE_DIR) -> list:
    """The guarded-field inventory of the real serve/ package: one
    entry per (class, owner expr, field, locks, declared) the static
    lockset tier establishes."""
    from cuvite_tpu.analysis.engine import SourceFile
    from cuvite_tpu.analysis.lockset import lockset_summary

    out = []
    for name in sorted(os.listdir(serve_dir)):
        if not name.endswith(".py"):
            continue
        path = os.path.join(serve_dir, name)
        with open(path, encoding="utf-8") as fh:
            text = fh.read()
        rel = f"cuvite_tpu/serve/{name}"
        out.extend(lockset_summary(SourceFile(text, path=path, rel=rel)))
    return out


# ---------------------------------------------------------------------------
# Vector-clock race detection


def _stack(skip: int = 3, limit: int = 14) -> tuple:
    """A compact (file, line, func, source) stack for race reports,
    trimmed to repo frames (the sync/concheck plumbing is noise)."""
    frames = traceback.extract_stack()[:-skip]
    keep = []
    for fr in frames[-limit:]:
        fn = fr.filename.replace(os.sep, "/")
        if fn.endswith(("serve/sync.py", "analysis/concheck.py",
                        "/threading.py")):
            continue
        keep.append((fn.rsplit("cuvite_tpu/", 1)[-1], fr.lineno,
                     fr.name, fr.line or ""))
    return tuple(keep[-8:])


class RaceDetector:
    """FastTrack-style epoch race detection over the scheduler's
    happens-before order (see module docstring).  ``record`` is called
    by Scheduler.access for every annotated shared-field access."""

    def __init__(self):
        # field -> {"w": {tid: (epoch, name, locks, stack)},
        #           "r": {tid: (epoch, name, locks, stack)}}
        self.state: dict = collections.defaultdict(
            lambda: {"w": {}, "r": {}})
        self.races: list = []
        self._seen: set = set()
        # field -> {"declared": set, "held": Counter, "accesses": int}
        self.guard_obs: dict = {}

    def record(self, key: str, kind: str, thread, held, declared) -> None:
        tid = thread.idx
        vc = thread.vc
        st = self.state[key]
        if declared:
            obs = self.guard_obs.setdefault(
                key, {"declared": set(), "held": collections.Counter(),
                      "accesses": 0})
            obs["declared"] |= set(declared)
            obs["held"].update(held)
            obs["accesses"] += 1
        me = (vc.get(tid, 0), thread.name, tuple(held), _stack())
        # A write conflicts with every prior unordered access; a read
        # only with prior unordered writes.
        against = (("w", "r") if kind == "write" else ("w",))
        for side in against:
            for otid, (epoch, oname, olocks, ostack) in st[side].items():
                if otid == tid:
                    continue
                if vc.get(otid, 0) >= epoch:
                    continue            # happens-before: ordered
                okind = "write" if side == "w" else "read"
                sig = (key, ostack[-1:], me[3][-1:], okind, kind)
                if sig in self._seen:
                    continue
                self._seen.add(sig)
                self.races.append({
                    "field": key,
                    "first": {"kind": okind, "thread": oname,
                              "locks": list(olocks),
                              "stack": [list(f) for f in ostack]},
                    "second": {"kind": kind, "thread": me[1],
                               "locks": list(held),
                               "stack": [list(f) for f in me[3]]},
                })
        st["w" if kind == "write" else "r"][tid] = me

    def warnings(self) -> list:
        """Stale guarded-by annotations: a declared lock that NO
        observed access of the field actually held, while the field was
        accessed at least once."""
        out = []
        for key, obs in sorted(self.guard_obs.items()):
            if not obs["accesses"]:
                continue
            never_held = sorted(lk for lk in obs["declared"]
                                if obs["held"].get(lk, 0) == 0)
            if never_held:
                observed = sorted(obs["held"]) or ["<none>"]
                out.append(
                    f"stale guarded-by annotation on {key}: declared "
                    f"{','.join(never_held)} was never held across "
                    f"{obs['accesses']} accesses (observed locks: "
                    f"{','.join(observed)})")
        return out


# ---------------------------------------------------------------------------
# Runtime instrumentation of the inventory


class _TrackedDict(dict):
    """dict proxy reporting reads/writes of the backing field to the
    scheduler (and through it the race detector)."""

    def _cc(self, kind):
        self._cc_sched.access(self._cc_key, kind, self._cc_declared)

    def __getitem__(self, k):
        self._cc("read")
        return dict.__getitem__(self, k)

    def __contains__(self, k):
        self._cc("read")
        return dict.__contains__(self, k)

    def get(self, k, default=None):
        self._cc("read")
        return dict.get(self, k, default)

    def __len__(self):
        self._cc("read")
        return dict.__len__(self)

    def __iter__(self):
        self._cc("read")
        return dict.__iter__(self)

    def values(self):
        self._cc("read")
        return dict.values(self)

    def items(self):
        self._cc("read")
        return dict.items(self)

    def __setitem__(self, k, v):
        self._cc("write")
        dict.__setitem__(self, k, v)

    def __delitem__(self, k):
        self._cc("write")
        dict.__delitem__(self, k)

    def pop(self, k, *default):
        self._cc("write")
        return dict.pop(self, k, *default)

    def clear(self):
        self._cc("write")
        dict.clear(self)

    def update(self, *a, **kw):
        self._cc("write")
        dict.update(self, *a, **kw)

    def setdefault(self, k, default=None):
        self._cc("write")
        return dict.setdefault(self, k, default)


class _TrackedDeque(collections.deque):
    def _cc(self, kind):
        self._cc_sched.access(self._cc_key, kind, self._cc_declared)

    def append(self, x):
        self._cc("write")
        collections.deque.append(self, x)

    def appendleft(self, x):
        self._cc("write")
        collections.deque.appendleft(self, x)

    def pop(self):
        self._cc("write")
        return collections.deque.pop(self)

    def popleft(self):
        self._cc("write")
        return collections.deque.popleft(self)

    def clear(self):
        self._cc("write")
        collections.deque.clear(self)

    def extend(self, it):
        self._cc("write")
        collections.deque.extend(self, it)

    def __iter__(self):
        self._cc("read")
        return collections.deque.__iter__(self)

    def __len__(self):
        self._cc("read")
        return collections.deque.__len__(self)


class _TrackedList(list):
    def _cc(self, kind):
        self._cc_sched.access(self._cc_key, kind, self._cc_declared)

    def append(self, x):
        self._cc("write")
        list.append(self, x)

    def extend(self, it):
        self._cc("write")
        list.extend(self, it)

    def clear(self):
        self._cc("write")
        list.clear(self)

    def pop(self, *a):
        self._cc("write")
        return list.pop(self, *a)

    def __iter__(self):
        self._cc("read")
        return list.__iter__(self)

    def __len__(self):
        self._cc("read")
        return list.__len__(self)


_TRACKED = {dict: _TrackedDict, collections.deque: _TrackedDeque,
            list: _TrackedList}
_attr_subclasses: dict = {}


def _attr_instrumented_class(base: type, fields: frozenset) -> type:
    """A ``base`` subclass whose __getattribute__/__setattr__ report
    accesses to ``fields`` (cached per (base, fields) — instances get
    their scheduler/keys via object.__setattr__'d control attrs)."""
    key = (base, fields)
    sub = _attr_subclasses.get(key)
    if sub is not None:
        return sub
    watched = set(fields)

    def __getattribute__(self, name):
        if name in watched:
            try:
                ctl = object.__getattribute__(self, "_cc_ctl")
            except AttributeError:
                ctl = None
            if ctl is not None:
                k, declared = ctl.fields[name]
                ctl.sched.access(k, "read", declared)
        return base.__getattribute__(self, name)

    def __setattr__(self, name, value):
        if name in watched:
            try:
                ctl = object.__getattribute__(self, "_cc_ctl")
            except AttributeError:
                ctl = None
            if ctl is not None:
                k, declared = ctl.fields[name]
                ctl.sched.access(k, "write", declared)
        base.__setattr__(self, name, value)

    sub = type(f"Concheck{base.__name__}", (base,), {
        "__getattribute__": __getattribute__,
        "__setattr__": __setattr__,
    })
    _attr_subclasses[key] = sub
    return sub


class _Ctl:
    __slots__ = ("sched", "fields")

    def __init__(self, sched, fields):
        self.sched = sched
        self.fields = fields    # attr -> (key, declared lock names)


def _resolve_chain(obj, expr: str):
    """'self.a.b' -> getattr(getattr(obj, 'a'), 'b'); None on a miss."""
    cur = obj
    parts = expr.split(".")
    if parts[0] != "self":
        return None
    for p in parts[1:]:
        cur = getattr(cur, p, None)
        if cur is None:
            return None
    return cur


def instrument(sched, roots, inventory) -> dict:
    """Attach the shared-field inventory to live objects.

    ``roots`` are the objects under test (daemon, server, stats, ...);
    each inventory entry resolves its owner expression against every
    root whose class name matches, locks get canonical
    ``OwnerClass.attr`` names (what the lock-ownership assertions and
    race reports print), and fields are wrapped: container fields with
    tracked proxies, scalars with an attribute-intercepting subclass.
    Returns {field key: declared lock names} for introspection."""
    by_cls: dict = {}
    for r in roots:
        by_cls.setdefault(type(r).__name__, []).append(r)
    # (id(owner), attr) -> (owner, key, declared names)
    plan: dict = {}
    for ent in inventory:
        for root in by_cls.get(ent["class"], ()):
            owner = (root if ent["owner"] == "self"
                     else _resolve_chain(root, ent["owner"]))
            if owner is None:
                continue
            key = f"{type(owner).__name__}.{ent['field']}"
            declared: set = set()
            for lock_expr in ent["locks"]:
                if not lock_expr.startswith("self."):
                    continue            # non-self spellings: unresolvable
                lk = _resolve_chain(root, lock_expr)
                if lk is None:
                    continue
                lk_owner = _resolve_chain(
                    root, lock_expr.rsplit(".", 1)[0]) or owner
                cname = (f"{type(lk_owner).__name__}."
                         f"{lock_expr.rsplit('.', 1)[1]}")
                if hasattr(lk, "name"):
                    lk.name = cname
                declared.add(cname)
            slot = plan.setdefault((id(owner), ent["field"]),
                                   [owner, key, set()])
            slot[2] |= (declared if ent["declared"] else set())
    out: dict = {}
    per_owner: dict = {}
    for (oid, field), (owner, key, declared) in plan.items():
        out[key] = sorted(declared)
        val = owner.__dict__.get(field)
        proxy_cls = _TRACKED.get(type(val))
        if proxy_cls is not None:
            proxy = proxy_cls(val)
            proxy._cc_sched = sched
            proxy._cc_key = key
            proxy._cc_declared = frozenset(declared)
            object.__setattr__(owner, field, proxy)
            continue
        per_owner.setdefault(id(owner), (owner, {}))[1][field] = (
            key, frozenset(declared))
    for owner, fields in per_owner.values():
        sub = _attr_instrumented_class(type(owner),
                                       frozenset(fields))
        object.__setattr__(owner, "_cc_ctl", _Ctl(sched, fields))
        owner.__class__ = sub
    return out


# ---------------------------------------------------------------------------
# The daemon harness


class _FakeConn:
    def close(self):
        pass


class FakeClient:
    """A _Client-shaped sink: records every payload, mimics the write
    lock, and asserts the PR-12 "no lock held across a socket send"
    claim — a send performed while any lock other than this client's
    own wlock is held is a recorded failure (head-of-line stall: a slow
    peer would block whatever that lock guards)."""

    def __init__(self, sched, idx: int):
        self._sched = sched
        self.idx = idx
        self.conn = _FakeConn()
        # Per-INSTANCE lock name: sending to client B while holding
        # client A's wlock is exactly the cross-client stall the
        # assertion polices, so only this client's own lock is exempt.
        self._wlock_name = f"_Client.wlock#{idx}"
        self.wlock = sync.Lock(name=self._wlock_name)
        self.sent: list = []

    def send(self, payload: dict) -> bool:
        held = [n for n in self._sched.held_lock_names()
                if n != self._wlock_name]
        if held:
            self._sched.record_failure(
                "lock-across-send",
                f"socket send with lock(s) held: {','.join(held)} — a "
                "slow client would head-of-line-stall whatever these "
                "locks guard",
                stack="".join(traceback.format_stack(limit=12)))
        with self.wlock:
            self.sent.append(payload)
        return True


def _stub_runner(graphs, **kw):
    """Deterministic pure-function batch runner (no jax dispatch):
    milliseconds per schedule, identical results per graph."""
    import numpy as np

    results = []
    for g in graphs:
        nv = g.num_vertices
        key = int(np.sum(g.tails)) % 997 if g.num_edges else 0
        results.append(types.SimpleNamespace(
            communities=(np.arange(nv) + key) % max(nv, 1),
            modularity=key / 997.0, phases=[1], total_iterations=3,
            num_communities=nv))
    return types.SimpleNamespace(results=results, n_phases=1)


class _StubStreamSession:
    """jax-free StreamSession twin for the streaming scenarios: the
    daemon's ``delta`` verb and the StreamPool's LRU/ledger machinery
    run for real; only the device work (slab upload, chokepoint apply,
    re-cluster) is stubbed — the same seam LouvainServer's injected
    ``runner`` gives the batch scenarios."""

    def __init__(self, graph, tracer=None):
        import numpy as np

        self.nv = graph.num_vertices
        self.ne = graph.num_edges
        self.frontier_frac = 0.0
        self._labels = None
        self._np = np

    def hbm_bytes(self) -> int:
        return 1000

    def labels(self):
        return self._labels

    def apply_delta(self, batch):
        self.ne = self.ne + batch.n_ins - batch.n_del
        self.frontier_frac = 0.25
        return {"n_ins": batch.n_ins, "n_del": batch.n_del,
                "n_del_hit": batch.n_del, "ne": self.ne,
                "frontier_frac": 0.25, "wall_s": 0.0}

    def recluster(self, warm="labels", **kw):
        self._labels = self._np.zeros(self.nv, dtype=self._np.int64)
        return types.SimpleNamespace(
            modularity=0.5, num_communities=1, phases=[1],
            total_iterations=2, communities=self._labels)


def _delta_reqs(n: int, tenant: str, nv: int = 6) -> list:
    """A tenant's delta stream: every request carries the graph spec
    (so an LRU-evicted session transparently re-admits — maximizing
    admit/evict interleavings under a tight budget), the last one also
    re-clusters."""
    reqs = []
    for i in range(n):
        req = {"op": "delta", "tenant": tenant,
               "graph": {"nv": nv, "src": [0, 1, 2], "dst": [1, 2, 3]},
               "ins": [[i % nv, (i + 2) % nv, 1.0]],
               "del": []}
        if i == n - 1:
            req["recluster"] = True
        reqs.append(req)
    return reqs


def _graph_reqs(n_jobs: int, tenant: str, *, with_ids: bool = False,
                nv: int = 6, ne: int = 8) -> list:
    import numpy as np

    reqs = []
    for i in range(n_jobs):
        rng = np.random.default_rng(1000 + i)
        req = {"op": "submit", "graph": {
            "nv": nv,
            "src": [int(x) for x in rng.integers(0, nv, ne)],
            "dst": [int(x) for x in rng.integers(0, nv, ne)],
        }, "tenant": tenant}
        if with_ids:
            req["id"] = f"{tenant}-req-{i}"
        reqs.append(req)
    return reqs


def _racy_route_results(self, finished, fails, sheds):
    """The PR-12 ``_routes`` race, resurrected as a fixture: lock-free
    pops racing intake's locked check-then-insert.  concheck MUST
    convict this within the default budget (the tier-1 regression
    pin)."""
    for job_id, res in finished:
        client, want_labels = self._routes.pop(job_id, (None, False))
        payload = {"job_id": job_id, "q": float(res.modularity)}
        self._send_or_drop(client, {"result": payload})
    for job_id, err in fails:
        client, _ = self._routes.pop(job_id, (None, False))
        self._send_or_drop(client, {"failed": {"job_id": job_id,
                                               "error": err}})
    for job_id, late_s in sheds:
        client, _ = self._routes.pop(job_id, (None, False))
        self._send_or_drop(client, {"shed": {"job_id": job_id,
                                             "late_s": late_s}})


def _send_under_lock_route_results(self, finished, fails, sheds):
    """A daemon variant that ships results while still holding the
    daemon lock — the head-of-line-stall regression the no-lock-across-
    send assertion exists to catch."""
    for job_id, res in finished:
        with self.lock:
            client, _ = self._routes.pop(job_id, (None, False))
            self._send_or_drop(client, {"result": {"job_id": job_id}})
    for job_id, err in fails:
        with self.lock:
            client, _ = self._routes.pop(job_id, (None, False))
            self._send_or_drop(client, {"failed": {"job_id": job_id,
                                                   "error": err}})
    for job_id, late_s in sheds:
        with self.lock:
            client, _ = self._routes.pop(job_id, (None, False))
            self._send_or_drop(client, {"shed": {"job_id": job_id}})


class DaemonScenario:
    """One explorable daemon workload: intake threads driving the real
    ``handle``, the real dispatcher (the serial loop, or the pipelined
    packer/executor seam-thread pair — ISSUE 14), a stats poller, and a
    drainer — conservation and exactly-once checked after every
    schedule.  ``pack_hold_s`` injects a virtual-clock sleep INSIDE the
    pack stage (a schedule point mid-pack), so schedules can interleave
    a drain request with an in-flight pack — the
    ``drain-vs-inflight-pack`` target."""

    def __init__(self, name: str, *, n_intake: int = 2, jobs_each: int = 2,
                 fault_plan: str | None = None, variant=None,
                 drain_after_s: float = 0.03, with_ids: bool = False,
                 b_max: int = 2, linger_s: float = 0.02,
                 max_retries: int = 2, retry_base_s: float = 0.05,
                 pipelined: bool = False, pack_hold_s: float = 0.0,
                 delta_tenants: int = 0, deltas_each: int = 0,
                 stream_budget_bytes: int | None = None,
                 merge_packing: bool = False, big_jobs: int = 0):
        self.name = name
        self.n_intake = n_intake
        self.jobs_each = jobs_each
        self.fault_plan = fault_plan
        self.variant = variant
        self.drain_after_s = drain_after_s
        self.with_ids = with_ids
        self.b_max = b_max
        self.linger_s = linger_s
        self.max_retries = max_retries
        self.retry_base_s = retry_base_s
        self.pipelined = pipelined
        self.pack_hold_s = pack_hold_s
        # Streaming arm (ISSUE 17): delta_tenants reader threads each
        # driving deltas_each `delta` requests through the REAL
        # _handle_delta/StreamPool path with stub sessions; a tight
        # stream_budget_bytes forces LRU evictions mid-schedule.
        self.delta_tenants = delta_tenants
        self.deltas_each = deltas_each
        self.stream_budget_bytes = stream_budget_bytes
        # Merge-aware packer arm (ISSUE 20): an extra intake thread
        # submits ``big_jobs`` larger-class graphs; once a plain big
        # batch completes, overflowing small bins may pop PAST b_max
        # and dispatch merged.  ``merged_batches_seen`` accumulates
        # across schedules so the tier-1 test can assert the scenario
        # actually exercises the merge path (teeth), not just that it
        # stays clean.
        self.merge_packing = merge_packing
        self.big_jobs = big_jobs
        self.merged_batches_seen = 0
        self.inventory = None   # filled by explore()/run_schedule()

    def setup(self, sched) -> dict:
        from cuvite_tpu.serve.daemon import ServeDaemon
        from cuvite_tpu.serve.faults import FaultPlan
        from cuvite_tpu.serve.queue import LouvainServer, ServeConfig

        server = LouvainServer(
            ServeConfig(b_max=self.b_max, linger_s=self.linger_s,
                        engine="fused", max_retries=self.max_retries,
                        retry_base_s=self.retry_base_s,
                        merge_packing=self.merge_packing,
                        stream_budget_bytes=(self.stream_budget_bytes
                                             or 256 << 20)),
            clock=sched.clock, sleep=sched.sleep,
            faults=FaultPlan.parse(self.fault_plan),
            runner=_stub_runner,
            stream_factory=(_StubStreamSession if self.deltas_each
                            else None))
        daemon = ServeDaemon(server, sock_path="<concheck>",
                             poll_s=0.01, pipelined=self.pipelined)
        for attr in ("_wake", "_drain_req", "_done"):
            getattr(daemon, attr).name = f"ServeDaemon.{attr}"
        daemon.lock.name = "ServeDaemon.lock"
        server.streams.lock.name = "StreamPool.lock"
        if self.pack_hold_s:
            # The hold runs on the server's (scheduler) sleep: a
            # schedule point inside the pack window, BEFORE the real
            # pack — every interleaving of drain-vs-pack is reachable.
            orig_pack = server.pack_batch

            def holding_pack(jobs, key, trigger, now):
                server.sleep(self.pack_hold_s)
                return orig_pack(jobs, key, trigger, now)

            server.pack_batch = holding_pack
        if self.variant is not None:
            daemon._route_results = types.MethodType(self.variant, daemon)
        inventory = self.inventory or serve_inventory()
        instrument(sched, [daemon, server, server.stats, server.streams],
                   inventory)
        clients = [FakeClient(sched, i) for i in range(self.n_intake)]
        acks: dict = {}
        delta_resps: list = []

        def intake(client, reqs):
            for req in reqs:
                resp = daemon.handle(req, client)
                if resp.get("ok") and "job_id" in resp:
                    acks[resp["job_id"]] = client

        def delta_intake(client, reqs):
            for req in reqs:
                delta_resps.append(daemon.handle(req, client))

        def poller():
            for _ in range(2):
                daemon.handle({"op": "stats"}, clients[0])

        def drainer():
            sched.sleep(self.drain_after_s)
            daemon.request_drain()

        if self.pipelined:
            pipe = daemon.pipe
            pipe.handoff._cond.lock.name = "Handoff.lock"
            daemon._dispatch_thread = sched.spawn(
                pipe._exec_loop, name="executor")
            pipe.pack_thread = sched.spawn(pipe._pack_loop, name="packer")
        else:
            daemon._dispatch_thread = sched.spawn(
                daemon._dispatch_loop, name="dispatch")
        for i, client in enumerate(clients):
            sched.spawn(intake, name=f"intake{i}", args=(
                client, _graph_reqs(self.jobs_each, f"t{i}",
                                    with_ids=self.with_ids)))
        if self.big_jobs:
            # Larger-class intake (ISSUE 20): nv=8192 with ~9k arcs
            # symmetrizes past the 16384-edge floor, landing in
            # (8192, 32768) — an exact n_sub=2 sub-row multiple of the
            # small graphs' (4096, 16384) floor class.  Schedules where
            # the big plain batch completes before the small bin
            # overflows dispatch a MERGED small batch; the others serve
            # plain — conservation/exactly-once must hold in both.
            sched.spawn(intake, name="intake-big", args=(
                clients[0], _graph_reqs(self.big_jobs, "big",
                                        with_ids=self.with_ids,
                                        nv=8192, ne=9000)))
        for t in range(self.delta_tenants):
            sched.spawn(delta_intake, name=f"delta{t}", args=(
                clients[0], _delta_reqs(self.deltas_each, f"d{t}")))
        sched.spawn(poller, name="poller")
        sched.spawn(drainer, name="drainer")
        return {"daemon": daemon, "server": server, "clients": clients,
                "acks": acks, "delta_resps": delta_resps}

    def check(self, sched, ctx) -> None:
        daemon, server = ctx["daemon"], ctx["server"]
        self.merged_batches_seen += int(server.stats.merged_batches)
        if not daemon._done.is_set():
            sched.record_failure(
                "no-drain", "dispatcher never completed the drain")
            return
        cons = server.conservation()
        if not cons["ok"] or cons["pending"] != 0:
            sched.record_failure(
                "conservation", f"job ledger broken after drain: {cons}")
        terminal: collections.Counter = collections.Counter()
        for client in ctx["clients"]:
            for payload in client.sent:
                for kind in ("result", "failed", "shed"):
                    if kind in payload:
                        terminal[payload[kind]["job_id"]] += 1
        for job_id in ctx["acks"]:
            n = terminal.get(job_id, 0)
            if n != 1:
                sched.record_failure(
                    "exactly-once",
                    f"job {job_id} produced {n} terminal reports "
                    "(want exactly 1)")
        for job_id in terminal:
            if job_id not in ctx["acks"]:
                sched.record_failure(
                    "phantom-result",
                    f"terminal report for never-acked job {job_id}")
        if self.deltas_each:
            # ISSUE 17 — tenant deltas racing drain + LRU eviction:
            # every delta request terminates exactly once (a dict reply,
            # ok or a loud refusal — never dropped, never doubled), the
            # stream pool's byte ledger conserves, and _finalize cleared
            # all residency.
            want = self.delta_tenants * self.deltas_each
            resps = ctx["delta_resps"]
            if len(resps) != want or not all(
                    isinstance(r, dict) for r in resps):
                sched.record_failure(
                    "delta-exactly-once",
                    f"{len(resps)}/{want} delta replies "
                    f"(non-dict: {sum(not isinstance(r, dict) for r in resps)})")
            scons = server.streams.conservation()
            if not scons["ok"]:
                sched.record_failure(
                    "stream-conservation",
                    f"stream pool ledger broken after drain: {scons}")
            elif scons["resident"] != 0:
                sched.record_failure(
                    "stream-residency",
                    f"{scons['resident']} sessions survived _finalize "
                    "(pool.clear() missed them)")


# ---------------------------------------------------------------------------
# Exploration driver


class ScheduleReport:
    def __init__(self, *, scenario, strategy, seed, failures, races,
                 warnings, signature, steps, trace):
        self.scenario = scenario
        self.strategy = strategy
        self.seed = seed
        self.failures = failures
        self.races = races
        self.warnings = warnings
        self.signature = signature
        self.steps = steps
        self.trace = trace

    @property
    def clean(self) -> bool:
        return not self.failures and not self.races


class ExploreReport:
    def __init__(self, scenario: str):
        self.scenario = scenario
        self.schedules = 0
        self.distinct = 0
        self.steps = 0
        self.failing: list = []     # ScheduleReports with findings
        self.warnings: list = []
        self._sigs: set = set()

    @property
    def clean(self) -> bool:
        return not self.failing

    def races(self) -> list:
        return [r for rep in self.failing for r in rep.races]

    def failures(self) -> list:
        return [f for rep in self.failing for f in rep.failures]

    def summary(self) -> dict:
        return {
            "scenario": self.scenario,
            "schedules": self.schedules,
            "distinct_interleavings": self.distinct,
            "steps": self.steps,
            "failing_schedules": len(self.failing),
            "races": len(self.races()),
            "warnings": list(self.warnings),
            "replay": [{"strategy": rep.strategy, "seed": rep.seed}
                       for rep in self.failing[:8]],
        }


def run_schedule(scenario: DaemonScenario, *, seed: int,
                 strategy: str = "random",
                 max_steps: int = 50000) -> ScheduleReport:
    """ONE schedule, fully determined by (scenario, strategy, seed) —
    the replay unit every failure report names."""
    detector = RaceDetector()
    sched = sync.Scheduler(seed=seed, strategy=strategy,
                           max_steps=max_steps, detector=detector)
    with sync.activated(sched):
        ctx = scenario.setup(sched)
    sched.run()
    scenario.check(sched, ctx)
    return ScheduleReport(
        scenario=scenario.name, strategy=strategy, seed=seed,
        failures=list(sched.failures), races=list(detector.races),
        warnings=detector.warnings(), signature=sched.signature(),
        steps=sched.steps, trace=list(sched.trace))


def explore(scenario: DaemonScenario, *, budget: int | None = None,
            seed: int = 0, strategies=("random", "pct"),
            stop_on_failure: bool = False, tracer=None) -> ExploreReport:
    """Walk ``budget`` seeded schedules of ``scenario``; every failing
    schedule is kept with its (strategy, seed) replay handle.  Results
    are NEVER cached — each call explores live."""
    if budget is None:
        budget = schedule_budget()
    if scenario.inventory is None:
        scenario.inventory = serve_inventory()
    report = ExploreReport(scenario.name)
    warned: set = set()
    for i in range(budget):
        strat = strategies[i % len(strategies)]
        s_seed = seed * 1_000_003 + i
        rep = run_schedule(scenario, seed=s_seed, strategy=strat)
        report.schedules += 1
        report.steps += rep.steps
        report._sigs.add(rep.signature)
        for w in rep.warnings:
            if w not in warned:
                warned.add(w)
                report.warnings.append(w)
        if not rep.clean:
            report.failing.append(rep)
            if tracer is not None:
                tracer.event("sched_trace", scenario=scenario.name,
                             strategy=strat, seed=s_seed,
                             steps=rep.steps,
                             failures=[f["kind"] for f in rep.failures],
                             races=[r["field"] for r in rep.races])
            if stop_on_failure:
                break
    report.distinct = len(report._sigs)
    if tracer is not None:
        tracer.event("concheck_explore", **report.summary())
    return report


# ---------------------------------------------------------------------------
# The scenario registry + self-check CLI


def builtin_scenarios() -> dict:
    """name -> (scenario factory, expectation).  'clean' scenarios must
    explore with zero findings; 'detect' fixtures resurrect known bugs
    and MUST be convicted — a checker that stops seeing them is broken
    (the true-positive/true-negative pair, ISSUE 13)."""
    return {
        "clean": (lambda: DaemonScenario(
            "clean", n_intake=2, jobs_each=2, with_ids=True), "clean"),
        "faulty-clean": (lambda: DaemonScenario(
            "faulty-clean", n_intake=2, jobs_each=2,
            fault_plan="device:transient:n=1"), "clean"),
        "drain-vs-retry": (lambda: DaemonScenario(
            "drain-vs-retry", n_intake=1, jobs_each=2,
            fault_plan="device:transient:n=1", drain_after_s=0.06,
            retry_base_s=0.08), "clean"),
        # ISSUE 14 — the pipelined dispatcher: packer + executor seam
        # threads, intake, stats poller and drainer all interleaved.
        "pipeline-clean": (lambda: DaemonScenario(
            "pipeline-clean", n_intake=2, jobs_each=2, with_ids=True,
            pipelined=True), "clean"),
        "pipeline-faulty": (lambda: DaemonScenario(
            "pipeline-faulty", n_intake=2, jobs_each=2, pipelined=True,
            fault_plan="device:transient:n=1;pack:transient:n=1"),
            "clean"),
        # Drain requested while a pack is IN FLIGHT (pack_hold_s parks
        # the packer mid-pack at a schedule point; the drain deadline
        # lands INSIDE that virtual hold window): the packed batch must
        # flush through the handoff slot exactly once, then the bins —
        # never dropped, never executed twice.
        "drain-vs-inflight-pack": (lambda: DaemonScenario(
            "drain-vs-inflight-pack", n_intake=1, jobs_each=2,
            pipelined=True, pack_hold_s=0.05, drain_after_s=0.02,
            linger_s=0.01), "clean"),
        # ISSUE 17 — tenant `delta` requests racing the daemon drain AND
        # LRU eviction: the 1500-byte budget vs 1000-byte stub sessions
        # forces admit/evict churn between the two tenants while the
        # drainer pulls the rug.  Every delta terminates exactly once
        # with the stream ledger conserved.
        "delta-vs-drain": (lambda: DaemonScenario(
            "delta-vs-drain", n_intake=1, jobs_each=1, delta_tenants=2,
            deltas_each=3, stream_budget_bytes=1500,
            drain_after_s=0.02), "clean"),
        # ISSUE 20 — the merge-aware packer: three small jobs against
        # b_max=2 overflow-merge into the big class certified by the
        # intake-big thread's plain batch.  Merged pops take jobs past
        # b_max in one dispatch; conservation and exactly-once must
        # survive every interleaving of the certifying big batch with
        # the small bin's overflow.
        "merge-pack-clean": (lambda: DaemonScenario(
            "merge-pack-clean", n_intake=1, jobs_each=3, with_ids=True,
            merge_packing=True, big_jobs=2), "clean"),
        "racy-routes": (lambda: DaemonScenario(
            "racy-routes", variant=_racy_route_results), "detect"),
        "send-under-lock": (lambda: DaemonScenario(
            "send-under-lock", variant=_send_under_lock_route_results),
            "detect"),
    }


def main(argv=None) -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser(
        prog="python -m cuvite_tpu.analysis.concheck",
        description="concheck: deterministic-schedule concurrency "
                    "self-check for the serving daemon (graftlint "
                    "tier 4)")
    ap.add_argument("--budget", type=int, default=None,
                    help=f"schedules per scenario (default: "
                         f"${BUDGET_ENV} or {DEFAULT_BUDGET})")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--scenario", default="all",
                    help="one scenario name, or 'all'")
    ap.add_argument("--replay", metavar="STRATEGY:SEED", default=None,
                    help="replay ONE schedule of --scenario from its "
                         "(strategy, raw seed) pair — the handle every "
                         "failure report prints — and show its findings")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--list", action="store_true")
    args = ap.parse_args(argv)

    scenarios = builtin_scenarios()
    if args.list:
        for name, (_f, expect) in scenarios.items():
            print(f"{name:18s} expect={expect}")
        return 0
    names = (list(scenarios) if args.scenario == "all"
             else [args.scenario])
    bad = [n for n in names if n not in scenarios]
    if bad:
        ap.error(f"unknown scenario(s) {bad}; have {sorted(scenarios)}")
    if args.replay is not None:
        if args.scenario == "all" or len(names) != 1:
            ap.error("--replay needs a single --scenario NAME")
        strat, _, raw = args.replay.partition(":")
        try:
            s_seed = int(raw)
        except ValueError:
            ap.error(f"--replay wants STRATEGY:SEED, got {args.replay!r}")
        scenario = scenarios[names[0]][0]()
        scenario.inventory = serve_inventory()
        rep = run_schedule(scenario, seed=s_seed, strategy=strat)
        print(f"concheck replay {names[0]} {strat}:{s_seed}: "
              f"{rep.steps} steps, {len(rep.failures)} failure(s), "
              f"{len(rep.races)} race(s)")
        for f in rep.failures:
            print(f"  {f['kind']}: {f['message']}")
        for r in rep.races:
            print(f"  race on {r['field']}: "
                  f"{r['first']['kind']}@{r['first']['thread']} vs "
                  f"{r['second']['kind']}@{r['second']['thread']}")
        return 0 if rep.clean else 1
    budget = args.budget if args.budget is not None else schedule_budget()
    inventory = serve_inventory()
    rc = 0
    results = []
    for name in names:
        factory, expect = scenarios[name]
        scenario = factory()
        scenario.inventory = inventory
        rep = explore(scenario, budget=budget, seed=args.seed,
                      stop_on_failure=(expect == "detect"))
        ok = rep.clean if expect == "clean" else not rep.clean
        results.append((name, expect, ok, rep))
        if not ok:
            rc = 1
    if args.format == "json":
        print(json.dumps([dict(rep.summary(), expect=expect, ok=ok)
                          for name, expect, ok, rep in results], indent=2))
        return rc
    for name, expect, ok, rep in results:
        verdict = "ok" if ok else "FAIL"
        print(f"concheck {name}: {verdict} — {rep.schedules} schedules "
              f"({rep.distinct} distinct), {len(rep.failing)} failing, "
              f"{len(rep.races())} race(s), expect={expect}")
        for w in rep.warnings:
            print(f"  warning: {w}")
        if not ok:
            for frep in rep.failing[:3]:
                print(f"  replay: --scenario {name} "
                      f"--replay {frep.strategy}:{frep.seed}")
                for f in frep.failures[:3]:
                    print(f"    {f['kind']}: {f['message']}")
                for r in frep.races[:3]:
                    print(f"    race on {r['field']}: "
                          f"{r['first']['kind']}@{r['first']['thread']} "
                          f"vs {r['second']['kind']}@"
                          f"{r['second']['thread']}")
    print(f"concheck: {'ok' if rc == 0 else 'FAIL'}")
    return rc


if __name__ == "__main__":
    import sys

    sys.exit(main())
