"""Tier 5 (dynamic half) — the mesh audit (M001-M003).

The static half (analysis/meshspec.py: R023-R025) reads source; this
module runs the REAL sharded entries — the bucketed SPMD step under
both exchanges (plus the env-driven auto cutover) and the batched
fused/bucketed phase programs — across several virtual mesh shapes and
grades three properties no AST walk can check:

  * **M001 — collective-sequence integrity.**  The per-shard collective
    sequence is extracted from the traced jaxpr (shard_map bodies,
    while/cond sub-jaxprs included, in program order).  Under SPMD
    every shard executes the one program, so per-shard divergence can
    only enter through data-dependent control flow: a ``cond`` whose
    branches issue different collective subsequences is a conviction
    (the "conditional psum" sabotage), and so is a sequence that
    changes STRUCTURE across mesh shapes (same program, different
    collective order = a shape-keyed schedule fork).

  * **M002 — mesh-shape label neutrality.**  Labels and modularity must
    be bit-identical across every audited mesh shape — the hand-written
    mesh-neutrality pins in tests/test_batched.py generalized into a
    closed gate over ALL sharded entries (:func:`assert_mesh_neutral`
    is the one shared implementation those tests now call).

  * **M003 — replication scaling.**  The HBM ledger's per-device column
    (obs/memory.py::per_device_nbytes) is graded against the declared
    per-category scaling law in ``tools/replication_budget.json``: a
    category declared ``sharded`` must shrink ~1/S as the mesh grows; a
    category declared ``replicated`` is allowed but must be LISTED —
    the manifest is the closed inventory.  "The community table is
    O(nv_total) per chip" (round-8) is now a failing test, not a note.
    Budget v2 adds the per-axis law ``ici_replicated`` for the
    two-level exchange: per-device bytes may reach the full extent over
    |dcn| (tables replicate inside the fast ICI submesh only), graded
    on the jaxpr-derived ``exchange_tables`` category
    (:func:`exchange_table_bytes` — the in-program all_gather/psum
    outputs the driver-buffer ledger cannot see) and on the dcn-sharded
    grouped routing (``exchange_grouped``).

Dynamic results are NEVER cached (the concheck precedent): every audit
re-runs the entries; only the static tier rides the incremental lint
cache.  ``tools/mesh_audit.py`` is the CLI; the tier-1 gate
(tests/test_meshcheck.py) runs the same audit in-process on the
forced-CPU 8-virtual-device shape.

Finding ids here (M*) are OUTSIDE the R-rule registry, like the tier-3
J*/B* ids: they anchor on entries/shapes, not source lines.
"""

from __future__ import annotations

import contextlib
import json
import os

import numpy as np

from cuvite_tpu.analysis.engine import Finding

# (spmd_axis_size, spare) factorizations of tier-1's 8-virtual-device
# pool: the 1-D entries use the first dim (vertex shards for the solo
# step, batch shards for the batched programs); the second dim is the
# idle remainder.  The two-level entry (bucketed_twolevel) reads the
# SAME tuples as (dcn, ici) hybrid-mesh factorizations — all eight
# devices active, community tables gathered only inside the ici
# submesh.
MESH_SHAPES = ((8, 1), (4, 2), (2, 4))

# Version 2 adds per-axis scaling laws ('ici_replicated': per-device
# bytes must shrink ~1/|dcn| of the full-table extent) next to v1's
# mesh-wide 'sharded'/'replicated'.  v1 manifests still load (they
# simply lack the per-axis categories, which then fail CLOSED as
# unlisted).
BUDGET_VERSION = 2
_BUDGET_VERSIONS_OK = (1, 2)

DEFAULT_BUDGET_REL = os.path.join("tools", "replication_budget.json")

# Scaling-law tolerance: measured per-device bytes for a 'sharded'
# category may exceed global/S by this factor plus the absolute floor
# (replicated scalars like the 1/(2m) constant ride in 'tables').
SHARDED_TOL = 1.5
SHARDED_FLOOR_BYTES = 4096

# Jaxpr primitives that are cross-shard collectives (communication
# order matters) — the dynamic twin of meshspec.SPMD_COLLECTIVES.
COLLECTIVE_PRIM_MARKERS = (
    "psum", "all_to_all", "all_gather", "ppermute", "pmin", "pmax",
    "reduce_scatter", "all_reduce", "collective_permute",
)


def _is_collective(prim_name: str) -> bool:
    return any(m in prim_name for m in COLLECTIVE_PRIM_MARKERS)


def _axes_of(eqn) -> tuple:
    p = eqn.params
    ax = p.get("axes", p.get("axis_name"))
    if ax is None:
        return ()
    if isinstance(ax, (tuple, list)):
        return tuple(str(a) for a in ax)
    return (str(ax),)


def _subjaxprs_of(value):
    from cuvite_tpu.analysis.jaxpr_audit import _sub_jaxprs

    return _sub_jaxprs(value)


def collective_sequence(jaxpr):
    """(sequence, branch_divergences) for one traced program.

    ``sequence`` is a nested tuple in deterministic program order:
    ``("psum", ("v",))`` for a collective, ``("while", (...))`` /
    ``("cond", ((...), (...)))`` wrapping control-flow bodies (a while
    body executes a data-dependent NUMBER of times, but the same
    number on every shard when its predicate is replicated — the
    structure, not the trip count, is the invariant).

    ``branch_divergences`` lists every cond whose branches issue
    DIFFERENT collective subsequences — the one way a single SPMD
    program can put shards into different collective orders.
    """
    divergences = []

    def walk(jx):
        core = getattr(jx, "jaxpr", jx)
        seq = []
        for eqn in getattr(core, "eqns", ()):
            name = eqn.primitive.name
            if _is_collective(name):
                seq.append((name, _axes_of(eqn)))
                continue
            if name == "cond":
                branches = [walk(b) for b in eqn.params.get("branches", ())]
                if len(set(branches)) > 1 and any(
                        _has_collective(b) for b in branches):
                    divergences.append(
                        ("cond", tuple(branches)))
                if any(branches):
                    seq.append(("cond", tuple(branches)))
                continue
            if name in ("while", "scan"):
                subs = []
                for key in sorted(eqn.params):
                    for sub in _subjaxprs_of(eqn.params[key]):
                        subs.extend(walk(sub))
                if subs:
                    seq.append((name, tuple(subs)))
                continue
            # Generic recursion (pjit bodies, shard_map bodies, custom
            # calls): inline the sub-sequence in param-key order.
            for key in sorted(eqn.params):
                for sub in _subjaxprs_of(eqn.params[key]):
                    seq.extend(walk(sub))
        return tuple(seq)

    seq = walk(jaxpr)
    return seq, divergences


def _has_collective(seq) -> bool:
    return bool(_flat_names(seq))


# Collective primitives whose OUTPUT is identical on every device of
# the reduced/gathered axes — i.e. the ones that materialize replicated
# tables.  all_to_all/ppermute move distinct data and are excluded (the
# sparse ghost channels are O(budget), not tables).
_REPLICATING_PRIMS = ("all_gather", "psum")


def exchange_table_bytes(jaxpr, axis_sizes: dict) -> dict:
    """Per-device bytes of replicating collective outputs (all_gather /
    non-scalar psum) in one traced step program — the in-program
    community tables the HBM ledger cannot see (they are never
    driver-placed buffers).

    Returns an M003 ledger row ``{"global": g, "per_device": p}``.
    ``per_device`` sums the output nbytes as the program holds them on
    one device.  ``global`` is each table's full-extent bytes: output
    nbytes times the number of DISTINCT copies across the mesh (total
    devices over the product of the collective's axis sizes — devices
    inside the collective's axes hold identical data by definition).
    An honest ici-scoped gather and its sabotaged global-axis widening
    therefore report the SAME ``global`` (the table covers all vertices
    either way) while ``per_device`` differs by the factor |dcn| —
    exactly the gap the ``ici_replicated`` law grades."""
    total = 1
    for v in axis_sizes.values():
        total *= max(int(v), 1)
    per_device = 0
    global_b = 0

    def walk(jx):
        nonlocal per_device, global_b
        core = getattr(jx, "jaxpr", jx)
        for eqn in getattr(core, "eqns", ()):
            name = eqn.primitive.name
            if any(m in name for m in _REPLICATING_PRIMS) \
                    and "scatter" not in name:
                copies = 1
                for a in _axes_of(eqn):
                    copies *= max(int(axis_sizes.get(a, 1)), 1)
                for ov in eqn.outvars:
                    aval = getattr(ov, "aval", None)
                    shape = getattr(aval, "shape", ())
                    if not shape:
                        continue  # scalar psums are not tables
                    nbytes = int(np.prod(shape)) * \
                        np.dtype(aval.dtype).itemsize
                    per_device += nbytes
                    global_b += nbytes * max(total // copies, 1)
            for key in sorted(eqn.params):
                for sub in _subjaxprs_of(eqn.params[key]):
                    walk(sub)

    walk(jaxpr)
    return {"global": int(global_b), "per_device": int(per_device)}


def _mfind(rule: str, entry: str, message: str, snippet: str = "") -> Finding:
    return Finding(rule=rule, severity="high", path=f"<mesh:{entry}>",
                   line=0, message=message, snippet=snippet)


def lint_collective_jaxpr(jaxpr, entry: str) -> list:
    """M001 findings intrinsic to ONE program: collectives under
    branch-divergent control flow (the conditional-psum class)."""
    _seq, div = collective_sequence(jaxpr)
    out = []
    for kind, branches in div:
        out.append(_mfind(
            "M001", entry,
            f"'{entry}' issues collectives under a data-dependent "
            f"'{kind}' whose branches differ "
            f"({[_flat_sigs(b) for b in branches]}): shards taking "
            "different branches issue different collective sequences — "
            "the canonical SPMD deadlock (R024's runtime twin)",
            snippet=kind))
    return out


def _flat_sigs(node) -> list:
    """Collective signatures ``'psum(v)'`` in a sequence tree, in
    order — the axes stay visible so two sequences that differ ONLY in
    axis names (the ICI/DCN rename class) render differently in the
    M001 message.  ``node`` is either an ITEM — ``("psum", axes)`` /
    ``("cond", (branch, ...))`` / ``("while", (item, ...))`` — or a
    (possibly empty) tuple of items/branches.  Axes tuples are all-str
    and skipped when recursing; empty branches contribute nothing (a
    cond with a collective-free branch is exactly the M001 conviction
    shape and must flatten, not crash)."""
    out: list = []
    if not isinstance(node, tuple):
        return out
    if node and isinstance(node[0], str):
        if _is_collective(node[0]):
            axes = [sub for sub in node[1:]
                    if isinstance(sub, tuple)
                    and all(isinstance(s, str) for s in sub)]
            out.append(f"{node[0]}({','.join(axes[0]) if axes else ''})")
        for sub in node[1:]:
            if isinstance(sub, tuple) \
                    and not all(isinstance(s, str) for s in sub):
                out.extend(_flat_sigs(sub))
        return out
    for sub in node:
        out.extend(_flat_sigs(sub))
    return out


def _flat_names(node) -> list:
    """Primitive names only (axes stripped) — the membership view."""
    return [sig.partition("(")[0] for sig in _flat_sigs(node)]


def check_sequences(entry: str, seq_by_shape: dict) -> list:
    """M001: the collective sequence must be structurally identical at
    every mesh shape (axis names and order; operand shapes legitimately
    scale with the mesh and are excluded by construction)."""
    tags = sorted(seq_by_shape)
    if len({seq_by_shape[t] for t in tags}) <= 1:
        return []
    a, b = tags[0], next(t for t in tags[1:]
                         if seq_by_shape[t] != seq_by_shape[tags[0]])
    return [_mfind(
        "M001", entry,
        f"'{entry}' traces DIFFERENT collective sequences at mesh "
        f"shapes {a} and {b} ({_flat_sigs(seq_by_shape[a])} vs "
        f"{_flat_sigs(seq_by_shape[b])}): the schedule forked on the "
        "mesh shape — every rank/shape must issue the identical "
        "sequence (arXiv:1702.04645's synchronized-collective "
        "contract)")]


def check_labels(entry: str, labels_by_shape: dict) -> list:
    """M002: per-tenant labels and modularity bit-identical across
    shapes.  ``labels_by_shape``: {tag: [(labels, q), ...]}."""
    tags = sorted(labels_by_shape)
    if not tags:
        return []
    ref_tag = tags[0]
    ref = labels_by_shape[ref_tag]
    out = []
    for tag in tags[1:]:
        got = labels_by_shape[tag]
        if len(got) != len(ref):
            out.append(_mfind(
                "M002", entry,
                f"'{entry}' returned {len(got)} results at shape {tag} "
                f"vs {len(ref)} at {ref_tag}"))
            continue
        for i, ((la, qa), (lb, qb)) in enumerate(zip(ref, got)):
            if not np.array_equal(np.asarray(la), np.asarray(lb)):
                out.append(_mfind(
                    "M002", entry,
                    f"'{entry}' labels for job {i} differ between mesh "
                    f"shapes {ref_tag} and {tag}: the mesh changed WHAT "
                    "was computed, not just where — mesh-shape "
                    "neutrality is the serving contract every sharded "
                    "entry must keep"))
                break
            if qa != qb:
                out.append(_mfind(
                    "M002", entry,
                    f"'{entry}' modularity for job {i} differs between "
                    f"{ref_tag} ({qa!r}) and {tag} ({qb!r}) with equal "
                    "labels: a mesh-shape-dependent reduction order "
                    "leaked into the scalar"))
                break
    return out


def check_replication(entry: str, ledger_by_shape: dict,
                      manifest: dict) -> list:
    """M003: per-device ledger bytes vs the declared scaling law.

    ``ledger_by_shape``: {tag: {"devices": n, "axes": {axis: size},
    "categories": {cat: {"global": g, "per_device": p}}}}.  ``axes``
    (optional, v2) carries the hybrid-mesh factorization the
    ``ici_replicated`` law divides by: per-device bytes may be the full
    extent over |dcn| (replicated inside the fast submesh only), so the
    allowance is ``global/|dcn| * tol + floor`` — a table widened back
    to the global axis blows through it by the factor |dcn|."""
    cats = manifest.get("categories", {})
    out = []
    seen = set()
    for tag in sorted(ledger_by_shape):
        rep = ledger_by_shape[tag]
        n = max(int(rep.get("devices", 1)), 1)
        n_dcn = max(int(rep.get("axes", {}).get("dcn", 1)), 1)
        for cat, row in sorted(rep.get("categories", {}).items()):
            g = int(row.get("global", 0))
            p = int(row.get("per_device", g))
            if g <= SHARDED_FLOOR_BYTES:
                continue
            law = cats.get(cat, {}).get("law")
            if law is None:
                if cat not in seen:
                    seen.add(cat)
                    out.append(_mfind(
                        "M003", entry,
                        f"'{entry}' tracked HBM category '{cat}' which "
                        "is not in tools/replication_budget.json: the "
                        "replication inventory is CLOSED — declare the "
                        "category's scaling law (sharded/replicated) "
                        "deliberately",
                        snippet=cat))
                continue
            if law == "sharded" and n > 1:
                allowed = g / n * SHARDED_TOL + SHARDED_FLOOR_BYTES
                if p > allowed:
                    out.append(_mfind(
                        "M003", entry,
                        f"'{entry}' at mesh shape {tag}: category "
                        f"'{cat}' holds {p} bytes per device but its "
                        f"declared law is 'sharded' (global {g} over "
                        f"{n} devices allows ~{int(allowed)}): an "
                        "unsharded O(nv)-scale buffer is riding a "
                        "sharded entry — the per-chip HBM wall class "
                        "round-8 measured; shard it or declare it "
                        "'replicated' with a reason",
                        snippet=cat))
            elif law == "ici_replicated":
                allowed = g / n_dcn * SHARDED_TOL + SHARDED_FLOOR_BYTES
                if p > allowed:
                    out.append(_mfind(
                        "M003", entry,
                        f"'{entry}' at mesh shape {tag}: category "
                        f"'{cat}' holds {p} bytes per device but its "
                        f"declared law is 'ici_replicated' (full extent "
                        f"{g} over |dcn|={n_dcn} allows ~{int(allowed)})"
                        ": a community table is replicated past the "
                        "fast ICI submesh — the two-level exchange "
                        "exists to keep per-device table bytes at "
                        "O(nv_total/|dcn|); gather it on the ici axis "
                        "only, or route it through the sparse ghost "
                        "protocol on dcn",
                        snippet=cat))
    return out


# ---------------------------------------------------------------------------
# Manifest.


def load_budget(path: str) -> dict:
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("version") not in _BUDGET_VERSIONS_OK:
        raise ValueError(f"replication budget {path!r}: unsupported "
                         f"version {data.get('version')!r}")
    return data


def write_budget(path: str, categories: dict, env: dict) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": BUDGET_VERSION, "env": env,
                   "categories": categories}, fh, indent=2,
                  sort_keys=True)
        fh.write("\n")


# ---------------------------------------------------------------------------
# Entry execution.


class ShapeReport:
    """One (entry, mesh shape) observation."""

    def __init__(self, tag: str, devices: int):
        self.tag = tag
        self.devices = devices
        self.labels: list = []       # [(labels np.ndarray, q float)]
        self.seq: tuple = ()
        self.intrinsic: list = []    # M001 findings from the jaxpr
        self.categories: dict = {}   # cat -> {"global", "per_device"}
        self.axes: dict = {}         # mesh axis sizes, e.g. {"dcn": 2}

    def ledger_row(self) -> dict:
        return {"devices": self.devices, "axes": self.axes,
                "categories": self.categories}


def _audit_graph(nv: int = 2048, ne: int = 8192):
    """The solo-entry audit graph: fixed structure (ring + deterministic
    extras), big enough that per-category sharding is measurable, small
    enough that six sharded step compiles stay in tier-1 budget."""
    from cuvite_tpu.analysis.jaxpr_audit import tiny_graphs

    return tiny_graphs(b=1, nv=nv, ne=ne)[0]


def _ledger_categories(ledger) -> dict:
    return {
        cat: {"global": int(ledger.peak_by_buffer.get(cat, 0)),
              "per_device": int(ledger.peak_per_device.get(cat, 0))}
        for cat in ledger.peak_by_buffer
    }


def _recorder():
    from cuvite_tpu.obs.recorder import NO_TRACE, FlightRecorder
    from cuvite_tpu.utils.trace import Tracer

    rec = FlightRecorder(NO_TRACE, watch_compiles=False)
    return rec, Tracer(recorder=rec)


@contextlib.contextmanager
def _env(name: str, value: str | None):
    prior = os.environ.get(name)
    try:
        if value is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = value
        yield
    finally:
        if prior is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = prior


def _solo_report(shape, exchange: str, *, cutover: bool = False):
    """Run the per-graph bucketed SPMD entry at one mesh shape: labels
    via the real driver, step jaxpr + HBM ledger via a directly-built
    PhaseRunner (the same factory the driver uses)."""
    import jax

    from cuvite_tpu.comm.mesh import make_mesh
    from cuvite_tpu.core.distgraph import DistGraph
    from cuvite_tpu.louvain.driver import (
        PhaseRunner,
        exchange_cutover,
        louvain_phases,
    )

    S = shape[0]
    g = _audit_graph()
    report = ShapeReport(f"{shape[0]}x{shape[1]}", S)
    ctx = _env("CUVITE_EXCHANGE_CUTOVER", "1") if cutover \
        else contextlib.nullcontext()
    with ctx:
        rec, tracer = _recorder()
        arg_exchange = "auto" if cutover else exchange
        res = louvain_phases(g, nshards=S, engine="bucketed",
                             exchange=arg_exchange, max_phases=1,
                             tracer=tracer, verbose=False)
        report.labels = [(np.asarray(res.communities),
                          float(res.modularity))]
        report.categories = _ledger_categories(rec.ledger)
        if cutover:
            dg_probe = DistGraph.build(g, S)
            if dg_probe.total_padded_vertices < exchange_cutover():
                report.intrinsic.append(_mfind(
                    "M000", "bucketed_cutover",
                    "CUVITE_EXCHANGE_CUTOVER=1 did not resolve "
                    "exchange='auto' to the sparse plan — the cutover "
                    "env override is broken"))
        # The step program actually compiled for this (mesh, exchange):
        # a second runner re-derives it from the same factory (plan
        # build + upload only, no execution) so make_jaxpr sees the
        # real shard_map body.
        dg = DistGraph.build(g, S)
        runner = PhaseRunner(dg, mesh=make_mesh(S), engine="bucketed",
                             exchange=exchange)
        jaxpr = jax.make_jaxpr(
            lambda c: runner._call(c, runner._extra))(runner.comm0)
    report.axes = {"v": S}
    report.categories["exchange_tables"] = exchange_table_bytes(
        jaxpr, report.axes)
    report.seq, _ = collective_sequence(jaxpr)
    report.intrinsic += lint_collective_jaxpr(
        jaxpr, f"bucketed_{'cutover' if cutover else exchange}")
    return report


def _twolevel_report(shape):
    """Run the two-level ICI/DCN entry with ``shape`` read as the
    (dcn, ici) hybrid-mesh factorization of the 8-device pool: labels
    via the real driver (mesh_shape plumbing included), step jaxpr via
    a directly-built PhaseRunner on the hybrid mesh.  The jaxpr feeds
    both M001 and the 'exchange_tables' per-axis ledger row — the
    community tables are in-program all_gathers, invisible to the HBM
    ledger's driver-buffer view."""
    import jax

    from cuvite_tpu.comm.mesh import make_hybrid_mesh
    from cuvite_tpu.core.distgraph import DistGraph
    from cuvite_tpu.louvain.driver import PhaseRunner, louvain_phases

    n_dcn, n_ici = shape
    g = _audit_graph()
    report = ShapeReport(f"{n_dcn}x{n_ici}", n_dcn * n_ici)
    report.axes = {"dcn": n_dcn, "ici": n_ici}
    rec, tracer = _recorder()
    res = louvain_phases(g, nshards=n_dcn * n_ici, engine="bucketed",
                         exchange="twolevel", mesh_shape=shape,
                         max_phases=1, tracer=tracer, verbose=False)
    report.labels = [(np.asarray(res.communities),
                      float(res.modularity))]
    report.categories = _ledger_categories(rec.ledger)
    dg = DistGraph.build(g, n_dcn * n_ici)
    runner = PhaseRunner(dg, mesh=make_hybrid_mesh(n_dcn, n_ici),
                         engine="bucketed", exchange="twolevel")
    jaxpr = jax.make_jaxpr(
        lambda c: runner._call(c, runner._extra))(runner.comm0)
    report.categories["exchange_tables"] = exchange_table_bytes(
        jaxpr, report.axes)
    report.seq, _ = collective_sequence(jaxpr)
    report.intrinsic += lint_collective_jaxpr(jaxpr, "bucketed_twolevel")
    return report


def _batched_report(shape, engine: str, b: int = 8):
    """Run the batched entry (fused or bucketed) with the batch axis
    over ``shape[0]`` devices; per-tenant labels, phase jaxpr, ledger."""
    import jax

    from cuvite_tpu.analysis.jaxpr_audit import tiny_graphs, \
        trace_phase_jaxprs
    from cuvite_tpu.louvain.batched import BATCH_AXIS, cluster_many

    nd = shape[0]
    mesh = None
    if nd > 1:
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()[:nd]), (BATCH_AXIS,))
    graphs = tiny_graphs(b=b)
    report = ShapeReport(f"{shape[0]}x{shape[1]}", nd)
    rec, tracer = _recorder()
    br = cluster_many(graphs, mesh=mesh, engine=engine, max_phases=2,
                      tracer=tracer)
    report.labels = [(np.asarray(r.communities), float(r.modularity))
                     for r in br.results]
    report.categories = _ledger_categories(rec.ledger)
    name = ("batched_bucketed_phase0" if engine == "bucketed"
            else "batched_fused_phase")
    jaxpr = trace_phase_jaxprs(b=b, mesh=mesh, programs=[name])[name]
    report.seq, _ = collective_sequence(jaxpr)
    report.intrinsic += lint_collective_jaxpr(jaxpr, f"batched_{engine}")
    return report


# Entry registry: name -> callable(shape) -> ShapeReport.  Names are
# what the CLI's --entries takes and what findings anchor on.
ENTRIES = {
    "bucketed_replicated":
        lambda shape: _solo_report(shape, "replicated"),
    "bucketed_sparse":
        lambda shape: _solo_report(shape, "sparse"),
    "bucketed_cutover":
        lambda shape: _solo_report(shape, "sparse", cutover=True),
    "bucketed_twolevel": _twolevel_report,
    "batched_fused":
        lambda shape: _batched_report(shape, "fused"),
    "batched_bucketed":
        lambda shape: _batched_report(shape, "bucketed"),
}


def run_mesh_audit(entry_names=None, shapes=MESH_SHAPES,
                   budget_path: str | None = None):
    """(findings, reports) over the audited entries.  ``reports``:
    {entry: {tag: ShapeReport}}.  Shared by tools/mesh_audit.py and the
    tier-1 gate — one implementation, one behavior.  Results are NEVER
    cached: the incremental lint cache holds only static summaries."""
    if budget_path is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        budget_path = os.path.join(root, DEFAULT_BUDGET_REL)
    try:
        manifest = load_budget(budget_path)
    except (OSError, ValueError) as e:
        manifest = None
        manifest_err = str(e)
    findings: list = []
    reports: dict = {}
    names = list(ENTRIES) if entry_names is None else list(entry_names)
    for name in names:
        run = ENTRIES[name]
        by_shape: dict = {}
        for shape in shapes:
            try:
                rep = run(shape)
            except Exception as e:  # fail CLOSED: a crashing entry is a
                findings.append(_mfind(  # finding, not a skipped check
                    "M000", name,
                    f"entry '{name}' failed at mesh shape "
                    f"{shape[0]}x{shape[1]}: {type(e).__name__}: {e}"))
                continue
            by_shape[rep.tag] = rep
            findings.extend(rep.intrinsic)
        reports[name] = by_shape
        if len(by_shape) >= 2:
            findings.extend(check_sequences(
                name, {t: r.seq for t, r in by_shape.items()}))
            findings.extend(check_labels(
                name, {t: r.labels for t, r in by_shape.items()}))
        if manifest is not None:
            findings.extend(check_replication(
                name, {t: r.ledger_row() for t, r in by_shape.items()},
                manifest))
    if manifest is None:
        findings.append(_mfind(
            "M000", "manifest",
            f"replication budget unreadable ({manifest_err}): the "
            "scaling-law inventory is the closed artifact — restore "
            "tools/replication_budget.json or regenerate with "
            "tools/mesh_audit.py --write-budget"))
    return findings, reports


# ---------------------------------------------------------------------------
# The shared mesh-neutrality helper (tests/test_batched.py and
# tests/test_pallas_spmd.py call this instead of hand-rolled loops).


def assert_mesh_neutral(run, configs, entry: str = "test") -> None:
    """Assert ``run(config)`` produces bit-identical (labels, Q) pairs
    for every config — THE one implementation of "the mesh (or engine)
    changes where work runs, never what it computes".  ``run`` returns
    a list of (labels, modularity) pairs (one per job/tenant)."""
    by_tag = {}
    for cfg in configs:
        tag = str(cfg)
        by_tag[tag] = [(np.asarray(l), q) for (l, q) in run(cfg)]
    findings = check_labels(entry, by_tag)
    if findings:
        raise AssertionError("\n".join(f.format() for f in findings))
