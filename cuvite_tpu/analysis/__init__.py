"""graftlint — AST-based static analysis for the cuvite_tpu codebase.

The correctness properties this repo depends on are mostly *not* testable
at unit-test cost: every host must issue the same collectives in the same
order (the multi-host analogue of the reference's lock-step MPI
exchanges), hot device paths must not silently fall back to host syncs or
64-bit dtypes, and reductions feeding modularity must stay deterministic
— the class of hazards that made synchronised/parallel Louvain variants
diverge from sequential quality (arXiv:1702.04645, arXiv:1805.10904).
graftlint encodes them as lint rules so every future PR is checked at
AST-walk cost instead of multi-host reproduction cost.

Layout (the three tiers, ANALYSIS.md "Tiers"):
  engine.py      — source loading, rule registry, suppressions, baseline
  rules.py       — tier 1: the per-file lexical rule set (R001..R016)
  callgraph.py   — tier 2: cross-module jit-reachability (R017/R018)
  lockset.py     — tier 2b: serve/ lockset concurrency checker (R019)
  lockorder.py   — tier 4 (static): lock-order cycles (R020) and
                   check-then-act atomicity (R021) for serve/
  concheck.py    — tier 4 (dynamic): deterministic-schedule concurrency
                   checker — vector-clock race detection over the
                   serve/sync.py cooperative scheduler; also runnable as
                   python -m cuvite_tpu.analysis.concheck
  cache.py       — incremental lint cache (content-hash keyed)
  jaxpr_audit.py — tier 3: jaxpr lint + compile-budget audit (J*/B*
                   findings; driven by tools/compile_audit.py)
  meshspec.py    — tier 5 (static): SPMD mesh/collective analysis —
                   axis-name drift (R023), whole-program collective-
                   order divergence (R024), replication audit (R025 +
                   the replicated-ok inventory)
  meshcheck.py   — tier 5 (dynamic): the mesh audit — real sharded
                   entries across virtual mesh shapes, graded M001
                   (collective sequences), M002 (label neutrality),
                   M003 (per-device HBM scaling laws); driven by
                   tools/mesh_audit.py
  __main__.py    — CLI: python -m cuvite_tpu.analysis [paths] [options]

See ANALYSIS.md at the repo root for the rule catalogue, suppression
syntax (``# graftlint: disable=R001``) and the baseline workflow.
"""

from cuvite_tpu.analysis.engine import (
    Finding,
    Rule,
    SEVERITIES,
    all_rules,
    apply_baseline,
    load_baseline,
    run_paths,
    run_source,
    write_baseline,
)

# Importing the rule modules populates the registry as a side effect
# (tier 1 lexical rules, tier 2 cross-module rules, tier 2b lockset,
# tier 4 static lock-order/atomicity, tier 5 static mesh/collective).
from cuvite_tpu.analysis import rules as _rules        # noqa: F401
from cuvite_tpu.analysis import callgraph as _cg       # noqa: F401
from cuvite_tpu.analysis import lockset as _lockset    # noqa: F401
from cuvite_tpu.analysis import lockorder as _lockord  # noqa: F401
from cuvite_tpu.analysis import meshspec as _meshspec  # noqa: F401
from cuvite_tpu.analysis.callgraph import (
    run_project,
    run_project_sources,
)

__all__ = [
    "Finding",
    "Rule",
    "SEVERITIES",
    "all_rules",
    "apply_baseline",
    "load_baseline",
    "run_paths",
    "run_project",
    "run_project_sources",
    "run_source",
    "write_baseline",
]
