"""Incremental lint cache: per-file findings + tier-2 summaries keyed
on (content sha256, rules-set version).

The self-lint runs on every tier-1 invocation and ``tools/lint.sh`` on
every pre-commit; reparsing ~100 unchanged files each time is pure tax.
The cache stores, per repo-relative path, the file's content hash, the
per-file findings it produced, and its :func:`~cuvite_tpu.analysis.
callgraph.summarize` dict — so a warm run re-parses only changed files
and still runs the cross-module tier over the full (cached) summary
set.  A hit is bit-identical to a cold run by construction: findings
round-trip through their dataclass fields and the project tier always
recomputes from summaries (tests/test_analysis.py pins this).

Invalidation is content-based on BOTH sides of the key:

  * the file's sha256 — any edit misses;
  * :func:`rules_version` — the sha256 of every source file of the
    analysis package itself, so editing a rule, the engine, or this
    module invalidates the whole cache without anyone remembering to
    bump a counter.

The cache file is advisory: a missing, corrupt, or version-skewed file
degrades to a cold run, and writes go through a temp file + rename so
a crashed run cannot leave a torn JSON behind.  Entries untouched by
the current run are KEPT (a ``lint.sh --changed`` subset run must not
evict the full-tree warm set) up to a generous cap; growth is bounded
by the path population, and a rules-version bump resets the file.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import json
import os

CACHE_VERSION = 1

# The default location (repo-relative), created on first use; hidden so
# `git status` noise stays low — it is .gitignore-able, never committed.
DEFAULT_CACHE_REL = os.path.join("tools", ".graftlint_cache.json")


@functools.lru_cache(maxsize=1)
def rules_version() -> str:
    """sha256 over the analysis package's own sources (sorted), so any
    rule/engine edit invalidates every cached entry."""
    pkg_dir = os.path.dirname(os.path.abspath(__file__))
    h = hashlib.sha256()
    for name in sorted(os.listdir(pkg_dir)):
        if not name.endswith(".py"):
            continue
        h.update(name.encode())
        with open(os.path.join(pkg_dir, name), "rb") as fh:
            h.update(fh.read())
    return h.hexdigest()


def content_sha(text: str) -> str:
    return hashlib.sha256(text.encode("utf-8")).hexdigest()


class LintCache:
    """Load-once / save-once JSON cache (see module docstring)."""

    def __init__(self, path: str):
        self.path = path
        self.entries: dict = {}
        self._touched: set = set()
        self._dirty = False
        self._load()

    def _load(self) -> None:
        try:
            with open(self.path, encoding="utf-8") as fh:
                data = json.load(fh)
        except (OSError, ValueError):
            return
        if not isinstance(data, dict) \
                or data.get("version") != CACHE_VERSION \
                or data.get("rules_version") != rules_version():
            return
        ents = data.get("entries")
        if isinstance(ents, dict):
            self.entries = ents

    def get(self, rel: str, sha: str):
        """(findings-as-dicts, summary) on a hit, else None."""
        ent = self.entries.get(rel)
        if not ent or ent.get("sha") != sha:
            return None
        self._touched.add(rel)
        return ent.get("findings", []), ent.get("summary")

    def put(self, rel: str, sha: str, findings, summary) -> None:
        self.entries[rel] = {
            "sha": sha,
            "findings": [f if isinstance(f, dict) else dataclasses.asdict(f)
                         for f in findings],
            "summary": summary,
        }
        self._touched.add(rel)
        self._dirty = True

    # Hard cap on entry count: untouched entries are evicted first once
    # crossed (renames/deletions accumulate dead keys VERY slowly, so
    # this mostly never fires).
    MAX_ENTRIES = 4096

    def save(self) -> None:
        """Write back (atomically); untouched entries survive (subset
        runs must not evict the warm full-tree set).  Silent on failure
        — the cache is an optimization, never a reason to fail a
        lint."""
        if not self._dirty:
            return
        if len(self.entries) > self.MAX_ENTRIES:
            for rel in sorted(set(self.entries) - self._touched):
                if len(self.entries) <= self.MAX_ENTRIES:
                    break
                del self.entries[rel]
        payload = {
            "version": CACHE_VERSION,
            "rules_version": rules_version(),
            "entries": self.entries,
        }
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(payload, fh, sort_keys=True)
                fh.write("\n")
            os.replace(tmp, self.path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
