"""Tier 6 (static half) — index-width/overflow analysis (R026-R028).

ROADMAP item 1's unlock is Friendster (1.8 B undirected edges, so the
directed slab and 2m both clear 2^31) and R-MAT scale 28, yet the hot
paths are deliberately 32-bit: the reference ships ``-DUSE_32_BIT_GRAPH``
as a compile-time gamble, R003 actively polices AGAINST 64-bit drift,
and until this tier the only machine-checked width contract was the one
``kbits + sbits <= 31`` predicate in ops/segment.py.  A silent int32
overflow in a cumsum, degree sum, or packed key at scale 28 produces
WRONG LABELS, not a crash — the worst failure class for a clustering
service.  This module closes the static half; analysis/widthaudit.py
runs the dynamic half (W001-W003) over real traced jaxprs.

**The interval model.**  A tiny forward abstract interpreter runs over
every function in the device-path modules (``ops/``, ``coarsen/``,
``louvain/``, ``kernels/``, ``core/``).  Each value carries an abstract
triple ``(bound, extent, int32)``:

* ``bound`` — a symbolic upper bound on the VALUE, as a JSON expression
  tree over the workload symbols (``nv_pad``, ``ne_pad``, ``nv_total``,
  ``kbits``, ``sbits``, ``B``, ``two_m``) — e.g. the packed sort key is
  ``(nv_pad << kbits) + nv_pad``;
* ``extent`` — a symbolic upper bound on the array LENGTH (the number
  of addends a reduction over it accumulates);
* ``int32`` — whether the value demonstrably flows through an int32
  dtype (``.astype(jnp.int32)``, ``dtype=jnp.int32``, ``jnp.int32(x)``).

**The symbol table.**  Bounds are seeded from NAMES, the repo's real
contract surface: parameters called ``nv_pad``/``nc``/``num_segments``
bound at ``nv_pad``, ``ne_pad`` at ``ne_pad``, edge-slab arrays
(``src``/``dst``/``ckey``/``w``...) get extent ``ne_pad`` and vertex-id
value bound ``nv_pad``, per-vertex arrays (``comm``/``vdeg``/``lab``...)
get extent ``nv_pad``.  Unknown names stay unknown — a bounded false
negative, never a false positive.

**Eligibility predicates refine the bounds.**  A leading
``if ne_pad > SLAB_NE_MAX: raise`` fail-loud guard (the ops/segment.py
slab contract) refines the symbol's bound for the rest of the function,
and an enclosing ``if fits32:`` / ``if packable:`` guard whose
(one-level-expanded) predicate mentions the bit-budget names marks a
packing site as TIED to its guard.  The rules:

* **R026** — int32-typed arithmetic whose symbolic upper bound exceeds
  2^31 - 1 when evaluated at the registry's declared max workload
  (:data:`MAX_WORKLOAD` — pinned against
  ``workloads/registry.max_workload()`` by tier-1), unless guarded by
  an eligibility predicate or carrying ``# graftlint:
  width-ok=<reason>`` (closed inventory, ``tools/width_audit.py
  --inventory``; the R025 precedent).
* **R027** — bit-packing sites (shift/or key construction) whose bit
  budget is not provably tied to the guard predicate gating them — the
  segment.py ``kbits + sbits <= 31`` contract generalized to EVERY
  packing site.  An unknown pack bound fails CLOSED (packs are rare,
  deliberate sites).
* **R028** — ``cumsum``/``sum``/``bincount``-class reductions over
  ``ne_pad``-extent arrays accumulating in an int32 input dtype: the
  run-id/compaction-offset class.  At ne_pad = 2^32 the cumsum of a
  mask already wraps; the SLAB_NE_MAX = 2^30 refinement (or a
  ``width-ok`` annotation) is the only way through.

Facts ride the tier-2 summary (and therefore the incremental lint
cache) under the ``"width"`` key, exactly like the lock and mesh
summaries; the dynamic W00x results are NEVER cached.
"""

from __future__ import annotations

import ast
import re

from cuvite_tpu.analysis.engine import Finding, SourceFile, dotted, register

WIDTH_SUMMARY_VERSION = 1

INT32_MAX = (1 << 31) - 1

# The registry's declared max workload, in symbols (tier-1 pins this
# dict == workloads/registry.max_workload(); the static tier itself
# stays stdlib-only so linting never imports jax/numpy):
#   nv_pad/nv_total — R-MAT scale-28 vertex space (2^28 ids, already
#     pow2 so padding is the identity; Friendster pads to 2^27);
#   ne_pad — the directed edge slab ceiling (Friendster's 3.61 B
#     directed rows and the scale-28 synth law's 16 * 2^28 both pad to
#     2^32);
#   two_m — total directed weight mass ceiling (unit weights make it
#     ne_pad; 2^33 leaves headroom for small integer weights);
#   kbits/sbits — the packed-sort bit budget at that vertex space
#     (key_bound = nv_pad -> 28 bits, src_bound = nv_pad + 1 -> 29);
#   B — the serving batch-ladder ceiling (core/batch.BATCH_SIZES).
MAX_WORKLOAD = {
    "nv_pad": 1 << 28,
    "nv_total": 1 << 28,
    "ne_pad": 1 << 32,
    "two_m": 1 << 33,
    "kbits": 28,
    "sbits": 29,
    "B": 64,
}

# Device-path modules the interpreter runs over (everything traced onto
# the chip plus the host-side plan/batch math that feeds it).  The
# serve/, obs/, comm/ and workloads/ layers hold no index arithmetic at
# slab extent.
WIDTH_PATH_PREFIXES = (
    "cuvite_tpu/ops/",
    "cuvite_tpu/coarsen/",
    "cuvite_tpu/louvain/",
    "cuvite_tpu/kernels/",
    "cuvite_tpu/core/",
)

_WIDTH_OK_RE = re.compile(r"#\s*graftlint:\s*width-ok\s*=\s*(.+?)\s*$")

# Parameter names whose VALUE is bounded by a workload symbol.
PARAM_BOUND_SYMBOLS = {
    "nv_pad": "nv_pad",
    "nv_total": "nv_total",
    "nc": "nv_pad",
    "num_segments": "nv_pad",
    "ne_pad": "ne_pad",
    "kbits": "kbits",
    "sbits": "sbits",
    "key_bound": "nv_pad",
    "src_bound": "nv_pad",
    "id_bound": "nv_pad",
    "sentinel": "nv_pad",
    "b": "B",
}

# Array parameter names -> (value-bound symbol or None, extent symbol).
# Suffixed spellings (src_s, w_s, dst2) normalize to the base name.
ARRAY_PARAM_SYMBOLS = {
    "src": ("nv_pad", "ne_pad"),
    "dst": ("nv_pad", "ne_pad"),
    "ckey": ("nv_pad", "ne_pad"),
    "w": (None, "ne_pad"),
    "weights": (None, "ne_pad"),
    "starts": (None, "ne_pad"),
    "emit": (None, "ne_pad"),
    "comm": ("nv_pad", "nv_pad"),
    "labels": ("nv_pad", "nv_pad"),
    "lab": ("nv_pad", "nv_pad"),
    "vdeg": (None, "nv_pad"),
    "deg": (None, "nv_pad"),
    "present": (None, "nv_pad"),
    "sizes": ("nv_pad", "nv_pad"),
}

_REDUCTION_CALLS = {"cumsum", "cumulative_sum", "sum", "bincount"}
_MINMAX_CALLS = {"minimum", "min", "maximum", "max"}
_ALLOC_CALLS = {"zeros", "ones", "full", "empty"}

_SITE_PRIORITY = {"arith": 0, "reduction": 1, "pack": 2}

_DIGITS = "0123456789"


# ---------------------------------------------------------------------------
# Symbolic expressions: JSON-serializable nested lists.
#   ["n", 7]  ["s", "ne_pad"]  ["+", a, b]  ["*", a, b]  ["min", a, b]
#   ["max", a, b]  ["<<", a, k]  [">>", a, k]  ["bits", a]
# All values are assumed non-negative (ids, counts, offsets), which is
# what makes + an upper bound for | and the left operand one for -.


def _n(v) -> list:
    return ["n", int(v)]


def _s(name: str) -> list:
    return ["s", name]


def sym_eval(expr, env: dict):
    """Evaluate a bound expression at ``env``; None when any symbol is
    unknown (the bounded-false-negative answer)."""
    if expr is None:
        return None
    tag = expr[0]
    if tag == "n":
        return int(expr[1])
    if tag == "s":
        v = env.get(expr[1])
        return None if v is None else int(v)
    args = [sym_eval(a, env) for a in expr[1:]]
    if any(a is None for a in args):
        return None
    if tag == "+":
        return sum(args)
    if tag == "*":
        p = 1
        for a in args:
            p *= a
        return p
    if tag == "min":
        return min(args)
    if tag == "max":
        return max(args)
    if tag == "<<":
        return args[0] * (2 ** max(args[1], 0))
    if tag == ">>":
        return args[0] // (2 ** max(args[1], 0))
    if tag == "bits":
        return max(args[0], 1).bit_length()
    return None


def sym_symbols(expr) -> set:
    """The workload symbols an expression mentions."""
    out: set = set()
    if not isinstance(expr, list) or not expr:
        return out
    if expr[0] == "s":
        out.add(expr[1])
        return out
    for sub in expr[1:]:
        if isinstance(sub, list):
            out |= sym_symbols(sub)
    return out


def sym_render(expr) -> str:
    """Human form for findings: ``(nv_pad << kbits) + nv_pad``."""
    if expr is None:
        return "?"
    tag = expr[0]
    if tag == "n":
        return str(expr[1])
    if tag == "s":
        return str(expr[1])
    args = [sym_render(a) for a in expr[1:]]
    if tag == "bits":
        return f"bits({args[0]})"
    if tag in ("min", "max"):
        return f"{tag}({', '.join(args)})"
    return "(" + f" {tag} ".join(args) + ")"


class AVal:
    """One abstract value: (symbolic value bound, symbolic extent,
    int32-typed flag).  ``None`` bound/extent means unknown."""

    __slots__ = ("bound", "extent", "int32")

    def __init__(self, bound=None, extent=None, int32=False):
        self.bound = bound
        self.extent = extent
        self.int32 = bool(int32)


_UNKNOWN = AVal()


def _max_bound(a, b):
    if a is None or b is None:
        return None
    return ["max", a, b]


def _sum_bound(a, b):
    if a is None or b is None:
        return None
    return ["+", a, b]


def _first_extent(*vals):
    for v in vals:
        if v is not None and v.extent is not None:
            return v.extent
    return None


def _last(name: str | None) -> str:
    return name.split(".")[-1] if name else ""


def _is_int32_dtype_expr(node: ast.AST | None) -> bool:
    """Does a dtype expression demonstrably denote a 32-bit-or-narrower
    integer (jnp.int32 / np.int32 / "int32" / int16/int8 variants)?"""
    if node is None:
        return False
    name = dotted(node)
    if name is None and isinstance(node, ast.Constant) \
            and isinstance(node.value, str):
        name = node.value
    if not name:
        return False
    last = name.split(".")[-1]
    return last in ("int32", "int16", "int8", "uint32", "uint16", "uint8")


def _width_ok_lines(sf: SourceFile) -> dict:
    """{lineno: reason} for every ``# graftlint: width-ok=`` pragma
    (real comment tokens, the replicated-ok discipline)."""
    out: dict = {}
    for lineno, comment in sf._iter_comments():
        if "width-ok" not in comment:
            continue
        m = _WIDTH_OK_RE.search(comment)
        if m:
            out[lineno] = m.group(1)
    return out


def _module_int_consts(sf: SourceFile) -> dict:
    """Module-level ``NAME = <int expr>`` constants, with shift/arith
    folding (``SLAB_NE_MAX = 1 << 30``) — the raise-guard ceilings."""
    out: dict = {}
    for node in sf.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            v = _const_int(node.value, out)
            if v is not None:
                out[node.targets[0].id] = v
    return out


def _const_int(node: ast.AST, consts: dict):
    """Fold an int-constant expression (Constant / module const Name /
    +-*<< BinOp over those); None when not statically an int."""
    if isinstance(node, ast.Constant):
        return node.value if isinstance(node.value, int) \
            and not isinstance(node.value, bool) else None
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    if isinstance(node, ast.BinOp):
        lo = _const_int(node.left, consts)
        hi = _const_int(node.right, consts)
        if lo is None or hi is None:
            return None
        if isinstance(node.op, ast.LShift):
            return lo << hi
        if isinstance(node.op, ast.Add):
            return lo + hi
        if isinstance(node.op, ast.Sub):
            return lo - hi
        if isinstance(node.op, ast.Mult):
            return lo * hi
        if isinstance(node.op, ast.Pow) and 0 <= hi <= 64:
            return lo ** hi
    return None


def _seed_aval(name: str) -> AVal | None:
    key = name if name in PARAM_BOUND_SYMBOLS \
        or name in ARRAY_PARAM_SYMBOLS \
        else name.split("_")[0].rstrip(_DIGITS)
    if key in PARAM_BOUND_SYMBOLS:
        return AVal(bound=_s(PARAM_BOUND_SYMBOLS[key]))
    if key in ARRAY_PARAM_SYMBOLS:
        bsym, esym = ARRAY_PARAM_SYMBOLS[key]
        return AVal(bound=_s(bsym) if bsym else None, extent=_s(esym))
    return None


def _names_in(node: ast.AST) -> set:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


# ---------------------------------------------------------------------------
# The per-function interpreter.


class _FnInterp:
    """Forward abstract interpretation of ONE function body, recording
    width hazard sites.  Statements are walked in order; ``if X: raise``
    prologue guards refine symbol bounds for the remainder; enclosing
    ``if`` predicates stack onto every recorded site."""

    def __init__(self, sf: SourceFile, info, consts: dict,
                 width_ok: dict, sites: list):
        self.sf = sf
        self.info = info
        self.consts = consts
        self.width_ok = width_ok
        self.sites = sites
        self.env: dict = {}
        self.refined: dict = {}
        self.guards: list = []
        self.assign_text: dict = {}
        self.bitlen_bases: dict = {}
        for p in info.params:
            seeded = _seed_aval(p)
            if seeded is not None:
                self.env[p] = seeded
        # Pre-pass: one-level guard expansion text and bit_length
        # derivation bases ("kbits = max(key_bound - 1, 1).bit_length()"
        # -> kbits derives from key_bound).
        for node in ast.walk(info.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tgt = node.targets[0].id
                try:
                    self.assign_text[tgt] = ast.unparse(node.value)
                except Exception:
                    pass
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Call) \
                            and isinstance(sub.func, ast.Attribute) \
                            and sub.func.attr == "bit_length":
                        self.bitlen_bases.setdefault(tgt, set()).update(
                            _names_in(sub.func.value))

    # -- recording ----------------------------------------------------

    def _record(self, node: ast.AST, kind: str, bound, *, extent=None,
                shift=(), int32=False):
        line = getattr(node, "lineno", 1)
        site = {
            "fn": self.info.name,
            "line": line,
            "snippet": self.sf.line(line),
            "kind": kind,
            "bound": bound,
            "extent": extent,
            "shift": sorted(shift),
            "guards": list(self.guards),
            "tied": self._tied(shift) if kind == "pack" else False,
            "refined": dict(self.refined),
            "width_ok": self.width_ok.get(line),
            "int32": bool(int32),
        }
        for i, prev in enumerate(self.sites):
            if prev["line"] == line and prev["fn"] == self.info.name:
                if _SITE_PRIORITY[kind] > _SITE_PRIORITY[prev["kind"]]:
                    self.sites[i] = site
                return
        self.sites.append(site)

    def _tied(self, shift_names) -> bool:
        """Is a pack's bit budget provably tied to a gating predicate?
        True when an enclosing guard (one-level expanded) mentions a
        shift-amount name, one of its ``bit_length`` base names, or any
        ``bit_length`` call — or when a prologue raise-guard already
        refined a symbol the shift amount derives from."""
        names = set(shift_names)
        for nm in list(names):
            names |= self.bitlen_bases.get(nm, set())
        texts = []
        for g in self.guards:
            texts.append(g)
            for nm in _names_in_text(g):
                if nm in self.assign_text:
                    texts.append(self.assign_text[nm])
        for t in texts:
            if "bit_length" in t:
                return True
            toks = _names_in_text(t)
            if toks & names:
                return True
        for nm in names:
            seeded = self.env.get(nm) or _seed_aval(nm)
            if seeded is not None and seeded.bound is not None:
                if sym_symbols(seeded.bound) & set(self.refined):
                    return True
        return False

    # -- statements ---------------------------------------------------

    def run(self):
        self._stmts(self.info.node.body)

    def _stmts(self, body):
        for st in body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs get their own pass
            if isinstance(st, ast.If):
                self._if(st)
            elif isinstance(st, (ast.For, ast.While)):
                if isinstance(st, ast.For):
                    self._assign_target(st.target, self._eval(st.iter))
                else:
                    self._eval(st.test)
                self._stmts(st.body)
                self._stmts(st.orelse)
            elif isinstance(st, ast.With):
                self._stmts(st.body)
            elif isinstance(st, ast.Try):
                self._stmts(st.body)
                for h in st.handlers:
                    self._stmts(h.body)
                self._stmts(st.orelse)
                self._stmts(st.finalbody)
            elif isinstance(st, ast.Assign):
                val = self._eval(st.value)
                for t in st.targets:
                    self._assign_target(t, val, value_node=st.value)
            elif isinstance(st, ast.AnnAssign) and st.value is not None:
                self._assign_target(st.target, self._eval(st.value))
            elif isinstance(st, ast.AugAssign):
                self._eval(st.value)
                if isinstance(st.target, ast.Name):
                    self.env[st.target.id] = _UNKNOWN
            elif isinstance(st, (ast.Expr, ast.Return)):
                if getattr(st, "value", None) is not None:
                    self._eval(st.value)
            elif isinstance(st, ast.Assert):
                self._eval(st.test)

    def _if(self, st: ast.If):
        # Prologue fail-loud guard: ``if SYM > CEIL: raise`` refines the
        # symbol's bound for everything after it (the SLAB_NE_MAX
        # eligibility-predicate shape).
        if len(st.body) == 1 and isinstance(st.body[0], ast.Raise) \
                and not st.orelse and self._refine_from(st.test):
            return
        try:
            gtext = ast.unparse(st.test)
        except Exception:
            gtext = "<guard>"
        self._eval(st.test)
        self.guards.append(gtext)
        self._stmts(st.body)
        self.guards.pop()
        self._stmts(st.orelse)

    def _refine_from(self, test: ast.AST) -> bool:
        if not (isinstance(test, ast.Compare) and len(test.ops) == 1
                and isinstance(test.ops[0], (ast.Gt, ast.GtE))
                and isinstance(test.left, ast.Name)):
            return False
        ceil = _const_int(test.comparators[0], self.consts)
        if ceil is None:
            return False
        if isinstance(test.ops[0], ast.GtE):
            ceil -= 1
        name = test.left.id
        aval = self.env.get(name) or _seed_aval(name)
        sym = None
        if aval is not None and aval.bound is not None \
                and aval.bound[0] == "s":
            sym = aval.bound[1]
        elif name in MAX_WORKLOAD:
            sym = name
        if sym is None:
            return False
        prev = self.refined.get(sym)
        self.refined[sym] = ceil if prev is None else min(prev, ceil)
        return True

    def _assign_target(self, target, val: AVal, value_node=None):
        if isinstance(target, ast.Name):
            if (val is _UNKNOWN or (val.bound is None
                                    and val.extent is None)):
                # Unknown RHS into a contract-named local adopts the
                # symbol (``nv_pad = acc.shape[0]`` keeps its meaning).
                seeded = _seed_aval(target.id)
                if seeded is not None and target.id in PARAM_BOUND_SYMBOLS:
                    self.env[target.id] = seeded
                    return
            self.env[target.id] = val
        elif isinstance(target, (ast.Tuple, ast.List)):
            parts = None
            if isinstance(value_node, (ast.Tuple, ast.List)) \
                    and len(value_node.elts) == len(target.elts):
                parts = [self._eval(e) for e in value_node.elts]
            for i, t in enumerate(target.elts):
                if isinstance(t, ast.Name):
                    self.env[t.id] = parts[i] if parts is not None \
                        else AVal(extent=val.extent)

    # -- expressions --------------------------------------------------

    def _eval(self, node: ast.AST) -> AVal:
        if node is None:
            return _UNKNOWN
        if isinstance(node, ast.Constant):
            if isinstance(node.value, bool):
                return AVal(bound=_n(1))
            if isinstance(node.value, int):
                return AVal(bound=_n(abs(node.value)))
            return _UNKNOWN
        if isinstance(node, ast.Name):
            got = self.env.get(node.id)
            if got is not None:
                return got
            if node.id in self.consts:
                return AVal(bound=_n(self.consts[node.id]))
            seeded = _seed_aval(node.id)
            return seeded if seeded is not None else _UNKNOWN
        if isinstance(node, ast.BinOp):
            return self._binop(node)
        if isinstance(node, ast.BoolOp):
            for v in node.values:
                self._eval(v)
            return AVal(bound=_n(1),
                        extent=_first_extent(*[self._eval(v)
                                               for v in node.values]))
        if isinstance(node, ast.Compare):
            left = self._eval(node.left)
            rights = [self._eval(c) for c in node.comparators]
            return AVal(bound=_n(1),
                        extent=_first_extent(left, *rights))
        if isinstance(node, ast.UnaryOp):
            inner = self._eval(node.operand)
            if isinstance(node.op, ast.Invert):
                return AVal(bound=_n(1) if inner.bound == _n(1) else None,
                            extent=inner.extent, int32=inner.int32)
            return AVal(bound=inner.bound, extent=inner.extent,
                        int32=inner.int32)
        if isinstance(node, ast.IfExp):
            self._eval(node.test)
            a, b = self._eval(node.body), self._eval(node.orelse)
            return AVal(bound=_max_bound(a.bound, b.bound),
                        extent=_first_extent(a, b),
                        int32=a.int32 or b.int32)
        if isinstance(node, ast.Call):
            return self._call(node)
        if isinstance(node, ast.Subscript):
            return self._subscript(node)
        if isinstance(node, ast.Attribute):
            base = self._eval(node.value)
            if node.attr in ("T", "real", "imag"):
                return base
            return _UNKNOWN
        if isinstance(node, (ast.Tuple, ast.List)):
            for e in node.elts:
                self._eval(e)
            return _UNKNOWN
        if isinstance(node, ast.Starred):
            return self._eval(node.value)
        return _UNKNOWN

    def _binop(self, node: ast.BinOp) -> AVal:
        a = self._eval(node.left)
        b = self._eval(node.right)
        int32 = a.int32 or b.int32
        extent = _first_extent(a, b)
        op = node.op
        bound = None
        shift_names: set = set()
        kind = "arith"
        if isinstance(op, ast.Add):
            bound = _sum_bound(a.bound, b.bound)
        elif isinstance(op, ast.Sub):
            c = _const_int(node.right, self.consts)
            if c is not None and a.bound is not None:
                bound = ["+", a.bound, _n(-c)]
            else:
                bound = a.bound
        elif isinstance(op, ast.Mult):
            bound = None if a.bound is None or b.bound is None \
                else ["*", a.bound, b.bound]
        elif isinstance(op, (ast.FloorDiv, ast.Div, ast.Mod)):
            bound = a.bound
        elif isinstance(op, ast.LShift):
            # A bare shift is NOT a pack: the `1 << bit_length()` pow2
            # padding idiom (next_pow2, pow2_floor, tree-sum padding,
            # mesh-size caps) shifts a constant 1, and shift-based
            # scaling never re-enters a packed field on its own.  Only a
            # BitOr that COMBINES a shifted field (below) records a pack
            # site; an int32 bare shift still falls through to the
            # generic arith record so R026 sees genuine overflow.
            if a.bound is not None and b.bound is not None:
                bound = ["<<", a.bound, b.bound]
        elif isinstance(op, ast.BitOr):
            bound = _sum_bound(a.bound, b.bound)  # a|b <= a+b, a,b >= 0
            for side in (node.left, node.right):
                for sub in ast.walk(side):
                    if isinstance(sub, ast.BinOp) \
                            and isinstance(sub.op, ast.LShift):
                        shift_names |= _names_in(sub.right)
                        kind = "pack"
        elif isinstance(op, ast.BitAnd):
            if a.bound is not None and b.bound is not None:
                bound = ["min", a.bound, b.bound]
            else:
                bound = a.bound if a.bound is not None else b.bound
        elif isinstance(op, ast.RShift):
            # `idx >> kbits` strips the low field off a flat key: the
            # bound genuinely shrinks, and keeping it symbolic lets the
            # nv_pad*nv_pad >> kbits domain cancel at evaluation.
            if a.bound is not None and b.bound is not None:
                bound = [">>", a.bound, b.bound]
            else:
                bound = a.bound
        out = AVal(bound=bound, extent=extent, int32=int32)
        if kind == "pack":
            self._record(node, "pack", bound, extent=extent,
                         shift=shift_names, int32=int32)
        elif int32 and bound is not None and sym_symbols(bound):
            self._record(node, "arith", bound, extent=extent, int32=True)
        return out

    def _subscript(self, node: ast.Subscript) -> AVal:
        # X.shape[i] -> the extent of X as a VALUE bound.
        if isinstance(node.value, ast.Attribute) \
                and node.value.attr == "shape":
            base = self._eval(node.value.value)
            return AVal(bound=base.extent)
        base = self._eval(node.value)
        self._eval(node.slice)
        return AVal(bound=base.bound, extent=base.extent, int32=base.int32)

    def _call(self, node: ast.Call) -> AVal:
        name = dotted(node.func)
        last = _last(name)
        kwargs = {kw.arg: kw.value for kw in node.keywords if kw.arg}

        # Method-style receivers: x.astype(d), x.sum(), x.reshape(...),
        # x.bit_length(), x.at[i].set(v)
        if isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            recv_node = node.func.value
            if attr == "astype":
                recv = self._eval(recv_node)
                is32 = node.args and _is_int32_dtype_expr(node.args[0])
                out = AVal(bound=recv.bound, extent=recv.extent,
                           int32=bool(is32) or recv.int32)
                if is32 and recv.bound is not None \
                        and sym_symbols(recv.bound):
                    self._record(node, "arith", recv.bound,
                                 extent=recv.extent, int32=True)
                return out
            if attr == "bit_length":
                recv = self._eval(recv_node)
                bound = None if recv.bound is None else ["bits", recv.bound]
                return AVal(bound=bound)
            if attr in ("reshape", "ravel", "flatten", "copy", "clip"):
                recv = self._eval(recv_node)
                for a in node.args:
                    self._eval(a)
                return AVal(bound=recv.bound, extent=recv.extent,
                            int32=recv.int32)
            if attr in _REDUCTION_CALLS and not name:
                recv = self._eval(recv_node)
                return self._reduction(node, attr, recv, kwargs)
            if attr in ("set", "add", "max", "min", "mul") \
                    and isinstance(recv_node, ast.Subscript) \
                    and isinstance(recv_node.value, ast.Attribute) \
                    and recv_node.value.attr == "at":
                base = self._eval(recv_node.value.value)
                self._eval(recv_node.slice)
                vals = [self._eval(a) for a in node.args]
                vb = vals[0].bound if vals else None
                return AVal(bound=_max_bound(base.bound, vb)
                            if vb is not None else base.bound,
                            extent=base.extent, int32=base.int32)

        args = [self._eval(a) for a in node.args]
        for v in kwargs.values():
            self._eval(v)

        if last in _REDUCTION_CALLS and args:
            return self._reduction(node, last, args[0], kwargs)
        if last in ("int", "abs", "round"):
            return args[0] if args else _UNKNOWN
        if last in ("int32", "uint32", "int16", "int8"):
            out = AVal(bound=args[0].bound if args else None,
                       extent=args[0].extent if args else None,
                       int32=True)
            if out.bound is not None and sym_symbols(out.bound):
                self._record(node, "arith", out.bound,
                             extent=out.extent, int32=True)
            return out
        if last in _MINMAX_CALLS and args:
            bounds = [a.bound for a in args]
            if any(b is None for b in bounds):
                merged = None if last in ("max", "maximum") else \
                    next((b for b in bounds if b is not None), None)
            else:
                tag = "min" if last in ("min", "minimum") else "max"
                merged = [tag] + bounds if len(bounds) > 1 else bounds[0]
            return AVal(bound=merged, extent=_first_extent(*args),
                        int32=any(a.int32 for a in args))
        if last == "arange":
            bound = args[0].bound if args else None
            is32 = _is_int32_dtype_expr(kwargs.get("dtype")) or (
                len(node.args) > 1
                and _is_int32_dtype_expr(node.args[1]))
            out = AVal(bound=bound, extent=bound, int32=is32)
            if is32 and bound is not None and sym_symbols(bound):
                self._record(node, "arith", bound, extent=bound,
                             int32=True)
            return out
        if last in _ALLOC_CALLS:
            extent = self._shape_extent(node.args[0]) if node.args \
                else None
            is32 = any(_is_int32_dtype_expr(a) for a in node.args[1:]) \
                or _is_int32_dtype_expr(kwargs.get("dtype"))
            fill = args[1].bound if last == "full" and len(args) > 1 \
                else _n(1 if last == "ones" else 0)
            return AVal(bound=fill, extent=extent, int32=is32)
        if last == "where" and len(args) >= 3:
            return AVal(bound=_max_bound(args[1].bound, args[2].bound),
                        extent=_first_extent(*args),
                        int32=args[1].int32 or args[2].int32)
        if last in ("take", "take_along_axis") and args:
            return AVal(bound=args[0].bound,
                        extent=args[1].extent if len(args) > 1
                        else args[0].extent,
                        int32=args[0].int32)
        if last == "concatenate":
            return AVal(extent=_first_extent(*args))
        if last == "broadcasted_iota":
            is32 = node.args and _is_int32_dtype_expr(node.args[0])
            return AVal(int32=bool(is32))
        # Unknown call: propagate the widest argument extent (the sorted
        # copies / run masks keep their slab extent through helpers).
        return AVal(extent=_first_extent(*args))

    def _shape_extent(self, shape_node: ast.AST):
        if isinstance(shape_node, (ast.Tuple, ast.List)):
            bounds = []
            for e in shape_node.elts:
                b = self._eval(e).bound
                if b is None:
                    return None
                bounds.append(b)
            if not bounds:
                return None
            out = bounds[0]
            for b in bounds[1:]:
                out = ["*", out, b]
            return out
        return self._eval(shape_node).bound

    def _reduction(self, node: ast.Call, op: str, inp: AVal,
                   kwargs: dict) -> AVal:
        is32 = inp.int32 or _is_int32_dtype_expr(kwargs.get("dtype"))
        if op == "bincount":
            # counts are bounded by the number of addends
            bound = inp.extent
            extent = None
            ml = kwargs.get("minlength")
            if ml is not None:
                extent = self._eval(ml).bound
            if kwargs.get("weights") is not None:
                is32 = False  # weighted bincount accumulates the weights
        else:
            per = inp.bound if inp.bound is not None else _n(1)
            bound = None if inp.extent is None else ["*", inp.extent, per]
            extent = inp.extent if op in ("cumsum", "cumulative_sum") \
                else None
        out = AVal(bound=bound, extent=extent, int32=is32)
        if is32 and bound is not None and sym_symbols(bound):
            self._record(node, "reduction", bound, extent=inp.extent,
                         int32=True)
        return out


def _names_in_text(text: str) -> set:
    return set(re.findall(r"[A-Za-z_][A-Za-z0-9_]*", text))


# ---------------------------------------------------------------------------
# Summary + inventory.


def width_summary(sf: SourceFile) -> dict:
    """The JSON-serializable width facts of one file; rides the tier-2
    summary under the ``"width"`` key.  Non-device-path files carry an
    empty site list (the serve/obs/comm layers hold no slab-extent
    index arithmetic)."""
    if not sf.rel.startswith(WIDTH_PATH_PREFIXES):
        return {"version": WIDTH_SUMMARY_VERSION, "sites": []}
    consts = _module_int_consts(sf)
    width_ok = _width_ok_lines(sf)
    sites: list = []
    for info in sf.functions:
        try:
            _FnInterp(sf, info, consts, width_ok, sites).run()
        except RecursionError:
            continue
    sites.sort(key=lambda s: (s["line"], s["fn"]))
    return {"version": WIDTH_SUMMARY_VERSION, "sites": sites}


def width_inventory(summaries) -> list:
    """Every ``width-ok``-annotated site in the summary set:
    [{rel, line, fn, kind, bound, reason, snippet}] — the closed,
    justified inventory of deliberate 32-bit choices
    (``python tools/width_audit.py --inventory`` prints it)."""
    out = []
    for s in summaries:
        width = (s or {}).get("width") or {}
        for site in width.get("sites", ()):
            if site.get("width_ok"):
                out.append({
                    "rel": s["rel"], "line": site["line"],
                    "fn": site["fn"], "kind": site["kind"],
                    "bound": sym_render(site["bound"]),
                    "reason": site["width_ok"],
                    "snippet": site["snippet"],
                })
    return sorted(out, key=lambda d: (d["rel"], d["line"]))


# ---------------------------------------------------------------------------
# Rules.

from cuvite_tpu.analysis.callgraph import ProjectRule  # noqa: E402


def _site_env(site: dict) -> dict:
    env = dict(MAX_WORKLOAD)
    env.update(site.get("refined") or {})
    return env


def _guarded(site: dict) -> bool:
    """Is the site inside a predicate that mentions one of the symbols
    its bound depends on (an eligibility guard)?"""
    syms = sym_symbols(site["bound"]) | sym_symbols(site.get("extent"))
    if not syms:
        return False
    for g in site.get("guards", ()):
        if _names_in_text(g) & syms:
            return True
    return False


def _wfind(rule, summary, site, message) -> Finding:
    return Finding(rule=rule.id, severity=rule.severity,
                   path=summary["rel"], line=site["line"],
                   message=message, snippet=site["snippet"])


def _width_sites(project):
    for summary in project.summaries:
        width = summary.get("width") or {}
        for site in width.get("sites", ()):
            if site.get("width_ok"):
                continue
            yield summary, site


@register
class Int32BoundOverflow(ProjectRule):
    id = "R026"
    severity = "high"
    title = "int32 index arithmetic whose symbolic bound exceeds " \
            "2^31-1 at the declared max workload"

    def check_project(self, project):
        for summary, site in _width_sites(project):
            if site["kind"] == "pack" or not site.get("int32"):
                continue
            if site["kind"] == "reduction" \
                    and "ne_pad" in sym_symbols(site.get("extent")):
                continue  # R028's partition
            val = sym_eval(site["bound"], _site_env(site))
            if val is None or val <= INT32_MAX:
                continue
            if _guarded(site):
                continue
            yield _wfind(
                self, summary, site,
                f"int32-typed value in '{site['fn']}' is bounded by "
                f"{sym_render(site['bound'])} = {val} at the registry's "
                f"declared max workload (> 2^31-1 = {INT32_MAX}): a "
                "silent wraparound here produces wrong labels, not a "
                "crash.  Guard it with an eligibility predicate (the "
                "SLAB_NE_MAX raise-guard shape), widen the dtype, or "
                "justify with '# graftlint: width-ok=<reason>' on this "
                "line (the annotation feeds the closed width inventory, "
                "tools/width_audit.py --inventory)")


@register
class UntiedBitPack(ProjectRule):
    id = "R027"
    severity = "high"
    title = "bit-packing site whose bit budget is not provably tied " \
            "to the guard predicate gating it"

    def check_project(self, project):
        for summary, site in _width_sites(project):
            if site["kind"] != "pack" or site.get("tied"):
                continue
            val = sym_eval(site["bound"], _site_env(site))
            if val is not None and val <= INT32_MAX:
                continue  # provably fits even unguarded
            shown = sym_render(site["bound"])
            at = "unknown" if val is None else str(val)
            yield _wfind(
                self, summary, site,
                f"packed key in '{site['fn']}' (budget "
                f"{shown}, {at} at max workload) is not tied to any "
                "gating predicate: nothing proves the shifted field "
                "cannot bleed into (or past) the sign bit — the "
                "segment.py contract is 'pack ONLY under a predicate "
                "that bounds the bit budget' (kbits + sbits <= 31).  "
                "Gate it on the packing bit width, bound the id space "
                "with a fail-loud raise-guard, or justify with "
                "'# graftlint: width-ok=<reason>'")


@register
class Int32SlabReduction(ProjectRule):
    id = "R028"
    severity = "high"
    title = "cumsum/sum/bincount over an ne_pad-extent array " \
            "accumulating in int32"

    def check_project(self, project):
        for summary, site in _width_sites(project):
            if site["kind"] != "reduction" or not site.get("int32"):
                continue
            if "ne_pad" not in sym_symbols(site.get("extent")):
                continue
            val = sym_eval(site["bound"], _site_env(site))
            if val is None or val <= INT32_MAX:
                continue
            if _guarded(site):
                continue
            yield _wfind(
                self, summary, site,
                f"int32 reduction in '{site['fn']}' accumulates over an "
                f"edge-slab extent ({sym_render(site.get('extent'))}); "
                f"its bound {sym_render(site['bound'])} = {val} clears "
                f"2^31-1 at the declared max workload.  The run-id/"
                "compaction-offset class: at a 2^32-row slab the cumsum "
                "of a MASK already wraps.  Bound the slab with the "
                "SLAB_NE_MAX raise-guard (ops/segment.py), accumulate "
                "wider, or justify with '# graftlint: "
                "width-ok=<reason>'")
